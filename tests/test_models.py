"""Model-level integration tests (SURVEY.md §4: LinearRegressionSuite,
LogisticRegressionSuite, SVMSuite analogues): train on synthetic data, assert
accuracy; with/without intercept; save/load round-trip; validators."""

import numpy as np
import pytest

from tpu_sgd.models import (
    LabeledPoint,
    LassoWithSGD,
    LinearRegressionModel,
    LinearRegressionWithSGD,
    LogisticRegressionModel,
    LogisticRegressionWithSGD,
    RidgeRegressionWithSGD,
    SVMModel,
    SVMWithSGD,
)
from tpu_sgd.ops.updaters import L1Updater
from tpu_sgd.utils.mlutils import linear_data, logistic_data, svm_data


def test_linear_regression_config1():
    """Config 1 (BASELINE.json:7) at test scale: dense synthetic least squares."""
    X, y, w_true = linear_data(5000, 20, eps=0.1, seed=0)
    model = LinearRegressionWithSGD.train((X, y), num_iterations=200, step_size=0.5)
    pred = np.asarray(model.predict(X))
    mse = np.mean((pred - y) ** 2)
    assert mse < 0.05  # noise floor is eps^2 = 0.01
    np.testing.assert_allclose(np.asarray(model.weights), w_true, atol=0.05)


def test_linear_regression_with_intercept():
    X, y, w_true = linear_data(5000, 8, intercept=2.5, eps=0.05, seed=1)
    model = LinearRegressionWithSGD.train(
        (X, y), num_iterations=300, step_size=0.5, intercept=True
    )
    assert abs(model.intercept - 2.5) < 0.1
    np.testing.assert_allclose(np.asarray(model.weights), w_true, atol=0.1)


def test_predict_single_vector():
    X, y, _ = linear_data(500, 4, seed=2)
    model = LinearRegressionWithSGD.train((X, y), num_iterations=100, step_size=0.5)
    single = float(model.predict(X[0]))
    batch = np.asarray(model.predict(X[:1]))[0]
    assert abs(single - batch) < 1e-6


def test_labeled_point_input():
    X, y, _ = linear_data(300, 3, seed=3)
    pts = [LabeledPoint(float(y[i]), X[i]) for i in range(len(y))]
    model = LinearRegressionWithSGD.train(pts, num_iterations=100, step_size=0.5)
    assert model.weights.shape == (3,)


def test_logistic_regression_accuracy():
    X, y, w_true = logistic_data(4000, 10, seed=4)
    model = LogisticRegressionWithSGD.train((X, y), num_iterations=100, reg_param=0.0)
    acc = np.mean(np.asarray(model.predict(X)) == y)
    bayes = np.mean((X @ w_true > 0).astype(np.float32) == y)  # optimal classifier
    assert acc > bayes - 0.02


def test_logistic_threshold_and_clear():
    X, y, _ = logistic_data(1000, 5, seed=5)
    model = LogisticRegressionWithSGD.train((X, y), num_iterations=50)
    raw = np.asarray(model.clear_threshold().predict(X))
    assert raw.min() >= 0.0 and raw.max() <= 1.0  # sigmoid scores
    model.set_threshold(0.5)
    lab = np.asarray(model.predict(X))
    assert set(np.unique(lab)) <= {0.0, 1.0}


def test_label_validator_rejects_bad_labels():
    X = np.random.default_rng(6).normal(size=(10, 3)).astype(np.float32)
    y = np.asarray([0, 1, 2, 0, 1, 0, 1, 0, 1, 0], np.float32)
    with pytest.raises(ValueError, match="0 or 1"):
        LogisticRegressionWithSGD.train((X, y), num_iterations=5)


def test_svm_accuracy_and_l1(tmp_path):
    """Config 3 shape (BASELINE.json:9): hinge + L1Updater."""
    X, y, _ = svm_data(4000, 10, noise=0.05, seed=7)
    model = SVMWithSGD.train(
        (X, y), num_iterations=100, reg_param=0.01, updater=L1Updater()
    )
    acc = np.mean(np.asarray(model.predict(X)) == y)
    assert acc > 0.9
    raw = np.asarray(model.clear_threshold().predict(X))
    assert raw.min() < 0 < raw.max()  # raw margins after clear_threshold


def test_lasso_sparsity_vs_ridge():
    r = np.random.default_rng(8)
    w_true = np.zeros(20, np.float32)
    w_true[:3] = [2.0, -1.5, 1.0]  # only 3 informative features
    X, y, _ = linear_data(3000, 20, weights=w_true, eps=0.05, seed=8)
    lasso = LassoWithSGD.train((X, y), num_iterations=200, reg_param=0.5,
                               step_size=0.5)
    ridge = RidgeRegressionWithSGD.train((X, y), num_iterations=200, reg_param=0.5,
                                         step_size=0.5)
    wl = np.asarray(lasso.weights)
    wr = np.asarray(ridge.weights)
    assert (np.abs(wl) < 1e-3).sum() > (np.abs(wr) < 1e-3).sum()
    assert (np.abs(wl[3:]) < 0.05).all()  # uninformative features killed


def test_save_load_roundtrip(tmp_path):
    X, y, _ = linear_data(500, 6, seed=9)
    model = LinearRegressionWithSGD.train((X, y), num_iterations=50, step_size=0.5,
                                          intercept=True)
    path = str(tmp_path / "m")
    model.save(path)
    loaded = LinearRegressionModel.load(path)
    np.testing.assert_allclose(np.asarray(loaded.weights),
                               np.asarray(model.weights))
    assert loaded.intercept == model.intercept
    np.testing.assert_allclose(np.asarray(loaded.predict(X)),
                               np.asarray(model.predict(X)))


def test_save_load_threshold_state(tmp_path):
    X, y, _ = logistic_data(300, 4, seed=10)
    model = LogisticRegressionWithSGD.train((X, y), num_iterations=20)
    model.clear_threshold()
    path = str(tmp_path / "m")
    model.save(path)
    loaded = LogisticRegressionModel.load(path)
    assert loaded.threshold is None  # cleared state survives


def test_load_wrong_class_rejected(tmp_path):
    X, y, _ = logistic_data(300, 4, seed=11)
    model = LogisticRegressionWithSGD.train((X, y), num_iterations=10)
    path = str(tmp_path / "m")
    model.save(path)
    with pytest.raises(ValueError, match="expected"):
        SVMModel.load(path)


def test_warm_start_initial_weights():
    X, y, w_true = linear_data(2000, 6, eps=0.01, seed=12)
    m1 = LinearRegressionWithSGD.train((X, y), num_iterations=50, step_size=0.5)
    m2 = LinearRegressionWithSGD.train(
        (X, y), num_iterations=50, step_size=0.5,
        initial_weights=np.asarray(m1.weights),
    )
    e1 = np.linalg.norm(np.asarray(m1.weights) - w_true)
    e2 = np.linalg.norm(np.asarray(m2.weights) - w_true)
    assert e2 <= e1 + 1e-4


def test_train_from_labeled_point_iterable():
    """The reference's native input is RDD[LabeledPoint]; the analogue here
    is any iterable of LabeledPoint records."""
    X, y, w_true = linear_data(2000, 5, eps=0.05, seed=21)
    points = [LabeledPoint(float(yi), xi) for xi, yi in zip(X, y)]
    model = LinearRegressionWithSGD.train(points, num_iterations=150,
                                          step_size=0.5)
    np.testing.assert_allclose(np.asarray(model.weights), w_true, atol=0.1)


def test_predict_streamed_matches_predict():
    """Chunked host-side prediction equals whole-matrix prediction (multiple
    chunks incl. a ragged tail, single-vector passthrough, empty input)."""
    import numpy as np

    from tpu_sgd.models import LinearRegressionWithSGD
    from tpu_sgd.utils.mlutils import linear_data

    X, y, _ = linear_data(2500, 7, eps=0.05, seed=21)
    model = LinearRegressionWithSGD.train((X, y), num_iterations=40,
                                          step_size=0.4)
    full = np.asarray(model.predict(X))
    chunked = model.predict_streamed(X, batch_rows=400)  # 6 chunks + tail
    # differently-shaped compiled programs may tile the matvec differently:
    # tight tolerance, not bitwise
    np.testing.assert_allclose(chunked, full, rtol=1e-6, atol=1e-7)
    single = model.predict_streamed(X[0])
    np.testing.assert_allclose(np.asarray(single), full[0])
    empty = model.predict_streamed(np.zeros((0, 7), np.float32))
    assert empty.shape == (0,)
    with pytest.raises(ValueError, match="batch_rows"):
        model.predict_streamed(X[0], batch_rows=0)


def test_predict_streamed_sparse_bcoo():
    """BCOO features chunk undensified through predict_streamed."""
    import numpy as np

    from tpu_sgd.models import LinearRegressionWithSGD
    from tpu_sgd.ops.sparse import sparse_data

    Xs, ys, _ = sparse_data(900, 40, nnz_per_row=5, seed=22)
    model = LinearRegressionWithSGD.train((Xs, ys), num_iterations=30,
                                          step_size=0.3)
    full = np.asarray(model.predict(Xs))
    chunked = model.predict_streamed(Xs, batch_rows=250)
    np.testing.assert_allclose(chunked, full, rtol=1e-6, atol=1e-7)


def test_linear_train_static_positional_parity(rng):
    """Reference static: train(input, numIterations, stepSize,
    miniBatchFraction) — the 4th positional is the FRACTION (there is no
    regParam slot); a ported call must not silently set reg instead."""
    X, y, _ = linear_data(2000, 6, seed=11)
    m_pos = LinearRegressionWithSGD.train((X, y), 60, 0.5, 0.25)
    m_kw = LinearRegressionWithSGD.train(
        (X, y), num_iterations=60, step_size=0.5, mini_batch_fraction=0.25)
    np.testing.assert_array_equal(np.asarray(m_pos.weights),
                                  np.asarray(m_kw.weights))


def test_logistic_train_static_positional_parity(rng):
    """Reference static: train(input, numIterations, stepSize,
    miniBatchFraction[, initialWeights]) and the companion object trains
    UNREGULARIZED (regParam 0.0, though the class default is 0.01)."""
    from tpu_sgd.models.classification import LogisticRegressionWithSGD

    X = rng.normal(size=(800, 5)).astype(np.float32)
    w = rng.uniform(-1, 1, 5).astype(np.float32)
    y = (X @ w > 0).astype(np.float32)
    m_static = LogisticRegressionWithSGD.train((X, y), 40, 1.0, 1.0)
    alg = LogisticRegressionWithSGD(1.0, 40, 0.0, 1.0)  # reg 0.0 explicit
    m_class = alg.run((X, y))
    np.testing.assert_array_equal(np.asarray(m_static.weights),
                                  np.asarray(m_class.weights))


def test_multinomial_intercept_warm_start_and_state(rng):
    """A trained multinomial intercept model's own weights must warm-start
    a continuation run (they carry per-class bias slots), and the run must
    not pollute the algorithm's num_features with the post-bias width."""
    from tpu_sgd.models.classification import LogisticRegressionWithLBFGS

    X = rng.normal(size=(600, 4)).astype(np.float32)
    W = rng.uniform(-1, 1, size=(2, 4)).astype(np.float32)
    logits = np.concatenate([np.zeros((600, 1)), X @ W.T], axis=1)
    y = np.argmax(logits, axis=1).astype(np.float32)

    alg = LogisticRegressionWithLBFGS(max_num_iterations=8)
    alg.set_num_classes(3).set_intercept(True)
    model = alg.run((X, y))
    assert model.weights.shape[-1] == 2 * 5  # (K-1)*(d+1) bias slots
    # continuation: the model's own weights round-trip through run_warm
    model2 = alg.run_warm((X, y), model)
    assert model2.weights.shape == model.weights.shape
    acc = float(np.mean(np.asarray(model2.predict(X)) == y))
    assert acc > 0.8
    # ...and fresh (K-1)*d weights still work (bias slots added inside)
    model3 = alg.run((X, y), np.zeros((2 * 4,), np.float32))
    assert model3.weights.shape == model.weights.shape
    # state hygiene: a later non-intercept run on the same object works
    alg.set_intercept(False)
    model4 = alg.run((X, y))
    assert model4.weights.shape[-1] == 2 * 4


def test_multinomial_intercept_honors_schedule_contract(rng):
    """set_schedule must not be silently ignored on the multinomial
    intercept branch: a schedule that cannot apply raises exactly as it
    does on every other path."""
    from tpu_sgd.models.classification import LogisticRegressionWithLBFGS

    X = rng.normal(size=(200, 4)).astype(np.float32)
    y = (rng.integers(0, 3, size=200)).astype(np.float32)
    alg = LogisticRegressionWithLBFGS(max_num_iterations=3)
    alg.set_num_classes(3).set_intercept(True)
    alg.set_schedule("resident_gram")
    with pytest.raises(ValueError):
        alg.run((X, y))
