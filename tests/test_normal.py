"""Normal-equations solver: exact OLS/ridge vs NumPy closed forms, mesh
parity, and the GLM-harness composition (intercept, model class)."""

import numpy as np
import pytest

from tpu_sgd.models import LinearRegressionModel, LinearRegressionWithNormal
from tpu_sgd.optimize.normal import NormalEquations
from tpu_sgd.parallel.mesh import data_mesh
from tpu_sgd.utils.mlutils import linear_data


def _ols(X, y, reg=0.0):
    n, d = X.shape
    A = X.T @ X / n + reg * np.eye(d)
    return np.linalg.solve(A, X.T @ y / n)


def test_exact_ols_matches_numpy():
    X, y, _ = linear_data(2000, 12, eps=0.3, seed=0)
    w = np.asarray(NormalEquations().optimize((X, y), np.zeros(12, np.float32)))
    np.testing.assert_allclose(w, _ols(X, y), rtol=1e-3, atol=1e-4)


def test_ridge_matches_numpy():
    X, y, _ = linear_data(2000, 12, eps=0.3, seed=1)
    reg = 0.37
    opt = NormalEquations(reg)
    w = np.asarray(opt.optimize((X, y), np.zeros(12, np.float32)))
    np.testing.assert_allclose(w, _ols(X, y, reg), rtol=1e-3, atol=1e-4)
    # loss history contract: one final-objective entry
    assert opt.loss_history.shape == (1,)
    resid = X @ w - y
    expect = 0.5 * np.mean(resid**2) + 0.5 * reg * np.dot(w, w)
    np.testing.assert_allclose(opt.loss_history[0], expect, rtol=1e-3)


def test_mesh_parity_with_single_device():
    X, y, _ = linear_data(4099, 10, eps=0.2, seed=2)  # odd n: ragged shards
    w1 = np.asarray(NormalEquations().optimize((X, y), np.zeros(10, np.float32)))
    opt = NormalEquations().set_mesh(data_mesh())
    w8 = np.asarray(opt.optimize((X, y), np.zeros(10, np.float32)))
    np.testing.assert_allclose(w8, w1, rtol=1e-4, atol=1e-5)


def test_model_level_train_with_intercept():
    X, y, w_true = linear_data(3000, 6, intercept=1.7, eps=0.05, seed=3)
    model = LinearRegressionWithNormal.train((X, y), intercept=True)
    assert isinstance(model, LinearRegressionModel)
    assert abs(model.intercept - 1.7) < 0.05
    np.testing.assert_allclose(np.asarray(model.weights), w_true, atol=0.05)
    mse = float(np.mean((np.asarray(model.predict(X)) - y) ** 2))
    assert mse < 0.01


def test_wrong_weight_dim_raises():
    X, y, _ = linear_data(100, 5, seed=4)
    with pytest.raises(ValueError):
        NormalEquations().optimize((X, y), np.zeros(3, np.float32))
