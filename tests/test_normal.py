"""Normal-equations solver: exact OLS/ridge vs NumPy closed forms, mesh
parity, and the GLM-harness composition (intercept, model class)."""

import numpy as np
import pytest

from tpu_sgd.models import LinearRegressionModel, LinearRegressionWithNormal
from tpu_sgd.optimize.normal import NormalEquations
from tpu_sgd.parallel.mesh import data_mesh
from tpu_sgd.utils.mlutils import linear_data


def _ols(X, y, reg=0.0):
    n, d = X.shape
    A = X.T @ X / n + reg * np.eye(d)
    return np.linalg.solve(A, X.T @ y / n)


def test_exact_ols_matches_numpy():
    X, y, _ = linear_data(2000, 12, eps=0.3, seed=0)
    w = np.asarray(NormalEquations().optimize((X, y), np.zeros(12, np.float32)))
    np.testing.assert_allclose(w, _ols(X, y), rtol=1e-3, atol=1e-4)


def test_ridge_matches_numpy():
    X, y, _ = linear_data(2000, 12, eps=0.3, seed=1)
    reg = 0.37
    opt = NormalEquations(reg)
    w = np.asarray(opt.optimize((X, y), np.zeros(12, np.float32)))
    np.testing.assert_allclose(w, _ols(X, y, reg), rtol=1e-3, atol=1e-4)
    # loss history contract: one final-objective entry
    assert opt.loss_history.shape == (1,)
    resid = X @ w - y
    expect = 0.5 * np.mean(resid**2) + 0.5 * reg * np.dot(w, w)
    np.testing.assert_allclose(opt.loss_history[0], expect, rtol=1e-3)


def test_mesh_parity_with_single_device():
    X, y, _ = linear_data(4099, 10, eps=0.2, seed=2)  # odd n: ragged shards
    w1 = np.asarray(NormalEquations().optimize((X, y), np.zeros(10, np.float32)))
    opt = NormalEquations().set_mesh(data_mesh())
    w8 = np.asarray(opt.optimize((X, y), np.zeros(10, np.float32)))
    np.testing.assert_allclose(w8, w1, rtol=1e-4, atol=1e-5)


def test_model_level_train_with_intercept():
    X, y, w_true = linear_data(3000, 6, intercept=1.7, eps=0.05, seed=3)
    model = LinearRegressionWithNormal.train((X, y), intercept=True)
    assert isinstance(model, LinearRegressionModel)
    assert abs(model.intercept - 1.7) < 0.05
    np.testing.assert_allclose(np.asarray(model.weights), w_true, atol=0.05)
    mse = float(np.mean((np.asarray(model.predict(X)) - y) ** 2))
    assert mse < 0.01


def test_wrong_weight_dim_raises():
    X, y, _ = linear_data(100, 5, seed=4)
    with pytest.raises(ValueError):
        NormalEquations().optimize((X, y), np.zeros(3, np.float32))


# ---- beyond-HBM exact solve (round 5) --------------------------------------

def test_normal_host_streamed_matches_resident(rng):
    """set_host_streaming: the exact solve from host-streamed Gram totals
    must match the resident solve (totals accumulate at f32 HIGHEST —
    at least as precise as the resident Gram matmul)."""
    from tpu_sgd.optimize.normal import NormalEquations

    from tpu_sgd.ops.gram import streamed_totals_chunking

    n, d = 4100, 12
    # batch_rows=512 < n: B=512, chunk=512 -> 8 full chunks + a 4-row
    # tail chunk, exercising the cross-chunk carry AND the sub-block
    # tail (_total_stats' nbf == 0 branch)
    B, chunk = streamed_totals_chunking(n, 8192, 512)
    assert (B, chunk) == (512, 512)
    assert n % chunk != 0 and n % chunk < B  # the tail is sub-block
    X = rng.normal(size=(n, d)).astype(np.float32)
    w_true = rng.uniform(-1, 1, d).astype(np.float32)
    y = (X @ w_true + 0.05 * rng.normal(size=n)).astype(np.float32)
    w0 = np.zeros(d, np.float32)
    w_res = NormalEquations(reg_param=0.01).optimize((X, y), w0)
    opt = NormalEquations(reg_param=0.01).set_host_streaming(
        True, batch_rows=512)
    w_str = opt.optimize((X, y), w0)
    np.testing.assert_allclose(np.asarray(w_str), np.asarray(w_res),
                               rtol=1e-4, atol=1e-5)
    assert opt.loss_history.shape == (1,)
    # the cap is honored EXACTLY even below the default block size
    # (the totals carry has no stack; B shrinks to the cap)
    B2, chunk2 = streamed_totals_chunking(100_000, 8192, 500)
    assert B2 == 500 and chunk2 == 500


def test_normal_host_streamed_meshed_matches_single(rng):
    """Meshed host streaming: per-shard streamed totals combine to the
    same exact solution (the n % k remainder rides with the last shard —
    EXACT, unlike the prefix-stack builders)."""
    from tpu_sgd import data_mesh
    from tpu_sgd.optimize.normal import NormalEquations

    n, d = 2051, 8  # n % 8 != 0: the remainder rides with the last shard
    X = rng.normal(size=(n, d)).astype(np.float32)
    y = rng.normal(size=(n,)).astype(np.float32)
    w0 = np.zeros(d, np.float32)
    w_one = NormalEquations(reg_param=0.01).set_host_streaming(True) \
        .optimize((X, y), w0)
    # batch_rows=64 < n_local=256: each shard streams MULTIPLE chunks
    # with a sub-block tail
    w_mesh = NormalEquations(reg_param=0.01).set_mesh(data_mesh()) \
        .set_host_streaming(True, batch_rows=64).optimize((X, y), w0)
    np.testing.assert_allclose(np.asarray(w_mesh), np.asarray(w_one),
                               rtol=1e-5, atol=1e-6)


def test_normal_host_streaming_batch_rows_validation():
    from tpu_sgd.optimize.normal import NormalEquations

    with pytest.raises(ValueError, match="batch_rows must be positive"):
        NormalEquations().set_host_streaming(True, batch_rows=0)


def test_normal_auto_streams_beyond_budget(rng, monkeypatch, caplog):
    """Zero-flag contract: a host dataset beyond the probed device budget
    streams its Gram totals automatically (and logs the decision) instead
    of committing the full matrix; set_host_streaming(False) forces
    resident."""
    import logging

    import tpu_sgd.plan as plan_mod
    from tpu_sgd.optimize.normal import NormalEquations

    monkeypatch.setattr(plan_mod, "device_budget",
                        lambda *a, **k: (8e3, "test"))  # 8 KB budget
    n, d = 1024, 8
    X = rng.normal(size=(n, d)).astype(np.float32)
    y = rng.normal(size=(n,)).astype(np.float32)
    w0 = np.zeros(d, np.float32)
    with caplog.at_level(logging.INFO, logger="tpu_sgd.plan"):
        w_auto = NormalEquations(reg_param=0.01).optimize((X, y), w0)
    assert any("normal host_streamed" in r.message for r in caplog.records)
    w_forced = NormalEquations(reg_param=0.01) \
        .set_host_streaming(False).optimize((X, y), w0)
    np.testing.assert_allclose(np.asarray(w_auto), np.asarray(w_forced),
                               rtol=1e-4, atol=1e-5)


def test_streamed_totals_resumable_bitwise(rng, tmp_path):
    """A totals accumulation killed mid-pass resumes from its carry
    checkpoint and produces BITWISE-identical totals (round 5: the cheap
    sibling of the prefix builder's resume)."""
    from tpu_sgd.ops import gram as gram_mod
    from tpu_sgd.ops.gram import GramLeastSquaresGradient

    n, d = 1500, 6
    X = rng.normal(size=(n, d)).astype(np.float32)
    y = rng.normal(size=(n,)).astype(np.float32)
    import jax.numpy as jnp

    sd = jnp.float32
    ref = GramLeastSquaresGradient._streamed_totals(X, y, 128, sd, 256)
    resume_dir = str(tmp_path / "totals")
    calls = {"n": 0}
    real = gram_mod._acc_totals

    def dying(*args):
        calls["n"] += 1
        if calls["n"] == 3:
            raise RuntimeError("simulated wedge")
        return real(*args)

    gram_mod._acc_totals = dying
    try:
        with pytest.raises(RuntimeError, match="wedge"):
            GramLeastSquaresGradient._streamed_totals(
                X, y, 128, sd, 256, resume_dir=resume_dir,
                checkpoint_every=1)
    finally:
        gram_mod._acc_totals = real
    import os

    assert os.path.exists(os.path.join(resume_dir, "totals.npz"))
    got = GramLeastSquaresGradient._streamed_totals(
        X, y, 128, sd, 256, resume_dir=resume_dir)
    for a, b in zip(got, ref):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert not os.path.exists(resume_dir)  # finalized


def test_streamed_totals_resume_rejects_different_dataset(rng, tmp_path):
    from tpu_sgd.ops import gram as gram_mod
    from tpu_sgd.ops.gram import GramLeastSquaresGradient
    import jax.numpy as jnp

    n, d = 800, 5
    XA = rng.normal(size=(n, d)).astype(np.float32)
    XB = rng.normal(size=(n, d)).astype(np.float32)
    y = rng.normal(size=(n,)).astype(np.float32)
    resume_dir = str(tmp_path / "totals")
    calls = {"n": 0}
    real = gram_mod._acc_totals

    def dying(*args):
        calls["n"] += 1
        if calls["n"] == 2:
            raise RuntimeError("wedge")
        return real(*args)

    gram_mod._acc_totals = dying
    try:
        with pytest.raises(RuntimeError):
            GramLeastSquaresGradient._streamed_totals(
                XA, y, 64, jnp.float32, 128, resume_dir=resume_dir,
                checkpoint_every=1)
    finally:
        gram_mod._acc_totals = real
    with pytest.raises(ValueError, match="different build"):
        GramLeastSquaresGradient._streamed_totals(
            XB, y, 64, jnp.float32, 128, resume_dir=resume_dir)


def test_normal_streamed_resume_dir_end_to_end(rng, tmp_path):
    """resume_dir threads through the public solver API (and is a no-op
    on an uninterrupted build)."""
    from tpu_sgd.optimize.normal import NormalEquations

    n, d = 1200, 7
    X = rng.normal(size=(n, d)).astype(np.float32)
    y = rng.normal(size=(n,)).astype(np.float32)
    w0 = np.zeros(d, np.float32)
    w_plain = NormalEquations(reg_param=0.01).set_host_streaming(
        True, batch_rows=256).optimize((X, y), w0)
    w_ckpt = NormalEquations(reg_param=0.01).set_host_streaming(
        True, batch_rows=256,
        resume_dir=str(tmp_path / "nrm")).optimize((X, y), w0)
    np.testing.assert_array_equal(np.asarray(w_ckpt), np.asarray(w_plain))
