"""Test harness: emulate an 8-device mesh on CPU.

SURVEY.md §4: the reference tests multi-worker behavior with local threads
(``local[2]`` / ``local-cluster``); the direct analogue here is
``--xla_force_host_platform_device_count=8`` on the CPU backend.  Must run
before jax initializes its backends.
"""

import os
import sys

# the package is not pip-installed: make the repo root importable so the
# suite runs under the bare `pytest` console script too, not only
# `python -m pytest` from the repo root (which happens to prepend cwd)
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# The environment's sitecustomize registers the remote-TPU ("axon") PJRT
# plugin and force-sets jax_platforms="axon,cpu" via jax.config, trampling
# the JAX_PLATFORMS env var — re-assert CPU so tests never dial the tunnel.
jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(0)
