"""Property-based tests (hypothesis), per SURVEY.md §4's test mapping:
closed-form updater identities and single-vs-sharded parity over random
inputs.  Shapes are FIXED (only values vary) so jitted functions compile
once per test — EXCEPT the sparse layout tests at the bottom, which
deliberately vary shapes (their edge cases — empty shards, row counts
below the shard count, ragged nse — live in the shape/sparsity structure)
and keep example counts small to bound the per-example compile cost."""

import numpy as np
import pytest
from hypothesis import given, settings, strategies as st

from tpu_sgd.ops.gradients import (
    HingeGradient,
    LeastSquaresGradient,
    LogisticGradient,
)
from tpu_sgd.ops.updaters import L1Updater, SimpleUpdater, SquaredL2Updater

D = 8
finite_vec = st.lists(
    st.floats(-10, 10, allow_nan=False, width=32), min_size=D, max_size=D
).map(lambda v: np.asarray(v, np.float32))


@settings(derandomize=True, max_examples=30, deadline=None)
@given(w=finite_vec, g=finite_vec,
       step=st.floats(0.01, 5.0), t=st.integers(1, 1000),
       reg=st.floats(0.0, 2.0))
def test_l1_prox_closed_form_property(w, g, step, t, reg):
    eta = step / np.sqrt(t)
    raw = w - eta * g
    expect = np.sign(raw) * np.maximum(np.abs(raw) - reg * eta, 0.0)
    got, reg_val = L1Updater().compute(w, g, step, t, reg)
    np.testing.assert_allclose(np.asarray(got), expect, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(
        float(reg_val), reg * np.abs(expect).sum(), rtol=1e-4, atol=1e-5
    )


@settings(derandomize=True, max_examples=30, deadline=None)
@given(w=finite_vec, g=finite_vec, step=st.floats(0.01, 5.0),
       t=st.integers(1, 1000), reg=st.floats(0.0, 2.0))
def test_l2_shrinkage_property(w, g, step, t, reg):
    eta = step / np.sqrt(t)
    expect = w * (1 - eta * reg) - eta * g
    got, reg_val = SquaredL2Updater().compute(w, g, step, t, reg)
    np.testing.assert_allclose(np.asarray(got), expect, rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(
        float(reg_val), 0.5 * reg * (expect**2).sum(), rtol=1e-3, atol=1e-4
    )


@settings(derandomize=True, max_examples=25, deadline=None)
@given(margins=finite_vec, labels=st.lists(st.integers(0, 1), min_size=D,
                                           max_size=D))
def test_logistic_pointwise_is_derivative(margins, labels):
    """coeff must equal d(loss)/d(margin) — finite-difference check."""
    y = np.asarray(labels, np.float32)
    g = LogisticGradient()
    eps = 1e-3
    coeff, _ = g.pointwise(margins, y)
    _, lp = g.pointwise(margins + eps, y)
    _, lm = g.pointwise(margins - eps, y)
    fd = (np.asarray(lp) - np.asarray(lm)) / (2 * eps)
    np.testing.assert_allclose(np.asarray(coeff), fd, rtol=5e-2, atol=5e-3)


@settings(derandomize=True, max_examples=20, deadline=None)
@given(seed=st.integers(0, 10_000))
def test_sharded_equals_single_device_property(seed):
    """psum re-association: 8-shard full-batch grad == single-device grad."""
    import jax
    from jax.sharding import PartitionSpec as P

    from tpu_sgd.parallel.mesh import data_mesh, shard_map_fn

    r = np.random.default_rng(seed)
    X = r.normal(size=(64, D)).astype(np.float32)
    y = r.normal(size=(64,)).astype(np.float32)
    w = r.normal(size=(D,)).astype(np.float32)
    g = LeastSquaresGradient()
    gs_ref, ls_ref, c_ref = g.batch_sums(X, y, w)
    mesh = data_mesh()

    def body(w, X, y):
        import jax.lax as lax

        gs, ls, c = g.batch_sums(X, y, w)
        return lax.psum((gs, ls, c), "data")

    fn = shard_map_fn(mesh, body, (P(), P("data", None), P("data")),
                      (P(), P(), P()))
    gs, ls, c = fn(w, X, y)
    np.testing.assert_allclose(np.asarray(gs), np.asarray(gs_ref), rtol=2e-3,
                               atol=2e-2)
    np.testing.assert_allclose(float(ls), float(ls_ref), rtol=2e-3, atol=1e-2)
    assert float(c) == float(c_ref)


@settings(derandomize=True, max_examples=25, deadline=None)
@given(margins=finite_vec, labels=st.lists(st.integers(0, 1), min_size=D,
                                           max_size=D))
def test_hinge_nonnegative_loss_property(margins, labels):
    y = np.asarray(labels, np.float32)
    coeff, loss = HingeGradient().pointwise(margins, y)
    assert np.all(np.asarray(loss) >= 0)
    # inactive examples (slack <= 0) have zero loss AND zero coefficient
    inactive = np.asarray(loss) == 0
    np.testing.assert_array_equal(np.asarray(coeff)[inactive], 0.0)


@settings(derandomize=True, max_examples=20, deadline=None)
@given(
    seed=st.integers(0, 10_000),
    n=st.integers(3, 60),
    d=st.integers(2, 40),
    grad_idx=st.integers(0, 2),
    with_mask=st.booleans(),
)
def test_sparse_batch_sums_equals_dense_property(seed, n, d, grad_idx,
                                                 with_mask):
    """For ANY sparsity pattern (including empty rows and columns), the
    BCOO lowering of batch_sums equals the dense path."""
    import jax.numpy as jnp
    from jax.experimental.sparse import BCOO

    rng = np.random.default_rng(seed)
    Xd = rng.normal(size=(n, d)).astype(np.float32)
    Xd[rng.uniform(size=(n, d)) < rng.uniform(0.3, 1.0)] = 0.0
    grad = [LeastSquaresGradient(), LogisticGradient(), HingeGradient()][
        grad_idx
    ]
    y = (
        rng.normal(size=(n,)).astype(np.float32)
        if grad_idx == 0
        else rng.integers(0, 2, size=(n,)).astype(np.float32)
    )
    w = rng.normal(size=(d,)).astype(np.float32)
    mask = jnp.asarray(rng.uniform(size=(n,)) < 0.6) if with_mask else None
    X = BCOO.fromdense(jnp.asarray(Xd))
    gs, ls, cs = grad.batch_sums(X, jnp.asarray(y), jnp.asarray(w), mask)
    gd, ld, cd = grad.batch_sums(
        jnp.asarray(Xd), jnp.asarray(y), jnp.asarray(w), mask
    )
    np.testing.assert_allclose(gs, gd, rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(ls, ld, rtol=1e-4, atol=1e-5)
    assert float(cs) == float(cd)


@settings(derandomize=True, max_examples=15, deadline=None)
@given(seed=st.integers(0, 10_000), n=st.integers(1, 100), d=st.integers(1, 30))
def test_shard_bcoo_layout_reconstructs_dense_property(seed, n, d):
    """The equal-nse shard layout is lossless: reassembling every shard's
    local block reproduces the original matrix exactly — including empty
    shards, empty rows, and row counts far below the shard count."""
    import jax.numpy as jnp
    from jax.experimental.sparse import BCOO

    from tpu_sgd.parallel import data_mesh
    from tpu_sgd.parallel.sparse_parallel import shard_bcoo

    rng = np.random.default_rng(seed)
    Xd = rng.normal(size=(n, d)).astype(np.float32)
    Xd[rng.uniform(size=(n, d)) < 0.8] = 0.0
    X = BCOO.fromdense(jnp.asarray(Xd))
    y = rng.normal(size=(n,)).astype(np.float32)
    mesh = data_mesh()
    n_shards = mesh.shape["data"]
    data, idx, yd, valid, rows_local, dd = shard_bcoo(mesh, X, y)
    assert dd == d
    data_h = np.asarray(data).reshape(n_shards, -1)
    idx_h = np.asarray(idx).reshape(n_shards, -1, 2)
    dense = np.zeros((n_shards * rows_local, d), np.float32)
    for s in range(n_shards):
        # scatter-ADD: null padding entries (0.0 at (0,0)) must be no-ops
        np.add.at(
            dense, (s * rows_local + idx_h[s, :, 0], idx_h[s, :, 1]),
            data_h[s],
        )
    np.testing.assert_allclose(dense[:n], Xd, rtol=1e-6)
    np.testing.assert_allclose(dense[n:], 0.0)
    np.testing.assert_allclose(np.asarray(yd)[:n], y)
    if valid is not None:
        v = np.asarray(valid)
        assert v[:n].all() and not v[n:].any()
    else:
        assert n == n_shards * rows_local


def test_sparse_batch_sums_fully_empty_matrix():
    """Deterministic pin of the nse=0 edge case (a random draw only rarely
    produces it): an all-zero BCOO matches the all-zero dense result."""
    import jax.numpy as jnp
    from jax.experimental.sparse import BCOO

    n, d = 12, 5
    X = BCOO.fromdense(jnp.zeros((n, d)))
    y = jnp.ones((n,), jnp.float32)
    w = jnp.ones((d,), jnp.float32)
    for grad in (LeastSquaresGradient(), LogisticGradient(), HingeGradient()):
        gs, ls, cs = grad.batch_sums(X, y, w)
        gd, ld, cd = grad.batch_sums(jnp.zeros((n, d)), y, w)
        np.testing.assert_allclose(gs, gd, atol=1e-6)
        np.testing.assert_allclose(ls, ld, rtol=1e-6)
        assert float(cs) == float(cd) == n


@settings(max_examples=20, deadline=None)
@given(
    seed=st.integers(0, 2**16),
    n=st.integers(5, 60),
    d=st.integers(2, 8),
    t=st.integers(1, 6),
    grad_idx=st.integers(0, 2),
    with_mask=st.booleans(),
)
def test_loss_sweep_equals_per_trial_property(seed, n, d, t, grad_idx,
                                              with_mask):
    """For every vector-weight gradient: the batched line-search sweep over
    T stacked trial weights equals T independent batch_sums losses, with
    identical counts, masked or not."""
    import jax.numpy as jnp

    gradient = [LeastSquaresGradient(), LogisticGradient(), HingeGradient()][
        grad_idx
    ]
    r = np.random.default_rng(seed)
    X = r.normal(size=(n, d)).astype(np.float32)
    y = (r.random(n) < 0.5).astype(np.float32)
    W = r.normal(size=(t, d)).astype(np.float32)
    mask = jnp.asarray((r.random(n) < 0.7).astype(np.float32)) if with_mask \
        else None
    sums, count = gradient.loss_sweep(jnp.asarray(X), jnp.asarray(y),
                                      jnp.asarray(W), mask=mask)
    assert sums.shape == (t,)
    for k in range(t):
        _, l_k, c_k = gradient.batch_sums(jnp.asarray(X), jnp.asarray(y),
                                          jnp.asarray(W[k]), mask=mask)
        np.testing.assert_allclose(float(sums[k]), float(l_k), rtol=2e-5,
                                   atol=1e-6)
        np.testing.assert_allclose(float(count), float(c_k))


@settings(max_examples=30, deadline=None)
@given(
    n=st.integers(10, 500),
    frac=st.floats(0.05, 0.99),
    r_frac=st.floats(0.0, 1.0),
)
def test_resident_window_probability_property(n, frac, r_frac):
    """The residency hit-rate formula bench records matches the sampler's
    actual accept set: a window [start, start+m) drawn from
    integers(0, n-m+1) lies in the resident prefix iff start <= R-m."""
    from tpu_sgd.optimize.streamed import (
        resident_window_probability,
        sliced_window_rows,
    )

    m = sliced_window_rows(n, frac)
    R = int(r_frac * n)
    hits = sum(
        1 for start in range(0, n - m + 1) if start + m <= R
    )
    assert hits / max(n - m + 1, 1) == pytest.approx(
        resident_window_probability(n, frac, R)
    )


# ---- chunked CostFun sums == one-pass sums over random grids (round 5) ----

@settings(derandomize=True, max_examples=12, deadline=None)
@given(
    n=st.integers(5, 400),
    batch_rows=st.integers(1, 500),
    seed=st.integers(0, 10_000),
    grad_i=st.integers(0, 2),
)
def test_streamed_costfun_sums_property(n, batch_rows, seed, grad_i):
    """For ANY (row count, chunk size) grid — chunks larger than the data,
    single-row chunks, ragged tails — the chunked accumulation equals the
    one-pass kernels up to summation reassociation (the treeAggregate
    invariance the reference gets from associativity)."""
    import jax.numpy as jnp

    from tpu_sgd.optimize.streamed_costfun import StreamedCostFun

    d = 6
    gradient = (LeastSquaresGradient(), LogisticGradient(),
                HingeGradient())[grad_i]
    r = np.random.default_rng(seed)
    X = r.normal(size=(n, d)).astype(np.float32)
    y = (r.random(n) > 0.5).astype(np.float32)
    w = r.normal(size=(d,)).astype(np.float32)
    scf = StreamedCostFun(gradient, X, y, batch_rows=batch_rows)
    gs, ls, c = (np.asarray(v) for v in scf.cost_sums(w))
    g0, l0, c0 = (np.asarray(v) for v in gradient.batch_sums(
        jnp.asarray(X), jnp.asarray(y), jnp.asarray(w)))
    assert c == c0 == n
    np.testing.assert_allclose(gs, g0, rtol=3e-5,
                               atol=3e-4 * max(1, n / 100))
    np.testing.assert_allclose(ls, l0, rtol=3e-5,
                               atol=3e-4 * max(1, n / 100))
