"""Property-based tests (hypothesis), per SURVEY.md §4's test mapping:
closed-form updater identities and single-vs-sharded parity over random
inputs.  Shapes are FIXED (only values vary) so jitted functions compile
once per test."""

import numpy as np
import pytest
from hypothesis import given, settings, strategies as st

from tpu_sgd.ops.gradients import (
    HingeGradient,
    LeastSquaresGradient,
    LogisticGradient,
)
from tpu_sgd.ops.updaters import L1Updater, SimpleUpdater, SquaredL2Updater

D = 8
finite_vec = st.lists(
    st.floats(-10, 10, allow_nan=False, width=32), min_size=D, max_size=D
).map(lambda v: np.asarray(v, np.float32))


@settings(max_examples=30, deadline=None)
@given(w=finite_vec, g=finite_vec,
       step=st.floats(0.01, 5.0), t=st.integers(1, 1000),
       reg=st.floats(0.0, 2.0))
def test_l1_prox_closed_form_property(w, g, step, t, reg):
    eta = step / np.sqrt(t)
    raw = w - eta * g
    expect = np.sign(raw) * np.maximum(np.abs(raw) - reg * eta, 0.0)
    got, reg_val = L1Updater().compute(w, g, step, t, reg)
    np.testing.assert_allclose(np.asarray(got), expect, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(
        float(reg_val), reg * np.abs(expect).sum(), rtol=1e-4, atol=1e-5
    )


@settings(max_examples=30, deadline=None)
@given(w=finite_vec, g=finite_vec, step=st.floats(0.01, 5.0),
       t=st.integers(1, 1000), reg=st.floats(0.0, 2.0))
def test_l2_shrinkage_property(w, g, step, t, reg):
    eta = step / np.sqrt(t)
    expect = w * (1 - eta * reg) - eta * g
    got, reg_val = SquaredL2Updater().compute(w, g, step, t, reg)
    np.testing.assert_allclose(np.asarray(got), expect, rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(
        float(reg_val), 0.5 * reg * (expect**2).sum(), rtol=1e-3, atol=1e-4
    )


@settings(max_examples=25, deadline=None)
@given(margins=finite_vec, labels=st.lists(st.integers(0, 1), min_size=D,
                                           max_size=D))
def test_logistic_pointwise_is_derivative(margins, labels):
    """coeff must equal d(loss)/d(margin) — finite-difference check."""
    y = np.asarray(labels, np.float32)
    g = LogisticGradient()
    eps = 1e-3
    coeff, _ = g.pointwise(margins, y)
    _, lp = g.pointwise(margins + eps, y)
    _, lm = g.pointwise(margins - eps, y)
    fd = (np.asarray(lp) - np.asarray(lm)) / (2 * eps)
    np.testing.assert_allclose(np.asarray(coeff), fd, rtol=5e-2, atol=5e-3)


@settings(max_examples=20, deadline=None)
@given(seed=st.integers(0, 10_000))
def test_sharded_equals_single_device_property(seed):
    """psum re-association: 8-shard full-batch grad == single-device grad."""
    import jax
    from jax.sharding import PartitionSpec as P

    from tpu_sgd.parallel.mesh import data_mesh, shard_map_fn

    r = np.random.default_rng(seed)
    X = r.normal(size=(64, D)).astype(np.float32)
    y = r.normal(size=(64,)).astype(np.float32)
    w = r.normal(size=(D,)).astype(np.float32)
    g = LeastSquaresGradient()
    gs_ref, ls_ref, c_ref = g.batch_sums(X, y, w)
    mesh = data_mesh()

    def body(w, X, y):
        import jax.lax as lax

        gs, ls, c = g.batch_sums(X, y, w)
        return lax.psum((gs, ls, c), "data")

    fn = shard_map_fn(mesh, body, (P(), P("data", None), P("data")),
                      (P(), P(), P()))
    gs, ls, c = fn(w, X, y)
    np.testing.assert_allclose(np.asarray(gs), np.asarray(gs_ref), rtol=2e-3,
                               atol=2e-2)
    np.testing.assert_allclose(float(ls), float(ls_ref), rtol=2e-3, atol=1e-2)
    assert float(c) == float(c_ref)


@settings(max_examples=25, deadline=None)
@given(margins=finite_vec, labels=st.lists(st.integers(0, 1), min_size=D,
                                           max_size=D))
def test_hinge_nonnegative_loss_property(margins, labels):
    y = np.asarray(labels, np.float32)
    coeff, loss = HingeGradient().pointwise(margins, y)
    assert np.all(np.asarray(loss) >= 0)
    # inactive examples (slack <= 0) have zero loss AND zero coefficient
    inactive = np.asarray(loss) == 0
    np.testing.assert_array_equal(np.asarray(coeff)[inactive], 0.0)
