"""GradientDescent semantics: convergence, loss history, sampling, reg.

Mirrors the reference's GradientDescentSuite strategy (SURVEY.md §4):
synthetic data from known weights, assert loss decreases and weights approach
truth; regParam changes solutions; convergence tolerance exits early.
"""

import numpy as np
import pytest

from tpu_sgd.config import SGDConfig
from tpu_sgd.ops.gradients import LeastSquaresGradient, LogisticGradient
from tpu_sgd.ops.updaters import SimpleUpdater, SquaredL2Updater
from tpu_sgd.optimize.gradient_descent import (
    GradientDescent,
    run_mini_batch_sgd,
)
from tpu_sgd.utils.mlutils import linear_data, logistic_data


def test_linear_recovers_truth():
    X, y, w_true = linear_data(2000, 10, eps=0.01, seed=0)
    opt = (
        GradientDescent(LeastSquaresGradient(), SimpleUpdater())
        .set_step_size(0.5)
        .set_num_iterations(200)
        .set_convergence_tol(0.0)
    )
    w, hist = opt.optimize_with_history((X, y), np.zeros(10, np.float32))
    assert hist[-1] < hist[0]
    np.testing.assert_allclose(np.asarray(w), w_true, atol=0.05)


def test_loss_history_decreases_and_matches_contract():
    X, y, _ = linear_data(500, 5, eps=0.0, seed=1)
    opt = (
        GradientDescent(LeastSquaresGradient(), SimpleUpdater())
        .set_step_size(0.2)
        .set_num_iterations(50)
        .set_convergence_tol(0.0)
    )
    w, hist = opt.optimize_with_history((X, y), np.zeros(5, np.float32))
    assert len(hist) == 50
    # first recorded loss is the loss at the INITIAL weights (before update)
    expect0 = 0.5 * np.mean((X @ np.zeros(5) - y) ** 2)
    np.testing.assert_allclose(hist[0], expect0, rtol=1e-4)
    assert hist[-1] < 1e-2 * hist[0]


def test_convergence_tol_early_exit():
    X, y, _ = linear_data(500, 5, eps=0.0, seed=2)
    opt = (
        GradientDescent(LeastSquaresGradient(), SimpleUpdater())
        .set_step_size(0.5)
        .set_num_iterations(500)
        .set_convergence_tol(1e-3)
    )
    _, hist = opt.optimize_with_history((X, y), np.zeros(5, np.float32))
    assert len(hist) < 500  # exited early


def test_reg_param_changes_solution():
    X, y, _ = logistic_data(1000, 8, seed=3)
    common = dict(step_size=1.0, num_iterations=60, mini_batch_fraction=1.0,
                  convergence_tol=0.0)
    w_low, _ = run_mini_batch_sgd(
        (X, y), LogisticGradient(), SquaredL2Updater(),
        reg_param=0.0, initial_weights=np.zeros(8, np.float32), **common)
    w_high, _ = run_mini_batch_sgd(
        (X, y), LogisticGradient(), SquaredL2Updater(),
        reg_param=1.0, initial_weights=np.zeros(8, np.float32), **common)
    assert np.linalg.norm(np.asarray(w_high)) < np.linalg.norm(np.asarray(w_low))


def test_mini_batch_fraction_path_converges():
    X, y, w_true = linear_data(4000, 6, eps=0.01, seed=4)
    opt = (
        GradientDescent(LeastSquaresGradient(), SimpleUpdater())
        .set_step_size(0.5)
        .set_num_iterations(300)
        .set_mini_batch_fraction(0.1)
        .set_convergence_tol(0.0)
    )
    w, hist = opt.optimize_with_history((X, y), np.zeros(6, np.float32))
    np.testing.assert_allclose(np.asarray(w), w_true, atol=0.1)


def test_sampling_is_deterministic_in_seed():
    X, y, _ = linear_data(1000, 4, seed=5)
    def go(seed):
        return np.asarray(
            GradientDescent(LeastSquaresGradient(), SimpleUpdater())
            .set_num_iterations(20)
            .set_mini_batch_fraction(0.3)
            .set_seed(seed)
            .optimize((X, y), np.zeros(4, np.float32))
        )
    a, b, c = go(42), go(42), go(7)
    np.testing.assert_array_equal(a, b)
    assert not np.array_equal(a, c)


def test_empty_input_returns_initial_weights():
    opt = GradientDescent()
    w0 = np.ones(3, np.float32)
    w, hist = opt.optimize_with_history(
        (np.zeros((0, 3), np.float32), np.zeros((0,), np.float32)), w0
    )
    np.testing.assert_array_equal(np.asarray(w), w0)
    assert len(hist) == 0


def test_tiny_fraction_warns():
    X, y, _ = linear_data(10, 2, seed=6)
    opt = GradientDescent().set_mini_batch_fraction(0.01).set_num_iterations(3)
    with pytest.warns(RuntimeWarning):
        opt.optimize((X, y), np.zeros(2, np.float32))


def test_indexed_sampling_converges():
    """The TPU fast-path sampler reaches the same solution quality."""
    X, y, w_true = linear_data(4000, 6, eps=0.01, seed=4)
    opt = (
        GradientDescent(LeastSquaresGradient(), SimpleUpdater())
        .set_step_size(0.5)
        .set_num_iterations(300)
        .set_mini_batch_fraction(0.1)
        .set_sampling("indexed")
        .set_convergence_tol(0.0)
    )
    w, hist = opt.optimize_with_history((X, y), np.zeros(6, np.float32))
    np.testing.assert_allclose(np.asarray(w), w_true, atol=0.1)
    assert len(hist) == 300


def test_indexed_sampling_dp_parity():
    """Indexed sampling under the 8-device mesh also converges."""
    import jax
    from tpu_sgd.parallel.mesh import data_mesh

    X, y, w_true = linear_data(8000, 8, eps=0.01, seed=5)
    opt = (
        GradientDescent(LeastSquaresGradient(), SimpleUpdater())
        .set_step_size(0.5)
        .set_num_iterations(300)
        .set_mini_batch_fraction(0.1)
        .set_sampling("indexed")
        .set_convergence_tol(0.0)
        .set_mesh(data_mesh())
    )
    w, _ = opt.optimize_with_history((X, y), np.zeros(8, np.float32))
    np.testing.assert_allclose(np.asarray(w), w_true, atol=0.1)


def test_host_streaming_converges():
    """Host-resident dataset, streamed minibatches, same solution quality."""
    X, y, w_true = linear_data(4000, 6, eps=0.01, seed=7)
    opt = (
        GradientDescent(LeastSquaresGradient(), SimpleUpdater())
        .set_step_size(0.5)
        .set_num_iterations(300)
        .set_mini_batch_fraction(0.1)
        .set_convergence_tol(0.0)
        .set_host_streaming()
    )
    w, hist = opt.optimize_with_history((X, y), np.zeros(6, np.float32))
    assert len(hist) == 300
    np.testing.assert_allclose(np.asarray(w), w_true, atol=0.1)


@pytest.mark.parametrize("sampling", ["bernoulli", "indexed", "sliced"])
def test_host_streaming_honors_sampling_mode(sampling):
    """config.sampling is honored host-side (VERDICT r1 weak #4): every mode
    converges, and the 8-way mesh trajectory matches single-device exactly
    (the sampler runs on the host either way)."""
    from tpu_sgd.parallel.mesh import data_mesh

    X, y, w_true = linear_data(6000, 8, eps=0.01, seed=11)
    w0 = np.zeros(8, np.float32)

    def make():
        return (
            GradientDescent(LeastSquaresGradient(), SimpleUpdater())
            .set_step_size(0.4).set_num_iterations(120)
            .set_mini_batch_fraction(0.15).set_convergence_tol(0.0)
            .set_sampling(sampling)
            .set_host_streaming()
        )

    w1, h1 = make().optimize_with_history((X, y), w0)
    np.testing.assert_allclose(np.asarray(w1), w_true, atol=0.1)
    w8, h8 = make().set_mesh(data_mesh()).optimize_with_history((X, y), w0)
    np.testing.assert_allclose(np.asarray(w8), np.asarray(w1), rtol=1e-4,
                               atol=1e-5)
    np.testing.assert_allclose(h8, h1, rtol=1e-4)


def test_host_streaming_checkpoint_resume(tmp_path):
    """Streamed path honors checkpointing: interrupt, resume, same result."""
    from tpu_sgd.utils.checkpoint import CheckpointManager

    X, y, _ = linear_data(2000, 5, seed=9)
    w0 = np.zeros(5, np.float32)

    def make(iters, ck):
        return (
            GradientDescent(LeastSquaresGradient(), SimpleUpdater())
            .set_step_size(0.5).set_num_iterations(iters)
            .set_mini_batch_fraction(0.2).set_convergence_tol(0.0)
            .set_host_streaming()
            .set_checkpoint(CheckpointManager(ck), every=10)
        )

    full = (
        GradientDescent(LeastSquaresGradient(), SimpleUpdater())
        .set_step_size(0.5).set_num_iterations(60)
        .set_mini_batch_fraction(0.2).set_convergence_tol(0.0)
        .set_host_streaming()
    )
    w_full, h_full = full.optimize_with_history((X, y), w0)
    ck = str(tmp_path / "ck")
    make(30, ck).optimize_with_history((X, y), w0)
    with pytest.warns(RuntimeWarning):
        w_res, h_res = make(60, ck).optimize_with_history((X, y), w0)
    assert len(h_res) == 60
    np.testing.assert_allclose(np.asarray(w_res), np.asarray(w_full),
                               rtol=1e-5, atol=1e-6)


def test_host_streaming_dp_mesh_parity():
    """Streamed batches sharded over the 8-way mesh match the single-device
    streamed trajectory (same host-side sampler, psum'd combine)."""
    from tpu_sgd.parallel.mesh import data_mesh

    X, y, _ = linear_data(3000, 6, eps=0.05, seed=10)
    w0 = np.zeros(6, np.float32)

    def make():
        return (
            GradientDescent(LeastSquaresGradient(), SimpleUpdater())
            .set_step_size(0.4).set_num_iterations(40)
            .set_mini_batch_fraction(0.2).set_convergence_tol(0.0)
            .set_host_streaming()
        )

    w1, h1 = make().optimize_with_history((X, y), w0)
    w8, h8 = make().set_mesh(data_mesh()).optimize_with_history((X, y), w0)
    assert len(h8) == 40
    np.testing.assert_allclose(np.asarray(w8), np.asarray(w1), rtol=1e-4,
                               atol=1e-5)
    np.testing.assert_allclose(h8, h1, rtol=1e-4)


def test_host_streaming_rejects_2d_mesh():
    from tpu_sgd.parallel.mesh import make_mesh

    X, y, _ = linear_data(100, 3, seed=10)
    opt = GradientDescent().set_host_streaming().set_mesh(make_mesh(4, 2))
    with pytest.raises(NotImplementedError, match="host streaming"):
        opt.optimize((X, y), np.zeros(3, np.float32))


def test_host_streaming_full_batch_matches_resident():
    """frac=1.0 streamed == resident path (identical math, no sampling)."""
    X, y, _ = linear_data(600, 5, seed=8)
    w0 = np.zeros(5, np.float32)
    cfg = dict(step_size=0.3, num_iterations=25)
    res = (
        GradientDescent(LeastSquaresGradient(), SimpleUpdater())
        .set_step_size(0.3).set_num_iterations(25).set_convergence_tol(0.0)
    )
    w_r, h_r = res.optimize_with_history((X, y), w0)
    st = (
        GradientDescent(LeastSquaresGradient(), SimpleUpdater())
        .set_step_size(0.3).set_num_iterations(25).set_convergence_tol(0.0)
        .set_host_streaming()
    )
    w_s, h_s = st.optimize_with_history((X, y), w0)
    np.testing.assert_allclose(np.asarray(w_s), np.asarray(w_r), rtol=1e-5,
                               atol=1e-6)
    np.testing.assert_allclose(h_s, h_r, rtol=1e-5)


def test_invalid_sampling_mode_rejected():
    with pytest.raises(ValueError, match="sampling"):
        GradientDescent().set_sampling("nope")


def test_bf16_data_f32_weights():
    """Mixed precision: bf16 features keep f32 master weights and converge."""
    import jax.numpy as jnp

    X, y, w_true = linear_data(4000, 6, eps=0.01, seed=6)
    opt = (
        GradientDescent(LeastSquaresGradient(), SimpleUpdater())
        .set_step_size(0.5)
        .set_num_iterations(200)
        .set_convergence_tol(0.0)
    )
    w, _ = opt.optimize_with_history((jnp.asarray(X, jnp.bfloat16), y),
                                     np.zeros(6, np.float32))
    assert w.dtype == jnp.float32
    np.testing.assert_allclose(np.asarray(w), w_true, atol=0.1)


def test_integer_features_are_cast():
    X = np.asarray([[0, 1], [1, 0], [1, 1], [0, 0]] * 50, np.int64)
    y = (X[:, 0] + 2 * X[:, 1]).astype(np.int64)
    w = (
        GradientDescent()
        .set_step_size(0.5)
        .set_num_iterations(500)
        .set_convergence_tol(0.0)
        .optimize((X, y), np.zeros(2, np.float32))
    )
    np.testing.assert_allclose(np.asarray(w), [1.0, 2.0], atol=0.15)


def test_repeat_optimize_hits_compile_cache():
    import time

    X, y, _ = linear_data(256, 4, seed=8)
    opt = GradientDescent().set_num_iterations(20).set_convergence_tol(0.0)
    w0 = np.zeros(4, np.float32)
    opt.optimize((X, y), w0)  # compile
    t0 = time.perf_counter()
    for _ in range(5):
        opt.optimize((X, y), w0)
    per_call = (time.perf_counter() - t0) / 5
    assert per_call < 0.05, f"repeat optimize too slow ({per_call:.3f}s) — retracing?"


def test_run_mini_batch_sgd_signature_parity():
    X, y, _ = linear_data(200, 3, seed=7)
    w, hist = run_mini_batch_sgd(
        data=(X, y),
        gradient=LeastSquaresGradient(),
        updater=SimpleUpdater(),
        step_size=0.5,
        num_iterations=30,
        reg_param=0.0,
        mini_batch_fraction=1.0,
        initial_weights=np.zeros(3, np.float32),
        convergence_tol=0.0,
    )
    assert len(hist) == 30
    assert hist[-1] < hist[0]


def test_sliced_sampling_converges():
    """sampling='sliced' (contiguous random window) reaches the same solution
    as bernoulli sampling on i.i.d. data."""
    import numpy as np

    from tpu_sgd.ops.gradients import LeastSquaresGradient
    from tpu_sgd.ops.updaters import SimpleUpdater
    from tpu_sgd.optimize.gradient_descent import GradientDescent
    from tpu_sgd.utils.mlutils import linear_data

    X, y, w_true = linear_data(4096, 12, eps=0.01, seed=11)
    opt = (
        GradientDescent(LeastSquaresGradient(), SimpleUpdater())
        .set_step_size(0.5)
        .set_num_iterations(120)
        .set_mini_batch_fraction(0.25)
        .set_convergence_tol(0.0)
        .set_sampling("sliced")
    )
    w, hist = opt.optimize_with_history((X, y), np.zeros(12, np.float32))
    assert len(hist) == 120 and hist[-1] < hist[0] * 0.1
    np.testing.assert_allclose(np.asarray(w), w_true, atol=0.05)


def test_sliced_sampling_under_dp_mesh():
    """Sliced sampling composes with shard_map data parallelism: each shard
    takes its own window; gradients are psum-combined."""
    import numpy as np

    from tpu_sgd.ops.gradients import LogisticGradient
    from tpu_sgd.ops.updaters import SquaredL2Updater
    from tpu_sgd.optimize.gradient_descent import GradientDescent
    from tpu_sgd.parallel.mesh import data_mesh
    from tpu_sgd.utils.mlutils import logistic_data

    X, y, w_true = logistic_data(4096, 8, seed=12)
    opt = (
        GradientDescent(LogisticGradient(), SquaredL2Updater())
        .set_step_size(1.0)
        .set_num_iterations(80)
        .set_reg_param(0.001)
        .set_mini_batch_fraction(0.25)
        .set_convergence_tol(0.0)
        .set_sampling("sliced")
        .set_mesh(data_mesh())
    )
    w, hist = opt.optimize_with_history((X, y), np.zeros(8, np.float32))
    assert hist[-1] < hist[0]
    acc = np.mean((np.asarray(X @ np.asarray(w)) > 0) == (y > 0.5))
    # ~0.76 is this noisy dataset's ceiling (bernoulli sampling reaches the
    # same); the point is parity, not separability.
    assert acc > 0.7


def test_sliced_sampling_ragged_shards():
    """n not divisible by the mesh: padding rows must stay invisible to the
    window sampler (valid-mask slicing)."""
    import numpy as np

    from tpu_sgd.ops.gradients import LeastSquaresGradient
    from tpu_sgd.ops.updaters import SimpleUpdater
    from tpu_sgd.optimize.gradient_descent import GradientDescent
    from tpu_sgd.parallel.mesh import data_mesh
    from tpu_sgd.utils.mlutils import linear_data

    X, y, w_true = linear_data(4001, 6, eps=0.01, seed=13)
    opt = (
        GradientDescent(LeastSquaresGradient(), SimpleUpdater())
        .set_step_size(0.5)
        .set_num_iterations(100)
        .set_mini_batch_fraction(0.5)
        .set_convergence_tol(0.0)
        .set_sampling("sliced")
        .set_mesh(data_mesh())
    )
    w, hist = opt.optimize_with_history((X, y), np.zeros(6, np.float32))
    assert np.all(np.isfinite(hist))
    np.testing.assert_allclose(np.asarray(w), w_true, atol=0.06)


def test_partial_residency_matches_plain_streaming():
    """resident_rows changes WHERE windows are read from (device prefix vs
    host transfer), never WHICH windows are drawn or what they compute: the
    trajectory must match plain streaming exactly, at every residency level
    including fully resident."""
    X, y, _ = linear_data(5000, 6, eps=0.01, seed=13)
    w0 = np.zeros(6, np.float32)

    def run(resident_rows):
        opt = (
            GradientDescent(LeastSquaresGradient(), SimpleUpdater())
            .set_step_size(0.4).set_num_iterations(80)
            .set_mini_batch_fraction(0.1).set_convergence_tol(0.0)
            .set_sampling("sliced")
            .set_host_streaming(True, resident_rows=resident_rows)
        )
        return opt.optimize_with_history((X, y), w0)

    w_plain, h_plain = run(0)
    for r in (1000, 3000, 5000):  # partial 20%/60%, fully resident
        w_r, h_r = run(r)
        np.testing.assert_allclose(np.asarray(w_r), np.asarray(w_plain),
                                   rtol=1e-6, atol=1e-7)
        # the two compiled programs (sliced-on-device vs transferred batch)
        # fuse differently -> ~1e-9 absolute reassociation noise in losses
        np.testing.assert_allclose(h_r, h_plain, rtol=1e-5, atol=1e-8)


def test_partial_residency_guards():
    """resident_rows misuse raises actionable errors instead of silently
    changing semantics."""
    from tpu_sgd.parallel.mesh import data_mesh

    X, y, _ = linear_data(1000, 4, seed=14)
    w0 = np.zeros(4, np.float32)

    def make(**hs):
        return (
            GradientDescent(LeastSquaresGradient(), SimpleUpdater())
            .set_num_iterations(3).set_mini_batch_fraction(0.1)
            .set_sampling("sliced")
            .set_host_streaming(True, **hs)
        )

    with pytest.raises(NotImplementedError, match="single device"):
        make(resident_rows=500).set_mesh(data_mesh()).optimize_with_history(
            (X, y), w0
        )
    with pytest.raises(NotImplementedError, match="sliced"):
        make(resident_rows=500).set_sampling("bernoulli") \
            .optimize_with_history((X, y), w0)
    with pytest.raises(ValueError, match="smaller than one window"):
        make(resident_rows=10).optimize_with_history((X, y), w0)


def test_partial_residency_via_train_api():
    """streaming_resident_rows is reachable from the user-facing train()
    and reproduces the plain streamed result."""
    from tpu_sgd.models import LinearRegressionWithSGD

    X, y, _ = linear_data(3000, 5, eps=0.01, seed=15)

    def fit(**kw):
        return LinearRegressionWithSGD.train(
            (X, y), num_iterations=60, step_size=0.4,
            mini_batch_fraction=0.2, sampling="sliced",
            host_streaming=True, **kw,
        )

    m_plain = fit()
    m_res = fit(streaming_resident_rows=2000)
    np.testing.assert_allclose(np.asarray(m_res.weights),
                               np.asarray(m_plain.weights),
                               rtol=1e-6, atol=1e-7)


def test_stepwise_numerics_reports_true_iteration(rng):
    """The stepwise (listener) driver checks one loss at a time; the
    numerics error must name the ACTUAL diverging iteration, not
    'iteration 1'."""
    from tpu_sgd.utils.events import SGDListener

    X = rng.normal(size=(256, 8)).astype(np.float32)
    y = (X @ rng.uniform(-1, 1, 8).astype(np.float32)).astype(np.float32)
    opt = (GradientDescent(LeastSquaresGradient(), SimpleUpdater())
           .set_step_size(1e12).set_num_iterations(10)
           .set_mini_batch_fraction(1.0).set_check_numerics(True)
           .set_listener(SGDListener()))
    with pytest.raises(FloatingPointError) as exc:
        opt.optimize_with_history((X, y), np.zeros(8, np.float32))
    import re

    reported = int(re.search(r"iteration (\d+)", str(exc.value)).group(1))
    assert reported > 1  # iteration 1 (w0=0) is always finite here


def test_host_streaming_validates_initial_weights(rng):
    """The host-streaming branch must raise the same clear ValueError
    as the resident paths on a wrong-length w0 — not an opaque XLA
    shape error inside the streamed step."""
    X = rng.normal(size=(128, 8)).astype(np.float32)
    y = rng.normal(size=(128,)).astype(np.float32)
    opt = GradientDescent().set_host_streaming(True)
    with pytest.raises(ValueError, match="initial_weights has length"):
        opt.optimize_with_history((X, y), np.zeros(5, np.float32))


def test_chunk_iters_warning_on_meshed_streamed_stats(rng):
    """The meshed streamed-stats route returns before the resident
    router; the dropped-chunk_iters warning must still fire there."""
    import warnings as _w

    from tpu_sgd import data_mesh

    X = rng.normal(size=(1024, 8)).astype(np.float32)
    y = (X @ rng.uniform(-1, 1, 8).astype(np.float32)).astype(np.float32)
    opt = (GradientDescent(LeastSquaresGradient(), SimpleUpdater())
           .set_num_iterations(3).set_mesh(data_mesh())
           .set_streamed_stats(True, block_rows=64)
           .set_gram_options(chunk_iters=4))
    with _w.catch_warnings(record=True) as rec:
        _w.simplefilter("always")
        opt.optimize_with_history((X, y), np.zeros(8, np.float32))
    assert any("chunk_iters applies" in str(r.message) for r in rec)
