"""Multinomial (K-class) logistic regression through the user API."""

import numpy as np
import pytest

from tpu_sgd.models import (
    LogisticRegressionWithLBFGS,
    MultinomialLogisticRegressionModel,
)


def _multiclass_data(n, d, K, seed=0):
    r = np.random.default_rng(seed)
    W = r.normal(size=(K, d)).astype(np.float32) * 2.0
    X = r.normal(size=(n, d)).astype(np.float32)
    logits = X @ W.T
    y = np.argmax(logits + r.gumbel(size=(n, K)), axis=1).astype(np.float32)
    return X, y, W


def test_multinomial_lbfgs_accuracy():
    K, d = 4, 10
    X, y, W = _multiclass_data(4000, d, K, seed=0)
    model = LogisticRegressionWithLBFGS.train((X, y), num_classes=K,
                                              reg_param=0.001)
    assert isinstance(model, MultinomialLogisticRegressionModel)
    pred = np.asarray(model.predict(X))
    acc = np.mean(pred == y)
    bayes = np.mean(np.argmax(X @ W.T, axis=1) == y)
    assert acc > bayes - 0.05
    assert set(np.unique(pred)) <= set(float(k) for k in range(K))


def test_multinomial_with_intercept():
    K, d = 3, 6
    X, y, _ = _multiclass_data(2000, d, K, seed=1)
    model = LogisticRegressionWithLBFGS.train((X, y), num_classes=K,
                                              intercept=True)
    # bias column folded in: num_features includes it
    assert model.num_features == d + 1
    assert model.predict(X).shape == (2000,)


def test_multinomial_k2_equals_binary():
    X, y, _ = _multiclass_data(1000, 5, 2, seed=2)
    m_bin = LogisticRegressionWithLBFGS.train((X, y))
    m_k2 = LogisticRegressionWithLBFGS.train((X, y), num_classes=2)
    np.testing.assert_allclose(np.asarray(m_bin.weights),
                               np.asarray(m_k2.weights), rtol=1e-4, atol=1e-5)


def test_multinomial_label_validation():
    X = np.zeros((10, 3), np.float32)
    y = np.full((10,), 5.0, np.float32)
    with pytest.raises(ValueError, match="in \\[0, 3\\)"):
        LogisticRegressionWithLBFGS.train((X, y), num_classes=3)


def test_multinomial_save_load_roundtrip(tmp_path):
    K, d = 3, 5
    X, y, _ = _multiclass_data(800, d, K, seed=4)
    model = LogisticRegressionWithLBFGS.train((X, y), num_classes=K,
                                              intercept=True)
    path = str(tmp_path / "m")
    model.save(path)
    loaded = MultinomialLogisticRegressionModel.load(path)
    assert loaded.num_classes == K
    assert loaded.has_intercept_column
    np.testing.assert_array_equal(np.asarray(loaded.predict(X)),
                                  np.asarray(model.predict(X)))


def test_single_vector_predict():
    K, d = 3, 4
    X, y, _ = _multiclass_data(500, d, K, seed=3)
    model = LogisticRegressionWithLBFGS.train((X, y), num_classes=K)
    single = model.predict(X[0])
    assert np.asarray(single).shape == ()


def test_multinomial_sgd_dp_mesh_parity():
    """Multinomial gradient under the 8-way data mesh matches single-device
    (the matrix-weight pytree flattens through the same psum path)."""
    from tpu_sgd.config import SGDConfig
    from tpu_sgd.ops.gradients import MultinomialLogisticGradient
    from tpu_sgd.ops.updaters import SimpleUpdater
    from tpu_sgd.optimize.gradient_descent import GradientDescent
    from tpu_sgd.parallel.mesh import data_mesh

    K, d = 3, 6
    X, y, _ = _multiclass_data(2000, d, K, seed=5)
    g = MultinomialLogisticGradient(K)
    w0 = np.zeros(((K - 1) * d,), np.float32)

    def make():
        return GradientDescent(
            g, SimpleUpdater(),
            SGDConfig(step_size=0.5, num_iterations=30,
                      mini_batch_fraction=1.0, convergence_tol=0.0),
        )

    w1, h1 = make().optimize_with_history((X, y), w0)
    opt8 = make().set_mesh(data_mesh())
    w8, h8 = opt8.optimize_with_history((X, y), w0)
    np.testing.assert_allclose(np.asarray(w8), np.asarray(w1), rtol=2e-4,
                               atol=2e-5)
    np.testing.assert_allclose(h8, h1, rtol=2e-4)
