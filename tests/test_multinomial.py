"""Multinomial (K-class) logistic regression through the user API."""

import numpy as np
import pytest

from tpu_sgd.models import (
    LogisticRegressionWithLBFGS,
    MultinomialLogisticRegressionModel,
)


def _multiclass_data(n, d, K, seed=0):
    r = np.random.default_rng(seed)
    W = r.normal(size=(K, d)).astype(np.float32) * 2.0
    X = r.normal(size=(n, d)).astype(np.float32)
    logits = X @ W.T
    y = np.argmax(logits + r.gumbel(size=(n, K)), axis=1).astype(np.float32)
    return X, y, W


def test_multinomial_lbfgs_accuracy():
    K, d = 4, 10
    X, y, W = _multiclass_data(4000, d, K, seed=0)
    model = LogisticRegressionWithLBFGS.train((X, y), num_classes=K,
                                              reg_param=0.001)
    assert isinstance(model, MultinomialLogisticRegressionModel)
    pred = np.asarray(model.predict(X))
    acc = np.mean(pred == y)
    bayes = np.mean(np.argmax(X @ W.T, axis=1) == y)
    assert acc > bayes - 0.05
    assert set(np.unique(pred)) <= set(float(k) for k in range(K))


def test_multinomial_with_intercept():
    K, d = 3, 6
    X, y, _ = _multiclass_data(2000, d, K, seed=1)
    model = LogisticRegressionWithLBFGS.train((X, y), num_classes=K,
                                              intercept=True)
    # bias column folded in: num_features includes it
    assert model.num_features == d + 1
    assert model.predict(X).shape == (2000,)


def test_multinomial_k2_equals_binary():
    X, y, _ = _multiclass_data(1000, 5, 2, seed=2)
    m_bin = LogisticRegressionWithLBFGS.train((X, y))
    m_k2 = LogisticRegressionWithLBFGS.train((X, y), num_classes=2)
    np.testing.assert_allclose(np.asarray(m_bin.weights),
                               np.asarray(m_k2.weights), rtol=1e-4, atol=1e-5)


def test_multinomial_label_validation():
    X = np.zeros((10, 3), np.float32)
    y = np.full((10,), 5.0, np.float32)
    with pytest.raises(ValueError, match="in \\[0, 3\\)"):
        LogisticRegressionWithLBFGS.train((X, y), num_classes=3)


def test_multinomial_save_load_roundtrip(tmp_path):
    K, d = 3, 5
    X, y, _ = _multiclass_data(800, d, K, seed=4)
    model = LogisticRegressionWithLBFGS.train((X, y), num_classes=K,
                                              intercept=True)
    path = str(tmp_path / "m")
    model.save(path)
    loaded = MultinomialLogisticRegressionModel.load(path)
    assert loaded.num_classes == K
    assert loaded.has_intercept_column
    np.testing.assert_array_equal(np.asarray(loaded.predict(X)),
                                  np.asarray(model.predict(X)))


def test_single_vector_predict():
    K, d = 3, 4
    X, y, _ = _multiclass_data(500, d, K, seed=3)
    model = LogisticRegressionWithLBFGS.train((X, y), num_classes=K)
    single = model.predict(X[0])
    assert np.asarray(single).shape == ()


def test_multinomial_sgd_dp_mesh_parity():
    """Multinomial gradient under the 8-way data mesh matches single-device
    (the matrix-weight pytree flattens through the same psum path)."""
    from tpu_sgd.config import SGDConfig
    from tpu_sgd.ops.gradients import MultinomialLogisticGradient
    from tpu_sgd.ops.updaters import SimpleUpdater
    from tpu_sgd.optimize.gradient_descent import GradientDescent
    from tpu_sgd.parallel.mesh import data_mesh

    K, d = 3, 6
    X, y, _ = _multiclass_data(2000, d, K, seed=5)
    g = MultinomialLogisticGradient(K)
    w0 = np.zeros(((K - 1) * d,), np.float32)

    def make():
        return GradientDescent(
            g, SimpleUpdater(),
            SGDConfig(step_size=0.5, num_iterations=30,
                      mini_batch_fraction=1.0, convergence_tol=0.0),
        )

    w1, h1 = make().optimize_with_history((X, y), w0)
    opt8 = make().set_mesh(data_mesh())
    w8, h8 = opt8.optimize_with_history((X, y), w0)
    np.testing.assert_allclose(np.asarray(w8), np.asarray(w1), rtol=2e-4,
                               atol=2e-5)
    np.testing.assert_allclose(h8, h1, rtol=2e-4)


def test_multinomial_loss_sweep_matches_per_trial():
    """The stacked line-search sweep equals T independent batch_sums losses
    (with and without a Bernoulli mask)."""
    import jax.numpy as jnp

    from tpu_sgd.ops.gradients import MultinomialLogisticGradient

    K, d, T = 4, 7, 6
    X, y, _ = _multiclass_data(300, d, K, seed=6)
    g = MultinomialLogisticGradient(K)
    r = np.random.default_rng(7)
    W = r.normal(size=(T, (K - 1) * d)).astype(np.float32)
    mask = (r.random(300) < 0.5).astype(np.float32)

    for m in (None, jnp.asarray(mask)):
        sums, count = g.loss_sweep(jnp.asarray(X), jnp.asarray(y),
                                   jnp.asarray(W), mask=m)
        for t in range(T):
            _, l_t, c_t = g.batch_sums(jnp.asarray(X), jnp.asarray(y),
                                       jnp.asarray(W[t]), mask=m)
            np.testing.assert_allclose(float(sums[t]), float(l_t), rtol=1e-5)
            np.testing.assert_allclose(float(count), float(c_t))


class _NoSweep:
    """A gradient with ``loss_sweep`` hidden: forces LBFGS/OWLQN's
    sequential line-search fallback branch (shared by the swept-vs-
    sequential parity tests)."""

    def __init__(self, inner):
        self._inner = inner

    def __getattr__(self, name):
        if name == "loss_sweep":
            raise AttributeError(name)
        return getattr(self._inner, name)


def test_multinomial_lbfgs_swept_equals_sequential():
    """The batched multinomial line-search ladder (one host sync/iter) must
    reproduce the sequential scalar ladder's trajectory exactly — same
    Armijo test, same largest-first acceptance order."""
    from tpu_sgd.ops.gradients import MultinomialLogisticGradient
    from tpu_sgd.optimize.lbfgs import LBFGS

    K, d = 3, 6
    X, y, _ = _multiclass_data(1500, d, K, seed=8)
    w0 = np.zeros(((K - 1) * d,), np.float32)

    g = MultinomialLogisticGradient(K)
    w_swept, h_swept = LBFGS(g, max_num_iterations=15).optimize_with_history(
        (X, y), w0
    )
    w_seq, h_seq = LBFGS(
        _NoSweep(MultinomialLogisticGradient(K)), max_num_iterations=15
    ).optimize_with_history((X, y), w0)
    assert not hasattr(_NoSweep(g), "loss_sweep")
    np.testing.assert_allclose(np.asarray(w_swept), np.asarray(w_seq),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(h_swept, h_seq, rtol=1e-5)


def test_multinomial_owlqn_swept_equals_sequential():
    """OWL-QN's orthant-projected ladder goes through the matrix-weight
    sweep as well (was: 30 sequential host syncs per iteration); the
    batched ladder must reproduce the sequential ladder's trajectory —
    same orthant projection, same Armijo-on-projected-step test."""
    from tpu_sgd.ops.gradients import MultinomialLogisticGradient
    from tpu_sgd.optimize.owlqn import OWLQN

    K, d = 3, 5
    X, y, _ = _multiclass_data(1200, d, K, seed=9)
    w0 = np.zeros(((K - 1) * d,), np.float32)

    def run(g):
        opt = OWLQN(g, reg_param=0.01, max_num_iterations=20)
        return opt.optimize_with_history((X, y), w0)

    w_swept, h_swept = run(MultinomialLogisticGradient(K))
    w_seq, h_seq = run(_NoSweep(MultinomialLogisticGradient(K)))
    assert h_swept[-1] < h_swept[0]
    np.testing.assert_allclose(np.asarray(w_swept), np.asarray(w_seq),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(h_swept, h_seq, rtol=1e-5)


def test_multinomial_loss_sweep_chunked_matches_unchunked(monkeypatch):
    """The memory-bounding trial chunking is invisible in the results:
    force a multi-chunk sweep (chunk=2 < T=7, incl. an odd tail chunk) by
    shrinking the element budget and compare against the single-pass sweep
    and per-trial evaluations."""
    import jax.numpy as jnp

    from tpu_sgd.ops import gradients as G

    K, d, T = 3, 6, 7
    n = 200
    X, y, _ = _multiclass_data(n, d, K, seed=11)
    g = G.MultinomialLogisticGradient(K)
    W = np.random.default_rng(12).normal(size=(T, (K - 1) * d)).astype(
        np.float32
    )
    Xj, yj, Wj = jnp.asarray(X), jnp.asarray(y), jnp.asarray(W)
    full, c_full = g.loss_sweep(Xj, yj, Wj)  # chunk == T: single pass
    monkeypatch.setattr(G, "SWEEP_BUDGET_ELEMS", 2 * n * K)  # chunk == 2
    chunked, c_chunked = g.loss_sweep(Xj, yj, Wj)
    np.testing.assert_allclose(np.asarray(chunked), np.asarray(full),
                               rtol=1e-6)
    np.testing.assert_allclose(float(c_chunked), float(c_full))
    per_trial = [
        float(g.loss_sweep(Xj, yj, Wj[t:t + 1])[0][0]) for t in range(T)
    ]
    np.testing.assert_allclose(np.asarray(full), per_trial, rtol=1e-5)


def test_sequential_fallback_warns_once_per_optimize():
    """A sweep-less gradient sends LBFGS/OWL-QN down the per-trial
    host-sync ladder; the framework must say so (VERDICT r3 weak #5),
    naming the ``loss_sweep`` protocol to implement."""
    import warnings

    from tpu_sgd.ops.gradients import MultinomialLogisticGradient
    from tpu_sgd.optimize.lbfgs import LBFGS
    from tpu_sgd.optimize.owlqn import OWLQN

    K, d = 3, 5
    X, y, _ = _multiclass_data(400, d, K, seed=11)
    w0 = np.zeros(((K - 1) * d,), np.float32)

    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        LBFGS(_NoSweep(MultinomialLogisticGradient(K)),
              max_num_iterations=3).optimize_with_history((X, y), w0)
    msgs = [str(r.message) for r in rec
            if issubclass(r.category, RuntimeWarning)]
    assert sum("loss_sweep" in m and "SEQUENTIAL" in m for m in msgs) == 1

    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        OWLQN(_NoSweep(MultinomialLogisticGradient(K)), reg_param=0.01,
              max_num_iterations=3).optimize_with_history((X, y), w0)
    msgs = [str(r.message) for r in rec
            if issubclass(r.category, RuntimeWarning)]
    assert sum("loss_sweep" in m and "SEQUENTIAL" in m for m in msgs) == 1

    # swept gradients stay silent
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        LBFGS(MultinomialLogisticGradient(K),
              max_num_iterations=3).optimize_with_history((X, y), w0)
    assert not any("loss_sweep" in str(r.message) for r in rec)
