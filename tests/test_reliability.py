"""Reliability subsystem (tpu_sgd/reliability): fault injection, retry/
backoff/breaker policies, preemption-safe supervised training, health
monitoring — and the measured-no-op contract for disabled failpoints."""

import os
import time

import numpy as np
import pytest

import tpu_sgd.reliability.failpoints as fp
from tpu_sgd.reliability import (
    CircuitBreaker,
    Deadline,
    DeadlineExceeded,
    FaultInjected,
    Heartbeat,
    HealthMonitor,
    RetriesExhausted,
    RetryPolicy,
    TrainingPreempted,
    TrainingSupervisor,
    fail_nth,
    fail_prob,
    inject_faults,
    inject_latency,
)
from tpu_sgd.utils.checkpoint import CheckpointManager
from tpu_sgd.utils.events import (
    CollectingListener,
    JsonLinesEventLog,
    ReliabilityEvent,
)


def _build_data(rng, n=512, d=8):
    X = rng.normal(size=(n, d)).astype(np.float32)
    w = rng.normal(size=d).astype(np.float32)
    y = (X @ w + 0.01 * rng.normal(size=n)).astype(np.float32)
    return X, y


def _streamed_opt(iters=16, sampling="sliced", seed=7):
    from tpu_sgd.optimize.gradient_descent import GradientDescent

    return (GradientDescent()
            .set_num_iterations(iters).set_step_size(0.1)
            .set_mini_batch_fraction(0.5).set_sampling(sampling)
            .set_convergence_tol(0.0).set_seed(seed)
            .set_host_streaming(True))


# -- (a) failpoints ---------------------------------------------------------

def test_fail_nth_is_one_shot():
    with inject_faults({"t.site": fail_nth(2)}):
        fp.failpoint("t.site")  # hit 1: pass
        with pytest.raises(FaultInjected):
            fp.failpoint("t.site")  # hit 2: trigger
        fp.failpoint("t.site")  # hit 3: healed (one-shot)
        assert fp.hits("t.site") == 3
        assert fp.triggers("t.site") == 1
    assert not fp.is_enabled()
    assert fp.hits("t.site") == 0  # counters cleared on deactivate


def test_fail_prob_replays_bitwise_from_seed():
    def pattern():
        out = []
        with inject_faults({"t.p": fail_prob(0.3, seed=5)}):
            for _ in range(64):
                try:
                    fp.failpoint("t.p")
                    out.append(0)
                except FaultInjected:
                    out.append(1)
        return out

    a, b = pattern(), pattern()
    assert a == b  # seeded stream: identical schedule
    assert 0 < sum(a) < 64  # actually fires, not always


def test_inject_latency_delays_without_raising():
    with inject_faults({"t.l": inject_latency(30.0)}):
        t0 = time.perf_counter()
        fp.failpoint("t.l")
        assert time.perf_counter() - t0 >= 0.025


def test_custom_exception_class():
    with inject_faults({"t.e": fail_nth(1, exc=OSError)}):
        with pytest.raises(OSError):
            fp.failpoint("t.e")


def test_spec_rejects_conflicting_modes():
    with pytest.raises(ValueError):
        fp.FailpointSpec(nth=2, prob=0.5)
    with pytest.raises(ValueError):
        fp.FailpointSpec(prob=1.5)


def test_disabled_failpoint_is_a_measured_noop():
    """Acceptance criterion: the disabled-mode cost is one global load
    and a branch — sub-microsecond per call even on this noisy 2-core
    host (the bound is ~20x the measured mean for CI headroom)."""
    assert not fp.is_enabled()
    n = 200_000
    t0 = time.perf_counter()
    for _ in range(n):
        fp.failpoint("io.prefetch.produce")
    per_call = (time.perf_counter() - t0) / n
    assert per_call < 2e-6, f"disabled failpoint costs {per_call*1e9:.0f}ns"


def test_streamed_build_unaffected_by_inactive_registry(rng):
    """Acceptance criterion: a streamed statistics build with the
    failpoint registry present-but-inactive matches the same build with
    the hooks compiled out entirely (monkeypatched to a no-op lambda) —
    i.e. the pre-PR build path — within ambient noise.  The 2-core
    harness is DRAM-wall noisy (bimodal up to ~1.7x on overlap paths),
    so the bound is deliberately loose; the tight per-call bound above
    is the real no-op evidence."""
    from tpu_sgd.io import prefetch as prefetch_mod
    from tpu_sgd.ops.gram import GramLeastSquaresGradient

    X, y = _build_data(rng, n=4096, d=16)

    def build_time():
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            GramLeastSquaresGradient.build_streamed(
                X, y, block_rows=256, batch_rows=512)
            best = min(best, time.perf_counter() - t0)
        return best

    GramLeastSquaresGradient.build_streamed(  # warm the jit caches
        X, y, block_rows=256, batch_rows=512)
    with_hooks = build_time()
    saved = prefetch_mod.failpoint
    try:
        prefetch_mod.failpoint = lambda name: None  # hooks compiled out
        without_hooks = build_time()
    finally:
        prefetch_mod.failpoint = saved
    assert with_hooks < max(without_hooks * 2.0, without_hooks + 0.05), (
        f"inactive failpoints slowed the build: {with_hooks:.4f}s vs "
        f"{without_hooks:.4f}s without hooks")


# -- (b) retry / deadline / breaker ----------------------------------------

def test_retry_policy_heals_transient_fault():
    calls = []

    def flaky():
        calls.append(1)
        fp.failpoint("t.r")
        return 42

    pol = RetryPolicy(max_attempts=3, base_backoff_s=1e-4, seed=0)
    with inject_faults({"t.r": fail_nth(1)}):
        assert pol.call(flaky) == 42
    assert len(calls) == 2


def test_retry_policy_exhausts_with_cause():
    pol = RetryPolicy(max_attempts=3, base_backoff_s=1e-4)

    def always():
        raise OSError("disk on fire")

    with pytest.raises(RetriesExhausted) as ei:
        pol.call(always)
    assert isinstance(ei.value.__cause__, OSError)


def test_retry_policy_nonretryable_propagates_immediately():
    calls = []

    def fatal():
        calls.append(1)
        raise ValueError("shape mismatch")

    with pytest.raises(ValueError):
        RetryPolicy(max_attempts=5, base_backoff_s=1e-4).call(fatal)
    assert len(calls) == 1  # no retry burned on a non-transient error


def test_retry_backoff_seeded_and_capped():
    a = RetryPolicy(base_backoff_s=0.1, multiplier=2.0, max_backoff_s=0.3,
                    jitter=0.5, seed=3)
    b = RetryPolicy(base_backoff_s=0.1, multiplier=2.0, max_backoff_s=0.3,
                    jitter=0.5, seed=3)
    seq_a = [a.backoff_s(k) for k in range(1, 6)]
    seq_b = [b.backoff_s(k) for k in range(1, 6)]
    assert seq_a == seq_b  # same seed, same schedule
    assert all(0 < s <= 0.3 for s in seq_a)  # cap holds through jitter
    # jitter scales in [1 - j, 1]: retry 1 sleeps at least half the base
    assert seq_a[0] >= 0.05


def test_deadline_check_and_retry_integration():
    d = Deadline(0.05)
    assert d.remaining_s > 0 and not d.expired
    time.sleep(0.06)
    assert d.expired
    with pytest.raises(DeadlineExceeded):
        d.check("unit test")
    # an expired deadline stops the retry loop before the next attempt
    pol = RetryPolicy(max_attempts=10, base_backoff_s=1e-4)
    calls = []

    def failing():
        calls.append(1)
        raise OSError("x")

    with pytest.raises(DeadlineExceeded):
        pol.call(failing, deadline=d)
    assert len(calls) == 0


def test_circuit_breaker_lifecycle():
    br = CircuitBreaker(failure_threshold=2, reset_timeout_s=0.05)
    assert br.state == "closed" and br.allow()
    br.record_failure()
    assert br.state == "closed"
    br.record_failure()
    assert br.state == "open" and not br.allow()
    time.sleep(0.06)
    assert br.state == "half_open" and br.allow()  # cooldown: one probe
    br.record_failure()  # failed probe: re-open with fresh cooldown
    assert br.state == "open" and br.total_opens == 2
    time.sleep(0.06)
    br.record_success()  # successful probe closes
    assert br.state == "closed" and br.allow()


# -- (c) prefetcher reliability --------------------------------------------

def test_prefetcher_retry_heals_producer_fault():
    from tpu_sgd.io import Prefetcher

    pol = RetryPolicy(max_attempts=3, base_backoff_s=1e-4)
    with inject_faults({"io.prefetch.produce": fail_nth(2)}):
        with Prefetcher(lambda i: i * i, range(6), depth=2,
                        retry_policy=pol) as pf:
            assert list(pf) == [i * i for i in range(6)]  # order kept


def test_prefetcher_fault_propagates_without_retry():
    from tpu_sgd.io import Prefetcher

    with inject_faults({"io.prefetch.produce": fail_nth(2)}):
        with pytest.raises(FaultInjected):
            list(Prefetcher(lambda i: i, range(6), depth=2))


def test_prefetcher_heartbeat_ticks_per_chunk():
    from tpu_sgd.io import Prefetcher

    hb = Heartbeat("ingest")
    with Prefetcher(lambda i: i, range(5), depth=2, heartbeat=hb) as pf:
        list(pf)
    assert hb.count == 5
    assert hb.age_s() is not None


# -- (d) checkpoint reliability (satellite) --------------------------------

def test_checkpoint_save_fault_leaves_no_partial_files(tmp_path):
    cm = CheckpointManager(str(tmp_path))
    with inject_faults({"checkpoint.save": fail_nth(1)}):
        with pytest.raises(FaultInjected):
            cm.save(1, np.ones(4), 0.0, np.zeros(1))
    assert os.listdir(str(tmp_path)) == []  # injected BEFORE any byte
    cm.save(1, np.ones(4), 0.0, np.zeros(1))  # healed
    assert cm.latest_version() == 1


def test_double_corrupt_restore_falls_back_and_names_quarantined(
        tmp_path, caplog):
    """Satellite: the latest TWO checkpoints torn — restore must fall
    back to the third, quarantine both, and name them in the warning
    and the on_corruption hook (no more silent skips)."""
    import logging

    seen = []
    cm = CheckpointManager(
        str(tmp_path), on_corruption=lambda p, q, e: seen.append((p, q)))
    for i in (1, 2, 3):
        cm.save(i, np.full(4, float(i)), 0.0, np.zeros(1))
    for i in (2, 3):
        p = cm._path(i)
        with open(p, "r+b") as f:
            f.truncate(os.path.getsize(p) // 2)
    with caplog.at_level(logging.WARNING, logger="tpu_sgd.checkpoint"):
        state = cm.restore()
    assert state is not None and state["iteration"] == 1
    np.testing.assert_array_equal(state["weights"], np.full(4, 1.0))
    assert len(seen) == 2
    for orig, quarantined in seen:
        assert quarantined is not None
        assert os.path.exists(quarantined)  # kept for forensics
        assert os.path.basename(quarantined).startswith(".bad_")
        assert quarantined in caplog.text  # warning names the new path
    assert cm.versions() == [1]  # bad files left the numbered namespace


def test_checkpoint_load_failpoint_exercises_fallback(tmp_path):
    cm = CheckpointManager(str(tmp_path))
    for i in (1, 2):
        cm.save(i, np.full(4, float(i)), 0.0, np.zeros(1))
    # one-shot load fault hits the NEWEST first; fallback lands on v1
    with inject_faults({"checkpoint.load": fail_nth(1)}):
        state = cm.restore()
    assert state["iteration"] == 1


def test_restore_transient_io_error_does_not_quarantine(tmp_path):
    """Review finding: a one-off OSError (NFS hiccup) on a fully VALID
    newest checkpoint must fall back for THIS restore but never
    quarantine the file — the next restore gets it back (same
    transient/corruption carve-out as the serve registry)."""
    seen = []
    cm = CheckpointManager(
        str(tmp_path), on_corruption=lambda p, q, e: seen.append(p))
    for i in (1, 2):
        cm.save(i, np.full(4, float(i)), 0.0, np.zeros(1))
    with inject_faults({"checkpoint.load": fail_nth(1, exc=OSError)}):
        state = cm.restore()
    assert state["iteration"] == 1  # fell back past the hiccup
    assert seen == []  # not reported as corruption
    assert cm.versions() == [1, 2]  # newest checkpoint untouched
    assert cm.restore()["iteration"] == 2  # healed: newest loads again


# -- (e) event log (satellite) ---------------------------------------------

def test_event_log_read_skips_torn_tail(tmp_path):
    path = str(tmp_path / "ev.jsonl")
    log = JsonLinesEventLog(path, fsync=True)  # durability knob
    log.on_reliability(ReliabilityEvent(kind="heartbeat", source="t",
                                        value=1.0))
    log.on_reliability(ReliabilityEvent(kind="retry", source="t"))
    log.close()
    with open(path, "a") as f:
        f.write('{"kind": "torn_mid')  # crash-truncated tail
    events = JsonLinesEventLog.read(path)
    assert [e["kind"] for e in events] == [
        "reliability_heartbeat", "reliability_retry"]
    assert events[0]["source"] == "t" and events[0]["value"] == 1.0


def test_event_log_read_raises_on_mid_file_corruption(tmp_path):
    import json

    path = str(tmp_path / "ev.jsonl")
    with open(path, "w") as f:
        f.write('{"kind": "a"}\nnot json\n{"kind": "b"}\n')
    with pytest.raises(json.JSONDecodeError):
        JsonLinesEventLog.read(path)  # only the TAIL is forgivable


def test_event_log_read_raises_on_terminated_bad_last_line(tmp_path):
    """Review finding: a newline-TERMINATED bad final line is a fully
    written corrupt record (writer bug / manual edit), not a torn
    tail — read() must raise, not silently drop it."""
    import json

    path = str(tmp_path / "ev.jsonl")
    with open(path, "w") as f:
        f.write('{"kind": "a"}\nnot json\n')  # complete but corrupt
    with pytest.raises(json.JSONDecodeError):
        JsonLinesEventLog.read(path)


# -- (f) serve-side reliability --------------------------------------------

def _trained_registry_dir(tmp_path, rng, iters=6):
    X, y = _build_data(rng, n=256, d=6)
    opt = _streamed_opt(iters=iters)
    opt.set_checkpoint(CheckpointManager(str(tmp_path)), every=2)
    opt.optimize_with_history((X, y), np.zeros(6, np.float32))
    return X


def test_registry_breaker_opens_and_short_circuits(tmp_path, rng):
    from tpu_sgd.models import LinearRegressionModel
    from tpu_sgd.serve import ModelRegistry

    _trained_registry_dir(tmp_path, rng)
    br = CircuitBreaker(failure_threshold=2, reset_timeout_s=30.0)
    registry = ModelRegistry(
        str(tmp_path), lambda w, b: LinearRegressionModel(w, b),
        breaker=br)
    # every reload attempt faults: transient branch, breaker counts
    with inject_faults({"serve.registry.reload": fail_prob(1.0, seed=0)}):
        assert registry.maybe_reload() is False
        assert registry.maybe_reload() is False
        assert br.state == "open"
        hits_when_open = fp.hits("serve.registry.reload")
        # OPEN: no directory walk, no load attempt, no failpoint hit
        assert registry.maybe_reload() is False
        assert fp.hits("serve.registry.reload") == hits_when_open
    assert registry.healthz()["breaker"]["state"] == "open"


def test_registry_degrades_to_previous_good_model(tmp_path, rng):
    from tpu_sgd.models import LinearRegressionModel
    from tpu_sgd.serve import ModelRegistry

    _trained_registry_dir(tmp_path, rng)
    registry = ModelRegistry(
        str(tmp_path), lambda w, b: LinearRegressionModel(w, b))
    registry.maybe_reload()
    v0 = registry.current_version
    assert v0 is not None
    model_before = registry.model()
    # a NEWER checkpoint appears but every load of it faults: serving
    # keeps the previous-good model (rollback is the absence of a swap)
    cm = registry.manager
    cm.save(v0 + 10, np.zeros(6, np.float32), 0.0, np.zeros(1))
    with inject_faults({"serve.registry.reload": fail_prob(1.0, seed=0)}):
        assert registry.maybe_reload() is False
        assert registry.current_version == v0
        assert registry.model() is model_before
    assert registry.maybe_reload() is True  # faults gone: catches up
    assert registry.current_version == v0 + 10


def test_server_healthz_snapshot(tmp_path, rng):
    from tpu_sgd.models import LinearRegressionModel
    from tpu_sgd.serve import ModelRegistry, Server

    X = _trained_registry_dir(tmp_path, rng)
    registry = ModelRegistry(
        str(tmp_path), lambda w, b: LinearRegressionModel(w, b),
        breaker=CircuitBreaker())
    with Server(registry=registry, max_latency_s=0.002) as server:
        server.predict(X[0], timeout=10)
        h = server.healthz()
    assert h["serving"] is True
    assert h["model_version"] == registry.current_version
    assert h["queue_depth"] == 0
    assert h["batch_count"] >= 1
    assert h["flush_heartbeat_age_s"] is not None
    assert h["registry"]["pinned"] is False
    assert h["registry"]["breaker"]["state"] == "closed"
    assert server.healthz()["serving"] is False  # stopped


def test_batcher_enqueue_failpoint_sheds_single_request(rng):
    from tpu_sgd.models import LinearRegressionModel
    from tpu_sgd.serve import Server

    model = LinearRegressionModel(
        rng.normal(size=6).astype(np.float32), 0.0)
    X = rng.normal(size=(4, 6)).astype(np.float32)
    with Server(model, max_latency_s=0.002) as server:
        with inject_faults({"serve.batcher.enqueue": fail_nth(2)}):
            a = server.submit(X[0])
            with pytest.raises(FaultInjected):
                server.submit(X[1])  # admission fault: this one sheds
            b = server.submit(X[2])
            got = [a.result(timeout=10), b.result(timeout=10)]
    want = np.asarray(model.predict(X[[0, 2]]))
    np.testing.assert_array_equal(np.asarray(got), want)


# -- (g) supervisor: crash-resume + preemption (satellite) ------------------

@pytest.mark.parametrize("mode", ["sliced", "indexed", "bernoulli"])
def test_kill_and_resume_bitwise_all_sampling_modes(tmp_path, mode, rng):
    """Satellite: failpoint-crash a streamed GD run mid-iteration,
    resume under the supervisor, and require the final weights AND the
    full loss trajectory bitwise equal to the fault-free run."""
    X, y = _build_data(rng)
    w0 = np.zeros(8, np.float32)
    w_ref, h_ref = _streamed_opt(sampling=mode).optimize_with_history(
        (X, y), w0)
    sup = TrainingSupervisor(
        _streamed_opt(sampling=mode),
        checkpoint_manager=CheckpointManager(str(tmp_path)),
        checkpoint_every=3,
        retry=RetryPolicy(max_attempts=4, base_backoff_s=1e-4),
        install_signal_handlers=False)
    with inject_faults({"optimize.streamed.step": fail_nth(9)}):
        res = sup.run((X, y), w0)
    assert res.completed and res.attempts == 2
    np.testing.assert_array_equal(np.asarray(res.weights),
                                  np.asarray(w_ref))
    np.testing.assert_array_equal(res.loss_history, h_ref)


def test_supervisor_preempt_checkpoints_and_resumes_bitwise(tmp_path, rng):
    X, y = _build_data(rng)
    w0 = np.zeros(8, np.float32)
    w_ref, h_ref = _streamed_opt().optimize_with_history((X, y), w0)

    events = CollectingListener()
    opt = _streamed_opt()
    sup = TrainingSupervisor(
        opt, checkpoint_manager=CheckpointManager(str(tmp_path)),
        checkpoint_every=100,  # cadence never fires: preempt must save
        listener=events, install_signal_handlers=False)

    count = [0]

    class Stopper:
        def on_run_start(self, c): ...

        def on_iteration(self, ev):
            count[0] += 1
            if count[0] == 5:
                sup.request_preempt()

        def on_run_end(self, ev): ...

    opt.set_listener(Stopper())
    res = sup.run((X, y), w0)
    assert res.status == "preempted" and res.preempted_at == 5
    # the preemption-path save captured iteration 5 exactly
    assert CheckpointManager(str(tmp_path)).latest_version() == 5
    assert any(e.kind == "preempted" for e in events.reliability)
    opt.set_listener(None)
    res2 = sup.run((X, y), w0)  # fresh run(): preempt flag cleared
    assert res2.completed
    np.testing.assert_array_equal(np.asarray(res2.weights),
                                  np.asarray(w_ref))
    np.testing.assert_array_equal(res2.loss_history, h_ref)


def test_supervisor_stepwise_path_preempts_too(tmp_path, rng):
    """set_stop_signal also covers the resident observed (listener/
    checkpoint) path — preempt there checkpoints the current iteration
    and the rerun resumes to the same final weights."""
    from tpu_sgd.optimize.gradient_descent import GradientDescent

    X, y = _build_data(rng, n=256, d=6)
    w0 = np.zeros(6, np.float32)

    def make():
        return (GradientDescent().set_num_iterations(12)
                .set_step_size(0.1).set_convergence_tol(0.0))

    ref = make()
    ref.set_checkpoint(CheckpointManager(str(tmp_path / "ref")), every=50)
    w_ref, h_ref = ref.optimize_with_history((X, y), w0)

    opt = make()
    sup = TrainingSupervisor(
        opt, checkpoint_manager=CheckpointManager(str(tmp_path / "s")),
        checkpoint_every=50, install_signal_handlers=False)
    n = [0]

    class Stop:
        def on_run_start(self, c): ...

        def on_iteration(self, ev):
            n[0] += 1
            if n[0] == 4:
                sup.request_preempt()

        def on_run_end(self, ev): ...

    opt.set_listener(Stop())
    res = sup.run((X, y), w0)
    assert res.status == "preempted" and res.preempted_at == 4
    opt.set_listener(None)
    res2 = sup.run((X, y), w0)
    assert res2.completed
    np.testing.assert_array_equal(np.asarray(res2.weights),
                                  np.asarray(w_ref))
    np.testing.assert_array_equal(res2.loss_history, h_ref)


def test_supervisor_gives_up_after_retry_budget(tmp_path, rng):
    X, y = _build_data(rng, n=256, d=6)
    sup = TrainingSupervisor(
        _streamed_opt(iters=8),
        checkpoint_manager=CheckpointManager(str(tmp_path)),
        retry=RetryPolicy(max_attempts=2, base_backoff_s=1e-4),
        install_signal_handlers=False)
    with inject_faults(
            {"optimize.streamed.step": fail_prob(1.0, seed=0)}):
        with pytest.raises(FaultInjected):
            sup.run((X, y), np.zeros(6, np.float32))


def test_supervisor_retry_only_wraps_lbfgs(rng):
    """LBFGS has no checkpoint path: the supervisor still gives it
    crash-retry from scratch (deterministic full-batch — a restart
    reproduces the same result)."""
    from tpu_sgd.optimize.lbfgs import LBFGS

    X, y = _build_data(rng, n=256, d=6)
    w0 = np.zeros(6, np.float32)
    w_ref, _ = LBFGS(max_num_iterations=6).optimize_with_history(
        (X, y), w0)
    crashed = [False]

    class CrashOnce(LBFGS):
        def optimize_with_history(self, data, w):
            if not crashed[0]:
                crashed[0] = True
                raise FaultInjected("boom")
            return super().optimize_with_history(data, w)

    sup = TrainingSupervisor(
        CrashOnce(max_num_iterations=6),
        retry=RetryPolicy(max_attempts=3, base_backoff_s=1e-4),
        install_signal_handlers=False)
    res = sup.run((X, y), w0)
    assert res.completed and res.attempts == 2
    np.testing.assert_array_equal(np.asarray(res.weights),
                                  np.asarray(w_ref))


def test_ingest_retry_option_heals_device_put_fault(rng):
    """set_ingest_options(retry=...) heals a transient transfer fault in
    place — same weights as the fault-free run, no supervisor needed."""
    X, y = _build_data(rng)
    w0 = np.zeros(8, np.float32)
    w_ref, h_ref = _streamed_opt().optimize_with_history((X, y), w0)
    opt = _streamed_opt().set_ingest_options(
        retry=RetryPolicy(max_attempts=3, base_backoff_s=1e-4))
    with inject_faults({"io.device_put": fail_nth(3)}):
        w, h = opt.optimize_with_history((X, y), w0)
    np.testing.assert_array_equal(np.asarray(w), np.asarray(w_ref))
    np.testing.assert_array_equal(h, h_ref)


def test_ingest_options_validates_retry():
    with pytest.raises(TypeError):
        _streamed_opt().set_ingest_options(retry="not a policy")
    opt = _streamed_opt().set_ingest_options(retry=RetryPolicy())
    assert opt.ingest_retry_policy is not None
    opt.set_ingest_options(retry=False)
    assert opt.ingest_retry_policy is None


# -- (h) health monitor -----------------------------------------------------

def test_health_monitor_emits_heartbeat_queue_and_straggler_events():
    sink = CollectingListener()
    mon = HealthMonitor(listener=sink, stall_after_s=0.01)
    hb = mon.watch_heartbeat(Heartbeat("worker"))
    mon.watch_queue("q", lambda: 7)
    assert mon.sample_once() == [
        ev for ev in sink.reliability]  # pre-beat: queue event only
    assert [e.kind for e in sink.reliability] == ["queue_depth"]
    assert sink.reliability[0].value == 7
    hb.beat()
    time.sleep(0.02)  # long enough to cross the stall threshold
    mon.sample_once()
    kinds = [e.kind for e in sink.reliability]
    assert "heartbeat" in kinds and "straggler" in kinds
    assert mon.straggler_count >= 1


def test_health_monitor_background_thread_lifecycle():
    sink = CollectingListener()
    with HealthMonitor(listener=sink, interval_s=0.01) as mon:
        mon.watch_queue("q", lambda: 1)
        time.sleep(0.06)
    n = len(sink.reliability)
    assert n >= 2  # sampled on the interval
    time.sleep(0.03)
    assert len(sink.reliability) == n  # stopped for real


# -- (i) the chaos soak (slow; excluded from tier-1) ------------------------

@pytest.mark.slow
def test_chaos_soak_seed0():
    from scripts.chaos_soak import soak

    summary = soak(seed=0, iters=40, verbose=False)
    assert summary["ok"]
    assert summary["served"] > 0
