"""Evaluation metrics tests.

Mirrors the reference's evaluation suites ([U] mllib/evaluation/*Suite) —
closed-form fixtures plus sklearn oracle cross-checks (SURVEY.md §4's
unit-tests-vs-closed-forms strategy).
"""

import numpy as np
import pytest

from tpu_sgd.evaluation import (BinaryClassificationMetrics,
                                MulticlassMetrics, RegressionMetrics)


class TestRegressionMetrics:
    def test_against_sklearn(self, rng):
        from sklearn import metrics as sk

        obs = rng.normal(size=(300,)).astype(np.float32)
        pred = obs + 0.3 * rng.normal(size=(300,)).astype(np.float32)
        m = RegressionMetrics(pred, obs)
        assert m.mean_squared_error == pytest.approx(
            sk.mean_squared_error(obs, pred), rel=1e-4
        )
        assert m.root_mean_squared_error == pytest.approx(
            np.sqrt(sk.mean_squared_error(obs, pred)), rel=1e-4
        )
        assert m.mean_absolute_error == pytest.approx(
            sk.mean_absolute_error(obs, pred), rel=1e-4
        )
        assert m.r2 == pytest.approx(sk.r2_score(obs, pred), rel=1e-3)

    def test_explained_variance_convention(self):
        # [U] RegressionMetrics.explainedVariance = sum((pred-mean(obs))^2)/n
        pred = np.array([1.0, 2.0, 3.0], np.float32)
        obs = np.array([1.0, 2.0, 9.0], np.float32)
        m = RegressionMetrics(pred, obs)
        expected = float(np.mean((pred - obs.mean()) ** 2))
        assert m.explained_variance == pytest.approx(expected, rel=1e-5)

    def test_perfect_fit(self):
        y = np.array([1.0, -2.0, 5.0], np.float32)
        m = RegressionMetrics(y, y)
        assert m.mean_squared_error == 0.0
        assert m.r2 == pytest.approx(1.0)

    def test_empty_rejected(self):
        with pytest.raises(ValueError):
            RegressionMetrics([], [])


class TestBinaryClassificationMetrics:
    def test_auc_against_sklearn(self, rng):
        from sklearn import metrics as sk

        labels = (rng.random(500) < 0.4).astype(np.float32)
        scores = (labels + rng.normal(scale=0.8, size=500)).astype(np.float32)
        m = BinaryClassificationMetrics(scores, labels)
        assert m.area_under_roc == pytest.approx(
            sk.roc_auc_score(labels, scores), abs=1e-4
        )

    def test_auc_with_ties(self):
        from sklearn import metrics as sk

        # Heavy ties: scores quantized to 3 levels — the group-tail collapse
        # must reproduce sklearn's tie handling exactly.
        rng = np.random.default_rng(7)
        labels = (rng.random(400) < 0.5).astype(np.float32)
        scores = np.round(labels * 0.6 + rng.random(400) * 0.4, 1).astype(
            np.float32
        )
        m = BinaryClassificationMetrics(scores, labels)
        assert m.area_under_roc == pytest.approx(
            sk.roc_auc_score(labels, scores), abs=1e-4
        )

    def test_curve_shapes_and_anchors(self):
        scores = np.array([0.9, 0.8, 0.7, 0.6, 0.5], np.float32)
        labels = np.array([1.0, 1.0, 0.0, 1.0, 0.0], np.float32)
        m = BinaryClassificationMetrics(scores, labels)
        roc = m.roc()
        assert tuple(roc[0]) == (0.0, 0.0)
        assert tuple(roc[-1]) == (1.0, 1.0)
        pr = m.pr()
        assert pr[0, 0] == 0.0
        assert pr[0, 1] == pr[1, 1]  # anchored at the first precision
        # 5 distinct thresholds
        assert m.thresholds().shape == (5,)
        # precision at threshold 0.9: top prediction is a true positive
        p = dict(map(tuple, m.precision_by_threshold()))
        assert p[np.float32(0.9)] == pytest.approx(1.0)
        r = dict(map(tuple, m.recall_by_threshold()))
        assert r[np.float32(0.5)] == pytest.approx(1.0)  # all pos recalled

    def test_perfect_separation(self):
        scores = np.array([0.9, 0.8, 0.2, 0.1], np.float32)
        labels = np.array([1.0, 1.0, 0.0, 0.0], np.float32)
        m = BinaryClassificationMetrics(scores, labels)
        assert m.area_under_roc == pytest.approx(1.0)
        assert m.area_under_pr == pytest.approx(1.0)

    def test_f1_matches_closed_form(self):
        scores = np.array([0.9, 0.8, 0.7, 0.6], np.float32)
        labels = np.array([1.0, 0.0, 1.0, 0.0], np.float32)
        m = BinaryClassificationMetrics(scores, labels)
        f = dict(map(tuple, m.f_measure_by_threshold()))
        # at threshold 0.7: tp=2, fp=1, fn=0 -> p=2/3, r=1 -> f1=0.8
        assert f[np.float32(0.7)] == pytest.approx(0.8)

    def test_num_bins_downsamples(self, rng):
        labels = (rng.random(1000) < 0.5).astype(np.float32)
        scores = rng.random(1000).astype(np.float32)
        full = BinaryClassificationMetrics(scores, labels)
        binned = BinaryClassificationMetrics(scores, labels, num_bins=20)
        assert binned.thresholds().size <= 21
        assert binned.thresholds().size < full.thresholds().size
        # binning must not change the AUCs (they integrate the full curve)
        assert binned.area_under_roc == pytest.approx(full.area_under_roc)

    def test_single_class_rejected(self):
        with pytest.raises(ValueError, match="both classes"):
            BinaryClassificationMetrics(
                np.array([0.5, 0.6], np.float32),
                np.array([1.0, 1.0], np.float32),
            )


class TestMulticlassMetrics:
    def test_confusion_and_aggregates(self):
        pred = np.array([0, 0, 1, 1, 2, 2, 2, 0], np.float64)
        obs = np.array([0, 1, 1, 1, 2, 2, 0, 0], np.float64)
        m = MulticlassMetrics(pred, obs)
        np.testing.assert_array_equal(
            m.confusion_matrix,
            [[2.0, 0.0, 1.0], [1.0, 2.0, 0.0], [0.0, 0.0, 2.0]],
        )
        assert m.accuracy == pytest.approx(6 / 8)
        assert m.precision(0) == pytest.approx(2 / 3)
        assert m.recall(0) == pytest.approx(2 / 3)
        assert m.precision(2) == pytest.approx(2 / 3)
        assert m.recall(2) == pytest.approx(1.0)
        assert m.f_measure(1) == pytest.approx(2 * (1.0 * 2 / 3) / (1.0 + 2 / 3))

    def test_weighted_against_sklearn(self, rng):
        from sklearn import metrics as sk

        obs = rng.integers(0, 4, size=200).astype(np.float64)
        pred = np.where(rng.random(200) < 0.7, obs,
                        rng.integers(0, 4, size=200)).astype(np.float64)
        m = MulticlassMetrics(pred, obs)
        assert m.accuracy == pytest.approx(sk.accuracy_score(obs, pred))
        assert m.weighted_precision == pytest.approx(
            sk.precision_score(obs, pred, average="weighted",
                               zero_division=0), abs=1e-6
        )
        assert m.weighted_recall == pytest.approx(
            sk.recall_score(obs, pred, average="weighted", zero_division=0),
            abs=1e-6,
        )
        assert m.weighted_f_measure() == pytest.approx(
            sk.f1_score(obs, pred, average="weighted", zero_division=0),
            abs=1e-6,
        )

    def test_explicit_num_classes(self):
        m = MulticlassMetrics([0.0, 1.0], [0.0, 1.0], num_classes=5)
        assert m.confusion_matrix.shape == (5, 5)
        assert m.recall(4) == 0.0  # absent class: 0, not NaN

    def test_out_of_range_rejected(self):
        # silent scatter-drop would deflate accuracy; must raise instead
        with pytest.raises(ValueError, match=r"\[0, 3\)"):
            MulticlassMetrics([0.0, 1.0, 2.0], [0.0, 3.0, 1.0],
                              num_classes=3)
        with pytest.raises(ValueError):
            MulticlassMetrics([-1.0, 1.0], [0.0, 1.0], num_classes=2)


class TestModelIntegration:
    def test_logistic_scores_feed_binary_metrics(self, rng):
        from tpu_sgd.models.classification import LogisticRegressionWithSGD

        n, d = 400, 5
        w = rng.normal(size=(d,)).astype(np.float32)
        X = rng.normal(size=(n, d)).astype(np.float32)
        y = (X @ w > 0).astype(np.float32)
        model = LogisticRegressionWithSGD.train((X, y), num_iterations=30)
        model.clear_threshold()
        scores = np.asarray(model.predict(X))
        m = BinaryClassificationMetrics(scores, y)
        assert m.area_under_roc > 0.95


def test_binary_metrics_rejects_plus_minus_one_labels():
    """LIBSVM's -1/+1 convention must raise clearly — each negative would
    otherwise count as 2 false positives and every curve silently skews."""
    with pytest.raises(ValueError, match="map -1/\\+1"):
        BinaryClassificationMetrics([0.9, 0.1, 0.8], [1.0, -1.0, 1.0])


def test_multiclass_metrics_rejects_fractional_classes():
    """astype(int32) would floor 0.7 and 1.2 into the wrong bins and
    report perfect accuracy for all-wrong predictions."""
    with pytest.raises(ValueError, match="integers"):
        MulticlassMetrics([0.7, 1.2], [0.2, 1.9], num_classes=2)
