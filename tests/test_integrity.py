"""End-to-end data-integrity plane (ISSUE 15).

Covers: the corrupting failpoint mode (deterministic payload mutation,
originals untouched), per-site corrupt-then-heal BITWISE fixtures at
every checksummed wire (dense chunks, sparse chunks, EF segments,
replica push payloads, delta-log records), poison admission at the
store (non-finite + norm-gate verdicts, reject-whole with EXACT EF mass
conservation at τ∈{0,2}), corrupt-state rollback through epoch fencing
(matched/bitwise replay), checkpoint content-checksum round-trip +
corrupt-restore quarantine, the integrity / heartbeat-stall detectors
(trip, no-trip, dedup/re-arm), the failpoint-coverage lint rule's
corruptpoint awareness, and the PR 8 zero-added-runtime pin re-asserted
with checksums ON.
"""

import threading
import time

import numpy as np
import pytest

from tpu_sgd.config import SGDConfig
from tpu_sgd.io.integrity import (IntegrityError, checksum_arrays, seal,
                                  set_integrity, verify)
from tpu_sgd.io.sparse_wire import ErrorFeedback
from tpu_sgd.ops.gradients import LeastSquaresGradient
from tpu_sgd.ops.updaters import SimpleUpdater, SquaredL2Updater
from tpu_sgd.optimize.gradient_descent import GradientDescent
from tpu_sgd.replica import ParameterStore, ReplicaDriver
from tpu_sgd.reliability import failpoints as fp
from tpu_sgd.reliability.retry import RetryPolicy
from tpu_sgd.utils.checkpoint import CheckpointManager


@pytest.fixture(autouse=True)
def _clean_global_state():
    """Every test leaves the failpoint registry disarmed and the
    integrity plane ON (its production default)."""
    yield
    fp.deactivate()
    set_integrity(True)


def _data(n=256, d=12, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, d)).astype(np.float32)
    w_true = rng.normal(size=d).astype(np.float32)
    y = (X @ w_true + 0.01 * rng.normal(size=n)).astype(np.float32)
    return X, y, np.zeros(d, np.float32)


def _objective(X, y, w, reg=0.1):
    r = X @ np.asarray(w) - y
    return float(0.5 * np.mean(r * r)
                 + 0.5 * reg * np.sum(np.asarray(w) ** 2))


# -- the checksum primitive ---------------------------------------------------


def test_checksum_covers_bytes_shape_and_dtype():
    a = np.arange(16, dtype=np.float32)
    base = checksum_arrays(a)
    flipped = a.copy()
    flipped[3] = np.float32(np.frombuffer(
        np.int32(np.frombuffer(flipped[3].tobytes(), np.int32)[0] ^ 1)
        .tobytes(), np.float32)[0])
    assert checksum_arrays(flipped) != base  # one flipped bit
    assert checksum_arrays(a[:15]) != base   # truncation
    assert checksum_arrays(a.astype(np.float64).astype(np.float32)) \
        == base                              # value-equal = digest-equal
    assert checksum_arrays(a.reshape(4, 4)) != base  # shape rides along
    assert checksum_arrays(a, None) != checksum_arrays(a)  # None leaf


def test_verify_raises_typed_and_seal_disables():
    a = np.arange(8, dtype=np.float32)
    ck = seal(a)
    verify("t.site", ck, a)  # clean passes
    with pytest.raises(IntegrityError) as ei:
        verify("t.site", ck, a + 1)
    assert ei.value.site == "t.site"
    assert ei.value.kind == "checksum"
    assert isinstance(ei.value, RuntimeError)  # retryable by default
    set_integrity(False)
    assert seal(a) is None
    verify("t.site", None, a + 1)  # unsealed frame: verify skips


# -- the corrupting failpoint mode --------------------------------------------


def test_corrupt_nth_mutates_copy_not_original():
    a = np.arange(32, dtype=np.float32)
    keep = a.copy()
    with fp.inject_faults({"t.wire": fp.corrupt_nth(1, kind="bitflip")}):
        (out,) = fp.corruptpoint("t.wire", (a,))
        assert not np.array_equal(out, keep)  # the copy is damaged
        np.testing.assert_array_equal(a, keep)  # the original is not
        (again,) = fp.corruptpoint("t.wire", (a,))
        np.testing.assert_array_equal(again, keep)  # one-shot: healed


@pytest.mark.parametrize("kind", ["bitflip", "nan", "truncate"])
def test_corrupt_kinds_all_fail_the_checksum(kind):
    a = np.linspace(-1, 1, 64, dtype=np.float32)
    b = np.arange(8, dtype=np.int32)
    ck = seal(a, b)
    with fp.inject_faults({"t.wire": fp.corrupt_nth(1, kind=kind)}):
        out = fp.corruptpoint("t.wire", (a, b))
    with pytest.raises(IntegrityError):
        verify("t.wire", ck, *out)
    if kind == "nan":
        assert not np.isfinite(np.asarray(out[0], np.float64)).all() \
            or not np.array_equal(out[1], b)


def test_corrupt_prob_is_seed_deterministic():
    a = np.arange(64, dtype=np.float32)

    def trail(seed):
        out = []
        with fp.inject_faults({"t.wire": fp.corrupt_prob(0.5, seed=seed)}):
            for _ in range(12):
                (o,) = fp.corruptpoint("t.wire", (a,))
                out.append(np.array_equal(o, a))
        return out

    assert trail(7) == trail(7)
    assert trail(7) != trail(8)


def test_corruptpoint_disabled_is_identity():
    payload = (np.arange(4), "tag", 3.5)
    assert fp.corruptpoint("t.wire", payload) is payload


# -- hook-site coverage (graftlint enforces both directions) ------------------


def test_corrupt_sites_registered_in_hook_sites():
    for site in ("io.chunk", "io.sparse_chunk", "io.segment",
                 "replica.push.wire", "replica.log.record"):
        assert site in fp.HOOK_SITES, site


def test_failpoint_coverage_rule_sees_corruptpoint_calls():
    from tpu_sgd.analysis.core import ModuleFile
    from tpu_sgd.analysis.rules_failpoint import FailpointCoverageRule

    wired = ModuleFile(
        "m.py", "m.py",
        "from tpu_sgd.reliability.failpoints import corruptpoint\n"
        "def f(p):\n"
        "    return corruptpoint('a.b', p)\n")
    bare = ModuleFile("m.py", "m.py", "def f(p):\n    return p\n")
    rogue = ModuleFile(
        "m.py", "m.py",
        "from tpu_sgd.reliability.failpoints import corruptpoint\n"
        "def f(p):\n"
        "    return corruptpoint('not.registered', p)\n")
    rule = FailpointCoverageRule(registry={"a.b": "m.py"})
    assert list(rule.run([wired], {})) == []
    missing = list(rule.run([bare], {}))
    assert len(missing) == 1 and "a.b" in missing[0].message
    extra = list(rule.run([rogue], {}))
    assert any("not.registered" in f.message for f in extra)


# -- per-site corrupt-then-heal BITWISE fixtures ------------------------------


def _streamed_opt(retry=None, superstep=1):
    o = (GradientDescent()
         .set_num_iterations(24).set_step_size(0.1)
         .set_mini_batch_fraction(0.5).set_sampling("sliced")
         .set_convergence_tol(0.0).set_seed(7)
         .set_host_streaming(True))
    if superstep > 1:
        o.set_superstep(superstep)
    if retry is not None:
        o.set_ingest_options(retry=retry)
    return o


def test_corrupt_chunk_heals_bitwise_streamed():
    """corrupt_prob armed at the dense chunk wire: every detected frame
    raises IntegrityError inside the prefetcher retry scope and the
    deterministic (seed, i) reassembly heals BITWISE."""
    X, y, w0 = _data()
    w_ref, h_ref = _streamed_opt().optimize_with_history((X, y), w0)
    opt = _streamed_opt(retry=RetryPolicy(max_attempts=6,
                                          base_backoff_s=0.001, seed=3))
    with fp.inject_faults({"io.chunk": fp.corrupt_prob(0.2, seed=11)}):
        w_c, h_c = opt.optimize_with_history((X, y), w0)
        assert fp.triggers("io.chunk") > 0
    np.testing.assert_array_equal(np.asarray(w_c), np.asarray(w_ref))
    np.testing.assert_array_equal(h_c, h_ref)


def test_corrupt_superchunk_heals_bitwise_fused():
    X, y, w0 = _data()
    w_ref, h_ref = _streamed_opt(superstep=4).optimize_with_history(
        (X, y), w0)
    opt = _streamed_opt(superstep=4,
                        retry=RetryPolicy(max_attempts=6,
                                          base_backoff_s=0.001, seed=4))
    with fp.inject_faults({"io.chunk": fp.corrupt_nth(2, kind="nan")}):
        w_c, h_c = opt.optimize_with_history((X, y), w0)
        assert fp.triggers("io.chunk") == 1
    np.testing.assert_array_equal(np.asarray(w_c), np.asarray(w_ref))
    np.testing.assert_array_equal(h_c, h_ref)


def test_corrupt_sparse_chunk_heals_bitwise():
    from tpu_sgd.ops.gradients import HingeGradient
    from tpu_sgd.ops.sparse import sparse_data

    Xs, ys, _ = sparse_data(256, 128, nnz_per_row=6, kind="svm", seed=0)
    w0 = np.zeros(Xs.shape[1], np.float32)

    def _opt(retry=None):
        o = (GradientDescent(gradient=HingeGradient())
             .set_num_iterations(12).set_step_size(0.2)
             .set_mini_batch_fraction(0.4).set_convergence_tol(0.0)
             .set_seed(7).set_host_streaming(True))
        if retry is not None:
            o.set_ingest_options(retry=retry)
        return o

    w_ref, h_ref = _opt().optimize_with_history((Xs, ys), w0)
    opt = _opt(retry=RetryPolicy(max_attempts=6, base_backoff_s=0.001,
                                 seed=5))
    with fp.inject_faults(
            {"io.sparse_chunk": fp.corrupt_prob(0.25, seed=12,
                                                kind="truncate")}):
        w_c, h_c = opt.optimize_with_history((Xs, ys), w0)
        assert fp.triggers("io.sparse_chunk") > 0
    np.testing.assert_array_equal(np.asarray(w_c), np.asarray(w_ref))
    np.testing.assert_array_equal(h_c, h_ref)


def test_corrupt_segment_detected_before_any_ef_mutation():
    """A corrupted top-k segment raises at the extraction boundary with
    the accumulator UNTOUCHED — the healing retry replays the whole
    compress and selects a bit-identical segment."""
    ef = ErrorFeedback(32, 0.25)
    twin = ErrorFeedback(32, 0.25)
    update = np.linspace(-2, 2, 32, dtype=np.float32)
    with fp.inject_faults({"io.segment": fp.corrupt_nth(1)}):
        with pytest.raises(IntegrityError):
            ef.compress(update.copy())
        np.testing.assert_array_equal(ef.acc, np.zeros(32, np.float32))
        idx, vals = ef.compress(update.copy())  # one-shot: healed
    idx_ref, vals_ref = twin.compress(update.copy())
    np.testing.assert_array_equal(idx, idx_ref)
    np.testing.assert_array_equal(vals, vals_ref)
    np.testing.assert_array_equal(ef.acc, twin.acc)


def _replica(tau=0, workers=2, iters=24, retry=None, standbys=0,
             compress=None):
    drv = (ReplicaDriver(LeastSquaresGradient(), SquaredL2Updater())
           .set_step_size(0.3).set_num_iterations(iters)
           .set_mini_batch_fraction(0.5).set_convergence_tol(0.0)
           .set_reg_param(0.1).set_workers(workers).set_staleness(tau))
    if retry is not None:
        drv.set_retry(retry)
    if standbys:
        drv.set_standbys(standbys)
    if compress is not None:
        drv.set_wire_compress(compress)
    return drv


def test_corrupt_push_wire_heals_bitwise_tau0():
    """A push payload damaged on the wire fails the store's
    consume-site verify; the worker's RetryPolicy re-sends the intact
    originals and the τ=0 trajectory is BITWISE the fault-free one."""
    X, y, w0 = _data()
    w_ref, h_ref = _replica().optimize_with_history((X, y), w0)
    drv = _replica(retry=RetryPolicy(max_attempts=6,
                                     base_backoff_s=0.001, seed=6))
    with fp.inject_faults(
            {"replica.push.wire": fp.corrupt_prob(0.1, seed=13)}):
        w_c, h_c = drv.optimize_with_history((X, y), w0)
        assert fp.triggers("replica.push.wire") > 0
    np.testing.assert_array_equal(np.asarray(w_c), np.asarray(w_ref))
    np.testing.assert_array_equal(h_c, h_ref)


def test_corrupt_compressed_push_heals_bitwise_and_conserves_ef():
    X, y, w0 = _data()
    ref = _replica(compress="topk:0.25")
    w_ref, h_ref = ref.optimize_with_history((X, y), w0)
    drv = _replica(compress="topk:0.25",
                   retry=RetryPolicy(max_attempts=6,
                                     base_backoff_s=0.001, seed=7))
    with fp.inject_faults(
            {"replica.push.wire": fp.corrupt_nth(3, kind="nan")}):
        w_c, h_c = drv.optimize_with_history((X, y), w0)
        assert fp.triggers("replica.push.wire") == 1
    # the retry re-sent the SAME extracted segment, so the healed run
    # is bitwise — corruption never touched the EF accumulator
    np.testing.assert_array_equal(np.asarray(w_c), np.asarray(w_ref))
    np.testing.assert_array_equal(h_c, h_ref)


def test_corrupt_log_record_heals_standby_bitwise():
    """A delta-log record damaged on the replication hop is detected at
    the standby's consume-site verify and re-read intact from the log —
    the standby stays BITWISE the primary at every version."""
    X, y, w0 = _data()
    drv = _replica(standbys=1)
    with fp.inject_faults(
            {"replica.log.record": fp.corrupt_nth(2, kind="bitflip")}):
        drv.optimize_with_history((X, y), w0)
        assert fp.triggers("replica.log.record") == 1
    sup = drv.last_supervisor
    assert sup.failover_count == 0  # healed in place, no promotion
    primary = sup.primary()
    standby = next(rep for rep in sup._standbys.values())
    assert standby.corrupt_healed >= 1
    assert standby.store.version == primary.version
    np.testing.assert_array_equal(np.asarray(standby.store.weights),
                                  np.asarray(primary.weights))


# -- poison admission ---------------------------------------------------------


def _store(tau=0, guard=10.0, iters=200):
    cfg = SGDConfig(num_iterations=iters, step_size=0.1,
                    mini_batch_fraction=1.0, reg_param=0.0,
                    convergence_tol=0.0)
    store = ParameterStore(SimpleUpdater(), cfg,
                           np.zeros(16, np.float32), staleness=tau,
                           poison_guard=guard)
    store.register_worker("w0", 0)
    return store


def test_non_finite_push_rejected_poisoned():
    store = _store()
    g = np.ones(16, np.float32)
    g[3] = np.nan
    res = store.push("w0", 0, g, np.float32(1.0), np.float32(4.0))
    assert res.poisoned and not res.accepted
    assert store.version == 0  # rejected WHOLE: the version line is clean
    res2 = store.push("w0", 0, np.ones(16, np.float32),
                      np.float32(1.0), np.float32(4.0))
    assert res2.accepted and not res2.poisoned
    snap = store.snapshot()
    assert snap["pushes_poisoned"] == 1
    assert snap["pushes_accepted"] == 1


def test_norm_gate_trips_after_warmup_and_guard_none_disables():
    store = _store(tau=1)
    for i in range(20):  # build the rolling-median baseline
        res = store.push("w0", store.version,
                         np.ones(16, np.float32), np.float32(0.5),
                         np.float32(4.0))
        assert res.accepted
    spike = np.full(16, 1e4, np.float32)
    res = store.push("w0", store.version, spike, np.float32(0.5),
                     np.float32(4.0))
    assert res.poisoned and not res.accepted
    # same spike through an unguarded store is admitted (the
    # configuration whose poison the rollback controller exists for)
    off = _store(tau=1, guard=None)
    for i in range(20):
        off.push("w0", off.version, np.ones(16, np.float32),
                 np.float32(0.5), np.float32(4.0))
    assert off.push("w0", off.version, spike, np.float32(0.5),
                    np.float32(4.0)).accepted


@pytest.mark.parametrize("tau", [0, 2])
def test_poisoned_compressed_push_conserves_ef_mass_exact(tau):
    """A poisoned compressed push is rejected WHOLE and the restored
    segment returns the extracted mass EXACTLY (bit-for-bit) — then the
    deterministic recompute selects the identical segment and is
    admitted."""
    store = _store(tau=tau)
    ef = store.error_feedback("w0", 0.25)
    update = (np.linspace(-3, 3, 16).astype(np.float32))
    idx, vals = ef.compress(update.copy())
    poisoned = vals.copy()
    poisoned[0] = np.inf  # the wire copy is damaged, ours is not
    res = store.push_compressed("w0", store.version, idx, poisoned,
                                1.0, 4.0)
    assert res.poisoned and not res.accepted
    assert store.version == 0
    ef.restore_segment(idx, vals)  # the worker's rejection path
    np.testing.assert_array_equal(ef.acc, update)  # EXACT conservation
    idx2, vals2 = ef.compress(np.zeros(16, np.float32))  # recompute
    np.testing.assert_array_equal(np.sort(idx2), np.sort(idx))
    res2 = store.push_compressed("w0", store.version, idx2, vals2,
                                 1.0, 4.0)
    assert res2.accepted
    assert store.snapshot()["pushes_poisoned"] == 1


def test_poison_guard_off_corruption_heals_via_guardless_objective():
    """Checksums OFF and the guard ON: NaN-corrupted push payloads are
    caught by the ADMISSION gate instead, the workers recompute, and
    the run still lands at the matched objective — the guard is the
    checksum's numerical backstop."""
    X, y, w0 = _data()
    set_integrity(False)  # unsealed wire: the checksum cannot catch it
    try:
        ref = _replica(tau=2, iters=48)
        w_ref, _ = ref.optimize_with_history((X, y), w0)
        drv = _replica(tau=2, iters=48)
        with fp.inject_faults(
                {"replica.push.wire": fp.corrupt_prob(
                    0.1, seed=21, kind="nan")}):
            w_p, _ = drv.optimize_with_history((X, y), w0)
            assert fp.triggers("replica.push.wire") > 0
    finally:
        set_integrity(True)
    snap = drv.last_store_snapshot
    assert snap["pushes_poisoned"] >= 1
    assert snap["version"] == 48
    assert np.isfinite(np.asarray(w_p)).all()
    assert _objective(X, y, w_p) <= _objective(X, y, w_ref) * 1.01


# -- corrupt-state rollback ---------------------------------------------------


def test_weight_corruption_rolls_back_through_epoch_fencing(tmp_path):
    """The forced weight-corruption cell: NaN planted in the live
    primary's weights mid-run.  The armed RollbackController fences the
    poisoned line (epoch bump — in-flight pushes come back fenced,
    never merged), cold-restores the last good checkpoint, and the τ=0
    replay lands BITWISE on the clean run's trajectory."""
    X, y, w0 = _data()
    iters = 60
    clean = CheckpointManager(str(tmp_path / "clean"), keep=4)
    ref = _replica(iters=iters)
    ref.set_checkpoint(clean, every=5)
    w_ref, h_ref = ref.optimize_with_history((X, y), w0)

    manager = CheckpointManager(str(tmp_path / "ckpt"), keep=4)
    drv = _replica(iters=iters)
    drv.set_checkpoint(manager, every=5).set_integrity_rollback(True)

    def corrupter():
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            sup = drv._live_supervisor
            if sup is not None:
                try:
                    if sup.primary().version >= 10:
                        drv.chaos_corrupt_weights()
                        return
                except Exception:
                    pass
            time.sleep(0.002)

    t = threading.Thread(target=corrupter, daemon=True)
    t.start()
    w_rb, h_rb = drv.optimize_with_history((X, y), w0)
    t.join(timeout=5)
    snap = drv.last_failover_snapshot
    assert snap is not None and snap["failovers"] >= 1
    assert any(r["cold_recovery"] for r in snap["records"])
    assert drv.last_store_snapshot["epoch"] >= 1
    assert np.isfinite(np.asarray(w_rb)).all()
    # failover to your own past IS a replay: τ=0 recomputes the lost
    # versions from (seed, version) and the trajectory is bitwise
    np.testing.assert_array_equal(np.asarray(w_rb), np.asarray(w_ref))
    np.testing.assert_array_equal(h_rb, h_ref)


def test_manual_rollback_handle_requires_live_ha_run():
    drv = _replica()
    assert drv.rollback() is False
    assert drv.chaos_corrupt_weights() is False


def test_rollback_rebuilds_standby_redundancy(tmp_path):
    """One rollback must not permanently strip a set_standbys(n) fleet
    of replication: the poisoned standbys are gone (they replayed the
    poison), but fresh ones resume from the restored line and the HA
    invariant survives."""
    X, y, w0 = _data()
    manager = CheckpointManager(str(tmp_path), keep=4)
    drv = _replica(iters=60, standbys=1)
    drv.set_checkpoint(manager, every=5).set_integrity_rollback(True)

    def corrupter():
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            sup = drv._live_supervisor
            if sup is not None:
                try:
                    if sup.primary().version >= 10:
                        drv.chaos_corrupt_weights()
                        return
                except Exception:
                    pass
            time.sleep(0.002)

    t = threading.Thread(target=corrupter, daemon=True)
    t.start()
    w_rb, _ = drv.optimize_with_history((X, y), w0)
    t.join(timeout=5)
    sup = drv.last_supervisor
    assert drv.last_failover_snapshot["failovers"] >= 1
    assert np.isfinite(np.asarray(w_rb)).all()
    live = [rep for rep in sup._standbys.values()
            if not (rep.store.failed or rep.store.fenced)]
    assert live, "rollback left the fleet with zero standbys"
    # the rebuilt standby chained onto the restored line: stop()
    # drained it to the log head, so it ends bitwise at the primary
    assert live[0].store.version == sup.primary().version
    np.testing.assert_array_equal(
        np.asarray(live[0].store.weights),
        np.asarray(sup.primary().weights))


def test_poison_livelock_fails_loudly_without_rollback(monkeypatch):
    """Poison that CANNOT heal (weights corrupted, rollback unarmed):
    the deterministic recompute reproduces the bad payload forever, so
    the worker must give up with a typed IntegrityError after its
    streak limit instead of silently livelocking the fleet."""
    from tpu_sgd.replica.worker import ReplicaWorker

    monkeypatch.setattr(ReplicaWorker, "POISON_STREAK_LIMIT", 8)
    X, y, w0 = _data()
    drv = _replica(workers=1, iters=500, standbys=1)
    drv.set_rejoin(RetryPolicy(max_attempts=2, base_backoff_s=0.001,
                               seed=3))

    def corrupter():
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            sup = drv._live_supervisor
            if sup is not None:
                try:
                    if sup.primary().version >= 5:
                        drv.chaos_corrupt_weights()
                        return
                except Exception:
                    pass
            time.sleep(0.002)

    t = threading.Thread(target=corrupter, daemon=True)
    t.start()
    with pytest.raises(IntegrityError) as ei:
        drv.optimize_with_history((X, y), w0)
    t.join(timeout=5)
    assert ei.value.kind == "poison"
    assert drv.last_store_snapshot["pushes_poisoned"] >= 8


# -- checkpoint content checksum ----------------------------------------------


def test_checkpoint_checksum_roundtrip(tmp_path):
    m = CheckpointManager(str(tmp_path), keep=3)
    w = np.arange(8, dtype=np.float32)
    m.save(5, w, 0.25, np.asarray([1.0, 0.5]), "cfg",
           extras={"ef": np.ones(4, np.float32)})
    state = m.restore()
    assert state["iteration"] == 5
    np.testing.assert_array_equal(state["weights"], w)
    np.testing.assert_array_equal(state["extras"]["ef"],
                                  np.ones(4, np.float32))


def test_checkpoint_disabled_integrity_omits_checksum(tmp_path):
    set_integrity(False)
    try:
        m = CheckpointManager(str(tmp_path), keep=3)
        path = m.save(1, np.ones(4, np.float32), 0.0,
                      np.asarray([1.0]), "")
        with np.load(path) as z:
            assert "checksum" not in z.files
    finally:
        set_integrity(True)
    assert m.restore()["iteration"] == 1  # legacy files keep loading


def test_corrupt_checkpoint_quarantined_and_falls_back(tmp_path):
    quarantined = []
    m = CheckpointManager(str(tmp_path), keep=3,
                          on_corruption=lambda p, q, e: quarantined
                          .append((q or p, e)))
    m.save(5, np.full(8, 5.0, np.float32), 0.0, np.asarray([1.0]), "")
    path10 = m.save(10, np.full(8, 10.0, np.float32), 0.0,
                    np.asarray([1.0, 0.5]), "")
    # silently damage the newest file's weights WITHOUT re-sealing —
    # exactly what a bit rotting at rest looks like to the reader
    with np.load(path10) as z:
        entries = {k: np.array(z[k]) for k in z.files}
    entries["weights"][0] = 999.0
    with open(path10, "wb") as f:
        np.savez(f, **entries)

    with pytest.raises(IntegrityError):
        m.restore_version(10)  # explicit request: raises, never swaps

    state = m.restore()  # latest-default: quarantine + fall back
    assert state["iteration"] == 5
    assert len(quarantined) == 1
    assert isinstance(quarantined[0][1], IntegrityError)
    assert m.versions() == [5]  # the bad file left the namespace


# -- detectors ----------------------------------------------------------------


def _window(index, series):
    return {"index": index, "t_start": float(index),
            "t_end": float(index + 1),
            "series": {k: ({"count": v, "mean": 0.0, "max": None,
                            "bytes": 0} if isinstance(v, int) else v)
                       for k, v in series.items()}}


def test_integrity_detector_trip_no_trip_and_rearm():
    from tpu_sgd.obs.detect import DetectorEngine, IntegrityDetector

    alerts = []
    eng = DetectorEngine(detectors=[IntegrityDetector()],
                         on_alert=alerts.append)
    eng.on_window_close(_window(0, {"train.loss": 4}))  # clean: no trip
    assert alerts == []
    eng.on_window_close(_window(1, {"integrity.corrupt.io.chunk": 2}))
    assert len(alerts) == 1
    assert alerts[0].rule == "integrity"
    assert alerts[0].series == "integrity.corrupt.io.chunk"
    assert alerts[0].value == 2.0
    # stays-tripped = ONE incident
    eng.on_window_close(_window(2, {"integrity.corrupt.io.chunk": 1}))
    assert len(alerts) == 1
    # a clean window re-arms; the next corrupt frame is a new incident
    eng.on_window_close(_window(3, {}))
    eng.on_window_close(_window(4, {"integrity.corrupt.io.chunk": 1}))
    assert len(alerts) == 2


def test_heartbeat_stall_detector_membership_and_fleet_silence():
    from tpu_sgd.obs.detect import DetectorEngine, HeartbeatStallDetector

    alerts = []
    eng = DetectorEngine(
        detectors=[HeartbeatStallDetector(stall_windows=2)],
        on_alert=alerts.append)
    watch = {"reliability.hb.watch[feed]": 1,
             "reliability.hb.watch[batcher]": 1}
    both = {**watch, "reliability.heartbeat[feed]": 3,
            "reliability.heartbeat[batcher]": 2}
    eng.on_window_close(_window(0, both))
    assert alerts == []
    # batcher goes silent while feed beats: trips after stall_windows
    one = {"reliability.heartbeat[feed]": 3}
    eng.on_window_close(_window(1, one))
    assert alerts == []  # 1 silent window < 2
    eng.on_window_close(_window(2, one))
    assert len(alerts) == 1
    assert "batcher" in alerts[0].series
    # fleet-wide silence (idle/finished process) never trips
    alerts.clear()
    eng2 = DetectorEngine(
        detectors=[HeartbeatStallDetector(stall_windows=2)],
        on_alert=alerts.append)
    eng2.on_window_close(_window(0, both))
    for i in range(1, 6):
        eng2.on_window_close(_window(i, {}))
    assert alerts == []
    # a retired (unwatched) component cannot trip
    eng3 = DetectorEngine(
        detectors=[HeartbeatStallDetector(stall_windows=2)],
        on_alert=alerts.append)
    eng3.on_window_close(_window(0, both))
    eng3.on_window_close(
        _window(1, {"reliability.hb.unwatch[batcher]": 1,
                    "reliability.heartbeat[feed]": 1}))
    for i in range(2, 6):
        eng3.on_window_close(
            _window(i, {"reliability.heartbeat[feed]": 1}))
    assert alerts == []


def test_unwatched_heartbeat_never_joins_roster():
    from tpu_sgd.obs.detect import DetectorEngine, HeartbeatStallDetector

    alerts = []
    eng = DetectorEngine(
        detectors=[HeartbeatStallDetector(stall_windows=1)],
        on_alert=alerts.append)
    # beats with NO watch declaration: an idle batcher is silent and
    # healthy — only declared-should-beat components are candidates
    eng.on_window_close(
        _window(0, {"reliability.heartbeat[feed]": 2,
                    "reliability.heartbeat[idle]": 1}))
    for i in range(1, 5):
        eng.on_window_close(
            _window(i, {"reliability.heartbeat[feed]": 2}))
    assert alerts == []


def test_health_monitor_watch_emits_roster_series():
    from tpu_sgd import obs
    from tpu_sgd.reliability.health import Heartbeat, HealthMonitor

    class _Sink:
        def emit(self, kind, payload):
            pass

    obs.enable(_Sink(), window_s=60.0)
    try:
        mon = HealthMonitor()
        hb = Heartbeat("test-feed")
        mon.watch_heartbeat(hb)
        hb.beat()
        mon.unwatch_heartbeat("test-feed")
        snap = obs.windows_snapshot()
    finally:
        obs.disable()
    series = {name for w in snap for name in w["series"]}
    assert "reliability.hb.watch[test-feed]" in series
    assert "reliability.heartbeat[test-feed]" in series
    assert "reliability.hb.unwatch[test-feed]" in series


# -- the zero-added-runtime pin (PR 8 discipline, checksums ON) ---------------


def test_integrity_zero_added_runtime_events():
    """Checksums are pure host work: the warmed fused driver runs with
    the SAME dispatch/compile/host-sync counts whether the integrity
    plane is on (the default this whole suite runs under) or off."""
    from tpu_sgd.analysis.runtime import count_dispatches, count_host_syncs

    X, y, w0 = _data()
    opt = _streamed_opt(superstep=4)
    opt.optimize_with_history((X, y), w0)  # warm every program
    with count_host_syncs() as s_on, count_dispatches() as d_on:
        opt.optimize_with_history((X, y), w0)
    set_integrity(False)
    try:
        with count_host_syncs() as s_off, count_dispatches() as d_off:
            opt.optimize_with_history((X, y), w0)
    finally:
        set_integrity(True)
    assert d_on["n"] == d_off["n"]
    assert s_on["n"] == s_off["n"]
