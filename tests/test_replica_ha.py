"""Highly-available parameter store: replicated delta log,
deterministic failover, partition-tolerant workers
(``tpu_sgd/replica/ha.py``).

The load-bearing pins:

* a standby replaying the delta log is BITWISE the primary at every
  version (loss AND weight-delta per applied version, via listeners on
  both stores);
* τ=0 with the primary killed mid-round is BITWISE the fault-free run
  after failover — failover is a replay, not a restart (ADVICE.md);
* epoch fencing: a stale-epoch push comes back ``fenced`` (never
  merged), a resurrected primary's delta records are refused at the
  log, and a fenced old primary's late checkpoint save never shadows
  the promoted store's state (the ``(epoch, version)`` restore order);
* a worker partitioned through a full failover rejoins the contract
  with ZERO lost error-feedback mass;
* double failure (primary and every standby) falls back to checkpoint
  cold recovery with a loud warning — and at τ=0 is STILL bitwise,
  because the lost versions recompute from ``(seed, version)``;
* preemption during an in-flight failover waits for promotion to
  settle, so ``TrainingPreempted`` unwinds from a consistent
  ``(epoch, version)`` (the PR's recorded bugfix), and a stopped store
  never applies a partial τ=0 round (the preempt-poison regression).
"""

import logging
import os
import threading
import time

import numpy as np
import pytest

from tpu_sgd.config import SGDConfig
from tpu_sgd.ops.gradients import LeastSquaresGradient
from tpu_sgd.ops.updaters import SquaredL2Updater
from tpu_sgd.replica import (ParameterStore, ReplicaDriver, ReplicaWorker,
                             StoreFailed, StoreFenced, StoreSupervisor,
                             StoreUnreachable, shard_rows)
from tpu_sgd.replica.ha import DeltaRecord
from tpu_sgd.reliability import failpoints as fp
from tpu_sgd.reliability.retry import RetryPolicy
from tpu_sgd.utils.checkpoint import CheckpointManager
from tpu_sgd.utils.events import CollectingListener


def _data(n=256, d=12, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, d)).astype(np.float32)
    w_true = rng.normal(size=d).astype(np.float32)
    y = (X @ w_true + 0.01 * rng.normal(size=n)).astype(np.float32)
    return X, y, np.zeros(d, np.float32)


def _driver(*, iters=24, frac=0.5, step=0.3, reg=0.1, workers=4, tau=0,
            standbys=0):
    drv = (ReplicaDriver(LeastSquaresGradient(), SquaredL2Updater())
           .set_step_size(step).set_num_iterations(iters)
           .set_mini_batch_fraction(frac).set_convergence_tol(0.0)
           .set_reg_param(reg).set_workers(workers).set_staleness(tau))
    if standbys:
        drv.set_standbys(standbys)
    return drv


def _full_objective(X, y, w, reg):
    r = X @ np.asarray(w) - y
    return float(0.5 * np.mean(r * r)
                 + 0.5 * reg * np.sum(np.asarray(w) ** 2))


def _cfg(**kw):
    base = dict(step_size=0.2, num_iterations=40,
                mini_batch_fraction=1.0, convergence_tol=0.0,
                reg_param=0.01)
    base.update(kw)
    return SGDConfig(**base)


def _store_pair(cfg, w0, *, tau=0, shared_ef=None, primary_listener=None,
                standby_listener=None, **sup_kw):
    """A primary + one standby under a supervisor (the direct, no-driver
    composition unit tests drive)."""
    ef = shared_ef if shared_ef is not None else {}
    primary = ParameterStore(SquaredL2Updater(), cfg, w0, staleness=tau,
                             listener=primary_listener, ef_registry=ef,
                             name="s0")
    standby = ParameterStore(SquaredL2Updater(), cfg, w0, staleness=tau,
                             listener=standby_listener, ef_registry=ef,
                             name="s1")
    sup = StoreSupervisor([primary, standby], **sup_kw)
    return primary, standby, sup


class _ListSink:
    def __init__(self):
        self.records = []

    def emit(self, kind, payload):
        self.records.append((kind, dict(payload)))


# -- standby bitwise ----------------------------------------------------------


@pytest.mark.parametrize("tau", [0, 2])
def test_standby_bitwise_at_every_version(tau):
    """The delta log replays, it does not approximate: the standby's
    per-version loss and weight-delta (listener events) and its final
    weights are bitwise the primary's."""
    X, y, w0 = _data(n=128, d=8, seed=3)
    cfg = _cfg(num_iterations=20, mini_batch_fraction=0.5, step_size=0.3)
    p_lis, s_lis = CollectingListener(), CollectingListener()
    primary, standby, sup = _store_pair(
        cfg, w0, tau=tau, primary_listener=p_lis,
        standby_listener=s_lis)
    client = sup.client()
    shards = shard_rows(X, y, 2)
    workers = [ReplicaWorker(f"w{s}", s, client, LeastSquaresGradient(),
                             cfg, *shards[s]) for s in range(2)]
    for s in range(2):
        client.register_worker(f"w{s}", s)
    threads = [threading.Thread(target=w.run) for w in workers]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    sup.stop()  # drains the standby to the log head
    np.testing.assert_array_equal(standby.loss_history(),
                                  primary.loss_history())
    np.testing.assert_array_equal(np.asarray(standby.weights),
                                  np.asarray(primary.weights))
    assert len(p_lis.iterations) == len(s_lis.iterations) == 20
    for pe, se in zip(p_lis.iterations, s_lis.iterations):
        assert (pe.iteration, pe.loss, pe.weight_delta_norm) == (
            se.iteration, se.loss, se.weight_delta_norm)


def test_ha_fault_free_bitwise_vs_single_store():
    """Replication is pure observation: a fault-free HA run is bitwise
    the single-store run (weights AND loss history)."""
    X, y, w0 = _data()
    w_ref, h_ref = _driver(tau=0).optimize_with_history((X, y), w0)
    drv = _driver(tau=0, standbys=1)
    w_ha, h_ha = drv.optimize_with_history((X, y), w0)
    np.testing.assert_array_equal(np.asarray(w_ha), np.asarray(w_ref))
    np.testing.assert_array_equal(h_ha, h_ref)
    assert drv.last_failover_snapshot["failovers"] == 0
    # and the standby store ended bitwise too (drained at stop)
    standby = drv.last_supervisor._stores[1]
    np.testing.assert_array_equal(standby.loss_history(), h_ref)


# -- kill the primary mid-round ----------------------------------------------


def test_tau0_kill_primary_mid_round_bitwise():
    """THE acceptance pin: τ=0 with the primary store killed mid-round
    is BITWISE the fault-free run after failover — the promoted standby
    replays the log gap and the workers' re-routed (fenced → re-pull →
    recompute) rounds are deterministic in (seed, version)."""
    X, y, w0 = _data()
    w_ref, h_ref = _driver(tau=0).optimize_with_history((X, y), w0)
    drv = _driver(tau=0, standbys=1)
    # ~8 store accesses per version (4 pulls + 4 pushes): hit 100 lands
    # the kill mid-run
    with fp.inject_faults({"replica.store_fail":
                           fp.fail_nth(100, exc=StoreFailed)}):
        w_k, h_k = drv.optimize_with_history((X, y), w0)
    snap = drv.last_failover_snapshot
    assert snap["failovers"] == 1, snap
    rec = snap["records"][0]
    assert rec["old_primary"] == "s0" and rec["new_primary"] == "s1"
    assert rec["epoch"] == 1 and not rec["cold_recovery"]
    assert rec["gap_replayed"] >= 0
    np.testing.assert_array_equal(np.asarray(w_k), np.asarray(w_ref))
    np.testing.assert_array_equal(h_k, h_ref)
    # the membership log carries the failover next to join/leave
    store_snap = drv.last_store_snapshot
    assert store_snap["epoch"] == 1
    assert store_snap["version"] == 24


def test_tau2_kill_primary_mid_round_converges():
    X, y, w0 = _data(n=512, d=10, seed=11)
    iters = 160
    ref = _driver(tau=0, iters=iters, frac=1.0, step=0.2, reg=0.01)
    w_ref, _ = ref.optimize_with_history((X, y), w0)
    ref_obj = _full_objective(X, y, w_ref, 0.01)
    drv = _driver(tau=2, iters=iters, frac=1.0, step=0.2, reg=0.01,
                  standbys=1)
    with fp.inject_faults({"replica.store_fail":
                           fp.fail_nth(400, exc=StoreFailed)}):
        w_k, h_k = drv.optimize_with_history((X, y), w0)
    assert drv.last_failover_snapshot["failovers"] == 1
    assert len(h_k) == iters
    assert drv.last_store_snapshot["max_accepted_staleness"] <= 2
    obj = _full_objective(X, y, w_k, 0.01)
    assert obj <= ref_obj * 1.01, (
        f"kill-primary objective {obj} vs sync {ref_obj}")


# -- epoch fencing ------------------------------------------------------------


def test_fenced_epoch_push_rejected_and_old_store_refuses():
    """A push whose basis belongs to the superseded epoch is FENCED
    (never discounted into the promoted line); the fenced old store
    refuses the whole protocol with the typed re-route error."""
    import jax.numpy as jnp

    _, _, w0 = _data(n=32, d=8)
    cfg = _cfg(num_iterations=50)
    primary, standby, sup = _store_pair(cfg, w0, tau=2)
    client = sup.client()
    client.register_worker("w0", 0)
    pulled = client.pull("w0")
    assert pulled.epoch == 0
    g = jnp.asarray(np.ones(8, np.float32))
    assert client.push("w0", pulled.version, g, jnp.asarray(1.0),
                       jnp.asarray(8.0), basis_epoch=pulled.epoch).accepted
    assert sup.kill_primary()
    assert sup.epoch == 1 and sup.primary() is standby
    # the old basis is fenced on the promoted store...
    res = standby.push("w0", pulled.version,
                       jnp.asarray(np.ones(8, np.float32)),
                       jnp.asarray(1.0), jnp.asarray(8.0), basis_epoch=0)
    assert res.fenced and not res.accepted
    assert standby.snapshot()["pushes_fenced"] == 1
    # ...the fenced old store refuses pulls and pushes outright...
    with pytest.raises(StoreFenced):
        primary.pull("w0")
    with pytest.raises(StoreFenced):
        primary.push("w0", 0, g, jnp.asarray(1.0), jnp.asarray(8.0))
    # ...and the CLIENT hides all of it: a fresh pull carries epoch 1
    pulled2 = client.pull("w0")
    assert pulled2.epoch == 1
    assert client.push("w0", pulled2.version,
                       jnp.asarray(np.ones(8, np.float32)),
                       jnp.asarray(1.0), jnp.asarray(8.0),
                       basis_epoch=pulled2.epoch).accepted


def test_resurrected_primary_delta_records_refused_by_log():
    """A fenced old primary that comes back and keeps applying is
    rejected BY EPOCH at the delta log — its stale applies are refused
    at the serialization point, never silently merged."""
    _, _, w0 = _data(n=32, d=8)
    primary, standby, sup = _store_pair(_cfg(), w0, tau=2)
    sup.kill_primary()
    log = sup._log
    assert log.epoch == 1
    stale = DeltaRecord(epoch=0, version=standby.version + 1,
                        kind="sums",
                        payloads=(("sums", np.zeros(8, np.float32),
                                   np.zeros((), np.float32),
                                   np.ones((), np.float32)),))
    with pytest.raises(StoreFenced):
        log.append(stale)
    # a fenced store also refuses direct replica-record application
    with pytest.raises(StoreFenced):
        primary.apply_replica_record(stale)


def test_failover_lock_discipline_and_order_validated_at_runtime():
    """A kill-primary failover under FULL lock instrumentation
    (supervisor + both stores + delta log + client, one shared
    recorder): no unguarded access, no Eraser race, and the observed
    acquisition order — including the ``set_replication(log.append)``
    callback edge the static lock-order graph cannot resolve — replays
    clean against the committed GRAFTLINT_LOCK_ORDER."""
    import jax.numpy as jnp

    from tpu_sgd.analysis.runtime import (LocksetRecorder, assert_lock_order,
                                          instrument_object)
    from tpu_sgd.replica import ha as ha_mod
    from tpu_sgd.replica import store as store_mod

    _, _, w0 = _data(n=32, d=8)
    primary, standby, sup = _store_pair(_cfg(num_iterations=200), w0, tau=2)
    # quiesce the standby applier while the locks are swapped for
    # instrumented twins — it polls DeltaLog.since() from its own
    # thread, and a swap mid-wait would look like an unguarded read
    sup._standbys[1].halt()
    rec = LocksetRecorder()
    instrument_object(sup._log, ha_mod.GRAFTLINT_LOCKS["DeltaLog"], rec)
    for st in (primary, standby):
        instrument_object(
            st, store_mod.GRAFTLINT_LOCKS["ParameterStore"], rec,
            owner="ParameterStore")
    sup._standbys[1].start()
    # instrument the supervisor LAST: the restart above reads
    # sup._standbys from the test thread, which is outside the lock
    instrument_object(sup, ha_mod.GRAFTLINT_LOCKS["StoreSupervisor"], rec)
    client = sup.client()
    instrument_object(client, ha_mod.GRAFTLINT_LOCKS["StoreClient"], rec)
    client.register_worker("w0", 0)
    client.register_worker("w1", 1)

    ok = [0, 0]

    def pusher(i):
        for _ in range(30):
            try:
                pulled = client.pull(f"w{i}")
                res = client.push(
                    f"w{i}", pulled.version,
                    jnp.asarray(np.ones(8, np.float32)),
                    jnp.asarray(1.0), jnp.asarray(8.0),
                    basis_epoch=pulled.epoch)
                ok[i] += bool(res.accepted)
            except Exception:
                pass  # transient mid-promotion refusals are protocol
            time.sleep(0.001)

    threads = [threading.Thread(target=pusher, args=(i,), name=f"push{i}")
               for i in range(2)]
    for t in threads:
        t.start()
    time.sleep(0.02)
    assert sup.kill_primary()  # the failover, mid-traffic
    for t in threads:
        t.join(timeout=60)
    assert sup.epoch == 1
    assert sum(ok) > 0  # traffic really flowed across the promotion
    assert rec.checked_accesses > 0
    assert rec.violations == []
    assert rec.races() == []
    # the statically-invisible callback edge WAS observed and is legal
    assert ("ParameterStore._cond", "DeltaLog._cond") in rec.order_pairs
    assert_lock_order(rec)


def test_fenced_old_primary_late_save_never_shadows(tmp_path):
    """The satellite-1 pin: restore() prefers the highest
    ``(epoch, version)`` — a fenced old primary's LATE save with a
    higher iteration number never shadows the promoted store's
    lower-numbered, newer-epoch state."""
    mgr = CheckpointManager(os.fspath(tmp_path), keep=8)
    w_old = np.full(4, 7.0, np.float32)
    w_new = np.full(4, 9.0, np.float32)
    mgr.save(38, w_new, 0.0, np.zeros(38), "ck", epoch=1)
    # the fenced old primary's late save: higher iteration, older epoch
    mgr.save(40, w_old, 0.0, np.zeros(40), "ck", epoch=0)
    state = mgr.restore()
    assert state["iteration"] == 38 and state["epoch"] == 1
    np.testing.assert_array_equal(state["weights"], w_new)
    # the same iteration saved in both epochs: the promoted copy wins
    mgr.save(40, w_new, 0.0, np.zeros(40), "ck", epoch=1)
    assert mgr.restore()["epoch"] == 1
    st = mgr.restore_version(40)
    assert st["epoch"] == 1
    np.testing.assert_array_equal(st["weights"], w_new)
    # versions() dedupes across epochs, (epoch, iteration) order
    assert mgr.versions() == [40, 38]
    assert mgr.latest_version() == 40


def test_checkpoint_epoch_roundtrips_and_prunes_oldest_epoch(tmp_path):
    mgr = CheckpointManager(os.fspath(tmp_path), keep=2)
    for it in (10, 20):
        mgr.save(it, np.zeros(3), 0.0, np.zeros(it), "ck")  # epoch 0
    mgr.save(15, np.ones(3), 0.0, np.zeros(15), "ck", epoch=2)
    # keep=2: the oldest (epoch, iteration) — epoch-0 iteration 10 —
    # is pruned; the epoch-2 save is newest despite its lower iteration
    assert mgr.versions() == [20, 15]
    assert mgr.restore()["epoch"] == 2
    assert mgr.restore()["iteration"] == 15
    # an epoch-0 file parsed back reports epoch 0 (legacy readers)
    assert mgr.restore_version(20)["epoch"] == 0


# -- partition tolerance ------------------------------------------------------


def test_partitioned_push_conserves_ef_mass_and_rejoins_after_failover():
    """The zero-lost-gradient-mass pin, end to end: a compressed push
    that cannot reach any store restores its extracted top-k segment
    into the error-feedback accumulator; after a failover the SAME
    accumulator (the registry is shared by the whole store group) is
    live on the promoted primary and the carried mass ships."""
    X, y, w0 = _data(n=64, d=16, seed=5)
    cfg = _cfg(num_iterations=50, step_size=0.1)
    shared_ef = {}
    primary, standby, sup = _store_pair(cfg, w0, tau=2,
                                        shared_ef=shared_ef)
    client = sup.client()
    client.register_worker("w0", 0)
    shards = shard_rows(X, y, 1)
    worker = ReplicaWorker("w0", 0, client, LeastSquaresGradient(), cfg,
                           *shards[0], wire_frac=0.25)
    assert worker.run_once()  # one clean cycle: EF live and registered
    acc_before = worker.ef.acc.copy()
    # the failpoint kills the PUSH (access 2 of the cycle), after the
    # pull and the EF fold/extract — exactly the partition moment that
    # would leak mass if the worker did not restore the segment
    with fp.inject_faults({"replica.store_fail": fp.fail_nth(2)}):
        with pytest.raises(fp.FaultInjected):
            worker.run_once()
    # the accumulator holds the WHOLE folded update: recompute what the
    # cycle folded in and check nothing leaked
    import jax.numpy as jnp

    pulled = client.pull("w0")
    g, l, c = worker._local_sums(pulled.weights, worker._X, worker._y,
                                 jnp.asarray(pulled.version + 1,
                                             jnp.int32))
    gn = np.asarray(g).reshape(-1) / max(float(c), 1.0)
    np.testing.assert_allclose(worker.ef.acc, acc_before + gn,
                               rtol=1e-5, atol=1e-7)
    # a full partition raises the typed unreachable error (heals under
    # the worker RetryPolicy; here it just propagates)
    client.partition("w0")
    with pytest.raises(StoreUnreachable):
        worker.run_once()
    client.heal("w0")
    # failover: the promoted primary hands back the SAME accumulator
    assert sup.kill_primary()
    assert sup.primary() is standby
    assert sup.primary().error_feedback("w0", 0.25) is worker.ef
    v_before = standby.version
    assert worker.run_once()  # fenced re-pull happens inside: push lands
    assert standby.version == v_before + 1
    assert worker.fenced == 0  # pull already carried the new epoch


def test_partition_through_full_failover_driver():
    """Driver-level: one worker partitioned across a primary kill (τ=2,
    compressed wire) retries under its RetryPolicy, rejoins the
    contract after the heal, and the run completes every version with
    a matched objective — a partition is just a longer rejection."""
    X, y, w0 = _data(n=512, d=10, seed=11)
    ref = _driver(tau=0, iters=160, frac=1.0, step=0.2, reg=0.01)
    w_ref, _ = ref.optimize_with_history((X, y), w0)
    ref_obj = _full_objective(X, y, w_ref, 0.01)
    iters = 320
    drv = (_driver(tau=2, iters=iters, frac=1.0, step=0.2, reg=0.01,
                   standbys=1)
           .set_wire_compress("topk:0.25")
           .set_retry(RetryPolicy(max_attempts=400, base_backoff_s=0.01,
                                  max_backoff_s=0.05, seed=3)))
    timers = [threading.Timer(0.25, drv.partition_worker, ("w1",)),
              threading.Timer(0.5, drv.kill_primary),
              threading.Timer(1.2, drv.heal_worker, ("w1",))]
    for t in timers:
        t.start()
    try:
        w_p, h_p = drv.optimize_with_history((X, y), w0)
    finally:
        for t in timers:
            t.cancel()
    snap = drv.last_store_snapshot
    assert drv.last_failover_snapshot["failovers"] == 1
    assert snap["version"] == iters and len(h_p) == iters
    assert snap["max_accepted_staleness"] <= 2
    obj = _full_objective(X, y, w_p, 0.01)
    assert obj <= ref_obj * 1.01, (
        f"partitioned run objective {obj} vs sync {ref_obj}")


# -- double failure -----------------------------------------------------------


def test_double_failure_cold_recovery_bitwise_with_loud_warning(
        tmp_path, caplog):
    """Primary AND standby down: the supervisor cold-recovers a fresh
    store from the last checkpoint — loudly — and at τ=0 the run is
    STILL bitwise (the lost versions recompute from (seed, version))."""
    X, y, w0 = _data()
    w_ref, h_ref = _driver(tau=0, iters=60).optimize_with_history(
        (X, y), w0)
    mgr = CheckpointManager(os.fspath(tmp_path))
    drv = (_driver(tau=0, iters=60, standbys=1)
           .set_checkpoint(mgr, every=5))

    class _KillTwice(CollectingListener):
        def __init__(self):
            super().__init__()
            self.killed = set()

        def on_iteration(self, ev):
            super().on_iteration(ev)
            if ev.iteration in (15, 30) and ev.iteration not in self.killed:
                self.killed.add(ev.iteration)
                drv.kill_primary()

    drv.set_listener(_KillTwice())
    with caplog.at_level(logging.WARNING, logger="tpu_sgd.replica.ha"):
        w_d, h_d = drv.optimize_with_history((X, y), w0)
    snap = drv.last_failover_snapshot
    assert snap["failovers"] == 2
    assert not snap["records"][0]["cold_recovery"]
    assert snap["records"][1]["cold_recovery"]
    assert any("cold-recovering" in r.message for r in caplog.records)
    np.testing.assert_array_equal(np.asarray(w_d), np.asarray(w_ref))
    np.testing.assert_array_equal(h_d, h_ref)
    # the final checkpoints carry the promoted epoch
    assert mgr.restore()["epoch"] == 2


# -- preemption vs failover (the recorded bugfix) -----------------------------


def test_preempt_waits_for_inflight_failover_to_settle():
    """``stop()``/``save_now()`` during an in-flight promotion block on
    ``await_settled`` — the preempted checkpoint is the PROMOTED
    store's consistent (epoch, version), never a mid-failover limbo."""
    import jax.numpy as jnp

    _, _, w0 = _data(n=32, d=8)
    primary, standby, sup = _store_pair(_cfg(), w0, tau=2)
    client = sup.client()
    client.register_worker("w0", 0)
    pulled = client.pull("w0")
    client.push("w0", pulled.version, jnp.asarray(np.ones(8, np.float32)),
                jnp.asarray(1.0), jnp.asarray(8.0),
                basis_epoch=pulled.epoch)
    # stretch the promotion with injected latency, stop() mid-flight
    with fp.inject_faults({"replica.failover":
                           fp.inject_latency(1000.0)}):
        killer = threading.Thread(target=sup.kill_primary)
        killer.start()
        time.sleep(0.25)  # the promotion is now sleeping in its span
        t0 = time.monotonic()
        client.stop()
        waited = time.monotonic() - t0
        killer.join(timeout=30)
    assert waited >= 0.25, (
        f"stop() returned in {waited:.3f}s while a 1s promotion was in "
        "flight — preemption did not wait for failover to settle")
    assert sup.failover_count == 1
    snap = client.snapshot()
    assert snap["epoch"] == 1 and snap["stopped"]
    assert sup.primary() is standby


def test_supervised_preempt_resume_bitwise_with_standby(tmp_path):
    """The PR 10 preempt-resume contract survives the HA layer: the
    checkpointed (epoch, version) resumes bitwise."""
    from tpu_sgd.reliability.supervisor import TrainingSupervisor

    X, y, w0 = _data()
    w_ref, h_ref = _driver(tau=0, workers=2, iters=40) \
        .optimize_with_history((X, y), w0)
    mgr = CheckpointManager(os.fspath(tmp_path))
    drv = _driver(tau=0, workers=2, iters=40, standbys=1)
    sup = TrainingSupervisor(drv, checkpoint_manager=mgr,
                             checkpoint_every=10,
                             install_signal_handlers=False)

    class _PreemptAt(CollectingListener):
        def on_iteration(self, ev):
            super().on_iteration(ev)
            if ev.iteration == 12:
                sup.request_preempt()

    drv.set_listener(_PreemptAt())
    res = sup.run((X, y), w0)
    assert res.status == "preempted"
    drv.set_listener(None)
    res2 = sup.run((X, y), w0)
    assert res2.completed
    np.testing.assert_array_equal(np.asarray(res2.weights),
                                  np.asarray(w_ref))
    np.testing.assert_array_equal(res2.loss_history, h_ref)


def test_stopped_store_never_applies_partial_round():
    """Regression (the preempt-poison race): at τ=0, a worker exiting
    AFTER stop() must not 'complete' a round holding only its peer's
    contribution — a half-batch update applied after the preempt
    version was read would silently poison the resume trajectory."""
    import jax.numpy as jnp

    _, _, w0 = _data(n=32, d=8)
    store = ParameterStore(SquaredL2Updater(), _cfg(), w0, staleness=0)
    store.register_worker("w0", 0)
    store.register_worker("w1", 1)
    results = []

    def _push():
        results.append(store.push(
            "w0", 0, jnp.asarray(np.ones(8, np.float32)),
            jnp.asarray(1.0), jnp.asarray(8.0)))

    t = threading.Thread(target=_push)
    t.start()
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        with store._cond:
            if "w0" in store._inbox:
                break
        time.sleep(0.005)
    store.stop()                    # preemption: version read here
    store.deregister_worker("w1")   # the peer's clean exit
    t.join(timeout=30)
    assert store.version == 0, (
        "a stopped store applied a HALF round (one of two registered "
        "contributions) — the preempt checkpoint is now off-trajectory")


# -- delta-log memory / retention ---------------------------------------------


def test_delta_log_trims_to_live_replication_gap():
    """The log's working set is the live gap, not the retention
    backstop: records every reader has applied are trimmed on append,
    and a run's end leaves a near-empty log (the retained payloads are
    full per-version gradient copies — retain×W×d bytes would dwarf
    the model at production widths)."""
    X, y, w0 = _data(n=128, d=8)
    drv = _driver(tau=0, workers=2, iters=40, standbys=1)
    drv.optimize_with_history((X, y), w0)
    log = drv.last_supervisor._log
    with log._cond:
        # the standby drained and kept advancing its cursor: only the
        # tail of the live gap survives, never the whole run
        assert len(log._records) <= 4, len(log._records)
        assert log._readers == {}  # stop() released every cursor


def test_standby_off_retention_window_marks_failed_never_promotes():
    """A standby that falls off the log's retention backstop can never
    catch up: it marks its store failed (loudly) and releases its
    cursor — promotion then skips it (here: straight to cold
    recovery) instead of fencing the primary and dying mid-promote."""
    from tpu_sgd.replica import DeltaLog, DeltaRecord, StandbyReplica

    _, _, w0 = _data(n=32, d=8)
    cfg = _cfg()
    store = ParameterStore(SquaredL2Updater(), cfg, w0, staleness=2,
                           name="s1")
    log = DeltaLog(retain=2)
    rep = StandbyReplica(store, log, name="s1")
    payload = ("sums", np.ones(8, np.float32),
               np.asarray(1.0, np.float32), np.asarray(8.0, np.float32))
    # the standby was not reading while versions 1..5 shipped: the
    # backstop evicted its next records before it ever registered
    for v in range(1, 6):
        log.append(DeltaRecord(0, v, "sums", (payload,)))
    rep.start()
    deadline = time.monotonic() + 10
    while not store.failed and time.monotonic() < deadline:
        time.sleep(0.01)
    assert store.failed, (
        "a standby off the retention window stayed promotion-eligible")
    with log._cond:
        assert "s1" not in log._readers
    rep.halt()


# -- lock discipline ----------------------------------------------------------


def test_supervisor_lock_discipline_validated_at_runtime():
    """GRAFTLINT_LOCKS for StoreSupervisor, validated dynamically on a
    live run with a mid-run failover (the runtime twin of the lexical
    rule)."""
    from tpu_sgd.analysis.runtime import instrument_object
    from tpu_sgd.replica import ha as ha_mod

    X, y, w0 = _data(n=64, d=6)
    cfg = _cfg(num_iterations=30, step_size=0.2,
               mini_batch_fraction=0.5)
    primary, standby, sup = _store_pair(cfg, w0, tau=1)
    recorder = instrument_object(
        sup, ha_mod.GRAFTLINT_LOCKS["StoreSupervisor"])
    client = sup.client()
    shards = shard_rows(X, y, 2)
    workers = [ReplicaWorker(f"w{s}", s, client, LeastSquaresGradient(),
                             cfg, *shards[s]) for s in range(2)]
    for s in range(2):
        client.register_worker(f"w{s}", s)
    killer = threading.Timer(0.1, sup.kill_primary)
    killer.start()
    threads = [threading.Thread(target=w.run) for w in workers]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    killer.cancel()
    sup.stop()
    assert sup.primary().version == 30
    assert recorder.checked_accesses > 0
    assert recorder.violations == []


# -- the obs surface ----------------------------------------------------------


def test_failover_detector_trips_on_failover_window_only():
    from tpu_sgd.obs.detect import (DetectorEngine, FailoverDetector,
                                    default_detectors)

    assert "failover" in {d.rule for d in default_detectors()}

    def _win(idx, series):
        return {"index": idx, "t_start": float(idx),
                "t_end": float(idx) + 1.0, "series": series}

    def _cnt(n):
        return {"count": n, "sum": 0.0, "mean": 0.0, "max": None,
                "bytes": 0}

    eng = DetectorEngine([FailoverDetector()])
    eng.on_window_close(_win(0, {"replica.step[w0]": _cnt(5)}))
    assert eng.trip_counts() == {}
    eng.on_window_close(_win(1, {"replica.failover": _cnt(1)}))
    assert eng.trip_counts() == {"failover": 1}
    # stays-tripped dedup + re-arm after a clean window
    eng.on_window_close(_win(2, {"replica.failover": _cnt(1)}))
    assert eng.trip_counts() == {"failover": 1}
    eng.on_window_close(_win(3, {}))
    eng.on_window_close(_win(4, {"replica.failover": _cnt(1)}))
    assert eng.trip_counts() == {"failover": 2}


def test_straggler_roster_survives_failover_window():
    """A promotion stalls the WHOLE fleet (re-route + recompute): the
    failover window resets the straggler deficits so the healed fleet
    never false-trips — while a worker still silent AFTER the failover
    keeps accumulating and trips."""
    from tpu_sgd.obs.detect import DetectorEngine, StragglerDetector

    def _win(idx, series):
        return {"index": idx, "t_start": float(idx),
                "t_end": float(idx) + 1.0, "series": series}

    def _cnt(n):
        return {"count": n, "sum": 0.0, "mean": 0.0, "max": None,
                "bytes": 0}

    det = StragglerDetector(min_fleet_steps=6)
    eng = DetectorEngine([det])
    eng.on_window_close(_win(0, {"replica.step[w0]": _cnt(3),
                                 "replica.step[w1]": _cnt(3)}))
    eng.on_window_close(_win(1, {"replica.step[w0]": _cnt(4)}))
    assert eng.trip_counts() == {}  # w1 deficit 4 < 6
    # failover window: deficits reset — without the reset w1 would be
    # at 8 >= 6 here and false-trip on re-routing latency
    eng.on_window_close(_win(2, {"replica.failover": _cnt(1),
                                 "replica.step[w0]": _cnt(4)}))
    assert eng.trip_counts() == {}
    # still silent after the failover: the rule keeps hunting
    eng.on_window_close(_win(3, {"replica.step[w0]": _cnt(4)}))
    assert eng.trip_counts() == {"replica-straggler": 1}


def test_membership_failover_record_and_event():
    from tpu_sgd.obs import spans
    from tpu_sgd.obs.timeseries import EVENT_FANOUT
    from tpu_sgd.replica import ReplicaMembership

    assert EVENT_FANOUT.get("replica.failover") == "new_primary"
    m = ReplicaMembership()
    sink = _ListSink()
    spans.enable_tracing(sink)
    try:
        m.failover("s0", "s1", 1, 7)
    finally:
        spans.disable_tracing()
    recs = m.failover_records()
    assert recs == [{"old_primary": "s0", "new_primary": "s1",
                     "epoch": 1, "gap_replayed": 7,
                     "cold_recovery": False}]
    evs = [p for k, p in sink.records
           if k == "trace_event" and p["name"] == "replica.failover"]
    assert len(evs) == 1
    assert evs[0]["new_primary"] == "s1" and evs[0]["gap"] == 7
