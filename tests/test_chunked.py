"""ChunkedGradient (one-read window schedule) parity tests.

The wrapper must be trajectory-equivalent to the stock two-pass
``window_sums`` (same window, same math, blocked f32 accumulation) for
every pointwise family, including ragged tails, masks, and the full
GradientDescent driver.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from tpu_sgd.ops.gradients import (ChunkedGradient, HingeGradient,
                                   LeastSquaresGradient, LogisticGradient)


def _data(rng, n=5000, d=32, dtype=np.float32):
    X = rng.normal(size=(n, d)).astype(dtype)
    w = rng.normal(size=(d,)).astype(np.float32)
    y = (X.astype(np.float32) @ w > 0).astype(np.float32)
    return jnp.asarray(X), jnp.asarray(y), jnp.asarray(w)


@pytest.mark.parametrize("base_cls", [LeastSquaresGradient, LogisticGradient,
                                      HingeGradient])
@pytest.mark.parametrize("m,chunk", [(1000, 256), (1000, 1000), (999, 256),
                                     (100, 4096)])
def test_window_sums_parity(rng, base_cls, m, chunk):
    X, y, w = _data(rng)
    base = base_cls()
    chunked = ChunkedGradient(base, chunk_rows=chunk)
    start = jnp.int32(123)
    g0, l0, c0 = base.window_sums(X, y, w, start, m)
    g1, l1, c1 = chunked.window_sums(X, y, w, start, m)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g0),
                               rtol=2e-5, atol=2e-4)
    assert float(l1) == pytest.approx(float(l0), rel=2e-5)
    assert float(c1) == float(c0) == m


def test_window_sums_with_valid_mask(rng):
    X, y, w = _data(rng, n=2000)
    valid = jnp.asarray((np.arange(2000) % 3 != 0).astype(np.float32))
    base = LeastSquaresGradient()
    chunked = ChunkedGradient(base, chunk_rows=128)
    g0, l0, c0 = base.window_sums(X, y, w, jnp.int32(40), 700, valid=valid)
    g1, l1, c1 = chunked.window_sums(X, y, w, jnp.int32(40), 700, valid=valid)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g0),
                               rtol=2e-5, atol=2e-4)
    assert float(c1) == float(c0)


def test_delegation_surface(rng):
    X, y, w = _data(rng, n=500)
    base = LogisticGradient()
    chunked = ChunkedGradient(base, chunk_rows=64)
    g0, l0, c0 = base.batch_sums(X, y, w)
    g1, l1, c1 = chunked.batch_sums(X, y, w)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g0), rtol=1e-6)
    assert chunked.weight_dim(32) == 32
    grad, loss = chunked.compute(X[0], y[0], w)
    grad0, loss0 = base.compute(X[0], y[0], w)
    np.testing.assert_allclose(np.asarray(grad), np.asarray(grad0))
    W = jnp.stack([w, 0.5 * w])
    s1, _ = chunked.loss_sweep(X, y, W)
    s0, _ = base.loss_sweep(X, y, W)
    np.testing.assert_allclose(np.asarray(s1), np.asarray(s0), rtol=1e-6)


def test_out_of_range_start_clamps_like_stock(rng):
    """start beyond n-m must clamp ONCE to the stock path's window, not
    per block (per-block clamping re-reads overlapping tail rows)."""
    X, y, w = _data(rng, n=5000)
    base = LeastSquaresGradient()
    chunked = ChunkedGradient(base, chunk_rows=1024)
    g0, l0, c0 = base.window_sums(X, y, w, jnp.int32(4000), 3000)
    g1, l1, c1 = chunked.window_sums(X, y, w, jnp.int32(4000), 3000)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g0),
                               rtol=2e-5, atol=2e-4)
    assert float(l1) == pytest.approx(float(l0), rel=2e-5)
    assert float(c1) == float(c0)


def test_bad_chunk_rejected():
    with pytest.raises(ValueError, match="chunk_rows"):
        ChunkedGradient(LeastSquaresGradient(), chunk_rows=0)


def test_full_driver_trajectory_matches(rng):
    """Same sliced-sampling SGD run, stock vs chunked gradient: the loss
    trajectories must agree to fp-reordering tolerance."""
    from tpu_sgd.optimize.gradient_descent import GradientDescent
    from tpu_sgd.ops.updaters import SimpleUpdater

    n, d = 8192, 16
    X = rng.normal(size=(n, d)).astype(np.float32)
    w_true = rng.normal(size=(d,)).astype(np.float32)
    y = (X @ w_true + 0.01 * rng.normal(size=n)).astype(np.float32)

    def run(gradient):
        opt = (
            GradientDescent(gradient, SimpleUpdater())
            .set_step_size(0.5)
            .set_num_iterations(12)
            .set_mini_batch_fraction(0.25)
            .set_sampling("sliced")
        )
        w = opt.optimize((X, y), np.zeros(d, np.float32))
        return np.asarray(w), list(opt.loss_history)

    w0, h0 = run(LeastSquaresGradient())
    w1, h1 = run(ChunkedGradient(LeastSquaresGradient(), chunk_rows=1024))
    np.testing.assert_allclose(w1, w0, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(h1, h0, rtol=1e-4, atol=1e-6)
