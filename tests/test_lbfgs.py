"""LBFGS behind the same Optimizer boundary (SURVEY.md §2 #18)."""

import numpy as np
import pytest

from tpu_sgd.models import LogisticRegressionWithLBFGS
from tpu_sgd.ops.gradients import LeastSquaresGradient, LogisticGradient
from tpu_sgd.ops.updaters import SimpleUpdater, SquaredL2Updater
from tpu_sgd.optimize.lbfgs import LBFGS
from tpu_sgd.utils.mlutils import linear_data, logistic_data


def test_lbfgs_solves_least_squares_exactly():
    X, y, w_true = linear_data(2000, 10, eps=0.0, seed=0)
    opt = LBFGS(LeastSquaresGradient(), SimpleUpdater(), max_num_iterations=100)
    w, hist = opt.optimize_with_history((X, y), np.zeros(10, np.float32))
    np.testing.assert_allclose(np.asarray(w), w_true, atol=1e-3)
    assert hist[-1] < 1e-6
    assert len(hist) < 60  # superlinear: far fewer iters than SGD needs


def test_lbfgs_beats_sgd_iteration_count():
    from tpu_sgd.optimize.gradient_descent import GradientDescent

    X, y, _ = logistic_data(2000, 8, seed=1)
    lb = LBFGS(LogisticGradient(), SquaredL2Updater(), reg_param=0.01,
               max_num_iterations=50)
    w_lb, h_lb = lb.optimize_with_history((X, y), np.zeros(8, np.float32))
    sgd = GradientDescent(LogisticGradient(), SquaredL2Updater())
    sgd.set_reg_param(0.01).set_num_iterations(50).set_convergence_tol(0.0)
    w_sgd, h_sgd = sgd.optimize_with_history((X, y), np.zeros(8, np.float32))
    assert h_lb[-1] <= h_sgd[-1] + 1e-4  # at least as good in <= iterations


def test_lbfgs_l2_reg_shrinks_weights():
    X, y, _ = logistic_data(1000, 6, seed=2)
    w0 = np.zeros(6, np.float32)
    w_low = np.asarray(LBFGS(LogisticGradient(), SquaredL2Updater(),
                             reg_param=0.0).optimize((X, y), w0))
    w_high = np.asarray(LBFGS(LogisticGradient(), SquaredL2Updater(),
                              reg_param=1.0).optimize((X, y), w0))
    assert np.linalg.norm(w_high) < np.linalg.norm(w_low)


def test_lbfgs_loss_monotone_nonincreasing():
    X, y, _ = logistic_data(500, 5, seed=3)
    _, hist = LBFGS(LogisticGradient(), SquaredL2Updater()).optimize_with_history(
        (X, y), np.zeros(5, np.float32)
    )
    assert all(hist[i + 1] <= hist[i] + 1e-6 for i in range(len(hist) - 1))


def test_logistic_regression_with_lbfgs_model():
    X, y, w_true = logistic_data(3000, 8, seed=4)
    model = LogisticRegressionWithLBFGS.train((X, y), reg_param=0.001,
                                              intercept=True)
    acc = np.mean(np.asarray(model.predict(X)) == y)
    bayes = np.mean((X @ w_true > 0).astype(np.float32) == y)
    assert acc > bayes - 0.02


def test_lbfgs_empty_input():
    opt = LBFGS(LeastSquaresGradient(), SimpleUpdater())
    w0 = np.ones(3, np.float32)
    w, hist = opt.optimize_with_history(
        (np.zeros((0, 3), np.float32), np.zeros((0,), np.float32)), w0
    )
    np.testing.assert_array_equal(np.asarray(w), w0)
    assert len(hist) == 0


def test_lbfgs_dp_mesh_parity():
    """set_mesh shards the cost function's batch sums with one psum (the
    treeAggregate CostFun analogue, VERDICT r1 missing #4): the 8-way
    trajectory matches single-device up to reduction-order float noise —
    including the padded path (n not divisible by the mesh)."""
    from tpu_sgd.parallel.mesh import data_mesh

    for n in (4000, 4001):  # even shards; padded shards (valid mask)
        X, y, _ = logistic_data(n, 8, seed=5)
        w0 = np.zeros(8, np.float32)
        args = (LogisticGradient(), SquaredL2Updater())
        w1, h1 = LBFGS(*args, reg_param=0.01).optimize_with_history(
            (X, y), w0
        )
        opt8 = LBFGS(*args, reg_param=0.01).set_mesh(data_mesh())
        w8, h8 = opt8.optimize_with_history((X, y), w0)
        assert len(h8) == len(h1)
        np.testing.assert_allclose(np.asarray(w8), np.asarray(w1),
                                   rtol=1e-3, atol=1e-4)
        np.testing.assert_allclose(h8, h1, rtol=1e-4, atol=1e-6)


def test_lbfgs_rejects_2d_mesh():
    from tpu_sgd.parallel.mesh import make_mesh

    with pytest.raises(ValueError, match="data-only mesh"):
        LBFGS().set_mesh(make_mesh(4, 2))


def test_lbfgs_multinomial_mesh():
    """The matrix-weight (multinomial) gradient also runs sharded: its
    batch_sums produce psum-able flat sums (sequential line search)."""
    from tpu_sgd.parallel.mesh import data_mesh

    rng = np.random.default_rng(7)
    n, d, k = 1200, 6, 3
    W_true = rng.normal(size=(k - 1, d)).astype(np.float32)
    X = rng.normal(size=(n, d)).astype(np.float32)
    logits = np.concatenate([np.zeros((n, 1)), X @ W_true.T], axis=1)
    y = logits.argmax(axis=1).astype(np.float32)

    from tpu_sgd.ops.gradients import MultinomialLogisticGradient

    g = MultinomialLogisticGradient(k)
    w0 = np.zeros((k - 1) * d, np.float32)
    w1, h1 = LBFGS(g, SquaredL2Updater(), reg_param=0.001,
                   max_num_iterations=30).optimize_with_history((X, y), w0)
    w8, h8 = (
        LBFGS(g, SquaredL2Updater(), reg_param=0.001, max_num_iterations=30)
        .set_mesh(data_mesh())
        .optimize_with_history((X, y), w0)
    )
    assert len(h8) == len(h1)
    np.testing.assert_allclose(np.asarray(w8), np.asarray(w1), rtol=1e-3,
                               atol=1e-4)


def test_run_lbfgs_signature_parity():
    """``run_lbfgs`` mirrors the reference's ``object LBFGS.runLBFGS``
    argument order and (weights, loss_history) return contract."""
    from tpu_sgd.optimize.lbfgs import run_lbfgs

    X, y, w_true = logistic_data(2000, 6, seed=11)
    w, hist = run_lbfgs(
        (X, y),
        LogisticGradient(),
        SquaredL2Updater(),
        10,      # num_corrections
        1e-6,    # convergence_tol
        50,      # max_num_iterations
        0.01,    # reg_param
        np.zeros(6, np.float32),
    )
    assert hist[-1] < hist[0]
    opt = LBFGS(LogisticGradient(), SquaredL2Updater(), reg_param=0.01)
    w2, hist2 = opt.optimize_with_history((X, y), np.zeros(6, np.float32))
    np.testing.assert_allclose(np.asarray(w), np.asarray(w2), rtol=1e-6)


# ---- meshed sufficient statistics (round 5: VERDICT r4 #5) -----------------

def test_lbfgs_meshed_sufficient_stats_matches_stock():
    """Meshed LBFGS + set_sufficient_stats: per-shard blockwise TOTALS +
    one psum, then the loop runs unmeshed from the replicated (d, d)
    statistics — the trajectory must match the stock full-batch run
    (totals are EXACT, including non-divisible row counts)."""
    from tpu_sgd.parallel.mesh import data_mesh

    for n in (4096, 4100):  # divisible and padded shard splits
        X, y, w_true = linear_data(n, 10, seed=3)
        w0 = np.zeros(10, np.float32)

        def make():
            return LBFGS(LeastSquaresGradient(), SimpleUpdater(),
                         max_num_iterations=12, convergence_tol=0.0)

        w_stock, h_stock = make().optimize_with_history((X, y), w0)
        opt = make().set_mesh(data_mesh()).set_sufficient_stats(True) \
            .set_gram_options(block_rows=256)
        w_mesh, h_mesh = opt.optimize_with_history((X, y), w0)
        # LS converges in ~3 LBFGS iterations; past that the loss is
        # flat at float32 resolution and the Armijo accept flips on
        # last-ulp differences (one path stops, the other re-accepts
        # no-op steps) — compare the descent prefix + final weights.
        L = min(len(h_stock), len(h_mesh))
        assert L >= 4, (n, len(h_stock), len(h_mesh))
        np.testing.assert_allclose(np.asarray(h_mesh)[:L],
                                   np.asarray(h_stock)[:L],
                                   rtol=1e-4, atol=1e-6)
        np.testing.assert_allclose(np.asarray(w_mesh),
                                   np.asarray(w_stock),
                                   rtol=1e-3, atol=1e-4)


def test_lbfgs_meshed_streamed_stats_matches_stock():
    """Meshed LBFGS + set_streamed_stats: each device streams its host
    row slice into an O(d²) totals carry (no prefix stack, no dropped
    tail — EXACT), combined once; must reproduce the stock full-batch
    trajectory."""
    from tpu_sgd.parallel.mesh import data_mesh

    X, y, w_true = linear_data(4100, 10, seed=4)  # n % 8 != 0
    w0 = np.zeros(10, np.float32)

    def make():
        return LBFGS(LeastSquaresGradient(), SimpleUpdater(),
                     max_num_iterations=12, convergence_tol=0.0)

    w_stock, h_stock = make().optimize_with_history((X, y), w0)
    opt = make().set_mesh(data_mesh()) \
        .set_streamed_stats(True, block_rows=128)
    w_mesh, h_mesh = opt.optimize_with_history((X, y), w0)
    L = min(len(h_stock), len(h_mesh))
    assert L >= 4  # see the divisibility test's flat-loss note
    np.testing.assert_allclose(np.asarray(h_mesh)[:L],
                               np.asarray(h_stock)[:L],
                               rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(np.asarray(w_mesh), np.asarray(w_stock),
                               rtol=1e-3, atol=1e-4)
    # the identity cache keys on the mesh: a repeat run reuses the build
    entry = opt._streamed_gram_entry
    opt.optimize_with_history((X, y), w0)
    assert opt._streamed_gram_entry is entry


def test_owlqn_meshed_sufficient_stats_matches_stock():
    """Lasso least squares (OWL-QN) through the meshed totals
    substitution."""
    from tpu_sgd.optimize.owlqn import OWLQN
    from tpu_sgd.parallel.mesh import data_mesh

    X, y, w_true = linear_data(2048, 8, seed=5)
    w0 = np.zeros(8, np.float32)

    def make():
        return OWLQN(LeastSquaresGradient(), max_num_iterations=10,
                     convergence_tol=0.0, reg_param=0.002)

    w_stock, h_stock = make().optimize_with_history((X, y), w0)
    opt = make().set_mesh(data_mesh()).set_sufficient_stats(True)
    w_mesh, h_mesh = opt.optimize_with_history((X, y), w0)
    L = min(len(h_stock), len(h_mesh))
    assert L >= 4  # see the flat-loss note above
    np.testing.assert_allclose(np.asarray(h_mesh)[:L],
                               np.asarray(h_stock)[:L],
                               rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(np.asarray(w_mesh), np.asarray(w_stock),
                               rtol=1e-3, atol=1e-4)


def test_repeat_optimize_reuses_compiled_evaluators(rng):
    """Repeated optimize() calls (the streaming mode's per-micro-batch
    re-entry) must reuse the jitted cost/sweep programs, not retrace and
    recompile them — the evaluator cache keys on everything the closures
    bake in, so the second call adds NO new entries."""
    X = rng.normal(size=(512, 8)).astype(np.float32)
    y = (X @ rng.uniform(-1, 1, 8).astype(np.float32)).astype(np.float32)
    w0 = np.zeros(8, np.float32)
    opt = LBFGS(LeastSquaresGradient(), SquaredL2Updater(),
                reg_param=0.01, max_num_iterations=3)
    w1, _ = opt.optimize_with_history((X, y), w0)
    entries = dict(opt._eval_cache)
    w2, _ = opt.optimize_with_history((X, y), w0)
    assert dict(opt._eval_cache) == entries  # same objects, no rebuild
    assert all(opt._eval_cache[k] is entries[k] for k in entries)
    np.testing.assert_allclose(np.asarray(w2), np.asarray(w1))
    # release clears the cache (entries close over dropped gradients)
    opt.release_sufficient_stats()
    assert opt._eval_cache == {}


def test_dataset_sweep_evicts_displaced_gram_evaluators(rng):
    """Switching datasets replaces the single-slot gram bundle; the
    evaluator cache must drop entries closing over the DISPLACED gram
    gradient, or a hyperparameter sweep pins every prior dataset's rows
    and prefix stacks in device memory."""
    def data(seed, n=512):
        r = np.random.default_rng(seed)
        X = r.normal(size=(n, 8)).astype(np.float32)
        return X, (X @ r.uniform(-1, 1, 8).astype(np.float32)).astype(
            np.float32)

    opt = LBFGS(LeastSquaresGradient(), SquaredL2Updater(),
                reg_param=0.01, max_num_iterations=3) \
        .set_sufficient_stats(True)
    w0 = np.zeros(8, np.float32)
    Xa, ya = data(1)
    opt.optimize_with_history((Xa, ya), w0)
    grad_a = opt._gram_entry[2]
    assert any(grad_a in k for k in opt._eval_cache)
    Xb, yb = data(2)
    opt.optimize_with_history((Xb, yb), w0)
    assert opt._gram_entry[2] is not grad_a
    assert not any(grad_a in k for k in opt._eval_cache)  # evicted
