"""Unit tests of gradients against NumPy closed forms (SURVEY.md §4)."""

import numpy as np
import pytest

from tpu_sgd.ops.gradients import (
    HingeGradient,
    LeastSquaresGradient,
    LogisticGradient,
    MultinomialLogisticGradient,
)


def _rand(n=32, d=7, seed=1):
    r = np.random.default_rng(seed)
    X = r.normal(size=(n, d)).astype(np.float32)
    w = r.normal(size=(d,)).astype(np.float32)
    return X, w


class TestLeastSquares:
    def test_closed_form_single(self):
        X, w = _rand()
        y = np.random.default_rng(2).normal(size=(X.shape[0],)).astype(np.float32)
        g = LeastSquaresGradient()
        grad, loss = g.compute(X[0], y[0], w)
        diff = X[0] @ w - y[0]
        np.testing.assert_allclose(loss, 0.5 * diff**2, rtol=1e-5)
        np.testing.assert_allclose(grad, diff * X[0], rtol=1e-5)

    def test_batch_matches_sum_of_singles(self):
        X, w = _rand()
        y = np.random.default_rng(2).normal(size=(X.shape[0],)).astype(np.float32)
        g = LeastSquaresGradient()
        gs, ls, c = g.batch_sums(X, y, w)
        grad_ref = sum(np.asarray(g.compute(X[i], y[i], w)[0]) for i in range(len(y)))
        loss_ref = sum(float(g.compute(X[i], y[i], w)[1]) for i in range(len(y)))
        np.testing.assert_allclose(gs, grad_ref, rtol=1e-4)
        np.testing.assert_allclose(ls, loss_ref, rtol=1e-4)
        assert c == len(y)

    def test_mask(self):
        X, w = _rand()
        y = np.zeros((X.shape[0],), np.float32)
        mask = np.zeros((X.shape[0],), bool)
        mask[:5] = True
        g = LeastSquaresGradient()
        gs, ls, c = g.batch_sums(X, y, w, mask)
        gs2, ls2, c2 = g.batch_sums(X[:5], y[:5], w)
        np.testing.assert_allclose(gs, gs2, rtol=1e-5)
        np.testing.assert_allclose(ls, ls2, rtol=1e-5)
        assert c == 5


class TestLogistic:
    def test_closed_form(self):
        X, w = _rand()
        y = (np.random.default_rng(3).uniform(size=(X.shape[0],)) < 0.5).astype(
            np.float32
        )
        g = LogisticGradient()
        margins = X @ w
        coeff, loss = g.pointwise(margins, y)
        sig = 1.0 / (1.0 + np.exp(-margins))
        np.testing.assert_allclose(coeff, sig - y, rtol=1e-4, atol=1e-6)
        # reference form: loss = log1p(exp(-x.w)) [- (-x.w) if y == 0]
        neg = -margins
        ref = np.log1p(np.exp(neg))
        ref = np.where(y > 0, ref, ref - neg)
        np.testing.assert_allclose(loss, ref, rtol=1e-4, atol=1e-6)

    def test_numerical_stability_large_margin(self):
        g = LogisticGradient()
        coeff, loss = g.pointwise(np.asarray([1e4, -1e4], np.float32),
                                  np.asarray([1.0, 0.0], np.float32))
        assert np.all(np.isfinite(np.asarray(loss)))
        assert np.all(np.isfinite(np.asarray(coeff)))

    def test_gradient_is_autodiff_of_loss(self):
        import jax
        import jax.numpy as jnp

        X, w = _rand(8, 5)
        y = (np.random.default_rng(4).uniform(size=(8,)) < 0.5).astype(np.float32)
        g = LogisticGradient()

        def total_loss(w_):
            _, loss = g.pointwise(jnp.asarray(X) @ w_, jnp.asarray(y))
            return jnp.sum(loss)

        auto = jax.grad(total_loss)(np.asarray(w))
        gs, _, _ = g.batch_sums(X, y, w)
        np.testing.assert_allclose(auto, gs, rtol=1e-3, atol=1e-5)


class TestHinge:
    def test_closed_form(self):
        X, w = _rand()
        y = (np.random.default_rng(5).uniform(size=(X.shape[0],)) < 0.5).astype(
            np.float32
        )
        g = HingeGradient()
        margins = X @ w
        coeff, loss = g.pointwise(margins, y)
        s = 2 * y - 1
        slack = 1 - s * margins
        np.testing.assert_allclose(
            loss, np.where(slack > 0, slack, 0.0), rtol=1e-5, atol=1e-6
        )
        np.testing.assert_allclose(
            coeff, np.where(slack > 0, -s, 0.0), rtol=1e-5, atol=1e-6
        )

    def test_inactive_examples_contribute_nothing(self):
        g = HingeGradient()
        # margin 5 with label +1 -> slack = -4 < 0
        grad, loss = g.compute(
            np.ones((3,), np.float32) * 2.0, np.float32(1.0),
            np.asarray([1.0, 0.5, 1.0], np.float32),
        )
        assert float(loss) == 0.0
        np.testing.assert_allclose(grad, np.zeros((3,)), atol=1e-7)


class TestMultinomial:
    def test_reduces_to_binary(self):
        X, w = _rand(64, 6, seed=7)
        y = (np.random.default_rng(8).uniform(size=(64,)) < 0.5).astype(np.float32)
        m = MultinomialLogisticGradient(2)
        b = LogisticGradient()
        gs_m, ls_m, c_m = m.batch_sums(X, y, w)
        gs_b, ls_b, c_b = b.batch_sums(X, y, w)
        np.testing.assert_allclose(gs_m, gs_b, rtol=1e-3, atol=1e-4)
        np.testing.assert_allclose(ls_m, ls_b, rtol=1e-3, atol=1e-4)

    def test_gradient_is_autodiff_of_loss(self):
        import jax
        import jax.numpy as jnp

        K, d, n = 4, 5, 32
        r = np.random.default_rng(9)
        X = r.normal(size=(n, d)).astype(np.float32)
        y = r.integers(0, K, size=(n,)).astype(np.float32)
        w = r.normal(size=((K - 1) * d,)).astype(np.float32)
        m = MultinomialLogisticGradient(K)

        def total_loss(w_):
            W = w_.reshape(K - 1, d)
            logits = jnp.concatenate(
                [jnp.zeros((n, 1)), jnp.asarray(X) @ W.T], axis=-1
            )
            lp = jax.nn.log_softmax(logits, axis=-1)
            return -jnp.sum(
                jnp.take_along_axis(lp, jnp.asarray(y, jnp.int32)[:, None], axis=-1)
            )

        auto = jax.grad(total_loss)(np.asarray(w))
        gs, ls, c = m.batch_sums(X, y, w)
        np.testing.assert_allclose(auto, gs, rtol=1e-3, atol=1e-4)
        np.testing.assert_allclose(float(total_loss(np.asarray(w))), float(ls), rtol=1e-4)
