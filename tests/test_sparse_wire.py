"""Compressed sparse gradient wire (ISSUE 9): top-k + error feedback,
and the host-streamed BCOO feed that never densifies.

Pins, per the issue's acceptance criteria:

* top-k + EF compression conserves mass (shipped + residual == sum of
  updates) and the compressed shard-totals merge matches the dense
  merge (the residual flush carries every coordinate);
* the compressed gradient wire trains to MATCHED final loss (<= 1%
  relative) vs the dense wire, replays bitwise, composes with
  ``set_superstep(K)``, and preempt->resume restores the checkpointed
  EF accumulator bitwise in all three sampling modes;
* the host-streamed sparse feed stages fixed-shape BCOO components
  (ONE compiled body per build), never materializes a dense chunk, and
  its wire ships >= 10x fewer physical bytes than the dense-f32
  equivalent (the obs wire counters);
* the ``io.sparse_wire`` failpoint heals through the ingest
  ``RetryPolicy`` bitwise.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from tpu_sgd.io.sparse_wire import (ErrorFeedback, bcoo_to_csr_host,
                                    gather_csr_rows, parse_wire_compress,
                                    plan_sparse_batches, stage_sparse_batch,
                                    topk_nnz, topk_select)
from tpu_sgd.ops.gradients import HingeGradient
from tpu_sgd.ops.sparse import sparse_data
from tpu_sgd.optimize.gradient_descent import GradientDescent


# -- wire-format primitives --------------------------------------------------

def test_parse_wire_compress():
    assert parse_wire_compress(None) is None
    assert parse_wire_compress("topk:0.01") == pytest.approx(0.01)
    assert parse_wire_compress("topk:1") == pytest.approx(1.0)
    for bad in ("topk", "topk:", "topk:0", "topk:1.5", "gzip:9", 0.5):
        with pytest.raises(ValueError):
            parse_wire_compress(bad)


def test_topk_nnz_and_select():
    assert topk_nnz(100, 0.01) == 1
    assert topk_nnz(1000, 0.013) == 13
    assert topk_nnz(10, 1.0) == 10
    v = np.array([0.1, -5.0, 2.0, 0.0, -3.0], np.float32)
    idx = topk_select(v, 2)
    assert set(idx.tolist()) == {1, 4}  # largest |v|
    assert idx.dtype == np.int32
    assert set(topk_select(v, 99).tolist()) == set(range(5))


def test_error_feedback_conserves_mass_and_roundtrips_state():
    ef = ErrorFeedback(32, 0.125)
    assert ef.k == 4
    rng = np.random.default_rng(0)
    total = np.zeros(32, np.float32)
    shipped = np.zeros(32, np.float32)
    for _ in range(7):
        u = rng.normal(size=32).astype(np.float32)
        total += u
        idx, vals = ef.compress(u)
        assert idx.shape == (4,) and vals.shape == (4,)
        shipped[idx] += vals
    # the dropped mass is CARRIED, not lost: shipped + residual is the
    # exact running sum (up to f.p. reassociation)
    np.testing.assert_allclose(shipped + ef.residual(), total,
                               rtol=1e-5, atol=1e-6)
    # checkpoint round-trip restores the accumulator exactly
    saved = ef.state()
    ef2 = ErrorFeedback(32, 0.125)
    ef2.load_state(saved)
    np.testing.assert_array_equal(ef2.acc, ef.acc)
    with pytest.raises(ValueError):
        ef2.load_state(np.zeros(31))
    with pytest.raises(ValueError):
        ef.compress(np.zeros(31, np.float32))


def test_csr_gather_and_stage_fixed_shape():
    X, _, _ = sparse_data(50, 40, nnz_per_row=5, seed=1)
    indptr, cols, vals, (n, d) = bcoo_to_csr_host(X)
    assert (n, d) == (50, 40) and vals.shape[0] == 250
    Xd = np.asarray(X.todense())
    lr, lc, lv = gather_csr_rows(indptr, cols, vals, np.array([7, 3]))
    assert lr.shape[0] == 10
    np.testing.assert_allclose(lv[lr == 0], Xd[7][Xd[7] != 0])
    np.testing.assert_allclose(lv[lr == 1], Xd[3][Xd[3] != 0])
    data, idx, valid = stage_sparse_batch(
        indptr, cols, vals, np.array([7, 3]), row_cap=4, nse_cap=16)
    assert data.shape == (16,) and idx.shape == (16, 2)
    assert valid.tolist() == [True, True, False, False]
    # padding entries are NULL entries: zero value at (0, 0)
    assert np.all(data[10:] == 0) and np.all(idx[10:] == 0)
    # dense reconstruction through scatter equals the gathered rows
    dense = np.zeros((4, 40), np.float32)
    np.add.at(dense, (idx[:, 0], idx[:, 1]), data)
    np.testing.assert_allclose(dense[0], Xd[7])
    np.testing.assert_allclose(dense[1], Xd[3])
    with pytest.raises(ValueError, match="capped nse"):
        stage_sparse_batch(indptr, cols, vals, np.array([7, 3]),
                           row_cap=4, nse_cap=8)


def test_plan_sparse_batches_covers_every_batch():
    X, _, _ = sparse_data(120, 60, nnz_per_row=4, seed=2)
    indptr, cols, vals, (n, d) = bcoo_to_csr_host(X)
    rng_rows = [np.random.default_rng(100 + i).choice(n, size=9,
                                                      replace=False)
                for i in range(1, 13)]

    def sample_rows(i):
        return rng_rows[i - 1]

    cap = plan_sparse_batches(indptr, sample_rows, 12, row_cap=9)
    row_nnz = np.diff(indptr)
    sizes = [int(row_nnz[r].sum()) for r in rng_rows]
    assert cap == max(sizes)


# -- compressed shard-totals merge (the gram/gradient merge wire) ------------

def test_compressed_totals_merge_matches_dense_and_shrinks_wire():
    from tpu_sgd import obs
    from tpu_sgd.obs import counters as obs_counters
    from tpu_sgd.obs.counters import wire_ratios
    from tpu_sgd.parallel.gram_parallel import build_streamed_total_stats
    from tpu_sgd.parallel.mesh import data_mesh

    mesh = data_mesh(jax.devices()[:4])
    rng = np.random.default_rng(3)
    Xh = rng.normal(size=(400, 16)).astype(np.float32)
    yh = rng.normal(size=400).astype(np.float32)
    dense = build_streamed_total_stats(mesh, Xh, yh, block_rows=32)
    obs_counters.enable()
    try:
        obs_counters.reset()
        comp = build_streamed_total_stats(mesh, Xh, yh, block_rows=32,
                                          wire_compress="topk:0.05")
        snap = obs_counters.snapshot()
    finally:
        obs_counters.disable()
        obs_counters.reset()
    # the EF residual flush carries every shard's full mass: totals are
    # exact up to reassociation of the adds
    np.testing.assert_allclose(np.asarray(comp.G_tot),
                               np.asarray(dense.G_tot),
                               rtol=2e-5, atol=1e-4)
    np.testing.assert_allclose(np.asarray(comp.b_tot),
                               np.asarray(dense.b_tot),
                               rtol=2e-5, atol=1e-4)
    # the merge's compressed segments shipped ~2*frac of the logical
    # bytes (value + int32 index per surviving entry)
    ratios = wire_ratios(snap)
    topk = [r for name, r in ratios.items() if name.endswith(".topk")]
    assert topk and topk[0]["n"] == 3  # shards 1..3 compressed
    assert topk[0]["ratio"] > 5.0


def test_compressed_merge_feeds_lbfgs_via_set_ingest_options():
    from tpu_sgd.optimize.lbfgs import LBFGS
    from tpu_sgd.parallel.mesh import data_mesh

    rng = np.random.default_rng(4)
    Xh = rng.normal(size=(256, 12)).astype(np.float32)
    w_true = rng.normal(size=12).astype(np.float32)
    yh = (Xh @ w_true).astype(np.float32)
    w0 = np.zeros(12, np.float32)

    def mk():
        return (LBFGS().set_max_num_iterations(15)
                .set_mesh(data_mesh(jax.devices()[:4]))
                .set_streamed_stats(True, block_rows=32))

    w_dense, h_dense = mk().optimize_with_history((Xh, yh), w0)
    o = mk()
    o.set_ingest_options(wire_compress="topk:0.1")
    w_comp, h_comp = o.optimize_with_history((Xh, yh), w0)
    np.testing.assert_allclose(np.asarray(w_comp), np.asarray(w_dense),
                               rtol=1e-3, atol=1e-4)
    # the exact linear system converges to float-noise loss; judge the
    # match with a noise-floor atol alongside the relative bound
    assert abs(h_comp[-1] - h_dense[-1]) <= max(
        0.01 * abs(h_dense[-1]), 1e-5)


# -- compressed gradient all-reduce (the data-parallel wire) -----------------

def _dense_reg(seed=0, n=384, d=20):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, d)).astype(np.float32)
    w_true = rng.normal(size=d).astype(np.float32)
    y = (X @ w_true + 0.01 * rng.normal(size=n)).astype(np.float32)
    return X, y


def _streamed_opt(iters=30, sampling="bernoulli", k=1, frac=0.5):
    o = (GradientDescent().set_num_iterations(iters).set_step_size(0.05)
         .set_mini_batch_fraction(frac).set_sampling(sampling)
         .set_convergence_tol(0.0).set_seed(7).set_host_streaming(True))
    if k > 1:
        o.set_superstep(k)
    return o


def test_compressed_wire_matched_final_loss_and_bitwise_replay():
    X, y = _dense_reg()
    w0 = np.zeros(X.shape[1], np.float32)
    _, h_dense = _streamed_opt(iters=80).optimize_with_history((X, y), w0)
    o = _streamed_opt(iters=80)
    o.set_ingest_options(wire_compress="topk:0.5")
    w1, h1 = o.optimize_with_history((X, y), w0)
    # acceptance: matched final loss, <= 1% relative (EF-SGD converges
    # to the dense optimum; early iterations lag while the accumulator
    # catches up, so the match is judged at the run's end)
    assert abs(h1[-1] - h_dense[-1]) <= 0.01 * abs(h_dense[-1])
    # compressed runs are deterministic: replay is bitwise
    o2 = _streamed_opt(iters=80)
    o2.set_ingest_options(wire_compress="topk:0.5")
    w2, h2 = o2.optimize_with_history((X, y), w0)
    np.testing.assert_array_equal(np.asarray(w1), np.asarray(w2))
    np.testing.assert_array_equal(h1, h2)


def test_compressed_wire_composes_with_superstep_and_mesh():
    from tpu_sgd.parallel.mesh import data_mesh

    X, y = _dense_reg(seed=1)
    w0 = np.zeros(X.shape[1], np.float32)
    mesh = data_mesh(jax.devices()[:4])
    # meshed compressed all-reduce: K=1 and K=4 fused
    o1 = _streamed_opt(iters=60)
    o1.set_mesh(mesh).set_ingest_options(wire_compress="topk:0.5")
    w1, h1 = o1.optimize_with_history((X, y), w0)
    o4 = _streamed_opt(iters=60, k=4)
    o4.set_mesh(mesh).set_ingest_options(wire_compress="topk:0.5")
    w4, h4 = o4.optimize_with_history((X, y), w0)
    assert len(h4) == len(h1) == 60
    # the meshed dense baseline: compressed stays matched-loss
    ob = _streamed_opt(iters=60)
    ob.set_mesh(mesh)
    _, hb = ob.optimize_with_history((X, y), w0)
    assert abs(h1[-1] - hb[-1]) <= 0.02 * abs(hb[-1])
    # full-batch shared feed fuses too
    of = _streamed_opt(iters=12, k=4, frac=1.0)
    of.set_ingest_options(wire_compress="topk:0.25")
    _, hf = of.optimize_with_history((X, y), w0)
    assert len(hf) == 12


@pytest.mark.parametrize("sampling,k", [("bernoulli", 1), ("sliced", 4),
                                        ("indexed", 4)])
def test_ef_state_resumes_bitwise_across_preemption(tmp_path, sampling, k):
    """EF accumulator is checkpointed and restored: a mid-run
    preempt->resume compressed run is bitwise vs uninterrupted."""
    from tpu_sgd.reliability import failpoints as fp
    from tpu_sgd.utils.checkpoint import CheckpointManager

    X, y = _dense_reg(seed=2, n=256, d=16)
    w0 = np.zeros(16, np.float32)

    def mk():
        o = _streamed_opt(iters=24, sampling=sampling, k=k)
        o.set_ingest_options(wire_compress="topk:0.25")
        return o

    w_ref, h_ref = mk().optimize_with_history((X, y), w0)
    ckdir = str(tmp_path / f"ck_{sampling}_{k}")
    o = mk().set_checkpoint(CheckpointManager(ckdir), every=5)
    # mid-run dispatch AFTER the first cadence checkpoint exists (one
    # dispatch per iteration at K=1, one per superstep at K=4)
    crash_at = 7 if k == 1 else 3
    with fp.inject_faults({"optimize.streamed.step": fp.fail_nth(crash_at)}):
        with pytest.raises(fp.FaultInjected):
            o.optimize_with_history((X, y), w0)
    # the checkpoint carries the EF accumulator alongside the weights
    from tpu_sgd.utils.checkpoint import CheckpointManager as CM

    state = CM(ckdir).restore()
    assert "ef" in state["extras"]
    o2 = mk().set_checkpoint(CheckpointManager(ckdir), every=5)
    w_res, h_res = o2.optimize_with_history((X, y), w0)
    np.testing.assert_array_equal(np.asarray(w_res), np.asarray(w_ref))
    np.testing.assert_array_equal(h_res, h_ref)


def test_resume_without_ef_state_warns():
    """A compressed resume from a checkpoint written WITHOUT EF state
    (dense run) restarts the accumulator at zero — loudly."""
    import tempfile

    from tpu_sgd.utils.checkpoint import CheckpointManager

    X, y = _dense_reg(seed=3, n=128, d=8)
    w0 = np.zeros(8, np.float32)
    with tempfile.TemporaryDirectory() as ckdir:
        # dense run writes checkpoints without EF extras
        o = _streamed_opt(iters=10)
        o.set_checkpoint(CheckpointManager(ckdir), every=5)
        o.optimize_with_history((X, y), w0)
        # make the final checkpoint non-final so the resume really runs
        o2 = _streamed_opt(iters=14)
        o2.set_ingest_options(wire_compress="topk:0.25")
        o2.set_checkpoint(CheckpointManager(ckdir), every=50)
        with pytest.warns(RuntimeWarning, match="without EF state"):
            o2.optimize_with_history((X, y), w0)


def test_wire_compress_composes_with_residency_partial_slab_falls_back():
    """ISSUE 20 lifted the PR 9 deviation: ``set_residency`` +
    ``wire_compress`` now composes — the EF accumulator rides the
    resident while-loop ring and the run is BITWISE its compressed
    superstep twin, with zero fallback warnings.  Only the
    partially-resident slab (no EF carry in the window step) still
    falls back to the dense wire, loudly."""
    import warnings

    X, y = _dense_reg(seed=4, n=128, d=8)
    w0 = np.zeros(8, np.float32)
    # whole-run resident driver: composes, bitwise vs compressed superstep
    o_sup = _streamed_opt(iters=8, k=4, frac=1.0)
    o_sup.set_ingest_options(wire_compress="topk:0.25")
    w_sup, h_sup = o_sup.optimize_with_history((X, y), w0)
    o = _streamed_opt(iters=8, k=4, frac=1.0)
    o.set_residency(2).set_ingest_options(wire_compress="topk:0.25")
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        w_res, h_res = o.optimize_with_history((X, y), w0)
    np.testing.assert_array_equal(np.asarray(w_res), np.asarray(w_sup))
    np.testing.assert_array_equal(h_res, h_sup)
    # partially-resident slab: warned fallback to the dense wire
    o2 = _streamed_opt(iters=8, sampling="sliced")
    o2.host_streaming = True
    o2.streaming_resident_rows = 100
    o2.set_ingest_options(wire_compress="topk:0.25")
    with pytest.warns(RuntimeWarning, match="partially-resident"):
        o2.optimize_with_history((X, y), w0)


# -- host-streamed BCOO feed (end-to-end sparse, never densified) ------------

def _sparse_problem(n=400, d=600, seed=5):
    X, y, _ = sparse_data(n, d, nnz_per_row=8, kind="svm", seed=seed)
    return X, y


def _sparse_opt(iters=20, k=1, frac=0.3):
    o = (GradientDescent(gradient=HingeGradient())
         .set_num_iterations(iters).set_step_size(0.2)
         .set_mini_batch_fraction(frac).set_convergence_tol(0.0)
         .set_seed(11).set_host_streaming(True))
    if k > 1:
        o.set_superstep(k)
    return o


def test_sparse_streamed_matches_dense_streamed():
    """The BCOO feed draws the SAME sampled row sequence as the dense
    streamed driver and trains the RCV1-shaped hinge workload to the
    same trajectory (sparse-vs-dense matmul lowering tolerance)."""
    X, y = _sparse_problem()
    w0 = np.zeros(X.shape[1], np.float32)
    w_sp, h_sp = _sparse_opt().optimize_with_history((X, y), w0)
    Xd = np.asarray(X.todense())
    w_d, h_d = _sparse_opt().optimize_with_history((Xd, y), w0)
    np.testing.assert_allclose(h_sp, h_d, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(w_sp), np.asarray(w_d),
                               rtol=1e-4, atol=1e-5)
    # matched final loss, the acceptance spelling
    assert abs(h_sp[-1] - h_d[-1]) <= 0.01 * max(abs(h_d[-1]), 1e-6)


def test_sparse_streamed_prefetch_ab_and_superstep_bitwise():
    X, y = _sparse_problem(seed=6)
    w0 = np.zeros(X.shape[1], np.float32)
    w1, h1 = _sparse_opt().optimize_with_history((X, y), w0)
    # prefetch off = the synchronous legacy feed, bitwise
    o = _sparse_opt()
    o.set_ingest_options(prefetch_depth=0)
    w2, h2 = o.optimize_with_history((X, y), w0)
    np.testing.assert_array_equal(np.asarray(w1), np.asarray(w2))
    # fused K=4 with a tail (20 % 4 == 0 -> use 18 for a real tail)
    oa = _sparse_opt(iters=18, k=4)
    wa, ha = oa.optimize_with_history((X, y), w0)
    ob = _sparse_opt(iters=18, k=4)
    wb, hb = ob.optimize_with_history((X, y), w0)
    assert len(ha) == 18
    np.testing.assert_array_equal(np.asarray(wa), np.asarray(wb))
    np.testing.assert_array_equal(ha, hb)


def test_sparse_streamed_one_compiled_body_per_build():
    from tpu_sgd.optimize import streamed_sparse as ss

    X, y = _sparse_problem(seed=7)
    w0 = np.zeros(X.shape[1], np.float32)
    ss._SPARSE_PROGRAMS.clear()
    _sparse_opt(iters=18, k=4).optimize_with_history((X, y), w0)
    progs = [p for k, p in ss._SPARSE_PROGRAMS.items() if k[4] == "super"]
    assert len(progs) == 1
    # tail superstep included, exactly ONE compiled fused body
    assert progs[0]._cache_size() == 1
    # a replay reuses the memoized program (no second trace)
    _sparse_opt(iters=18, k=4).optimize_with_history((X, y), w0)
    assert progs[0]._cache_size() == 1


def test_sparse_streamed_never_densifies_and_10x_wire_bytes():
    """Acceptance: the BCOO path never materializes a dense chunk
    (todense is poisoned for the whole run) and the wire ships >= 10x
    fewer physical bytes than dense-f32 (the obs wire counters)."""
    from jax.experimental import sparse as jsparse

    from tpu_sgd.obs import counters as obs_counters
    from tpu_sgd.obs.counters import wire_ratios

    X, y = _sparse_problem(seed=8)
    w0 = np.zeros(X.shape[1], np.float32)

    def _boom(*a, **kw):  # pragma: no cover - the pin
        raise AssertionError("dense chunk materialized on the sparse path")

    orig = jsparse.BCOO.todense
    jsparse.BCOO.todense = _boom
    obs_counters.enable()
    try:
        obs_counters.reset()
        _, h = _sparse_opt(iters=12, k=4).optimize_with_history((X, y),
                                                                w0)
        snap = obs_counters.snapshot()
    finally:
        jsparse.BCOO.todense = orig
        obs_counters.disable()
        obs_counters.reset()
    assert len(h) == 12
    ratios = wire_ratios(snap)
    bcoo = [r for name, r in ratios.items() if name.endswith(".bcoo")]
    assert bcoo, f"no bcoo wire records in {sorted(ratios)}"
    # 8 nnz of 600 features: the physical/logical gap is huge; >= 10x
    # is the acceptance floor
    assert bcoo[0]["ratio"] >= 10.0


def test_sparse_streamed_resume_and_failpoint_heal_bitwise(tmp_path):
    from tpu_sgd.reliability import failpoints as fp
    from tpu_sgd.reliability.retry import RetryPolicy
    from tpu_sgd.utils.checkpoint import CheckpointManager

    X, y = _sparse_problem(seed=9)
    w0 = np.zeros(X.shape[1], np.float32)
    w_ref, h_ref = _sparse_opt(iters=16, k=4).optimize_with_history(
        (X, y), w0)
    # crash mid-run + bare resume: bitwise
    ckdir = str(tmp_path / "ck_sparse")
    o = _sparse_opt(iters=16, k=4)
    o.set_checkpoint(CheckpointManager(ckdir), every=4)
    # aim the one-shot crash at the sparse stage site (io.sparse_wire
    # fires once per staged batch, on the prefetch worker)
    with fp.inject_faults({"io.sparse_wire": fp.fail_nth(6)}):
        with pytest.raises(fp.FaultInjected):
            o.optimize_with_history((X, y), w0)
    o2 = _sparse_opt(iters=16, k=4)
    o2.set_checkpoint(CheckpointManager(ckdir), every=4)
    w_res, h_res = o2.optimize_with_history((X, y), w0)
    np.testing.assert_array_equal(np.asarray(w_res), np.asarray(w_ref))
    np.testing.assert_array_equal(h_res, h_ref)
    # armed one-shot fault + RetryPolicy: heals in place, bitwise
    o3 = _sparse_opt(iters=16, k=4)
    o3.set_ingest_options(retry=RetryPolicy(max_attempts=3,
                                            base_backoff_s=0.001))
    with fp.inject_faults({"io.sparse_wire": fp.fail_nth(5)}):
        w_heal, h_heal = o3.optimize_with_history((X, y), w0)
        assert fp.triggers("io.sparse_wire") == 1
    np.testing.assert_array_equal(np.asarray(w_heal), np.asarray(w_ref))
    np.testing.assert_array_equal(h_heal, h_ref)


def test_sparse_streamed_full_batch_and_guards():
    X, y = _sparse_problem(n=120, d=200, seed=10)
    w0 = np.zeros(X.shape[1], np.float32)
    # full batch transfers once and scans (K=1 and fused)
    _, h1 = _sparse_opt(iters=6, frac=1.0).optimize_with_history((X, y),
                                                                 w0)
    _, h4 = _sparse_opt(iters=6, k=3, frac=1.0).optimize_with_history(
        (X, y), w0)
    assert len(h1) == 6 and len(h4) == 6
    # sliced sampling has no sparse row layout: loud error
    o = _sparse_opt().set_sampling("sliced")
    with pytest.raises(NotImplementedError, match="bernoulli"):
        o.optimize_with_history((X, y), w0)
    # wire_compress on the sparse feed: warned no-op (the BCOO
    # components ARE the wire format)
    o2 = _sparse_opt(iters=4)
    o2.set_ingest_options(wire_compress="topk:0.5")
    with pytest.warns(RuntimeWarning, match="already compressed"):
        o2.optimize_with_history((X, y), w0)


# -- planner -----------------------------------------------------------------

def test_choose_wire_compress_cost_model():
    from tpu_sgd.plan import CostModel, choose_wire_compress

    cm = CostModel()
    # single device: no all-reduce wire, never compress
    assert choose_wire_compress(10_000_000, 1, cm) is None
    # small d: compress overhead dominates the wire saving
    assert choose_wire_compress(1000, 8, cm) is None
    # huge d on a mesh: the wire dominates -> topk at the model's frac
    spec = choose_wire_compress(2_000_000, 8, cm)
    assert spec == f"topk:{cm.wire_compress_frac:g}"
    assert parse_wire_compress(spec) == pytest.approx(
        cm.wire_compress_frac)
    # a faster link raises the break-even dimension
    fast = CostModel(allreduce_gb_s=1000.0)
    assert choose_wire_compress(2_000_000, 8, fast) is None


def test_plan_wire_compress_knob_plumbing():
    from tpu_sgd.plan import (CostModel, Plan, apply_gram_knobs,
                              plan, reset_plan_owned_gram_knobs)

    # the meshed host_streamed schedule records (and proposes) the wire
    cm = CostModel(allreduce_gb_s=0.001, compress_overhead_s=1e-7)
    p = plan(2_000_000, 4096, itemsize=4, sampling="bernoulli",
             mini_batch_fraction=0.5, num_iterations=100, n_devices=8,
             free_hbm=1e9, cost_model=cm)
    assert p.schedule == "host_streamed"
    assert p.wire_compress == f"topk:{cm.wire_compress_frac:g}"
    assert "compressed gradient wire" in p.reason
    assert p.estimates["wire_compress"] == p.wire_compress

    o = GradientDescent()
    apply_gram_knobs(o, p)
    assert o.ingest_wire_compress == p.wire_compress
    reset_plan_owned_gram_knobs(o)
    assert o.ingest_wire_compress is None
    # user-set knob wins over the plan
    o2 = GradientDescent().set_ingest_options(wire_compress="topk:0.2")
    apply_gram_knobs(o2, p)
    assert o2.ingest_wire_compress == "topk:0.2"
    # False clears the user knob
    o2.set_ingest_options(wire_compress=False)
    assert o2.ingest_wire_compress is None
    # validation is eager
    with pytest.raises(ValueError):
        GradientDescent().set_ingest_options(wire_compress="topk:2.0")
    # single-device plans never propose compression
    p1 = plan(2_000_000, 4096, itemsize=4, sampling="bernoulli",
              mini_batch_fraction=0.5, num_iterations=100, n_devices=1,
              free_hbm=1e9, cost_model=cm)
    assert p1.wire_compress is None
    assert Plan("host_streamed", "x").wire_compress is None
