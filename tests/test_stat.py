"""Summary-statistics tests ([U] mllib/stat/StatisticsSuite shape)."""

import numpy as np
import pytest

from tpu_sgd.ops.sparse import sparse_data
from tpu_sgd.stat import col_stats, corr


class TestColStats:
    def test_dense_closed_forms(self, rng):
        X = rng.normal(size=(200, 5)).astype(np.float32) * 3 + 1
        X[:, 3] = 0.0
        s = col_stats(X)
        assert s.count == 200
        np.testing.assert_allclose(s.mean, X.mean(0), rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(
            s.variance, X.var(0, ddof=1), rtol=1e-3, atol=1e-6
        )
        np.testing.assert_allclose(s.max, X.max(0), rtol=1e-6)
        np.testing.assert_allclose(s.min, X.min(0), rtol=1e-6)
        np.testing.assert_array_equal(s.num_nonzeros, (X != 0).sum(0))
        np.testing.assert_allclose(
            s.norm_l1, np.abs(X).sum(0), rtol=1e-4
        )
        np.testing.assert_allclose(
            s.norm_l2, np.sqrt((X * X).sum(0)), rtol=1e-4
        )

    def test_sparse_matches_dense(self):
        X, _, _ = sparse_data(300, 50, nnz_per_row=6, seed=9)
        s_sp = col_stats(X)
        Xd = np.asarray(X.todense())
        s_d = col_stats(Xd)
        np.testing.assert_allclose(s_sp.mean, s_d.mean, rtol=1e-4, atol=1e-6)
        np.testing.assert_allclose(
            s_sp.variance, s_d.variance, rtol=1e-3, atol=1e-6
        )
        np.testing.assert_allclose(s_sp.max, s_d.max, rtol=1e-6, atol=1e-7)
        np.testing.assert_allclose(s_sp.min, s_d.min, rtol=1e-6, atol=1e-7)
        np.testing.assert_array_equal(s_sp.num_nonzeros, s_d.num_nonzeros)
        np.testing.assert_allclose(
            s_sp.norm_l2, s_d.norm_l2, rtol=1e-4, atol=1e-6
        )

    def test_sparse_implicit_zero_extrema(self):
        """A column whose stored values are all positive still has min 0
        when some row lacks an entry (reference summarizer semantics)."""
        from jax.experimental.sparse import BCOO
        import jax.numpy as jnp

        # col 0: entries in rows 0,1 only (of 3) -> min must be 0
        idx = np.array([[0, 0], [1, 0], [2, 1]], np.int32)
        vals = np.array([2.0, 3.0, -4.0], np.float32)
        X = BCOO((jnp.asarray(vals), jnp.asarray(idx)), shape=(3, 2))
        s = col_stats(X)
        assert s.min[0] == 0.0
        assert s.max[0] == 3.0
        assert s.max[1] == 0.0  # all stored values negative, zeros exist
        assert s.min[1] == -4.0

    def test_empty_rejected(self):
        with pytest.raises(ValueError):
            col_stats(np.zeros((0, 3), np.float32))


class TestCorr:
    def test_pearson_against_numpy(self, rng):
        X = rng.normal(size=(400, 6)).astype(np.float32)
        X[:, 1] = 2.0 * X[:, 0] + 0.1 * X[:, 1]  # strong correlation
        C = corr(X)
        np.testing.assert_allclose(C, np.corrcoef(X.T), rtol=2e-3, atol=2e-4)
        np.testing.assert_allclose(np.diag(C), 1.0)
        assert C[0, 1] > 0.99

    def test_spearman_against_scipy_convention(self, rng):
        X = rng.normal(size=(300, 3)).astype(np.float32)
        X[:, 2] = np.exp(X[:, 0])  # monotone -> spearman 1, pearson < 1
        C = corr(X, method="spearman")
        assert C[0, 2] == pytest.approx(1.0, abs=1e-5)
        assert corr(X)[0, 2] < 0.95

    def test_spearman_ties(self):
        # quantized data with heavy ties: average-rank convention
        from scipy.stats import spearmanr

        rng = np.random.default_rng(4)
        X = np.round(rng.normal(size=(200, 2)), 1).astype(np.float32)
        C = corr(X, method="spearman")
        expect = spearmanr(X[:, 0], X[:, 1]).statistic
        assert C[0, 1] == pytest.approx(expect, abs=1e-4)

    def test_constant_column_nan_off_diagonal(self, rng):
        X = rng.normal(size=(100, 2)).astype(np.float32)
        X[:, 1] = 5.0
        C = corr(X)
        assert np.isnan(C[0, 1])
        assert C[0, 0] == 1.0

    def test_unknown_method_rejected(self, rng):
        with pytest.raises(ValueError, match="unknown"):
            corr(rng.normal(size=(10, 2)), method="kendall")

    def test_sparse_pearson_never_densifies_input(self):
        """BCOO Pearson goes through the sparse-sparse Gram and must match
        the dense computation."""
        X, _, _ = sparse_data(300, 25, nnz_per_row=5, seed=11)
        C_sp = corr(X)
        C_d = corr(np.asarray(X.todense()))
        np.testing.assert_allclose(C_sp, C_d, rtol=2e-3, atol=2e-3)

    def test_sparse_spearman_rejected(self):
        X, _, _ = sparse_data(50, 10, nnz_per_row=3, seed=5)
        with pytest.raises(ValueError, match="dense rank"):
            corr(X, method="spearman")
