"""graftlint (tpu_sgd/analysis): rule fixtures, suppressions, mutation
checks against the REAL modules, and the runtime validators.

The mutation tests are the load-bearing half: they take the actual
source of ``io/prefetch.py`` / ``serve/batcher.py``, delete the exact
thing each rule exists to protect (a ``failpoint(...)`` hook, a
``with self._cond:``), and assert lint catches the seeded violation —
proof the rules guard the real code, not just synthetic fixtures."""

import os
import textwrap
import threading
import time

import numpy as np
import pytest

from tpu_sgd.analysis.core import (Config, Finding, KNOWN_RULES, ModuleFile,
                                   run_lint)
from tpu_sgd.analysis.rules_donation import DonationSafetyRule
from tpu_sgd.analysis.rules_failpoint import FailpointCoverageRule
from tpu_sgd.analysis.rules_lock import LockDisciplineRule
from tpu_sgd.analysis.rules_shape import EagerInLoopRule, ShapeTrapRule
from tpu_sgd.analysis.runtime import (CompileCountError, InstrumentedLock,
                                      LocksetRecorder, assert_compile_count,
                                      instrument_object)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

def mod(src: str, relpath: str = "fixture_mod.py") -> ModuleFile:
    return ModuleFile("/fixtures/" + relpath, relpath,
                      textwrap.dedent(src))


def lint(modules, rules, **cfg):
    cfg.setdefault("root", "/fixtures")
    if isinstance(modules, ModuleFile):
        modules = [modules]
    return run_lint(config=Config(**cfg), rules=rules, modules=modules)


def by_rule(result, rule: str):
    return [f for f in result.findings if f.rule == rule]


# -- shape-trap -------------------------------------------------------------

def test_shape_trap_fires_on_eager_pad_and_concatenate():
    res = lint(mod("""
        import jax.numpy as jnp

        def host_assemble(X, tail):
            Xp = jnp.pad(X, ((0, tail), (0, 0)))
            return jnp.concatenate([Xp, Xp])
    """), [ShapeTrapRule()])
    found = by_rule(res, "shape-trap")
    assert len(found) == 2
    assert "per input shape" in found[0].message


def test_shape_trap_fires_on_dynamic_slice_of_device_array():
    res = lint(mod("""
        import jax.numpy as jnp

        def score(X, w, n):
            out = jnp.matmul(X, w)
            return out[:n]
    """), [ShapeTrapRule()])
    assert len(by_rule(res, "shape-trap")) == 1


def test_shape_trap_silent_inside_jit_and_on_numpy():
    res = lint(mod("""
        import functools
        import jax
        import jax.numpy as jnp
        import numpy as np

        @jax.jit
        def traced_pad(X):
            return jnp.pad(X, ((0, 1), (0, 0)))

        @functools.partial(jax.jit, static_argnums=(1,))
        def traced_cat(X, k):
            def inner(A):
                return jnp.concatenate([A, A])
            return inner(X)[:k]

        def wrapped(X):
            return jnp.concatenate([X, X])

        apply_wrapped = jax.vmap(wrapped)

        def host_numpy(X, n):
            Xp = np.pad(X, ((0, 3), (0, 0)))
            return np.concatenate([Xp, Xp])[:n]

        def lax_map_body(X, B):
            def one(k):
                return jnp.concatenate([X, X])
            return jax.lax.map(one, jnp.arange(4))
    """), [ShapeTrapRule()])
    assert by_rule(res, "shape-trap") == []


def test_shape_trap_silent_on_helper_called_from_traced_fn():
    res = lint(mod("""
        import jax
        import jax.numpy as jnp

        def helper(X):
            return jnp.concatenate([X, X])

        @jax.jit
        def body(X):
            return helper(X)
    """), [ShapeTrapRule()])
    assert by_rule(res, "shape-trap") == []


def test_shape_trap_ignores_lax_dynamic_slice():
    # lax.dynamic_slice* has STATIC sizes: eager use compiles once per
    # input shape — it is the shape-stable idiom, not the trap
    res = lint(mod("""
        import jax
        import jax.numpy as jnp

        def window(X, k, B):
            return jax.lax.dynamic_slice_in_dim(X, k * B, B, 0)
    """), [ShapeTrapRule()])
    assert by_rule(res, "shape-trap") == []


# -- eager-in-loop ----------------------------------------------------------

def test_eager_in_loop_fires_on_jit_constructed_per_iteration():
    res = lint(mod("""
        import jax
        from functools import partial

        def run(fs, X):
            outs = []
            for f in fs:
                outs.append(jax.jit(f)(X))
            while X.sum() < 0:
                g = partial(jax.jit, donate_argnums=(0,))(fs[0])
            return outs
    """), [EagerInLoopRule()])
    assert len(by_rule(res, "eager-in-loop")) == 2


def test_eager_in_loop_silent_on_hoisted_and_memoized():
    res = lint(mod("""
        import functools
        import jax

        @functools.lru_cache(maxsize=None)
        def _program(B):
            return jax.jit(lambda X: X * B)

        compiled = jax.jit(lambda X: X + 1)

        def run(chunks):
            return [_program(c.shape[0])(c) for c in chunks]

        def loop_defines_fn(chunks):
            for c in chunks:
                # the jit lives in a def only CALLED later, not built here
                def build():
                    return jax.jit(lambda X: X)
                yield build
    """), [EagerInLoopRule()])
    assert by_rule(res, "eager-in-loop") == []


# -- lock-discipline --------------------------------------------------------

LOCKED_SRC = """
    import threading

    GRAFTLINT_LOCKS = {
        "Box": {
            "_val": "_lock",
            "_ref": "_lock:w",
        },
    }

    class Box:
        def __init__(self):
            self._lock = threading.Lock()
            self._val = 0
            self._ref = None

        def good(self):
            with self._lock:
                self._val += 1
                self._ref = object()

        def read_ref(self):
            return self._ref            # :w mode: bare read sanctioned
"""


def test_lock_discipline_clean_fixture():
    res = lint(mod(LOCKED_SRC), [LockDisciplineRule()])
    assert by_rule(res, "lock-discipline") == []


def test_lock_discipline_flags_unlocked_access_and_w_mode_write():
    res = lint(mod("""
        import threading

        GRAFTLINT_LOCKS = {"Box": {"_val": "_lock", "_ref": "_lock:w"}}

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self._val = 0
                self._ref = None

            def bad_read(self):
                return self._val

            def bad_write(self):
                self._ref = object()

            def closure_leak(self):
                def worker():
                    self._val += 1
                return worker
    """), [LockDisciplineRule()])
    found = by_rule(res, "lock-discipline")
    assert len(found) == 3
    assert any("read of guarded attribute self._val" in f.message
               for f in found)
    assert any("write of guarded attribute self._ref" in f.message
               for f in found)


def test_lock_discipline_init_exempt_and_declaration_drift():
    res = lint(mod("""
        import threading

        GRAFTLINT_LOCKS = {
            "Ghost": {"_x": "_lock"},
            "Real": {"_x": "_missing_lock"},
        }

        class Real:
            def __init__(self):
                self._x = 0
    """), [LockDisciplineRule()])
    found = by_rule(res, "lock-discipline")
    msgs = " | ".join(f.message for f in found)
    assert "no such class" in msgs            # Ghost
    assert "never assigned" in msgs           # _missing_lock
    # __init__'s unguarded self._x write itself is exempt
    assert "guarded attribute" not in msgs


# -- donation-safety --------------------------------------------------------

def test_donation_safety_fires_on_read_after_donate():
    res = lint(mod("""
        import jax
        from functools import partial

        @partial(jax.jit, donate_argnums=(0,))
        def acc(G, Gi):
            return G + Gi

        def build(chunks, G0):
            G = G0
            out = acc(G, chunks[0])
            return G.sum() + out.sum()
    """), [DonationSafetyRule()])
    found = by_rule(res, "donation-safety")
    assert len(found) == 1
    assert "donated to `acc`" in found[0].message


def test_donation_safety_silent_on_rebind_idiom():
    res = lint(mod("""
        import jax
        from functools import partial

        @partial(jax.jit, donate_argnums=(0,))
        def acc(G, Gi):
            return G + Gi

        def build(chunks, G0):
            G = G0
            for c in chunks:
                G = acc(G, c)
            return G
    """), [DonationSafetyRule()])
    assert by_rule(res, "donation-safety") == []


def test_donation_safety_resolves_cross_module_imports():
    provider = mod("""
        import jax

        def _raw(G, Gi):
            return G + Gi

        acc = jax.jit(_raw, donate_argnums=(0,))
    """, relpath="provider.py")
    consumer = mod("""
        from provider import acc

        def build(G, Gi):
            out = acc(G, Gi)
            return G.sum()
    """, relpath="consumer.py")
    res = lint([provider, consumer], [DonationSafetyRule()])
    found = by_rule(res, "donation-safety")
    assert len(found) == 1
    assert found[0].path == "consumer.py"


# -- failpoint-coverage -----------------------------------------------------

def test_failpoint_coverage_both_directions():
    registry = {"io.feed": "feed.py"}
    ok = mod("""
        from tpu_sgd.reliability.failpoints import failpoint

        def produce():
            failpoint("io.feed")
    """, relpath="feed.py")
    res = lint([ok], [FailpointCoverageRule(registry=registry)])
    assert by_rule(res, "failpoint-coverage") == []

    missing = mod("""
        def produce():
            pass
    """, relpath="feed.py")
    res = lint([missing], [FailpointCoverageRule(registry=registry)])
    found = by_rule(res, "failpoint-coverage")
    assert len(found) == 1 and "deleted or never wired" in found[0].message

    unregistered = mod("""
        from tpu_sgd.reliability.failpoints import failpoint

        def produce():
            failpoint("io.feed")
            failpoint("io.rogue_site")
    """, relpath="feed.py")
    res = lint([unregistered], [FailpointCoverageRule(registry=registry)])
    found = by_rule(res, "failpoint-coverage")
    assert len(found) == 1 and "not registered" in found[0].message


def test_failpoint_coverage_points_at_moved_hook():
    registry = {"io.feed": "feed.py"}
    elsewhere = mod("""
        from tpu_sgd.reliability.failpoints import failpoint

        def produce():
            failpoint("io.feed")
    """, relpath="other.py")
    empty = mod("def produce():\n    pass\n", relpath="feed.py")
    res = lint([empty, elsewhere],
               [FailpointCoverageRule(registry=registry)])
    found = by_rule(res, "failpoint-coverage")
    assert len(found) == 1 and "other.py" in found[0].message


# -- suppressions -----------------------------------------------------------

def test_suppression_same_line_with_reason():
    res = lint(mod("""
        import jax.numpy as jnp

        def host(X):
            return jnp.concatenate([X, X])  # graftlint: disable=shape-trap -- fixture reason
    """), [ShapeTrapRule()])
    assert res.findings == [] and res.suppressed == 1


def test_suppression_standalone_line_above():
    res = lint(mod("""
        import jax.numpy as jnp

        def host(X):
            # graftlint: disable=shape-trap -- fixture reason
            return jnp.concatenate([X, X])
    """), [ShapeTrapRule()])
    assert res.findings == [] and res.suppressed == 1


def test_suppression_all_wildcard_and_wrong_rule():
    res = lint(mod("""
        import jax.numpy as jnp

        def host(X):
            # graftlint: disable=all -- fixture reason
            return jnp.concatenate([X, X])

        def host2(X):
            # graftlint: disable=lock-discipline -- wrong rule on purpose
            return jnp.concatenate([X, X])
    """), [ShapeTrapRule()])
    assert len(by_rule(res, "shape-trap")) == 1  # host2 not covered
    assert res.suppressed == 1


def test_bare_suppression_and_unknown_rule_are_findings():
    res = lint(mod("""
        import jax.numpy as jnp

        def host(X):
            # graftlint: disable=shape-trap
            return jnp.concatenate([X, X])

        def host2(X):
            # graftlint: disable=shape_trap -- underscores, not a rule id
            return jnp.concatenate([X, X])
    """), [ShapeTrapRule()])
    rules = {f.rule for f in res.findings}
    assert "bare-suppression" in rules
    assert "unknown-rule" in rules


# -- mutation checks against the REAL modules -------------------------------

def _real_module(relpath: str, transform=None) -> ModuleFile:
    with open(os.path.join(REPO, relpath), encoding="utf-8") as f:
        src = f.read()
    if transform is not None:
        mutated = transform(src)
        assert mutated != src, "mutation did not apply"
        src = mutated
    return ModuleFile("/mutated/" + relpath, relpath, src)


def test_mutation_deleted_failpoint_hook_fails_lint():
    """Delete the prefetcher's failpoint call in a copy of the real
    module: the failpoint-coverage rule must catch it."""
    registry_mod = _real_module("tpu_sgd/reliability/failpoints.py")
    intact = _real_module("tpu_sgd/io/prefetch.py")
    res = lint([registry_mod, intact], [FailpointCoverageRule()])
    baseline = by_rule(res, "failpoint-coverage")
    assert [f for f in baseline
            if "io.prefetch.produce" in f.message] == []

    mutated = _real_module(
        "tpu_sgd/io/prefetch.py",
        lambda s: s.replace('failpoint("io.prefetch.produce")', "pass"))
    res = lint([registry_mod, mutated], [FailpointCoverageRule()])
    found = by_rule(res, "failpoint-coverage")
    assert any("io.prefetch.produce" in f.message
               and "deleted or never wired" in f.message for f in found)


def test_mutation_deleted_lock_block_fails_lint():
    """Replace ``submit``'s ``with self._cond:`` with ``if True:`` in a
    copy of the real batcher: the lock-discipline rule must flag the
    now-unguarded queue accesses."""
    intact = _real_module("tpu_sgd/serve/batcher.py")
    res = lint([intact], [LockDisciplineRule()])
    assert by_rule(res, "lock-discipline") == []

    mutated = _real_module(
        "tpu_sgd/serve/batcher.py",
        lambda s: s.replace("with self._cond:", "if True:", 1))
    res = lint([mutated], [LockDisciplineRule()])
    found = by_rule(res, "lock-discipline")
    assert len(found) >= 2  # _stopped read + _pending touches in submit
    assert all("outside `with self._cond:`" in f.message for f in found)


def test_every_rule_fires_on_its_seeded_violation():
    """One seeded violation per rule, one combined sweep: each of the
    five rules must report exactly its own planted bug."""
    registry = {"io.feed": "seeded.py"}
    seeded = mod("""
        import threading
        import jax
        import jax.numpy as jnp
        from functools import partial

        GRAFTLINT_LOCKS = {"S": {"_q": "_lock"}}

        class S:
            def __init__(self):
                self._lock = threading.Lock()
                self._q = []

            def racy(self):
                return len(self._q)

        @partial(jax.jit, donate_argnums=(0,))
        def acc(G, Gi):
            return G + Gi

        def host(X, G, Gi):
            Xp = jnp.pad(X, ((0, 1), (0, 0)))
            out = acc(G, Gi)
            use_after = G.sum()
            for _ in range(2):
                f = jax.jit(lambda a: a)
            return Xp, out, use_after, f
    """, relpath="seeded.py")
    res = lint([seeded], [
        ShapeTrapRule(), LockDisciplineRule(), DonationSafetyRule(),
        FailpointCoverageRule(registry=registry), EagerInLoopRule()])
    fired = {f.rule for f in res.findings}
    assert set(KNOWN_RULES) <= fired, (
        f"rules that failed to fire: {set(KNOWN_RULES) - fired}")


# -- the repo itself is clean ----------------------------------------------

def test_repo_lints_clean():
    """The acceptance gate, as a test: zero unsuppressed findings over
    the configured include set, and every suppression carries a reason."""
    res = run_lint(root=REPO)
    assert res.findings == [], "\n".join(str(f) for f in res.findings)
    assert res.files > 50  # the sweep really walked the package


def test_cli_exit_codes(tmp_path, capsys):
    from tpu_sgd.analysis import lint as lint_cli

    assert lint_cli.main(["--root", REPO, "-q"]) == 0

    bad = tmp_path / "bad.py"
    bad.write_text(textwrap.dedent("""
        import jax.numpy as jnp

        def host(X):
            return jnp.pad(X, ((0, 1),))
    """))
    (tmp_path / "pyproject.toml").write_text("")
    rc = lint_cli.main(["--root", str(tmp_path), str(bad)])
    out = capsys.readouterr().out
    assert rc == 1 and "shape-trap" in out

    # a typo'd explicit path must fail loudly (exit 2), never report
    # clean with zero files checked
    rc = lint_cli.main(["--root", REPO, "tpu_sgd/no_such_file_xyz.py"])
    err = capsys.readouterr().err
    assert rc == 2 and "does not exist" in err

    # same for a typo'd config include: a renamed package must not turn
    # the CI lint gate vacuously green
    with pytest.raises(FileNotFoundError, match="include"):
        run_lint(config=Config(root=REPO, include=["tpu_sgd_renamed"]))


# -- runtime: assert_compile_count -----------------------------------------

class _FakeJitted:
    def __init__(self):
        self.n = 0

    def _cache_size(self):
        return self.n


def test_assert_compile_count_exact_and_at_most():
    fn = _FakeJitted()
    with assert_compile_count(2, of=fn):
        fn.n += 2
    with assert_compile_count(2, of=fn, at_most=True):
        fn.n += 1
    with pytest.raises(CompileCountError, match="allows exactly 1"):
        with assert_compile_count(1, of=fn):
            fn.n += 3
    with pytest.raises(CompileCountError, match="allows at most 0"):
        with assert_compile_count(0, of=fn, at_most=True):
            fn.n += 1


def test_assert_compile_count_sums_mixed_sources():
    fn, extra = _FakeJitted(), [0]
    with assert_compile_count(3, of=[fn, lambda: extra[0]]):
        fn.n += 1
        extra[0] += 2
    with pytest.raises(ValueError):
        assert_compile_count(-1, of=fn).__enter__()
    with pytest.raises(TypeError):
        with assert_compile_count(0, of=object()):
            pass


def test_assert_compile_count_on_real_jit():
    import jax
    import jax.numpy as jnp

    f = jax.jit(lambda x: x + 1)
    with assert_compile_count(1, of=f):
        f(jnp.zeros((3,)))
    with assert_compile_count(0, of=f):  # warm shape: no growth
        f(jnp.ones((3,)))
    with assert_compile_count(1, of=f):  # new shape: exactly one
        f(jnp.zeros((4,)))


# -- runtime: InstrumentedLock / instrument_object --------------------------

def test_instrumented_lock_tracks_holding_thread():
    rec = LocksetRecorder()
    lk = InstrumentedLock(threading.Lock(), name="L", recorder=rec)
    assert not lk.held_by_current_thread()
    with lk:
        assert lk.held_by_current_thread()
        seen = []
        t = threading.Thread(
            target=lambda: seen.append(lk.held_by_current_thread()))
        t.start()
        t.join()
        assert seen == [False]  # held-ness is per-thread
    assert not lk.held_by_current_thread()


def test_instrument_object_records_unguarded_access():
    class Box:
        def __init__(self):
            self._lock = threading.Lock()
            self._val = 0
            self._ref = None

        def good(self):
            with self._lock:
                self._val += 1

        def bad(self):
            self._val += 1

        def write_ref_unlocked(self):
            self._ref = object()

        def read_ref_unlocked(self):
            return self._ref

    box = Box()
    rec = instrument_object(box, {"_val": "_lock", "_ref": "_lock:w"})
    box.good()
    assert rec.violations == []
    box.bad()
    assert rec.violating_functions() == {"bad"}
    box.read_ref_unlocked()          # :w — bare read sanctioned
    assert rec.violating_functions() == {"bad"}
    box.write_ref_unlocked()         # :w — write must lock
    assert rec.violating_functions() == {"bad", "write_ref_unlocked"}


def test_real_batcher_declaration_validates_at_runtime():
    """The lock-discipline declaration in serve/batcher.py, validated
    dynamically: a real submit/flush workload over an instrumented
    MicroBatcher records NO unguarded access except the statically
    suppressed racy readers (queue_depth / the metrics sample)."""
    from tpu_sgd.serve.batcher import GRAFTLINT_LOCKS, MicroBatcher

    b = MicroBatcher(lambda X: np.asarray(X).sum(axis=1),
                     max_batch=4, max_latency_s=0.002)
    rec = instrument_object(b, GRAFTLINT_LOCKS["MicroBatcher"])
    futs = [b.submit(np.ones(3, np.float32)) for _ in range(9)]
    with b:
        got = [f.result(timeout=10) for f in futs]
    assert [float(g) for g in got] == [3.0] * 9
    depth = b.queue_depth  # the sanctioned racy read IS recorded
    assert depth == 0
    allowed = {"queue_depth", "_flush"}
    assert rec.violating_functions() <= allowed, rec.violations
    assert "queue_depth" in rec.violating_functions()
    assert rec.checked_accesses > 20  # the workload really went through


def test_real_eventlog_declaration_validates_at_runtime(tmp_path):
    from tpu_sgd.utils.events import (GRAFTLINT_LOCKS, IterationEvent,
                                      JsonLinesEventLog)

    log = JsonLinesEventLog(str(tmp_path / "ev.jsonl"))
    rec = instrument_object(log, GRAFTLINT_LOCKS["JsonLinesEventLog"])

    def writer(i):
        for j in range(20):
            log.on_iteration(IterationEvent(
                iteration=i * 100 + j, loss=0.0, weight_delta_norm=0.0,
                mini_batch_size=1, wall_time_s=0.0))

    threads = [threading.Thread(target=writer, args=(i,)) for i in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    log.close()
    assert rec.violations == []
    events = JsonLinesEventLog.read(str(tmp_path / "ev.jsonl"))
    assert len(events) == 60  # every line whole, none torn


def test_instrumented_condition_wait_releases_lockset():
    """Condition.wait releases the lock while blocked; the recorder must
    not count the waiter as a holder during that window."""
    rec = LocksetRecorder()
    cond = InstrumentedLock(threading.Condition(), name="c", recorder=rec)
    observed = []

    def waiter():
        with cond:
            observed.append(("pre", cond.held_by_current_thread()))
            cond.wait(timeout=5)
            observed.append(("post", cond.held_by_current_thread()))

    t = threading.Thread(target=waiter)
    t.start()
    time.sleep(0.2)
    with cond:  # acquirable because the waiter dropped it
        assert cond.held_by_current_thread()
        cond.notify_all()
    t.join(timeout=5)
    assert not t.is_alive()
    assert observed == [("pre", True), ("post", True)]
