"""graftlint (tpu_sgd/analysis): rule fixtures, suppressions, mutation
checks against the REAL modules, and the runtime validators.

The mutation tests are the load-bearing half: they take the actual
source of ``io/prefetch.py`` / ``serve/batcher.py``, delete the exact
thing each rule exists to protect (a ``failpoint(...)`` hook, a
``with self._cond:``), and assert lint catches the seeded violation —
proof the rules guard the real code, not just synthetic fixtures."""

import os
import textwrap
import threading
import time

import numpy as np
import pytest

from tpu_sgd.analysis import GRAFTLINT_LOCK_ORDER
from tpu_sgd.analysis.core import (Config, Finding, KNOWN_RULES, ModuleFile,
                                   load_config, load_modules, run_lint)
from tpu_sgd.analysis.rules_callback import CallbackDisciplineRule
from tpu_sgd.analysis.rules_carry import CarryStabilityRule
from tpu_sgd.analysis.rules_cond import CondDisciplineRule
from tpu_sgd.analysis.rules_contract import ContractDriftRule
from tpu_sgd.analysis.rules_donation import DonationSafetyRule
from tpu_sgd.analysis.rules_failpoint import FailpointCoverageRule
from tpu_sgd.analysis.rules_lock import LockDisciplineRule
from tpu_sgd.analysis.rules_memo import MemoKeyRule
from tpu_sgd.analysis.rules_order import LockOrderRule
from tpu_sgd.analysis.rules_shape import EagerInLoopRule, ShapeTrapRule
from tpu_sgd.analysis.rules_sync import HostSyncRule, ObsDisciplineRule
from tpu_sgd.analysis.runtime import (CompileCountError, InstrumentedLock,
                                      LockOrderError, LocksetRecorder,
                                      assert_compile_count, assert_lock_order,
                                      instrument_object)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

def mod(src: str, relpath: str = "fixture_mod.py") -> ModuleFile:
    return ModuleFile("/fixtures/" + relpath, relpath,
                      textwrap.dedent(src))


def lint(modules, rules, **cfg):
    cfg.setdefault("root", "/fixtures")
    if isinstance(modules, ModuleFile):
        modules = [modules]
    return run_lint(config=Config(**cfg), rules=rules, modules=modules)


def by_rule(result, rule: str):
    return [f for f in result.findings if f.rule == rule]


# -- shape-trap -------------------------------------------------------------

def test_shape_trap_fires_on_eager_pad_and_concatenate():
    res = lint(mod("""
        import jax.numpy as jnp

        def host_assemble(X, tail):
            Xp = jnp.pad(X, ((0, tail), (0, 0)))
            return jnp.concatenate([Xp, Xp])
    """), [ShapeTrapRule()])
    found = by_rule(res, "shape-trap")
    assert len(found) == 2
    assert "per input shape" in found[0].message


def test_shape_trap_fires_on_dynamic_slice_of_device_array():
    res = lint(mod("""
        import jax.numpy as jnp

        def score(X, w, n):
            out = jnp.matmul(X, w)
            return out[:n]
    """), [ShapeTrapRule()])
    assert len(by_rule(res, "shape-trap")) == 1


def test_shape_trap_silent_inside_jit_and_on_numpy():
    res = lint(mod("""
        import functools
        import jax
        import jax.numpy as jnp
        import numpy as np

        @jax.jit
        def traced_pad(X):
            return jnp.pad(X, ((0, 1), (0, 0)))

        @functools.partial(jax.jit, static_argnums=(1,))
        def traced_cat(X, k):
            def inner(A):
                return jnp.concatenate([A, A])
            return inner(X)[:k]

        def wrapped(X):
            return jnp.concatenate([X, X])

        apply_wrapped = jax.vmap(wrapped)

        def host_numpy(X, n):
            Xp = np.pad(X, ((0, 3), (0, 0)))
            return np.concatenate([Xp, Xp])[:n]

        def lax_map_body(X, B):
            def one(k):
                return jnp.concatenate([X, X])
            return jax.lax.map(one, jnp.arange(4))
    """), [ShapeTrapRule()])
    assert by_rule(res, "shape-trap") == []


def test_shape_trap_silent_on_helper_called_from_traced_fn():
    res = lint(mod("""
        import jax
        import jax.numpy as jnp

        def helper(X):
            return jnp.concatenate([X, X])

        @jax.jit
        def body(X):
            return helper(X)
    """), [ShapeTrapRule()])
    assert by_rule(res, "shape-trap") == []


def test_shape_trap_ignores_lax_dynamic_slice():
    # lax.dynamic_slice* has STATIC sizes: eager use compiles once per
    # input shape — it is the shape-stable idiom, not the trap
    res = lint(mod("""
        import jax
        import jax.numpy as jnp

        def window(X, k, B):
            return jax.lax.dynamic_slice_in_dim(X, k * B, B, 0)
    """), [ShapeTrapRule()])
    assert by_rule(res, "shape-trap") == []


# -- eager-in-loop ----------------------------------------------------------

def test_eager_in_loop_fires_on_jit_constructed_per_iteration():
    res = lint(mod("""
        import jax
        from functools import partial

        def run(fs, X):
            outs = []
            for f in fs:
                outs.append(jax.jit(f)(X))
            while X.sum() < 0:
                g = partial(jax.jit, donate_argnums=(0,))(fs[0])
            return outs
    """), [EagerInLoopRule()])
    assert len(by_rule(res, "eager-in-loop")) == 2


def test_eager_in_loop_silent_on_hoisted_and_memoized():
    res = lint(mod("""
        import functools
        import jax

        @functools.lru_cache(maxsize=None)
        def _program(B):
            return jax.jit(lambda X: X * B)

        compiled = jax.jit(lambda X: X + 1)

        def run(chunks):
            return [_program(c.shape[0])(c) for c in chunks]

        def loop_defines_fn(chunks):
            for c in chunks:
                # the jit lives in a def only CALLED later, not built here
                def build():
                    return jax.jit(lambda X: X)
                yield build
    """), [EagerInLoopRule()])
    assert by_rule(res, "eager-in-loop") == []


# -- lock-discipline --------------------------------------------------------

LOCKED_SRC = """
    import threading

    GRAFTLINT_LOCKS = {
        "Box": {
            "_val": "_lock",
            "_ref": "_lock:w",
        },
    }

    class Box:
        def __init__(self):
            self._lock = threading.Lock()
            self._val = 0
            self._ref = None

        def good(self):
            with self._lock:
                self._val += 1
                self._ref = object()

        def read_ref(self):
            return self._ref            # :w mode: bare read sanctioned
"""


def test_lock_discipline_clean_fixture():
    res = lint(mod(LOCKED_SRC), [LockDisciplineRule()])
    assert by_rule(res, "lock-discipline") == []


def test_lock_discipline_flags_unlocked_access_and_w_mode_write():
    res = lint(mod("""
        import threading

        GRAFTLINT_LOCKS = {"Box": {"_val": "_lock", "_ref": "_lock:w"}}

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self._val = 0
                self._ref = None

            def bad_read(self):
                return self._val

            def bad_write(self):
                self._ref = object()

            def closure_leak(self):
                def worker():
                    self._val += 1
                return worker
    """), [LockDisciplineRule()])
    found = by_rule(res, "lock-discipline")
    assert len(found) == 3
    assert any("read of guarded attribute self._val" in f.message
               for f in found)
    assert any("write of guarded attribute self._ref" in f.message
               for f in found)


def test_lock_discipline_init_exempt_and_declaration_drift():
    res = lint(mod("""
        import threading

        GRAFTLINT_LOCKS = {
            "Ghost": {"_x": "_lock"},
            "Real": {"_x": "_missing_lock"},
        }

        class Real:
            def __init__(self):
                self._x = 0
    """), [LockDisciplineRule()])
    found = by_rule(res, "lock-discipline")
    msgs = " | ".join(f.message for f in found)
    assert "no such class" in msgs            # Ghost
    assert "never assigned" in msgs           # _missing_lock
    # __init__'s unguarded self._x write itself is exempt
    assert "guarded attribute" not in msgs


# -- donation-safety --------------------------------------------------------

def test_donation_safety_fires_on_read_after_donate():
    res = lint(mod("""
        import jax
        from functools import partial

        @partial(jax.jit, donate_argnums=(0,))
        def acc(G, Gi):
            return G + Gi

        def build(chunks, G0):
            G = G0
            out = acc(G, chunks[0])
            return G.sum() + out.sum()
    """), [DonationSafetyRule()])
    found = by_rule(res, "donation-safety")
    assert len(found) == 1
    assert "donated to `acc`" in found[0].message


def test_donation_safety_silent_on_rebind_idiom():
    res = lint(mod("""
        import jax
        from functools import partial

        @partial(jax.jit, donate_argnums=(0,))
        def acc(G, Gi):
            return G + Gi

        def build(chunks, G0):
            G = G0
            for c in chunks:
                G = acc(G, c)
            return G
    """), [DonationSafetyRule()])
    assert by_rule(res, "donation-safety") == []


def test_donation_safety_resolves_cross_module_imports():
    provider = mod("""
        import jax

        def _raw(G, Gi):
            return G + Gi

        acc = jax.jit(_raw, donate_argnums=(0,))
    """, relpath="provider.py")
    consumer = mod("""
        from provider import acc

        def build(G, Gi):
            out = acc(G, Gi)
            return G.sum()
    """, relpath="consumer.py")
    res = lint([provider, consumer], [DonationSafetyRule()])
    found = by_rule(res, "donation-safety")
    assert len(found) == 1
    assert found[0].path == "consumer.py"


# -- failpoint-coverage -----------------------------------------------------

def test_failpoint_coverage_both_directions():
    registry = {"io.feed": "feed.py"}
    ok = mod("""
        from tpu_sgd.reliability.failpoints import failpoint

        def produce():
            failpoint("io.feed")
    """, relpath="feed.py")
    res = lint([ok], [FailpointCoverageRule(registry=registry)])
    assert by_rule(res, "failpoint-coverage") == []

    missing = mod("""
        def produce():
            pass
    """, relpath="feed.py")
    res = lint([missing], [FailpointCoverageRule(registry=registry)])
    found = by_rule(res, "failpoint-coverage")
    assert len(found) == 1 and "deleted or never wired" in found[0].message

    unregistered = mod("""
        from tpu_sgd.reliability.failpoints import failpoint

        def produce():
            failpoint("io.feed")
            failpoint("io.rogue_site")
    """, relpath="feed.py")
    res = lint([unregistered], [FailpointCoverageRule(registry=registry)])
    found = by_rule(res, "failpoint-coverage")
    assert len(found) == 1 and "not registered" in found[0].message


def test_failpoint_coverage_points_at_moved_hook():
    registry = {"io.feed": "feed.py"}
    elsewhere = mod("""
        from tpu_sgd.reliability.failpoints import failpoint

        def produce():
            failpoint("io.feed")
    """, relpath="other.py")
    empty = mod("def produce():\n    pass\n", relpath="feed.py")
    res = lint([empty, elsewhere],
               [FailpointCoverageRule(registry=registry)])
    found = by_rule(res, "failpoint-coverage")
    assert len(found) == 1 and "other.py" in found[0].message


# -- suppressions -----------------------------------------------------------

def test_suppression_same_line_with_reason():
    res = lint(mod("""
        import jax.numpy as jnp

        def host(X):
            return jnp.concatenate([X, X])  # graftlint: disable=shape-trap -- fixture reason
    """), [ShapeTrapRule()])
    assert res.findings == [] and res.suppressed == 1


def test_suppression_standalone_line_above():
    res = lint(mod("""
        import jax.numpy as jnp

        def host(X):
            # graftlint: disable=shape-trap -- fixture reason
            return jnp.concatenate([X, X])
    """), [ShapeTrapRule()])
    assert res.findings == [] and res.suppressed == 1


def test_suppression_all_wildcard_and_wrong_rule():
    res = lint(mod("""
        import jax.numpy as jnp

        def host(X):
            # graftlint: disable=all -- fixture reason
            return jnp.concatenate([X, X])

        def host2(X):
            # graftlint: disable=lock-discipline -- wrong rule on purpose
            return jnp.concatenate([X, X])
    """), [ShapeTrapRule()])
    assert len(by_rule(res, "shape-trap")) == 1  # host2 not covered
    assert res.suppressed == 1


def test_bare_suppression_and_unknown_rule_are_findings():
    res = lint(mod("""
        import jax.numpy as jnp

        def host(X):
            # graftlint: disable=shape-trap
            return jnp.concatenate([X, X])

        def host2(X):
            # graftlint: disable=shape_trap -- underscores, not a rule id
            return jnp.concatenate([X, X])
    """), [ShapeTrapRule()])
    rules = {f.rule for f in res.findings}
    assert "bare-suppression" in rules
    assert "unknown-rule" in rules


# -- mutation checks against the REAL modules -------------------------------

def _real_module(relpath: str, transform=None) -> ModuleFile:
    with open(os.path.join(REPO, relpath), encoding="utf-8") as f:
        src = f.read()
    if transform is not None:
        mutated = transform(src)
        assert mutated != src, "mutation did not apply"
        src = mutated
    return ModuleFile("/mutated/" + relpath, relpath, src)


def test_mutation_deleted_failpoint_hook_fails_lint():
    """Delete the prefetcher's failpoint call in a copy of the real
    module: the failpoint-coverage rule must catch it."""
    registry_mod = _real_module("tpu_sgd/reliability/failpoints.py")
    intact = _real_module("tpu_sgd/io/prefetch.py")
    res = lint([registry_mod, intact], [FailpointCoverageRule()])
    baseline = by_rule(res, "failpoint-coverage")
    assert [f for f in baseline
            if "io.prefetch.produce" in f.message] == []

    mutated = _real_module(
        "tpu_sgd/io/prefetch.py",
        lambda s: s.replace('failpoint("io.prefetch.produce")', "pass"))
    res = lint([registry_mod, mutated], [FailpointCoverageRule()])
    found = by_rule(res, "failpoint-coverage")
    assert any("io.prefetch.produce" in f.message
               and "deleted or never wired" in f.message for f in found)


def test_mutation_deleted_lock_block_fails_lint():
    """Replace ``submit``'s ``with self._cond:`` with ``if True:`` in a
    copy of the real batcher: the lock-discipline rule must flag the
    now-unguarded queue accesses."""
    intact = _real_module("tpu_sgd/serve/batcher.py")
    res = lint([intact], [LockDisciplineRule()])
    assert by_rule(res, "lock-discipline") == []

    mutated = _real_module(
        "tpu_sgd/serve/batcher.py",
        lambda s: s.replace("with self._cond:", "if True:", 1))
    res = lint([mutated], [LockDisciplineRule()])
    found = by_rule(res, "lock-discipline")
    assert len(found) >= 2  # _stopped read + _pending touches in submit
    assert all("outside `with self._cond:`" in f.message for f in found)


def test_every_rule_fires_on_its_seeded_violation():
    """One seeded violation per rule, one combined sweep: each of the
    thirteen rules must report exactly its own planted bug."""
    registry = {"io.feed": "seeded.py"}
    seeded = mod("""
        import threading
        import jax
        import jax.numpy as jnp
        import numpy as np
        from jax import lax
        from jax.experimental import io_callback
        from functools import partial
        from tpu_sgd.obs.spans import event

        GRAFTLINT_LOCKS = {
            "S": {"_q": "_lock"},
            "Cyc": {"_xa": "_l1", "_xb": "_l2"},
            "W": {"_q2": "_cv"},
        }

        class Cyc:  # lock-order: the two methods nest opposite ways
            def ab(self):
                with self._l1:
                    with self._l2:
                        pass

            def ba(self):
                with self._l2:
                    with self._l1:
                        pass

        class W:  # cond-discipline: a wait with no while around it
            def bad_wait(self):
                with self._cv:
                    self._cv.wait()

        # contract-drift: an SLO gate over a counter nothing emits
        SEEDED_SLOS = [
            {"metric": "counter", "name": "seeded",
             "counter": "no.such.counter", "max": 1},
        ]

        HIST = []
        _PROGRAMS = {}

        class S:
            def __init__(self):
                self._lock = threading.Lock()
                self._q = []

            def racy(self):
                return len(self._q)

        @partial(jax.jit, donate_argnums=(0,))
        def acc(G, Gi):
            return G + Gi

        step = jax.jit(lambda w: w * 2)

        def host(X, G, Gi):
            Xp = jnp.pad(X, ((0, 1), (0, 0)))
            out = acc(G, Gi)
            use_after = G.sum()
            for _ in range(2):
                f = jax.jit(lambda a: a)
            return Xp, out, use_after, f

        def drive(w, n):
            hist = []
            for _ in range(n):
                w = step(w)
                hist.append(float(w))
            return hist

        def leaky_cb(x):
            HIST.append(x)
            return x

        def resident(w):
            def body(carry):
                i, w = carry
                r = io_callback(leaky_cb, w, w)
                return (i + 1, r)
            return lax.while_loop(lambda c: c[0] < 3, body, (0, w))

        def program_for(k, lr):
            fn = _PROGRAMS.get(k)
            if fn is None:
                fn = jax.jit(lambda w: w * lr)
                _PROGRAMS[k] = fn
            return fn

        def traced_tick(w):
            out = step(w)
            event("train.tick", loss=out)
            return out
    """, relpath="seeded.py")
    from tpu_sgd.analysis.core import default_rules
    rules = [FailpointCoverageRule(registry=registry)
             if r.name == "failpoint-coverage" else r
             for r in default_rules()]
    res = lint([seeded], rules)
    fired = {f.rule for f in res.findings}
    assert set(KNOWN_RULES) <= fired, (
        f"rules that failed to fire: {set(KNOWN_RULES) - fired}")


# -- the repo itself is clean ----------------------------------------------

def test_repo_lints_clean():
    """The acceptance gate, as a test: zero unsuppressed findings over
    the configured include set, and every suppression carries a reason."""
    res = run_lint(root=REPO)
    assert res.findings == [], "\n".join(str(f) for f in res.findings)
    assert res.files > 50  # the sweep really walked the package


def test_cli_exit_codes(tmp_path, capsys):
    from tpu_sgd.analysis import lint as lint_cli

    assert lint_cli.main(["--root", REPO, "-q"]) == 0

    bad = tmp_path / "bad.py"
    bad.write_text(textwrap.dedent("""
        import jax.numpy as jnp

        def host(X):
            return jnp.pad(X, ((0, 1),))
    """))
    (tmp_path / "pyproject.toml").write_text("")
    rc = lint_cli.main(["--root", str(tmp_path), str(bad)])
    out = capsys.readouterr().out
    assert rc == 1 and "shape-trap" in out

    # a typo'd explicit path must fail loudly (exit 2), never report
    # clean with zero files checked
    rc = lint_cli.main(["--root", REPO, "tpu_sgd/no_such_file_xyz.py"])
    err = capsys.readouterr().err
    assert rc == 2 and "does not exist" in err

    # same for a typo'd config include: a renamed package must not turn
    # the CI lint gate vacuously green
    with pytest.raises(FileNotFoundError, match="include"):
        run_lint(config=Config(root=REPO, include=["tpu_sgd_renamed"]))


# -- runtime: assert_compile_count -----------------------------------------

class _FakeJitted:
    def __init__(self):
        self.n = 0

    def _cache_size(self):
        return self.n


def test_assert_compile_count_exact_and_at_most():
    fn = _FakeJitted()
    with assert_compile_count(2, of=fn):
        fn.n += 2
    with assert_compile_count(2, of=fn, at_most=True):
        fn.n += 1
    with pytest.raises(CompileCountError, match="allows exactly 1"):
        with assert_compile_count(1, of=fn):
            fn.n += 3
    with pytest.raises(CompileCountError, match="allows at most 0"):
        with assert_compile_count(0, of=fn, at_most=True):
            fn.n += 1


def test_assert_compile_count_sums_mixed_sources():
    fn, extra = _FakeJitted(), [0]
    with assert_compile_count(3, of=[fn, lambda: extra[0]]):
        fn.n += 1
        extra[0] += 2
    with pytest.raises(ValueError):
        assert_compile_count(-1, of=fn).__enter__()
    with pytest.raises(TypeError):
        with assert_compile_count(0, of=object()):
            pass


def test_assert_compile_count_on_real_jit():
    import jax
    import jax.numpy as jnp

    f = jax.jit(lambda x: x + 1)
    with assert_compile_count(1, of=f):
        f(jnp.zeros((3,)))
    with assert_compile_count(0, of=f):  # warm shape: no growth
        f(jnp.ones((3,)))
    with assert_compile_count(1, of=f):  # new shape: exactly one
        f(jnp.zeros((4,)))


# -- runtime: InstrumentedLock / instrument_object --------------------------

def test_instrumented_lock_tracks_holding_thread():
    rec = LocksetRecorder()
    lk = InstrumentedLock(threading.Lock(), name="L", recorder=rec)
    assert not lk.held_by_current_thread()
    with lk:
        assert lk.held_by_current_thread()
        seen = []
        t = threading.Thread(
            target=lambda: seen.append(lk.held_by_current_thread()))
        t.start()
        t.join()
        assert seen == [False]  # held-ness is per-thread
    assert not lk.held_by_current_thread()


def test_instrument_object_records_unguarded_access():
    class Box:
        def __init__(self):
            self._lock = threading.Lock()
            self._val = 0
            self._ref = None

        def good(self):
            with self._lock:
                self._val += 1

        def bad(self):
            self._val += 1

        def write_ref_unlocked(self):
            self._ref = object()

        def read_ref_unlocked(self):
            return self._ref

    box = Box()
    rec = instrument_object(box, {"_val": "_lock", "_ref": "_lock:w"})
    box.good()
    assert rec.violations == []
    box.bad()
    assert rec.violating_functions() == {"bad"}
    box.read_ref_unlocked()          # :w — bare read sanctioned
    assert rec.violating_functions() == {"bad"}
    box.write_ref_unlocked()         # :w — write must lock
    assert rec.violating_functions() == {"bad", "write_ref_unlocked"}


def test_real_batcher_declaration_validates_at_runtime():
    """The lock-discipline declaration in serve/batcher.py, validated
    dynamically: a real submit/flush workload over an instrumented
    MicroBatcher records NO unguarded access except the statically
    suppressed racy readers (queue_depth / the metrics sample)."""
    from tpu_sgd.serve.batcher import GRAFTLINT_LOCKS, MicroBatcher

    b = MicroBatcher(lambda X: np.asarray(X).sum(axis=1),
                     max_batch=4, max_latency_s=0.002)
    rec = instrument_object(b, GRAFTLINT_LOCKS["MicroBatcher"])
    futs = [b.submit(np.ones(3, np.float32)) for _ in range(9)]
    with b:
        got = [f.result(timeout=10) for f in futs]
    assert [float(g) for g in got] == [3.0] * 9
    depth = b.queue_depth  # the sanctioned racy read IS recorded
    assert depth == 0
    allowed = {"queue_depth", "_flush"}
    assert rec.violating_functions() <= allowed, rec.violations
    assert "queue_depth" in rec.violating_functions()
    assert rec.checked_accesses > 20  # the workload really went through


def test_real_eventlog_declaration_validates_at_runtime(tmp_path):
    from tpu_sgd.utils.events import (GRAFTLINT_LOCKS, IterationEvent,
                                      JsonLinesEventLog)

    log = JsonLinesEventLog(str(tmp_path / "ev.jsonl"))
    rec = instrument_object(log, GRAFTLINT_LOCKS["JsonLinesEventLog"])

    def writer(i):
        for j in range(20):
            log.on_iteration(IterationEvent(
                iteration=i * 100 + j, loss=0.0, weight_delta_norm=0.0,
                mini_batch_size=1, wall_time_s=0.0))

    threads = [threading.Thread(target=writer, args=(i,)) for i in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    log.close()
    assert rec.violations == []
    events = JsonLinesEventLog.read(str(tmp_path / "ev.jsonl"))
    assert len(events) == 60  # every line whole, none torn


def test_instrumented_condition_wait_releases_lockset():
    """Condition.wait releases the lock while blocked; the recorder must
    not count the waiter as a holder during that window."""
    rec = LocksetRecorder()
    cond = InstrumentedLock(threading.Condition(), name="c", recorder=rec)
    observed = []

    def waiter():
        with cond:
            observed.append(("pre", cond.held_by_current_thread()))
            cond.wait(timeout=5)
            observed.append(("post", cond.held_by_current_thread()))

    t = threading.Thread(target=waiter)
    t.start()
    time.sleep(0.2)
    with cond:  # acquirable because the waiter dropped it
        assert cond.held_by_current_thread()
        cond.notify_all()
    t.join(timeout=5)
    assert not t.is_alive()
    assert observed == [("pre", True), ("post", True)]


# -- host-sync (dataflow) ----------------------------------------------------

def test_host_sync_fires_on_scalar_coercions_in_loop():
    res = lint(mod("""
        import jax

        step = jax.jit(lambda w: w * 2)

        def drive(w, n):
            hist = []
            for _ in range(n):
                w = step(w)
                hist.append(float(w))
            return hist
    """), [HostSyncRule()])
    found = by_rule(res, "host-sync")
    assert len(found) == 1 and "float()" in found[0].message


def test_host_sync_fires_on_implicit_bool_and_while_test():
    res = lint(mod("""
        import jax

        step = jax.jit(lambda w: w)

        def poll(w):
            flag = step(w)
            while flag:
                flag = step(flag)
    """), [HostSyncRule()])
    found = by_rule(res, "host-sync")
    assert len(found) == 1 and "bool()" in found[0].message


def test_host_sync_fires_on_comparison_bool_test():
    """`if c > 0:` on a device value builds a device bool then coerces
    it — same per-trip sync as a bare-name test; and a host rebind
    (`c = int(c)`, itself flagged) releases the name for later tests."""
    res = lint(mod("""
        import jax

        step = jax.jit(lambda w: w)

        def poll(w, n):
            for _ in range(n):
                w = step(w)
                if w > 0:
                    break

        def drain(c):
            c = step(c)
            while c > 0:
                c = step(c)
    """), [HostSyncRule()])
    found = by_rule(res, "host-sync")
    assert len(found) == 2
    assert all("bool()" in f.message for f in found)

    res = lint(mod("""
        import jax

        step = jax.jit(lambda w: w)

        def drive(w, n):
            for _ in range(n):
                w = step(w)
                c = int(w)  # graftlint: disable=host-sync -- one sanctioned fetch
                if c > 0:
                    break
    """), [HostSyncRule()])
    assert by_rule(res, "host-sync") == []


def test_host_sync_interprocedural_flags_loop_borne_call_site():
    """A helper that forces the sync internally is flagged at its
    loop-borne call site — the line that pays."""
    res = lint(mod("""
        import jax
        import numpy as np

        step = jax.jit(lambda w: w * 2)

        def fetch(v):
            return np.asarray(v)

        def drive(w, n):
            for _ in range(n):
                w = step(w)
                fetch(w)
    """), [HostSyncRule()])
    found = by_rule(res, "host-sync")
    assert len(found) == 1
    assert "fetch" in found[0].message and found[0].line == 13


def test_host_sync_silent_on_boundary_fetch_and_traced_loops():
    """No finding for: a fetch AFTER the loop (the contract), the
    sanctioned genexp bulk fetch, a loop inside a traced function, and
    values the rule cannot prove device-resident."""
    res = lint(mod("""
        import jax
        import jax.numpy as jnp
        import numpy as np

        step = jax.jit(lambda w: w * 2)

        def drive(w, n):
            for _ in range(n):
                w = step(w)
            return float(w)

        def bulk(w, n):
            ys = step(w)
            for _ in range(n):
                w = step(w)
            return tuple(np.asarray(a) for a in (w, ys))

        @jax.jit
        def traced(w):
            for _ in range(3):
                w = jnp.sin(w)
            return w

        def host_numpy(rows, n):
            out = []
            for r in rows:
                out.append(np.asarray(r))
            return out
    """), [HostSyncRule()])
    assert by_rule(res, "host-sync") == []


def test_host_sync_silent_on_for_iterable_and_else_clause():
    """A for's iterable and a loop's else clause evaluate ONCE — the
    one-fetch-then-iterate spelling must not fire; the same fetch moved
    into the body still does, and an iterable fetch nested inside an
    OUTER loop's body is per-outer-trip and fires."""
    res = lint(mod("""
        import jax
        import numpy as np

        count = jax.jit(lambda w: w.sum())

        def once(w, rows):
            n = count(w)
            for i in range(int(n)):
                rows.append(i)
            else:
                tail = float(n)
            return tail

        def per_trip(w, rows):
            n = count(w)
            for _ in rows:
                k = int(n)
            return k

        def per_outer_trip(w, grids):
            n = count(w)
            for g in grids:
                for i in range(int(n)):
                    g.append(i)
    """), [HostSyncRule()])
    found = by_rule(res, "host-sync")
    assert len(found) == 2
    assert {f.line for f in found} == {18, 24}


# -- callback-discipline -----------------------------------------------------

def test_callback_unordered_consumed_result_and_leaky_target():
    res = lint(mod("""
        import jax
        from jax.experimental import io_callback

        HIST = []

        def bad_cb(x):
            HIST.append(x)
            return x

        def body(x):
            r = io_callback(bad_cb, x, x)
            return r
    """), [CallbackDisciplineRule()])
    found = by_rule(res, "callback-discipline")
    msgs = " | ".join(f.message for f in found)
    assert len(found) == 3
    assert "not ordered=True" in msgs
    assert "exception cross the FFI boundary" in msgs
    assert "appends to closure variable" in msgs


def test_callback_clean_site_passes():
    """ordered=True + stash-flag-reraise guard + bookkeeper-owned state:
    the resident_driver contract, distilled."""
    res = lint(mod("""
        import numpy as np
        from jax.experimental import io_callback

        class Keeper:
            def on_window(self, start, ws):
                try:
                    self.last = np.asarray(ws)
                    return np.zeros((), np.bool_)
                except BaseException as e:
                    self.error = e
                    return np.ones((), np.bool_)

        def build(keeper, spec):
            def fire(start, ws):
                return io_callback(keeper.on_window, spec, start, ws,
                                   ordered=True)
            return fire
    """), [CallbackDisciplineRule()])
    assert by_rule(res, "callback-discipline") == []


def test_callback_fire_and_forget_unordered_is_fine():
    """An Expr-statement callback (result unused) may stay unordered —
    no bookkeeping is driven by its result."""
    res = lint(mod("""
        from jax.experimental import io_callback

        def tick(x):
            try:
                print(x)
            except BaseException:
                pass

        def body(x):
            io_callback(tick, None, x)
            return x
    """), [CallbackDisciplineRule()])
    assert by_rule(res, "callback-discipline") == []


def test_callback_reraising_handler_is_still_leaky():
    res = lint(mod("""
        from jax.experimental import io_callback

        def cb(x):
            try:
                return x
            except BaseException:
                raise

        def body(x):
            r = io_callback(cb, x, x, ordered=True)
            return r
    """), [CallbackDisciplineRule()])
    found = by_rule(res, "callback-discipline")
    assert len(found) == 1
    assert "exception cross the FFI boundary" in found[0].message


def test_callback_target_resolution_survives_name_collision():
    """An unrelated `def on_window` elsewhere in the lint set must not
    silently void the contract checks: the call site's own module wins
    the tie, and a collision with NO local def is itself a finding."""
    caller = mod("""
        from jax.experimental import io_callback

        class Keeper:
            def on_window(self, x):
                return x

        def build(keeper, spec):
            def fire(x):
                return io_callback(keeper.on_window, spec, x,
                                   ordered=True)
            return fire
    """, "caller_mod.py")
    other = mod("""
        class Widget:
            def on_window(self, event):
                return event
    """, "other_mod.py")
    # alone: the unguarded local target is flagged
    res = lint([caller], [CallbackDisciplineRule()])
    found = by_rule(res, "callback-discipline")
    assert len(found) == 1
    assert "exception cross the FFI boundary" in found[0].message
    # with the colliding module: SAME finding — local def still wins
    res = lint([caller, other], [CallbackDisciplineRule()])
    found = by_rule(res, "callback-discipline")
    assert len(found) == 1
    assert "exception cross the FFI boundary" in found[0].message

    # no local def + several remote candidates: ambiguity is loud
    remote_caller = mod("""
        from jax.experimental import io_callback

        def build(hooks, spec):
            def fire(x):
                return io_callback(hooks.on_window, spec, x,
                                   ordered=True)
            return fire
    """, "remote_caller.py")
    other2 = mod("""
        class Panel:
            def on_window(self, event):
                return event
    """, "other2_mod.py")
    res = lint([remote_caller, other, other2],
               [CallbackDisciplineRule()])
    found = by_rule(res, "callback-discipline")
    assert len(found) == 1
    assert "matches several defs" in found[0].message


# -- carry-stability ---------------------------------------------------------

def test_carry_fires_on_python_scalar_init():
    res = lint(mod("""
        import jax
        from jax import lax

        def run(w):
            def body(carry):
                i, wc = carry
                return (i + 1, wc * 2)
            return lax.while_loop(lambda c: c[0] < 3, body, (0, w))
    """), [CarryStabilityRule()])
    found = by_rule(res, "carry-stability")
    assert len(found) == 1 and "WEAK-typed" in found[0].message


def test_carry_fires_on_scalar_reset_in_body():
    res = lint(mod("""
        import jax.numpy as jnp
        from jax import lax

        def run(xs, w):
            def body(c, x):
                return (0, c[1] + x)
            init = (jnp.asarray(0, jnp.int32), w)
            return lax.scan(body, init, xs)
    """), [CarryStabilityRule()])
    found = by_rule(res, "carry-stability")
    assert len(found) == 1 and "re-enters" in found[0].message


def test_carry_silent_on_pinned_init_and_device_reset():
    res = lint(mod("""
        import jax.numpy as jnp
        from jax import lax

        def run(xs, w):
            def body(c, x):
                slot = jnp.where(x > 0, jnp.zeros_like(c[0]), c[0])
                return (slot, c[1] + x), x
            init = (jnp.asarray(0, jnp.int32), w)
            return lax.scan(body, init, xs)

        def local_scan_helper_does_not_fire(scan, data):
            return scan(lambda c, x: (0, c), 0, data)
    """), [CarryStabilityRule()])
    assert by_rule(res, "carry-stability") == []


def test_carry_silent_on_non_jax_lax_lookalikes():
    """Only `lax` / `*.lax` heads are loop entries: `flax.while_loop`
    or a `parallax.scan` must not fire (the substring-match trap), while
    the real `jax.lax` spellings still do."""
    res = lint(mod("""
        import flax
        import parallax

        def run(w):
            flax.while_loop(lambda c: c, lambda c: c, (0, w))
            return parallax.scan(lambda c, x: (0, c), 0, w)
    """), [CarryStabilityRule()])
    assert by_rule(res, "carry-stability") == []

    res = lint(mod("""
        import jax
        from jax import lax

        def run(w, xs):
            jax.lax.while_loop(lambda c: c[0] < 3,
                               lambda c: (c[0] + 1, c[1]), (0, w))
            return lax.scan(lambda c, x: (c, x), 0.0, xs)
    """), [CarryStabilityRule()])
    assert len(by_rule(res, "carry-stability")) == 2


def test_carry_fires_on_keyword_init_and_body():
    """`lax.scan(body, init=(0, w), xs=xs)` and
    `lax.while_loop(..., init_val=..., body_fun=...)` are standard
    spellings — keyword-passed carries must not slip the net."""
    res = lint(mod("""
        import jax.numpy as jnp
        from jax import lax

        def kw_init(w, xs):
            return lax.scan(lambda c, x: (c, x), init=(0, w), xs=xs)

        def kw_body_reset(w, xs):
            init = (jnp.asarray(0, jnp.int32), w)
            return lax.scan(xs=xs, init=init,
                            f=lambda c, x: ((0, c[1] + x), x))

        def kw_while(w):
            return lax.while_loop(
                cond_fun=lambda c: c[0] < 3,
                body_fun=lambda c: (c[0] + 1, c[1]),
                init_val=(0, w))
    """), [CarryStabilityRule()])
    found = by_rule(res, "carry-stability")
    assert len(found) == 3
    msgs = " | ".join(f.message for f in found)
    assert "WEAK-typed" in msgs and "re-enters" in msgs


# -- memo-key ----------------------------------------------------------------

def test_memo_local_alias_store_attaches_to_declared_cache():
    """`cache = self._cache; cache[key] = fn` — the idiomatic local
    alias must attach to the declaration (no never-stores drift, no
    undeclared-alias finding), and its factory check still works."""
    res = lint(mod("""
        import jax

        GRAFTLINT_MEMO = {"Engine._cache": ("size",)}

        class Engine:
            def __init__(self, size):
                self._cache = {}
                self.size = size

            def program_for(self):
                cache = self._cache
                key = (self.size,)
                fn = cache.get(key)
                if fn is None:
                    fn = jax.jit(lambda x: x * self.size)
                    cache[key] = fn
                return fn
    """), [MemoKeyRule()])
    assert by_rule(res, "memo-key") == []


def test_memo_undeclared_program_cache_is_a_finding():
    res = lint(mod("""
        import jax

        _CACHE = {}

        def program_for(key):
            fn = _CACHE.get(key)
            if fn is None:
                fn = jax.jit(lambda x: x)
                _CACHE[key] = fn
            return fn
    """), [MemoKeyRule()])
    found = by_rule(res, "memo-key")
    assert len(found) == 1 and "no GRAFTLINT_MEMO entry" in found[0].message


def test_memo_declared_cache_with_complete_key_passes():
    res = lint(mod("""
        import jax

        _CACHE = {}
        GRAFTLINT_MEMO = {"_CACHE": ("key", "lr")}

        def program_for(key, lr):
            fn = _CACHE.get((key, lr))
            if fn is None:
                fn = jax.jit(lambda w: w * lr)
                _CACHE[(key, lr)] = fn
            return fn
    """), [MemoKeyRule()])
    assert by_rule(res, "memo-key") == []


def test_memo_declaration_drift_both_directions():
    res = lint(mod("""
        import jax

        _CACHE = {}
        GRAFTLINT_MEMO = {"_CACHE": ("key", "ghost")}

        def program_for(key, flavor):
            fn = jax.jit(lambda x: x + len(flavor))
            _CACHE[(key, flavor)] = fn
            return fn
    """), [MemoKeyRule()])
    found = by_rule(res, "memo-key")
    msgs = " | ".join(f.message for f in found)
    assert "'ghost'" in msgs and "no store site's key derives" in msgs
    assert "'flavor'" in msgs and "does not list it" in msgs


def test_memo_factory_read_outside_key_is_a_finding():
    """THE incomplete-memo-key bug: the stored program bakes in ``lr``
    but the key does not carry it — two configs share one program."""
    res = lint(mod("""
        import jax

        _CACHE = {}
        GRAFTLINT_MEMO = {"_CACHE": ("k",)}

        def program_for(k, lr):
            fn = jax.jit(lambda w: w * lr)
            _CACHE[k] = fn
            return fn
    """), [MemoKeyRule()])
    found = by_rule(res, "memo-key")
    assert any("`lr`" in f.message and "key does not include it"
               in f.message for f in found)


def test_memo_missing_cache_and_malformed_declaration():
    res = lint(mod("""
        GRAFTLINT_MEMO = {"_GONE": ("key",)}
    """), [MemoKeyRule()])
    found = by_rule(res, "memo-key")
    assert len(found) == 1 and "no such name" in found[0].message

    res = lint(mod("""
        GRAFTLINT_MEMO = {"_C": "not-a-tuple"}
        _C = {}
    """), [MemoKeyRule()])
    found = by_rule(res, "memo-key")
    assert len(found) == 1 and "literal" in found[0].message


# -- call-graph upgrades (lock + donation) -----------------------------------

def test_lock_private_helper_proven_by_locked_call_sites():
    """The _swap pattern: every in-class call site of the private helper
    holds the lock, so its unlocked accesses pass without suppression."""
    res = lint(mod("""
        import threading

        GRAFTLINT_LOCKS = {"R": {"_model": "_lock"}}

        class R:
            def __init__(self):
                self._lock = threading.Lock()
                self._model = None

            def _swap(self, m):
                self._model = m

            def reload(self, m):
                with self._lock:
                    self._swap(m)

            def rollback(self, m):
                with self._lock:
                    self._swap(m)
    """), [LockDisciplineRule()])
    assert by_rule(res, "lock-discipline") == []


def test_lock_one_unlocked_call_site_voids_the_proof():
    res = lint(mod("""
        import threading

        GRAFTLINT_LOCKS = {"R": {"_model": "_lock"}}

        class R:
            def __init__(self):
                self._lock = threading.Lock()
                self._model = None

            def _swap(self, m):
                self._model = m

            def reload(self, m):
                with self._lock:
                    self._swap(m)

            def sloppy(self, m):
                self._swap(m)
    """), [LockDisciplineRule()])
    found = by_rule(res, "lock-discipline")
    assert len(found) == 1 and "_model" in found[0].message


def test_donation_forwarder_one_call_level():
    """helper() forwards its param into a donated position, so calling
    helper(G) donates G — a later read of G is a finding."""
    res = lint(mod("""
        import jax
        from functools import partial

        @partial(jax.jit, donate_argnums=(0,))
        def acc(G, Gi):
            return G + Gi

        def helper(G, Gi):
            return acc(G, Gi)

        def use(G, Gi):
            out = helper(G, Gi)
            tail = G.sum()
            return out, tail
    """), [DonationSafetyRule()])
    found = by_rule(res, "donation-safety")
    assert len(found) == 1 and "helper" in found[0].message


def test_donation_forwarder_voided_by_param_rebind():
    res = lint(mod("""
        import jax
        from functools import partial

        @partial(jax.jit, donate_argnums=(0,))
        def acc(G, Gi):
            return G + Gi

        def safe_helper(G, Gi):
            G = G + 0  # a fresh buffer is donated, not the caller's
            return acc(G, Gi)

        def use(G, Gi):
            out = safe_helper(G, Gi)
            tail = G.sum()
            return out, tail
    """), [DonationSafetyRule()])
    assert by_rule(res, "donation-safety") == []


# -- stale suppressions ------------------------------------------------------

def test_stale_suppression_is_a_finding():
    res = lint(mod("""
        import jax.numpy as jnp

        def clean(x):
            return x + 1  # graftlint: disable=shape-trap -- historical
    """), [ShapeTrapRule()])
    found = by_rule(res, "stale-suppression")
    assert len(found) == 1 and "no longer fires" in found[0].message


def test_live_suppression_is_not_stale():
    res = lint(mod("""
        import jax.numpy as jnp

        def host_assemble(X, tail):
            return jnp.pad(X, ((0, tail), (0, 0)))  # graftlint: disable=shape-trap -- fixture: intentionally eager
    """), [ShapeTrapRule()])
    assert by_rule(res, "stale-suppression") == []
    assert by_rule(res, "shape-trap") == []
    assert res.suppressed == 1


def test_stale_not_reported_for_rules_that_did_not_run():
    """Staleness is only provable when the rule had its chance to fire:
    a host-sync suppression is NOT stale under a shape-trap-only run."""
    res = lint(mod("""
        def clean(x):
            return x + 1  # graftlint: disable=host-sync -- not checked this run
    """), [ShapeTrapRule()])
    assert by_rule(res, "stale-suppression") == []


def test_stale_all_wildcard_needs_every_rule_to_have_run():
    """A `disable=all` wildcard is only provably stale when EVERY known
    rule had its chance to fire: under a shape-trap-only run the
    host-sync finding it eats never existed, so the wildcard must not
    be reported stale — but under the full default rule set a clean
    line's wildcard is."""
    from tpu_sgd.analysis.core import default_rules
    src = """
        import jax

        step = jax.jit(lambda w: w)

        def drive(w, n):
            for _ in range(n):
                w = step(w)
                probe = w.item()  # graftlint: disable=all -- intentional probe
            return probe
    """
    res = lint(mod(src), [ShapeTrapRule()])
    assert by_rule(res, "stale-suppression") == []

    clean = """
        def clean(x):
            return x + 1  # graftlint: disable=all -- nothing here
    """
    res = lint(mod(clean), default_rules())
    found = by_rule(res, "stale-suppression")
    assert len(found) == 1 and "'all'" in found[0].message
    res = lint(mod(clean), [ShapeTrapRule()])
    assert by_rule(res, "stale-suppression") == []


# -- real-module mutation checks (graftlint v2) ------------------------------

def test_mutation_deleted_memo_key_field_fails_lint():
    """Delete the 'X' key field from streamed.py's _RESIDENT_LOOPS
    declaration: the memo-key drift check must catch it."""
    intact = _real_module("tpu_sgd/optimize/streamed.py")
    res = lint([intact], [MemoKeyRule()])
    assert by_rule(res, "memo-key") == []

    mutated = _real_module(
        "tpu_sgd/optimize/streamed.py",
        lambda s: s.replace('"wire_compress", "X"),',
                            '"wire_compress"),'))
    res = lint([mutated], [MemoKeyRule()])
    found = by_rule(res, "memo-key")
    assert any("'X'" in f.message and "does not list it" in f.message
               for f in found)


def test_mutation_item_in_resident_loop_body_fails_lint():
    """Insert a ``.item()`` on the step's result inside the observed
    streamed K=1 loop (just before its contractual barrier — the one
    spot the PR 10 observe_step extraction left in the loop body): the
    host-sync rule must catch the new per-iteration sync."""
    gd = _real_module("tpu_sgd/optimize/gradient_descent.py")
    intact = _real_module("tpu_sgd/optimize/streamed.py")
    res = lint([intact, gd], [HostSyncRule()])
    assert by_rule(res, "host-sync") == []

    barrier = (
        "                # graftlint: disable=host-sync -- observed "
        "driver: one barrier per step precedes the scalar reads below\n"
        "                new_w = jax.block_until_ready(new_w)")
    assert barrier in intact.source  # anchor must track the real loop
    mutated = _real_module(
        "tpu_sgd/optimize/streamed.py",
        lambda s: s.replace(
            barrier,
            "                probe = new_w.item()\n" + barrier, 1))
    res = lint([mutated, gd], [HostSyncRule()])
    found = by_rule(res, "host-sync")
    assert any(".item()" in f.message for f in found)


def test_mutation_unguarded_resident_callback_fails_lint():
    """Make the real `on_window` handler re-raise (breaking the
    stash-flag-reraise contract): callback-discipline must flag the
    io_callback site — proof the attribute-hop target resolution
    actually attaches the contract to the resident driver."""
    intact = _real_module("tpu_sgd/optimize/resident_driver.py")
    res = lint([intact], [CallbackDisciplineRule()])
    assert by_rule(res, "callback-discipline") == []

    mutated = _real_module(
        "tpu_sgd/optimize/resident_driver.py",
        lambda s: s.replace(
            "self.error = e\n            return np.bool_(True)",
            "self.error = e\n            raise"))
    res = lint([mutated], [CallbackDisciplineRule()])
    found = by_rule(res, "callback-discipline")
    # two io_callback sites share the handler since the extras-carry
    # variant landed (legacy ring and EF-carry ring) — both must flag
    assert len(found) == 2
    assert all("on_window" in f.message for f in found)
    assert all("exception cross the FFI boundary" in f.message
               for f in found)


# -- runtime twins: host-sync + callback buffers -----------------------------

def test_count_host_syncs_counts_coercions_not_cache_hits():
    import jax
    import jax.numpy as jnp

    from tpu_sgd.analysis.runtime import count_host_syncs

    f = jax.jit(lambda x: x * 2)
    a = f(jnp.arange(8.0))
    jax.block_until_ready(a)
    with count_host_syncs() as c:
        float(a[0])          # scalar coercion: one transfer
        a.__array__()        # materializes (and caches) the array
        a.__array__()        # cached: free
        jax.block_until_ready(a)  # barrier, never a transfer
    assert c["n"] == 2
    assert all(isinstance(s, tuple) for s, _ in c["shapes"])


def test_assert_no_host_sync_raises_and_allows():
    import jax
    import jax.numpy as jnp

    from tpu_sgd.analysis.runtime import (HostSyncError,
                                          assert_no_host_sync)

    f = jax.jit(lambda x: x + 1)
    a = f(jnp.arange(4.0))
    jax.block_until_ready(a)
    with pytest.raises(HostSyncError) as ei:
        with assert_no_host_sync():
            a.item(0)
    assert "device->host transfer" in str(ei.value)

    b = f(jnp.arange(4.0))
    with assert_no_host_sync(allow=1):
        float(b[1])

    # call-through form: dispatching is not syncing
    out = assert_no_host_sync(lambda: f(jnp.arange(4.0)))
    assert out.shape == (4,)


def test_assert_bounded_callback_buffer():
    import numpy as np

    from tpu_sgd.analysis.runtime import (CallbackBufferError,
                                          assert_bounded_callback_buffer)

    grows = []
    with pytest.raises(CallbackBufferError):
        with assert_bounded_callback_buffer(grows):
            grows.append(1)

    ring = np.zeros(16)
    with assert_bounded_callback_buffer(lambda: ring):
        ring[3] = 1.0  # overwrite in place: bounded

    capped = [1, 2]
    with assert_bounded_callback_buffer(capped, max_len=4):
        capped.append(3)


# -- lock-order (fixtures) ---------------------------------------------------

def test_lock_order_cycle_is_a_deadlock_finding():
    """Opposite nestings of two declared locks form a cycle: a deadlock
    finding even with no GRAFTLINT_LOCK_ORDER declared anywhere."""
    res = lint(mod("""
        import threading

        GRAFTLINT_LOCKS = {"C": {"_xa": "_l1", "_xb": "_l2"}}

        class C:
            def ab(self):
                with self._l1:
                    with self._l2:
                        pass

            def ba(self):
                with self._l2:
                    with self._l1:
                        pass
    """), [LockOrderRule()])
    found = by_rule(res, "lock-order")
    assert len(found) == 1
    assert "CYCLE" in found[0].message and "deadlock" in found[0].message
    assert "C._l1" in found[0].message and "C._l2" in found[0].message


def test_lock_order_without_declaration_checks_cycles_only():
    res = lint(mod("""
        import threading

        GRAFTLINT_LOCKS = {"C": {"_xa": "_l1", "_xb": "_l2"}}

        class C:
            def ab(self):
                with self._l1:
                    with self._l2:
                        pass
    """), [LockOrderRule()])
    assert by_rule(res, "lock-order") == []


def test_lock_order_inverted_edge_names_both_paths():
    """An acquisition path that inverts a declared pair fails lint, and
    the finding carries the full call-resolved path (the nesting goes
    through a typed ``self._a`` receiver, not a lexical ``with``)."""
    res = lint(mod("""
        import threading

        GRAFTLINT_LOCKS = {
            "A": {"_xa": "_la"},
            "B": {"_xb": "_lb"},
        }

        GRAFTLINT_LOCK_ORDER = (("A._la", "B._lb"),)

        class A:
            def hold(self):
                with self._la:
                    pass

        class B:
            def __init__(self, a):
                self._a: "A" = a

            def inverted(self):
                with self._lb:
                    self._a.hold()
    """), [LockOrderRule()])
    found = by_rule(res, "lock-order")
    inv = [f for f in found if "INVERTS the declared order" in f.message]
    assert len(inv) == 1
    msg = inv[0].message
    assert "lock nesting B._lb -> A._la" in msg
    assert "B.inverted" in msg and "A.hold" in msg  # the proving path
    assert "declared-direction path" in msg


def test_lock_order_undeclared_edge_and_stale_pair_both_fail():
    """Drift fails in both directions: a discovered nesting missing
    from the declaration, and a declared pair the graph cannot find."""
    res = lint(mod("""
        import threading

        GRAFTLINT_LOCKS = {
            "C": {"_xa": "_l1", "_xb": "_l2", "_xc": "_l3"},
        }

        GRAFTLINT_LOCK_ORDER = (("C._l1", "C._l3"),)

        class C:
            def ab(self):
                with self._l1:
                    with self._l2:
                        pass
    """), [LockOrderRule()])
    found = by_rule(res, "lock-order")
    assert any("is not in GRAFTLINT_LOCK_ORDER" in f.message
               and '("C._l1", "C._l2")' in f.message for f in found)
    assert any("matches no nesting" in f.message
               and "C._l1 -> C._l3" in f.message for f in found)
    assert len(found) == 2


def test_lock_order_declaration_matching_graph_is_clean():
    res = lint(mod("""
        import threading

        GRAFTLINT_LOCKS = {"C": {"_xa": "_l1", "_xb": "_l2"}}

        GRAFTLINT_LOCK_ORDER = (("C._l1", "C._l2"),)

        class C:
            def ab(self):
                with self._l1:
                    with self._l2:
                        pass
    """), [LockOrderRule()])
    assert by_rule(res, "lock-order") == []


def test_lock_order_rejects_malformed_declaration():
    res = lint(mod("""
        GRAFTLINT_LOCK_ORDER = ("oops",)
    """), [LockOrderRule()])
    found = by_rule(res, "lock-order")
    assert len(found) == 1
    assert "literal sequence" in found[0].message


def test_committed_lock_order_is_acyclic_and_covers_the_repo():
    """The committed declaration itself: acyclic (a cyclic declaration
    would sanction a deadlock), and exactly the graph — which the
    repo-clean sweep enforces; here we pin the structural property."""
    adj = {}
    for a, b in GRAFTLINT_LOCK_ORDER:
        adj.setdefault(a, set()).add(b)
    seen, done = set(), set()

    def dfs(u):
        seen.add(u)
        for v in adj.get(u, ()):
            assert v not in seen or v in done, (
                f"committed GRAFTLINT_LOCK_ORDER has a cycle through {v}")
            if v not in done:
                dfs(v)
        done.add(u)

    for node in list(adj):
        if node not in done:
            dfs(node)
    # every node is a Class.lockattr pair
    for a, b in GRAFTLINT_LOCK_ORDER:
        assert "." in a and "." in b


# -- cond-discipline (fixtures) ----------------------------------------------

def test_cond_wait_not_in_while_fires_wait_for_and_while_exempt():
    res = lint(mod("""
        import threading

        GRAFTLINT_LOCKS = {"C": {"_q": "_cv"}}

        class C:
            def bad(self):
                with self._cv:
                    if not self._q:
                        self._cv.wait()

            def good(self):
                with self._cv:
                    while not self._q:
                        self._cv.wait()

            def also_good(self):
                with self._cv:
                    self._cv.wait_for(lambda: self._q, timeout=1.0)
    """), [CondDisciplineRule()])
    found = by_rule(res, "cond-discipline")
    assert len(found) == 1
    assert "not re-checked in a `while`" in found[0].message


def test_cond_notify_outside_lock_fires_helper_proof_holds():
    res = lint(mod("""
        import threading

        GRAFTLINT_LOCKS = {"C": {"_q": "_cv"}}

        class C:
            def bad(self):
                self._cv.notify_all()

            def good(self):
                with self._cv:
                    self._cv.notify()

            def _helper(self):
                self._cv.notify_all()  # every caller holds the cv

            def caller(self):
                with self._cv:
                    self._helper()
    """), [CondDisciplineRule()])
    found = by_rule(res, "cond-discipline")
    assert len(found) == 1
    assert "notify without the owning lock" in found[0].message
    assert found[0].line is not None


def test_cond_untimed_wait_on_stop_path_fires_stop_flag_exempts():
    res = lint(mod("""
        import threading

        GRAFTLINT_LOCKS = {"Bad": {"_q": "_cv"}, "Good": {"_q": "_cv"}}

        class Bad:
            def close(self):
                self.drain()

            def drain(self):
                with self._cv:
                    while not self._done:
                        self._cv.wait()

        class Good:
            def close(self):
                with self._cv:
                    self._stopped = True
                    self._cv.notify_all()
                self.drain()

            def drain(self):
                with self._cv:
                    while not self._done and not self._stopped:
                        self._cv.wait()
    """), [CondDisciplineRule()])
    found = by_rule(res, "cond-discipline")
    assert len(found) == 1
    assert "reachable from Bad.close()" in found[0].message
    assert "hang" in found[0].message


def test_cond_unjoined_daemon_thread_fires_join_anywhere_exempts():
    res = lint(mod("""
        import threading

        class Leaky:
            def start(self):
                threading.Thread(target=self._run, daemon=True).start()

        class Owned:
            def start(self):
                self._t = threading.Thread(target=self._run, daemon=True)
                self._t.start()

            def stop(self):
                self._t.join()
    """), [CondDisciplineRule()])
    found = by_rule(res, "cond-discipline")
    assert len(found) == 1
    assert "Leaky" in found[0].message
    assert "daemon is a backstop" in found[0].message


def test_cond_unobserved_future_exception_cross_module():
    setter = mod("""
        def fail(fut, e):
            fut.set_exception(e)
    """, relpath="setter.py")
    res = lint([setter], [CondDisciplineRule()])
    found = by_rule(res, "cond-discipline")
    assert len(found) == 1
    assert ".result()/.exception()" in found[0].message

    observer = mod("""
        def harvest(fut):
            return fut.result(timeout=1.0)
    """, relpath="observer.py")
    res = lint([setter, observer], [CondDisciplineRule()])
    assert by_rule(res, "cond-discipline") == []


# -- contract-drift (fixtures) -----------------------------------------------

def test_contract_slo_counter_and_rule_typos_fire():
    """The deliberate-rename fixture: one resolving SLO entry, one
    counter typo, one unknown detector rule — only the renames fail."""
    res = lint(mod("""
        from tpu_sgd.obs.counters import inc

        def emit():
            inc("scenario.answered")

        class D:
            rule = "shed-rate"

        SLOS = [
            {"metric": "counter", "name": "ok",
             "counter": "scenario.answered", "max": 1},
            {"metric": "counter", "name": "typo",
             "counter": "scenario.answred", "max": 1},
            {"metric": "detector", "name": "r", "rule": "no-such-rule"},
        ]
    """), [ContractDriftRule()])
    found = by_rule(res, "contract-drift")
    assert len(found) == 2
    assert any("'scenario.answred'" in f.message
               and "0 of nothing passes" in f.message for f in found)
    assert any("'no-such-rule'" in f.message for f in found)


def test_contract_detector_default_series_must_resolve():
    res = lint(mod("""
        from tpu_sgd.obs.spans import event

        def emit():
            event("train.tick", n=1)

        class Silent:
            rule = "silent"

            def __init__(self, series="train.renamed"):
                self.series = series

        class Wired:
            rule = "wired"

            def __init__(self, series="train.tick", prefix="train."):
                self.series = series
                self.prefix = prefix
    """), [ContractDriftRule()])
    found = by_rule(res, "contract-drift")
    assert len(found) == 1
    assert "series='train.renamed'" in found[0].message
    assert "permanently silent" in found[0].message


def test_contract_fanout_tables_and_tagged_emits_resolve():
    """EVENT_FANOUT keys emit ``name[actor]`` (+ the ``.error[`` twin),
    and ``inc(_tagged("x"))`` emits the ``.x`` suffix under any
    subsystem — consumers over those shapes resolve."""
    res = lint(mod("""
        from tpu_sgd.obs.counters import inc, _tagged

        EVENT_FANOUT = {"tenant.swap": ("tenant", None)}

        def emit():
            inc(_tagged("dispatch"))

        class D:
            rule = "fanout"

            def __init__(self, prefix="tenant.swap[",
                         series="train.dispatch"):
                self.prefix = prefix
                self.series = series
    """), [ContractDriftRule()])
    assert by_rule(res, "contract-drift") == []


def test_contract_gate_paths_validate_against_committed_baselines():
    """Gate JSON paths resolve against the real BENCH_*.json files at
    the project root: a dangling segment and a missing baseline each
    fail; the intact path is silent."""
    res = lint(mod("""
        GATES = {
            "BENCH_OBS.json": [
                Gate("headline/superstep_count_deltas", "lower"),
                Gate("headline/superstep_count_deltas/no_such_key",
                     "lower"),
            ],
            "BENCH_MISSING.json": [Gate("x", "lower")],
        }
    """), [ContractDriftRule()], root=REPO)
    found = by_rule(res, "contract-drift")
    assert len(found) == 2
    assert any("dangles" in f.message
               and "'no_such_key'" in f.message for f in found)
    assert any("missing or unreadable" in f.message
               and "BENCH_MISSING.json" in f.message for f in found)


# -- mutation: inverted acquisition in the real replica store ----------------

_STORE_REL = "tpu_sgd/replica/store.py"
_PULL_ANCHOR = '    def pull(self, worker_id: str = "") -> PulledState:'
_INVERSION = (
    "    def _mutant_hold_and_poke(self, sup):\n"
    '        self._mutant_sup: "StoreSupervisor" = sup\n'
    "        with self._cond:\n"
    "            return self._mutant_sup.primary()\n\n"
)


def test_mutation_inverted_acquisition_fails_lock_order_lint():
    """Seed a method into the real ParameterStore that acquires the
    supervisor's lock while holding the store condition — the inverse
    of the committed (StoreSupervisor._lock, ParameterStore._cond)
    pair.  The lock-order rule must name the inversion AND the cycle it
    forms with the declared-direction path."""
    cfg = load_config(REPO)
    mods = load_modules(cfg, None)
    mutated = _real_module(
        _STORE_REL,
        lambda s: s.replace(_PULL_ANCHOR, _INVERSION + _PULL_ANCHOR, 1))
    mods = [mutated if m.relpath == _STORE_REL else m for m in mods]
    res = run_lint(config=cfg, rules=[LockOrderRule()], modules=mods)
    found = by_rule(res, "lock-order")
    inv = [f for f in found if "INVERTS the declared order" in f.message]
    assert len(inv) == 1, found
    msg = inv[0].message
    assert "ParameterStore._cond -> StoreSupervisor._lock" in msg
    assert "_mutant_hold_and_poke" in msg  # this path
    assert "declared-direction path" in msg
    # both directions now discovered: the deadlock cycle is named too
    assert any("CYCLE" in f.message for f in found)


def test_mutation_inverted_acquisition_fails_runtime_replay():
    """The runtime twin of the same mutation: the declared-direction
    acquisition passes replay, the inverted one raises."""
    rec = LocksetRecorder()
    sup_lk = InstrumentedLock(threading.Lock(),
                              name="StoreSupervisor._lock", recorder=rec)
    store_cv = InstrumentedLock(threading.Condition(),
                                name="ParameterStore._cond", recorder=rec)
    with sup_lk:
        with store_cv:
            pass
    assert_lock_order(rec)  # declared direction: clean

    rec2 = LocksetRecorder()
    sup_lk2 = InstrumentedLock(threading.Lock(),
                               name="StoreSupervisor._lock", recorder=rec2)
    store_cv2 = InstrumentedLock(threading.Condition(),
                                 name="ParameterStore._cond", recorder=rec2)
    with store_cv2:
        with sup_lk2:
            pass
    with pytest.raises(LockOrderError, match="INVERTS the committed"):
        assert_lock_order(rec2)


def test_lock_order_replay_uses_transitive_closure():
    """A -> B -> C declared; observing C-then-A is an inversion even
    though no single declared pair relates them directly."""
    rec = LocksetRecorder()
    a = InstrumentedLock(threading.Lock(), name="A.l", recorder=rec)
    c = InstrumentedLock(threading.Lock(), name="C.l", recorder=rec)
    with c:
        with a:
            pass
    order = (("A.l", "B.l"), ("B.l", "C.l"))
    with pytest.raises(LockOrderError):
        assert_lock_order(rec, order=order)
    # unrelated pairs pass: the declaration does not order D against A
    rec2 = LocksetRecorder()
    d = InstrumentedLock(threading.Lock(), name="D.l", recorder=rec2)
    a2 = InstrumentedLock(threading.Lock(), name="A.l", recorder=rec2)
    with d:
        with a2:
            pass
    assert_lock_order(rec2, order=order)


# -- mutation: unlocked write into the real WeightSlab -----------------------

def test_mutation_unlocked_slab_access_fails_lint():
    intact = _real_module("tpu_sgd/tenant/slab.py")
    res = lint([intact], [LockDisciplineRule()])
    assert by_rule(res, "lock-discipline") == []

    mutated = _real_module(
        "tpu_sgd/tenant/slab.py",
        lambda s: s.replace("with self._lock:", "if True:", 1))
    res = lint([mutated], [LockDisciplineRule()])
    found = by_rule(res, "lock-discipline")
    assert len(found) >= 1
    assert all("outside `with self._lock:`" in f.message for f in found)


def test_mutation_unlocked_slab_write_flagged_by_eraser():
    """The runtime twin: a live two-thread workload where one thread
    writes a guarded slab attribute without the lock — the Eraser
    lockset intersection must produce a race report naming both
    threads' sites."""
    from tpu_sgd.tenant.slab import GRAFTLINT_LOCKS as SLAB_LOCKS
    from tpu_sgd.tenant.slab import WeightSlab

    slab = WeightSlab(4, 3)
    rec = instrument_object(slab, SLAB_LOCKS["WeightSlab"])

    def locked_writer():
        with slab._lock:
            slab._published_at = dict(slab._published_at)

    def unlocked_writer():  # the seeded race
        slab._published_at = {}

    t1 = threading.Thread(target=locked_writer, name="locked")
    t1.start(); t1.join()
    t2 = threading.Thread(target=unlocked_writer, name="racy")
    t2.start(); t2.join()

    races = rec.races()
    hit = [r for r in races
           if r.cls_name == "WeightSlab" and r.attr == "_published_at"]
    assert len(hit) == 1, races
    assert {"locked", "racy"} <= hit[0].threads
    assert any(op == "write" for _, op, _, _ in hit[0].sites)


def test_eraser_clean_on_consistently_locked_slab_workload():
    """Contrast case: the same slab driven correctly from two threads —
    every access under the lock — reports no races and no violations,
    and the observed acquisition order replays clean."""
    from tpu_sgd.tenant.slab import GRAFTLINT_LOCKS as SLAB_LOCKS
    from tpu_sgd.tenant.slab import WeightSlab

    slab = WeightSlab(4, 3)
    rec = instrument_object(slab, SLAB_LOCKS["WeightSlab"])

    def worker(base):
        for i in range(8):
            slab.put(base + i % 3, np.ones(3, np.float32), 0.5, version=i)
            slab.version_of(base + i % 3)

    threads = [threading.Thread(target=worker, args=(b,), name=f"w{b}")
               for b in (0, 100)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert rec.violations == []
    assert rec.races() == []
    assert_lock_order(rec)
    assert rec.checked_accesses > 20


# -- runtime: the fixed racing schedules, pinned -----------------------------

def test_flightrec_concurrent_triggers_rate_limited_once(tmp_path):
    """The flightrec fix pinned: the min-interval check and the clock
    update are one atomic region, so N concurrent debounced triggers
    produce exactly ONE dump — and the instrumented run shows every
    ``_last_dump_t`` access under the declared lock."""
    from tpu_sgd.obs.flightrec import FlightRecorder, GRAFTLINT_LOCKS

    fr = FlightRecorder(str(tmp_path / "fr.jsonl"), capacity=8)
    fr.record("probe", {"i": 0})
    rec = instrument_object(fr, GRAFTLINT_LOCKS["FlightRecorder"])

    results = []
    threads = [threading.Thread(
        target=lambda: results.append(
            fr.trigger("race", min_interval_s=60.0)))
        for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert fr.dumps == 1  # one winner; the rest saw the fresh clock
    assert sum(r is not None for r in results) == 1
    assert rec.violations == []
    assert rec.races() == []


def test_batcher_concurrent_start_spawns_one_worker_and_restarts():
    """The batcher start/stop fix pinned: racing ``start()`` calls
    create exactly one worker thread, ``stop()`` resets the handle
    under the condition so a later ``start()`` really restarts."""
    from tpu_sgd.serve.batcher import MicroBatcher

    b = MicroBatcher(lambda X: np.asarray(X).sum(axis=1),
                     max_batch=4, max_latency_s=0.002)
    starters = [threading.Thread(target=b.start) for _ in range(4)]
    for t in starters:
        t.start()
    for t in starters:
        t.join()
    workers = [t for t in threading.enumerate()
               if t.name == "tpu-sgd-serve-batcher"]
    assert len(workers) == 1

    futs = [b.submit(np.ones(3, np.float32)) for _ in range(5)]
    assert [float(f.result(timeout=10)) for f in futs] == [3.0] * 5
    b.stop()
    assert not workers[0].is_alive()

    b.start()  # the reset handle admits a true restart
    assert float(b.submit(np.ones(3, np.float32)).result(timeout=10)) == 3.0
    b.stop()


def test_batcher_burst_eraser_clean_and_counters_consistent():
    """The batcher burst path under full instrumentation: no lockset
    violations, no Eraser races (the sanctioned racy reader
    ``queue_depth`` is simply not exercised), the acquisition order
    replays against the committed declaration, and the counter pair
    moved under ``_cond`` adds up."""
    from tpu_sgd.serve.batcher import GRAFTLINT_LOCKS, MicroBatcher

    b = MicroBatcher(lambda X: np.asarray(X).sum(axis=1),
                     max_batch=4, max_latency_s=0.002)
    rec = instrument_object(b, GRAFTLINT_LOCKS["MicroBatcher"])
    with b:
        futs = []
        for wave in range(3):
            futs += [b.submit(np.ones(3, np.float32)) for _ in range(8)]
            got = [f.result(timeout=10) for f in futs[-8:]]
            assert [float(g) for g in got] == [3.0] * 8
    allowed = {"_flush"}  # the metrics sample reads qd outside the cv
    assert rec.violating_functions() <= allowed, rec.violations
    assert rec.races() == []
    assert_lock_order(rec)
    with b._cond:
        assert b.batch_count >= 6  # 24 requests / max_batch 4
        assert b.reject_count == 0
