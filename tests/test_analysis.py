"""graftlint (tpu_sgd/analysis): rule fixtures, suppressions, mutation
checks against the REAL modules, and the runtime validators.

The mutation tests are the load-bearing half: they take the actual
source of ``io/prefetch.py`` / ``serve/batcher.py``, delete the exact
thing each rule exists to protect (a ``failpoint(...)`` hook, a
``with self._cond:``), and assert lint catches the seeded violation —
proof the rules guard the real code, not just synthetic fixtures."""

import os
import textwrap
import threading
import time

import numpy as np
import pytest

from tpu_sgd.analysis.core import (Config, Finding, KNOWN_RULES, ModuleFile,
                                   run_lint)
from tpu_sgd.analysis.rules_callback import CallbackDisciplineRule
from tpu_sgd.analysis.rules_carry import CarryStabilityRule
from tpu_sgd.analysis.rules_donation import DonationSafetyRule
from tpu_sgd.analysis.rules_failpoint import FailpointCoverageRule
from tpu_sgd.analysis.rules_lock import LockDisciplineRule
from tpu_sgd.analysis.rules_memo import MemoKeyRule
from tpu_sgd.analysis.rules_shape import EagerInLoopRule, ShapeTrapRule
from tpu_sgd.analysis.rules_sync import HostSyncRule, ObsDisciplineRule
from tpu_sgd.analysis.runtime import (CompileCountError, InstrumentedLock,
                                      LocksetRecorder, assert_compile_count,
                                      instrument_object)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

def mod(src: str, relpath: str = "fixture_mod.py") -> ModuleFile:
    return ModuleFile("/fixtures/" + relpath, relpath,
                      textwrap.dedent(src))


def lint(modules, rules, **cfg):
    cfg.setdefault("root", "/fixtures")
    if isinstance(modules, ModuleFile):
        modules = [modules]
    return run_lint(config=Config(**cfg), rules=rules, modules=modules)


def by_rule(result, rule: str):
    return [f for f in result.findings if f.rule == rule]


# -- shape-trap -------------------------------------------------------------

def test_shape_trap_fires_on_eager_pad_and_concatenate():
    res = lint(mod("""
        import jax.numpy as jnp

        def host_assemble(X, tail):
            Xp = jnp.pad(X, ((0, tail), (0, 0)))
            return jnp.concatenate([Xp, Xp])
    """), [ShapeTrapRule()])
    found = by_rule(res, "shape-trap")
    assert len(found) == 2
    assert "per input shape" in found[0].message


def test_shape_trap_fires_on_dynamic_slice_of_device_array():
    res = lint(mod("""
        import jax.numpy as jnp

        def score(X, w, n):
            out = jnp.matmul(X, w)
            return out[:n]
    """), [ShapeTrapRule()])
    assert len(by_rule(res, "shape-trap")) == 1


def test_shape_trap_silent_inside_jit_and_on_numpy():
    res = lint(mod("""
        import functools
        import jax
        import jax.numpy as jnp
        import numpy as np

        @jax.jit
        def traced_pad(X):
            return jnp.pad(X, ((0, 1), (0, 0)))

        @functools.partial(jax.jit, static_argnums=(1,))
        def traced_cat(X, k):
            def inner(A):
                return jnp.concatenate([A, A])
            return inner(X)[:k]

        def wrapped(X):
            return jnp.concatenate([X, X])

        apply_wrapped = jax.vmap(wrapped)

        def host_numpy(X, n):
            Xp = np.pad(X, ((0, 3), (0, 0)))
            return np.concatenate([Xp, Xp])[:n]

        def lax_map_body(X, B):
            def one(k):
                return jnp.concatenate([X, X])
            return jax.lax.map(one, jnp.arange(4))
    """), [ShapeTrapRule()])
    assert by_rule(res, "shape-trap") == []


def test_shape_trap_silent_on_helper_called_from_traced_fn():
    res = lint(mod("""
        import jax
        import jax.numpy as jnp

        def helper(X):
            return jnp.concatenate([X, X])

        @jax.jit
        def body(X):
            return helper(X)
    """), [ShapeTrapRule()])
    assert by_rule(res, "shape-trap") == []


def test_shape_trap_ignores_lax_dynamic_slice():
    # lax.dynamic_slice* has STATIC sizes: eager use compiles once per
    # input shape — it is the shape-stable idiom, not the trap
    res = lint(mod("""
        import jax
        import jax.numpy as jnp

        def window(X, k, B):
            return jax.lax.dynamic_slice_in_dim(X, k * B, B, 0)
    """), [ShapeTrapRule()])
    assert by_rule(res, "shape-trap") == []


# -- eager-in-loop ----------------------------------------------------------

def test_eager_in_loop_fires_on_jit_constructed_per_iteration():
    res = lint(mod("""
        import jax
        from functools import partial

        def run(fs, X):
            outs = []
            for f in fs:
                outs.append(jax.jit(f)(X))
            while X.sum() < 0:
                g = partial(jax.jit, donate_argnums=(0,))(fs[0])
            return outs
    """), [EagerInLoopRule()])
    assert len(by_rule(res, "eager-in-loop")) == 2


def test_eager_in_loop_silent_on_hoisted_and_memoized():
    res = lint(mod("""
        import functools
        import jax

        @functools.lru_cache(maxsize=None)
        def _program(B):
            return jax.jit(lambda X: X * B)

        compiled = jax.jit(lambda X: X + 1)

        def run(chunks):
            return [_program(c.shape[0])(c) for c in chunks]

        def loop_defines_fn(chunks):
            for c in chunks:
                # the jit lives in a def only CALLED later, not built here
                def build():
                    return jax.jit(lambda X: X)
                yield build
    """), [EagerInLoopRule()])
    assert by_rule(res, "eager-in-loop") == []


# -- lock-discipline --------------------------------------------------------

LOCKED_SRC = """
    import threading

    GRAFTLINT_LOCKS = {
        "Box": {
            "_val": "_lock",
            "_ref": "_lock:w",
        },
    }

    class Box:
        def __init__(self):
            self._lock = threading.Lock()
            self._val = 0
            self._ref = None

        def good(self):
            with self._lock:
                self._val += 1
                self._ref = object()

        def read_ref(self):
            return self._ref            # :w mode: bare read sanctioned
"""


def test_lock_discipline_clean_fixture():
    res = lint(mod(LOCKED_SRC), [LockDisciplineRule()])
    assert by_rule(res, "lock-discipline") == []


def test_lock_discipline_flags_unlocked_access_and_w_mode_write():
    res = lint(mod("""
        import threading

        GRAFTLINT_LOCKS = {"Box": {"_val": "_lock", "_ref": "_lock:w"}}

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self._val = 0
                self._ref = None

            def bad_read(self):
                return self._val

            def bad_write(self):
                self._ref = object()

            def closure_leak(self):
                def worker():
                    self._val += 1
                return worker
    """), [LockDisciplineRule()])
    found = by_rule(res, "lock-discipline")
    assert len(found) == 3
    assert any("read of guarded attribute self._val" in f.message
               for f in found)
    assert any("write of guarded attribute self._ref" in f.message
               for f in found)


def test_lock_discipline_init_exempt_and_declaration_drift():
    res = lint(mod("""
        import threading

        GRAFTLINT_LOCKS = {
            "Ghost": {"_x": "_lock"},
            "Real": {"_x": "_missing_lock"},
        }

        class Real:
            def __init__(self):
                self._x = 0
    """), [LockDisciplineRule()])
    found = by_rule(res, "lock-discipline")
    msgs = " | ".join(f.message for f in found)
    assert "no such class" in msgs            # Ghost
    assert "never assigned" in msgs           # _missing_lock
    # __init__'s unguarded self._x write itself is exempt
    assert "guarded attribute" not in msgs


# -- donation-safety --------------------------------------------------------

def test_donation_safety_fires_on_read_after_donate():
    res = lint(mod("""
        import jax
        from functools import partial

        @partial(jax.jit, donate_argnums=(0,))
        def acc(G, Gi):
            return G + Gi

        def build(chunks, G0):
            G = G0
            out = acc(G, chunks[0])
            return G.sum() + out.sum()
    """), [DonationSafetyRule()])
    found = by_rule(res, "donation-safety")
    assert len(found) == 1
    assert "donated to `acc`" in found[0].message


def test_donation_safety_silent_on_rebind_idiom():
    res = lint(mod("""
        import jax
        from functools import partial

        @partial(jax.jit, donate_argnums=(0,))
        def acc(G, Gi):
            return G + Gi

        def build(chunks, G0):
            G = G0
            for c in chunks:
                G = acc(G, c)
            return G
    """), [DonationSafetyRule()])
    assert by_rule(res, "donation-safety") == []


def test_donation_safety_resolves_cross_module_imports():
    provider = mod("""
        import jax

        def _raw(G, Gi):
            return G + Gi

        acc = jax.jit(_raw, donate_argnums=(0,))
    """, relpath="provider.py")
    consumer = mod("""
        from provider import acc

        def build(G, Gi):
            out = acc(G, Gi)
            return G.sum()
    """, relpath="consumer.py")
    res = lint([provider, consumer], [DonationSafetyRule()])
    found = by_rule(res, "donation-safety")
    assert len(found) == 1
    assert found[0].path == "consumer.py"


# -- failpoint-coverage -----------------------------------------------------

def test_failpoint_coverage_both_directions():
    registry = {"io.feed": "feed.py"}
    ok = mod("""
        from tpu_sgd.reliability.failpoints import failpoint

        def produce():
            failpoint("io.feed")
    """, relpath="feed.py")
    res = lint([ok], [FailpointCoverageRule(registry=registry)])
    assert by_rule(res, "failpoint-coverage") == []

    missing = mod("""
        def produce():
            pass
    """, relpath="feed.py")
    res = lint([missing], [FailpointCoverageRule(registry=registry)])
    found = by_rule(res, "failpoint-coverage")
    assert len(found) == 1 and "deleted or never wired" in found[0].message

    unregistered = mod("""
        from tpu_sgd.reliability.failpoints import failpoint

        def produce():
            failpoint("io.feed")
            failpoint("io.rogue_site")
    """, relpath="feed.py")
    res = lint([unregistered], [FailpointCoverageRule(registry=registry)])
    found = by_rule(res, "failpoint-coverage")
    assert len(found) == 1 and "not registered" in found[0].message


def test_failpoint_coverage_points_at_moved_hook():
    registry = {"io.feed": "feed.py"}
    elsewhere = mod("""
        from tpu_sgd.reliability.failpoints import failpoint

        def produce():
            failpoint("io.feed")
    """, relpath="other.py")
    empty = mod("def produce():\n    pass\n", relpath="feed.py")
    res = lint([empty, elsewhere],
               [FailpointCoverageRule(registry=registry)])
    found = by_rule(res, "failpoint-coverage")
    assert len(found) == 1 and "other.py" in found[0].message


# -- suppressions -----------------------------------------------------------

def test_suppression_same_line_with_reason():
    res = lint(mod("""
        import jax.numpy as jnp

        def host(X):
            return jnp.concatenate([X, X])  # graftlint: disable=shape-trap -- fixture reason
    """), [ShapeTrapRule()])
    assert res.findings == [] and res.suppressed == 1


def test_suppression_standalone_line_above():
    res = lint(mod("""
        import jax.numpy as jnp

        def host(X):
            # graftlint: disable=shape-trap -- fixture reason
            return jnp.concatenate([X, X])
    """), [ShapeTrapRule()])
    assert res.findings == [] and res.suppressed == 1


def test_suppression_all_wildcard_and_wrong_rule():
    res = lint(mod("""
        import jax.numpy as jnp

        def host(X):
            # graftlint: disable=all -- fixture reason
            return jnp.concatenate([X, X])

        def host2(X):
            # graftlint: disable=lock-discipline -- wrong rule on purpose
            return jnp.concatenate([X, X])
    """), [ShapeTrapRule()])
    assert len(by_rule(res, "shape-trap")) == 1  # host2 not covered
    assert res.suppressed == 1


def test_bare_suppression_and_unknown_rule_are_findings():
    res = lint(mod("""
        import jax.numpy as jnp

        def host(X):
            # graftlint: disable=shape-trap
            return jnp.concatenate([X, X])

        def host2(X):
            # graftlint: disable=shape_trap -- underscores, not a rule id
            return jnp.concatenate([X, X])
    """), [ShapeTrapRule()])
    rules = {f.rule for f in res.findings}
    assert "bare-suppression" in rules
    assert "unknown-rule" in rules


# -- mutation checks against the REAL modules -------------------------------

def _real_module(relpath: str, transform=None) -> ModuleFile:
    with open(os.path.join(REPO, relpath), encoding="utf-8") as f:
        src = f.read()
    if transform is not None:
        mutated = transform(src)
        assert mutated != src, "mutation did not apply"
        src = mutated
    return ModuleFile("/mutated/" + relpath, relpath, src)


def test_mutation_deleted_failpoint_hook_fails_lint():
    """Delete the prefetcher's failpoint call in a copy of the real
    module: the failpoint-coverage rule must catch it."""
    registry_mod = _real_module("tpu_sgd/reliability/failpoints.py")
    intact = _real_module("tpu_sgd/io/prefetch.py")
    res = lint([registry_mod, intact], [FailpointCoverageRule()])
    baseline = by_rule(res, "failpoint-coverage")
    assert [f for f in baseline
            if "io.prefetch.produce" in f.message] == []

    mutated = _real_module(
        "tpu_sgd/io/prefetch.py",
        lambda s: s.replace('failpoint("io.prefetch.produce")', "pass"))
    res = lint([registry_mod, mutated], [FailpointCoverageRule()])
    found = by_rule(res, "failpoint-coverage")
    assert any("io.prefetch.produce" in f.message
               and "deleted or never wired" in f.message for f in found)


def test_mutation_deleted_lock_block_fails_lint():
    """Replace ``submit``'s ``with self._cond:`` with ``if True:`` in a
    copy of the real batcher: the lock-discipline rule must flag the
    now-unguarded queue accesses."""
    intact = _real_module("tpu_sgd/serve/batcher.py")
    res = lint([intact], [LockDisciplineRule()])
    assert by_rule(res, "lock-discipline") == []

    mutated = _real_module(
        "tpu_sgd/serve/batcher.py",
        lambda s: s.replace("with self._cond:", "if True:", 1))
    res = lint([mutated], [LockDisciplineRule()])
    found = by_rule(res, "lock-discipline")
    assert len(found) >= 2  # _stopped read + _pending touches in submit
    assert all("outside `with self._cond:`" in f.message for f in found)


def test_every_rule_fires_on_its_seeded_violation():
    """One seeded violation per rule, one combined sweep: each of the
    ten rules must report exactly its own planted bug."""
    registry = {"io.feed": "seeded.py"}
    seeded = mod("""
        import threading
        import jax
        import jax.numpy as jnp
        import numpy as np
        from jax import lax
        from jax.experimental import io_callback
        from functools import partial
        from tpu_sgd.obs.spans import event

        GRAFTLINT_LOCKS = {"S": {"_q": "_lock"}}

        HIST = []
        _PROGRAMS = {}

        class S:
            def __init__(self):
                self._lock = threading.Lock()
                self._q = []

            def racy(self):
                return len(self._q)

        @partial(jax.jit, donate_argnums=(0,))
        def acc(G, Gi):
            return G + Gi

        step = jax.jit(lambda w: w * 2)

        def host(X, G, Gi):
            Xp = jnp.pad(X, ((0, 1), (0, 0)))
            out = acc(G, Gi)
            use_after = G.sum()
            for _ in range(2):
                f = jax.jit(lambda a: a)
            return Xp, out, use_after, f

        def drive(w, n):
            hist = []
            for _ in range(n):
                w = step(w)
                hist.append(float(w))
            return hist

        def leaky_cb(x):
            HIST.append(x)
            return x

        def resident(w):
            def body(carry):
                i, w = carry
                r = io_callback(leaky_cb, w, w)
                return (i + 1, r)
            return lax.while_loop(lambda c: c[0] < 3, body, (0, w))

        def program_for(k, lr):
            fn = _PROGRAMS.get(k)
            if fn is None:
                fn = jax.jit(lambda w: w * lr)
                _PROGRAMS[k] = fn
            return fn

        def traced_tick(w):
            out = step(w)
            event("train.tick", loss=out)
            return out
    """, relpath="seeded.py")
    from tpu_sgd.analysis.core import default_rules
    rules = [FailpointCoverageRule(registry=registry)
             if r.name == "failpoint-coverage" else r
             for r in default_rules()]
    res = lint([seeded], rules)
    fired = {f.rule for f in res.findings}
    assert set(KNOWN_RULES) <= fired, (
        f"rules that failed to fire: {set(KNOWN_RULES) - fired}")


# -- the repo itself is clean ----------------------------------------------

def test_repo_lints_clean():
    """The acceptance gate, as a test: zero unsuppressed findings over
    the configured include set, and every suppression carries a reason."""
    res = run_lint(root=REPO)
    assert res.findings == [], "\n".join(str(f) for f in res.findings)
    assert res.files > 50  # the sweep really walked the package


def test_cli_exit_codes(tmp_path, capsys):
    from tpu_sgd.analysis import lint as lint_cli

    assert lint_cli.main(["--root", REPO, "-q"]) == 0

    bad = tmp_path / "bad.py"
    bad.write_text(textwrap.dedent("""
        import jax.numpy as jnp

        def host(X):
            return jnp.pad(X, ((0, 1),))
    """))
    (tmp_path / "pyproject.toml").write_text("")
    rc = lint_cli.main(["--root", str(tmp_path), str(bad)])
    out = capsys.readouterr().out
    assert rc == 1 and "shape-trap" in out

    # a typo'd explicit path must fail loudly (exit 2), never report
    # clean with zero files checked
    rc = lint_cli.main(["--root", REPO, "tpu_sgd/no_such_file_xyz.py"])
    err = capsys.readouterr().err
    assert rc == 2 and "does not exist" in err

    # same for a typo'd config include: a renamed package must not turn
    # the CI lint gate vacuously green
    with pytest.raises(FileNotFoundError, match="include"):
        run_lint(config=Config(root=REPO, include=["tpu_sgd_renamed"]))


# -- runtime: assert_compile_count -----------------------------------------

class _FakeJitted:
    def __init__(self):
        self.n = 0

    def _cache_size(self):
        return self.n


def test_assert_compile_count_exact_and_at_most():
    fn = _FakeJitted()
    with assert_compile_count(2, of=fn):
        fn.n += 2
    with assert_compile_count(2, of=fn, at_most=True):
        fn.n += 1
    with pytest.raises(CompileCountError, match="allows exactly 1"):
        with assert_compile_count(1, of=fn):
            fn.n += 3
    with pytest.raises(CompileCountError, match="allows at most 0"):
        with assert_compile_count(0, of=fn, at_most=True):
            fn.n += 1


def test_assert_compile_count_sums_mixed_sources():
    fn, extra = _FakeJitted(), [0]
    with assert_compile_count(3, of=[fn, lambda: extra[0]]):
        fn.n += 1
        extra[0] += 2
    with pytest.raises(ValueError):
        assert_compile_count(-1, of=fn).__enter__()
    with pytest.raises(TypeError):
        with assert_compile_count(0, of=object()):
            pass


def test_assert_compile_count_on_real_jit():
    import jax
    import jax.numpy as jnp

    f = jax.jit(lambda x: x + 1)
    with assert_compile_count(1, of=f):
        f(jnp.zeros((3,)))
    with assert_compile_count(0, of=f):  # warm shape: no growth
        f(jnp.ones((3,)))
    with assert_compile_count(1, of=f):  # new shape: exactly one
        f(jnp.zeros((4,)))


# -- runtime: InstrumentedLock / instrument_object --------------------------

def test_instrumented_lock_tracks_holding_thread():
    rec = LocksetRecorder()
    lk = InstrumentedLock(threading.Lock(), name="L", recorder=rec)
    assert not lk.held_by_current_thread()
    with lk:
        assert lk.held_by_current_thread()
        seen = []
        t = threading.Thread(
            target=lambda: seen.append(lk.held_by_current_thread()))
        t.start()
        t.join()
        assert seen == [False]  # held-ness is per-thread
    assert not lk.held_by_current_thread()


def test_instrument_object_records_unguarded_access():
    class Box:
        def __init__(self):
            self._lock = threading.Lock()
            self._val = 0
            self._ref = None

        def good(self):
            with self._lock:
                self._val += 1

        def bad(self):
            self._val += 1

        def write_ref_unlocked(self):
            self._ref = object()

        def read_ref_unlocked(self):
            return self._ref

    box = Box()
    rec = instrument_object(box, {"_val": "_lock", "_ref": "_lock:w"})
    box.good()
    assert rec.violations == []
    box.bad()
    assert rec.violating_functions() == {"bad"}
    box.read_ref_unlocked()          # :w — bare read sanctioned
    assert rec.violating_functions() == {"bad"}
    box.write_ref_unlocked()         # :w — write must lock
    assert rec.violating_functions() == {"bad", "write_ref_unlocked"}


def test_real_batcher_declaration_validates_at_runtime():
    """The lock-discipline declaration in serve/batcher.py, validated
    dynamically: a real submit/flush workload over an instrumented
    MicroBatcher records NO unguarded access except the statically
    suppressed racy readers (queue_depth / the metrics sample)."""
    from tpu_sgd.serve.batcher import GRAFTLINT_LOCKS, MicroBatcher

    b = MicroBatcher(lambda X: np.asarray(X).sum(axis=1),
                     max_batch=4, max_latency_s=0.002)
    rec = instrument_object(b, GRAFTLINT_LOCKS["MicroBatcher"])
    futs = [b.submit(np.ones(3, np.float32)) for _ in range(9)]
    with b:
        got = [f.result(timeout=10) for f in futs]
    assert [float(g) for g in got] == [3.0] * 9
    depth = b.queue_depth  # the sanctioned racy read IS recorded
    assert depth == 0
    allowed = {"queue_depth", "_flush"}
    assert rec.violating_functions() <= allowed, rec.violations
    assert "queue_depth" in rec.violating_functions()
    assert rec.checked_accesses > 20  # the workload really went through


def test_real_eventlog_declaration_validates_at_runtime(tmp_path):
    from tpu_sgd.utils.events import (GRAFTLINT_LOCKS, IterationEvent,
                                      JsonLinesEventLog)

    log = JsonLinesEventLog(str(tmp_path / "ev.jsonl"))
    rec = instrument_object(log, GRAFTLINT_LOCKS["JsonLinesEventLog"])

    def writer(i):
        for j in range(20):
            log.on_iteration(IterationEvent(
                iteration=i * 100 + j, loss=0.0, weight_delta_norm=0.0,
                mini_batch_size=1, wall_time_s=0.0))

    threads = [threading.Thread(target=writer, args=(i,)) for i in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    log.close()
    assert rec.violations == []
    events = JsonLinesEventLog.read(str(tmp_path / "ev.jsonl"))
    assert len(events) == 60  # every line whole, none torn


def test_instrumented_condition_wait_releases_lockset():
    """Condition.wait releases the lock while blocked; the recorder must
    not count the waiter as a holder during that window."""
    rec = LocksetRecorder()
    cond = InstrumentedLock(threading.Condition(), name="c", recorder=rec)
    observed = []

    def waiter():
        with cond:
            observed.append(("pre", cond.held_by_current_thread()))
            cond.wait(timeout=5)
            observed.append(("post", cond.held_by_current_thread()))

    t = threading.Thread(target=waiter)
    t.start()
    time.sleep(0.2)
    with cond:  # acquirable because the waiter dropped it
        assert cond.held_by_current_thread()
        cond.notify_all()
    t.join(timeout=5)
    assert not t.is_alive()
    assert observed == [("pre", True), ("post", True)]


# -- host-sync (dataflow) ----------------------------------------------------

def test_host_sync_fires_on_scalar_coercions_in_loop():
    res = lint(mod("""
        import jax

        step = jax.jit(lambda w: w * 2)

        def drive(w, n):
            hist = []
            for _ in range(n):
                w = step(w)
                hist.append(float(w))
            return hist
    """), [HostSyncRule()])
    found = by_rule(res, "host-sync")
    assert len(found) == 1 and "float()" in found[0].message


def test_host_sync_fires_on_implicit_bool_and_while_test():
    res = lint(mod("""
        import jax

        step = jax.jit(lambda w: w)

        def poll(w):
            flag = step(w)
            while flag:
                flag = step(flag)
    """), [HostSyncRule()])
    found = by_rule(res, "host-sync")
    assert len(found) == 1 and "bool()" in found[0].message


def test_host_sync_fires_on_comparison_bool_test():
    """`if c > 0:` on a device value builds a device bool then coerces
    it — same per-trip sync as a bare-name test; and a host rebind
    (`c = int(c)`, itself flagged) releases the name for later tests."""
    res = lint(mod("""
        import jax

        step = jax.jit(lambda w: w)

        def poll(w, n):
            for _ in range(n):
                w = step(w)
                if w > 0:
                    break

        def drain(c):
            c = step(c)
            while c > 0:
                c = step(c)
    """), [HostSyncRule()])
    found = by_rule(res, "host-sync")
    assert len(found) == 2
    assert all("bool()" in f.message for f in found)

    res = lint(mod("""
        import jax

        step = jax.jit(lambda w: w)

        def drive(w, n):
            for _ in range(n):
                w = step(w)
                c = int(w)  # graftlint: disable=host-sync -- one sanctioned fetch
                if c > 0:
                    break
    """), [HostSyncRule()])
    assert by_rule(res, "host-sync") == []


def test_host_sync_interprocedural_flags_loop_borne_call_site():
    """A helper that forces the sync internally is flagged at its
    loop-borne call site — the line that pays."""
    res = lint(mod("""
        import jax
        import numpy as np

        step = jax.jit(lambda w: w * 2)

        def fetch(v):
            return np.asarray(v)

        def drive(w, n):
            for _ in range(n):
                w = step(w)
                fetch(w)
    """), [HostSyncRule()])
    found = by_rule(res, "host-sync")
    assert len(found) == 1
    assert "fetch" in found[0].message and found[0].line == 13


def test_host_sync_silent_on_boundary_fetch_and_traced_loops():
    """No finding for: a fetch AFTER the loop (the contract), the
    sanctioned genexp bulk fetch, a loop inside a traced function, and
    values the rule cannot prove device-resident."""
    res = lint(mod("""
        import jax
        import jax.numpy as jnp
        import numpy as np

        step = jax.jit(lambda w: w * 2)

        def drive(w, n):
            for _ in range(n):
                w = step(w)
            return float(w)

        def bulk(w, n):
            ys = step(w)
            for _ in range(n):
                w = step(w)
            return tuple(np.asarray(a) for a in (w, ys))

        @jax.jit
        def traced(w):
            for _ in range(3):
                w = jnp.sin(w)
            return w

        def host_numpy(rows, n):
            out = []
            for r in rows:
                out.append(np.asarray(r))
            return out
    """), [HostSyncRule()])
    assert by_rule(res, "host-sync") == []


def test_host_sync_silent_on_for_iterable_and_else_clause():
    """A for's iterable and a loop's else clause evaluate ONCE — the
    one-fetch-then-iterate spelling must not fire; the same fetch moved
    into the body still does, and an iterable fetch nested inside an
    OUTER loop's body is per-outer-trip and fires."""
    res = lint(mod("""
        import jax
        import numpy as np

        count = jax.jit(lambda w: w.sum())

        def once(w, rows):
            n = count(w)
            for i in range(int(n)):
                rows.append(i)
            else:
                tail = float(n)
            return tail

        def per_trip(w, rows):
            n = count(w)
            for _ in rows:
                k = int(n)
            return k

        def per_outer_trip(w, grids):
            n = count(w)
            for g in grids:
                for i in range(int(n)):
                    g.append(i)
    """), [HostSyncRule()])
    found = by_rule(res, "host-sync")
    assert len(found) == 2
    assert {f.line for f in found} == {18, 24}


# -- callback-discipline -----------------------------------------------------

def test_callback_unordered_consumed_result_and_leaky_target():
    res = lint(mod("""
        import jax
        from jax.experimental import io_callback

        HIST = []

        def bad_cb(x):
            HIST.append(x)
            return x

        def body(x):
            r = io_callback(bad_cb, x, x)
            return r
    """), [CallbackDisciplineRule()])
    found = by_rule(res, "callback-discipline")
    msgs = " | ".join(f.message for f in found)
    assert len(found) == 3
    assert "not ordered=True" in msgs
    assert "exception cross the FFI boundary" in msgs
    assert "appends to closure variable" in msgs


def test_callback_clean_site_passes():
    """ordered=True + stash-flag-reraise guard + bookkeeper-owned state:
    the resident_driver contract, distilled."""
    res = lint(mod("""
        import numpy as np
        from jax.experimental import io_callback

        class Keeper:
            def on_window(self, start, ws):
                try:
                    self.last = np.asarray(ws)
                    return np.zeros((), np.bool_)
                except BaseException as e:
                    self.error = e
                    return np.ones((), np.bool_)

        def build(keeper, spec):
            def fire(start, ws):
                return io_callback(keeper.on_window, spec, start, ws,
                                   ordered=True)
            return fire
    """), [CallbackDisciplineRule()])
    assert by_rule(res, "callback-discipline") == []


def test_callback_fire_and_forget_unordered_is_fine():
    """An Expr-statement callback (result unused) may stay unordered —
    no bookkeeping is driven by its result."""
    res = lint(mod("""
        from jax.experimental import io_callback

        def tick(x):
            try:
                print(x)
            except BaseException:
                pass

        def body(x):
            io_callback(tick, None, x)
            return x
    """), [CallbackDisciplineRule()])
    assert by_rule(res, "callback-discipline") == []


def test_callback_reraising_handler_is_still_leaky():
    res = lint(mod("""
        from jax.experimental import io_callback

        def cb(x):
            try:
                return x
            except BaseException:
                raise

        def body(x):
            r = io_callback(cb, x, x, ordered=True)
            return r
    """), [CallbackDisciplineRule()])
    found = by_rule(res, "callback-discipline")
    assert len(found) == 1
    assert "exception cross the FFI boundary" in found[0].message


def test_callback_target_resolution_survives_name_collision():
    """An unrelated `def on_window` elsewhere in the lint set must not
    silently void the contract checks: the call site's own module wins
    the tie, and a collision with NO local def is itself a finding."""
    caller = mod("""
        from jax.experimental import io_callback

        class Keeper:
            def on_window(self, x):
                return x

        def build(keeper, spec):
            def fire(x):
                return io_callback(keeper.on_window, spec, x,
                                   ordered=True)
            return fire
    """, "caller_mod.py")
    other = mod("""
        class Widget:
            def on_window(self, event):
                return event
    """, "other_mod.py")
    # alone: the unguarded local target is flagged
    res = lint([caller], [CallbackDisciplineRule()])
    found = by_rule(res, "callback-discipline")
    assert len(found) == 1
    assert "exception cross the FFI boundary" in found[0].message
    # with the colliding module: SAME finding — local def still wins
    res = lint([caller, other], [CallbackDisciplineRule()])
    found = by_rule(res, "callback-discipline")
    assert len(found) == 1
    assert "exception cross the FFI boundary" in found[0].message

    # no local def + several remote candidates: ambiguity is loud
    remote_caller = mod("""
        from jax.experimental import io_callback

        def build(hooks, spec):
            def fire(x):
                return io_callback(hooks.on_window, spec, x,
                                   ordered=True)
            return fire
    """, "remote_caller.py")
    other2 = mod("""
        class Panel:
            def on_window(self, event):
                return event
    """, "other2_mod.py")
    res = lint([remote_caller, other, other2],
               [CallbackDisciplineRule()])
    found = by_rule(res, "callback-discipline")
    assert len(found) == 1
    assert "matches several defs" in found[0].message


# -- carry-stability ---------------------------------------------------------

def test_carry_fires_on_python_scalar_init():
    res = lint(mod("""
        import jax
        from jax import lax

        def run(w):
            def body(carry):
                i, wc = carry
                return (i + 1, wc * 2)
            return lax.while_loop(lambda c: c[0] < 3, body, (0, w))
    """), [CarryStabilityRule()])
    found = by_rule(res, "carry-stability")
    assert len(found) == 1 and "WEAK-typed" in found[0].message


def test_carry_fires_on_scalar_reset_in_body():
    res = lint(mod("""
        import jax.numpy as jnp
        from jax import lax

        def run(xs, w):
            def body(c, x):
                return (0, c[1] + x)
            init = (jnp.asarray(0, jnp.int32), w)
            return lax.scan(body, init, xs)
    """), [CarryStabilityRule()])
    found = by_rule(res, "carry-stability")
    assert len(found) == 1 and "re-enters" in found[0].message


def test_carry_silent_on_pinned_init_and_device_reset():
    res = lint(mod("""
        import jax.numpy as jnp
        from jax import lax

        def run(xs, w):
            def body(c, x):
                slot = jnp.where(x > 0, jnp.zeros_like(c[0]), c[0])
                return (slot, c[1] + x), x
            init = (jnp.asarray(0, jnp.int32), w)
            return lax.scan(body, init, xs)

        def local_scan_helper_does_not_fire(scan, data):
            return scan(lambda c, x: (0, c), 0, data)
    """), [CarryStabilityRule()])
    assert by_rule(res, "carry-stability") == []


def test_carry_silent_on_non_jax_lax_lookalikes():
    """Only `lax` / `*.lax` heads are loop entries: `flax.while_loop`
    or a `parallax.scan` must not fire (the substring-match trap), while
    the real `jax.lax` spellings still do."""
    res = lint(mod("""
        import flax
        import parallax

        def run(w):
            flax.while_loop(lambda c: c, lambda c: c, (0, w))
            return parallax.scan(lambda c, x: (0, c), 0, w)
    """), [CarryStabilityRule()])
    assert by_rule(res, "carry-stability") == []

    res = lint(mod("""
        import jax
        from jax import lax

        def run(w, xs):
            jax.lax.while_loop(lambda c: c[0] < 3,
                               lambda c: (c[0] + 1, c[1]), (0, w))
            return lax.scan(lambda c, x: (c, x), 0.0, xs)
    """), [CarryStabilityRule()])
    assert len(by_rule(res, "carry-stability")) == 2


def test_carry_fires_on_keyword_init_and_body():
    """`lax.scan(body, init=(0, w), xs=xs)` and
    `lax.while_loop(..., init_val=..., body_fun=...)` are standard
    spellings — keyword-passed carries must not slip the net."""
    res = lint(mod("""
        import jax.numpy as jnp
        from jax import lax

        def kw_init(w, xs):
            return lax.scan(lambda c, x: (c, x), init=(0, w), xs=xs)

        def kw_body_reset(w, xs):
            init = (jnp.asarray(0, jnp.int32), w)
            return lax.scan(xs=xs, init=init,
                            f=lambda c, x: ((0, c[1] + x), x))

        def kw_while(w):
            return lax.while_loop(
                cond_fun=lambda c: c[0] < 3,
                body_fun=lambda c: (c[0] + 1, c[1]),
                init_val=(0, w))
    """), [CarryStabilityRule()])
    found = by_rule(res, "carry-stability")
    assert len(found) == 3
    msgs = " | ".join(f.message for f in found)
    assert "WEAK-typed" in msgs and "re-enters" in msgs


# -- memo-key ----------------------------------------------------------------

def test_memo_local_alias_store_attaches_to_declared_cache():
    """`cache = self._cache; cache[key] = fn` — the idiomatic local
    alias must attach to the declaration (no never-stores drift, no
    undeclared-alias finding), and its factory check still works."""
    res = lint(mod("""
        import jax

        GRAFTLINT_MEMO = {"Engine._cache": ("size",)}

        class Engine:
            def __init__(self, size):
                self._cache = {}
                self.size = size

            def program_for(self):
                cache = self._cache
                key = (self.size,)
                fn = cache.get(key)
                if fn is None:
                    fn = jax.jit(lambda x: x * self.size)
                    cache[key] = fn
                return fn
    """), [MemoKeyRule()])
    assert by_rule(res, "memo-key") == []


def test_memo_undeclared_program_cache_is_a_finding():
    res = lint(mod("""
        import jax

        _CACHE = {}

        def program_for(key):
            fn = _CACHE.get(key)
            if fn is None:
                fn = jax.jit(lambda x: x)
                _CACHE[key] = fn
            return fn
    """), [MemoKeyRule()])
    found = by_rule(res, "memo-key")
    assert len(found) == 1 and "no GRAFTLINT_MEMO entry" in found[0].message


def test_memo_declared_cache_with_complete_key_passes():
    res = lint(mod("""
        import jax

        _CACHE = {}
        GRAFTLINT_MEMO = {"_CACHE": ("key", "lr")}

        def program_for(key, lr):
            fn = _CACHE.get((key, lr))
            if fn is None:
                fn = jax.jit(lambda w: w * lr)
                _CACHE[(key, lr)] = fn
            return fn
    """), [MemoKeyRule()])
    assert by_rule(res, "memo-key") == []


def test_memo_declaration_drift_both_directions():
    res = lint(mod("""
        import jax

        _CACHE = {}
        GRAFTLINT_MEMO = {"_CACHE": ("key", "ghost")}

        def program_for(key, flavor):
            fn = jax.jit(lambda x: x + len(flavor))
            _CACHE[(key, flavor)] = fn
            return fn
    """), [MemoKeyRule()])
    found = by_rule(res, "memo-key")
    msgs = " | ".join(f.message for f in found)
    assert "'ghost'" in msgs and "no store site's key derives" in msgs
    assert "'flavor'" in msgs and "does not list it" in msgs


def test_memo_factory_read_outside_key_is_a_finding():
    """THE incomplete-memo-key bug: the stored program bakes in ``lr``
    but the key does not carry it — two configs share one program."""
    res = lint(mod("""
        import jax

        _CACHE = {}
        GRAFTLINT_MEMO = {"_CACHE": ("k",)}

        def program_for(k, lr):
            fn = jax.jit(lambda w: w * lr)
            _CACHE[k] = fn
            return fn
    """), [MemoKeyRule()])
    found = by_rule(res, "memo-key")
    assert any("`lr`" in f.message and "key does not include it"
               in f.message for f in found)


def test_memo_missing_cache_and_malformed_declaration():
    res = lint(mod("""
        GRAFTLINT_MEMO = {"_GONE": ("key",)}
    """), [MemoKeyRule()])
    found = by_rule(res, "memo-key")
    assert len(found) == 1 and "no such name" in found[0].message

    res = lint(mod("""
        GRAFTLINT_MEMO = {"_C": "not-a-tuple"}
        _C = {}
    """), [MemoKeyRule()])
    found = by_rule(res, "memo-key")
    assert len(found) == 1 and "literal" in found[0].message


# -- call-graph upgrades (lock + donation) -----------------------------------

def test_lock_private_helper_proven_by_locked_call_sites():
    """The _swap pattern: every in-class call site of the private helper
    holds the lock, so its unlocked accesses pass without suppression."""
    res = lint(mod("""
        import threading

        GRAFTLINT_LOCKS = {"R": {"_model": "_lock"}}

        class R:
            def __init__(self):
                self._lock = threading.Lock()
                self._model = None

            def _swap(self, m):
                self._model = m

            def reload(self, m):
                with self._lock:
                    self._swap(m)

            def rollback(self, m):
                with self._lock:
                    self._swap(m)
    """), [LockDisciplineRule()])
    assert by_rule(res, "lock-discipline") == []


def test_lock_one_unlocked_call_site_voids_the_proof():
    res = lint(mod("""
        import threading

        GRAFTLINT_LOCKS = {"R": {"_model": "_lock"}}

        class R:
            def __init__(self):
                self._lock = threading.Lock()
                self._model = None

            def _swap(self, m):
                self._model = m

            def reload(self, m):
                with self._lock:
                    self._swap(m)

            def sloppy(self, m):
                self._swap(m)
    """), [LockDisciplineRule()])
    found = by_rule(res, "lock-discipline")
    assert len(found) == 1 and "_model" in found[0].message


def test_donation_forwarder_one_call_level():
    """helper() forwards its param into a donated position, so calling
    helper(G) donates G — a later read of G is a finding."""
    res = lint(mod("""
        import jax
        from functools import partial

        @partial(jax.jit, donate_argnums=(0,))
        def acc(G, Gi):
            return G + Gi

        def helper(G, Gi):
            return acc(G, Gi)

        def use(G, Gi):
            out = helper(G, Gi)
            tail = G.sum()
            return out, tail
    """), [DonationSafetyRule()])
    found = by_rule(res, "donation-safety")
    assert len(found) == 1 and "helper" in found[0].message


def test_donation_forwarder_voided_by_param_rebind():
    res = lint(mod("""
        import jax
        from functools import partial

        @partial(jax.jit, donate_argnums=(0,))
        def acc(G, Gi):
            return G + Gi

        def safe_helper(G, Gi):
            G = G + 0  # a fresh buffer is donated, not the caller's
            return acc(G, Gi)

        def use(G, Gi):
            out = safe_helper(G, Gi)
            tail = G.sum()
            return out, tail
    """), [DonationSafetyRule()])
    assert by_rule(res, "donation-safety") == []


# -- stale suppressions ------------------------------------------------------

def test_stale_suppression_is_a_finding():
    res = lint(mod("""
        import jax.numpy as jnp

        def clean(x):
            return x + 1  # graftlint: disable=shape-trap -- historical
    """), [ShapeTrapRule()])
    found = by_rule(res, "stale-suppression")
    assert len(found) == 1 and "no longer fires" in found[0].message


def test_live_suppression_is_not_stale():
    res = lint(mod("""
        import jax.numpy as jnp

        def host_assemble(X, tail):
            return jnp.pad(X, ((0, tail), (0, 0)))  # graftlint: disable=shape-trap -- fixture: intentionally eager
    """), [ShapeTrapRule()])
    assert by_rule(res, "stale-suppression") == []
    assert by_rule(res, "shape-trap") == []
    assert res.suppressed == 1


def test_stale_not_reported_for_rules_that_did_not_run():
    """Staleness is only provable when the rule had its chance to fire:
    a host-sync suppression is NOT stale under a shape-trap-only run."""
    res = lint(mod("""
        def clean(x):
            return x + 1  # graftlint: disable=host-sync -- not checked this run
    """), [ShapeTrapRule()])
    assert by_rule(res, "stale-suppression") == []


def test_stale_all_wildcard_needs_every_rule_to_have_run():
    """A `disable=all` wildcard is only provably stale when EVERY known
    rule had its chance to fire: under a shape-trap-only run the
    host-sync finding it eats never existed, so the wildcard must not
    be reported stale — but under the full default rule set a clean
    line's wildcard is."""
    from tpu_sgd.analysis.core import default_rules
    src = """
        import jax

        step = jax.jit(lambda w: w)

        def drive(w, n):
            for _ in range(n):
                w = step(w)
                probe = w.item()  # graftlint: disable=all -- intentional probe
            return probe
    """
    res = lint(mod(src), [ShapeTrapRule()])
    assert by_rule(res, "stale-suppression") == []

    clean = """
        def clean(x):
            return x + 1  # graftlint: disable=all -- nothing here
    """
    res = lint(mod(clean), default_rules())
    found = by_rule(res, "stale-suppression")
    assert len(found) == 1 and "'all'" in found[0].message
    res = lint(mod(clean), [ShapeTrapRule()])
    assert by_rule(res, "stale-suppression") == []


# -- real-module mutation checks (graftlint v2) ------------------------------

def test_mutation_deleted_memo_key_field_fails_lint():
    """Delete the 'X' key field from streamed.py's _RESIDENT_LOOPS
    declaration: the memo-key drift check must catch it."""
    intact = _real_module("tpu_sgd/optimize/streamed.py")
    res = lint([intact], [MemoKeyRule()])
    assert by_rule(res, "memo-key") == []

    mutated = _real_module(
        "tpu_sgd/optimize/streamed.py",
        lambda s: s.replace('"resident_cadence", "X"),',
                            '"resident_cadence"),'))
    res = lint([mutated], [MemoKeyRule()])
    found = by_rule(res, "memo-key")
    assert any("'X'" in f.message and "does not list it" in f.message
               for f in found)


def test_mutation_item_in_resident_loop_body_fails_lint():
    """Insert a ``.item()`` on the step's result inside the observed
    streamed K=1 loop (just before its contractual barrier — the one
    spot the PR 10 observe_step extraction left in the loop body): the
    host-sync rule must catch the new per-iteration sync."""
    gd = _real_module("tpu_sgd/optimize/gradient_descent.py")
    intact = _real_module("tpu_sgd/optimize/streamed.py")
    res = lint([intact, gd], [HostSyncRule()])
    assert by_rule(res, "host-sync") == []

    barrier = (
        "                # graftlint: disable=host-sync -- observed "
        "driver: one barrier per step precedes the scalar reads below\n"
        "                new_w = jax.block_until_ready(new_w)")
    assert barrier in intact.source  # anchor must track the real loop
    mutated = _real_module(
        "tpu_sgd/optimize/streamed.py",
        lambda s: s.replace(
            barrier,
            "                probe = new_w.item()\n" + barrier, 1))
    res = lint([mutated, gd], [HostSyncRule()])
    found = by_rule(res, "host-sync")
    assert any(".item()" in f.message for f in found)


def test_mutation_unguarded_resident_callback_fails_lint():
    """Make the real `on_window` handler re-raise (breaking the
    stash-flag-reraise contract): callback-discipline must flag the
    io_callback site — proof the attribute-hop target resolution
    actually attaches the contract to the resident driver."""
    intact = _real_module("tpu_sgd/optimize/resident_driver.py")
    res = lint([intact], [CallbackDisciplineRule()])
    assert by_rule(res, "callback-discipline") == []

    mutated = _real_module(
        "tpu_sgd/optimize/resident_driver.py",
        lambda s: s.replace(
            "self.error = e\n            return np.bool_(True)",
            "self.error = e\n            raise"))
    res = lint([mutated], [CallbackDisciplineRule()])
    found = by_rule(res, "callback-discipline")
    assert len(found) == 1
    assert "on_window" in found[0].message
    assert "exception cross the FFI boundary" in found[0].message


# -- runtime twins: host-sync + callback buffers -----------------------------

def test_count_host_syncs_counts_coercions_not_cache_hits():
    import jax
    import jax.numpy as jnp

    from tpu_sgd.analysis.runtime import count_host_syncs

    f = jax.jit(lambda x: x * 2)
    a = f(jnp.arange(8.0))
    jax.block_until_ready(a)
    with count_host_syncs() as c:
        float(a[0])          # scalar coercion: one transfer
        a.__array__()        # materializes (and caches) the array
        a.__array__()        # cached: free
        jax.block_until_ready(a)  # barrier, never a transfer
    assert c["n"] == 2
    assert all(isinstance(s, tuple) for s, _ in c["shapes"])


def test_assert_no_host_sync_raises_and_allows():
    import jax
    import jax.numpy as jnp

    from tpu_sgd.analysis.runtime import (HostSyncError,
                                          assert_no_host_sync)

    f = jax.jit(lambda x: x + 1)
    a = f(jnp.arange(4.0))
    jax.block_until_ready(a)
    with pytest.raises(HostSyncError) as ei:
        with assert_no_host_sync():
            a.item(0)
    assert "device->host transfer" in str(ei.value)

    b = f(jnp.arange(4.0))
    with assert_no_host_sync(allow=1):
        float(b[1])

    # call-through form: dispatching is not syncing
    out = assert_no_host_sync(lambda: f(jnp.arange(4.0)))
    assert out.shape == (4,)


def test_assert_bounded_callback_buffer():
    import numpy as np

    from tpu_sgd.analysis.runtime import (CallbackBufferError,
                                          assert_bounded_callback_buffer)

    grows = []
    with pytest.raises(CallbackBufferError):
        with assert_bounded_callback_buffer(grows):
            grows.append(1)

    ring = np.zeros(16)
    with assert_bounded_callback_buffer(lambda: ring):
        ring[3] = 1.0  # overwrite in place: bounded

    capped = [1, 2]
    with assert_bounded_callback_buffer(capped, max_len=4):
        capped.append(3)
