"""GramLeastSquaresGradient (sufficient-statistics path) parity tests.

The bound gradient must reproduce the stock two-pass results exactly (up to
float summation order) for window sums at arbitrary offsets including
partial-block edges and non-block-multiple tails, full-batch sums, the
line-search sweep, and the whole GradientDescent / LBFGS trajectories —
and must fall back (warning once) whenever it is called with anything but
the bound dataset.
"""

import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_sgd import GradientDescent, LBFGS, SimpleUpdater, SquaredL2Updater
from tpu_sgd.ops.gradients import LeastSquaresGradient
from tpu_sgd.ops.gram import GramLeastSquaresGradient


def _data(rng, n=1000, d=16, noise=0.1):
    X = rng.normal(size=(n, d)).astype(np.float32)
    w = rng.uniform(-1, 1, size=(d,)).astype(np.float32)
    y = (X @ w + noise * rng.normal(size=(n,))).astype(np.float32)
    return jnp.asarray(X), jnp.asarray(y), jnp.asarray(w)


@pytest.mark.parametrize("block", [64, 100, 1000, 2048])
@pytest.mark.parametrize("start,m", [(0, 100), (37, 200), (123, 64),
                                     (900, 100), (999, 1), (0, 1000)])
def test_window_sums_parity(rng, block, start, m):
    # n=1000 is NOT a multiple of 64 or 2048 -> exercises the tail backoff
    X, y, w = _data(rng)
    base = LeastSquaresGradient()
    gram = GramLeastSquaresGradient.build(X, y, block_rows=block)
    g0, l0, c0 = base.window_sums(X, y, w, jnp.int32(start), m)
    g1, l1, c1 = gram.window_sums(X, y, w, jnp.int32(start), m)
    # Absolute tolerance scales with the f32 prefix cancellation: results
    # are differences of [0, r) accumulations, so tiny windows (m=1) carry
    # the full-prefix rounding noise while their own magnitude is O(1).
    atol = 2e-3 if m >= 64 else 2e-2
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g0),
                               rtol=2e-4, atol=atol)
    assert float(l1) == pytest.approx(float(l0), rel=1e-3, abs=atol)
    assert float(c1) == float(c0) == min(m, 1000)


def test_window_start_clamp_matches_stock(rng):
    X, y, w = _data(rng, n=500)
    base = LeastSquaresGradient()
    gram = GramLeastSquaresGradient.build(X, y, block_rows=128)
    # out-of-range start: stock dynamic_slice clamps to n - m
    g0, l0, _ = base.window_sums(X, y, w, jnp.int32(490), 100)
    g1, l1, _ = gram.window_sums(X, y, w, jnp.int32(490), 100)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g0),
                               rtol=2e-4, atol=2e-3)
    assert float(l1) == pytest.approx(float(l0), rel=1e-3, abs=2e-3)


def test_batch_sums_and_loss_sweep_parity(rng):
    X, y, w = _data(rng)
    base = LeastSquaresGradient()
    gram = GramLeastSquaresGradient.build(X, y, block_rows=100)
    g0, l0, c0 = base.batch_sums(X, y, w)
    g1, l1, c1 = gram.batch_sums(X, y, w)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g0),
                               rtol=2e-4, atol=2e-3)
    assert float(l1) == pytest.approx(float(l0), rel=2e-4)
    assert float(c1) == float(c0)

    W = jnp.stack([w, 0.5 * w, jnp.zeros_like(w)])
    s0, n0 = base.loss_sweep(X, y, W)
    s1, n1 = gram.loss_sweep(X, y, W)
    np.testing.assert_allclose(np.asarray(s1), np.asarray(s0),
                               rtol=2e-4, atol=2e-3)
    assert float(n1) == float(n0)


def test_masked_paths_delegate_exactly(rng):
    X, y, w = _data(rng, n=300)
    base = LeastSquaresGradient()
    gram = GramLeastSquaresGradient.build(X, y, block_rows=64)
    mask = jnp.asarray((np.arange(300) % 2 == 0).astype(np.float32))
    g0, l0, c0 = base.batch_sums(X, y, w, mask)
    g1, l1, c1 = gram.batch_sums(X, y, w, mask)
    # delegation is the SAME code path -> bitwise equal
    np.testing.assert_array_equal(np.asarray(g1), np.asarray(g0))
    assert float(l1) == float(l0) and float(c1) == float(c0)

    valid = jnp.asarray(np.ones((300,), np.float32))
    g0, l0, c0 = base.window_sums(X, y, w, jnp.int32(10), 50, valid=valid)
    g1, l1, c1 = gram.window_sums(X, y, w, jnp.int32(10), 50, valid=valid)
    np.testing.assert_array_equal(np.asarray(g1), np.asarray(g0))


def test_unbound_matrix_falls_back_with_warning(rng):
    X, y, w = _data(rng, n=200)
    gram = GramLeastSquaresGradient.build(X, y, block_rows=64)
    X2, y2, _ = _data(rng, n=150)
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        g1, l1, c1 = gram.window_sums(X2, y2, w, jnp.int32(0), 50)
        gram.window_sums(X2, y2, w, jnp.int32(0), 50)  # warns only once
    assert sum(issubclass(r.category, RuntimeWarning) for r in rec) == 1
    g0, l0, c0 = LeastSquaresGradient().window_sums(
        X2, y2, w, jnp.int32(0), 50)
    np.testing.assert_array_equal(np.asarray(g1), np.asarray(g0))


def test_gd_trajectory_parity_sliced(rng):
    X, y, _ = _data(rng, n=4096, d=24)
    gram = GramLeastSquaresGradient.build(X, y, block_rows=512)

    def run(gradient):
        opt = (GradientDescent(gradient, SimpleUpdater())
               .set_step_size(0.2).set_num_iterations(30)
               .set_mini_batch_fraction(0.1).set_sampling("sliced")
               .set_seed(7).set_convergence_tol(0.0))
        return opt.optimize_with_history((X, y), jnp.zeros((24,)))

    w0, h0 = run(LeastSquaresGradient())
    w1, h1 = run(gram)
    np.testing.assert_allclose(np.asarray(h1), np.asarray(h0),
                               rtol=5e-4, atol=5e-4)
    np.testing.assert_allclose(np.asarray(w1), np.asarray(w0),
                               rtol=5e-4, atol=5e-4)


def test_gd_trajectory_parity_full_batch(rng):
    X, y, _ = _data(rng, n=1500, d=12)
    gram = GramLeastSquaresGradient.build(X, y, block_rows=256)

    def run(gradient):
        opt = (GradientDescent(gradient, SquaredL2Updater())
               .set_step_size(0.3).set_num_iterations(25)
               .set_reg_param(0.01).set_seed(3))
        return opt.optimize_with_history((X, y), jnp.zeros((12,)))

    w0, h0 = run(LeastSquaresGradient())
    w1, h1 = run(gram)
    np.testing.assert_allclose(np.asarray(h1), np.asarray(h0),
                               rtol=5e-4, atol=5e-4)
    np.testing.assert_allclose(np.asarray(w1), np.asarray(w0),
                               rtol=5e-4, atol=5e-4)


def test_lbfgs_matches_stock_and_accelerated_cost(rng):
    X, y, _ = _data(rng, n=2000, d=20)
    gram = GramLeastSquaresGradient.build(X, y, block_rows=256)

    def run(gradient):
        opt = LBFGS(gradient, SquaredL2Updater(), reg_param=0.01,
                    max_num_iterations=15)
        return opt.optimize_with_history((X, y), jnp.zeros((20,)))

    w0, h0 = run(LeastSquaresGradient())
    w1, h1 = run(gram)
    assert float(h1[-1]) == pytest.approx(float(h0[-1]), rel=1e-3)
    np.testing.assert_allclose(np.asarray(w1), np.asarray(w0),
                               rtol=1e-2, atol=1e-3)


def test_bf16_data_close_to_f32_truth(rng):
    """With bf16 data the gram path computes at HIGHEST precision in f32
    internally (the matmul_dtype bandwidth contract would amplify bf16
    rounding by prefix/window magnitude — see the module docstring), so it
    must track the f32 truth OF THE bf16 DATA tightly — tighter than the
    stock bf16 two-pass tracks it."""
    X, y, w = _data(rng, n=2048, d=16)
    Xb = X.astype(jnp.bfloat16)
    Xf = np.asarray(Xb, np.float32)  # the bf16 data, exactly, in f32
    gram = GramLeastSquaresGradient.build(Xb, y, block_rows=256)
    g1, l1, c1 = gram.window_sums(Xb, y, w, jnp.int32(100), 512)
    win = slice(100, 612)
    resid = Xf[win] @ np.asarray(w) - np.asarray(y)[win]
    g_truth = Xf[win].T @ resid
    l_truth = 0.5 * float(resid @ resid)
    np.testing.assert_allclose(np.asarray(g1, np.float32), g_truth,
                               rtol=1e-3, atol=5e-2)
    assert float(l1) == pytest.approx(l_truth, rel=1e-3)


def test_build_rejects_narrow_stats_and_empty(rng):
    X, y, _ = _data(rng, n=64)
    with pytest.raises(ValueError, match="f32"):
        GramLeastSquaresGradient.build(X, y, stats_dtype=jnp.bfloat16)
    with pytest.raises(ValueError, match="non-empty"):
        GramLeastSquaresGradient.build(jnp.zeros((0, 4)), jnp.zeros((0,)))


def test_int_features_build_and_match(rng):
    Xi = (rng.integers(0, 2, size=(500, 8))).astype(np.int32)
    y = rng.normal(size=(500,)).astype(np.float32)
    w = rng.normal(size=(8,)).astype(np.float32)
    gram = GramLeastSquaresGradient.build(Xi, y, block_rows=128)
    # build() coerces int features to f32 internally; the accelerated path
    # is reached through the GramData bundle (identity binding means a
    # caller-side re-cast can never silently alias)
    Xf = jnp.asarray(Xi).astype(jnp.float32)
    g1, l1, c1 = gram.window_sums(gram.data, jnp.asarray(y), w,
                                  jnp.int32(3), 200)
    g0, l0, c0 = LeastSquaresGradient().window_sums(
        Xf, jnp.asarray(y), w, jnp.int32(3), 200)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g0),
                               rtol=2e-4, atol=2e-3)


def test_gd_set_sufficient_stats_flag(rng):
    X, y, _ = _data(rng, n=2048, d=16)

    def make(flag):
        opt = (GradientDescent(LeastSquaresGradient(), SimpleUpdater())
               .set_step_size(0.2).set_num_iterations(20)
               .set_mini_batch_fraction(0.25).set_sampling("sliced")
               .set_seed(5).set_convergence_tol(0.0))
        return opt.set_sufficient_stats(flag)

    w0, h0 = make(False).optimize_with_history((X, y), jnp.zeros((16,)))
    opt = make(True)
    w1, h1 = opt.optimize_with_history((X, y), jnp.zeros((16,)))
    np.testing.assert_allclose(np.asarray(h1), np.asarray(h0),
                               rtol=5e-4, atol=5e-4)
    assert opt._gram_entry is not None
    # identity cache: same arrays -> same built gradient; gradient restored
    built = opt._gram_entry[2]
    opt.optimize_with_history((X, y), jnp.zeros((16,)))
    assert opt._gram_entry[2] is built
    assert type(opt.gradient) is LeastSquaresGradient


def test_gd_sufficient_stats_noop_cases(rng):
    from tpu_sgd.ops.gradients import LogisticGradient

    X = jnp.asarray(rng.normal(size=(256, 8)).astype(np.float32))
    y = jnp.asarray((rng.uniform(size=(256,)) > 0.5).astype(np.float32))
    # non-least-squares gradient: flag must be a no-op
    opt = (GradientDescent(LogisticGradient(), SimpleUpdater())
           .set_num_iterations(3).set_sufficient_stats(True))
    opt.optimize_with_history((X, y), jnp.zeros((8,)))
    assert opt._gram_entry is None
    # bernoulli sub-unit sampling: no gram either
    opt2 = (GradientDescent(LeastSquaresGradient(), SimpleUpdater())
            .set_num_iterations(3).set_mini_batch_fraction(0.5)
            .set_sufficient_stats(True))
    opt2.optimize_with_history((X, y), jnp.zeros((8,)))
    assert opt2._gram_entry is None


def test_lbfgs_and_owlqn_sufficient_stats_flag(rng):
    from tpu_sgd import OWLQN

    X, y, _ = _data(rng, n=1500, d=12)

    r0 = LBFGS(LeastSquaresGradient(), SquaredL2Updater(), reg_param=0.01,
               max_num_iterations=12).optimize_with_history(
                   (X, y), jnp.zeros((12,)))
    lb = LBFGS(LeastSquaresGradient(), SquaredL2Updater(), reg_param=0.01,
               max_num_iterations=12).set_sufficient_stats(True)
    r1 = lb.optimize_with_history((X, y), jnp.zeros((12,)))
    assert float(r1[1][-1]) == pytest.approx(float(r0[1][-1]), rel=1e-3)
    assert lb._gram_entry is not None

    o0 = OWLQN(LeastSquaresGradient(), reg_param=1e-3,
               max_num_iterations=12).optimize_with_history(
                   (X, y), jnp.zeros((12,)))
    ow = OWLQN(LeastSquaresGradient(), reg_param=1e-3,
               max_num_iterations=12).set_sufficient_stats(True)
    o1 = ow.optimize_with_history((X, y), jnp.zeros((12,)))
    assert float(o1[1][-1]) == pytest.approx(float(o0[1][-1]), rel=1e-3)
    assert ow._gram_entry is not None


def test_gramdata_argument_path_matches_plain(rng):
    """Stats passed as the X argument (GramData pytree — the big-slab
    plumbing) must give the same results as plain-array binding, and must
    flow through a jitted make_run unchanged."""
    from tpu_sgd.config import SGDConfig
    from tpu_sgd.optimize.gradient_descent import make_run

    X, y, w = _data(rng, n=2048, d=16)
    gram = GramLeastSquaresGradient.build(X, y, block_rows=256)
    g0, l0, c0 = gram.window_sums(X, y, w, jnp.int32(100), 512)
    g1, l1, c1 = gram.window_sums(gram.data, y, w, jnp.int32(100), 512)
    np.testing.assert_array_equal(np.asarray(g1), np.asarray(g0))
    assert float(l1) == float(l0)

    cfg = SGDConfig(step_size=0.2, num_iterations=10,
                    mini_batch_fraction=0.25, convergence_tol=0.0,
                    sampling="sliced")
    run = jax.jit(make_run(gram, SimpleUpdater(), cfg))
    w1, h1, nr1 = run(jnp.zeros((16,)), gram.data, y)
    run0 = jax.jit(make_run(LeastSquaresGradient(), SimpleUpdater(), cfg))
    w0, h0, nr0 = run0(jnp.zeros((16,)), X, y)
    np.testing.assert_allclose(np.asarray(h1)[:int(nr1)],
                               np.asarray(h0)[:int(nr0)],
                               rtol=5e-4, atol=5e-4)


def test_gramdata_rejects_indexing():
    import pytest as _pytest

    X = jnp.ones((64, 4))
    y = jnp.ones((64,))
    gram = GramLeastSquaresGradient.build(X, y, block_rows=16)
    with _pytest.raises(TypeError, match="sliced"):
        gram.data[0]


def test_model_level_sufficient_stats(rng):
    from tpu_sgd import LinearRegressionWithSGD

    X = rng.normal(size=(1024, 10)).astype(np.float32)
    w = rng.uniform(-1, 1, size=(10,)).astype(np.float32)
    y = X @ w + 0.05 * rng.normal(size=(1024,)).astype(np.float32)
    m0 = LinearRegressionWithSGD.train((X, y), num_iterations=40,
                                       step_size=0.3, intercept=True)
    m1 = LinearRegressionWithSGD.train((X, y), num_iterations=40,
                                       step_size=0.3, intercept=True,
                                       sufficient_stats=True)
    np.testing.assert_allclose(np.asarray(m1.weights),
                               np.asarray(m0.weights),
                               rtol=1e-3, atol=1e-3)
    assert float(m1.intercept) == pytest.approx(float(m0.intercept),
                                                abs=1e-3)


def test_same_shape_different_matrix_never_binds(rng):
    """Review finding: a DIFFERENT matrix with the same shape/dtype must
    not silently train against stale statistics — identity binding."""
    X, y, w = _data(rng, n=400, d=8)
    gram = GramLeastSquaresGradient.build(X, y, block_rows=128)
    X2 = jnp.asarray(np.asarray(X) + 1.0)  # same shape, same dtype
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        g1, l1, _ = gram.window_sums(X2, y, w, jnp.int32(0), 200)
    assert any(issubclass(r.category, RuntimeWarning) for r in rec)
    g0, l0, _ = LeastSquaresGradient().window_sums(
        X2, y, w, jnp.int32(0), 200)
    # fell back to the stock path ON X2 (not X's stats): bitwise equal
    np.testing.assert_array_equal(np.asarray(g1), np.asarray(g0))
    assert float(l1) == float(l0)


def test_prebuilt_gram_routes_gramdata_through_optimizer(rng):
    """Passing a user-built gram gradient with its bound matrix must
    accelerate (GramData routed into the traced program), not fall back."""
    X, y, _ = _data(rng, n=2048, d=16)
    gram = GramLeastSquaresGradient.build(X, y, block_rows=256)
    opt = (GradientDescent(gram, SimpleUpdater())
           .set_step_size(0.2).set_num_iterations(10)
           .set_mini_batch_fraction(0.25).set_sampling("sliced")
           .set_convergence_tol(0.0))
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        w1, h1 = opt.optimize_with_history((X, y), jnp.zeros((16,)))
    assert not any(issubclass(r.category, RuntimeWarning) for r in rec)
    opt0 = (GradientDescent(LeastSquaresGradient(), SimpleUpdater())
            .set_step_size(0.2).set_num_iterations(10)
            .set_mini_batch_fraction(0.25).set_sampling("sliced")
            .set_convergence_tol(0.0))
    w0, h0 = opt0.optimize_with_history((X, y), jnp.zeros((16,)))
    np.testing.assert_allclose(np.asarray(h1), np.asarray(h0),
                               rtol=5e-4, atol=5e-4)


def test_dp_mesh_sufficient_stats_trajectory_parity(rng):
    """Gram over the 1-D data mesh (config 4's 8-way DP shape) must match
    the stock mesh trajectory — per-shard prefix stats, same psums."""
    from tpu_sgd import data_mesh

    mesh = data_mesh()
    X, y, _ = _data(rng, n=4096, d=24)  # divides the 8-way axis

    def run(flag):
        opt = (GradientDescent(LeastSquaresGradient(), SimpleUpdater())
               .set_step_size(0.2).set_num_iterations(25)
               .set_mini_batch_fraction(0.2).set_sampling("sliced")
               .set_seed(11).set_convergence_tol(0.0)
               .set_mesh(mesh).set_sufficient_stats(flag))
        return opt, opt.optimize_with_history((X, y), jnp.zeros((24,)))

    _, (w0, h0) = run(False)
    opt1, (w1, h1) = run(True)
    assert opt1._gram_dp_entry is not None  # the dp path actually engaged
    np.testing.assert_allclose(np.asarray(h1), np.asarray(h0),
                               rtol=5e-4, atol=5e-4)
    np.testing.assert_allclose(np.asarray(w1), np.asarray(w0),
                               rtol=5e-4, atol=5e-4)
    # identity cache: re-optimize on the same arrays reuses the stats
    stats0 = opt1._gram_dp_entry[3]
    opt1.optimize_with_history((X, y), jnp.zeros((24,)))
    assert opt1._gram_dp_entry[3] is stats0


def test_dp_mesh_full_batch_and_padding_fallback(rng):
    from tpu_sgd import data_mesh

    mesh = data_mesh()
    # full batch, divisible
    X, y, _ = _data(rng, n=2048, d=12)
    o0 = (GradientDescent(LeastSquaresGradient(), SquaredL2Updater())
          .set_step_size(0.3).set_num_iterations(15).set_reg_param(0.01)
          .set_mesh(mesh))
    w0, h0 = o0.optimize_with_history((X, y), jnp.zeros((12,)))
    o1 = (GradientDescent(LeastSquaresGradient(), SquaredL2Updater())
          .set_step_size(0.3).set_num_iterations(15).set_reg_param(0.01)
          .set_mesh(mesh).set_sufficient_stats(True))
    w1, h1 = o1.optimize_with_history((X, y), jnp.zeros((12,)))
    assert o1._gram_dp_entry is not None
    np.testing.assert_allclose(np.asarray(h1), np.asarray(h0),
                               rtol=5e-4, atol=5e-4)

    # NON-divisible row count: padded -> valid mask -> gram must fall back
    Xp, yp, _ = _data(rng, n=2049, d=12)
    o2 = (GradientDescent(LeastSquaresGradient(), SquaredL2Updater())
          .set_step_size(0.3).set_num_iterations(8).set_reg_param(0.01)
          .set_mesh(mesh).set_sufficient_stats(True))
    w2, h2 = o2.optimize_with_history((Xp, yp), jnp.zeros((12,)))
    assert o2._gram_dp_entry is None  # fell back to the stock mesh path
    o3 = (GradientDescent(LeastSquaresGradient(), SquaredL2Updater())
          .set_step_size(0.3).set_num_iterations(8).set_reg_param(0.01)
          .set_mesh(mesh))
    w3, h3 = o3.optimize_with_history((Xp, yp), jnp.zeros((12,)))
    np.testing.assert_array_equal(np.asarray(h2), np.asarray(h3))


def test_unbound_executor_is_silent_on_plain_arrays(rng):
    """An unbound executor (data=None, the DP-mesh internal) must treat
    plain arrays as stock input with NO warning."""
    X, y, w = _data(rng, n=256, d=8)
    unbound = GramLeastSquaresGradient()
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        g1, l1, c1 = unbound.window_sums(X, y, w, jnp.int32(0), 64)
    assert not any(issubclass(r.category, RuntimeWarning) for r in rec)
    g0, l0, c0 = LeastSquaresGradient().window_sums(
        X, y, w, jnp.int32(0), 64)
    np.testing.assert_array_equal(np.asarray(g1), np.asarray(g0))


def test_meshed_listener_warns_sufficient_stats_not_applied(rng):
    from tpu_sgd import data_mesh
    from tpu_sgd.utils.events import CollectingListener

    mesh = data_mesh()
    X, y, _ = _data(rng, n=512, d=8)
    opt = (GradientDescent(LeastSquaresGradient(), SimpleUpdater())
           .set_num_iterations(2).set_mesh(mesh)
           .set_sufficient_stats(True)
           .set_listener(CollectingListener()))
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        opt.optimize_with_history((X, y), jnp.zeros((8,)))
    assert any("sufficient_stats is not applied" in str(r.message)
               for r in rec)


def test_dp_stats_builder_memoized(rng):
    from tpu_sgd import data_mesh
    from tpu_sgd.parallel.gram_parallel import _stats_builder

    mesh = data_mesh()
    before = _stats_builder.cache_info().currsize

    def run(Xr, yr):
        opt = (GradientDescent(LeastSquaresGradient(), SimpleUpdater())
               .set_num_iterations(2).set_mesh(mesh)
               .set_sufficient_stats(True))
        opt.optimize_with_history((Xr, yr), jnp.zeros((8,)))

    X1, y1, _ = _data(rng, n=512, d=8)
    X2, y2, _ = _data(rng, n=512, d=8)  # different data, same shape
    run(X1, y1)
    run(X2, y2)
    # one builder serves both datasets (jit caches per shape underneath)
    assert _stats_builder.cache_info().currsize <= before + 1


def test_odd_dimensions_and_blocks(rng):
    """Nothing in the math requires lane-friendly shapes: odd d, odd n,
    odd block size must all agree with the stock path."""
    n, d = 777, 37
    X = jnp.asarray(rng.normal(size=(n, d)).astype(np.float32))
    w = jnp.asarray(rng.uniform(-1, 1, size=(d,)).astype(np.float32))
    y = jnp.asarray(
        (np.asarray(X) @ np.asarray(w)
         + 0.1 * rng.normal(size=(n,))).astype(np.float32))
    gram = GramLeastSquaresGradient.build(X, y, block_rows=53)
    for start, m in [(0, 100), (51, 53), (700, 77), (123, 1)]:
        g0, l0, c0 = LeastSquaresGradient().window_sums(
            X, y, w, jnp.int32(start), m)
        g1, l1, c1 = gram.window_sums(X, y, w, jnp.int32(start), m)
        np.testing.assert_allclose(np.asarray(g1), np.asarray(g0),
                                   rtol=2e-4, atol=2e-2)
        assert float(c1) == float(c0)


def test_f64_data_keeps_f64_stats():
    """f64 data (jax_enable_x64) must get f64 statistics by default, not a
    silent f32 downgrade relative to the stock f64 path.  x64 is a global
    switch, so this runs in a subprocess."""
    import os
    import subprocess
    import sys

    code = (
        "import os; os.environ['XLA_FLAGS']=''; "
        "import jax; jax.config.update('jax_platforms','cpu'); "
        "jax.config.update('jax_enable_x64', True); "
        "import jax.numpy as jnp, numpy as np; "
        "from tpu_sgd.ops.gram import GramLeastSquaresGradient; "
        "X = jnp.asarray(np.random.default_rng(0).normal(size=(64,4))); "
        "y = jnp.asarray(np.random.default_rng(1).normal(size=(64,))); "
        "assert X.dtype == jnp.float64, X.dtype; "
        "g = GramLeastSquaresGradient.build(X, y, block_rows=16); "
        "assert g.data.PG.dtype == jnp.float64, g.data.PG.dtype; "
        "gs = GramLeastSquaresGradient.build_streamed("
        "    np.asarray(X), np.asarray(y), block_rows=16); "
        "assert gs.data.Pb.dtype == jnp.float64, gs.data.Pb.dtype; "
        "np.testing.assert_allclose(np.asarray(gs.data.Pb), "
        "    np.asarray(g.data.Pb), rtol=1e-12); "
        "print('OK')"
    )
    env = dict(os.environ)
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))) + os.pathsep + env.get("PYTHONPATH", "")
    r = subprocess.run([sys.executable, "-c", code], env=env, timeout=300,
                       capture_output=True, text=True)
    assert r.returncode == 0 and "OK" in r.stdout, r.stderr[-2000:]


def test_gram_composes_with_listener_and_checkpoint(rng, tmp_path):
    """Single-device observed path (listener / checkpoint) with the
    sufficient-stats flag: the stepwise driver receives GramData and must
    produce the same trajectory as the stock stepwise run."""
    from tpu_sgd.utils.checkpoint import CheckpointManager
    from tpu_sgd.utils.events import CollectingListener

    X, y, _ = _data(rng, n=1024, d=8)

    def run(flag, subdir):
        listener = CollectingListener()
        opt = (GradientDescent(LeastSquaresGradient(), SimpleUpdater())
               .set_step_size(0.2).set_num_iterations(6)
               .set_mini_batch_fraction(0.5).set_sampling("sliced")
               .set_convergence_tol(0.0)
               .set_listener(listener)
               .set_checkpoint(CheckpointManager(str(tmp_path / subdir)), 2)
               .set_sufficient_stats(flag))
        w, h = opt.optimize_with_history((X, y), jnp.zeros((8,)))
        return w, h, listener

    w0, h0, _ = run(False, "a")
    w1, h1, lis = run(True, "b")
    assert len(lis.iterations) == 6
    np.testing.assert_allclose(np.asarray(h1), np.asarray(h0),
                               rtol=5e-4, atol=5e-4)


# ---- streamed / virtual (beyond-HBM) mode --------------------------------

def test_build_streamed_matches_resident_build(rng):
    """Chunked host streaming must produce the SAME statistics as the
    resident build on the block-truncated dataset."""
    X = rng.normal(size=(1000, 12)).astype(np.float32)
    y = (X @ rng.uniform(-1, 1, 12).astype(np.float32)).astype(np.float32)
    gs = GramLeastSquaresGradient.build_streamed(X, y, block_rows=64,
                                                 batch_rows=200)
    n_use = (1000 // 64) * 64  # 960
    g0 = GramLeastSquaresGradient.build(X[:n_use], y[:n_use], block_rows=64)
    assert gs.data.X is None
    assert gs.data.shape == (n_use, 12)
    np.testing.assert_allclose(np.asarray(gs.data.PG),
                               np.asarray(g0.data.PG), rtol=1e-6, atol=1e-3)
    np.testing.assert_allclose(np.asarray(gs.data.Pb),
                               np.asarray(g0.data.Pb), rtol=1e-6, atol=1e-4)
    np.testing.assert_allclose(np.asarray(gs.data.G_tot),
                               np.asarray(g0.data.G_tot),
                               rtol=1e-6, atol=1e-3)


def test_aligned_window_math_vs_numpy(rng):
    X = rng.normal(size=(512, 8)).astype(np.float32)
    w = rng.uniform(-1, 1, 8).astype(np.float32)
    y = (X @ w + 0.1 * rng.normal(size=512)).astype(np.float32)
    B = 64
    gram = GramLeastSquaresGradient.build_streamed(X, y, block_rows=B)
    m = 130  # rounds to 2 blocks = 128 rows
    start = 70  # floors to block 1 -> rows [64, 192)
    g1, l1, c1 = gram.window_sums(gram.data, jnp.asarray(y), jnp.asarray(w),
                                  jnp.int32(start), m)
    rows = slice(64, 192)
    r = X[rows] @ w - y[rows]
    np.testing.assert_allclose(np.asarray(g1), X[rows].T @ r,
                               rtol=1e-4, atol=1e-2)
    assert float(l1) == pytest.approx(0.5 * float(r @ r), rel=1e-4)
    assert float(c1) == 128


def test_virtual_full_batch_matches_stock_on_truncated(rng):
    X = rng.normal(size=(960, 10)).astype(np.float32)
    wt = rng.uniform(-1, 1, 10).astype(np.float32)
    y = (X @ wt + 0.05 * rng.normal(size=960)).astype(np.float32)
    gram = GramLeastSquaresGradient.build_streamed(X, y, block_rows=64)

    opt_v = GradientDescent(gram, SquaredL2Updater()) \
        .set_step_size(0.3).set_num_iterations(20).set_reg_param(0.01)
    wv, hv = opt_v.optimize_with_history((gram.data, y), np.zeros(10))
    opt_s = GradientDescent(LeastSquaresGradient(), SquaredL2Updater()) \
        .set_step_size(0.3).set_num_iterations(20).set_reg_param(0.01)
    ws, hs = opt_s.optimize_with_history((X, y), np.zeros(10))
    np.testing.assert_allclose(np.asarray(hv), np.asarray(hs),
                               rtol=5e-4, atol=5e-4)
    np.testing.assert_allclose(np.asarray(wv), np.asarray(ws),
                               rtol=5e-4, atol=5e-4)


def test_virtual_sliced_gd_converges(rng):
    X = rng.normal(size=(8192, 16)).astype(np.float32)
    wt = rng.uniform(-1, 1, 16).astype(np.float32)
    y = (X @ wt + 0.05 * rng.normal(size=8192)).astype(np.float32)
    gram = GramLeastSquaresGradient.build_streamed(X, y, block_rows=256)
    opt = (GradientDescent(gram, SimpleUpdater())
           .set_step_size(0.3).set_num_iterations(60)
           .set_mini_batch_fraction(0.125).set_sampling("sliced")
           .set_convergence_tol(0.0))
    w, hist = opt.optimize_with_history((gram.data, y), np.zeros(16))
    werr = float(np.linalg.norm(np.asarray(w) - wt) / np.linalg.norm(wt))
    assert werr < 0.05, werr
    assert hist[-1] < hist[0] * 0.1


def test_virtual_lbfgs_full_batch(rng):
    X = rng.normal(size=(2048, 12)).astype(np.float32)
    wt = rng.uniform(-1, 1, 12).astype(np.float32)
    y = (X @ wt + 0.05 * rng.normal(size=2048)).astype(np.float32)
    gram = GramLeastSquaresGradient.build_streamed(X, y, block_rows=128)
    opt = LBFGS(gram, SquaredL2Updater(), reg_param=0.001,
                max_num_iterations=15)
    w, hist = opt.optimize_with_history((gram.data, y), np.zeros(12))
    werr = float(np.linalg.norm(np.asarray(w) - wt) / np.linalg.norm(wt))
    assert werr < 0.02, werr


def test_virtual_guards(rng):
    X = rng.normal(size=(256, 8)).astype(np.float32)
    y = rng.normal(size=256).astype(np.float32)
    gram = GramLeastSquaresGradient.build_streamed(X, y, block_rows=64)
    # bernoulli sub-unit sampling: clear error
    opt = (GradientDescent(gram, SimpleUpdater())
           .set_num_iterations(2).set_mini_batch_fraction(0.5))
    with pytest.raises(NotImplementedError, match="sliced"):
        opt.optimize((gram.data, y), np.zeros(8))
    # mesh: clear error
    from tpu_sgd import data_mesh
    opt2 = GradientDescent(gram, SimpleUpdater()).set_mesh(data_mesh())
    with pytest.raises(NotImplementedError, match="single-device"):
        opt2.optimize((gram.data, y), np.zeros(8))
    # plain gradient with GramData input: clear error
    opt3 = GradientDescent(LeastSquaresGradient(), SimpleUpdater())
    with pytest.raises(ValueError, match="GramLeastSquaresGradient"):
        opt3.optimize((gram.data, y), np.zeros(8))
    # masked call on virtual data: clear error
    valid = jnp.ones((256,), jnp.float32)
    with pytest.raises(NotImplementedError, match="virtual"):
        gram.window_sums(gram.data, jnp.asarray(y), jnp.zeros(8),
                         jnp.int32(0), 64, valid=valid)
    # meshed LBFGS on GramData: clear error
    lb = LBFGS(gram, SquaredL2Updater()).set_mesh(data_mesh())
    with pytest.raises(NotImplementedError, match="unmeshed"):
        lb.optimize_with_history((gram.data, y), np.zeros(8))


def test_resident_aligned_mode(rng):
    """aligned=True on RESIDENT data: same prefix-only math as the
    virtual path — results match the exact sums over the quantized
    window, and converge like the exact mode on i.i.d. data."""
    X, y, w = _data(rng, n=2048, d=16)
    gram = GramLeastSquaresGradient.build(X, y, block_rows=128,
                                          aligned=True)
    g1, l1, c1 = gram.window_sums(X, y, w, jnp.int32(200), 300)
    # start 200 floors to block 1 (128); 300 rows round to 2 blocks (256)
    rows = slice(128, 384)
    Xn, yn = np.asarray(X), np.asarray(y)
    r = Xn[rows] @ np.asarray(w) - yn[rows]
    np.testing.assert_allclose(np.asarray(g1), Xn[rows].T @ r,
                               rtol=1e-4, atol=1e-2)
    assert float(c1) == 256

    opt = (GradientDescent(gram, SimpleUpdater())
           .set_step_size(0.3).set_num_iterations(40)
           .set_mini_batch_fraction(0.25).set_sampling("sliced")
           .set_convergence_tol(0.0))
    wv, hist = opt.optimize_with_history((X, y), jnp.zeros((16,)))
    assert hist[-1] < hist[0] * 0.1


def test_lbfgs_gramdata_with_stock_gradient_clear_error(rng):
    X = jnp.asarray(rng.normal(size=(128, 8)).astype(np.float32))
    y = jnp.asarray(rng.normal(size=128).astype(np.float32))
    gram = GramLeastSquaresGradient.build(X, y, block_rows=32)
    lb = LBFGS(LeastSquaresGradient(), SquaredL2Updater())
    with pytest.raises(ValueError, match="GramLeastSquaresGradient"):
        lb.optimize_with_history((gram.data, y), np.zeros(8))


def test_virtual_gramdata_requires_logical_metadata():
    from tpu_sgd.ops.gram import GramData

    z = jnp.zeros((2, 4, 4))
    with pytest.raises(ValueError, match="logical_shape"):
        GramData(None, z, jnp.zeros((2, 4)), jnp.zeros((2,)),
                 jnp.zeros((4, 4)), jnp.zeros((4,)), jnp.zeros(()), 4)


def test_build_rejects_bad_rank_and_streamed_int_features(rng):
    with pytest.raises(ValueError, match="non-empty"):
        GramLeastSquaresGradient.build(jnp.zeros((8,)), jnp.zeros((8,)))
    # int features through the streamed builder coerce to f32 stats
    Xi = rng.integers(0, 3, size=(256, 6)).astype(np.int32)
    yi = rng.normal(size=256).astype(np.float32)
    g = GramLeastSquaresGradient.build_streamed(Xi, yi, block_rows=64)
    assert g.data.dtype == jnp.float32
    assert g.data.PG.dtype == jnp.float32


def test_gramdata_save_load_round_trip(rng, tmp_path):
    """Statistics persist (streamed builds are expensive) and load back
    VIRTUAL — training from the loaded bundle matches training from the
    original."""
    from tpu_sgd.ops.gram import GramData

    X = rng.normal(size=(512, 8)).astype(np.float32)
    wt = rng.uniform(-1, 1, 8).astype(np.float32)
    y = (X @ wt + 0.05 * rng.normal(size=512)).astype(np.float32)
    g0 = GramLeastSquaresGradient.build_streamed(X, y, block_rows=64)
    p = str(tmp_path / "stats")
    g0.data.save(p)
    data = GramData.load(p)
    assert data.X is None and data.shape == g0.data.shape
    g1 = GramLeastSquaresGradient(data)

    def run(gg):
        opt = (GradientDescent(gg, SimpleUpdater())
               .set_step_size(0.3).set_num_iterations(20)
               .set_mini_batch_fraction(0.25).set_sampling("sliced"))
        return opt.optimize_with_history((gg.data, y), np.zeros(8))

    w0, h0 = run(g0)
    w1, h1 = run(g1)
    np.testing.assert_allclose(np.asarray(h1), np.asarray(h0),
                               rtol=1e-6, atol=1e-6)

    # wrong-class / wrong-version guards
    import json
    meta = json.load(open(p + "/metadata.json"))
    meta["class"] = "SomethingElse"
    json.dump(meta, open(p + "/metadata.json", "w"))
    with pytest.raises(ValueError, match="expected GramData"):
        GramData.load(p)


def test_gram_random_shape_window_parity_sweep(rng):
    """Randomized breadth: arbitrary (n, d, B, start, m) combinations must
    reproduce the stock window sums — catches shape/edge interactions the
    parametrized grid doesn't enumerate."""
    for _ in range(12):
        n = int(rng.integers(40, 1500))
        d = int(rng.integers(2, 40))
        B = int(rng.integers(8, n + 8))
        X = jnp.asarray(rng.normal(size=(n, d)).astype(np.float32))
        w = jnp.asarray(rng.uniform(-1, 1, d).astype(np.float32))
        y = jnp.asarray(
            (np.asarray(X) @ np.asarray(w)
             + 0.1 * rng.normal(size=n)).astype(np.float32))
        gram = GramLeastSquaresGradient.build(X, y, block_rows=B)
        for _ in range(3):
            m = int(rng.integers(1, n + 1))
            start = int(rng.integers(0, n))
            g0, l0, c0 = LeastSquaresGradient().window_sums(
                X, y, w, jnp.int32(start), m)
            g1, l1, c1 = gram.window_sums(X, y, w, jnp.int32(start), m)
            scale = max(1.0, float(jnp.max(jnp.abs(g0))))
            np.testing.assert_allclose(
                np.asarray(g1), np.asarray(g0), rtol=5e-4,
                atol=5e-3 * scale,
                err_msg=f"n={n} d={d} B={B} start={start} m={m}")
            assert float(c1) == float(c0)


def test_virtual_gramdata_with_listener_and_checkpoint(rng, tmp_path):
    """Beyond-HBM stats + the observed per-iteration path: the stepwise
    driver must accept a virtual GramData as X (listener events fire,
    checkpoints save/restore weights)."""
    from tpu_sgd.utils.checkpoint import CheckpointManager
    from tpu_sgd.utils.events import CollectingListener

    X = rng.normal(size=(512, 8)).astype(np.float32)
    wt = rng.uniform(-1, 1, 8).astype(np.float32)
    y = (X @ wt + 0.05 * rng.normal(size=512)).astype(np.float32)
    gram = GramLeastSquaresGradient.build_streamed(X, y, block_rows=64)
    listener = CollectingListener()
    opt = (GradientDescent(gram, SimpleUpdater())
           .set_step_size(0.3).set_num_iterations(6)
           .set_mini_batch_fraction(0.25).set_sampling("sliced")
           .set_convergence_tol(0.0)
           .set_listener(listener)
           .set_checkpoint(CheckpointManager(str(tmp_path / "ck")), 2))
    w, hist = opt.optimize_with_history((gram.data, y), np.zeros(8))
    assert len(listener.iterations) == 6
    assert len(hist) == 6 and hist[-1] < hist[0]


def test_feature_scaling_composes_with_sufficient_stats(rng):
    """GLM feature scaling rescales the training matrix before the
    optimizer sees it; the gram substitution must build on the SCALED
    matrix and produce the same model as the unaccelerated scaled run."""
    from tpu_sgd import LinearRegressionWithLBFGS

    X = (rng.normal(size=(1024, 12)) * np.logspace(0, 3, 12)).astype(
        np.float32)
    wt = (rng.uniform(-1, 1, 12) / np.logspace(0, 3, 12)).astype(np.float32)
    y = (X @ wt + 0.01 * rng.normal(size=1024)).astype(np.float32)
    m0 = LinearRegressionWithLBFGS.train((X, y), feature_scaling=True,
                                         intercept=True)
    m1 = LinearRegressionWithLBFGS.train((X, y), feature_scaling=True,
                                         intercept=True,
                                         sufficient_stats=True)
    np.testing.assert_allclose(np.asarray(m1.weights),
                               np.asarray(m0.weights), rtol=1e-3,
                               atol=1e-6)


def test_unbound_gram_gradient_runs_stock_in_optimizers(rng):
    """ADVICE r3 (medium): an UNBOUND ``GramLeastSquaresGradient(data=None)``
    — the documented DP-mesh constructor mode — handed to GradientDescent,
    LBFGS, or OWLQN with a plain matrix must fall through to the stock
    path bitwise, not crash the gram-substitution identity check with an
    AttributeError on ``None.X``."""
    from tpu_sgd.optimize.owlqn import OWLQN

    X, y, _ = _data(rng, n=256, d=8)
    w0 = jnp.zeros((8,))

    def gd(gradient):
        opt = (GradientDescent(gradient, SimpleUpdater())
               .set_step_size(0.2).set_num_iterations(8)
               .set_convergence_tol(0.0))
        return opt.optimize_with_history((X, y), w0)

    ws, hs = gd(LeastSquaresGradient())
    wu, hu = gd(GramLeastSquaresGradient())
    np.testing.assert_array_equal(np.asarray(wu), np.asarray(ws))
    np.testing.assert_array_equal(np.asarray(hu), np.asarray(hs))

    ws, hs = LBFGS(LeastSquaresGradient()).set_max_num_iterations(
        5).optimize_with_history((X, y), w0)
    wu, hu = LBFGS(GramLeastSquaresGradient()).set_max_num_iterations(
        5).optimize_with_history((X, y), w0)
    np.testing.assert_array_equal(np.asarray(wu), np.asarray(ws))
    np.testing.assert_array_equal(np.asarray(hu), np.asarray(hs))

    wu, hu = OWLQN(GramLeastSquaresGradient(), reg_param=0.01,
                   max_num_iterations=5).optimize_with_history((X, y), w0)
    assert np.all(np.isfinite(np.asarray(wu))) and len(hu) >= 1


def test_release_sufficient_stats_frees_cache(rng):
    """``release_sufficient_stats`` drops the identity-cached bundles (and
    gram-keyed compiled runners); the next run rebuilds and reproduces the
    same trajectory."""
    X, y, _ = _data(rng, n=512, d=8)

    opt = (GradientDescent(LeastSquaresGradient(), SimpleUpdater())
           .set_step_size(0.2).set_num_iterations(6)
           .set_convergence_tol(0.0).set_sufficient_stats(True))
    w1, h1 = opt.optimize_with_history((X, y), jnp.zeros((8,)))
    assert opt._gram_entry is not None
    opt.release_sufficient_stats()
    assert opt._gram_entry is None and opt._gram_dp_entry is None
    assert not any(
        isinstance(part, GramLeastSquaresGradient)
        for k in opt._run_cache for part in k
    )
    w2, h2 = opt.optimize_with_history((X, y), jnp.zeros((8,)))
    np.testing.assert_array_equal(np.asarray(w2), np.asarray(w1))

    lb = (LBFGS(LeastSquaresGradient()).set_max_num_iterations(5)
          .set_sufficient_stats(True))
    lb.optimize_with_history((X, y), jnp.zeros((8,)))
    assert lb._gram_entry is not None
    lb.release_sufficient_stats()
    assert lb._gram_entry is None


# ---- streamed statistics composed with the data mesh (round 4) -----------

def test_build_streamed_sharded_stats_match_per_shard_resident(rng):
    """Each shard's streamed-from-host statistics must equal the resident
    build of that shard's (block-truncated) row slice — uneven row counts
    drop the n % k remainder plus per-shard tails, like the single-device
    build_streamed."""
    from tpu_sgd import data_mesh
    from tpu_sgd.parallel.gram_parallel import (
        build_streamed_sharded_gram_stats,
    )

    mesh = data_mesh()
    k = mesh.shape["data"]
    n, d, B = k * 300 + 5, 6, 64
    X = rng.normal(size=(n, d)).astype(np.float32)
    y = rng.normal(size=(n,)).astype(np.float32)
    stats, Bout, n_used = build_streamed_sharded_gram_stats(
        mesh, X, y, block_rows=B, batch_rows=128)
    n_local = n // k
    assert Bout == B and n_used == (n_local // B) * B
    PG, Pb, _, Gt, bt, yyt = (np.asarray(s) for s in stats)
    for i in range(k):
        s = i * n_local
        g = GramLeastSquaresGradient.build(
            X[s:s + n_used], y[s:s + n_used], block_rows=B)
        np.testing.assert_allclose(PG[i], np.asarray(g.data.PG),
                                   rtol=1e-5, atol=1e-3)
        np.testing.assert_allclose(Pb[i], np.asarray(g.data.Pb),
                                   rtol=1e-5, atol=1e-4)
        np.testing.assert_allclose(Gt[i], np.asarray(g.data.G_tot),
                                   rtol=1e-5, atol=1e-3)
        np.testing.assert_allclose(yyt[i], float(g.data.yy_tot),
                                   rtol=1e-5)


def test_sharded_build_rejects_dataless_mesh(rng):
    """A mesh WITHOUT a 'data' axis must raise the intended
    NotImplementedError, not a bare KeyError from reading
    mesh.shape['data'] before the axes check (ADVICE r4)."""
    import jax
    from jax.sharding import Mesh

    from tpu_sgd.parallel.gram_parallel import (
        build_streamed_sharded_gram_stats,
    )
    from tpu_sgd.parallel.mesh import MODEL_AXIS

    mesh = Mesh(np.array(jax.devices()[:2]), (MODEL_AXIS,))
    X = rng.normal(size=(64, 4)).astype(np.float32)
    y = rng.normal(size=(64,)).astype(np.float32)
    with pytest.raises(NotImplementedError, match="1-D 'data' mesh"):
        build_streamed_sharded_gram_stats(mesh, X, y, block_rows=16)


def test_streamed_stats_mesh_matches_resident_aligned_dp(rng):
    """Meshed set_streamed_stats (per-shard VIRTUAL stats built from host
    row streams, zero rows on device) must reproduce the meshed RESIDENT
    aligned-gram trajectory: same per-shard block-floored windows, same
    statistics math (VERDICT r3 #2)."""
    from tpu_sgd import data_mesh

    mesh = data_mesh()
    k = mesh.shape["data"]
    n, d, B = k * 512, 8, 64  # divisible everywhere: no truncation
    X = rng.normal(size=(n, d)).astype(np.float32)
    wt = rng.uniform(-1, 1, d).astype(np.float32)
    y = (X @ wt + 0.05 * rng.normal(size=n)).astype(np.float32)

    def mk():
        return (GradientDescent(LeastSquaresGradient(), SimpleUpdater())
                .set_step_size(0.3).set_num_iterations(20)
                .set_mini_batch_fraction(0.25).set_sampling("sliced")
                .set_convergence_tol(0.0).set_seed(9).set_mesh(mesh)
                .set_gram_options(block_rows=B))

    opt_v = mk().set_streamed_stats(True)
    w_v, h_v = opt_v.optimize_with_history((X, y), jnp.zeros((d,)))
    assert opt_v._streamed_gram_dp_entry is not None

    opt_r = mk().set_sufficient_stats(True).set_gram_options(aligned=True)
    w_r, h_r = opt_r.optimize_with_history((X, y), jnp.zeros((d,)))
    assert opt_r._gram_dp_entry is not None

    np.testing.assert_allclose(np.asarray(h_v), np.asarray(h_r),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(w_v), np.asarray(w_r),
                               rtol=1e-5, atol=1e-6)
    assert h_v[-1] < h_v[0]  # and it actually optimizes


def test_streamed_stats_mesh_build_is_identity_cached(rng):
    from tpu_sgd import data_mesh

    mesh = data_mesh()
    n, d = 8 * 128, 6
    X = rng.normal(size=(n, d)).astype(np.float32)
    y = rng.normal(size=(n,)).astype(np.float32)
    opt = (GradientDescent(LeastSquaresGradient(), SimpleUpdater())
           .set_num_iterations(3).set_convergence_tol(0.0)
           .set_mesh(mesh).set_streamed_stats(True, block_rows=32))
    opt.optimize((X, y), jnp.zeros((d,)))
    entry1 = opt._streamed_gram_dp_entry
    opt.optimize((X, y), jnp.zeros((d,)))
    assert opt._streamed_gram_dp_entry is entry1  # no rebuild
    opt.release_sufficient_stats()
    assert opt._streamed_gram_dp_entry is None


# ---- resumable streamed build (round 5: VERDICT r4 #4) ---------------------

def test_build_streamed_resumable_bitwise(rng, tmp_path):
    """A streamed build killed after chunk j must resume from its
    high-water block and produce BITWISE-identical statistics — RDD
    lineage replay semantics for the one expensive pass (a 278 s build
    through this environment's tunnel restarts from zero otherwise)."""
    from tpu_sgd.ops import gram as gram_mod

    n, d, B = 1000, 6, 32
    X = rng.normal(size=(n, d)).astype(np.float32)
    y = rng.normal(size=(n,)).astype(np.float32)

    ref = GramLeastSquaresGradient.build_streamed(
        X, y, block_rows=B, batch_rows=128)

    # kill the build partway: the 3rd per-chunk prefix computation dies
    resume_dir = str(tmp_path / "ckpt")
    calls = {"n": 0}
    real = gram_mod._chunk_prefix

    def dying(*args):
        calls["n"] += 1
        if calls["n"] == 3:
            raise RuntimeError("simulated tunnel wedge")
        return real(*args)

    gram_mod._chunk_prefix = dying
    try:
        with pytest.raises(RuntimeError, match="wedge"):
            GramLeastSquaresGradient.build_streamed(
                X, y, block_rows=B, batch_rows=128,
                resume_dir=resume_dir)
    finally:
        gram_mod._chunk_prefix = real
    import json
    import os

    with open(os.path.join(resume_dir, "meta.json")) as f:
        meta = json.load(f)
    assert 0 < meta["high_water_rows"] < (n // B) * B  # mid-pass state

    resumed = GramLeastSquaresGradient.build_streamed(
        X, y, block_rows=B, batch_rows=128, resume_dir=resume_dir)
    for leaf in ("PG", "Pb", "Pyy", "G_tot", "b_tot", "yy_tot"):
        np.testing.assert_array_equal(
            np.asarray(getattr(resumed.data, leaf)),
            np.asarray(getattr(ref.data, leaf)), err_msg=leaf)
    assert not os.path.exists(resume_dir)  # finalized: parts cleaned up


def test_build_streamed_resume_rejects_mismatched_geometry(rng, tmp_path):
    X = rng.normal(size=(256, 4)).astype(np.float32)
    y = rng.normal(size=(256,)).astype(np.float32)
    resume_dir = str(tmp_path / "ckpt")
    from tpu_sgd.ops.gram import _PrefixBuildCheckpoint

    ck = _PrefixBuildCheckpoint(resume_dir, n_used=256, d=4, B=32,
                                sd_name="float32", chunk=64)
    ck.save_part(0, np.zeros((2, 4, 4), np.float32),
                 np.zeros((2, 4), np.float32),
                 np.zeros((2,), np.float32), high_water_rows=64)
    with pytest.raises(ValueError, match="different build"):
        GramLeastSquaresGradient.build_streamed(
            X, y, block_rows=16, resume_dir=resume_dir)


def test_sharded_streamed_build_resumable(rng, tmp_path):
    """The per-shard mesh builder checkpoints each shard independently
    (resume_dir/shard_i) and a full re-run from checkpoints matches the
    uninterrupted build."""
    from tpu_sgd import data_mesh
    from tpu_sgd.parallel.gram_parallel import (
        build_streamed_sharded_gram_stats,
    )

    mesh = data_mesh()
    k = mesh.shape["data"]
    n, d, B = k * 160, 5, 32
    X = rng.normal(size=(n, d)).astype(np.float32)
    y = rng.normal(size=(n,)).astype(np.float32)
    ref, Bout, n_used = build_streamed_sharded_gram_stats(
        mesh, X, y, block_rows=B, batch_rows=64)
    resume_dir = str(tmp_path / "shards")
    # first pass persists per-shard parts; second pass resumes (and since
    # the first completed+finalized, it rebuilds — both must agree with
    # the checkpoint-free build bitwise)
    got, _, _ = build_streamed_sharded_gram_stats(
        mesh, X, y, block_rows=B, batch_rows=64, resume_dir=resume_dir)
    for a, b in zip(ref, got):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_build_streamed_resume_rejects_different_dataset(rng, tmp_path):
    """A stale resume_dir from a DIFFERENT same-shaped dataset must be
    rejected (dataset fingerprint in the meta) — replaying another
    dataset's chunks would silently corrupt the statistics
    (code-review r5)."""
    from tpu_sgd.ops import gram as gram_mod

    n, d, B = 512, 5, 32
    XA = rng.normal(size=(n, d)).astype(np.float32)
    XB = rng.normal(size=(n, d)).astype(np.float32)  # same shape/dtype
    y = rng.normal(size=(n,)).astype(np.float32)
    resume_dir = str(tmp_path / "ckpt")

    calls = {"n": 0}
    real = gram_mod._chunk_prefix

    def dying(*args):
        calls["n"] += 1
        if calls["n"] == 2:
            raise RuntimeError("simulated wedge")
        return real(*args)

    gram_mod._chunk_prefix = dying
    try:
        with pytest.raises(RuntimeError, match="wedge"):
            GramLeastSquaresGradient.build_streamed(
                XA, y, block_rows=B, batch_rows=64,
                resume_dir=resume_dir)
    finally:
        gram_mod._chunk_prefix = real
    with pytest.raises(ValueError, match="different build"):
        GramLeastSquaresGradient.build_streamed(
            XB, y, block_rows=B, batch_rows=64, resume_dir=resume_dir)


# ---- chunked-gather driver (round 5) ---------------------------------------

def _chunked_setup(rng, n=4096, d=12, B=256):
    X = rng.normal(size=(n, d)).astype(np.float32)
    w = rng.uniform(-1, 1, d).astype(np.float32)
    y = (X @ w + 0.05 * rng.normal(size=n)).astype(np.float32)
    return X, y


@pytest.mark.parametrize("chunk_iters", [1, 7, 16])
def test_chunked_driver_matches_per_iteration_aligned(rng, chunk_iters):
    """The chunked-gather driver must reproduce the per-iteration
    aligned-gram trajectory exactly (same fold_in window stream, same
    prefix-difference math) — including chunk sizes that do not divide
    the iteration count."""
    X, y = _chunked_setup(rng)

    def make(chunked):
        opt = (GradientDescent(LeastSquaresGradient(), SimpleUpdater())
               .set_step_size(0.3).set_num_iterations(30)
               .set_mini_batch_fraction(0.1).set_sampling("sliced")
               .set_seed(11).set_convergence_tol(0.0)
               .set_streamed_stats(True, block_rows=256))
        if chunked:
            opt.set_gram_options(chunk_iters=chunk_iters)
        return opt

    w0, h0 = make(False).optimize_with_history(
        (X, y), np.zeros(12, np.float32))
    w1, h1 = make(True).optimize_with_history(
        (X, y), np.zeros(12, np.float32))
    assert len(h0) == len(h1) == 30
    np.testing.assert_allclose(np.asarray(h1), np.asarray(h0),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(w1), np.asarray(w0),
                               rtol=1e-5, atol=1e-6)


def test_chunked_driver_convergence_contract(rng):
    """With convergence_tol > 0 the chunked driver must record EXACTLY
    as many losses as the per-iteration driver (post-convergence updates
    inside a chunk are masked to no-ops)."""
    X, y = _chunked_setup(rng)

    def make(chunked):
        opt = (GradientDescent(LeastSquaresGradient(), SimpleUpdater())
               .set_step_size(0.5).set_num_iterations(200)
               .set_mini_batch_fraction(0.1).set_sampling("sliced")
               .set_seed(5).set_convergence_tol(1e-4)
               .set_streamed_stats(True, block_rows=256))
        if chunked:
            opt.set_gram_options(chunk_iters=16)
        return opt

    w0, h0 = make(False).optimize_with_history(
        (X, y), np.zeros(12, np.float32))
    w1, h1 = make(True).optimize_with_history(
        (X, y), np.zeros(12, np.float32))
    assert 0 < len(h0) < 200  # converged early — the contract under test
    assert len(h1) == len(h0)
    np.testing.assert_allclose(np.asarray(h1), np.asarray(h0),
                               rtol=1e-5, atol=1e-6)


def test_chunked_driver_resident_aligned(rng):
    """Resident statistics in ALIGNED mode route through the chunked
    driver too; exact (unaligned) mode ignores the knob."""
    X, y = _chunked_setup(rng, n=2048)

    def make(aligned):
        return (GradientDescent(LeastSquaresGradient(), SimpleUpdater())
                .set_step_size(0.3).set_num_iterations(20)
                .set_mini_batch_fraction(0.1).set_sampling("sliced")
                .set_seed(3).set_convergence_tol(0.0)
                .set_sufficient_stats(True)
                .set_gram_options(block_rows=256, aligned=aligned,
                                  chunk_iters=8))

    opt_a = make(True)
    w_a, h_a = opt_a.optimize_with_history((X, y), np.zeros(12, np.float32))
    assert any(k[0] == "chunked_gram_run" for k in opt_a._run_cache)
    ref = (GradientDescent(LeastSquaresGradient(), SimpleUpdater())
           .set_step_size(0.3).set_num_iterations(20)
           .set_mini_batch_fraction(0.1).set_sampling("sliced")
           .set_seed(3).set_convergence_tol(0.0)
           .set_sufficient_stats(True)
           .set_gram_options(block_rows=256, aligned=True))
    w_r, h_r = ref.optimize_with_history((X, y), np.zeros(12, np.float32))
    np.testing.assert_allclose(np.asarray(h_a), np.asarray(h_r),
                               rtol=1e-5, atol=1e-6)
    # exact mode: the knob is ignored (edge corrections need rows)
    opt_e = make(False)
    opt_e.optimize_with_history((X, y), np.zeros(12, np.float32))
    assert not any(k[0] == "chunked_gram_run" for k in opt_e._run_cache)


def test_chunk_iters_knob_validation_and_plan_ownership():
    from tpu_sgd import GradientDescent
    from tpu_sgd.plan import Plan

    with pytest.raises(ValueError, match="chunk_iters must be positive"):
        GradientDescent().set_gram_options(chunk_iters=0)
    # plan-owned reset unless user-set
    opt = GradientDescent()
    Plan("streamed_virtual_gram", "t", block_rows=32, aligned=True,
         chunk_iters=16).apply(opt)
    assert opt.gram_chunk_iters == 16
    Plan("resident_stock", "t").apply(opt)
    assert opt.gram_chunk_iters is None
    user = GradientDescent().set_gram_options(chunk_iters=8)
    Plan("streamed_virtual_gram", "t", block_rows=32,
         aligned=True).apply(user)
    assert user.gram_chunk_iters == 8  # user knob survives


def test_chunk_iters_meshed_warns_and_falls_back(rng):
    """chunk_iters is single-device-only: meshed gram runs warn once and
    keep the per-iteration driver rather than silently dropping the
    expected speedup (code-review r5)."""
    from tpu_sgd import data_mesh

    n, d = 2048, 8
    X = rng.normal(size=(n, d)).astype(np.float32)
    y = rng.normal(size=(n,)).astype(np.float32)
    opt = (GradientDescent(LeastSquaresGradient(), SimpleUpdater())
           .set_step_size(0.2).set_num_iterations(5)
           .set_mini_batch_fraction(0.25).set_sampling("sliced")
           .set_convergence_tol(0.0)
           .set_mesh(data_mesh())
           .set_sufficient_stats(True)
           .set_gram_options(block_rows=64, aligned=True, chunk_iters=8))
    with pytest.warns(RuntimeWarning, match="single-device"):
        w, h = opt.optimize_with_history((X, y), np.zeros(d, np.float32))
    assert np.all(np.isfinite(np.asarray(w)))
    assert not any(k[0] == "chunked_gram_run" for k in opt._run_cache)


def test_chunk_iters_listener_warns(rng):
    """The observed (listener) path warns that chunk_iters is ignored —
    chunking amortizes exactly the per-iteration host hop listeners
    provide."""
    from tpu_sgd.utils.events import SGDListener

    X, y = _chunked_setup(rng, n=512)
    opt = (GradientDescent(LeastSquaresGradient(), SimpleUpdater())
           .set_step_size(0.2).set_num_iterations(3)
           .set_mini_batch_fraction(0.25).set_sampling("sliced")
           .set_streamed_stats(True, block_rows=64)
           .set_gram_options(chunk_iters=8))
    opt.listener = SGDListener()
    with pytest.warns(RuntimeWarning, match="observed"):
        opt.optimize_with_history((X, y), np.zeros(12, np.float32))


def test_chunked_driver_ignores_optimizer_aligned_on_prebuilt_exact(rng):
    """A prebuilt EXACT (aligned=False) gram gradient runs exact windows
    per-iteration; ``set_gram_options(aligned=True, chunk_iters=K)`` on
    the OPTIMIZER configures future auto-builds and must NOT reroute the
    prebuilt gradient through the aligned chunked driver — that would
    switch the window math and silently change the trajectory the
    chunk_iters contract promises to preserve."""
    X, y = _chunked_setup(rng, n=2048)
    gram = GramLeastSquaresGradient.build(X, y, block_rows=256)

    def make(chunk):
        opt = (GradientDescent(gram, SimpleUpdater())
               .set_step_size(0.3).set_num_iterations(20)
               .set_mini_batch_fraction(0.1).set_sampling("sliced")
               .set_seed(7).set_convergence_tol(0.0))
        opt.set_gram_options(aligned=True,
                             chunk_iters=chunk if chunk else None)
        return opt

    opt_c = make(8)
    w_c, h_c = opt_c.optimize_with_history(
        (gram.data, y), np.zeros(12, np.float32))
    assert not any(k[0] == "chunked_gram_run" for k in opt_c._run_cache)
    opt_0 = make(None)
    w_0, h_0 = opt_0.optimize_with_history(
        (gram.data, y), np.zeros(12, np.float32))
    np.testing.assert_allclose(np.asarray(h_c), np.asarray(h_0),
                               rtol=1e-6, atol=1e-7)
    np.testing.assert_allclose(np.asarray(w_c), np.asarray(w_0),
                               rtol=1e-6, atol=1e-7)


def test_statistics_evaluator_dots_run_highest_precision(rng):
    """EVERY matmul inside the statistics evaluators must carry
    Precision.HIGHEST: the TPU default runs f32 operands through bf16
    passes, and near convergence the quadratic loss is a near-zero
    difference of ~||y||^2-magnitude terms — a default-precision dot's
    relative error dwarfs it (module docstring contract).  CPU runs
    full-precision dots either way, so this asserts the lowered jaxpr's
    precision attributes instead of numerics."""
    X, y, w = _data(rng)
    g = GramLeastSquaresGradient.build(X, y, block_rows=128)
    W = jnp.stack([w, 0.5 * w])
    evaluators = {
        "batch_sums": lambda: g.batch_sums(g.data, y, w),
        "loss_sweep": lambda: g.loss_sweep(g.data, y, W),
        "window_sums_exact": lambda: g.window_sums(
            g.data, y, w, jnp.int32(17), 256),
        "total_stats": lambda: GramLeastSquaresGradient._total_stats(
            jnp.asarray(X), jnp.asarray(y), B=128,
            stats_dtype=jnp.float32),
    }
    for name, fn in evaluators.items():
        s = str(jax.make_jaxpr(fn)())
        assert "dot_general" in s, name
        assert "precision=None" not in s, (
            f"{name} lowers a default-precision matmul")


def test_stats_dtype_rejects_non_floating(rng):
    """An int stats_dtype would silently truncate every element in the
    upcast; the resolver must reject the whole non-float family, not
    just sub-f32 floats."""
    X, y, _ = _data(rng)
    for bad in (jnp.int32, jnp.int16, bool):
        with pytest.raises(ValueError, match="floating"):
            GramLeastSquaresGradient.build(X, y, stats_dtype=bad)
    with pytest.raises(ValueError, match="float32 or wider"):
        GramLeastSquaresGradient.build(X, y, stats_dtype=jnp.bfloat16)


def test_single_block_virtual_stats_warn_on_sliced(rng):
    """A totals-only/single-block virtual bundle cannot express
    sub-batch windows — feeding it to sliced mini-batch GD silently
    runs full-batch iterations, and the driver must say so."""
    import warnings as _w

    from tpu_sgd import GradientDescent, SimpleUpdater

    X, y, _ = _data(rng, n=512, d=8)
    g = GramLeastSquaresGradient.build_streamed(X, y, block_rows=512)
    assert g.data.PG.shape[0] == 2  # single block by construction
    opt = (GradientDescent(g, SimpleUpdater())
           .set_step_size(0.1).set_num_iterations(3)
           .set_mini_batch_fraction(0.25).set_sampling("sliced"))
    with _w.catch_warnings(record=True) as rec:
        _w.simplefilter("always")
        opt.optimize_with_history((g.data, y), np.zeros(8, np.float32))
    assert any("degenerate to FULL-BATCH" in str(r.message) for r in rec)
