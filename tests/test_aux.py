"""Aux subsystems: listener/event tracing, checkpoint/resume (SURVEY.md §5)."""

import json
import os

import numpy as np
import pytest

from tpu_sgd.optimize.gradient_descent import GradientDescent
from tpu_sgd.ops.gradients import LeastSquaresGradient
from tpu_sgd.ops.updaters import SimpleUpdater
from tpu_sgd.utils.checkpoint import CheckpointManager
from tpu_sgd.utils.events import CollectingListener, JsonLinesEventLog
from tpu_sgd.utils.mlutils import linear_data


def _opt(iters=30, tol=0.0):
    return (
        GradientDescent(LeastSquaresGradient(), SimpleUpdater())
        .set_step_size(0.3)
        .set_num_iterations(iters)
        .set_convergence_tol(tol)
    )


def test_listener_receives_every_iteration():
    X, y, _ = linear_data(500, 5, seed=0)
    lst = CollectingListener()
    opt = _opt(25).set_listener(lst)
    w, hist = opt.optimize_with_history((X, y), np.zeros(5, np.float32))
    assert len(lst.iterations) == 25 == len(hist)
    assert [e.iteration for e in lst.iterations] == list(range(1, 26))
    np.testing.assert_allclose([e.loss for e in lst.iterations], hist, rtol=1e-6)
    assert all(e.mini_batch_size == 500 for e in lst.iterations)
    assert lst.runs[-1].event == "run_completed"
    assert lst.runs[-1].num_iterations == 25


def test_stepwise_path_matches_fused_path():
    """The observed path must preserve the exact optimizer semantics."""
    X, y, _ = linear_data(800, 6, seed=1)
    w0 = np.zeros(6, np.float32)
    w_fused, h_fused = _opt(30).optimize_with_history((X, y), w0)
    opt = _opt(30).set_listener(CollectingListener())
    w_step, h_step = opt.optimize_with_history((X, y), w0)
    np.testing.assert_allclose(np.asarray(w_step), np.asarray(w_fused),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(h_step, h_fused, rtol=1e-5)


def test_stepwise_convergence_early_exit():
    X, y, _ = linear_data(500, 5, eps=0.0, seed=2)
    lst = CollectingListener()
    opt = _opt(500, tol=1e-3).set_listener(lst)
    opt.optimize_with_history((X, y), np.zeros(5, np.float32))
    assert lst.runs[-1].converged_early
    assert lst.runs[-1].num_iterations < 500


def test_jsonl_event_log(tmp_path):
    X, y, _ = linear_data(300, 4, seed=3)
    path = str(tmp_path / "events.jsonl")
    log = JsonLinesEventLog(path)
    _opt(10).set_listener(log).optimize_with_history((X, y), np.zeros(4, np.float32))
    log.close()
    lines = [json.loads(l) for l in open(path)]
    kinds = [l["kind"] for l in lines]
    assert kinds[0] == "run_started" and kinds[-1] == "run_completed"
    assert kinds.count("iteration") == 10
    assert lines[0]["config"]["num_iterations"] == 10


def test_checkpoint_save_restore_roundtrip(tmp_path):
    mgr = CheckpointManager(str(tmp_path / "ck"))
    w = np.arange(4, dtype=np.float32)
    mgr.save(7, w, 0.5, np.asarray([3.0, 2.0]), "cfg")
    state = mgr.restore()
    assert state["iteration"] == 7 and state["reg_val"] == 0.5
    np.testing.assert_array_equal(state["weights"], w)
    np.testing.assert_array_equal(state["loss_history"], [3.0, 2.0])
    assert state["config_key"] == "cfg"


def test_checkpoint_prune_keeps_newest(tmp_path):
    mgr = CheckpointManager(str(tmp_path / "ck"), keep=2)
    for i in (1, 2, 3, 4):
        mgr.save(i, np.zeros(2, np.float32), 0.0, np.zeros(1))
    assert mgr.restore()["iteration"] == 4
    files = sorted(os.listdir(str(tmp_path / "ck")))
    assert len(files) == 2


def test_checkpointed_training_resumes(tmp_path):
    """Kill training mid-run; resume must continue from the checkpoint and
    reach the same result as an uninterrupted run."""
    X, y, _ = linear_data(600, 5, seed=4)
    w0 = np.zeros(5, np.float32)
    w_full, h_full = _opt(40).optimize_with_history((X, y), w0)

    ckdir = str(tmp_path / "ck")
    # phase 1: run only 20 iterations (simulated interruption)
    opt1 = _opt(20).set_checkpoint(CheckpointManager(ckdir), every=5)
    opt1.optimize_with_history((X, y), w0)
    # phase 2: new optimizer instance, full horizon, resumes at iter 21
    opt2 = _opt(40).set_checkpoint(CheckpointManager(ckdir), every=5)
    with pytest.warns(RuntimeWarning):  # config differs (20 vs 40 iters)
        w_res, h_res = opt2.optimize_with_history((X, y), w0)
    assert len(h_res) == 40
    np.testing.assert_allclose(np.asarray(w_res), np.asarray(w_full),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(h_res, h_full, rtol=1e-5)


def test_checkpoint_with_dp_mesh(tmp_path):
    from tpu_sgd.parallel.mesh import data_mesh

    X, y, _ = linear_data(640, 5, seed=5)
    w0 = np.zeros(5, np.float32)
    w_fused, h_fused = _opt(20).optimize_with_history((X, y), w0)
    opt = (
        _opt(20)
        .set_mesh(data_mesh())
        .set_checkpoint(CheckpointManager(str(tmp_path / "ck")), every=10)
        .set_listener(CollectingListener())
    )
    w_dp, h_dp = opt.optimize_with_history((X, y), w0)
    np.testing.assert_allclose(np.asarray(w_dp), np.asarray(w_fused),
                               rtol=2e-4, atol=1e-5)


def test_check_numerics_raises_on_divergence():
    """A wildly too-large step size diverges; the sanitizer flags it."""
    X, y, _ = linear_data(500, 5, seed=6)
    X = X * 100.0  # blow up the curvature
    opt = (
        GradientDescent(LeastSquaresGradient(), SimpleUpdater())
        .set_step_size(1000.0)
        .set_num_iterations(50)
        .set_convergence_tol(0.0)
        .set_check_numerics()
    )
    with pytest.raises(FloatingPointError, match="non-finite loss"):
        opt.optimize((X, y), np.zeros(5, np.float32))


def test_check_numerics_clean_run_passes():
    X, y, _ = linear_data(300, 4, seed=7)
    opt = (
        GradientDescent(LeastSquaresGradient(), SimpleUpdater())
        .set_step_size(0.3)
        .set_num_iterations(10)
        .set_check_numerics()
    )
    opt.optimize((X, y), np.zeros(4, np.float32))  # no raise


def test_distributed_helpers_single_process():
    from tpu_sgd.parallel.distributed import (
        global_data_mesh,
        process_count,
        process_index,
    )

    assert process_count() == 1 and process_index() == 0
    mesh = global_data_mesh()
    assert mesh.shape["data"] == 8  # all 8 virtual devices


def test_global_mesh_2d():
    import pytest

    from tpu_sgd.parallel.distributed import global_mesh_2d

    mesh = global_mesh_2d(n_model=2)
    assert dict(mesh.shape) == {"data": 4, "model": 2}
    with pytest.raises(ValueError, match="does not divide"):
        global_mesh_2d(n_model=3)


def test_step_timer():
    from tpu_sgd.utils.events import StepTimer

    t = StepTimer()
    with t.time():
        pass
    assert len(t.times) == 1 and t.mean_s >= 0


def test_profile_trace_writes_logdir(tmp_path):
    """profile_trace captures an XLA trace directory around a jitted call."""
    import jax.numpy as jnp

    from tpu_sgd.utils import profile_trace

    logdir = str(tmp_path / "tb")
    with profile_trace(logdir):
        (jnp.ones((8, 8)) @ jnp.ones((8, 8))).block_until_ready()
    found = list((tmp_path / "tb").rglob("*"))
    assert found, "no trace files written"


def test_checkpoint_ordering_survives_digit_rollover(tmp_path):
    """Filenames grow a digit at iteration 10^8; ordering must follow the
    PARSED iteration, or latest_path returns stale state and _prune
    deletes every new checkpoint as 'oldest'."""
    mgr = CheckpointManager(str(tmp_path / "ck"), keep=2)
    w = np.ones(4, np.float32)
    mgr.save(99_999_999, w, 0.0, [1.0])
    mgr.save(100_000_000, 2 * w, 0.0, [1.0, 0.5])
    assert mgr.latest_path().endswith("ckpt_100000000.npz")
    st = mgr.restore()
    assert st["iteration"] == 100_000_000
    mgr.save(100_000_001, 3 * w, 0.0, [1.0, 0.5, 0.2])
    # prune kept the two NEWEST, not the two lexicographically-largest
    assert mgr.restore()["iteration"] == 100_000_001
    import glob as _g
    kept = sorted(int(p.split("ckpt_")[1][:-4])
                  for p in _g.glob(str(tmp_path / "ck" / "ckpt_*.npz")))
    assert kept == [100_000_000, 100_000_001]


def test_checkpoint_restore_falls_back_past_corruption(tmp_path):
    """keep > 1 exists so one torn newest file cannot break resume: the
    default restore falls back through older retained checkpoints."""
    mgr = CheckpointManager(str(tmp_path / "ck"), keep=3)
    w = np.ones(4, np.float32)
    mgr.save(10, w, 0.1, [1.0])
    mgr.save(20, 2 * w, 0.2, [1.0, 0.5])
    newest = mgr.latest_path()
    with open(newest, "wb") as f:
        f.write(b"torn")  # truncated/unreadable newest
    st = mgr.restore()
    assert st is not None and st["iteration"] == 10
    # an EXPLICIT path still raises (the caller asked for that file)
    with pytest.raises(Exception):
        mgr.restore(path=newest)


def test_checkpoint_init_sweeps_orphaned_tmp_files(tmp_path):
    """A crash between write and rename leaves .tmp_ckpt_* orphans; the
    next manager construction must clean up the STALE ones (recent temp
    files may belong to a live writer and are spared)."""
    import os as _os

    d = tmp_path / "ck"
    d.mkdir()
    orphan = d / ".tmp_ckpt_00000007.npz"
    orphan.write_bytes(b"partial")
    _os.utime(orphan, (1.0, 1.0))  # stale: crashed long ago
    CheckpointManager(str(d))
    assert not orphan.exists()


def test_checkpoint_tolerates_hand_named_files(tmp_path):
    """A user-copied 'ckpt_best.npz' must not break every save/restore
    in the directory; only numbered checkpoints participate."""
    mgr = CheckpointManager(str(tmp_path / "ck"), keep=2)
    w = np.ones(3, np.float32)
    mgr.save(5, w, 0.0, [1.0])
    (tmp_path / "ck" / "ckpt_best.npz").write_bytes(b"hand-named")
    mgr.save(6, 2 * w, 0.0, [1.0, 0.5])  # _prune must not crash
    assert mgr.restore()["iteration"] == 6
    assert (tmp_path / "ck" / "ckpt_best.npz").exists()  # never pruned


def test_checkpoint_sweep_spares_recent_tmp_files(tmp_path):
    """The orphan sweep must not delete another process's in-flight
    temp file — only stale ones (no live writer plausible)."""
    d = tmp_path / "ck"
    d.mkdir()
    fresh = d / ".tmp_ckpt_00000009.npz"
    fresh.write_bytes(b"in-flight")
    old = d / ".tmp_ckpt_00000001.npz"
    old.write_bytes(b"orphan")
    import os as _os

    _os.utime(old, (1.0, 1.0))  # ancient mtime: a true orphan
    CheckpointManager(str(d))
    assert fresh.exists() and not old.exists()


def test_checkpoint_corrupt_file_quarantined(tmp_path):
    """A file the fallback proved unreadable must leave the numbered
    namespace — otherwise _prune keeps it as 'newest' and deletes every
    VALID checkpoint the resumed run writes below its iteration."""
    mgr = CheckpointManager(str(tmp_path / "ck"), keep=1)
    w = np.ones(3, np.float32)
    mgr.save(20, w, 0.0, [1.0])
    torn = tmp_path / "ck" / "ckpt_00000020.npz"
    torn.write_bytes(b"torn")
    assert mgr.restore() is None  # keep=1: nothing valid retained
    assert not torn.exists()  # quarantined aside
    assert (tmp_path / "ck" / ".bad_ckpt_00000020.npz").exists()
    # a resumed run's fresh checkpoints now survive pruning
    mgr.save(1, w, 0.0, [1.0])
    assert mgr.restore()["iteration"] == 1


def test_take_rows_dense_rejects_out_of_range(rng):
    """The dense fold path must raise like the sparse one — numpy would
    silently resolve -1 to the tail row."""
    from tpu_sgd.utils.mlutils import _take_rows

    X = rng.normal(size=(16, 4)).astype(np.float32)
    with pytest.raises(IndexError, match="row indices"):
        _take_rows(X, np.array([-1, 0]))
    with pytest.raises(IndexError, match="row indices"):
        _take_rows(X, np.array([0, 16]))
    assert _take_rows(X, np.array([3, 1])).shape == (2, 4)


def test_jsonl_log_serializes_numpy_scalars(tmp_path):
    """A resumed run's loss list holds np.float32 items; the event log
    must not crash serializing them."""
    from tpu_sgd.utils.events import JsonLinesEventLog

    path = str(tmp_path / "ev.jsonl")
    log = JsonLinesEventLog(path)
    log._write("probe", {"value": np.float32(1.5),
                         "arr_item": np.int64(3)})
    log.close()
    rec = json.loads(open(path).read().strip())
    assert rec["value"] == 1.5 and rec["arr_item"] == 3


def test_step_timer_records_raising_block():
    from tpu_sgd.utils.events import StepTimer

    t = StepTimer()
    with pytest.raises(RuntimeError):
        with t.time():
            raise RuntimeError("boom")
    assert len(t.times) == 1  # the failed call's wall clock still counts


def test_model_save_overwrites_durably(tmp_path):
    """Re-saving over an existing model directory uses atomic per-file
    replaces — no torn metadata/weights pair is ever visible."""
    from tpu_sgd.models.regression import LinearRegressionModel

    path = str(tmp_path / "m")
    m1 = LinearRegressionModel(np.ones(4, np.float32), 1.0)
    m1.save(path)
    m2 = LinearRegressionModel(2 * np.ones(4, np.float32), 2.0)
    m2.save(path)  # overwrite in place
    loaded = LinearRegressionModel.load(path)
    np.testing.assert_array_equal(np.asarray(loaded.weights),
                                  np.asarray(m2.weights))
    assert loaded.intercept == 2.0
    leftovers = [p for p in os.listdir(path) if p.endswith(".tmp")]
    assert leftovers == []


def test_model_load_detects_torn_directory(tmp_path):
    """A crash between the two file replaces leaves new weights beside
    stale metadata; load must raise clearly, not return a wrong model."""
    import json as _json

    from tpu_sgd.models.regression import LinearRegressionModel

    path = str(tmp_path / "m")
    LinearRegressionModel(np.ones(4, np.float32), 1.0).save(path)
    # simulate the torn overwrite: refresh data.npz's saveId only
    meta = _json.load(open(os.path.join(path, "metadata.json")))
    np.savez(os.path.join(path, "data.npz"),
             weights=2 * np.ones(4, np.float32), save_id="different")
    assert meta["saveId"] != "different"
    with pytest.raises(ValueError, match="torn"):
        LinearRegressionModel.load(path)
