"""Oracle-loss verification for the workload configs (VERDICT r1 missing #5).

BASELINE.md pass criteria, demonstrably checked:
  config 1 (least squares)  — final objective within 1% of the EXACT
                              normal-equations minimizer;
  config 2 (logistic + L2)  — within 1% of a tight-tolerance LBFGS optimum;
  config 3 (hinge + L1)     — subgradient SGD is O(1/sqrt(t)) on the
                              nonsmooth hinge (reference-identical
                              limitation, see tpu_sgd/optimize/oracle.py),
                              so: objective within 20% of the tight OWL-QN
                              reference point AND accuracy within 1 point.
Shapes are scaled down from the config sizes to keep CI fast; the
full-scale checks run in examples/run_configs.py.
"""

import numpy as np
import pytest

from tpu_sgd.models.classification import LogisticRegressionWithSGD, SVMWithSGD
from tpu_sgd.models.regression import LinearRegressionWithSGD
from tpu_sgd.ops.gradients import (
    HingeGradient,
    LeastSquaresGradient,
    LogisticGradient,
)
from tpu_sgd.ops.updaters import L1Updater
from tpu_sgd.optimize.oracle import (
    full_objective,
    hinge_l1_oracle,
    least_squares_oracle,
    logistic_l2_oracle,
    objective_gap,
)
from tpu_sgd.utils.mlutils import linear_data, logistic_data, svm_data


def test_config1_matches_normal_equations_oracle():
    X, y, _ = linear_data(20_000, 60, eps=0.1, seed=0)
    w_star = least_squares_oracle(X, y)
    model = LinearRegressionWithSGD.train(
        (X, y), num_iterations=100, step_size=1.0
    )
    gap, L, L_star = objective_gap(
        LeastSquaresGradient(), X, y, model.weights, w_star
    )
    assert gap < 0.01, f"gap {gap:.4f} (L={L:.6f} L*={L_star:.6f})"


def test_config2_matches_lbfgs_oracle():
    X, y, _ = logistic_data(10_000, 60, seed=1)
    y = np.where(y > 0, 1.0, 0.0).astype(np.float32)
    reg = 0.01
    w_star = logistic_l2_oracle(X, y, reg)
    alg = LogisticRegressionWithSGD(2.0, 500, reg, 1.0)
    alg.optimizer.set_convergence_tol(0.0)
    model = alg.run((X, y))
    gap, L, L_star = objective_gap(
        LogisticGradient(), X, y, model.weights, w_star, reg, "l2"
    )
    assert gap < 0.01, f"gap {gap:.4f} (L={L:.6f} L*={L_star:.6f})"


def test_config3_tracks_owlqn_oracle():
    X, y, _ = svm_data(10_000, 50, seed=2)
    reg = 1e-4
    w_star = hinge_l1_oracle(X, y, reg)
    alg = SVMWithSGD(10.0, 3000, reg, 1.0)
    alg.optimizer.set_updater(L1Updater()).set_convergence_tol(0.0)
    model = alg.run((X, y))
    gap, L, L_star = objective_gap(
        HingeGradient(), X, y, model.weights, w_star, reg, "l1"
    )
    # nonsmooth subgradient rate: documented looser objective bound ...
    assert gap < 0.20, f"gap {gap:.4f} (L={L:.6f} L*={L_star:.6f})"
    # ... plus accuracy parity with the oracle's decision rule
    from tpu_sgd.models.classification import SVMModel

    acc_sgd = float(np.mean(np.asarray(model.predict(X)) == y))
    acc_star = float(
        np.mean(np.asarray(SVMModel(w_star, 0.0).predict(X)) == y)
    )
    assert acc_sgd > acc_star - 0.01, (acc_sgd, acc_star)


def test_oracle_objective_helper_closed_form():
    """full_objective agrees with the hand-computed least-squares value."""
    rng = np.random.default_rng(0)
    X = rng.normal(size=(50, 4)).astype(np.float32)
    w = rng.normal(size=(4,)).astype(np.float32)
    y = rng.normal(size=(50,)).astype(np.float32)
    expect = float(np.mean(0.5 * (X @ w - y) ** 2)) + 0.5 * 0.1 * float(
        np.sum(w**2)
    )
    got = full_objective(LeastSquaresGradient(), X, y, w, 0.1, "l2")
    np.testing.assert_allclose(got, expect, rtol=1e-5)
    with pytest.raises(ValueError, match="unknown reg kind"):
        full_objective(LeastSquaresGradient(), X, y, w, 0.1, "elastic")


def test_host_streamed_costfun_reaches_logistic_oracle():
    """Round 5: the beyond-HBM chunked-CostFun schedule must reach the
    SAME optimum as a resident fit — the oracle gap is the end-to-end
    check that chunked accumulation loses nothing (the reference's
    CostFun converges identically however many partitions feed it)."""
    from tpu_sgd.ops.updaters import SquaredL2Updater
    from tpu_sgd.optimize.lbfgs import LBFGS

    X, y, _ = logistic_data(10_000, 40, seed=9)
    reg = 0.01
    w_star = logistic_l2_oracle(X, y, reg_param=reg)
    opt = (LBFGS(LogisticGradient(), SquaredL2Updater(), reg_param=reg,
                 max_num_iterations=60, convergence_tol=1e-9)
           .set_host_streaming(True, batch_rows=1024))
    w, hist = opt.optimize_with_history(
        (X, y), np.zeros(X.shape[1], np.float32))
    gap, L, L_star = objective_gap(
        LogisticGradient(), X, y, w, w_star, reg_param=reg, reg="l2"
    )
    assert gap < 0.01, f"gap {gap:.4f} (L={L:.6f} L*={L_star:.6f})"


def test_chunked_gram_driver_reaches_least_squares_oracle():
    """Round 5: the chunked-gather aligned driver converges to the same
    normal-equations optimum as the per-iteration schedules (the aligned
    sampling deviation does not move the optimum on shuffled data)."""
    from tpu_sgd.ops.updaters import SimpleUpdater
    from tpu_sgd.optimize.gradient_descent import GradientDescent

    X, y, _ = linear_data(20_000, 40, eps=0.1, seed=2)
    w_star = least_squares_oracle(X, y)
    opt = (GradientDescent(LeastSquaresGradient(), SimpleUpdater())
           .set_step_size(1.0).set_num_iterations(200)
           .set_mini_batch_fraction(0.1).set_sampling("sliced")
           .set_convergence_tol(0.0)
           .set_streamed_stats(True, block_rows=512)
           .set_gram_options(chunk_iters=16))
    w, hist = opt.optimize_with_history(
        (X, y), np.zeros(X.shape[1], np.float32))
    assert any(k[0] == "chunked_gram_run" for k in opt._run_cache)
    gap, L, L_star = objective_gap(
        LeastSquaresGradient(), X, y, w, w_star
    )
    assert gap < 0.02, f"gap {gap:.4f} (L={L:.6f} L*={L_star:.6f})"
