"""Sharded parameter store + SparCML tree-merged compressed pushes
(``tpu_sgd/replica/shard.py``, ``io/sparse_wire.py`` merge,
``plan.choose_store_shards``).

The load-bearing pins:

* τ=0 through a sharded store is BITWISE the synchronous meshed
  trajectory at every S — sharding splits the COMBINE (contiguous
  f32 slice accumulation commutes with concatenation bitwise), never
  the updater's whole-vector apply (ADVICE.md "Shard the apply, not
  the contract");
* the HA composition survives sharding: a standby replaying per-shard
  delta-log payload groups is bitwise the primary at every version,
  and a mid-run primary kill at τ=0 stays bitwise the fault-free run;
* a compressed workload confined to one shard replays ONLY that
  shard's pipeline through a failover — replication bytes and replay
  work scale with the touched coordinate range;
* a rejected sharded compressed push restores its EF mass shard by
  shard with nothing leaked;
* the lock discipline (store ``_cond`` → pipeline ``_cond``, depth-1
  per shard) holds on a LIVE sharded store, validated dynamically
  against the same GRAFTLINT_LOCKS literals the lexical rule reads.
"""

import threading

import jax
import numpy as np
import pytest

from tpu_sgd.config import SGDConfig
from tpu_sgd.optimize.gradient_descent import GradientDescent
from tpu_sgd.parallel.mesh import DATA_AXIS
from tpu_sgd.io.sparse_wire import ErrorFeedback, merge_sparse_segments
from tpu_sgd.ops.gradients import LeastSquaresGradient
from tpu_sgd.ops.updaters import SquaredL2Updater
from tpu_sgd.replica import (ReplicaDriver, ReplicaWorker,
                             ShardedParameterStore, StoreFailed,
                             StoreSupervisor, shard_offsets, shard_rows)
from tpu_sgd.reliability import failpoints as fp
from tpu_sgd.reliability.retry import RetryPolicy
from tpu_sgd.utils.events import CollectingListener


def _data(n=128, d=12, seed=1):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, d)).astype(np.float32)
    w_true = rng.normal(size=d).astype(np.float32)
    y = (X @ w_true + 0.01 * rng.normal(size=n)).astype(np.float32)
    return X, y, np.zeros(d, np.float32)


def _mesh(n_shards):
    from jax.sharding import Mesh

    return Mesh(np.asarray(jax.devices()[:n_shards]), (DATA_AXIS,))


def _driver(*, iters=12, frac=0.5, step=0.3, reg=0.1, workers=4, tau=0,
            store_shards=1, standbys=0):
    drv = (ReplicaDriver(LeastSquaresGradient(), SquaredL2Updater())
           .set_step_size(step).set_num_iterations(iters)
           .set_mini_batch_fraction(frac).set_convergence_tol(0.0)
           .set_reg_param(reg).set_workers(workers).set_staleness(tau))
    if store_shards > 1:
        drv.set_store_shards(store_shards)
    if standbys:
        drv.set_standbys(standbys)
    return drv


def _sync_reference(X, y, w0, *, iters=12, frac=0.5, step=0.3, reg=0.1,
                    workers=4):
    opt = (GradientDescent(LeastSquaresGradient(), SquaredL2Updater())
           .set_step_size(step).set_num_iterations(iters)
           .set_mini_batch_fraction(frac).set_convergence_tol(0.0)
           .set_reg_param(reg).set_mesh(_mesh(workers))
           .set_listener(CollectingListener()))
    w, h = opt.optimize_with_history((X, y), w0)
    return np.asarray(w), np.asarray(h)


def _cfg(**kw):
    base = dict(step_size=0.2, num_iterations=20,
                mini_batch_fraction=1.0, convergence_tol=0.0,
                reg_param=0.01)
    base.update(kw)
    return SGDConfig(**base)


def _sharded_pair(cfg, w0, *, n_shards=2, tau=0, primary_listener=None,
                  standby_listener=None, **sup_kw):
    """A sharded primary + sharded standby under a supervisor — the HA
    composition unit (same shard count group-wide, like the driver)."""
    ef = {}
    primary = ShardedParameterStore(
        SquaredL2Updater(), cfg, w0, n_shards=n_shards, staleness=tau,
        listener=primary_listener, ef_registry=ef, name="s0")
    standby = ShardedParameterStore(
        SquaredL2Updater(), cfg, w0, n_shards=n_shards, staleness=tau,
        listener=standby_listener, ef_registry=ef, name="s1")
    sup = StoreSupervisor([primary, standby], **sup_kw)
    return primary, standby, sup


# -- shard layout -------------------------------------------------------------


def test_shard_offsets_contiguous_balanced():
    assert shard_offsets(10, 3) == [(0, 4), (4, 7), (7, 10)]
    assert shard_offsets(12, 4) == [(0, 3), (3, 6), (6, 9), (9, 12)]
    # more shards than coordinates clamps to unit shards
    assert shard_offsets(4, 8) == [(0, 1), (1, 2), (2, 3), (3, 4)]
    # contiguity + full cover, arbitrary split
    offs = shard_offsets(17, 5)
    assert offs[0][0] == 0 and offs[-1][1] == 17
    assert all(a[1] == b[0] for a, b in zip(offs, offs[1:]))


# -- the SparCML merge --------------------------------------------------------


def test_merge_sparse_segments_matches_dense_reference():
    """The tree merge is an exact sparse sum at EVERY density
    crossover — the crossover changes where the densification
    happens, never the result (within f32 re-association tolerance
    of the float64 reference)."""
    rng = np.random.default_rng(0)
    dim = 200
    segs = []
    for _ in range(7):
        k = int(rng.integers(1, 40))
        idx = rng.choice(dim, size=k, replace=False).astype(np.int32)
        vals = rng.normal(size=k).astype(np.float32)
        segs.append((idx, vals))
    ref = np.zeros(dim, np.float64)
    for i, v in segs:
        np.add.at(ref, i, v.astype(np.float64))
    for crossover in (0.0, 0.05, 0.25, 1.0):
        out = merge_sparse_segments(segs, dim, crossover)
        assert out.dtype == np.float32 and out.shape == (dim,)
        np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)


def test_merge_sparse_segments_dedups_and_handles_empties():
    # duplicate coordinates within and across segments sum
    out = merge_sparse_segments(
        [(np.asarray([3, 3, 1], np.int32),
          np.asarray([1.0, 2.0, 4.0], np.float32)),
         (np.asarray([3], np.int32), np.asarray([8.0], np.float32))],
        dim=5, density_crossover=0.25)
    np.testing.assert_array_equal(
        out, np.asarray([0, 4, 0, 11, 0], np.float32))
    # no contributions at all → zeros
    np.testing.assert_array_equal(
        merge_sparse_segments([], dim=3, density_crossover=0.25),
        np.zeros(3, np.float32))
    # empty segments drop, not crash
    out = merge_sparse_segments(
        [(np.asarray([], np.int32), np.asarray([], np.float32)),
         (np.asarray([2], np.int32), np.asarray([5.0], np.float32))],
        dim=3, density_crossover=1.0)
    np.testing.assert_array_equal(
        out, np.asarray([0, 0, 5.0], np.float32))


# -- τ=0 bitwise vs sync, per shard count -------------------------------------


@pytest.mark.parametrize("n_shards", [1, 2, 4])
def test_tau0_bitwise_vs_sync_per_shard_count(n_shards):
    """THE acceptance pin: τ=0 through S apply pipelines is BITWISE
    the synchronous meshed trajectory — weights AND loss history —
    because per-shard slice accumulation in payload order is the same
    f32 add chain as the sequential combine, and the whole-vector
    jitted apply is untouched."""
    X, y, w0 = _data()
    w_ref, h_ref = _sync_reference(X, y, w0)
    drv = _driver(store_shards=n_shards)
    w, h = drv.optimize_with_history((X, y), w0)
    np.testing.assert_array_equal(np.asarray(w), w_ref)
    np.testing.assert_array_equal(np.asarray(h), h_ref)
    snap = drv.last_store_snapshot
    if n_shards > 1:
        assert snap["store_shards"] == n_shards
        # dense pushes touch every shard: 12 versions × 4 workers
        assert snap["shard_pushes"] == [48] * n_shards
        assert snap["shard_applies"] == [12] * n_shards


# -- HA composition -----------------------------------------------------------


def test_sharded_standby_bitwise_at_every_version():
    """Per-shard delta-log payload groups replay, they do not
    approximate: the sharded standby's per-version loss and
    weight-delta and its final weights are bitwise the sharded
    primary's, and every dense record replays through every
    pipeline."""
    X, y, w0 = _data(n=128, d=8, seed=3)
    cfg = _cfg(num_iterations=16, mini_batch_fraction=0.5,
               step_size=0.3)
    p_lis, s_lis = CollectingListener(), CollectingListener()
    primary, standby, sup = _sharded_pair(
        cfg, w0, n_shards=2, tau=0, primary_listener=p_lis,
        standby_listener=s_lis)
    client = sup.client()
    shards = shard_rows(X, y, 2)
    workers = [ReplicaWorker(f"w{s}", s, client, LeastSquaresGradient(),
                             cfg, *shards[s]) for s in range(2)]
    for s in range(2):
        client.register_worker(f"w{s}", s)
    threads = [threading.Thread(target=w.run) for w in workers]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    sup.stop()  # drains the standby to the log head
    np.testing.assert_array_equal(standby.loss_history(),
                                  primary.loss_history())
    np.testing.assert_array_equal(np.asarray(standby.weights),
                                  np.asarray(primary.weights))
    assert len(p_lis.iterations) == len(s_lis.iterations) == 16
    for pe, se in zip(p_lis.iterations, s_lis.iterations):
        assert (pe.iteration, pe.loss, pe.weight_delta_norm) == (
            se.iteration, se.loss, se.weight_delta_norm)
    # dense ssums groups touch both shards on every replayed record
    s_snap = standby.snapshot()
    assert s_snap["shard_replays"] == [16, 16]


def test_tau0_kill_primary_sharded_bitwise_across_failover():
    """The HA pin composed with sharding: τ=0 + sharded store + a
    primary kill mid-run is STILL bitwise the fault-free UNSHARDED
    run — failover replays the per-shard payload groups, the promoted
    pipelines pick up where the log ends."""
    X, y, w0 = _data()
    w_ref, h_ref = _driver().optimize_with_history((X, y), w0)
    drv = (_driver(store_shards=2, standbys=1)
           .set_retry(RetryPolicy(max_attempts=400, base_backoff_s=0.01,
                                  max_backoff_s=0.05, seed=7)))
    with fp.inject_faults({"replica.store_fail":
                           fp.fail_nth(48, exc=StoreFailed)}):
        w_k, h_k = drv.optimize_with_history((X, y), w0)
    assert drv.last_failover_snapshot["failovers"] == 1
    np.testing.assert_array_equal(np.asarray(w_k), np.asarray(w_ref))
    np.testing.assert_array_equal(h_k, h_ref)
    snap = drv.last_store_snapshot
    assert snap["store_shards"] == 2
    # the promoted store replayed records into BOTH pipelines (dense
    # groups) before taking over live pushes
    assert all(r > 0 for r in snap["shard_replays"])


def test_single_shard_failover_replays_only_its_gap():
    """Compressed pushes confined to shard 0's coordinate range
    produce ``stopk`` records whose shard-1 group is ``None`` —
    through live replication AND the failover gap replay, pipeline 1
    never replays and never counts a push.  Replay work scales with
    the touched range, which is the point of the per-shard payload
    groups."""
    d = 16
    cfg = _cfg(num_iterations=10, step_size=0.1)
    w0 = np.zeros(d, np.float32)
    primary, standby, sup = _sharded_pair(cfg, w0, n_shards=2, tau=2)
    assert primary.shard_layout() == [(0, 8), (8, 16)]
    client = sup.client()
    for s in range(2):
        client.register_worker(f"w{s}", s)
    rng = np.random.default_rng(5)

    def push_lower(wid):
        pulled = client.pull(wid)
        idx = np.asarray([0, 2, 5], np.int32)  # shard 0 only
        vals = rng.normal(size=3).astype(np.float32)
        r = client.push_compressed(wid, pulled.version, idx, vals,
                                   0.5, 64.0)
        assert r.accepted

    for _ in range(3):
        push_lower("w0")
        push_lower("w1")
    assert sup.kill_primary()
    for _ in range(2):
        push_lower("w0")
        push_lower("w1")
    sup.stop()
    promoted = sup.primary()
    assert promoted is standby
    snap = promoted.snapshot()
    assert snap["version"] == 10
    assert snap["shard_replays"][0] >= 1   # the touched shard replays
    assert snap["shard_replays"][1] == 0   # the untouched one NEVER
    assert snap["shard_pushes"][1] == 0    # live pushes skip it too
    rec = sup.snapshot()["records"][0]
    assert rec["new_primary"] == "s1" and not rec["cold_recovery"]


# -- EF mass conservation, per shard ------------------------------------------


def test_rejected_sharded_compressed_push_restores_ef_per_shard():
    """A stale compressed push rejected by a sharded store restores
    its EF segment shard by shard with ZERO leaked mass — each
    shard's restore lands exactly its own coordinate range."""
    d = 16
    cfg = _cfg(num_iterations=10, step_size=0.1)
    store = ShardedParameterStore(
        SquaredL2Updater(), cfg, np.zeros(d, np.float32), n_shards=2,
        staleness=1)
    try:
        store.register_worker("w0", 0)
        store.register_worker("w1", 1)
        ef = store.error_feedback("w0", 0.5)
        rng = np.random.default_rng(9)
        update = rng.normal(size=d).astype(np.float32)
        idx, vals = ef.compress(update.copy())
        # advance the store 2 versions past w0's basis (tau=1)
        g = rng.normal(size=d).astype(np.float32)
        assert store.push("w1", 0, g, 0.5, 8.0).accepted
        assert store.push("w0", 0, g, 0.5, 8.0).accepted
        res = store.push_compressed("w0", 0, idx, vals, 0.5, 8.0)
        assert not res.accepted and res.staleness > 1
        # the worker-side heal, split exactly as the wire was: restore
        # shard 0's segment → ONLY coords [0, 8) are whole again
        (a0, b0), (a1, b1) = store.shard_layout()
        m0 = (idx >= a0) & (idx < b0)
        ef.restore_segment(idx[m0], vals[m0])
        np.testing.assert_allclose(ef.acc[a0:b0], update[a0:b0],
                                   rtol=1e-5)
        if np.any(~m0):  # mass extracted from shard 1 still missing
            assert not np.allclose(ef.acc[a1:b1], update[a1:b1])
        ef.restore_segment(idx[~m0], vals[~m0])
        np.testing.assert_allclose(ef.acc, update, rtol=1e-5)
    finally:
        store.stop()


# -- lock discipline ----------------------------------------------------------


def test_sharded_store_lock_discipline_validated_at_runtime():
    """GRAFTLINT_LOCKS for the store AND every pipeline, validated
    dynamically on a live sharded run — the runtime twin of the
    lexical rule, proving the two-level discipline (store ``_cond`` →
    pipeline ``_cond``, never the reverse) holds under real worker
    concurrency."""
    from tpu_sgd.analysis.runtime import (LocksetRecorder, assert_lock_order,
                                          instrument_object)
    from tpu_sgd.replica import shard as shard_mod
    from tpu_sgd.replica import store as store_mod

    X, y, w0 = _data(n=64, d=6)
    cfg = _cfg(num_iterations=20, step_size=0.2,
               mini_batch_fraction=0.5)
    store = ShardedParameterStore(
        SquaredL2Updater(), cfg, w0, n_shards=2, staleness=1)
    # ONE recorder across store + pipelines so cross-object acquisition
    # ORDER pairs are observed, then replayed against the committed
    # GRAFTLINT_LOCK_ORDER (the Eraser + lock-order runtime twins)
    rec = LocksetRecorder()
    instrument_object(store, store_mod.GRAFTLINT_LOCKS["ParameterStore"],
                      rec, owner="ParameterStore")
    for p in store._pipes:
        instrument_object(p, shard_mod.GRAFTLINT_LOCKS["ShardPipeline"],
                          rec, owner="ShardPipeline")
    shards = shard_rows(X, y, 2)
    workers = [ReplicaWorker(f"w{s}", s, store, LeastSquaresGradient(),
                             cfg, *shards[s]) for s in range(2)]
    for s in range(2):
        store.register_worker(f"w{s}", s)
    threads = [threading.Thread(target=w.run) for w in workers]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    store.stop()
    assert store.version == 20
    assert rec.checked_accesses > 0
    assert rec.violations == []
    assert rec.races() == []
    assert ("ParameterStore._cond",
            "ShardPipeline._cond") in rec.order_pairs
    assert_lock_order(rec)  # the observed nesting matches the committed order


def test_shard_pipeline_concurrent_shutdown_and_post_shutdown_submit():
    """The ISSUE 19 shard fix pinned: ``shutdown()`` swaps the thread
    handle to None UNDER the condition, so racing shutdowns join the
    worker exactly once, the handle cannot be re-read mid-swap, and a
    submit after shutdown fails typed instead of posting into a dead
    pipeline."""
    from tpu_sgd.replica.shard import ShardPipeline

    p = ShardPipeline(0, 0, 4)
    p.submit(lambda: 41 + 1)  # lazily spawns the worker under _cond
    assert p.collect() == 42
    worker = p._thread
    assert worker is not None and worker.is_alive()

    closers = [threading.Thread(target=p.shutdown) for _ in range(4)]
    for t in closers:
        t.start()
    for t in closers:
        t.join(timeout=10)
    assert not worker.is_alive()
    assert p._thread is None  # the swapped handle, observed post-join
    with pytest.raises(RuntimeError, match="shut down"):
        p.submit(lambda: 0)


# -- the planner --------------------------------------------------------------


def test_choose_store_shards_small_model_stays_unsharded():
    from tpu_sgd.plan import choose_store_shards

    # a 12-wide update's wire never dominates one dispatch
    assert choose_store_shards(256, 12, n_devices=8) == 1
    # one device cannot place pipelines worth having
    assert choose_store_shards(2000, 20_000_000, n_devices=1) == 1


def test_choose_store_shards_wide_model_shards_and_clamps():
    from tpu_sgd.plan import choose_store_shards

    s8 = choose_store_shards(2_000_000, 20_000_000, n_devices=8)
    assert 1 < s8 <= 8
    # dispatch dominance: each pipeline's wire share must still beat
    # one dispatch, so S stops growing when the share shrinks under it
    s4 = choose_store_shards(2_000_000, 20_000_000, n_devices=4)
    assert 1 < s4 <= 4 and s4 <= s8


def test_choose_replicas_grows_with_store_shards():
    from tpu_sgd.plan import choose_replicas

    # store-bound regime: the wide update wire throttles the fleet a
    # single-pipeline store can feed; sharding the combine relieves it
    w1 = choose_replicas(2000, 20_000_000, n_devices=8)
    w4 = choose_replicas(2000, 20_000_000, n_devices=8, store_shards=4)
    assert w4 > w1 >= 2


def test_plan_exposes_store_shards():
    from tpu_sgd.plan import DEFAULT_COST_MODEL, plan

    assert DEFAULT_COST_MODEL.sparse_merge_density == 0.25
    small = plan(256, 12, n_devices=8)
    assert small.store_shards == 1
    wide = plan(2_000_000, 20_000_000, n_devices=8)
    assert wide.store_shards > 1
    assert wide.estimates["store_shards"] == wide.store_shards


# -- the obs surface ----------------------------------------------------------


def test_record_wire_shard_tag_fans_out_counter_series():
    from tpu_sgd.obs import counters as obs_counters

    obs_counters.enable()
    obs_counters.reset()
    try:
        obs_counters.record_wire("dense-f32", 128, 128, tag="s0")
        obs_counters.record_wire("dense-f32", 128, 64, tag="s1")
        snap = obs_counters.snapshot()
    finally:
        obs_counters.disable()
    tagged = {n for n in snap
              if ".wire.dense-f32[" in n and not n.endswith(".logical")}
    assert len(tagged) == 2
    ratios = obs_counters.wire_ratios(snap)
    by_tag = {n[n.index("["):]: r for n, r in ratios.items()
              if "[" in n}
    assert by_tag["[s0]"]["physical_bytes"] == 128
    assert by_tag["[s1]"]["physical_bytes"] == 64
    assert by_tag["[s1]"]["logical_bytes"] == 128


def test_shard_imbalance_detector_trips_on_lagging_shard_only():
    from tpu_sgd.obs.detect import (DetectorEngine,
                                    ShardImbalanceDetector,
                                    default_detectors)

    # an operator opt-in fixture, NOT in the defaults (the
    # LossPlateauDetector precedent)
    assert "shard-imbalance" not in {d.rule for d in default_detectors()}

    def _win(idx, series):
        return {"index": idx, "t_start": float(idx),
                "t_end": float(idx) + 1.0, "series": series}

    def _cnt(n):
        return {"count": n, "sum": 0.0, "mean": 0.0, "max": None,
                "bytes": 0}

    eng = DetectorEngine([ShardImbalanceDetector()])
    # balanced: no trip
    eng.on_window_close(_win(0, {"replica.shard.push[s0]": _cnt(20),
                                 "replica.shard.push[s1]": _cnt(18)}))
    assert eng.trip_counts() == {}
    # one shard lags below half the busiest: trips
    eng.on_window_close(_win(1, {"replica.shard.push[s0]": _cnt(20),
                                 "replica.shard.push[s1]": _cnt(2)}))
    assert eng.trip_counts() == {"shard-imbalance": 1}
    # a quiet window (busiest under the floor) cannot trip on noise
    eng2 = DetectorEngine([ShardImbalanceDetector()])
    eng2.on_window_close(_win(0, {"replica.shard.push[s0]": _cnt(4),
                                  "replica.shard.push[s1]": _cnt(0)}))
    assert eng2.trip_counts() == {}
    # a single series (unsharded store) never trips
    eng2.on_window_close(_win(1, {"replica.shard.push[s0]": _cnt(50)}))
    assert eng2.trip_counts() == {}
