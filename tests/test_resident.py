"""Device-resident training driver tests (ISSUE 6): the whole run as
ONE ``lax.while_loop`` dispatch, host only at cadence.

Contracts pinned here (and documented in ``optimize/resident_driver.py``):

* The resident driver's trajectory, loss history, listener events, and
  checkpoint bytes are BITWISE the K-superstep driver's in all three
  sampling modes — the while_loop wraps the SAME fused scan, and the
  ring ys replay through the same ``_replay_fused_steps``.
* A converged-or-budget-exhausted run is exactly ONE program dispatch
  (``assert_dispatch_count``), and the whole run compiles exactly ONE
  program (``assert_compile_count``) — tails, resumes, and cadence
  windows included.
* Convergence is detected at the TRUE iteration even mid-window;
  ring-buffer tails (N not dividing C·K) replay without padding
  artifacts; stop signals land within one cadence window (C·K
  iterations) at a window-boundary checkpoint.
"""

import numpy as np
import pytest

from tpu_sgd.config import SGDConfig
from tpu_sgd.ops.gradients import LeastSquaresGradient
from tpu_sgd.ops.updaters import SimpleUpdater
from tpu_sgd.optimize.gradient_descent import GradientDescent
from tpu_sgd.optimize.streamed import optimize_host_streamed

MODES = ("sliced", "indexed", "bernoulli")
TOL = dict(rtol=5e-5, atol=1e-6)


def _data(rng, n=1000, d=12):
    X = rng.normal(size=(n, d)).astype(np.float32)
    w = rng.uniform(-1, 1, d).astype(np.float32)
    y = (X @ w + 0.01 * rng.normal(size=n)).astype(np.float32)
    return X, y


def _opt(mode="sliced", iters=22, k=4, c=0, seed=7, listener=True):
    from tpu_sgd.utils.events import SGDListener

    o = (GradientDescent()
         .set_num_iterations(iters).set_step_size(0.1)
         .set_mini_batch_fraction(0.5).set_sampling(mode)
         .set_convergence_tol(0.0).set_seed(seed)
         .set_superstep(k))
    if listener:
        o.set_listener(SGDListener())
    if c:
        o.set_residency(c)
    return o


def _stream(cfg, X, y, **kw):
    return optimize_host_streamed(
        LeastSquaresGradient(), SimpleUpdater(), cfg, X, y,
        np.zeros(X.shape[1], np.float32), **kw)


# ---- bitwise replay contract vs the K-superstep driver ---------------------

@pytest.mark.parametrize("mode", MODES)
def test_stepwise_resident_bitwise_vs_superstep_all_modes(rng, mode):
    """THE trajectory contract: resident runs are bitwise-equal to the
    superstep driver's (weights AND history) — the while_loop wraps the
    same fused scan, in every sampling mode."""
    X, y = _data(rng)
    w0 = np.zeros(12, np.float32)
    wS, hS = _opt(mode, c=0).optimize_with_history((X, y), w0)
    wR, hR = _opt(mode, c=2).optimize_with_history((X, y), w0)
    np.testing.assert_array_equal(np.asarray(wR), np.asarray(wS))
    np.testing.assert_array_equal(hR, hS)


def test_streamed_full_batch_resident_bitwise_vs_superstep(rng):
    """Streamed full-batch feed: the one-time transfer plus the
    resident while_loop reproduce the superstep driver bitwise."""
    X, y = _data(rng, n=600, d=8)
    cfg = SGDConfig(step_size=0.1, num_iterations=22,
                    mini_batch_fraction=1.0, convergence_tol=0.0,
                    sampling="bernoulli", seed=7)
    wS, hS = _stream(cfg, X, y, superstep_k=4)
    wR, hR = _stream(cfg, X, y, superstep_k=4, resident_cadence=2)
    np.testing.assert_array_equal(np.asarray(wR), np.asarray(wS))
    np.testing.assert_array_equal(hR, hS)


def test_streamed_resident_slab_same_windows_and_replay_bitwise(rng):
    """Fully-resident slab feed (resident_rows = n, sliced): the
    precomputed start sequence reproduces the host sampler's windows
    exactly (same history LENGTH and sampled sequence, weights at
    reassociation tolerance vs the cond-structured window superstep —
    the documented cross-program caveat), and resident replays are
    bitwise."""
    X, y = _data(rng, n=800, d=10)
    n = X.shape[0]
    cfg = SGDConfig(step_size=0.1, num_iterations=22,
                    mini_batch_fraction=0.25, convergence_tol=0.0,
                    sampling="sliced", seed=7)
    wS, hS = _stream(cfg, X, y, resident_rows=n, superstep_k=4)
    wR, hR = _stream(cfg, X, y, resident_rows=n, superstep_k=4,
                     resident_cadence=2)
    assert len(hR) == len(hS) == 22
    np.testing.assert_allclose(np.asarray(wR), np.asarray(wS), **TOL)
    np.testing.assert_allclose(hR, hS, **TOL)
    wR2, hR2 = _stream(cfg, X, y, resident_rows=n, superstep_k=4,
                       resident_cadence=2)
    np.testing.assert_array_equal(np.asarray(wR), np.asarray(wR2))
    np.testing.assert_array_equal(hR, hR2)


def test_resident_listener_events_match_superstep(rng):
    """Per-iteration listener events fire from the window replays — in
    order, with the exact losses, iterations 1..N."""
    X, y = _data(rng, n=500, d=8)

    class Rec:
        def __init__(self):
            self.events = []
            self.ended = None

        def on_run_start(self, cfg):
            pass

        def on_iteration(self, e):
            self.events.append(e)

        def on_run_end(self, e):
            self.ended = e

    def run(c):
        rec = Rec()
        o = _opt("indexed", iters=10, k=4, c=c, listener=False)
        o.set_listener(rec)
        w, h = o.optimize_with_history((X, y), np.zeros(8, np.float32))
        return w, h, rec

    wS, hS, recS = run(0)
    wR, hR, recR = run(2)
    assert [e.iteration for e in recR.events] == list(range(1, 11))
    np.testing.assert_array_equal(
        np.asarray([e.loss for e in recR.events], np.float32),
        np.asarray([e.loss for e in recS.events], np.float32))
    assert recR.ended is not None and recR.ended.num_iterations == 10


def test_resident_checkpoint_cadence_matches_superstep(rng, tmp_path):
    """Cadence saves fire inside the window callback on the legacy
    iterations with the exact iteration state — same files, same
    restored bytes as the superstep driver."""
    import glob

    from tpu_sgd.utils.checkpoint import CheckpointManager

    X, y = _data(rng, n=400, d=6)

    def run(c, sub):
        o = _opt("sliced", iters=10, k=4, c=c, listener=False)
        o.set_checkpoint(CheckpointManager(str(tmp_path / sub),
                                           keep=100), every=3)
        o.optimize_with_history((X, y), np.zeros(6, np.float32))
        return sorted(int(f[-12:-4]) for f in
                      glob.glob(str(tmp_path / sub / "ckpt_*.npz")))

    assert run(0, "superstep") == run(2, "resident") == [3, 6, 9, 10]
    sS = CheckpointManager(str(tmp_path / "superstep")).restore()
    sR = CheckpointManager(str(tmp_path / "resident")).restore()
    np.testing.assert_array_equal(sR["weights"], sS["weights"])
    np.testing.assert_array_equal(sR["loss_history"], sS["loss_history"])


# ---- convergence at the true iteration inside a window ---------------------

def test_resident_convergence_detected_at_true_iteration(rng):
    """The device predicate exits the loop; the host replay pins the
    TRUE converged iteration inside the cadence window — history ends
    exactly where the superstep driver's does, mid-window."""
    X, y = _data(rng, n=512, d=8)
    w0 = np.zeros(8, np.float32)

    def run(c):
        o = (GradientDescent().set_num_iterations(400)
             .set_step_size(0.05).set_mini_batch_fraction(0.5)
             .set_sampling("sliced").set_convergence_tol(0.01)
             .set_seed(7).set_superstep(8))
        from tpu_sgd.utils.events import SGDListener

        o.set_listener(SGDListener())
        if c:
            o.set_residency(c)
        return o.optimize_with_history((X, y), w0)

    wS, hS = run(0)
    wR, hR = run(4)
    assert len(hR) == len(hS)
    assert len(hR) % (4 * 8) != 0  # genuinely mid-window
    np.testing.assert_array_equal(np.asarray(wR), np.asarray(wS))
    np.testing.assert_array_equal(hR, hS)


# ---- ring-buffer tail ------------------------------------------------------

@pytest.mark.parametrize("iters", (7, 19, 23, 37))
def test_resident_ring_tail_when_n_not_dividing_window(rng, iters):
    """N not dividing C·K: the partial tail window (and a padded tail
    superstep inside it) replays from the returned carry without
    length or value artifacts — bitwise vs the superstep driver."""
    X, y = _data(rng, n=400, d=6)
    w0 = np.zeros(6, np.float32)
    wS, hS = _opt("indexed", iters=iters, k=4, c=0) \
        .optimize_with_history((X, y), w0)
    wR, hR = _opt("indexed", iters=iters, k=4, c=3) \
        .optimize_with_history((X, y), w0)
    assert len(hR) == iters
    np.testing.assert_array_equal(np.asarray(wR), np.asarray(wS))
    np.testing.assert_array_equal(hR, hS)


# ---- one dispatch / one program --------------------------------------------

def test_resident_run_is_one_dispatch(rng):
    """THE structural claim: a whole resident run — cadence windows,
    ring writes, tail — is ONE program launch, where the matched
    superstep driver pays one per superstep.  Counted with the runtime
    twin (assert_dispatch_count), not timed."""
    import jax.numpy as jnp

    from tpu_sgd.analysis import assert_dispatch_count
    from tpu_sgd.optimize.resident_driver import ResidentBookkeeper

    X, y = _data(rng, n=400, d=6)
    w0 = np.zeros(6, np.float32)

    o = _opt("sliced", iters=32, k=4, c=2)
    o.optimize_with_history((X, y), w0)  # warm the compile
    key = ("resident", o.gradient, o.updater, o.config, 4, 2)
    loop = o._run_cache[key]
    Xd, yd = jnp.asarray(X), jnp.asarray(y)
    hooks = ResidentBookkeeper(o.config, 4, 2, losses=[], reg_val=0.0,
                               start_iter=1)
    with assert_dispatch_count(1):
        loop.run(jnp.asarray(w0), 0.0, 1, (Xd, yd), hooks)
    assert len(hooks.losses) == 32 and hooks.windows_fired == 4


def test_resident_dispatches_independent_of_run_length(rng):
    """Public-API twin of the one-dispatch claim: doubling the
    iteration budget adds ZERO launches on the resident path (more
    cadence windows are host callbacks, not dispatches), while the
    superstep driver pays at least one launch per extra superstep."""
    from tpu_sgd.analysis import count_dispatches

    X, y = _data(rng, n=400, d=6)
    w0 = np.zeros(6, np.float32)

    def count(iters, c):
        o = _opt("sliced", iters=iters, k=4, c=c)
        o.optimize_with_history((X, y), w0)  # warm the compiles
        with count_dispatches() as got:
            o.optimize_with_history((X, y), w0)
        return got["n"]

    assert count(64, c=2) == count(32, c=2)
    extra_supersteps = (64 - 32) // 4
    assert count(64, c=0) - count(32, c=0) >= extra_supersteps


def test_resident_loop_compiles_one_program(rng):
    """assert_compile_count on the while-loop body: a full run
    (multiple windows + tail) traces and compiles exactly one XLA
    program, and a re-run compiles nothing new."""
    from tpu_sgd.analysis import assert_compile_count

    X, y = _data(rng, n=400, d=6)
    w0 = np.zeros(6, np.float32)
    o = _opt("bernoulli", iters=23, k=4, c=2)
    o.optimize_with_history((X, y), w0)
    key = ("resident", o.gradient, o.updater, o.config, 4, 2)
    loop = o._run_cache[key]
    assert loop.compile_cache_size() == 1
    with assert_compile_count(0, of=loop.compile_cache_size):
        o.optimize_with_history((X, y), w0)


def test_resident_warmed_window_no_host_sync(rng):
    """graftlint v2's runtime twin on the real driver: a warmed resident
    run forces host syncs proportional to CADENCE WINDOWS, never to
    iterations — one tiny int32 scalar per window (the ordered
    callback's ``win_start``) plus the three documented end-of-run
    boundary scalars, every one of them shape-() (no bulk fetch rides
    along).  Doubling the iteration budget at fixed cadence doubles
    windows, not per-iteration syncs."""
    import jax.numpy as jnp

    from tpu_sgd.analysis import assert_no_host_sync
    from tpu_sgd.optimize.resident_driver import ResidentBookkeeper

    X, y = _data(rng, n=400, d=6)
    w0 = np.zeros(6, np.float32)

    def run_counted(iters):
        o = _opt("sliced", iters=iters, k=4, c=2)
        o.optimize_with_history((X, y), w0)  # warm the compile
        key = ("resident", o.gradient, o.updater, o.config, 4, 2)
        loop = o._run_cache[key]
        hooks = ResidentBookkeeper(o.config, 4, 2, losses=[],
                                   reg_val=0.0, start_iter=1)
        windows = iters // (4 * 2)
        with assert_no_host_sync(allow=windows + 3) as counter:
            loop.run(jnp.asarray(w0), 0.0, 1,
                     (jnp.asarray(X), jnp.asarray(y)), hooks)
        assert counter["n"] == windows + 3
        assert all(shape == () for shape, _ in counter["shapes"])
        return counter["n"]

    assert run_counted(64) - run_counted(32) == (64 - 32) // (4 * 2)


def test_resident_warmed_sync_pin_holds_with_tracing_on(rng):
    """ISSUE 8: the windows+3 pin is not a tracing-off artifact — with
    span tracing ENABLED (live sink, spans emitted from the callback
    thread and the driver) the warmed resident run still forces exactly
    windows+3 shape-() syncs: the span machinery reuses the window's
    one win_start fetch (``i0_host``) instead of fetching twice, and
    span timestamps never block_until_ready (ADVICE.md "Span
    timestamps are attribution, not truth")."""
    import jax.numpy as jnp

    from tpu_sgd.analysis import assert_no_host_sync
    from tpu_sgd.obs.spans import disable_tracing, enable_tracing
    from tpu_sgd.optimize.resident_driver import ResidentBookkeeper

    X, y = _data(rng, n=400, d=6)
    w0 = np.zeros(6, np.float32)
    iters, windows = 64, 64 // (4 * 2)
    o = _opt("sliced", iters=iters, k=4, c=2)
    o.optimize_with_history((X, y), w0)  # warm the compile
    key = ("resident", o.gradient, o.updater, o.config, 4, 2)
    loop = o._run_cache[key]

    class Sink:
        def __init__(self):
            self.records = []

        def emit(self, kind, payload):
            self.records.append((kind, payload))

    sink = Sink()
    hooks = ResidentBookkeeper(o.config, 4, 2, losses=[],
                               reg_val=0.0, start_iter=1)
    enable_tracing(sink)
    try:
        with assert_no_host_sync(allow=windows + 3) as counter:
            loop.run(jnp.asarray(w0), 0.0, 1,
                     (jnp.asarray(X), jnp.asarray(y)), hooks)
    finally:
        disable_tracing()
    assert counter["n"] == windows + 3
    assert all(shape == () for shape, _ in counter["shapes"])
    # tracing really ran: one window span per cadence window, one
    # dispatch span, every win_start attr from the SHARED fetch
    wins = [p for k, p in sink.records
            if k == "trace_span" and p["name"] == "train.window"]
    assert [w["i0"] for w in wins] == [1 + 8 * i for i in range(windows)]
    assert sum(1 for k, p in sink.records if k == "trace_span"
               and p["name"] == "train.resident_dispatch") == 1


# ---- stop signal / preemption ----------------------------------------------

def test_resident_stop_latency_bounded_by_cadence_window(rng, tmp_path):
    """A stop requested before the run begins is honored at the FIRST
    cadence window — preemption latency is bounded by C·K iterations,
    the boundary iteration is checkpointed exactly, and a resumed run
    finishes bitwise."""
    from tpu_sgd.reliability.supervisor import TrainingPreempted
    from tpu_sgd.utils.checkpoint import CheckpointManager

    X, y = _data(rng, n=512, d=8)
    w0 = np.zeros(8, np.float32)
    K, C = 4, 2
    wRef, hRef = _opt("sliced", iters=24, k=K, c=C) \
        .optimize_with_history((X, y), w0)

    o = _opt("sliced", iters=24, k=K, c=C, listener=False)
    o.set_checkpoint(CheckpointManager(str(tmp_path)), every=100)
    o.set_stop_signal(lambda: True)
    with pytest.raises(TrainingPreempted) as ei:
        o.optimize_with_history((X, y), w0)
    assert ei.value.iteration == C * K  # first window boundary
    assert CheckpointManager(str(tmp_path)).latest_version() == C * K
    o.set_stop_signal(None)
    wR, hR = o.optimize_with_history((X, y), w0)
    np.testing.assert_array_equal(np.asarray(wR), np.asarray(wRef))
    np.testing.assert_array_equal(hR, hRef)


@pytest.mark.parametrize("mode", MODES)
def test_resident_preempt_resume_bitwise_all_modes(rng, mode, tmp_path):
    """Supervisor-style mid-run preempt: stop at the second window,
    resume (off the original window grid), finish bitwise."""
    from tpu_sgd.reliability.supervisor import TrainingPreempted
    from tpu_sgd.utils.checkpoint import CheckpointManager

    X, y = _data(rng, n=512, d=8)
    w0 = np.zeros(8, np.float32)
    wRef, hRef = _opt(mode, iters=30, k=4, c=2) \
        .optimize_with_history((X, y), w0)

    class StopSecond:
        def __init__(self):
            self.polls = 0

        def __call__(self):
            self.polls += 1
            return self.polls == 2

    o = _opt(mode, iters=30, k=4, c=2, listener=False)
    o.set_checkpoint(CheckpointManager(str(tmp_path / mode)), every=100)
    o.set_stop_signal(StopSecond())
    with pytest.raises(TrainingPreempted) as ei:
        o.optimize_with_history((X, y), w0)
    assert ei.value.iteration == 16  # second C*K window boundary
    o.set_stop_signal(None)
    wR, hR = o.optimize_with_history((X, y), w0)
    np.testing.assert_array_equal(np.asarray(wR), np.asarray(wRef))
    np.testing.assert_array_equal(hR, hRef)


# ---- reliability: io.resident_callback failpoint ---------------------------

def test_resident_callback_failpoint_heals_via_retry(rng):
    """An injected fault in the window callback heals through the
    ingest RetryPolicy inside the callback (before any bookkeeping
    mutates) — healed runs are bitwise."""
    from tpu_sgd.reliability import failpoints as fp
    from tpu_sgd.reliability.failpoints import FaultInjected, fail_nth
    from tpu_sgd.reliability.retry import RetryPolicy

    X, y = _data(rng, n=512, d=8)
    w0 = np.zeros(8, np.float32)
    wRef, hRef = _opt("indexed", iters=24, k=4, c=2) \
        .optimize_with_history((X, y), w0)

    o = _opt("indexed", iters=24, k=4, c=2)
    o.set_ingest_options(retry=RetryPolicy(max_attempts=3,
                                           base_backoff_s=0.0))
    with fp.inject_faults({"io.resident_callback": fail_nth(2)}):
        w, h = o.optimize_with_history((X, y), w0)
        assert fp.triggers("io.resident_callback") == 1
    np.testing.assert_array_equal(np.asarray(w), np.asarray(wRef))
    np.testing.assert_array_equal(h, hRef)

    # without a retry policy the fault is stashed at the FFI boundary
    # and re-raised host-side with its true class — never an opaque
    # XlaRuntimeError — so the supervisor's retry classifier sees it
    with fp.inject_faults({"io.resident_callback": fail_nth(1)}):
        with pytest.raises(FaultInjected):
            _opt("indexed", iters=24, k=4, c=2) \
                .optimize_with_history((X, y), w0)


def test_resident_crash_resume_bitwise_via_supervisor(rng, tmp_path):
    """Exhausted callback retries crash the run with the original
    exception; the TrainingSupervisor resumes from the cadence
    checkpoint and the finished run is bitwise vs fault-free."""
    from tpu_sgd.reliability import failpoints as fp
    from tpu_sgd.reliability.failpoints import fail_nth
    from tpu_sgd.reliability.retry import RetryPolicy
    from tpu_sgd.reliability.supervisor import TrainingSupervisor
    from tpu_sgd.utils.checkpoint import CheckpointManager

    X, y = _data(rng, n=512, d=8)
    w0 = np.zeros(8, np.float32)
    wRef, hRef = _opt("sliced", iters=32, k=4, c=2) \
        .optimize_with_history((X, y), w0)

    sup = TrainingSupervisor(
        _opt("sliced", iters=32, k=4, c=2, listener=False),
        checkpoint_manager=CheckpointManager(str(tmp_path)),
        checkpoint_every=5,
        retry=RetryPolicy(max_attempts=4, base_backoff_s=0.0),
        install_signal_handlers=False)
    # no ingest retry: the 2nd window's callback fault crashes the run;
    # the supervisor restarts and the resume replays from iteration 5's
    # checkpoint — OFF the original window grid (window regrouping)
    with fp.inject_faults({"io.resident_callback": fail_nth(2)}):
        res = sup.run((X, y), w0)
    assert res.completed and res.attempts == 2
    np.testing.assert_array_equal(np.asarray(res.weights),
                                  np.asarray(wRef))
    np.testing.assert_array_equal(res.loss_history, hRef)


# ---- knobs / planner -------------------------------------------------------

def test_set_residency_validates():
    with pytest.raises(ValueError, match="cadence 1"):
        GradientDescent().set_residency(1)
    with pytest.raises(ValueError, match="cadence"):
        GradientDescent().set_residency(-2)
    assert GradientDescent().set_residency(4).resident_cadence == 4
    assert GradientDescent().set_residency(0).resident_cadence == 0


def test_residency_without_superstep_warns_and_falls_back(rng):
    X, y = _data(rng, n=256, d=6)
    o = _opt("sliced", iters=6, k=1, c=2)
    with pytest.warns(RuntimeWarning, match="fused superstep executor"):
        w, h = o.optimize_with_history((X, y), np.zeros(6, np.float32))
    assert len(h) == 6


def test_streamed_host_sampled_residency_warns_and_falls_back(rng):
    X, y = _data(rng, n=512, d=8)
    cfg = SGDConfig(step_size=0.1, num_iterations=8,
                    mini_batch_fraction=0.25, convergence_tol=0.0,
                    sampling="indexed", seed=7)
    with pytest.warns(RuntimeWarning, match="host hop IS the data"):
        w, h = _stream(cfg, X, y, superstep_k=4, resident_cadence=2)
    assert len(h) == 8


def test_choose_residency_crossover_rule():
    from tpu_sgd.plan import choose_residency

    # window must hold >= 2 supersteps: K=4 within checkpoint_every=10
    # fits C=2; checkpoint_every=7 fits only one superstep -> 0
    assert choose_residency(4, checkpoint_every=10) == 2
    assert choose_residency(4, checkpoint_every=7) == 0
    # no fused executor, no residency
    assert choose_residency(1, checkpoint_every=100) == 0
    # the tighter of checkpoint cadence and preemption budget wins
    assert choose_residency(4, checkpoint_every=100,
                            preempt_latency_iters=9) == 2
    # cap bounds the ring
    assert choose_residency(2, checkpoint_every=10 ** 6, cap=16) == 16


def test_plan_applies_residency_and_user_knob_wins():
    from tpu_sgd.plan import Plan

    opt = GradientDescent()
    Plan("host_streamed", "t", superstep=8, residency=4).apply(opt)
    assert opt.resident_cadence == 4 and opt.superstep == 8
    Plan("resident_stock", "t").apply(opt)
    assert opt.resident_cadence == 0
    opt2 = GradientDescent().set_residency(6)
    Plan("host_streamed", "t", superstep=8, residency=2).apply(opt2)
    assert opt2.resident_cadence == 6


def test_planner_picks_residency_for_full_batch_streams():
    from tpu_sgd.plan import plan

    p = plan(200_000, 16, itemsize=4, sampling="bernoulli",
             mini_batch_fraction=1.0, num_iterations=1000,
             free_hbm=8e6, host_resident_ok=True, checkpoint_every=64)
    assert p.schedule == "host_streamed"
    assert p.superstep > 1
    assert p.residency >= 2
    assert p.estimates["residency"] == p.residency
    # sampled feeds stay on the superstep driver
    p2 = plan(200_000, 16, itemsize=4, sampling="indexed",
              mini_batch_fraction=0.02, num_iterations=1000,
              free_hbm=8e6, host_resident_ok=True, checkpoint_every=64)
    assert p2.residency == 0


# ---- runtime twin: dispatch counting ---------------------------------------

def test_count_dispatches_counts_warm_jit_calls():
    import jax
    import jax.numpy as jnp

    from tpu_sgd.analysis import (DispatchCountError,
                                  assert_dispatch_count,
                                  count_dispatches)

    @jax.jit
    def f(x):
        return x * 2 + 1

    x = jnp.ones(4)
    f(x)  # warm (fastpath installed — the hook must still see calls)
    with count_dispatches() as c:
        for _ in range(3):
            jax.block_until_ready(f(x))
    assert c["n"] == 3
    with pytest.raises(DispatchCountError, match="launched 2"):
        with assert_dispatch_count(1):
            f(x)
            jax.block_until_ready(f(x))
    with assert_dispatch_count(2, at_most=True):
        jax.block_until_ready(f(x))
    # restored: the fastpath works again after the region
    assert int(f(x)[0]) == 3
