"""Unit tests for bench.py's decision logic.

The benchmark is the round's headline artifact; its host-side arithmetic
(stopping rule, matched-loss speedup, persisted-result fallback) must not
regress silently.  Device measurement itself is exercised on hardware, not
here.
"""

import importlib.util
import json
import os
import sys

import numpy as np
import pytest

_BENCH_PATH = os.path.join(os.path.dirname(__file__), "..", "bench.py")


@pytest.fixture(scope="module")
def bench():
    """Import bench.py as a module without running main()."""
    spec = importlib.util.spec_from_file_location(
        "bench_module", _BENCH_PATH
    )
    mod = importlib.util.module_from_spec(spec)
    sys.modules["bench_module"] = mod
    spec.loader.exec_module(mod)
    return mod


def test_first_crossing(bench):
    assert bench._first_crossing([1.0, 0.5, 0.04, 0.01], 0.05) == 3
    assert bench._first_crossing([0.04], 0.05) == 1
    assert bench._first_crossing([1.0, 0.9], 0.05) is None
    assert bench._first_crossing([], 0.05) is None


def test_matched_loss_speedup_math(bench):
    t = bench.TARGET_LOSS
    cpu = {"matched_iter_s": 0.5,
           "matched_losses": [t * 4, t * 2, t, t / 2]}
    tpu = {"matched_iter_s": 0.001,
           "matched_losses": [t * 4, t * 2, t * 1.5, t * 0.9]}
    speedup, detail = bench.matched_loss_speedup(cpu, tpu)
    # cpu hits at iter 3 (1.5 s), tpu at iter 4 (0.004 s)
    np.testing.assert_allclose(speedup, 1.5 / 0.004)
    assert detail["cpu_hit_iter"] == 3 and detail["tpu_hit_iter"] == 4
    np.testing.assert_allclose(detail["cpu_wall_s"], 1.5)


def test_matched_loss_speedup_no_crossing(bench):
    t = bench.TARGET_LOSS
    cpu = {"matched_iter_s": 0.5, "matched_losses": [t * 4, t * 2]}
    tpu = {"matched_iter_s": 0.001, "matched_losses": [t / 2]}
    speedup, detail = bench.matched_loss_speedup(cpu, tpu)
    assert speedup is None and detail is None


def test_report_persisted_marks_stale(bench, tmp_path, monkeypatch, capsys):
    record = {
        "timestamp": "2026-07-30T06:11:17",
        "result": {"metric": "m", "value": 18.2, "unit": "epochs/sec"},
    }
    path = tmp_path / "last.json"
    path.write_text(json.dumps(record))
    monkeypatch.setattr(bench, "LAST_TPU_PATH", str(path))
    bench._report_persisted()
    out = capsys.readouterr().out.strip().splitlines()[-1]
    reported = json.loads(out)
    assert reported["value"] == 18.2
    assert "persisted TPU measurement" in reported["note"]
    assert "2026-07-30T06:11:17" in reported["note"]


def test_streamed_summary_uses_measured_rows(bench):
    """epochs/sec must be epochs of the MEASURED dataset: overriding
    BENCH_STREAM_ROWS must not silently rescale to the 10M-row problem."""
    walls = [5.0, 1.2, 1.0, 1.0, 1.0]  # first two are compile/cold
    s = bench._streamed_summary(
        rows=1_000_000, dim=1000, frac=0.1, gen_s=10.0, iter_walls=walls,
        total_s=9.2, final_loss=0.05,
    )
    assert s["steady_state_iter_s"] == 1.0
    # frac=0.1 of 1M rows per second of steady iteration
    assert s["rows_per_sec"] == pytest.approx(100_000.0)
    # epochs of the 1M-row dataset, NOT divided by TARGET_ROWS
    assert s["epochs_per_sec"] == pytest.approx(0.1)
    assert s["iters"] == 5


def test_streamed_summary_short_run_falls_back_to_mean(bench):
    s = bench._streamed_summary(
        rows=100, dim=10, frac=0.1, gen_s=0.0, iter_walls=[2.0, 2.0], total_s=4.0,
        final_loss=1.0,
    )
    assert s["steady_state_iter_s"] == pytest.approx(2.0)
