"""Unit tests for bench.py's decision logic.

The benchmark is the round's headline artifact; its host-side arithmetic
(stopping rule, matched-loss speedup, persisted-result fallback) must not
regress silently.  Device measurement itself is exercised on hardware, not
here.
"""

import importlib.util
import json
import os
import sys

import numpy as np
import pytest

_BENCH_PATH = os.path.join(os.path.dirname(__file__), "..", "bench.py")


@pytest.fixture(scope="module")
def bench():
    """Import bench.py as a module without running main()."""
    spec = importlib.util.spec_from_file_location(
        "bench_module", _BENCH_PATH
    )
    mod = importlib.util.module_from_spec(spec)
    sys.modules["bench_module"] = mod
    spec.loader.exec_module(mod)
    return mod


def test_first_crossing(bench):
    assert bench._first_crossing([1.0, 0.5, 0.04, 0.01], 0.05) == 3
    assert bench._first_crossing([0.04], 0.05) == 1
    assert bench._first_crossing([1.0, 0.9], 0.05) is None
    assert bench._first_crossing([], 0.05) is None


def test_matched_loss_speedup_math(bench):
    t = bench.TARGET_LOSS
    cpu = {"matched_iter_s": 0.5,
           "matched_losses": [t * 4, t * 2, t, t / 2]}
    tpu = {"matched_iter_s": 0.001,
           "matched_losses": [t * 4, t * 2, t * 1.5, t * 0.9]}
    speedup, detail = bench.matched_loss_speedup(cpu, tpu)
    # cpu hits at iter 3 (1.5 s), tpu at iter 4 (0.004 s)
    np.testing.assert_allclose(speedup, 1.5 / 0.004)
    assert detail["cpu_hit_iter"] == 3 and detail["tpu_hit_iter"] == 4
    np.testing.assert_allclose(detail["cpu_wall_s"], 1.5)


def test_matched_loss_speedup_no_crossing(bench):
    t = bench.TARGET_LOSS
    cpu = {"matched_iter_s": 0.5, "matched_losses": [t * 4, t * 2]}
    tpu = {"matched_iter_s": 0.001, "matched_losses": [t / 2]}
    speedup, detail = bench.matched_loss_speedup(cpu, tpu)
    assert speedup is None and detail is None


def test_report_persisted_marks_stale(bench, tmp_path, monkeypatch, capsys):
    record = {
        "timestamp": "2026-07-30T06:11:17",
        "result": {"metric": "m", "value": 18.2, "unit": "epochs/sec"},
    }
    path = tmp_path / "last.json"
    path.write_text(json.dumps(record))
    monkeypatch.setattr(bench, "LAST_TPU_PATH", str(path))
    bench._report_persisted()
    out = capsys.readouterr().out.strip().splitlines()[-1]
    reported = json.loads(out)
    assert reported["value"] == 18.2
    assert "persisted TPU measurement" in reported["note"]
    assert "2026-07-30T06:11:17" in reported["note"]


def test_streamed_summary_uses_measured_rows(bench):
    """epochs/sec must be epochs of the MEASURED dataset: overriding
    BENCH_STREAM_ROWS must not silently rescale to the 10M-row problem."""
    walls = [5.0, 1.2, 1.0, 1.0, 1.0]  # first two are compile/cold
    s = bench._streamed_summary(
        rows=1_000_000, dim=1000, frac=0.1, gen_s=10.0, iter_walls=walls,
        total_s=9.2, final_loss=0.05,
    )
    assert s["steady_state_iter_s"] == 1.0
    # frac=0.1 of 1M rows per second of steady iteration
    assert s["rows_per_sec"] == pytest.approx(100_000.0)
    # epochs of the 1M-row dataset, NOT divided by TARGET_ROWS
    assert s["epochs_per_sec"] == pytest.approx(0.1)
    assert s["iters"] == 5


def test_streamed_summary_short_run_falls_back_to_mean(bench):
    s = bench._streamed_summary(
        rows=100, dim=10, frac=0.1, gen_s=0.0, iter_walls=[2.0, 2.0], total_s=4.0,
        final_loss=1.0,
    )
    assert s["steady_state_iter_s"] == pytest.approx(2.0)


def test_fit_steady_state_recovers_line(bench):
    """Exact linear points recover (slope, fixed) with ~zero residuals."""
    slope, fixed, fit = bench.fit_steady_state(
        [(100, 0.065 + 100 * 2e-5), (300, 0.065 + 300 * 2e-5),
         (1200, 0.065 + 1200 * 2e-5)])
    assert slope == pytest.approx(2e-5, rel=1e-9)
    assert fixed == pytest.approx(0.065, rel=1e-9)
    assert all(abs(r) < 1e-6 for r in fit["residual_ms"])
    assert fit["slope_rel_err"] == pytest.approx(0.0, abs=1e-6)


def test_fit_steady_state_jitter_residuals_and_error(bench):
    """Launch jitter shows up in the residuals and the slope error bar —
    the visibility the round-3 two-point fit lacked (VERDICT r3 weak #1)."""
    rng = np.random.default_rng(0)
    its = [1200, 3600, 14400]
    true_slope, true_fixed, jitter = 2.5e-5, 0.065, 0.015
    pts = [(i, true_fixed + true_slope * i + jitter * rng.normal())
           for i in its]
    slope, fixed, fit = bench.fit_steady_state(pts)
    # legs are long enough that the slope survives 15 ms of jitter
    assert slope == pytest.approx(true_slope, rel=0.15)
    assert len(fit["residual_ms"]) == 3
    assert fit["slope_rel_err"] is not None and fit["slope_rel_err"] < 0.15


def test_fit_steady_state_nonpositive_slope_fallback(bench):
    """A jitter-inverted fit (short legs, noisy host) falls back to the
    longest run's mean instead of reporting a negative rate."""
    slope, fixed, fit = bench.fit_steady_state([(30, 0.5), (120, 0.4)])
    assert slope == pytest.approx(0.4 / 120)
    assert fixed == 0.0
    assert "fallback" in fit


def test_fit_steady_state_two_points_matches_old_protocol(bench):
    """With exactly two points the regression degenerates to the round-3
    two-point fit (slope through the points, no error bar)."""
    slope, fixed, fit = bench.fit_steady_state([(30, 0.1), (120, 0.25)])
    assert slope == pytest.approx((0.25 - 0.1) / 90)
    assert fixed == pytest.approx(0.1 - slope * 30)
    assert "slope_rel_err" not in fit


def test_promote_measured_at_size(bench):
    """VERDICT r4 #3: the measured-at-size figure IS the headline value;
    the resident-slab conversion demotes to a named secondary field and
    vs_baseline rescales with the promotion."""
    result = {"metric": "m", "value": 1210.9, "vs_baseline": 181092.64}
    record = {"streamed": {"gram": {
        "epochs_per_sec_post_build": 3885.21, "epochs_per_sec_amortized_100":
        0.8213, "rows_used": 9994240, "dim": 1000, "build_s": 278.7,
        "build_feed_gb_per_s": 0.0717}}}
    bench.promote_measured_at_size(result, record)
    assert result["value"] == 3885.2  # MEASURED at size leads
    assert result["epochs_per_sec_converted_from_resident"] == 1210.9
    assert result["vs_baseline"] == pytest.approx(
        181092.64 * 3885.21 / 1210.9, rel=1e-3)
    assert result["epochs_per_sec_amortized_100"] == 0.82
    assert result["build_s"] == 278.7
    assert result["measured_rows"] == 9994240
    assert "MEASURED" in result["value_basis"]
    # the amortized figure carries its environment basis: a cold reader
    # must see it is tunnel-feed-bound, not a device property
    assert "tunnel" in result["amortized_basis"]
    assert "pod-local" in result["amortized_basis"]
    # absent capture: result untouched
    r2 = {"metric": "m"}
    bench.promote_measured_at_size(r2, {"streamed": None})
    assert r2 == {"metric": "m"}


def test_promote_measured_at_size_idempotent(bench):
    """Re-promotion (the stream-gram check merges fresh captures into a
    persisted record; _report_persisted promotes on read) must not
    double-rescale vs_baseline or lose the pristine conversion."""
    result = {"metric": "m", "value": 1210.9, "vs_baseline": 181092.64}
    gram = {
        "epochs_per_sec_post_build": 3885.21,
        "epochs_per_sec_amortized_100": 0.8213,
        "rows_used": 9994240, "dim": 1000, "build_s": 278.7,
        "build_feed_gb_per_s": 0.0717,
    }
    record = {"streamed": {"gram": gram}}
    bench.promote_measured_at_size(result, record)
    once = dict(result)
    bench.promote_measured_at_size(result, record)
    assert result == once  # same capture: a no-op
    # a FRESHER capture re-promotes from the new measurement
    gram2 = dict(gram, epochs_per_sec_post_build=4000.0)
    bench.promote_measured_at_size(result, {"streamed": {"gram": gram2}})
    assert result["value"] == 4000.0
    assert result["epochs_per_sec_converted_from_resident"] == 1210.9
    assert result["vs_baseline"] == pytest.approx(
        181092.64 * 4000.0 / 1210.9, rel=1e-3)

def _matched(cpu_wall, tpu_wall=0.06, **over):
    m = {"target_loss": None, "rows": None, "iters_budget": 25,
         "cpu_hit_iter": 20, "tpu_hit_iter": 20,
         "cpu_wall_s": cpu_wall, "tpu_wall_s": tpu_wall}
    m.update(over)
    return m


def _with_workload(bench, m):
    m["target_loss"] = bench.TARGET_LOSS
    m["rows"] = bench.MATCHED_ROWS
    return m


def test_keep_conservative_matched_prior_wins(bench):
    """A contended fresh capture (higher ratio) must not displace the
    prior quiet one; the result speedup recomputes from the prior."""
    prev = {"timestamp": "T0",
            "matched": _with_workload(bench, _matched(12.0, 0.06))}
    record = {"timestamp": "T1",
              "matched": _with_workload(bench, _matched(39.0, 0.065))}
    result = {"matched_loss_speedup": 600.0}
    bench.keep_conservative_matched(prev, record, result)
    assert record["matched"]["cpu_wall_s"] == 12.0
    assert record["matched"]["captured_at"] == "T0"
    np.testing.assert_allclose(result["matched_loss_speedup"], 200.0)
    disp = record["matched"]["displaced_contended_capture"]
    assert disp["cpu_wall_s"] == 39.0
    assert disp["captured_at"] == "T1"


def test_keep_conservative_matched_fresh_wins(bench):
    """A quieter fresh capture (lower ratio) IS the conservative one
    and replaces the prior untouched."""
    prev = {"timestamp": "T0",
            "matched": _with_workload(bench, _matched(39.0))}
    record = {"matched": _with_workload(bench, _matched(12.0))}
    result = {"matched_loss_speedup": 200.0}
    bench.keep_conservative_matched(prev, record, result)
    assert record["matched"]["cpu_wall_s"] == 12.0
    assert result["matched_loss_speedup"] == 200.0
    assert "displaced_contended_capture" not in record["matched"]


def test_keep_conservative_matched_compares_ratios_not_walls(bench):
    """A prior with a LOWER CPU wall but a faster TPU wall can carry a
    HIGHER ratio than the fresh quiet run; conservatism compares the
    computed speedups, so the fresh (lower-ratio) capture stays."""
    prev = {"timestamp": "T0",
            "matched": _with_workload(bench, _matched(12.0, 0.03))}  # 400x
    record = {"timestamp": "T1",
              "matched": _with_workload(bench, _matched(13.0, 0.065))}  # 200x
    result = {"matched_loss_speedup": 200.0}
    bench.keep_conservative_matched(prev, record, result)
    assert record["matched"]["cpu_wall_s"] == 13.0
    assert result["matched_loss_speedup"] == 200.0


def test_keep_conservative_matched_no_fresh(bench):
    """A run whose matched leg produced nothing keeps the prior capture
    (clobber protection, same as the streamed/gram legs)."""
    prev = {"timestamp": "T0",
            "matched": _with_workload(bench, _matched(12.0, 0.06))}
    record = {"matched": None}
    result = {}
    bench.keep_conservative_matched(prev, record, result)
    assert record["matched"]["cpu_wall_s"] == 12.0
    np.testing.assert_allclose(result["matched_loss_speedup"], 200.0)


def test_keep_conservative_matched_workload_mismatch(bench):
    """A prior capture from a different workload or target never applies."""
    prev = {"timestamp": "T0", "matched": _matched(12.0, rows=1234,
                                                   target_loss=0.5)}
    record = {"matched": _with_workload(bench, _matched(39.0))}
    result = {"matched_loss_speedup": 600.0}
    bench.keep_conservative_matched(prev, record, result)
    assert record["matched"]["cpu_wall_s"] == 39.0
    assert result["matched_loss_speedup"] == 600.0


def _cpu_baseline(bench, eps, **over):
    b = {"epochs_per_sec": eps, "rows": bench.MATCHED_ROWS,
         "dim": bench.DIM, "captured_at": "T0"}
    b.update(over)
    return b


def test_keep_conservative_cpu_baseline_prior_wins(bench):
    """A loaded host can only SLOW the deterministic baseline, inflating
    vs_baseline; the fastest observed CPU rate is authoritative."""
    prev = {"timestamp": "T0",
            "cpu_baseline": _cpu_baseline(bench, 0.0040)}
    record = {"timestamp": "T1",
              "cpu_baseline": _cpu_baseline(bench, 0.0020,
                                            captured_at="T1")}
    result = {"vs_baseline": 1_900_000.0}
    bench.keep_conservative_cpu_baseline(prev, record, result, 3900.0)
    assert record["cpu_baseline"]["epochs_per_sec"] == 0.0040
    np.testing.assert_allclose(result["vs_baseline"], 3900.0 / 0.0040)
    disp = record["cpu_baseline"]["displaced_contended_reading"]
    assert disp["epochs_per_sec"] == 0.0020
    assert disp["captured_at"] == "T1"


def test_keep_conservative_cpu_baseline_fresh_wins(bench):
    """A faster fresh reading (quieter machine) replaces the prior."""
    prev = {"timestamp": "T0",
            "cpu_baseline": _cpu_baseline(bench, 0.0020)}
    record = {"cpu_baseline": _cpu_baseline(bench, 0.0041)}
    result = {"vs_baseline": 951_219.51}
    bench.keep_conservative_cpu_baseline(prev, record, result, 3900.0)
    assert record["cpu_baseline"]["epochs_per_sec"] == 0.0041
    assert result["vs_baseline"] == 951_219.51  # untouched


def test_keep_conservative_cpu_baseline_workload_mismatch(bench):
    """A prior baseline from a different shape never applies."""
    prev = {"timestamp": "T0",
            "cpu_baseline": _cpu_baseline(bench, 0.0040, rows=1234)}
    record = {"cpu_baseline": _cpu_baseline(bench, 0.0020)}
    result = {"vs_baseline": 500.0}
    bench.keep_conservative_cpu_baseline(prev, record, result, 3900.0)
    assert record["cpu_baseline"]["epochs_per_sec"] == 0.0020
    assert result["vs_baseline"] == 500.0


def test_keep_conservative_cpu_baseline_no_prior(bench):
    """Old-format records without a cpu_baseline are a no-op."""
    record = {"cpu_baseline": _cpu_baseline(bench, 0.0020)}
    result = {"vs_baseline": 500.0}
    bench.keep_conservative_cpu_baseline({}, record, result, 3900.0)
    assert result["vs_baseline"] == 500.0


def test_keep_conservative_cpu_baseline_no_tpu_eps(bench):
    """Without a TPU rate vs_baseline cannot be recomputed; the record
    keeps the self-consistent fresh pair rather than a mismatched one."""
    prev = {"timestamp": "T0",
            "cpu_baseline": _cpu_baseline(bench, 0.0040)}
    record = {"cpu_baseline": _cpu_baseline(bench, 0.0020)}
    result = {"vs_baseline": 500.0}
    bench.keep_conservative_cpu_baseline(prev, record, result, None)
    assert record["cpu_baseline"]["epochs_per_sec"] == 0.0020
    assert result["vs_baseline"] == 500.0


def test_keep_conservative_cpu_baseline_prior_wins_no_fresh(bench):
    """A record missing its fresh reading still adopts the prior and
    recomputes (and the log path must not crash on the absent fresh)."""
    prev = {"timestamp": "T0",
            "cpu_baseline": _cpu_baseline(bench, 0.0040)}
    record = {"timestamp": "T1"}
    result = {"vs_baseline": 500.0}
    bench.keep_conservative_cpu_baseline(prev, record, result, 3900.0)
    assert record["cpu_baseline"]["epochs_per_sec"] == 0.0040
    np.testing.assert_allclose(result["vs_baseline"], 3900.0 / 0.0040)


def test_keep_conservative_cpu_baseline_malformed_prior(bench):
    """A hand-edited prior (string rate / non-dict field) raises inside
    the keeper; main()'s best-effort block catches it — here we assert
    the error types stay within that block's widened except clause."""
    record = {"cpu_baseline": _cpu_baseline(bench, 0.0020)}
    for bad in ({"cpu_baseline": {"epochs_per_sec": "fast", "rows": 1}},
                {"cpu_baseline": "oops"}):
        try:
            bench.keep_conservative_cpu_baseline(
                bad, dict(record), {"vs_baseline": 1.0}, 3900.0)
        except (TypeError, KeyError, AttributeError, ValueError):
            pass  # must be one of the types main() suppresses


def test_enrich_from_prev_isolates_sections(bench):
    """A malformed `matched` in a hand-edited prior must not disable the
    cpu-baseline keeper, and a malformed leg must not leak into the
    record (each enrichment step is independently best-effort)."""
    prev = {"timestamp": "T0",
            "matched": _with_workload(bench, _matched("corrupt", 0.06)),
            "chunked": {"not": "a list"},
            "gram": [{"ok": 1}],
            "cpu_baseline": _cpu_baseline(bench, 0.0040)}
    record = {"timestamp": "T1", "chunked": None, "gram": None,
              "pallas": None,
              "cpu_baseline": _cpu_baseline(bench, 0.0020)}
    result = {"vs_baseline": 500.0, "matched_loss_speedup": 600.0}
    streamed = bench.enrich_from_prev(prev, record, result, 3900.0)
    assert streamed is None
    assert record["chunked"] is None            # malformed: not restored
    assert record["gram"] == [{"ok": 1, "captured_at": "T0"}]
    # the bad matched section did NOT stop the baseline keeper
    assert record["cpu_baseline"]["epochs_per_sec"] == 0.0040
    np.testing.assert_allclose(result["vs_baseline"], 3900.0 / 0.0040)
    assert result["matched_loss_speedup"] == 600.0  # untouched by corrupt


def test_enrich_from_prev_restores_streamed(bench):
    """A prior streamed capture survives a run that skipped the leg; an
    errored or non-dict one is ignored."""
    prev = {"timestamp": "T0", "streamed": {"iter_s": 68.0}}
    out = bench.enrich_from_prev(prev, {}, {}, 1.0)
    assert out == {"iter_s": 68.0, "captured_at": "T0"}
    assert bench.enrich_from_prev(
        {"streamed": {"error": "x"}}, {}, {}, 1.0) is None
    assert bench.enrich_from_prev({"streamed": "bad"}, {}, {}, 1.0) is None
