"""Execution-planner decision boundaries + train() auto-planning.

The planner (``tpu_sgd/plan.py``) is the DAGScheduler/``cache()`` analogue
(SURVEY.md §2 #16): ``train()`` with zero schedule flags must land on the
measured-best schedule.  These tests pin the decision boundaries with an
explicit ``free_hbm`` (the probe is environment-dependent) and then drive
the wired-up model layer end to end.
"""

import logging
import warnings

import jax.numpy as jnp
import numpy as np
import pytest

from tpu_sgd.plan import (CostModel, Plan, SCHEDULES,  # noqa: F401
                          choose_block_rows, device_budget, plan, plan_for)


def test_plan_module_attribute_not_shadowed():
    """`import tpu_sgd.plan as m` must resolve to the MODULE: the package
    must not re-export the bare `plan` function under the same name
    (regression: `tpu_sgd.plan.plan(...)` raised AttributeError)."""
    import types

    import tpu_sgd
    import tpu_sgd.plan as m

    assert isinstance(tpu_sgd.plan, types.ModuleType)
    assert isinstance(m, types.ModuleType) and callable(m.plan)

GB = 1e9


# ---- pure decision boundaries --------------------------------------------

def test_resident_gram_for_big_least_squares_full_batch():
    p = plan(3_000_000, 1000, itemsize=2, gram_able=True,
             mini_batch_fraction=1.0, num_iterations=5000,
             free_hbm=12 * GB)
    assert p.schedule == "resident_gram"
    assert not p.aligned  # exact mode is the default
    assert p.block_rows is not None
    assert p.estimates["build_amortize_iters"] < 5000
    assert "fits" in p.reason and "B=" in p.reason


def test_short_run_amortization_keeps_stock():
    """The one-time statistics build must pay for itself inside the run
    (VERDICT r3 #1: warn/avoid when build_amortize_iters > iterations)."""
    p = plan(3_000_000, 1000, itemsize=2, gram_able=True,
             mini_batch_fraction=1.0, num_iterations=50,
             free_hbm=12 * GB)
    assert p.schedule == "resident_stock"
    assert "amortize" in p.reason
    assert p.estimates["build_amortize_iters"] > 50


def test_small_problem_keeps_stock():
    """Tiny datasets stay on the bitwise round-2 stock path — the build
    overhead dominates any per-iteration saving."""
    p = plan(100_000, 100, gram_able=True, num_iterations=100,
             free_hbm=12 * GB)
    assert p.schedule == "resident_stock"


def test_non_least_squares_never_grams():
    p = plan(3_000_000, 1000, itemsize=2, gram_able=False,
             num_iterations=10_000, free_hbm=12 * GB)
    assert p.schedule == "resident_stock"


def test_bernoulli_sampling_is_honored():
    """The planner never changes the user's sampling semantics: bernoulli
    mini-batches disqualify gram (sliced windows only)."""
    p = plan(3_000_000, 1000, itemsize=2, gram_able=True,
             sampling="bernoulli", mini_batch_fraction=0.1,
             num_iterations=10_000, free_hbm=12 * GB)
    assert p.schedule == "resident_stock"
    assert "sampling" in p.reason


def test_sliced_sampling_qualifies_gram():
    p = plan(3_000_000, 1000, itemsize=2, gram_able=True,
             sampling="sliced", mini_batch_fraction=0.1,
             num_iterations=10_000, free_hbm=12 * GB)
    assert p.schedule == "resident_gram"


def test_beyond_hbm_least_squares_goes_virtual_gram():
    """The 10Mx1000 config-4 shape: rows exceed HBM, statistics fit —
    one streaming build pass, then zero-transfer iterations."""
    p = plan(10_000_000, 1000, itemsize=2, gram_able=True,
             sampling="sliced", mini_batch_fraction=0.1,
             num_iterations=1000, free_hbm=12 * GB)
    assert p.schedule == "streamed_virtual_gram"
    assert p.aligned  # virtual stats are aligned by construction...
    assert "ALIGNED" in p.reason  # ...and the plan says so loudly
    assert p.estimates["stack_bytes"] < 12 * GB


def test_beyond_hbm_non_gram_partial_residency():
    """Sliced non-LS (or bernoulli-excluded) data just beyond HBM keeps a
    resident prefix."""
    p = plan(10_000_000, 1000, itemsize=2, gram_able=False,
             sampling="sliced", mini_batch_fraction=0.1,
             num_iterations=1000, free_hbm=12 * GB)
    assert p.schedule == "partial_residency"
    assert p.resident_rows > 0
    assert p.estimates["resident_window_p"] >= 0.05


def test_beyond_hbm_bernoulli_streams():
    p = plan(10_000_000, 1000, itemsize=2, gram_able=False,
             sampling="bernoulli", mini_batch_fraction=0.1,
             num_iterations=1000, free_hbm=12 * GB)
    assert p.schedule == "host_streamed"


def test_beyond_hbm_meshed_goes_virtual_gram():
    """Virtual gram composes with the mesh (round 4): per-shard statistics
    streamed to each device — config 4's 8-way shape at 8x-beyond-HBM
    scale picks it."""
    p = plan(80_000_000, 1000, itemsize=2, gram_able=True,
             sampling="sliced", mini_batch_fraction=0.1,
             num_iterations=1000, n_devices=8, free_hbm=12 * GB)
    assert p.schedule == "streamed_virtual_gram"
    # non-gram data at the same scale still streams
    p2 = plan(80_000_000, 1000, itemsize=2, gram_able=False,
              sampling="sliced", mini_batch_fraction=0.1,
              num_iterations=1000, n_devices=8, free_hbm=12 * GB)
    assert p2.schedule == "host_streamed"


def test_mesh_divides_rows_for_fit():
    """8 devices hold 8x the rows: a dataset that streams on one chip is
    resident on the mesh."""
    one = plan(10_000_000, 1000, itemsize=2, gram_able=False,
               num_iterations=100, free_hbm=12 * GB)
    eight = plan(10_000_000, 1000, itemsize=2, gram_able=False,
                 num_iterations=100, n_devices=8, free_hbm=12 * GB)
    assert one.schedule == "host_streamed"
    assert eight.schedule == "resident_stock"


def test_device_committed_data_never_streams():
    p = plan(10_000_000, 1000, itemsize=2, gram_able=False,
             num_iterations=100, free_hbm=12 * GB,
             host_resident_ok=False)
    assert p.schedule == "resident_stock"
    assert "device-committed" in p.reason


def test_huge_d_disqualifies_gram():
    """Very wide features break the gram economics two ways (ops/gram.py
    module docs): beyond-HBM, no block size makes the O(d²) stack fit;
    resident, the per-iteration d² prefix matvec costs more than the row
    reads it replaces.  Both must fall back."""
    # 200 GB of rows, 40 GB per Gram matrix: nothing fits -> streams
    p = plan(1_000_000, 100_000, itemsize=2, gram_able=True,
             num_iterations=10_000, free_hbm=12 * GB)
    assert p.schedule == "host_streamed"
    # 0.4 GB of rows fit, but reading two (20k, 20k) prefix entries per
    # iteration exceeds the two-pass row traffic -> negative saving
    p = plan(10_000, 20_000, itemsize=2, gram_able=True,
             num_iterations=10_000, free_hbm=12 * GB)
    assert p.schedule == "resident_stock"


def test_force_overrides_with_warning():
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        p = plan(3_000_000, 1000, itemsize=2, gram_able=True,
                 mini_batch_fraction=1.0, num_iterations=50,
                 free_hbm=12 * GB, force="resident_gram")
    assert p.schedule == "resident_gram"
    assert any("NET LOSS" in str(r.message) for r in rec)
    assert "forced by caller" in p.reason


def test_force_rejects_unknown_schedule():
    with pytest.raises(ValueError, match="unknown schedule"):
        plan(1000, 10, force="warp_drive")


def test_choose_block_rows_doubles_to_fit():
    # 1M x 1000: stack at B=4096 is ~(245)*4MB ~ 1GB; at a 0.2GB budget
    # the block must grow
    b_small = choose_block_rows(1_000_000, 1000, 0.2 * GB)
    b_big = choose_block_rows(1_000_000, 1000, 4 * GB)
    assert b_big == 4096
    assert b_small is not None and b_small > b_big
    assert choose_block_rows(1_000_000, 1000, 1e6) is None  # nothing fits


def test_estimates_are_recorded():
    p = plan(3_000_000, 1000, itemsize=2, gram_able=True,
             num_iterations=5000, free_hbm=12 * GB)
    for key in ("n", "d", "free_hbm", "stock_iter_s", "gram_iter_s",
                "gram_build_s", "build_amortize_iters", "fits_resident"):
        assert key in p.estimates, key


def test_device_budget_returns_positive():
    free, source = device_budget()
    assert free > 0
    assert source in ("memory_stats", "fallback")


# ---- plan_for probing -----------------------------------------------------

def test_plan_for_probes_optimizer(rng):
    from tpu_sgd import GradientDescent

    X = rng.normal(size=(512, 8)).astype(np.float32)
    y = rng.normal(size=(512,)).astype(np.float32)
    opt = GradientDescent()
    p = plan_for(opt, X, y)
    assert p is not None and p.schedule == "resident_stock"
    p.apply(opt)
    assert opt.last_plan is p


def test_plan_for_skips_sparse_and_non_gd(rng):
    from tpu_sgd import GradientDescent, LBFGS
    from tpu_sgd.ops.sparse import sparse_data

    Xs, ys, _ = sparse_data(64, 32, nnz_per_row=4, seed=0)
    assert plan_for(GradientDescent(), Xs, ys) is None
    X = rng.normal(size=(64, 8)).astype(np.float32)
    y = rng.normal(size=(64,)).astype(np.float32)
    assert plan_for(LBFGS(), X, y) is None


def test_apply_clears_previous_schedule(rng):
    from tpu_sgd import GradientDescent

    opt = GradientDescent().set_host_streaming(True, resident_rows=100)
    Plan("resident_stock", "test").apply(opt)
    assert not opt.host_streaming and opt.streaming_resident_rows == 0
    Plan("resident_gram", "test", block_rows=64).apply(opt)
    assert opt.sufficient_stats and opt.gram_block_rows == 64
    Plan("streamed_virtual_gram", "test", block_rows=32,
         aligned=True).apply(opt)
    assert opt.streamed_stats and not opt.sufficient_stats


def test_apply_always_resets_plan_owned_knobs(rng):
    """A previous dataset's gram knobs (block size, streamed-build chunk
    cap, aligned mode) must not leak into the next plan's build — the
    gram identity caches key on them, so stale values silently rebuild
    with the wrong geometry (ADVICE r4)."""
    from tpu_sgd import GradientDescent
    from tpu_sgd.ops.gram import DEFAULT_BLOCK_ROWS

    opt = GradientDescent()
    Plan("streamed_virtual_gram", "small-data plan", block_rows=32,
         batch_rows=64, aligned=True).apply(opt)
    assert opt.gram_batch_rows == 64
    assert opt.gram_block_rows == 32 and opt.gram_aligned
    Plan("resident_stock", "new-data plan").apply(opt)
    assert opt.gram_batch_rows is None
    assert opt.gram_block_rows == DEFAULT_BLOCK_ROWS
    assert not opt.gram_aligned


def test_apply_preserves_user_set_gram_knobs(rng):
    """Knob fields the USER set via set_gram_options survive auto-
    planning: a tight-device batch_rows cap must not be clobbered by a
    plan that carries none (plans only own what the user didn't set)."""
    from tpu_sgd import GradientDescent

    opt = GradientDescent().set_gram_options(batch_rows=256)
    Plan("resident_gram", "auto plan", block_rows=4096).apply(opt)
    assert opt.gram_batch_rows == 256  # user knob preserved
    assert opt.gram_block_rows == 4096  # plan-owned field applied
    opt2 = GradientDescent().set_gram_options(block_rows=64, aligned=True)
    Plan("streamed_virtual_gram", "auto plan", block_rows=4096,
         batch_rows=8192, aligned=False).apply(opt2)
    assert opt2.gram_block_rows == 64 and opt2.gram_aligned
    assert opt2.gram_batch_rows == 8192


def test_knob_setter_keeps_replanning_alive(rng, caplog):
    """set_gram_options is a KNOB, not a schedule choice: after an auto-
    planned run, tweaking a knob must invalidate the plan cache (so the
    next run re-plans, honoring the knob) WITHOUT tripping the manual
    gate that disables planning — a plan-set schedule flag must never
    masquerade as user-set (code-review r5)."""
    from tpu_sgd import LinearRegressionWithSGD

    X = rng.normal(size=(2048, 16)).astype(np.float32)
    w = rng.uniform(-1, 1, 16).astype(np.float32)
    y = (X @ w + 0.05 * rng.normal(size=2048)).astype(np.float32)
    alg = LinearRegressionWithSGD()
    alg.optimizer.set_step_size(1.0)
    alg.run((X, y))
    assert alg.optimizer.last_plan is not None
    alg.optimizer.set_gram_options(batch_rows=256)
    assert alg.optimizer._plan_key is None  # cache invalidated...
    assert alg.optimizer.last_plan is not None  # ...but not the gate
    with caplog.at_level(logging.INFO, logger="tpu_sgd.plan"):
        alg.run((X, y))
    # re-planning DID run (a fresh plan: line logged, key repopulated)
    assert any(r.message.startswith("plan: ") for r in caplog.records)
    assert alg.optimizer._plan_key is not None
    assert alg.optimizer.gram_batch_rows == 256  # user knob survived


def test_force_resident_beyond_hbm_warns():
    """Forcing a resident_* schedule onto beyond-HBM data must warn that
    the slab does not fit — the no-feasible-block guard alone misses this
    case because the streamed builder DID find a block size (ADVICE r4)."""
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        p = plan(10_000_000, 1000, itemsize=2, gram_able=True,
                 mini_batch_fraction=1.0, num_iterations=100_000,
                 free_hbm=12 * GB, force="resident_gram")
    assert p.schedule == "resident_gram"
    assert any("does not fit" in str(r.message) for r in rec)
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        p = plan(10_000_000, 1000, itemsize=2, gram_able=False,
                 mini_batch_fraction=1.0, num_iterations=100,
                 free_hbm=12 * GB, force="resident_stock")
    assert p.schedule == "resident_stock"
    assert any("does not fit" in str(r.message) for r in rec)


# ---- wired into the model layer ------------------------------------------

def test_train_zero_flags_plans_and_logs(rng, caplog):
    from tpu_sgd import LinearRegressionWithSGD

    X = rng.normal(size=(2048, 16)).astype(np.float32)
    w = rng.uniform(-1, 1, 16).astype(np.float32)
    y = (X @ w + 0.05 * rng.normal(size=2048)).astype(np.float32)
    with caplog.at_level(logging.INFO, logger="tpu_sgd.plan"):
        model = LinearRegressionWithSGD.train((X, y), num_iterations=100,
                                              step_size=1.0)
    assert any(r.message.startswith("plan: ") for r in caplog.records)
    err = float(np.linalg.norm(np.asarray(model.weights) - w))
    assert err < 0.1


def test_train_schedule_off_keeps_legacy_path(rng):
    from tpu_sgd import LinearRegressionWithSGD

    X = rng.normal(size=(256, 8)).astype(np.float32)
    y = rng.normal(size=(256,)).astype(np.float32)
    alg = LinearRegressionWithSGD(0.2, 10)
    alg.set_schedule("off")
    alg.run((X, y))
    assert alg.optimizer.last_plan is None


def test_train_manual_flags_win_over_auto(rng):
    from tpu_sgd import LinearRegressionWithSGD

    X = rng.normal(size=(2048, 8)).astype(np.float32)
    w = rng.uniform(-1, 1, 8).astype(np.float32)
    y = (X @ w).astype(np.float32)
    alg = LinearRegressionWithSGD(1.0, 100)
    alg.optimizer.set_sufficient_stats(True)
    model = alg.run((X, y))
    # the planner did not run (it would have cleared/chosen itself)
    assert alg.optimizer.last_plan is None
    assert alg.optimizer.sufficient_stats
    assert np.linalg.norm(np.asarray(model.weights) - w) < 0.1


def test_forced_streamed_virtual_gram_trains(rng):
    """schedule='streamed_virtual_gram' exercises set_streamed_stats end
    to end on a small dataset: build from host rows, iterate from virtual
    statistics, converge."""
    from tpu_sgd import LinearRegressionWithSGD

    n, d = 4096, 12
    X = rng.normal(size=(n, d)).astype(np.float32)
    w = rng.uniform(-1, 1, d).astype(np.float32)
    y = (X @ w + 0.01 * rng.normal(size=n)).astype(np.float32)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)  # net-loss warn ok
        model = LinearRegressionWithSGD.train(
            (X, y), num_iterations=60, step_size=0.3,
            mini_batch_fraction=0.25, sampling="sliced",
            schedule="streamed_virtual_gram",
        )
    assert np.linalg.norm(np.asarray(model.weights) - w) < 0.1


def test_forced_schedule_validates_name():
    from tpu_sgd import LinearRegressionWithSGD

    with pytest.raises(ValueError, match="schedule must be one of"):
        LinearRegressionWithSGD.train(
            (np.zeros((4, 2), np.float32), np.zeros(4, np.float32)),
            schedule="warp_drive",
        )


def test_set_streamed_stats_guards(rng):
    from tpu_sgd import GradientDescent
    from tpu_sgd.ops.gradients import LogisticGradient

    X = rng.normal(size=(256, 8)).astype(np.float32)
    y = rng.normal(size=(256,)).astype(np.float32)
    w0 = jnp.zeros((8,))
    with pytest.raises(NotImplementedError, match="least squares"):
        GradientDescent(LogisticGradient()).set_streamed_stats(True) \
            .optimize((X, np.abs(np.sign(y))), w0)
    from tpu_sgd import make_mesh

    with pytest.raises(NotImplementedError, match="1-D 'data' mesh"):
        GradientDescent().set_streamed_stats(True) \
            .set_mesh(make_mesh(n_data=4, n_model=2)) \
            .optimize((X, y), w0)
    with pytest.raises(ValueError, match="alternative"):
        GradientDescent().set_streamed_stats(True) \
            .set_host_streaming(True).optimize((X, y), w0)
    with pytest.raises(NotImplementedError, match="sliced"):
        GradientDescent().set_streamed_stats(True) \
            .set_mini_batch_fraction(0.5).optimize((X, y), w0)


def test_streamed_stats_matches_manual_virtual_run(rng):
    """set_streamed_stats must reproduce the manual build_streamed +
    GramData-input flow exactly (same build, same aligned windows)."""
    from tpu_sgd import GradientDescent, SimpleUpdater
    from tpu_sgd.ops.gram import GramLeastSquaresGradient

    n, d = 2048, 8
    X = rng.normal(size=(n, d)).astype(np.float32)
    w = rng.uniform(-1, 1, d).astype(np.float32)
    y = (X @ w + 0.05 * rng.normal(size=n)).astype(np.float32)

    def mk():
        return (GradientDescent(updater=SimpleUpdater())
                .set_step_size(0.3).set_num_iterations(25)
                .set_mini_batch_fraction(0.25).set_sampling("sliced")
                .set_convergence_tol(0.0).set_seed(5))

    opt1 = mk().set_streamed_stats(True, block_rows=256)
    w1, h1 = opt1.optimize_with_history((X, y), jnp.zeros((d,)))

    g = GramLeastSquaresGradient.build_streamed(X, y, block_rows=256)
    opt2 = mk()
    opt2.set_gradient(g)
    w2, h2 = opt2.optimize_with_history(
        (g.data, y[:g.data.shape[0]]), jnp.zeros((d,)))
    np.testing.assert_allclose(np.asarray(w1), np.asarray(w2),
                               rtol=1e-6, atol=1e-7)
    np.testing.assert_allclose(np.asarray(h1), np.asarray(h2),
                               rtol=1e-6, atol=1e-7)


def test_schedule_names_stable():
    """The public schedule vocabulary (docs, train(schedule=...), bench)
    must not drift silently."""
    assert SCHEDULES == ("resident_stock", "resident_gram",
                         "partial_residency", "host_streamed",
                         "streamed_virtual_gram")


def test_gram_options_rebuild_on_change(rng):
    """Changing block size between runs on the SAME arrays must rebuild
    (the identity cache keys on the options too)."""
    from tpu_sgd import GradientDescent

    X = rng.normal(size=(1024, 8)).astype(np.float32)
    w = rng.uniform(-1, 1, 8).astype(np.float32)
    y = (X @ w).astype(np.float32)
    opt = (GradientDescent().set_num_iterations(5)
           .set_sufficient_stats(True).set_gram_options(block_rows=128))
    opt.optimize((X, y), jnp.zeros((8,)))
    g1 = opt._gram_entry[2]
    assert g1.data.block_rows == 128
    opt.set_gram_options(block_rows=256)
    opt.optimize((X, y), jnp.zeros((8,)))
    g2 = opt._gram_entry[2]
    assert g2 is not g1 and g2.data.block_rows == 256


def test_second_run_replans_on_new_dataset(rng, caplog):
    """Planner-set flags must not masquerade as manual flags: a second
    run() on the same algorithm re-plans for the new dataset instead of
    reusing the stale schedule (review r4 finding)."""
    from tpu_sgd import LinearRegressionWithSGD

    X1 = rng.normal(size=(256, 8)).astype(np.float32)
    y1 = rng.normal(size=(256,)).astype(np.float32)
    X2 = rng.normal(size=(512, 8)).astype(np.float32)
    y2 = rng.normal(size=(512,)).astype(np.float32)
    alg = LinearRegressionWithSGD(0.2, 5)
    with caplog.at_level(logging.INFO, logger="tpu_sgd.plan"):
        alg.run((X1, y1))
        first = alg.optimizer.last_plan
        alg.run((X2, y2))
        second = alg.optimizer.last_plan
    assert first is not None and second is not None and second is not first
    assert sum(r.message.startswith("plan: ")
               for r in caplog.records) == 2


# ---- quasi-Newton planning (round 4 extension) ---------------------------

class _ShapeOnly:
    """Shape/dtype carrier for boundary tests — np.shape reads .shape
    without materializing, so huge logical datasets cost nothing here."""

    def __init__(self, shape, dtype=np.float32):
        self.shape = shape
        self.dtype = np.dtype(dtype)


def test_plan_quasi_newton_boundaries():
    from tpu_sgd import LBFGS, plan_quasi_newton
    from tpu_sgd.ops.gradients import LogisticGradient

    y = None  # unused by the decision

    # big resident least squares: ~4 full passes/iter -> gram amortizes
    big = _ShapeOnly((3_000_000, 1000), np.float16)  # 2-byte rows
    p = plan_quasi_newton(LBFGS(), big, y, free_hbm=12 * GB)
    assert p.schedule == "resident_gram"
    assert p.block_rows is not None
    assert p.estimates["build_amortize_iters"] < 100

    # small data: build overhead dominates -> stock
    small = _ShapeOnly((10_000, 50))
    p = plan_quasi_newton(LBFGS(), small, y, free_hbm=12 * GB)
    assert p.schedule == "resident_stock"
    assert "amortize" in p.reason

    # beyond HBM: the statistics are the only viable schedule — one
    # streaming build pass, then O(d^2) full-batch evaluations
    huge = _ShapeOnly((100_000_000, 1000), np.float16)
    p = plan_quasi_newton(LBFGS(), huge, y, free_hbm=12 * GB)
    assert p.schedule == "streamed_virtual_gram"
    assert p.block_rows is not None
    assert p.estimates["stack_bytes"] < 12 * GB

    # beyond HBM with an impossible stack (huge d): nothing fits
    huge_d = _ShapeOnly((1_000_000, 100_000), np.float16)
    p = plan_quasi_newton(LBFGS(), huge_d, y, free_hbm=12 * GB)
    assert p.schedule == "resident_stock"
    assert "no schedule fits" in p.reason

    # non-least-squares gradient, resident: stock full-batch passes
    p = plan_quasi_newton(LBFGS(LogisticGradient()), big, y,
                          free_hbm=12 * GB)
    assert p.schedule == "resident_stock"
    assert "no fixed-size statistics" in p.reason

    # non-least-squares gradient, beyond HBM: the chunked treeAggregate
    # CostFun (round 5, VERDICT r4 #1) — host_streamed with a chunk cap
    p = plan_quasi_newton(LBFGS(LogisticGradient()), huge, y,
                          free_hbm=12 * GB)
    assert p.schedule == "host_streamed"
    assert p.batch_rows is not None
    # two in-flight chunks fit in half the budget
    assert 2 * p.batch_rows * 1000 * 2 <= 12 * GB
    assert "treeAggregate" in p.reason

    # schedules outside the quasi-Newton menu still reject
    with pytest.raises(ValueError, match="does not exist behind"):
        plan_quasi_newton(LBFGS(), big, y, free_hbm=12 * GB,
                          force="partial_residency")

    # forcing gram on a short run warns
    opt = LBFGS(max_num_iterations=3)
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        p = plan_quasi_newton(opt, big, y, free_hbm=12 * GB,
                              force="resident_gram")
    assert p.schedule == "resident_gram"
    assert any("NET LOSS" in str(r.message) for r in rec)


def test_train_auto_plans_host_streamed_costfun(rng, caplog, monkeypatch):
    """Zero-flag quasi-Newton train() on beyond-HBM NON-least-squares
    data lands on the chunked-CostFun schedule and still converges — the
    reference's any-size-any-loss CostFun contract (VERDICT r4 #1)."""
    import tpu_sgd.plan as plan_mod
    from tpu_sgd.models import LogisticRegressionWithLBFGS

    monkeypatch.setattr(plan_mod, "device_budget",
                        lambda *a, **k: (8e3, "test"))  # 8 KB "HBM"
    X = rng.normal(size=(512, 8)).astype(np.float32)
    w = rng.uniform(-1, 1, 8).astype(np.float32)
    y = (X @ w > 0).astype(np.float32)
    with caplog.at_level(logging.INFO, logger="tpu_sgd.plan"):
        alg = LogisticRegressionWithLBFGS()
        model = alg.run((X, y))
    msgs = [r.message for r in caplog.records
            if r.message.startswith("plan: ")]
    assert msgs and "host_streamed" in msgs[0]
    assert alg.optimizer.host_streaming
    assert alg.optimizer.stream_batch_rows is not None
    acc = float((np.asarray(model.predict(X)) == y).mean())
    assert acc > 0.9


def test_stale_plan_flags_reset_on_unplannable_input(rng, monkeypatch):
    """A later run on an un-plannable input (BCOO) must not crash on the
    PREVIOUS plan's host_streaming flag — plan-owned flags reset when the
    planner has nothing to say (code-review r5)."""
    import tpu_sgd.plan as plan_mod
    from tpu_sgd.models import LogisticRegressionWithLBFGS
    from tpu_sgd.ops.sparse import sparse_data

    monkeypatch.setattr(plan_mod, "device_budget",
                        lambda *a, **k: (8e3, "test"))
    X = rng.normal(size=(512, 8)).astype(np.float32)
    w = rng.uniform(-1, 1, 8).astype(np.float32)
    y = (X @ w > 0).astype(np.float32)
    alg = LogisticRegressionWithLBFGS(max_num_iterations=5)
    alg.run((X, y))
    assert alg.optimizer.host_streaming  # planner picked the CostFun
    Xs, ys, _ = sparse_data(64, 8, nnz_per_row=3, seed=0)
    ys = np.abs(np.sign(np.asarray(ys)))
    model = alg.run((Xs, ys))  # must not raise "needs dense rows"
    assert not alg.optimizer.host_streaming  # stale flag was reset
    assert model is not None


def test_force_gram_rejected_for_non_ls_gradient():
    """Forcing a statistics schedule onto a loss with no fixed-size
    statistics must raise a clear error naming the loss family, not warn
    about block sizes and silently run stock (code-review r5)."""
    from tpu_sgd import LBFGS, plan_quasi_newton
    from tpu_sgd.ops.gradients import LogisticGradient

    big = _ShapeOnly((3_000_000, 1000), np.float16)
    for force in ("resident_gram", "streamed_virtual_gram"):
        with pytest.raises(ValueError, match="LogisticGradient"):
            plan_quasi_newton(LBFGS(LogisticGradient()), big, None,
                              free_hbm=12 * GB, force=force)


def test_meshed_coercion_defers_device_commit(rng):
    """Meshed quasi-Newton inputs stay HOST arrays through coercion: a
    jnp.asarray there would stage the whole beyond-one-HBM matrix through
    the default device before sharding (code-review r5)."""
    import jax

    from tpu_sgd.optimize.lbfgs import _coerce_inputs

    X = rng.normal(size=(64, 4)).astype(np.float64)
    y = rng.integers(0, 2, 64)
    w0 = np.zeros(4, np.float32)
    Xc, yc, wc = _coerce_inputs(X, y, w0, defer_commit=True)
    assert isinstance(Xc, np.ndarray) and not isinstance(Xc, jax.Array)
    assert isinstance(yc, np.ndarray) and not isinstance(yc, jax.Array)
    assert yc.dtype == np.float32  # int labels still coerce
    assert isinstance(wc, jax.Array)
    # unmeshed coercion commits as before
    Xc2, _, _ = _coerce_inputs(X, y, w0)
    assert isinstance(Xc2, jax.Array)


def test_plan_quasi_newton_meshed_boundaries():
    """VERDICT r4 #5: the quasi-Newton planner divides the HBM budget by
    the data-shard count like the GD planner, and plans the per-shard
    statistics substitution."""
    from tpu_sgd import LBFGS, data_mesh, plan_quasi_newton
    from tpu_sgd.ops.gradients import LogisticGradient

    y = None
    mesh = data_mesh()  # 8-way

    # 8 devices hold 8x the rows: a dataset that must stream on one chip
    # is resident (and gram-able) on the mesh
    mid = _ShapeOnly((40_000_000, 1000), np.float16)  # ~80 GB total
    one = plan_quasi_newton(LBFGS(), mid, y, free_hbm=12 * GB)
    eight = plan_quasi_newton(LBFGS().set_mesh(mesh), mid, y,
                              free_hbm=12 * GB)
    assert one.schedule == "streamed_virtual_gram"
    assert eight.schedule == "resident_gram"
    assert eight.estimates["n_devices"] == 8
    assert "per-shard totals" in eight.reason

    # beyond even the meshed budget: per-shard streamed TOTALS builds
    # (exact — no dropped tail, unlike the single-device prefix build)
    huge = _ShapeOnly((800_000_000, 1000), np.float16)
    p = plan_quasi_newton(LBFGS().set_mesh(mesh), huge, y,
                          free_hbm=12 * GB)
    assert p.schedule == "streamed_virtual_gram"
    assert "EXACT totals" in p.reason

    # meshed non-LS beyond HBM: the chunked CostFun composes with the
    # mesh (per-shard chunk streams + psum)
    p = plan_quasi_newton(LBFGS(LogisticGradient()).set_mesh(mesh),
                          huge, y, free_hbm=12 * GB)
    assert p.schedule == "host_streamed"
    assert p.batch_rows is not None

    # a model-sharded mesh is left alone
    from tpu_sgd import make_mesh

    opt = LBFGS()
    opt.mesh = make_mesh(n_data=4, n_model=2)  # bypass the setter guard
    assert plan_quasi_newton(opt, mid, y, free_hbm=12 * GB) is None


def test_lbfgs_train_auto_plans_and_forced_gram(rng, caplog):
    from tpu_sgd import LinearRegressionWithLBFGS

    X = rng.normal(size=(2048, 12)).astype(np.float32)
    w = rng.uniform(-1, 1, 12).astype(np.float32)
    y = (X @ w + 0.01 * rng.normal(size=2048)).astype(np.float32)

    # zero flags: small data -> stock, but the plan ran and logged
    alg = LinearRegressionWithLBFGS()
    with caplog.at_level(logging.INFO, logger="tpu_sgd.plan"):
        m0 = alg.run((X, y))
    assert alg.optimizer.last_plan is not None
    assert alg.optimizer.last_plan.schedule == "resident_stock"
    assert not alg.optimizer.sufficient_stats
    assert any(r.message.startswith("plan: ") for r in caplog.records)

    # forced gram engages the substitution and reproduces the solution
    alg2 = LinearRegressionWithLBFGS().set_schedule("resident_gram")
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        m1 = alg2.run((X, y))
    assert alg2.optimizer.sufficient_stats
    assert alg2.optimizer._gram_entry is not None
    np.testing.assert_allclose(np.asarray(m1.weights),
                               np.asarray(m0.weights), rtol=1e-3,
                               atol=1e-4)


def test_owlqn_forced_gram_plans(rng):
    from tpu_sgd.models.regression import LassoWithOWLQN

    X = rng.normal(size=(1024, 10)).astype(np.float32)
    w = rng.uniform(-1, 1, 10).astype(np.float32)
    y = (X @ w).astype(np.float32)
    alg = LassoWithOWLQN(reg_param=1e-4).set_schedule("resident_gram")
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        m = alg.run((X, y))
    assert alg.optimizer.sufficient_stats
    assert alg.optimizer._gram_entry is not None
    assert np.all(np.isfinite(np.asarray(m.weights)))


def test_manual_flag_after_auto_plan_wins(rng):
    """A user setter called AFTER an auto-planned run must win on the next
    run — the setters clear last_plan, so the planner steps aside (review
    r4 finding: the planner used to clobber the user's choice)."""
    from tpu_sgd import LinearRegressionWithSGD

    X1 = rng.normal(size=(256, 8)).astype(np.float32)
    y1 = rng.normal(size=(256,)).astype(np.float32)
    X2 = rng.normal(size=(300, 8)).astype(np.float32)
    y2 = rng.normal(size=(300,)).astype(np.float32)
    alg = LinearRegressionWithSGD(0.2, 5)
    alg.run((X1, y1))
    assert alg.optimizer.last_plan is not None  # auto-planned
    alg.optimizer.set_sufficient_stats(True)    # user takes the wheel
    assert alg.optimizer.last_plan is None
    alg.run((X2, y2))
    assert alg.optimizer.sufficient_stats       # NOT clobbered
    assert alg.optimizer.last_plan is None      # planner stayed out


def test_forced_schedule_on_unplanned_input_raises_clearly(rng):
    """Forcing a schedule on an input the planner declines (sparse) must
    raise a clear error, not a quasi-Newton-flavored one."""
    from tpu_sgd import LinearRegressionWithSGD
    from tpu_sgd.ops.sparse import sparse_data

    Xs, ys, _ = sparse_data(64, 16, nnz_per_row=4, seed=0)
    with pytest.raises(ValueError, match="cannot be applied here"):
        LinearRegressionWithSGD.train((Xs, ys), num_iterations=3,
                                      schedule="host_streamed")


def test_forced_partial_residency_messages():
    # data fits: accurate "already fits" error
    with pytest.raises(ValueError, match="already fits"):
        plan(1000, 8, sampling="sliced", mini_batch_fraction=0.1,
             free_hbm=1 * GB, force="partial_residency")
    # beyond HBM but bernoulli: accurate requirements error
    with pytest.raises(ValueError, match="sliced sampling"):
        plan(10_000_000, 1000, itemsize=2, sampling="bernoulli",
             mini_batch_fraction=0.1, free_hbm=1 * GB,
             force="partial_residency")


def test_repeat_runs_skip_replanning(rng, caplog):
    """Identically-shaped repeat runs (the streaming micro-batch loop)
    plan once, not per batch."""
    from tpu_sgd import LinearRegressionWithSGD

    X = rng.normal(size=(256, 8)).astype(np.float32)
    y = rng.normal(size=(256,)).astype(np.float32)
    alg = LinearRegressionWithSGD(0.2, 5)
    with caplog.at_level(logging.INFO, logger="tpu_sgd.plan"):
        for _ in range(4):
            alg.run((X, y))
    assert sum(r.message.startswith("plan: ")
               for r in caplog.records) == 1


def test_device_budget_probe_shapes():
    """memory_stats-reporting devices are probed; zero/absent stats (the
    axon remote-TPU case) fall back to the cost model's default."""

    class Dev:
        def memory_stats(self):
            return {"bytes_limit": 16e9, "bytes_in_use": 4e9}

    free, source = device_budget(Dev())
    assert source == "memory_stats"
    assert free == pytest.approx(12e9 * 0.8)

    class DevZeros:  # axon reports zeros
        def memory_stats(self):
            return {"bytes_limit": 0, "bytes_in_use": 0}

    free, source = device_budget(DevZeros())
    assert source == "fallback" and free > 0

    class DevRaises:
        def memory_stats(self):
            raise RuntimeError("no stats")

    free, source = device_budget(DevRaises())
    assert source == "fallback" and free > 0


def test_lbfgs_streamed_stats_matches_manual_virtual_flow(rng):
    """LBFGS.set_streamed_stats must reproduce the manual build_streamed +
    GramData-input flow exactly, for both LBFGS and OWL-QN."""
    from tpu_sgd import LBFGS
    from tpu_sgd.ops.gram import GramLeastSquaresGradient
    from tpu_sgd.optimize.owlqn import OWLQN

    n, d = 2048, 10
    X = rng.normal(size=(n, d)).astype(np.float32)
    w = rng.uniform(-1, 1, d).astype(np.float32)
    y = (X @ w + 0.01 * rng.normal(size=n)).astype(np.float32)
    w0 = np.zeros((d,), np.float32)

    opt1 = LBFGS(max_num_iterations=10).set_streamed_stats(
        True, block_rows=256)
    w1, h1 = opt1.optimize_with_history((X, y), w0)
    assert opt1._streamed_gram_entry is not None

    g = GramLeastSquaresGradient.build_streamed(X, y, block_rows=256)
    opt2 = LBFGS(g, max_num_iterations=10)
    w2, h2 = opt2.optimize_with_history((g.data, y[:g.data.shape[0]]), w0)
    np.testing.assert_array_equal(np.asarray(w1), np.asarray(w2))
    np.testing.assert_array_equal(np.asarray(h1), np.asarray(h2))

    # repeat call hits the identity cache (no rebuild)
    entry = opt1._streamed_gram_entry
    opt1.optimize_with_history((X, y), w0)
    assert opt1._streamed_gram_entry is entry
    opt1.release_sufficient_stats()
    assert opt1._streamed_gram_entry is None

    # OWL-QN through the same flag
    ow = OWLQN(reg_param=1e-4, max_num_iterations=8).set_streamed_stats(
        True, block_rows=256)
    w3, h3 = ow.optimize_with_history((X, y), w0)
    assert ow._streamed_gram_entry is not None
    assert np.all(np.isfinite(np.asarray(w3))) and h3[-1] <= h3[0]


def test_lbfgs_streamed_stats_guards(rng):
    from tpu_sgd import LBFGS, data_mesh
    from tpu_sgd.ops.gradients import LogisticGradient

    X = rng.normal(size=(128, 6)).astype(np.float32)
    y = rng.normal(size=(128,)).astype(np.float32)
    w0 = np.zeros((6,), np.float32)
    with pytest.raises(NotImplementedError, match="least squares"):
        LBFGS(LogisticGradient()).set_streamed_stats(True) \
            .optimize_with_history((X, np.abs(np.sign(y))), w0)
    # meshed streamed statistics are SUPPORTED since round 5 (per-shard
    # totals builds — tests/test_lbfgs.py) — the old single-device guard
    # is gone; the remaining mesh guard is the model-axis rejection
    from tpu_sgd import make_mesh

    with pytest.raises(ValueError, match="data-only mesh"):
        LBFGS().set_mesh(make_mesh(n_data=4, n_model=2))


def test_choose_streamed_build_budgets_chunk():
    """The streamed build's device footprint is stack + TWO in-flight
    chunks — the double-buffered ingest pipeline stages chunk k+1 while
    chunk k's kernel consumes its buffer (review r4 established the
    single-chunk accounting; the io-layer prefetcher doubles it)."""
    from tpu_sgd.plan import _stack_bytes, choose_streamed_build

    B, batch = choose_streamed_build(100_000_000, 1000, 2, 12 * GB)
    assert B is not None and batch is not None
    stack = _stack_bytes(100_000_000, B, 1000)
    chunk = batch * (1000 * 2 + 4)
    assert stack + 2 * chunk <= 12 * GB  # double-buffer staging
    assert batch >= B  # at least one whole block per transfer
    # impossible O(d^2) stack: nothing fits
    assert choose_streamed_build(1_000_000, 100_000, 2,
                                 12 * GB) == (None, None)


def test_forced_gram_infeasible_budget_warns():
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        p = plan(1_000_000, 100_000, itemsize=2, gram_able=True,
                 sampling="sliced", mini_batch_fraction=0.1,
                 num_iterations=1000, free_hbm=12 * GB,
                 force="streamed_virtual_gram")
    assert p.schedule == "streamed_virtual_gram"
    assert p.block_rows is None
    assert any("NO feasible block size" in str(r.message) for r in rec)


def test_plan_batch_rows_plumbs_to_optimizer():
    from tpu_sgd import GradientDescent

    p = plan(10_000_000, 1000, itemsize=2, gram_able=True,
             sampling="sliced", mini_batch_fraction=0.1,
             num_iterations=1000, free_hbm=12 * GB)
    assert p.schedule == "streamed_virtual_gram"
    assert p.batch_rows is not None and p.batch_rows >= p.block_rows
    opt = p.apply(GradientDescent())
    assert opt.gram_batch_rows == p.batch_rows
    assert opt.gram_block_rows == p.block_rows


def test_manual_setter_clears_planned_sibling_flags(rng):
    """A manual schedule setter after an auto-planned run must clear the
    PLAN's sibling flags — the mutual-exclusion guards must never blame
    the user for a flag the planner set (code-review r5)."""
    from tpu_sgd import GradientDescent, LBFGS
    from tpu_sgd.ops.gradients import LeastSquaresGradient

    opt = GradientDescent()
    Plan("host_streamed", "auto plan").apply(opt)
    assert opt.host_streaming
    opt.set_streamed_stats(True)
    assert not opt.host_streaming  # plan-set sibling cleared
    assert opt.streamed_stats

    lb = LBFGS(LeastSquaresGradient(), max_num_iterations=3)
    lb.host_streaming = True  # as the QN planner leaves it...
    lb.last_plan = Plan("host_streamed", "auto plan")  # ...with last_plan
    lb.set_streamed_stats(True, block_rows=32)
    assert not lb.host_streaming
    # and the run proceeds without the exclusion guard firing
    X = rng.normal(size=(256, 6)).astype(np.float32)
    y = rng.normal(size=(256,)).astype(np.float32)
    w, h = lb.optimize_with_history((X, y), np.zeros(6, np.float32))
    assert np.all(np.isfinite(np.asarray(w)))
    # USER-set flags (last_plan is None) are never cleared by a sibling
    lb2 = LBFGS().set_host_streaming(True)
    with pytest.raises(ValueError, match="alternative"):
        lb2.set_streamed_stats(True).optimize_with_history(
            (X, y), np.zeros(6, np.float32))


def test_meshed_resident_gram_skips_stack_feasibility():
    """Meshed quasi-Newton resident gram carries O(d²) totals, not a
    prefix stack: slim headroom that forbids a stack must not push the
    planner back to stock (code-review r5)."""
    from tpu_sgd import LBFGS, data_mesh, plan_quasi_newton

    # per-device slab ~11.9 GB of 12 GB: no prefix stack fits, but the
    # 3*d² totals carry (12 MB) does
    tight = _ShapeOnly((47_500_000, 1000), np.float16)
    p = plan_quasi_newton(LBFGS().set_mesh(data_mesh()), tight, None,
                          free_hbm=12 * GB)
    assert p.schedule == "resident_gram"


# ---- self-calibration (round 5: VERDICT r4 #6) -----------------------------

def test_cost_model_calibrate_probe():
    """The ~2 s probe returns measured positive rates and keeps the
    other constants (plus explicit overrides)."""
    cm = CostModel.calibrate(copy_mb=4, feed_mb=4)
    assert cm.hbm_gb_s > 0 and cm.host_feed_gb_s > 0
    # A collapsed/elided measurement reads ~700,000 GB/s (the axon
    # constant-trip-count failure, CALIBRATION_TPU_CHECK round 5); no
    # real memory system exceeds ~20 TB/s, so a sane probe stays under.
    assert cm.hbm_gb_s < 20_000
    assert cm.hbm_bytes == CostModel().hbm_bytes  # defaults untouched
    cm2 = CostModel.calibrate(copy_mb=4, feed_mb=4, hbm_safety=0.5)
    assert cm2.hbm_safety == 0.5
    # overrides win over the measured fields too (probe one, pin one)
    cm3 = CostModel.calibrate(copy_mb=4, feed_mb=4, host_feed_gb_s=50.0)
    assert cm3.host_feed_gb_s == 50.0 and cm3.hbm_gb_s > 0
    # feed_mb so small both probe buffers clamp to the same 1024-element
    # minimum: zero byte delta must fall back to the default rate, never
    # 0.0 (plan() divides by host_feed_gb_s)
    cm4 = CostModel.calibrate(copy_mb=4, feed_mb=0.003)
    assert cm4.host_feed_gb_s == CostModel().host_feed_gb_s
    # the report says WHICH probes fell back (hardware checks gate on it)
    assert cm4.calibration_report["feed_fell_back"] is True
    assert cm.calibration_report["hbm_fell_back"] is False
    # report is advisory: excluded from model equality
    assert CostModel(calibration_report={"x": 1}) == CostModel()


def test_fed_cost_model_flips_streaming_boundary():
    """Decision boundaries must MOVE with the cost model: on the slow
    calibrated tunnel feed (0.15 GB/s) a 20-iteration beyond-HBM run
    amortizes the one-time virtual-gram build in ~10 iterations; on a
    pod-local 50 GB/s feed the same build needs ~40 — the planner must
    flip away from the build (VERDICT r4 #6: the persisted constants are
    single-environment calibrations)."""
    kw = dict(itemsize=2, gram_able=True, sampling="sliced",
              mini_batch_fraction=0.1, num_iterations=20,
              free_hbm=12 * GB)
    slow = plan(10_000_000, 1000, **kw)
    assert slow.schedule == "streamed_virtual_gram"
    fast = plan(10_000_000, 1000,
                cost_model=CostModel(host_feed_gb_s=50.0), **kw)
    assert fast.schedule == "partial_residency"
    assert fast.estimates["streamed_iter_s"] < \
        slow.estimates["streamed_iter_s"] / 100


def test_host_streamed_plan_does_not_leak_stream_chunk_into_gram_knob():
    """A host_streamed quasi-Newton plan sizes batch_rows as the STREAM
    chunk (a global, mesh-scaled row count owned by stream_batch_rows);
    applying it must leave the gram build's chunk cap alone — a later
    manual streamed-gram build on the same optimizer would otherwise
    inherit an absurd host->device chunk (VERDICT r4's knob-ownership
    class)."""
    from tpu_sgd import LBFGS, LeastSquaresGradient, SquaredL2Updater
    from tpu_sgd.plan import Plan

    opt = LBFGS(LeastSquaresGradient(), SquaredL2Updater())
    p = Plan("host_streamed", "test", batch_rows=6_400_000)
    p.apply_quasi_newton(opt)
    assert opt.host_streaming
    assert opt.stream_batch_rows == 6_400_000   # the stream chunk knob
    assert opt.gram_batch_rows is None          # the gram knob untouched
    # ...and a gram-building plan still owns the gram knob as before
    p2 = Plan("streamed_virtual_gram", "test", block_rows=256,
              batch_rows=4096, aligned=True)
    p2.apply_quasi_newton(opt)
    assert opt.streamed_stats and not opt.host_streaming
    assert opt.stream_batch_rows is None
    assert opt.gram_batch_rows == 4096


def test_manual_schedule_after_plan_resets_plan_owned_knobs():
    """A manual schedule setter taking the wheel after an auto-planned
    run must reset the plan's SIZING knobs too: a block size / chunk cap
    sized for the planned dataset leaking into a manual build on a
    different dataset is the same class as the host_streamed batch_rows
    leak (round-5 fix), via the manual-after-plan path."""
    from tpu_sgd import GradientDescent
    from tpu_sgd.ops.gram import DEFAULT_BLOCK_ROWS

    opt = GradientDescent()
    p = Plan("streamed_virtual_gram", "test", block_rows=512,
             batch_rows=4096, aligned=True)
    p.apply(opt)
    assert opt.gram_block_rows == 512 and opt.gram_batch_rows == 4096
    opt.set_streamed_stats(True)  # user takes the wheel, new dataset
    assert opt.gram_block_rows == DEFAULT_BLOCK_ROWS
    assert opt.gram_batch_rows is None
    assert opt.gram_aligned is False and opt.gram_chunk_iters is None
    # ...but a USER-set knob survives the reset
    opt2 = GradientDescent().set_gram_options(block_rows=128)
    Plan("streamed_virtual_gram", "t", block_rows=512,
         batch_rows=4096).apply(opt2)
    assert opt2.gram_block_rows == 128  # user knob held through the plan
    opt2.set_sufficient_stats(True)
    assert opt2.gram_block_rows == 128  # and through the manual reset
    assert opt2.gram_batch_rows is None


def test_set_gram_options_validates_before_applying():
    """A bad LATER knob must not leave earlier knobs half-applied (and
    unrecorded in _user_gram_opts)."""
    from tpu_sgd import GradientDescent, LBFGS
    from tpu_sgd.ops.gram import DEFAULT_BLOCK_ROWS

    for opt in (GradientDescent(), LBFGS()):
        with pytest.raises(ValueError, match="batch_rows must be positive"):
            opt.set_gram_options(block_rows=4096, batch_rows=0)
        assert opt.gram_block_rows == DEFAULT_BLOCK_ROWS
        assert "block_rows" not in opt._user_gram_opts
