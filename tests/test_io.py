"""Ingestion-layer tests (``tpu_sgd/io``): chunk planner math, prefetcher
ordering/exception semantics, wire format round-trips, pipelined-vs-sync
build equality (f32 bitwise), and the one-compiled-program contract."""

import threading
import time

import numpy as np
import pytest

from tpu_sgd.io import (DEFAULT_PREFETCH_DEPTH, Prefetcher, pad_rows,
                        plan_chunks, resolve_wire_dtype, wire_cast)


# ---- chunk planner ---------------------------------------------------------

def test_plan_chunks_fixed_shapes_cover_rows():
    plan = plan_chunks(1000, 256, round_to=32)
    chunks = list(plan)
    assert [c.rows for c in chunks] == [256] * 4
    assert chunks[0].start == 0 and chunks[-1].stop == 1000
    # contiguous cover, no overlap
    for a, b in zip(chunks, chunks[1:]):
        assert b.start == a.start + a.rows
    # only the tail pads, and it pads to the fixed shape
    assert [c.pad for c in chunks] == [0, 0, 0, 24]
    assert plan.pad_rows == 24


def test_plan_chunks_clamps_to_span():
    # one small dataset: chunk shrinks to the (block-rounded) span
    # instead of emitting a mostly-pad transfer
    plan = plan_chunks(64, 1024, round_to=16)
    (c,) = list(plan)
    assert c.rows == 64 and c.pad == 0
    # ragged span rounds up to whole blocks only
    plan = plan_chunks(70, 1024, round_to=16)
    (c,) = list(plan)
    assert c.rows == 80 and c.valid == 70 and c.pad == 10


def test_plan_chunks_offset_resume_alignment():
    full = [c.start for c in plan_chunks(1000, 256, round_to=32)]
    resumed = plan_chunks(1000, 256, offset=512, round_to=32)
    assert [c.start for c in resumed] == [s for s in full if s >= 512]
    with pytest.raises(ValueError, match="multiple of round_to"):
        plan_chunks(1000, 256, offset=100, round_to=32)
    with pytest.raises(ValueError, match="outside"):
        plan_chunks(100, 32, offset=101)


def test_plan_chunks_honors_streamed_totals_caps():
    """The ``batch_rows`` caps from ``streamed_totals_chunking`` flow
    into the planner unchanged: the capped chunk is the fixed shape."""
    from tpu_sgd.ops.gram import streamed_totals_chunking

    B, chunk = streamed_totals_chunking(100_000, 8192, 500)
    assert B <= 500 and chunk <= 500  # the cap is exact
    plan = plan_chunks(100_000, chunk, round_to=B)
    chunks = list(plan)
    assert all(c.rows == plan.chunk_rows <= 500 for c in chunks)
    assert chunks[-1].stop == 100_000
    assert plan.chunk_rows % B == 0


def test_pad_rows_zero_copy_and_cast():
    a = np.ones((8, 3), np.float32)
    assert pad_rows(a, 8) is a  # right shape + dtype: zero-copy
    p = pad_rows(a, 10)
    assert p.shape == (10, 3) and np.all(p[8:] == 0) and p.dtype == a.dtype
    import ml_dtypes

    q = pad_rows(a, 10, dtype=ml_dtypes.bfloat16)  # pad + wire cast, one pass
    assert q.dtype == ml_dtypes.bfloat16 and np.all(
        np.asarray(q[:8], np.float32) == 1.0)
    with pytest.raises(ValueError, match="do not fit"):
        pad_rows(a, 4)


# ---- prefetcher ------------------------------------------------------------

def test_prefetcher_preserves_order():
    def produce(i):
        time.sleep(0.002 * (5 - i % 5))  # jittered production times
        return i * i

    assert list(Prefetcher(produce, range(12), depth=3)) == [
        i * i for i in range(12)]


def test_prefetcher_runs_producer_off_thread():
    main = threading.get_ident()
    seen = []

    def produce(i):
        seen.append(threading.get_ident())
        return i

    list(Prefetcher(produce, range(4), depth=2))
    assert all(t != main for t in seen)
    # depth=0 is the synchronous passthrough: producer on the caller
    seen.clear()
    list(Prefetcher(produce, range(4), depth=0))
    assert all(t == main for t in seen)


def test_prefetcher_exception_propagates_in_order():
    def produce(i):
        if i == 3:
            raise RuntimeError("wedged at 3")
        return i

    pf = Prefetcher(produce, range(6), depth=2)
    got = []
    with pytest.raises(RuntimeError, match="wedged at 3"):
        for v in pf:
            got.append(v)
    assert got == [0, 1, 2]  # items before the failure arrived intact
    # the prefetcher closed itself: iteration is over, not wedged
    with pytest.raises(StopIteration):
        next(pf)


def test_prefetcher_close_cancels_lookahead():
    produced = []

    def produce(i):
        produced.append(i)
        time.sleep(0.01)
        return i

    pf = Prefetcher(produce, range(100), depth=2)
    assert next(pf) == 0
    pf.close()  # early exit (convergence): queued work must not run on
    time.sleep(0.05)  # let any stray producer call finish
    assert len(produced) <= 4  # 0 consumed + bounded lookahead, no more
    with pytest.raises(StopIteration):
        next(pf)


def test_prefetcher_bounded_lookahead():
    """depth bounds TOTAL materialized chunks (held + staged): depth=2
    must never have more than ONE result staged ahead of the consumer —
    the staging budget ``choose_streamed_build`` sizes is depth chunks,
    not depth+1 (code-review finding)."""
    in_flight = []

    def produce(i):
        in_flight.append(i)
        return i

    pf = Prefetcher(produce, range(50), depth=2)
    time.sleep(0.05)
    assert len(in_flight) <= 1  # only the lookahead window, pre-consume
    assert next(pf) == 0
    time.sleep(0.05)
    # consumer holds 0; at most ONE more may be staged/in production
    assert len(in_flight) <= 2
    pf.close()

    with pytest.raises(ValueError, match="depth"):
        Prefetcher(produce, range(3), depth=-1)


# ---- wire format -----------------------------------------------------------

def test_resolve_wire_dtype():
    import ml_dtypes

    assert resolve_wire_dtype(None, np.float32) is None
    # wire == data dtype: nothing to cast
    assert resolve_wire_dtype("bfloat16", ml_dtypes.bfloat16) is None
    wd = resolve_wire_dtype("bfloat16", np.float32)
    assert wd == np.dtype(ml_dtypes.bfloat16)
    with pytest.raises(ValueError, match="floating"):
        resolve_wire_dtype("int32", np.float32)


def test_wire_cast_round_trip_tolerance():
    rng = np.random.default_rng(0)
    a = rng.normal(size=(256, 8)).astype(np.float32)
    assert wire_cast(a, None) is a  # f32 wire: zero-copy identity
    wd = resolve_wire_dtype("bfloat16", a.dtype)
    back = np.asarray(wire_cast(a, wd), np.float32)
    # bf16 keeps 8 mantissa bits: ~0.4% relative
    np.testing.assert_allclose(back, a, rtol=8e-3, atol=1e-6)


# ---- pipelined vs legacy sync builds ---------------------------------------

def _build_data(rng, n=1000, d=12):
    X = rng.normal(size=(n, d)).astype(np.float32)
    y = (X @ rng.uniform(-1, 1, d).astype(np.float32)).astype(np.float32)
    return X, y


def test_pipelined_prefix_build_bitwise_equals_sync(rng):
    """f32 wire, padded tail chunk (960 rows into 256-row chunks): the
    pipelined build must be BIT-identical to the legacy sync loop —
    zero blocks contribute exact zeros and valid blocks run the same
    (B, d) matmuls."""
    from tpu_sgd.ops.gram import GramLeastSquaresGradient

    X, y = _build_data(rng)
    ref = GramLeastSquaresGradient.build_streamed(
        X, y, block_rows=64, batch_rows=256, pipeline=False)
    pip = GramLeastSquaresGradient.build_streamed(
        X, y, block_rows=64, batch_rows=256, pipeline=True)
    assert list(pip.data.PG.shape) == list(ref.data.PG.shape)
    for leaf in ("PG", "Pb", "Pyy", "G_tot", "b_tot", "yy_tot"):
        np.testing.assert_array_equal(
            np.asarray(getattr(pip.data, leaf)),
            np.asarray(getattr(ref.data, leaf)), err_msg=leaf)


def test_pipelined_bf16_wire_build_within_tolerance(rng):
    """bf16 wire rounds the INPUTS (~0.4% relative); accumulation stays
    f32, so the statistics track the f32-wire build at input-rounding
    tolerance."""
    from tpu_sgd.ops.gram import GramLeastSquaresGradient

    X, y = _build_data(rng)
    ref = GramLeastSquaresGradient.build_streamed(
        X, y, block_rows=64, batch_rows=256)
    bw = GramLeastSquaresGradient.build_streamed(
        X, y, block_rows=64, batch_rows=256, wire_dtype="bfloat16")
    G0 = np.asarray(ref.data.G_tot)
    np.testing.assert_allclose(np.asarray(bw.data.G_tot), G0,
                               rtol=2e-2, atol=2e-2 * np.abs(G0).max())


def test_pipelined_totals_exact(rng):
    """Whole-block row counts: bitwise.  Ragged counts: the final
    partial block's matmul runs at the padded shape — same values at
    reassociation tolerance (documented in ``_streamed_totals``)."""
    from tpu_sgd.ops.gram import GramLeastSquaresGradient

    X, y = _build_data(rng, n=1024)
    sd = np.dtype("float32")
    for n in (1024, 1000):
        ref = GramLeastSquaresGradient._streamed_totals(
            X[:n], y[:n], 64, sd, 256, pipeline=False)
        pip = GramLeastSquaresGradient._streamed_totals(
            X[:n], y[:n], 64, sd, 256, pipeline=True)
        for a, b in zip(ref, pip):
            if n % 64 == 0:
                np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
            else:
                np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                           rtol=1e-6, atol=1e-3)


def test_pipelined_build_compiles_one_body_program(rng):
    """THE shape-discipline assertion: a pipelined build with a padded
    tail runs exactly ONE compiled per-chunk stats program (fixed-shape
    chunks; the tail padded in host numpy) — the legacy loop compiled a
    second program for every distinct tail shape.  Asserted through the
    shared ``assert_compile_count`` (tpu_sgd.analysis), the runtime twin
    of graftlint's shape-trap rule."""
    from tpu_sgd.analysis import assert_compile_count
    from tpu_sgd.ops import gram as gram_mod
    from tpu_sgd.ops.gram import GramLeastSquaresGradient

    X, y = _build_data(rng, n=990)
    # unique (B, dtype, donate) key so other tests' compiles don't count
    B = 33
    gram_mod._streamed_stats_fn.cache_clear()
    with assert_compile_count(
            1, of=gram_mod._streamed_stats_fn(B, "float32", False)):
        GramLeastSquaresGradient.build_streamed(
            X, y, block_rows=B, batch_rows=4 * B, pipeline=True)

    gram_mod._streamed_totals_fn.cache_clear()
    with assert_compile_count(
            1, of=gram_mod._streamed_totals_fn(33, "float32", False)):
        GramLeastSquaresGradient._streamed_totals(
            X, y, 33, np.dtype("float32"), 4 * 33, pipeline=True)


def test_pipelined_sharded_totals_match_sync(rng):
    """Meshed streamed totals: pipelined feed + the jitted donated
    per-shard accumulate must reproduce the legacy sync build."""
    from tpu_sgd import data_mesh
    from tpu_sgd.parallel.gram_parallel import build_streamed_total_stats

    mesh = data_mesh()
    k = mesh.shape["data"]
    n, d = k * 130 + 7, 6  # ragged: remainder rides the last shard
    X = rng.normal(size=(n, d)).astype(np.float32)
    y = rng.normal(size=(n,)).astype(np.float32)
    ref = build_streamed_total_stats(mesh, X, y, block_rows=32,
                                     batch_rows=64, pipeline=False)
    pip = build_streamed_total_stats(mesh, X, y, block_rows=32,
                                     batch_rows=64, pipeline=True)
    for leaf in ("G_tot", "b_tot", "yy_tot"):
        np.testing.assert_allclose(
            np.asarray(getattr(pip, leaf)),
            np.asarray(getattr(ref, leaf)), rtol=1e-6, atol=1e-4,
            err_msg=leaf)


def test_pipelined_sharded_prefix_matches_sync(rng):
    from tpu_sgd import data_mesh
    from tpu_sgd.parallel.gram_parallel import (
        build_streamed_sharded_gram_stats,
    )

    mesh = data_mesh()
    k = mesh.shape["data"]
    X = rng.normal(size=(k * 160, 5)).astype(np.float32)
    y = rng.normal(size=(k * 160,)).astype(np.float32)
    ref, _, _ = build_streamed_sharded_gram_stats(
        mesh, X, y, block_rows=32, batch_rows=64, pipeline=False)
    pip, _, _ = build_streamed_sharded_gram_stats(
        mesh, X, y, block_rows=32, batch_rows=64, pipeline=True)
    for a, b in zip(ref, pip):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# ---- host-streamed SGD lookahead ------------------------------------------

@pytest.mark.parametrize("mode", ["sliced", "indexed", "bernoulli"])
def test_host_streamed_prefetch_trajectory_bitwise(rng, mode):
    """The lookahead worker must not change WHAT is sampled — only
    where the assembly runs: depth=2 and the synchronous depth=0 feed
    produce bit-identical weights and loss history in every sampling
    mode (the indexed mode's gather is the satellite fix: it now rides
    the worker)."""
    from tpu_sgd.config import SGDConfig
    from tpu_sgd.ops.gradients import LeastSquaresGradient
    from tpu_sgd.ops.updaters import SimpleUpdater
    from tpu_sgd.optimize.streamed import optimize_host_streamed

    X, y = _build_data(rng, n=2000, d=16)
    cfg = SGDConfig(step_size=0.2, num_iterations=8,
                    mini_batch_fraction=0.25, convergence_tol=0.0,
                    sampling=mode)

    def run(depth):
        return optimize_host_streamed(
            LeastSquaresGradient(), SimpleUpdater(), cfg, X, y,
            np.zeros(16, np.float32), prefetch_depth=depth)

    w2, h2 = run(2)
    w0, h0 = run(0)
    np.testing.assert_array_equal(np.asarray(w2), np.asarray(w0))
    np.testing.assert_array_equal(h2, h0)


def test_host_streamed_bf16_wire_converges(rng):
    from tpu_sgd.config import SGDConfig
    from tpu_sgd.ops.gradients import LeastSquaresGradient
    from tpu_sgd.ops.updaters import SimpleUpdater
    from tpu_sgd.optimize.streamed import optimize_host_streamed

    X, y = _build_data(rng, n=2000, d=16)
    cfg = SGDConfig(step_size=0.2, num_iterations=12,
                    mini_batch_fraction=0.25, convergence_tol=0.0,
                    sampling="sliced")
    w, hist = optimize_host_streamed(
        LeastSquaresGradient(), SimpleUpdater(), cfg, X, y,
        np.zeros(16, np.float32), wire_dtype="bfloat16")
    assert hist[-1] < hist[0] * 0.5  # halves the bytes, still trains


def test_host_streamed_early_convergence_closes_prefetcher(rng):
    """A convergence early-exit must not leave worker lookahead running
    (the prefetcher is closed in the driver's finally)."""
    from tpu_sgd.config import SGDConfig
    from tpu_sgd.ops.gradients import LeastSquaresGradient
    from tpu_sgd.ops.updaters import SimpleUpdater
    from tpu_sgd.optimize.streamed import optimize_host_streamed

    X, y = _build_data(rng, n=512, d=8)
    cfg = SGDConfig(step_size=1e-6, num_iterations=500,
                    mini_batch_fraction=0.5, convergence_tol=0.5,
                    sampling="sliced")
    before = threading.active_count()
    _, hist = optimize_host_streamed(
        LeastSquaresGradient(), SimpleUpdater(), cfg, X, y,
        np.zeros(8, np.float32), prefetch_depth=2)
    assert len(hist) < 500  # converged early
    time.sleep(0.05)
    assert threading.active_count() <= before + 1  # no leaked workers


def test_host_streamed_resume_completed_checkpoint_returns(rng, tmp_path):
    """Re-running with a checkpoint saved at the FINAL iteration must
    return the restored weights, not raise StopIteration from an empty
    prefetch range (code-review finding)."""
    from tpu_sgd.config import SGDConfig
    from tpu_sgd.ops.gradients import LeastSquaresGradient
    from tpu_sgd.ops.updaters import SimpleUpdater
    from tpu_sgd.optimize.streamed import optimize_host_streamed
    from tpu_sgd.utils.checkpoint import CheckpointManager

    X, y = _build_data(rng, n=256, d=6)
    cfg = SGDConfig(step_size=0.2, num_iterations=4,
                    mini_batch_fraction=0.5, convergence_tol=0.0,
                    sampling="sliced")
    cm = CheckpointManager(str(tmp_path))
    w1, h1 = optimize_host_streamed(
        LeastSquaresGradient(), SimpleUpdater(), cfg, X, y,
        np.zeros(6, np.float32), checkpoint_manager=cm,
        checkpoint_every=1)
    # the run completed and checkpointed at i == num_iterations; a rerun
    # restores start_iter = 5 > 4 and must just hand the weights back
    w2, h2 = optimize_host_streamed(
        LeastSquaresGradient(), SimpleUpdater(), cfg, X, y,
        np.zeros(6, np.float32), checkpoint_manager=cm,
        checkpoint_every=1)
    np.testing.assert_array_equal(np.asarray(w2), np.asarray(w1))
    np.testing.assert_array_equal(h2, h1)


def test_streamed_build_resume_rejects_wire_change(rng, tmp_path):
    """A build killed mid-pass must refuse to resume under a DIFFERENT
    wire dtype — the halves would silently mix f32-wire and bf16-wire
    statistics (code-review finding)."""
    from tpu_sgd.ops import gram as gram_mod
    from tpu_sgd.ops.gram import GramLeastSquaresGradient

    X, y = _build_data(rng, n=512, d=5)
    resume_dir = str(tmp_path / "ckpt")
    calls = {"n": 0}
    real = gram_mod._chunk_prefix

    def dying(*args):
        calls["n"] += 1
        if calls["n"] == 2:
            raise RuntimeError("simulated wedge")
        return real(*args)

    gram_mod._chunk_prefix = dying
    try:
        with pytest.raises(RuntimeError, match="wedge"):
            GramLeastSquaresGradient.build_streamed(
                X, y, block_rows=32, batch_rows=64,
                resume_dir=resume_dir)
    finally:
        gram_mod._chunk_prefix = real
    with pytest.raises(ValueError, match="different build"):
        GramLeastSquaresGradient.build_streamed(
            X, y, block_rows=32, batch_rows=64, resume_dir=resume_dir,
            wire_dtype="bfloat16")


# ---- optimizer knob plumbing ----------------------------------------------

def test_set_ingest_options_validates_and_invalidates_cache(rng):
    from tpu_sgd import GradientDescent

    opt = GradientDescent()
    assert opt.ingest_prefetch_depth == DEFAULT_PREFETCH_DEPTH
    opt.set_ingest_options(wire_dtype="bfloat16", prefetch_depth=3,
                           pipeline=True)
    assert opt.ingest_wire_dtype == "bfloat16"
    assert opt.ingest_prefetch_depth == 3
    assert {"wire_dtype", "prefetch_depth",
            "pipeline"} <= opt._user_gram_opts
    with pytest.raises(ValueError, match="floating"):
        opt.set_ingest_options(wire_dtype="int8")
    with pytest.raises(ValueError, match="prefetch_depth"):
        opt.set_ingest_options(prefetch_depth=-1)

    # a wire change must invalidate the identity-cached streamed build:
    # the statistics DEPEND on the wire dtype
    X, y = _build_data(rng, n=512, d=8)
    opt = (GradientDescent().set_num_iterations(2)
           .set_streamed_stats(True, block_rows=64))
    opt.optimize((X, y), np.zeros(8, np.float32))
    entry1 = opt._streamed_gram_entry
    opt.optimize((X, y), np.zeros(8, np.float32))
    assert opt._streamed_gram_entry is entry1  # same config: cached
    opt.set_ingest_options(wire_dtype="bfloat16")
    opt.optimize((X, y), np.zeros(8, np.float32))
    assert opt._streamed_gram_entry is not entry1  # wire change: rebuilt


def test_streamed_stats_pipeline_off_matches_on(rng):
    """set_streamed_stats trains identically through the pipelined and
    legacy feeds (f32 wire is bitwise at the build, so the trajectories
    are bitwise too)."""
    from tpu_sgd import GradientDescent

    X, y = _build_data(rng, n=1024, d=8)

    def run(pipeline):
        opt = (GradientDescent().set_num_iterations(10)
               .set_step_size(0.2).set_streamed_stats(True, block_rows=64))
        opt.set_ingest_options(pipeline=pipeline)
        return opt.optimize_with_history((X, y), np.zeros(8, np.float32))

    w1, h1 = run(True)
    w0, h0 = run(False)
    np.testing.assert_array_equal(np.asarray(w1), np.asarray(w0))
    np.testing.assert_array_equal(np.asarray(h1), np.asarray(h0))


def test_host_streamed_pipeline_off_disables_wire(rng):
    """pipeline=False is the bitwise legacy A/B feed: it must null the
    wire cast too, not just the lookahead (code-review finding) —
    matching the gram builders' effective-wire reduction."""
    from tpu_sgd import GradientDescent

    X, y = _build_data(rng, n=1024, d=8)

    def run(**ingest):
        opt = (GradientDescent().set_num_iterations(6).set_step_size(0.2)
               .set_mini_batch_fraction(0.25).set_sampling("sliced")
               .set_host_streaming(True))
        if ingest:
            opt.set_ingest_options(**ingest)
        return opt.optimize_with_history((X, y), np.zeros(8, np.float32))

    w_legacy, h_legacy = run()  # default pipelined f32 == legacy values
    w_off, h_off = run(wire_dtype="bfloat16", pipeline=False)
    np.testing.assert_array_equal(np.asarray(w_off), np.asarray(w_legacy))
    np.testing.assert_array_equal(h_off, h_legacy)


def test_plan_apply_respects_user_ingest_knobs():
    from tpu_sgd import GradientDescent
    from tpu_sgd.plan import Plan

    opt = GradientDescent().set_ingest_options(wire_dtype="bfloat16",
                                               prefetch_depth=4)
    Plan("host_streamed", "test").apply(opt)
    # user knobs survive the plan (the planner never silently rounds)
    assert opt.ingest_wire_dtype == "bfloat16"
    assert opt.ingest_prefetch_depth == 4

    opt2 = GradientDescent()
    Plan("host_streamed", "test", prefetch_depth=3).apply(opt2)
    assert opt2.ingest_wire_dtype is None  # plan default: wire OFF
    assert opt2.ingest_prefetch_depth == 3
