"""StandardScaler + GLM feature-scaling tests.

Mirrors the reference's StandardScalerSuite shape ([U]
mllib/feature/StandardScaler.scala; SURVEY.md §4 unit-tests-vs-closed-forms)
plus the harness-level ``useFeatureScaling`` contract from [U]
GeneralizedLinearAlgorithm.run: scaled training must return weights in
ORIGINAL feature space.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from tpu_sgd.feature import Normalizer, StandardScaler
from tpu_sgd.models.classification import LogisticRegressionWithLBFGS
from tpu_sgd.models.regression import LinearRegressionWithSGD
from tpu_sgd.ops.sparse import sparse_data


def _skewed(rng, n=500, d=6):
    X = rng.normal(size=(n, d)).astype(np.float32)
    scales = np.array([1e-2, 1.0, 30.0, 400.0, 5.0, 0.5], np.float32)[:d]
    return X * scales


class TestStandardScaler:
    def test_unit_std_no_centering(self, rng):
        X = _skewed(rng)
        model = StandardScaler().fit(X)
        Xs = np.asarray(model.transform(X))
        np.testing.assert_allclose(Xs.std(axis=0, ddof=1), 1.0, rtol=1e-4)
        # with_mean=False: means move by the scale factor, not to zero
        np.testing.assert_allclose(
            Xs.mean(axis=0),
            X.mean(axis=0) / X.std(axis=0, ddof=1),
            rtol=1e-3,
        )

    def test_with_mean_centers(self, rng):
        X = _skewed(rng)
        model = StandardScaler(with_mean=True, with_std=True).fit(X)
        Xs = np.asarray(model.transform(X))
        np.testing.assert_allclose(Xs.mean(axis=0), 0.0, atol=1e-4)
        np.testing.assert_allclose(Xs.std(axis=0, ddof=1), 1.0, rtol=1e-4)

    def test_constant_column_zeroed(self, rng):
        X = _skewed(rng)
        X[:, 2] = 7.0
        model = StandardScaler().fit(X)
        Xs = np.asarray(model.transform(X))
        # factor=0 for zero-variance columns (reference convention)
        np.testing.assert_allclose(Xs[:, 2], 0.0)
        assert float(model.factor[2]) == 0.0

    def test_neither_flag_rejected(self):
        with pytest.raises(ValueError):
            StandardScaler(with_mean=False, with_std=False)

    def test_sparse_matches_dense(self):
        X, _, _ = sparse_data(200, 40, nnz_per_row=8, seed=3)
        model_sp = StandardScaler().fit(X)
        Xd = np.asarray(X.todense())
        model_d = StandardScaler().fit(Xd)
        np.testing.assert_allclose(
            np.asarray(model_sp.variance),
            np.asarray(model_d.variance),
            rtol=2e-4, atol=1e-6,
        )
        Xs_sp = np.asarray(model_sp.transform(X).todense())
        Xs_d = np.asarray(model_d.transform(Xd))
        np.testing.assert_allclose(Xs_sp, Xs_d, rtol=2e-4, atol=1e-5)

    def test_sparse_with_mean_rejected(self):
        X, _, _ = sparse_data(50, 10, nnz_per_row=3, seed=1)
        model = StandardScaler(with_mean=True).fit(np.asarray(X.todense()))
        with pytest.raises(ValueError, match="with_mean"):
            model.transform(X)

    def test_vector_roundtrip(self, rng):
        """transform() on a weight vector is the inverse of w * std — the
        scale->train->rescale identity the harness relies on."""
        X = _skewed(rng)
        model = StandardScaler().fit(X)
        w = rng.normal(size=(X.shape[1],)).astype(np.float32)
        back = np.asarray(model.transform(jnp.asarray(w) * model.std))
        np.testing.assert_allclose(back, w, rtol=1e-4)


class TestNormalizer:
    def test_l2_rows(self, rng):
        X = rng.normal(size=(50, 8)).astype(np.float32)
        Xn = np.asarray(Normalizer().transform(X))
        np.testing.assert_allclose(
            np.linalg.norm(Xn, axis=1), 1.0, rtol=1e-5
        )
        # direction preserved
        i = 7
        np.testing.assert_allclose(
            Xn[i] * np.linalg.norm(X[i]), X[i], rtol=1e-4
        )

    def test_l1_and_inf(self, rng):
        X = rng.normal(size=(30, 5)).astype(np.float32)
        X1 = np.asarray(Normalizer(p=1.0).transform(X))
        np.testing.assert_allclose(np.abs(X1).sum(axis=1), 1.0, rtol=1e-5)
        Xi = np.asarray(Normalizer(p=float("inf")).transform(X))
        np.testing.assert_allclose(np.abs(Xi).max(axis=1), 1.0, rtol=1e-5)

    def test_zero_row_passthrough(self):
        X = np.zeros((3, 4), np.float32)
        X[1] = [1.0, 0.0, 0.0, 0.0]
        Xn = np.asarray(Normalizer().transform(X))
        np.testing.assert_allclose(Xn[0], 0.0)
        np.testing.assert_allclose(Xn[1], X[1])

    def test_single_vector(self):
        v = np.array([3.0, 4.0], np.float32)
        out = np.asarray(Normalizer().transform(v))
        np.testing.assert_allclose(out, [0.6, 0.8], rtol=1e-6)

    def test_sparse_matches_dense(self):
        X, _, _ = sparse_data(100, 30, nnz_per_row=5, seed=8)
        Xn_sp = np.asarray(Normalizer().transform(X).todense())
        Xn_d = np.asarray(Normalizer().transform(np.asarray(X.todense())))
        np.testing.assert_allclose(Xn_sp, Xn_d, rtol=1e-4, atol=1e-6)

    def test_sparse_single_vector_matches_dense(self):
        """1-D BCOO is ONE row — must match the dense single-vector path,
        not treat each stored entry as its own row."""
        import jax.numpy as jnp
        from jax.experimental.sparse import BCOO

        v = jnp.array([3.0, 0.0, 4.0], jnp.float32)
        out = Normalizer().transform(BCOO.fromdense(v))
        np.testing.assert_allclose(
            np.asarray(out.todense()), [0.6, 0.0, 0.8], rtol=1e-6
        )
        out_inf = Normalizer(p=float("inf")).transform(BCOO.fromdense(v))
        np.testing.assert_allclose(
            np.asarray(out_inf.todense()), [0.75, 0.0, 1.0], rtol=1e-6
        )

    def test_bad_p_rejected(self):
        with pytest.raises(ValueError):
            Normalizer(p=0.0)


class TestGLMFeatureScaling:
    def test_scaled_training_returns_original_space(self, rng):
        """With reg=0 the optimum is scale-invariant, so the scaled run must
        land on the same ORIGINAL-space weights the problem was built from —
        proof the rescale-back happened."""
        from tpu_sgd.models.regression import LinearRegressionWithLBFGS

        w_true = np.array([2.0, -0.5, 0.03, 1e-3], np.float32)
        X = (rng.normal(size=(800, 4)) * np.array([1.0, 3.0, 40.0, 900.0])) \
            .astype(np.float32)
        y = (X @ w_true + 0.01 * rng.normal(size=(800,))).astype(np.float32)

        scaled = (
            LinearRegressionWithLBFGS()
            .set_feature_scaling(True)
            .run((X, y))
        )
        np.testing.assert_allclose(
            np.asarray(scaled.weights), w_true, rtol=0.05, atol=1e-3
        )
        pred = np.asarray(scaled.predict(X[:50]))
        np.testing.assert_allclose(pred, y[:50], atol=0.2)

    def test_scaling_improves_conditioning_for_sgd(self, rng):
        """On badly scaled features plain SGD stalls; the scaled run must
        reach a much lower objective in the same iteration budget."""
        w_true = np.array([1.0, -2.0, 0.5], np.float32)
        X = (rng.normal(size=(1000, 3)) * np.array([1.0, 50.0, 2000.0])) \
            .astype(np.float32)
        y = (X @ w_true).astype(np.float32)

        def mse(model):
            return float(np.mean((np.asarray(model.predict(X)) - y) ** 2))

        plain = LinearRegressionWithSGD.train(
            (X, y), num_iterations=50, step_size=1e-7
        )
        # After scaling, the reference default step (1.0) is the right one:
        # unit-variance uncorrelated columns make the full-batch step land
        # near the optimum immediately.
        scaled_alg = LinearRegressionWithSGD(
            step_size=1.0, num_iterations=50
        ).set_feature_scaling(True)
        scaled = scaled_alg.run((X, y))
        assert mse(scaled) < mse(plain) * 1e-2

    def test_multinomial_scaled_predicts(self, rng):
        K, d, n = 3, 4, 600
        W = rng.normal(size=(K, d)).astype(np.float32)
        X = (rng.normal(size=(n, d)) * np.array([1.0, 10.0, 100.0, 0.1])) \
            .astype(np.float32)
        y = np.argmax(X @ W.T, axis=1).astype(np.float32)
        alg = (
            LogisticRegressionWithLBFGS(max_num_iterations=60)
            .set_num_classes(K)
            .set_intercept(True)
            .set_feature_scaling(True)
        )
        model = alg.run((X, y))
        acc = float(np.mean(np.asarray(model.predict(X)) == y))
        assert acc > 0.9

    def test_multinomial_scaled_no_intercept(self, rng):
        """The flat (K-1)*d weight layout must rescale per d-block through
        the generic harness path (no intercept -> no override)."""
        K, d, n = 3, 4, 600
        W = rng.normal(size=(K, d)).astype(np.float32)
        X = (rng.normal(size=(n, d)) * np.array([1.0, 10.0, 100.0, 0.1])) \
            .astype(np.float32)
        y = np.argmax(X @ W.T, axis=1).astype(np.float32)
        alg = (
            LogisticRegressionWithLBFGS(max_num_iterations=60)
            .set_num_classes(K)
            .set_feature_scaling(True)
        )
        model = alg.run((X, y))
        acc = float(np.mean(np.asarray(model.predict(X)) == y))
        assert acc > 0.85

    def test_high_mean_low_variance_column_survives(self, rng):
        """A ~N(1e6, 1) column (CV 1e-6) is informative and must NOT be
        zeroed by the constant-column noise floor."""
        X = rng.normal(size=(500, 2)).astype(np.float32)
        X[:, 1] = 1e6 + rng.normal(size=500).astype(np.float32)
        model = StandardScaler().fit(X)
        assert float(model.factor[1]) > 0.0
        Xs = np.asarray(model.transform(X))
        assert Xs[:, 1].std() > 0.5

    def test_warm_start_original_space(self, rng):
        """Initial weights are given in original space; a scaled run warmed
        with the true weights must start (and stay) essentially converged."""
        from tpu_sgd.models.regression import LinearRegressionWithLBFGS

        w_true = np.array([3.0, -1.0], np.float32)
        X = (rng.normal(size=(400, 2)) * np.array([1.0, 100.0])) \
            .astype(np.float32)
        y = (X @ w_true).astype(np.float32)
        alg = LinearRegressionWithLBFGS().set_feature_scaling(True)
        model = alg.run((X, y), initial_weights=w_true)
        np.testing.assert_allclose(
            np.asarray(model.weights), w_true, rtol=1e-3, atol=1e-4
        )
