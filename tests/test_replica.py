"""Async elastic multi-replica training: the bounded-staleness
contracts.

The load-bearing pins:

* τ=0 (bulk-synchronous rounds) is BITWISE the synchronous
  data-parallel trajectory — weights AND loss history — against the
  meshed observed stepwise driver over the same shard count, because
  the workers run the shared ``_make_local_sums`` recipe with the
  shard index folded exactly where ``axis_index`` folds, and the store
  combines contributions in shard order with ``make_step``'s exact
  post-psum math.
* τ>0: no ACCEPTED push ever exceeds the bound — asserted from the
  obs trace (every ``replica.push`` event carries its staleness), not
  from the store's own counters alone.
* Elasticity: a worker killed mid-run deregisters (a τ=0 round in
  flight completes with the survivors — no fleet stall), rejoins with
  backoff, and the run converges to the synchronous final loss.
* The store checkpoint round-trips version + per-worker error-feedback
  state, and a supervised preempt-resume at τ=0 is bitwise.
"""

import os
import threading

import jax
import numpy as np
import pytest

from tpu_sgd.config import SGDConfig
from tpu_sgd.ops.gradients import LeastSquaresGradient, LogisticGradient
from tpu_sgd.ops.updaters import SimpleUpdater, SquaredL2Updater
from tpu_sgd.optimize.gradient_descent import GradientDescent
from tpu_sgd.parallel.mesh import DATA_AXIS
from tpu_sgd.replica import (ParameterStore, ReplicaDriver,
                             ReplicaMembership, StalenessContract,
                             shard_rows)
from tpu_sgd.reliability import failpoints as fp
from tpu_sgd.reliability.retry import RetryPolicy
from tpu_sgd.utils.checkpoint import CheckpointManager
from tpu_sgd.utils.events import CollectingListener


def _data(n=256, d=12, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, d)).astype(np.float32)
    w_true = rng.normal(size=d).astype(np.float32)
    y = (X @ w_true + 0.01 * rng.normal(size=n)).astype(np.float32)
    return X, y, np.zeros(d, np.float32)


def _mesh(n_shards):
    from jax.sharding import Mesh

    return Mesh(np.asarray(jax.devices()[:n_shards]), (DATA_AXIS,))


def _driver(gradient, updater, *, iters=24, frac=0.5, step=0.3,
            reg=0.1, workers=4, tau=0, tol=0.0):
    return (ReplicaDriver(gradient, updater)
            .set_step_size(step).set_num_iterations(iters)
            .set_mini_batch_fraction(frac).set_convergence_tol(tol)
            .set_reg_param(reg).set_workers(workers).set_staleness(tau))


def _sync_reference(gradient, updater, X, y, w0, *, iters=24, frac=0.5,
                    step=0.3, reg=0.1, workers=4, tol=0.0):
    """The synchronous data-parallel trajectory: the meshed OBSERVED
    stepwise driver (per-iteration ``dp_step_fn`` under shard_map with
    the psum all-reduce) over the same shard count."""
    opt = (GradientDescent(gradient, updater)
           .set_step_size(step).set_num_iterations(iters)
           .set_mini_batch_fraction(frac).set_convergence_tol(tol)
           .set_reg_param(reg).set_mesh(_mesh(workers))
           .set_listener(CollectingListener()))
    w, h = opt.optimize_with_history((X, y), w0)
    return np.asarray(w), np.asarray(h)


class _ListSink:
    """Minimal obs sink: collects (kind, payload) records."""

    def __init__(self):
        self.records = []

    def emit(self, kind, payload):
        self.records.append((kind, dict(payload)))


# -- staleness contract -------------------------------------------------------


def test_staleness_contract_semantics():
    c0 = StalenessContract(0)
    assert c0.synchronous and c0.bounded
    assert c0.check(5, 5).admissible
    assert not c0.check(5, 4).admissible
    assert c0.check(5, 3).staleness == 2

    c2 = StalenessContract(2)
    assert not c2.synchronous and c2.bounded
    assert c2.check(7, 5).admissible
    assert not c2.check(8, 5).admissible

    import math

    for unbounded in (None, math.inf):
        cu = StalenessContract(unbounded)
        assert not cu.bounded and not cu.synchronous
        assert cu.check(1000, 0).admissible

    with pytest.raises(ValueError):
        StalenessContract(-1)
    with pytest.raises(ValueError):
        StalenessContract(1.5)
    with pytest.raises(ValueError):
        StalenessContract(2).check(3, 5)  # basis ahead of head


def test_shard_rows_matches_mesh_layout():
    """Replica shard ``i`` must hold bit-identical rows to mesh shard
    ``i`` (the τ=0 comparison's precondition)."""
    from tpu_sgd.parallel.data_parallel import pad_to_multiple

    X, y, _ = _data(n=203, d=5)
    shards = shard_rows(X, y, 4)
    Xp, yp, valid = pad_to_multiple(X, y, 4)
    n_local = Xp.shape[0] // 4
    for s, (Xs, ys, vs) in enumerate(shards):
        sl = slice(s * n_local, (s + 1) * n_local)
        np.testing.assert_array_equal(Xs, Xp[sl])
        np.testing.assert_array_equal(ys, yp[sl])
        np.testing.assert_array_equal(vs, valid[sl])
    # divisible row count: no mask at all, like shard_dataset's None
    shards = shard_rows(X[:200], y[:200], 4)
    assert all(v is None for _, _, v in shards)


# -- τ=0: bitwise vs the synchronous data-parallel path ----------------------


@pytest.mark.parametrize("workers", [2, 4])
def test_tau0_bitwise_vs_sync_data_parallel(workers):
    X, y, w0 = _data()
    w_ref, h_ref = _sync_reference(
        LeastSquaresGradient(), SquaredL2Updater(), X, y, w0,
        workers=workers)
    drv = _driver(LeastSquaresGradient(), SquaredL2Updater(),
                  workers=workers, tau=0)
    w_rep, h_rep = drv.optimize_with_history((X, y), w0)
    np.testing.assert_array_equal(np.asarray(w_rep), w_ref)
    np.testing.assert_array_equal(h_rep, h_ref)
    snap = drv.last_store_snapshot
    assert snap["version"] == 24
    assert snap["max_accepted_staleness"] == 0
    assert snap["pushes_accepted"] == 24 * workers


def test_tau0_bitwise_uneven_shards_and_simple_updater():
    """n not divisible by the worker count: the padding valid-mask path
    must stay bitwise too (mask & bernoulli, like the meshed step)."""
    X, y, w0 = _data(n=203, d=7, seed=3)
    w_ref, h_ref = _sync_reference(
        LeastSquaresGradient(), SimpleUpdater(), X, y, w0, workers=4,
        reg=0.0)
    drv = _driver(LeastSquaresGradient(), SimpleUpdater(), workers=4,
                  tau=0, reg=0.0)
    w_rep, h_rep = drv.optimize_with_history((X, y), w0)
    np.testing.assert_array_equal(np.asarray(w_rep), w_ref)
    np.testing.assert_array_equal(h_rep, h_ref)


def test_tau0_bitwise_logistic_full_batch():
    X, y, w0 = _data(n=192, d=6, seed=5)
    y = (y > 0).astype(np.float32)
    w_ref, h_ref = _sync_reference(
        LogisticGradient(), SquaredL2Updater(), X, y, w0, workers=2,
        frac=1.0, iters=15)
    drv = _driver(LogisticGradient(), SquaredL2Updater(), workers=2,
                  tau=0, frac=1.0, iters=15)
    w_rep, h_rep = drv.optimize_with_history((X, y), w0)
    np.testing.assert_array_equal(np.asarray(w_rep), w_ref)
    np.testing.assert_array_equal(h_rep, h_ref)


def test_tau0_convergence_tol_early_exit():
    """The store's observe_step convergence matches the sync driver's
    detected iteration (same norms rule, same tolerance math)."""
    X, y, w0 = _data(n=128, d=6, seed=7)
    kwargs = dict(iters=60, frac=1.0, step=0.5, reg=0.0, workers=2,
                  tol=1e-3)
    w_ref, h_ref = _sync_reference(LeastSquaresGradient(),
                                   SimpleUpdater(), X, y, w0, **kwargs)
    drv = _driver(LeastSquaresGradient(), SimpleUpdater(), **kwargs,
                  tau=0)
    w_rep, h_rep = drv.optimize_with_history((X, y), w0)
    assert len(h_rep) < 60, "tolerance never fired; test is vacuous"
    np.testing.assert_array_equal(h_rep, h_ref)
    np.testing.assert_array_equal(np.asarray(w_rep), np.asarray(w_ref))
    assert drv.last_store_snapshot["converged"]


# -- τ>0: the bound holds, asserted from the trace ---------------------------


@pytest.mark.parametrize("tau", [1, 4])
def test_staleness_bound_never_violated_in_trace(tau):
    from tpu_sgd.obs import spans

    X, y, w0 = _data()
    sink = _ListSink()
    spans.enable_tracing(sink)
    try:
        drv = _driver(LeastSquaresGradient(), SquaredL2Updater(),
                      workers=4, tau=tau, iters=48, step=0.1)
        drv.optimize_with_history((X, y), w0)
    finally:
        spans.disable_tracing()
    pushes = [p for k, p in sink.records
              if k == "trace_event" and p["name"] == "replica.push"]
    accepted = [p for p in pushes if p["accepted"]]
    assert len(accepted) == 48, "every applied version leaves one record"
    assert max(p["staleness"] for p in accepted) <= tau
    # rejected pushes (if any) were all OVER the bound — rejection is
    # never spurious
    for p in pushes:
        if not p["accepted"]:
            assert p["staleness"] > tau
    snap = drv.last_store_snapshot
    assert snap["max_accepted_staleness"] <= tau
    assert snap["pushes_rejected"] == len(pushes) - len(accepted)


def test_unbounded_staleness_accepts_everything():
    X, y, w0 = _data()
    drv = _driver(LeastSquaresGradient(), SquaredL2Updater(),
                  workers=4, tau=None, iters=40, step=0.1)
    drv.optimize_with_history((X, y), w0)
    assert drv.last_store_snapshot["pushes_rejected"] == 0
    assert drv.last_store_snapshot["version"] == 40


# -- reliability: failpoint heal, kill/rejoin --------------------------------


def test_push_pull_failpoints_heal_bitwise():
    """Transient replica.pull/replica.push faults healed by the worker
    RetryPolicy leave the τ=0 trajectory bitwise (the protocol mutates
    nothing before the failpoint)."""
    X, y, w0 = _data()
    w_ref, h_ref = _sync_reference(
        LeastSquaresGradient(), SquaredL2Updater(), X, y, w0, workers=2)
    drv = (_driver(LeastSquaresGradient(), SquaredL2Updater(),
                   workers=2, tau=0)
           .set_retry(RetryPolicy(max_attempts=4, base_backoff_s=0.001,
                                  seed=5)))
    with fp.inject_faults({
            "replica.pull": fp.fail_prob(0.05, seed=1),
            "replica.push": fp.fail_prob(0.05, seed=2)}):
        w_rep, h_rep = drv.optimize_with_history((X, y), w0)
        assert fp.hits("replica.pull") > 0
        assert fp.hits("replica.push") > 0
    np.testing.assert_array_equal(np.asarray(w_rep), w_ref)
    np.testing.assert_array_equal(h_rep, h_ref)


def _full_objective(X, y, w, reg):
    """Exact full-batch objective (mean squared residual / 2 + L2 reg)
    — the matched-loss metric, immune to minibatch sampling noise."""
    r = X @ np.asarray(w) - y
    return float(0.5 * np.mean(r * r) + 0.5 * reg * np.sum(
        np.asarray(w) ** 2))


@pytest.mark.parametrize("tau", [0, 2])
def test_worker_kill_and_rejoin_converges(tau):
    """A worker killed mid-run (one-shot failpoint, no worker retry)
    deregisters — a τ=0 round in flight completes with the survivors,
    the fleet never stalls — rejoins with backoff, and the run still
    converges to the synchronous final loss (matched objective, not
    bitwise: the fleet composition changed mid-run)."""
    X, y, w0 = _data(n=512, d=10, seed=11)
    iters = 160
    w_ref, _ = _sync_reference(
        LeastSquaresGradient(), SquaredL2Updater(), X, y, w0,
        workers=4, iters=iters, frac=1.0, step=0.2, reg=0.01)
    ref_obj = _full_objective(X, y, w_ref, 0.01)
    drv = (_driver(LeastSquaresGradient(), SquaredL2Updater(),
                   workers=4, tau=tau, iters=iters, frac=1.0, step=0.2,
                   reg=0.01)
           .set_rejoin(RetryPolicy(max_attempts=5,
                                   base_backoff_s=0.005, seed=7)))
    with fp.inject_faults({"replica.push": fp.fail_nth(30)}):
        w_k, h_k = drv.optimize_with_history((X, y), w0)
    assert len(h_k) == iters
    membership = drv.last_membership_snapshot
    assert any(rec["joins"] > 1 for rec in membership.values()), (
        f"no worker ever rejoined: {membership}")
    assert any(rec["failures"] > 0 for rec in membership.values())
    obj = _full_objective(X, y, w_k, 0.01)
    assert obj <= ref_obj * 1.01, (
        f"kill/rejoin run objective {obj} vs sync {ref_obj}")


def test_fatal_worker_error_propagates():
    """An unretryable worker death (rejoin budget cannot absorb it)
    aborts the run with the real error — never a hang."""
    X, y, w0 = _data()
    drv = (_driver(LeastSquaresGradient(), SquaredL2Updater(),
                   workers=2, tau=0, iters=40)
           .set_rejoin(RetryPolicy(max_attempts=2,
                                   base_backoff_s=0.001, seed=1)))
    with fp.inject_faults(
            {"replica.pull": fp.fail_nth(10, exc=ValueError)}):
        with pytest.raises(ValueError):
            drv.optimize_with_history((X, y), w0)


# -- async convergence: matched final loss -----------------------------------


@pytest.mark.parametrize("tau", [1, 4, None])
def test_async_converges_to_matched_loss(tau):
    X, y, w0 = _data(n=512, d=10, seed=11)
    iters = 160
    w_ref, _ = _sync_reference(
        LeastSquaresGradient(), SquaredL2Updater(), X, y, w0,
        workers=4, iters=iters, frac=1.0, step=0.2, reg=0.01)
    ref_obj = _full_objective(X, y, w_ref, 0.01)
    drv = _driver(LeastSquaresGradient(), SquaredL2Updater(),
                  workers=4, tau=tau, iters=iters, frac=1.0, step=0.2,
                  reg=0.01)
    w_a, h_a = drv.optimize_with_history((X, y), w0)
    assert len(h_a) == iters
    obj = _full_objective(X, y, w_a, 0.01)
    assert obj <= ref_obj * 1.01, (
        f"tau={tau} objective {obj} vs sync {ref_obj}")


# -- compressed wire ----------------------------------------------------------


def test_compressed_wire_matched_loss_and_wire_bytes():
    from tpu_sgd.obs import counters as obs_counters
    from tpu_sgd.obs import spans

    X, y, w0 = _data(n=512, d=64, seed=13)
    iters = 200
    w_ref, _ = _sync_reference(
        LeastSquaresGradient(), SquaredL2Updater(), X, y, w0,
        workers=2, iters=iters, frac=1.0, step=0.2, reg=0.01)
    ref_obj = _full_objective(X, y, w_ref, 0.01)
    drv = (_driver(LeastSquaresGradient(), SquaredL2Updater(),
                   workers=2, tau=1, iters=iters, frac=1.0, step=0.2,
                   reg=0.01)
           .set_wire_compress("topk:0.125"))
    # tracing must be on for the counters' subsystem attribution (the
    # replica.step span tags the worker thread)
    spans.enable_tracing(_ListSink())
    obs_counters.enable()
    obs_counters.reset()  # the registry is process-wide
    try:
        w_c, h_c = drv.optimize_with_history((X, y), w0)
        snap = obs_counters.snapshot()
    finally:
        obs_counters.disable()
        spans.disable_tracing()
    obj = _full_objective(X, y, w_c, 0.01)
    assert obj <= ref_obj * 1.01, (
        f"compressed objective {obj} vs sync {ref_obj}")
    # the push wire shipped topk segments, and their physical bytes are
    # a real compression of the logical update bytes (counter name is
    # <subsystem>.wire.topk — the replica.step span tags the worker)
    from tpu_sgd.obs.counters import wire_ratios

    ratios = wire_ratios(snap)
    topk = ratios.get("replica.wire.topk")
    assert topk is not None, f"no topk wire counted: {sorted(ratios)}"
    assert topk["physical_bytes"] > 0
    assert topk["physical_bytes"] < 0.5 * topk["logical_bytes"]


def test_rejected_compressed_push_conserves_ef_mass():
    from tpu_sgd.io.sparse_wire import ErrorFeedback

    ef = ErrorFeedback(16, 0.25)
    update = np.arange(16, dtype=np.float32) - 8.0
    idx, vals = ef.compress(update.copy())
    # delivered: acc + extracted == update
    np.testing.assert_allclose(
        ef.acc.sum() + vals.sum(), update.sum(), rtol=1e-6)
    # rejection path: restore the segment — the accumulator holds the
    # WHOLE update again, nothing leaked
    ef.restore_segment(idx, vals)
    np.testing.assert_allclose(ef.acc, update, rtol=1e-6)


# -- checkpoint / resume ------------------------------------------------------


def test_store_checkpoint_roundtrips_version_and_ef_state(tmp_path):
    import jax.numpy as jnp

    cfg = SGDConfig(step_size=0.1, num_iterations=50,
                    convergence_tol=0.0, reg_param=0.01)
    mgr = CheckpointManager(os.fspath(tmp_path))
    store = ParameterStore(
        SquaredL2Updater(), cfg, np.zeros(8, np.float32), staleness=2,
        checkpoint_manager=mgr, checkpoint_every=100, config_key="ck")
    store.register_worker("w0", 0)
    store.register_worker("w1", 1)
    ef0 = store.error_feedback("w0", 0.25)
    ef1 = store.error_feedback("w1", 0.25)
    rng = np.random.default_rng(0)
    # alternate pushers: the SSP progress bound (staleness.py) blocks
    # a worker running more than τ accepted pushes ahead of the
    # slowest active one, so a single-threaded driver must interleave
    for wid in ("w0", "w1", "w0"):
        pulled = store.pull(wid)
        g = jnp.asarray(rng.normal(size=8).astype(np.float32))
        res = store.push(wid, pulled.version, g,
                         jnp.asarray(4.0), jnp.asarray(8.0))
        assert res.accepted
    gn = rng.normal(size=8).astype(np.float32)
    idx, vals = ef1.compress(gn)
    assert store.push_compressed("w1", store.version, idx, vals, 4.0,
                                 8.0).accepted
    store.save_now()

    state = mgr.restore()
    assert state["iteration"] == 4 == store.version
    restored = ParameterStore(
        SquaredL2Updater(), cfg, state["weights"], staleness=2,
        config_key="ck", resume_state=state)
    assert restored.version == 4
    np.testing.assert_array_equal(np.asarray(restored.weights),
                                  np.asarray(store.weights))
    np.testing.assert_array_equal(restored.loss_history(),
                                  store.loss_history())
    # per-worker EF accumulators round-trip bitwise
    np.testing.assert_array_equal(
        restored.error_feedback("w0", 0.25).acc, ef0.acc)
    np.testing.assert_array_equal(
        restored.error_feedback("w1", 0.25).acc, ef1.acc)


def test_supervised_preempt_resume_bitwise(tmp_path):
    from tpu_sgd.reliability.supervisor import TrainingSupervisor

    X, y, w0 = _data()
    w_ref, h_ref = _sync_reference(
        LeastSquaresGradient(), SquaredL2Updater(), X, y, w0, workers=2,
        iters=40)
    mgr = CheckpointManager(os.fspath(tmp_path))
    drv = _driver(LeastSquaresGradient(), SquaredL2Updater(),
                  workers=2, tau=0, iters=40)
    sup = TrainingSupervisor(drv, checkpoint_manager=mgr,
                             checkpoint_every=10,
                             install_signal_handlers=False)

    class _PreemptAt(CollectingListener):
        def on_iteration(self, ev):
            super().on_iteration(ev)
            if ev.iteration == 12:
                sup.request_preempt()

    drv.set_listener(_PreemptAt())
    res = sup.run((X, y), w0)
    assert res.status == "preempted"
    assert 0 < res.preempted_at < 40
    drv.set_listener(None)
    res2 = sup.run((X, y), w0)
    assert res2.completed
    np.testing.assert_array_equal(np.asarray(res2.weights), w_ref)
    np.testing.assert_array_equal(res2.loss_history, h_ref)


# -- membership / health ------------------------------------------------------


def test_membership_records_and_stragglers():
    m = ReplicaMembership()
    rec = m.join("w0", 0)
    m.join("w1", 1)
    assert set(m.active_ids()) == {"w0", "w1"}
    rec.heartbeat.beat()
    assert m.stragglers(stall_after_s=1e-9) == ["w0"]  # w1 never beat
    m.leave("w1", error=RuntimeError("boom"))
    assert m.active_ids() == ["w0"]
    snap = m.snapshot()
    assert snap["w1"]["failures"] == 1
    assert "RuntimeError" in snap["w1"]["last_error"]
    rec2 = m.join("w1", 1)  # rejoin keeps the record identity
    assert rec2.joins == 2
    assert len(m.heartbeats()) == 2


def test_store_lock_discipline_validated_at_runtime():
    """The GRAFTLINT_LOCKS declaration for ParameterStore, validated
    dynamically on a live multi-worker run (the runtime twin of the
    lexical rule)."""
    from tpu_sgd.analysis.runtime import instrument_object
    from tpu_sgd.replica import store as store_mod

    X, y, w0 = _data(n=64, d=6)
    cfg = SGDConfig(step_size=0.2, num_iterations=10,
                    mini_batch_fraction=0.5, convergence_tol=0.0,
                    reg_param=0.01)
    store = ParameterStore(SquaredL2Updater(), cfg, w0, staleness=1)
    recorder = instrument_object(
        store, store_mod.GRAFTLINT_LOCKS["ParameterStore"])
    from tpu_sgd.replica import ReplicaWorker

    shards = shard_rows(X, y, 2)
    workers = [
        ReplicaWorker(f"w{s}", s, store, LeastSquaresGradient(), cfg,
                      *shards[s])
        for s in range(2)
    ]
    for s in range(2):
        store.register_worker(f"w{s}", s)
    threads = [threading.Thread(target=w.run) for w in workers]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert store.version == 10
    assert recorder.checked_accesses > 0
    assert recorder.violations == []


# -- planner ------------------------------------------------------------------


def test_choose_replicas_scaling():
    from tpu_sgd.plan import Plan, choose_replicas, plan

    # tiny workload: the store would serialize the fleet — stay sync
    assert choose_replicas(1000, 16, n_devices=8) == 0
    # a single device can never place a fleet, whatever the cost model
    assert choose_replicas(10_000_000, 1000, n_devices=1) == 0
    # north-star shape: a real fleet pays, bounded by devices and cap
    w_big = choose_replicas(10_000_000, 1000, n_devices=8)
    assert 2 <= w_big <= 8
    # more devices never shrink the choice; caps bind
    assert choose_replicas(10_000_000, 1000, n_devices=2) <= 2
    assert choose_replicas(10_000_000, 1000, n_devices=8, cap=3) <= 3
    # monotone in workload size
    assert (choose_replicas(10_000_000, 1000, n_devices=8)
            >= choose_replicas(100_000, 1000, n_devices=8))
    # the Plan carries the advice; default stays synchronous
    assert Plan(schedule="resident", reason="r").replicas == 0
    # ...and plan() stamps it on every returned plan
    p = plan(10_000_000, 1000, n_devices=8)
    assert p.replicas == w_big
    assert p.estimates["replicas"] == w_big
    assert plan(4096, 16, n_devices=8).replicas == 0
