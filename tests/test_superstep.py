"""Superstep executor tests (ISSUE 5): K fused SGD iterations per
compiled program on the host-dispatched paths.

Trajectory contract pinned here (and documented in ``make_superstep``):

* SAME-PROGRAM comparisons are BITWISE — a fused run replayed, resumed
  from a mid-run checkpoint, fault-healed, or prefetch-A/B'd reproduces
  its weights and loss history exactly, in all three sampling modes.
* Fused-vs-legacy comparisons share the per-step math and the
  deterministic ``(seed, i)`` sample sequence, so the loss-history
  LENGTH, the detected convergence iteration, and the checkpoint
  cadence are exactly equal; the weights agree to reassociation noise
  (~1 ulp/step: XLA lowers the batch dot through a different emitter
  inside a scanned program than as a standalone dispatch — measured in
  this repo, same caveat as partial residency's ``resident_step``).
"""

import threading

import numpy as np
import pytest

from tpu_sgd.config import SGDConfig
from tpu_sgd.ops.gradients import LeastSquaresGradient
from tpu_sgd.ops.updaters import SimpleUpdater
from tpu_sgd.optimize.gradient_descent import GradientDescent
from tpu_sgd.optimize.streamed import optimize_host_streamed

MODES = ("sliced", "indexed", "bernoulli")
TOL = dict(rtol=5e-5, atol=1e-6)


def _data(rng, n=1000, d=12):
    X = rng.normal(size=(n, d)).astype(np.float32)
    w = rng.uniform(-1, 1, d).astype(np.float32)
    y = (X @ w + 0.01 * rng.normal(size=n)).astype(np.float32)
    return X, y


def _cfg(mode="sliced", iters=10, frac=0.25, tol=0.0, seed=7):
    return SGDConfig(step_size=0.1, num_iterations=iters,
                     mini_batch_fraction=frac, convergence_tol=tol,
                     sampling=mode, seed=seed)


def _stream(cfg, X, y, **kw):
    d = X.shape[1]
    return optimize_host_streamed(
        LeastSquaresGradient(), SimpleUpdater(), cfg, X, y,
        np.zeros(d, np.float32), **kw)


def _opt(mode="sliced", iters=12, k=1, seed=7):
    o = (GradientDescent()
         .set_num_iterations(iters).set_step_size(0.1)
         .set_mini_batch_fraction(0.5).set_sampling(mode)
         .set_convergence_tol(0.0).set_seed(seed)
         .set_host_streaming(True))
    if k > 1:
        o.set_superstep(k)
    return o


# ---- superchunk assembly ---------------------------------------------------

def test_stack_superchunk_shapes_and_tail_padding():
    from tpu_sgd.io import stack_superchunk

    xs = [np.full((5, 3), t, np.float32) for t in range(2)]
    ys = [np.full((5,), t, np.float32) for t in range(2)]
    vs = [np.ones((5,), bool) for _ in range(2)]
    Xs, Ys, Vs = stack_superchunk(xs, ys, vs, k=4)
    assert Xs.shape == (4, 5, 3) and Ys.shape == (4, 5)
    assert Vs.shape == (4, 5) and Vs.dtype == bool
    np.testing.assert_array_equal(Xs[1], xs[1])
    # padded trailing steps: zero rows, all-False valid (no-op updates)
    assert not Xs[2:].any() and not Vs[2:].any()
    # k defaults to len(xs); undersized k raises
    Xs2, _, _ = stack_superchunk(xs, ys, vs)
    assert Xs2.shape == (2, 5, 3)
    with pytest.raises(ValueError, match="do not fit"):
        stack_superchunk(xs, ys, vs, k=1)
    with pytest.raises(ValueError, match="matching"):
        stack_superchunk(xs, ys[:1], vs)


# ---- fused vs legacy: streamed path ----------------------------------------

@pytest.mark.parametrize("mode", MODES)
def test_streamed_fused_matches_legacy_all_modes(rng, mode):
    """K=4 over 10 iterations (K does not divide: padded tail
    superstep): same sample sequence, same history length, weights and
    losses at reassociation tolerance."""
    X, y = _data(rng, n=2000, d=16)
    cfg = _cfg(mode)
    w1, h1 = _stream(cfg, X, y)
    w4, h4 = _stream(cfg, X, y, superstep_k=4)
    assert len(h1) == len(h4) == 10
    np.testing.assert_allclose(np.asarray(w4), np.asarray(w1), **TOL)
    np.testing.assert_allclose(h4, h1, **TOL)


def test_streamed_fused_full_batch_shared_transfer(rng):
    """frac >= 1: the fused driver transfers the batch ONCE and scans
    over it — trajectory matches the per-iteration re-transfer loop."""
    X, y = _data(rng, n=600, d=8)
    cfg = _cfg(frac=1.0, iters=9)
    w1, h1 = _stream(cfg, X, y)
    w4, h4 = _stream(cfg, X, y, superstep_k=4)
    assert len(h4) == 9
    np.testing.assert_allclose(np.asarray(w4), np.asarray(w1), **TOL)
    np.testing.assert_allclose(h4, h1, **TOL)


@pytest.mark.parametrize("mode", MODES)
def test_streamed_fused_replay_bitwise(rng, mode):
    """Same-program contract: two fused runs are bit-identical."""
    X, y = _data(rng)
    cfg = _cfg(mode)
    wa, ha = _stream(cfg, X, y, superstep_k=4)
    wb, hb = _stream(cfg, X, y, superstep_k=4)
    np.testing.assert_array_equal(np.asarray(wa), np.asarray(wb))
    np.testing.assert_array_equal(ha, hb)


def test_streamed_fused_prefetch_depth_bitwise(rng):
    """The superchunk lookahead must not change WHAT is sampled: depth=2
    and the synchronous depth=0 feed are bit-identical (the ingest
    pipeline's own invariant, preserved under fusion)."""
    X, y = _data(rng)
    cfg = _cfg("indexed")
    wa, ha = _stream(cfg, X, y, superstep_k=3, prefetch_depth=2)
    wb, hb = _stream(cfg, X, y, superstep_k=3, prefetch_depth=0)
    np.testing.assert_array_equal(np.asarray(wa), np.asarray(wb))
    np.testing.assert_array_equal(ha, hb)


def test_streamed_fused_closes_prefetcher_on_convergence(rng):
    import time

    X, y = _data(rng, n=512, d=8)
    cfg = SGDConfig(step_size=1e-6, num_iterations=500,
                    mini_batch_fraction=0.5, convergence_tol=0.5,
                    sampling="sliced")
    before = threading.active_count()
    _, hist = _stream(cfg, X, y, superstep_k=8)
    assert len(hist) < 500  # converged early
    time.sleep(0.05)
    assert threading.active_count() <= before + 1


# ---- convergence-tol semantics under fusion --------------------------------

def test_streamed_fused_convergence_reports_true_iteration(rng):
    """Convergence is detected from the scan ys at the TRUE iteration,
    not the superstep boundary: the fused history ends exactly where
    the legacy loop's does, even mid-superstep."""
    X, y = _data(rng, n=512, d=8)
    cfg = SGDConfig(step_size=0.05, num_iterations=400,
                    mini_batch_fraction=0.5, convergence_tol=0.01,
                    sampling="sliced", seed=7)
    w1, h1 = _stream(cfg, X, y)
    w8, h8 = _stream(cfg, X, y, superstep_k=8)
    assert len(h8) == len(h1)
    assert len(h8) % 8 != 0  # genuinely mid-superstep
    np.testing.assert_allclose(np.asarray(w8), np.asarray(w1), **TOL)


def test_stepwise_fused_convergence_reports_true_iteration(rng):
    X, y = _data(rng, n=512, d=8)

    def run(k):
        from tpu_sgd.utils.events import SGDListener

        o = (GradientDescent().set_num_iterations(400).set_step_size(0.05)
             .set_mini_batch_fraction(0.5).set_sampling("sliced")
             .set_convergence_tol(0.01).set_seed(7)
             .set_listener(SGDListener()))
        if k > 1:
            o.set_superstep(k)
        return o.optimize_with_history((X, y), np.zeros(8, np.float32))

    w1, h1 = run(1)
    w8, h8 = run(8)
    assert len(h8) == len(h1)
    assert len(h8) % 8 != 0
    np.testing.assert_allclose(np.asarray(w8), np.asarray(w1), **TOL)


# ---- fused vs legacy: stepwise (observed) path -----------------------------

class _Recorder:
    def __init__(self):
        self.events = []
        self.ended = None

    def on_run_start(self, cfg):
        pass

    def on_iteration(self, e):
        self.events.append(e)

    def on_run_end(self, e):
        self.ended = e


@pytest.mark.parametrize("mode", MODES)
def test_stepwise_fused_matches_legacy_with_events(rng, mode):
    """Listener path: K=4 over 10 iterations — per-iteration events
    still fire, in order, with the exact losses of the fused history."""
    X, y = _data(rng, n=800, d=10)

    def run(k):
        rec = _Recorder()
        o = (GradientDescent().set_num_iterations(10).set_step_size(0.1)
             .set_mini_batch_fraction(0.5).set_sampling(mode)
             .set_convergence_tol(0.0).set_seed(3).set_listener(rec))
        if k > 1:
            o.set_superstep(k)
        w, h = o.optimize_with_history((X, y), np.zeros(10, np.float32))
        return w, h, rec

    w1, h1, _ = run(1)
    w4, h4, rec = run(4)
    assert len(h4) == len(h1) == 10
    np.testing.assert_allclose(np.asarray(w4), np.asarray(w1), **TOL)
    assert [e.iteration for e in rec.events] == list(range(1, 11))
    np.testing.assert_array_equal(
        np.asarray([e.loss for e in rec.events], np.float32), h4)
    assert rec.ended is not None and rec.ended.num_iterations == 10


def test_stepwise_fused_checkpoint_cadence_matches_legacy(rng, tmp_path):
    """Fused checkpoints land on the SAME iterations as legacy ones
    (cadence + final), carrying the exact iteration state from the ys."""
    import glob

    from tpu_sgd.utils.checkpoint import CheckpointManager

    X, y = _data(rng, n=400, d=6)

    def run(k, sub):
        o = (GradientDescent().set_num_iterations(10).set_step_size(0.1)
             .set_mini_batch_fraction(0.5).set_sampling("sliced")
             .set_convergence_tol(0.0).set_seed(3)
             .set_checkpoint(CheckpointManager(str(tmp_path / sub),
                                               keep=100), every=3))
        if k > 1:
            o.set_superstep(k)
        o.optimize_with_history((X, y), np.zeros(6, np.float32))
        return sorted(int(f[-12:-4]) for f in
                      glob.glob(str(tmp_path / sub / "ckpt_*.npz")))

    assert run(1, "legacy") == run(4, "fused") == [3, 6, 9, 10]


def test_stepwise_fused_mesh_runs_fused(rng):
    """ISSUE 6 lift: the meshed observed path joins the fused driver
    (dp_shared_superstep_fn) — no fall-back warning, trajectory at the
    usual fused-vs-legacy tolerance, history exact length."""
    import warnings as _warnings

    from tpu_sgd import data_mesh
    from tpu_sgd.utils.events import SGDListener

    X, y = _data(rng, n=256, d=6)

    def run(k):
        o = (GradientDescent().set_num_iterations(10).set_step_size(0.1)
             .set_mini_batch_fraction(0.5).set_sampling("bernoulli")
             .set_convergence_tol(0.0).set_seed(3)
             .set_mesh(data_mesh()).set_listener(SGDListener()))
        if k > 1:
            o.set_superstep(k)
        return o.optimize_with_history((X, y), np.zeros(6, np.float32))

    with _warnings.catch_warnings():
        _warnings.simplefilter("error")
        w1, h1 = run(1)
        w4, h4 = run(4)
    assert len(h4) == len(h1) == 10
    np.testing.assert_allclose(np.asarray(w4), np.asarray(w1), **TOL)
    np.testing.assert_allclose(h4, h1, **TOL)


# ---- preemption / resume at superstep boundaries ---------------------------

@pytest.mark.parametrize("mode", MODES)
def test_fused_preempt_resumes_bitwise_all_modes(rng, mode, tmp_path):
    """Stop mid-run: the fused driver checkpoints the exact superstep
    BOUNDARY iteration and a resumed fused run finishes bit-identical
    to the uninterrupted fused run (the PR-3 guarantee under fusion)."""
    from tpu_sgd.reliability.supervisor import TrainingPreempted
    from tpu_sgd.utils.checkpoint import CheckpointManager

    X, y = _data(rng, n=512, d=8)
    w0 = np.zeros(8, np.float32)
    w_ref, h_ref = _opt(mode, iters=18, k=4).optimize_with_history(
        (X, y), w0)

    class StopSecond:
        def __init__(self):
            self.polls = 0

        def __call__(self):
            self.polls += 1
            return self.polls == 2

    opt = (_opt(mode, iters=18, k=4)
           .set_checkpoint(CheckpointManager(str(tmp_path / mode)),
                           every=100))
    opt.set_stop_signal(StopSecond())
    with pytest.raises(TrainingPreempted) as ei:
        opt.optimize_with_history((X, y), w0)
    # polled once per superstep -> preempted at the SECOND boundary
    assert ei.value.iteration == 8
    opt.set_stop_signal(None)
    w_res, h_res = opt.optimize_with_history((X, y), w0)
    np.testing.assert_array_equal(np.asarray(w_res), np.asarray(w_ref))
    np.testing.assert_array_equal(h_res, h_ref)


def test_supervisor_preempts_fused_run_at_boundary(rng, tmp_path):
    """TrainingSupervisor drives the same path end-to-end: a preempt
    requested mid-superstep lands at the NEXT superstep boundary (the
    scan cannot stop mid-program), the boundary iteration is
    checkpointed exactly, and a second supervised run resumes and
    completes bitwise."""
    from tpu_sgd.reliability.supervisor import TrainingSupervisor
    from tpu_sgd.utils.checkpoint import CheckpointManager

    X, y = _data(rng, n=512, d=8)
    w0 = np.zeros(8, np.float32)
    w_ref, h_ref = _opt("sliced", iters=16, k=4).optimize_with_history(
        (X, y), w0)

    opt = _opt("sliced", iters=16, k=4)
    sup = TrainingSupervisor(
        opt, checkpoint_manager=CheckpointManager(str(tmp_path)),
        checkpoint_every=100,  # cadence never fires: preempt must save
        install_signal_handlers=False)

    class Stopper:
        def on_run_start(self, c): ...

        def on_iteration(self, ev):
            if ev.iteration == 5:  # mid-superstep [5, 8]
                sup.request_preempt()

        def on_run_end(self, ev): ...

    opt.set_listener(Stopper())
    res = sup.run((X, y), w0)
    assert res.status == "preempted" and res.preempted_at == 8
    assert CheckpointManager(str(tmp_path)).latest_version() == 8
    opt.set_listener(None)
    res2 = sup.run((X, y), w0)  # fresh run(): preempt flag cleared
    assert res2.completed
    np.testing.assert_array_equal(np.asarray(res2.weights),
                                  np.asarray(w_ref))
    np.testing.assert_array_equal(res2.loss_history, h_ref)


def test_fused_crash_resume_unaligned_grid_bitwise(rng, tmp_path):
    """A crash-resume restart from a cadence checkpoint lands MID-GRID
    (every=3, K=4 -> resume at iteration 4, 7, ...): the superstep
    regrouping after the resume must not change the trajectory — the
    per-iteration math is grouping-independent, so the resumed run is
    still bitwise equal to the uninterrupted fused run."""
    from tpu_sgd.reliability import failpoints as fp
    from tpu_sgd.reliability.failpoints import fail_nth
    from tpu_sgd.reliability.retry import RetryPolicy
    from tpu_sgd.reliability.supervisor import TrainingSupervisor
    from tpu_sgd.utils.checkpoint import CheckpointManager

    X, y = _data(rng, n=512, d=8)
    w0 = np.zeros(8, np.float32)
    w_ref, h_ref = _opt("sliced", iters=14, k=4).optimize_with_history(
        (X, y), w0)

    sup = TrainingSupervisor(
        _opt("sliced", iters=14, k=4),
        checkpoint_manager=CheckpointManager(str(tmp_path)),
        checkpoint_every=3,
        retry=RetryPolicy(max_attempts=4, base_backoff_s=0.0),
        install_signal_handlers=False)
    # crash the SECOND superstep dispatch: the latest checkpoint is
    # iteration 3, so the resume restarts at 4 — off the original
    # [1,5,9,13] superstep grid
    with fp.inject_faults({"optimize.streamed.step": fail_nth(2)}):
        res = sup.run((X, y), w0)
    assert res.completed and res.attempts == 2
    np.testing.assert_array_equal(np.asarray(res.weights),
                                  np.asarray(w_ref))
    np.testing.assert_array_equal(res.loss_history, h_ref)


# ---- one fused-body program ------------------------------------------------

def test_superstep_builder_compiles_one_program(rng):
    """THE dispatch-count assertion: a full superstep and a padded tail
    superstep share ONE compiled fused-body program (fixed (K, cap)
    shapes — the host pads, the device never re-traces)."""
    import jax
    import jax.numpy as jnp

    from tpu_sgd.analysis import assert_compile_count
    from tpu_sgd.io import stack_superchunk
    from tpu_sgd.optimize.gradient_descent import make_superstep

    X, y = _data(rng, n=400, d=6)
    cfg = _cfg(frac=1.0)  # step consumes the whole transferred batch
    fused = jax.jit(make_superstep(
        LeastSquaresGradient(), SimpleUpdater(), cfg))
    cap = 100
    full = [(X[i * cap:(i + 1) * cap], y[i * cap:(i + 1) * cap],
             np.ones((cap,), bool)) for i in range(4)]
    w = jnp.zeros(6, jnp.float32)
    with assert_compile_count(1, of=fused):
        # full superstep
        Xs, Ys, Vs = stack_superchunk([p[0] for p in full],
                                      [p[1] for p in full],
                                      [p[2] for p in full])
        w, ys = fused(w, jnp.asarray(0.0, jnp.float32),
                      jnp.asarray(1, jnp.int32), Xs, Ys, Vs)
        # tail superstep: 2 real batches padded to K=4 — same shapes,
        # same program
        Xs, Ys, Vs = stack_superchunk([p[0] for p in full[:2]],
                                      [p[1] for p in full[:2]],
                                      [p[2] for p in full[:2]], k=4)
        w, ys = fused(w, jnp.asarray(0.0, jnp.float32),
                      jnp.asarray(5, jnp.int32), Xs, Ys, Vs)
        jax.block_until_ready(w)


def test_stepwise_fused_run_compiles_one_program(rng):
    """Integration twin: a whole fused stepwise run (incl. the K ∤ N
    tail) leaves exactly one program in the memoized superstepper."""
    from tpu_sgd.utils.events import SGDListener

    X, y = _data(rng, n=400, d=6)
    o = (GradientDescent().set_num_iterations(10).set_step_size(0.1)
         .set_mini_batch_fraction(0.5).set_sampling("sliced")
         .set_convergence_tol(0.0).set_seed(3)
         .set_listener(SGDListener()).set_superstep(4))
    o.optimize_with_history((X, y), np.zeros(6, np.float32))
    key = ("superstep", o.gradient, o.updater, o.config, 4, None, False)
    fn = o._run_cache[key]
    assert fn._cache_size() == 1


# ---- reliability: io.superstep failpoint -----------------------------------

def test_io_superstep_failpoint_heals_via_retry_policy(rng):
    """An injected fault in superchunk assembly heals through the
    feed's existing RetryPolicy (the producer re-runs; the sample is
    deterministic in (seed, i), so the healed run stays bitwise)."""
    from tpu_sgd.reliability import failpoints as fp
    from tpu_sgd.reliability.failpoints import FaultInjected, fail_nth
    from tpu_sgd.reliability.retry import RetryPolicy

    X, y = _data(rng, n=512, d=8)
    w0 = np.zeros(8, np.float32)
    w_ref, h_ref = _opt("indexed", iters=12, k=4).optimize_with_history(
        (X, y), w0)

    opt = (_opt("indexed", iters=12, k=4)
           .set_ingest_options(retry=RetryPolicy(max_attempts=3,
                                                 base_backoff_s=0.0)))
    with fp.inject_faults({"io.superstep": fail_nth(1)}):
        w, h = opt.optimize_with_history((X, y), w0)
        assert fp.triggers("io.superstep") == 1
    np.testing.assert_array_equal(np.asarray(w), np.asarray(w_ref))
    np.testing.assert_array_equal(h, h_ref)

    # without a retry policy the same fault propagates — the site is
    # really on the path
    with fp.inject_faults({"io.superstep": fail_nth(1)}):
        with pytest.raises(FaultInjected):
            _opt("indexed", iters=12, k=4).optimize_with_history(
                (X, y), w0)


def test_full_batch_fused_transfer_heals_via_retry(rng):
    """Review regression: the fused full-batch path's ONE-TIME transfer
    runs outside a prefetcher, so the ingest RetryPolicy must wrap it
    directly — a transient device_put fault heals exactly as it does on
    the per-iteration feed."""
    from tpu_sgd.reliability import failpoints as fp
    from tpu_sgd.reliability.failpoints import FaultInjected, fail_nth
    from tpu_sgd.reliability.retry import RetryPolicy

    X, y = _data(rng, n=256, d=6)
    w0 = np.zeros(6, np.float32)

    def full(k, retry=None):
        o = (GradientDescent().set_num_iterations(6).set_step_size(0.1)
             .set_mini_batch_fraction(1.0).set_convergence_tol(0.0)
             .set_seed(7).set_host_streaming(True).set_superstep(k))
        if retry is not None:
            o.set_ingest_options(retry=retry)
        return o

    w_ref, h_ref = full(4).optimize_with_history((X, y), w0)
    with fp.inject_faults({"io.device_put": fail_nth(1)}):
        w, h = full(4, RetryPolicy(max_attempts=3, base_backoff_s=0.0)
                    ).optimize_with_history((X, y), w0)
        assert fp.triggers("io.device_put") == 1
    np.testing.assert_array_equal(np.asarray(w), np.asarray(w_ref))
    np.testing.assert_array_equal(h, h_ref)
    with fp.inject_faults({"io.device_put": fail_nth(1)}):
        with pytest.raises(FaultInjected):
            full(4).optimize_with_history((X, y), w0)


# ---- knob plumbing ---------------------------------------------------------

def test_set_superstep_validates():
    with pytest.raises(ValueError, match="superstep"):
        GradientDescent().set_superstep(0)
    assert GradientDescent().set_superstep(8).superstep == 8


def test_streamed_fused_mesh_and_residency_run_fused(rng):
    """ISSUE 6 lift: a mesh and partial residency both JOIN the fused
    driver — no fall-back warning, trajectories at the usual tolerance
    vs their per-iteration drivers, same-program replays bitwise."""
    import warnings as _warnings

    from tpu_sgd import data_mesh

    X, y = _data(rng, n=512, d=8)
    cfg = _cfg("sliced")

    # partial residency: mixed resident/transferred windows, one fused
    # program (make_resident_window_superstep)
    with _warnings.catch_warnings():
        _warnings.simplefilter("error")
        wl, hl = _stream(cfg, X, y, resident_rows=300)
        wf, hf = _stream(cfg, X, y, superstep_k=4, resident_rows=300)
    assert len(hf) == len(hl) == 10
    np.testing.assert_allclose(np.asarray(wf), np.asarray(wl), **TOL)
    wf2, _ = _stream(cfg, X, y, superstep_k=4, resident_rows=300)
    np.testing.assert_array_equal(np.asarray(wf), np.asarray(wf2))

    # mesh: the sharded superchunk feed (dp_superstep_fn)
    mesh = data_mesh()
    with _warnings.catch_warnings():
        _warnings.simplefilter("error")
        wm1, hm1 = _stream(cfg, X, y, mesh=mesh)
        wm4, hm4 = _stream(cfg, X, y, mesh=mesh, superstep_k=4)
    assert len(hm4) == len(hm1) == 10
    np.testing.assert_allclose(np.asarray(wm4), np.asarray(wm1), **TOL)


def test_choose_superstep_amortizes_and_respects_budget():
    from tpu_sgd.plan import CostModel, choose_superstep

    cm = CostModel(dispatch_overhead_s=8e-4, superstep_dispatch_frac=0.05)
    # 2 ms/iter feed -> residual tax must drop below 0.1 ms -> K=8
    assert choose_superstep(5000, 16, 4, 2e-3, 1e9, cm) == 8
    # fat iterations: the tax is already below the target -> K=1
    assert choose_superstep(10**6, 1000, 4, 26.0, 1e9, cm) == 1
    # no staging room for a double-buffered 2-batch superchunk -> K=1
    assert choose_superstep(5000, 16, 4, 2e-3, 100.0, cm) == 1
    # the budget clamp binds before the amortization target
    batch = 5000 * (16 * 4 + 5.0)
    assert choose_superstep(5000, 16, 4, 2e-3, 2 * batch * 3, cm) == 3


def test_plan_applies_superstep_and_user_knob_wins():
    from tpu_sgd.plan import Plan

    opt = GradientDescent()
    Plan("host_streamed", "t", superstep=8).apply(opt)
    assert opt.superstep == 8 and opt.host_streaming
    # a non-streamed plan resets the plan-owned knob
    Plan("resident_stock", "t").apply(opt)
    assert opt.superstep == 1
    # user-set superstep survives planning
    opt2 = GradientDescent().set_superstep(16)
    Plan("host_streamed", "t", superstep=4).apply(opt2)
    assert opt2.superstep == 16


def test_planner_picks_superstep_for_small_dim_streams():
    from tpu_sgd.plan import plan

    p = plan(200_000, 16, itemsize=4, sampling="indexed",
             mini_batch_fraction=0.02, num_iterations=1000,
             free_hbm=8e6, host_resident_ok=True)
    assert p.schedule == "host_streamed"
    assert p.superstep > 1
    assert p.estimates["superstep"] == p.superstep
