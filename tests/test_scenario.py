"""Production scenario harness (tpu_sgd/scenario + scripts/scenario_live.py):
the open-loop load generator's conservation ledger, the per-lane SLO
metrics in obs.report, and ONE full smoke scenario whose gate must pass
— and must FAIL when an SLO is deliberately violated (a gate only ever
seen passing is a gate nobody has tested)."""

import json
import threading
import time
from concurrent.futures import Future

import numpy as np
import pytest

from tpu_sgd.obs import report as obs_report
from tpu_sgd.scenario import build_slos, run_scenario
from tpu_sgd.scenario.loadgen import OpenLoopLoadGen, Phase, TrafficSpec
from tpu_sgd.serve.batcher import Overloaded


# -- loadgen ledger ---------------------------------------------------------
def test_loadgen_ledger_conserves_every_outcome():
    """Answered, typed-rejected at submit, displaced (typed via the
    future), errored, and dropped (never resolved) must sum to
    submitted — the conservation the scenario SLO gate audits."""
    hang = Future()  # never resolves: the one deliberate drop

    def submit(spec, i, rng):
        if i % 7 == 3:
            raise Overloaded("shed", spec.lane)
        fut = Future()
        if i == 12:  # not on the i%7==3 reject grid: really submitted
            return hang
        if i % 11 == 5:
            fut.set_exception(ValueError("transport error"))
        elif i % 13 == 6:
            fut.set_exception(Overloaded("displaced", spec.lane))
        else:
            fut.set_result(1.0)
        return fut

    gen = OpenLoopLoadGen(
        submit,
        [TrafficSpec("a", "interactive", 0.7, deadline_s=0.1),
         TrafficSpec("b", "batch", 0.3)],
        [Phase("p", 0.25, 400)],
        seed=0, drain_timeout_s=0.5)
    rep = gen.run()
    t = rep["totals"]
    assert t["submitted"] > 20
    assert t["submitted"] == (t["answered"] + t["rejected"]
                              + t["displaced"] + t["errored"]
                              + t["dropped"])
    assert t["dropped"] == 1  # exactly the hung future
    assert t["rejected"] > 0 and t["errored"] > 0 and t["displaced"] > 0
    # per-lane rollup conserves too
    for lane in rep["lanes"].values():
        assert lane["submitted"] == sum(
            lane[k] for k in ("answered", "rejected", "displaced",
                              "errored", "dropped"))
    assert rep["phases"]["p"]["offered"] >= t["submitted"]


def test_loadgen_latency_percentiles_recorded():
    def submit(spec, i, rng):
        fut = Future()
        fut.set_result(0.0)
        return fut

    gen = OpenLoopLoadGen(
        submit, [TrafficSpec("a", "interactive", 1.0)],
        [Phase("p", 0.15, 300)], seed=1)
    rep = gen.run()
    cls = rep["classes"]["a"]
    assert cls["answered"] > 0
    assert 0.0 <= cls["p50_s"] <= cls["p99_s"]


# -- per-lane SLO metrics over a synthetic trace ----------------------------
def _lane_trace():
    records = [
        {"kind": "serve_batch", "ts": 1.0, "batch_size": 4,
         "lanes": {"interactive": {"n": 3, "max_latency_s": 0.010},
                   "batch": {"n": 1, "max_latency_s": 0.200}}},
        {"kind": "serve_batch", "ts": 2.0, "batch_size": 2,
         "lanes": {"interactive": {"n": 2, "max_latency_s": 0.030}}},
        {"kind": "metric_counters", "ts": 3.0, "counters": {
            "serve.admitted.interactive": {"n": 90, "bytes": 0},
            "serve.rejected.interactive": {"n": 6, "bytes": 0},
            "serve.shed.interactive": {"n": 4, "bytes": 0},
            "serve.shed.shadow": {"n": 40, "bytes": 0},
            "serve.admitted.batch": {"n": 20, "bytes": 0},
            "serve.displaced.batch": {"n": 5, "bytes": 0},
        }},
    ]
    return records


def test_lane_latency_and_admission_stats():
    lat = obs_report.lane_latency_stats(_lane_trace())
    assert lat["interactive"]["requests"] == 5
    assert lat["interactive"]["batches"] == 2
    assert lat["interactive"]["p99_s"] == pytest.approx(0.030)
    assert lat["batch"]["p99_s"] == pytest.approx(0.200)
    adm = obs_report.lane_admission_stats(_lane_trace())
    assert adm["interactive"]["offered"] == 100
    assert adm["interactive"]["reject_rate"] == pytest.approx(0.10)
    # a lane with only sheds still appears, fully rejected
    assert adm["shadow"]["offered"] == 40
    assert adm["shadow"]["reject_rate"] == pytest.approx(1.0)
    # displaced requests were ALSO admitted: offered counts them once
    # (in admitted), the rate counts their typed rejection
    assert adm["batch"]["offered"] == 20
    assert adm["batch"]["reject_rate"] == pytest.approx(0.25)


def test_lane_slo_metrics_evaluate_and_gate():
    verdicts = obs_report.evaluate_slos(_lane_trace(), {"slos": [
        {"name": "i-p99", "metric": "lane_p99_s",
         "lane": "interactive", "max": 0.05},
        {"name": "b-p99-too-tight", "metric": "lane_p99_s",
         "lane": "batch", "max": 0.05},
        {"name": "i-sheds", "metric": "lane_shed_fraction",
         "lane": "interactive", "max": 0.5},
    ]})
    by_name = {v["name"]: v for v in verdicts}
    assert by_name["i-p99"]["ok"]
    assert not by_name["b-p99-too-tight"]["ok"]
    assert by_name["i-sheds"]["ok"]
    assert by_name["i-sheds"]["value"] == pytest.approx(0.10)


def test_lane_slo_unevaluable_is_violation_not_free_pass():
    """A lane absent from the trace cannot pass a latency or shed
    bound silently (the unevaluable-is-violation report contract)."""
    verdicts = obs_report.evaluate_slos(_lane_trace(), {"slos": [
        {"name": "ghost-p99", "metric": "lane_p99_s",
         "lane": "ghost", "max": 1.0},
        {"name": "ghost-sheds", "metric": "lane_shed_fraction",
         "lane": "ghost", "max": 1.0},
    ]})
    assert all(v["value"] is None and not v["ok"] for v in verdicts)


def test_lane_slo_metrics_require_lane_field():
    with pytest.raises(ValueError, match="lane"):
        obs_report.evaluate_slos(
            _lane_trace(),
            {"slos": [{"name": "x", "metric": "lane_p99_s", "max": 1.0}]})


def test_build_slos_violation_spelling():
    doc = build_slos("smoke", violate="interactive-p99")
    slo = [s for s in doc["slos"] if s["name"] == "interactive-p99"][0]
    assert slo["max"] < 0  # impossible: p99 is never negative
    with pytest.raises(ValueError, match="no such SLO"):
        build_slos("smoke", violate="not-an-slo")


# -- the full smoke scenario, once per session ------------------------------
@pytest.fixture(scope="module")
def scenario_run(tmp_path_factory):
    out = tmp_path_factory.mktemp("scenario")
    rc = run_scenario(seed=0, smoke=True, out_dir=str(out), verbose=False)
    return rc, out


def test_scenario_smoke_all_slos_pass(scenario_run):
    rc, out = scenario_run
    assert rc == 0, "the smoke scenario's SLO gate must pass"
    summary = json.loads((out / "scenario_summary.json").read_text())
    # zero dropped requests across >= 2 hot reloads and a kill/rejoin —
    # the acceptance spelling, re-asserted from the summary the harness
    # wrote (the SLO gate asserted the same from the trace counters)
    assert summary["totals"]["dropped"] == 0
    assert summary["totals"]["errored"] == 0
    assert summary["hot_reloads"] >= 2
    assert summary["rejoins"] >= 1
    assert summary["totals"]["answered"] >= 50
    # the ledger conserves
    t = summary["totals"]
    assert t["submitted"] == (t["answered"] + t["rejected"]
                              + t["displaced"] + t["errored"]
                              + t["dropped"])


def test_scenario_trace_shows_live_system(scenario_run):
    """The trace really contains the whole circulatory system: serve
    batches with lane composition, checkpoint saves, hot reloads, and
    replica pushes — not just a load test against a static model."""
    rc, out = scenario_run
    records = obs_report.load_trace(str(out / "scenario_trace.jsonl"))
    kinds = {r.get("kind") for r in records}
    assert {"serve_batch", "serve_reload", "trace_span",
            "metric_counters"} <= kinds
    reloads = [r for r in records if r.get("kind") == "serve_reload"
               and r.get("event") == "reloaded"]
    assert len(reloads) >= 3  # initial load + >= 2 hot reloads
    lat = obs_report.lane_latency_stats(records)
    assert "interactive" in lat and "batch" in lat
    stale = obs_report.staleness_samples(records)
    assert stale and all(s["staleness_s"] >= 0.0 for s in stale)


def test_scenario_violated_slo_fails_the_gate(scenario_run, tmp_path):
    """Same trace, one deliberately impossible bound: the report CLI
    must exit 1 — proving the gate can actually fail."""
    rc, out = scenario_run
    bad = tmp_path / "bad_slo.json"
    bad.write_text(json.dumps(build_slos(
        "smoke", violate="interactive-p99")))
    assert obs_report.main(
        [str(out / "scenario_trace.jsonl"), "--slo", str(bad)]) == 1
