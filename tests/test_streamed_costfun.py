"""Host-streamed chunked CostFun: beyond-HBM quasi-Newton for ANY loss.

VERDICT r4 #1: the reference's LBFGS CostFun does a full-batch
treeAggregate over an RDD of ANY size for ANY gradient ([U]
mllib/optimization/LBFGS.scala); `optimize/streamed_costfun.py` is the
chunked host-streaming analogue.  These tests pin (a) sum-level
equivalence of the chunked accumulation vs the one-pass resident kernels,
(b) trajectory parity of host-streamed LBFGS/OWL-QN vs the resident runs
for logistic, hinge, least-squares, and multinomial losses, (c) the mesh
composition (per-shard chunks + psum), and (d) the guard rails.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from tpu_sgd.ops.gradients import (
    HingeGradient,
    LeastSquaresGradient,
    LogisticGradient,
    MultinomialLogisticGradient,
)
from tpu_sgd.ops.updaters import SimpleUpdater, SquaredL2Updater
from tpu_sgd.optimize.lbfgs import LBFGS
from tpu_sgd.optimize.owlqn import OWLQN
from tpu_sgd.optimize.streamed_costfun import (
    StreamedCostFun,
    default_stream_batch_rows,
)


@pytest.fixture
def rng():
    return np.random.default_rng(7)


def _binary_data(rng, n=2048, d=12):
    X = rng.normal(size=(n, d)).astype(np.float32)
    w = rng.uniform(-1, 1, d).astype(np.float32)
    y = (X @ w + 0.3 * rng.normal(size=n) > 0).astype(np.float32)
    return X, y


def _ls_data(rng, n=2048, d=12):
    X = rng.normal(size=(n, d)).astype(np.float32)
    w = rng.uniform(-1, 1, d).astype(np.float32)
    y = (X @ w + 0.05 * rng.normal(size=n)).astype(np.float32)
    return X, y


# ---- sum-level equivalence -------------------------------------------------

@pytest.mark.parametrize("gradient", [
    LeastSquaresGradient(), LogisticGradient(), HingeGradient(),
])
def test_chunked_sums_match_one_pass(rng, gradient):
    """cost/loss/sweep sums over a non-divisible chunk grid must equal the
    single fused pass (up to summation reassociation)."""
    X, y = _binary_data(rng, n=1000, d=8)
    w = rng.normal(size=(8,)).astype(np.float32)
    scf = StreamedCostFun(gradient, X, y, batch_rows=192)  # 1000 % 192 != 0
    assert scf.n_chunks == 6
    gs, ls, c = (np.asarray(v) for v in scf.cost_sums(w))
    g_ref, l_ref, c_ref = (np.asarray(v) for v in
                           gradient.batch_sums(jnp.asarray(X),
                                               jnp.asarray(y),
                                               jnp.asarray(w)))
    assert c == c_ref == 1000
    np.testing.assert_allclose(gs, g_ref, rtol=2e-5, atol=2e-4)
    np.testing.assert_allclose(ls, l_ref, rtol=2e-5, atol=2e-4)
    ls2, c2 = (np.asarray(v) for v in scf.loss_sums(w))
    np.testing.assert_allclose(ls2, l_ref, rtol=2e-5, atol=2e-4)
    assert c2 == 1000
    W = np.stack([w, 0.5 * w, np.zeros_like(w)]).astype(np.float32)
    sw, c3 = (np.asarray(v) for v in scf.sweep_sums(jnp.asarray(W)))
    sw_ref, _ = gradient.loss_sweep(jnp.asarray(X), jnp.asarray(y),
                                    jnp.asarray(W))
    np.testing.assert_allclose(sw, np.asarray(sw_ref), rtol=2e-5, atol=2e-4)
    assert c3 == 1000


def test_default_batch_rows_scales_with_row_bytes():
    assert default_stream_batch_rows(1000, 4) == 64000
    assert default_stream_batch_rows(1000, 2) == 128000
    assert default_stream_batch_rows(10_000_000, 4) == 1024  # floor


# ---- trajectory parity: LBFGS ---------------------------------------------

@pytest.mark.parametrize("gradient,updater", [
    (LogisticGradient(), SquaredL2Updater()),
    (HingeGradient(), SquaredL2Updater()),
    (LeastSquaresGradient(), SimpleUpdater()),
])
def test_lbfgs_host_streamed_matches_resident(rng, gradient, updater):
    """Host-streamed LBFGS must reproduce the resident trajectory — the
    beyond-HBM CostFun is the same math, chunked."""
    X, y = (_binary_data(rng) if not isinstance(gradient,
                                                LeastSquaresGradient)
            else _ls_data(rng))
    w0 = np.zeros((X.shape[1],), np.float32)

    def make():
        return LBFGS(gradient, updater, max_num_iterations=15,
                     convergence_tol=0.0, reg_param=0.01)

    w_res, h_res = make().optimize_with_history((X, y), w0)
    opt = make().set_host_streaming(True, batch_rows=300)
    w_str, h_str = opt.optimize_with_history((X, y), w0)
    # Once the loss is flat at machine precision, the Armijo accept can
    # flip on last-ulp differences between chunked and fused sums (one
    # path stops, the other keeps re-accepting no-op steps) — so compare
    # the common prefix, which must cover the whole descent.
    L = min(len(h_res), len(h_str))
    assert L >= 8
    np.testing.assert_allclose(np.asarray(h_str)[:L],
                               np.asarray(h_res)[:L],
                               rtol=5e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(w_str), np.asarray(w_res),
                               rtol=5e-4, atol=5e-4)


def test_lbfgs_host_streamed_multinomial(rng):
    """Matrix-weight (flattened) multinomial: the chunked sweep must feed
    the same ladder economy as the resident run."""
    n, d, K = 1536, 10, 4
    X = rng.normal(size=(n, d)).astype(np.float32)
    Wt = rng.normal(size=(K - 1, d)).astype(np.float32)
    logits = np.concatenate([np.zeros((n, 1)), X @ Wt.T], axis=1)
    y = logits.argmax(axis=1).astype(np.float32)
    g = MultinomialLogisticGradient(K)
    w0 = np.zeros((g.weight_dim(d),), np.float32)

    def make():
        return LBFGS(g, SquaredL2Updater(), max_num_iterations=10,
                     convergence_tol=0.0, reg_param=0.01)

    w_res, h_res = make().optimize_with_history((X, y), w0)
    w_str, h_str = make().set_host_streaming(True, batch_rows=500) \
        .optimize_with_history((X, y), w0)
    assert len(h_res) == len(h_str)
    np.testing.assert_allclose(np.asarray(h_str), np.asarray(h_res),
                               rtol=5e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(w_str), np.asarray(w_res),
                               rtol=5e-4, atol=5e-5)


def test_lbfgs_host_streamed_sequential_fallback(rng):
    """A gradient without loss_sweep falls back to sequential trials with
    the documented warning; the chunked loss-only evaluation still
    reproduces the resident fallback trajectory."""

    class NoSweep(LogisticGradient):
        pass

    NoSweep.loss_sweep = property()  # hides the attribute (AttributeError)
    X, y = _binary_data(rng, n=800, d=6)
    w0 = np.zeros((6,), np.float32)
    g = NoSweep()
    assert not hasattr(g, "loss_sweep")

    def make():
        return LBFGS(g, SquaredL2Updater(), max_num_iterations=8,
                     convergence_tol=0.0, reg_param=0.01)

    with pytest.warns(RuntimeWarning, match="SEQUENTIAL"):
        w_res, h_res = make().optimize_with_history((X, y), w0)
    with pytest.warns(RuntimeWarning, match="SEQUENTIAL"):
        w_str, h_str = make().set_host_streaming(True, batch_rows=300) \
            .optimize_with_history((X, y), w0)
    assert len(h_res) == len(h_str)
    np.testing.assert_allclose(np.asarray(w_str), np.asarray(w_res),
                               rtol=5e-4, atol=5e-5)


# ---- trajectory parity: OWL-QN --------------------------------------------

def test_owlqn_host_streamed_matches_resident(rng):
    X, y = _binary_data(rng)
    w0 = np.zeros((X.shape[1],), np.float32)

    def make():
        return OWLQN(LogisticGradient(), max_num_iterations=12,
                     convergence_tol=0.0, reg_param=0.005)

    w_res, h_res = make().optimize_with_history((X, y), w0)
    w_str, h_str = make().set_host_streaming(True, batch_rows=300) \
        .optimize_with_history((X, y), w0)
    assert len(h_res) == len(h_str)
    np.testing.assert_allclose(np.asarray(h_str), np.asarray(h_res),
                               rtol=5e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(w_str), np.asarray(w_res),
                               rtol=5e-4, atol=5e-5)
    # L1 actually sparsifies on both paths identically
    assert (np.asarray(w_str) == 0).sum() == (np.asarray(w_res) == 0).sum()


# ---- mesh composition ------------------------------------------------------

def test_lbfgs_host_streamed_mesh_matches_single(rng):
    """Per-shard chunk streams + psum must reproduce the single-device
    host-streamed run (and so the resident run) — the multi-executor
    treeAggregate shape."""
    from tpu_sgd import data_mesh

    X, y = _binary_data(rng, n=2048, d=12)
    w0 = np.zeros((12,), np.float32)

    def make():
        return LBFGS(LogisticGradient(), SquaredL2Updater(),
                     max_num_iterations=12, convergence_tol=0.0,
                     reg_param=0.01)

    w_one, h_one = make().set_host_streaming(True, batch_rows=512) \
        .optimize_with_history((X, y), w0)
    w_mesh, h_mesh = make().set_host_streaming(True, batch_rows=512) \
        .set_mesh(data_mesh()).optimize_with_history((X, y), w0)
    assert len(h_one) == len(h_mesh)
    np.testing.assert_allclose(np.asarray(h_mesh), np.asarray(h_one),
                               rtol=5e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(w_mesh), np.asarray(w_one),
                               rtol=5e-4, atol=5e-5)


def test_mesh_chunk_cap_padding(rng):
    """A chunk cap that does not divide the mesh is padded up and masked
    — sums stay exact."""
    from tpu_sgd import data_mesh

    mesh = data_mesh()
    X, y = _binary_data(rng, n=700, d=8)
    g = LogisticGradient()
    w = rng.normal(size=(8,)).astype(np.float32)
    scf = StreamedCostFun(g, X, y, batch_rows=250, mesh=mesh)
    assert scf.cap % mesh.shape["data"] == 0
    gs, ls, c = (np.asarray(v) for v in scf.cost_sums(w))
    g_ref, l_ref, _ = (np.asarray(v) for v in
                       g.batch_sums(jnp.asarray(X), jnp.asarray(y),
                                    jnp.asarray(w)))
    assert c == 700
    np.testing.assert_allclose(gs, g_ref, rtol=2e-5, atol=2e-4)
    np.testing.assert_allclose(ls, l_ref, rtol=2e-5, atol=2e-4)


# ---- guards ----------------------------------------------------------------

def test_host_streaming_guards(rng):
    from tpu_sgd.ops.gram import GramLeastSquaresGradient
    from tpu_sgd.ops.sparse import sparse_data

    X, y = _ls_data(rng, n=256, d=8)
    w0 = np.zeros((8,), np.float32)
    Xs, ys, _ = sparse_data(64, 8, nnz_per_row=3, seed=0)
    with pytest.raises(NotImplementedError, match="dense rows"):
        LBFGS().set_host_streaming(True).optimize_with_history(
            (Xs, ys), w0)
    g = GramLeastSquaresGradient.build(X, y, block_rows=64)
    with pytest.raises(ValueError, match="statistics"):
        LBFGS(g).set_host_streaming(True).optimize_with_history(
            (g.data, y), w0)
    with pytest.raises(ValueError, match="alternative"):
        LBFGS().set_host_streaming(True).set_streamed_stats(True) \
            .optimize_with_history((X, y), w0)
    with pytest.raises(ValueError, match="device-resident"):
        LBFGS().set_host_streaming(True).set_sufficient_stats(True) \
            .optimize_with_history((X, y), w0)
    with pytest.raises(ValueError, match="batch_rows must be positive"):
        LBFGS().set_host_streaming(True, batch_rows=0)


def test_streamed_costfun_identity_cache(rng):
    """Repeat optimize() calls on the same arrays must reuse the compiled
    CostFun (identity cache), and release_sufficient_stats drops it."""
    X, y = _binary_data(rng, n=512, d=8)
    w0 = np.zeros((8,), np.float32)
    opt = LBFGS(LogisticGradient(), SquaredL2Updater(),
                max_num_iterations=3, convergence_tol=0.0) \
        .set_host_streaming(True, batch_rows=256)
    opt.optimize_with_history((X, y), w0)
    entry = opt._stream_costfun_entry
    assert entry is not None
    opt.optimize_with_history((X, y), w0)
    assert opt._stream_costfun_entry is entry  # reused, not rebuilt
    opt.release_sufficient_stats()
    assert opt._stream_costfun_entry is None


def test_empty_input_falls_through(rng):
    w0 = np.zeros((4,), np.float32)
    X = np.zeros((0, 4), np.float32)
    y = np.zeros((0,), np.float32)
    w, h = LBFGS().set_host_streaming(True).optimize_with_history(
        (X, y), w0)
    assert h.shape == (0,)
