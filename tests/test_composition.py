"""ISSUE 20 composition grid: one fused resident core.

The matrix (feed × compressed × resident × meshed) — every cell either
trains BITWISE against its recorded twin, or is matched-loss
(≤ 1.01×) and says so (compressed cells change the update rule), or
is a LOUD recorded fallback whose warning names this grid.  The
dispatch/compile pins are counted with the runtime twins
(``assert_dispatch_count`` / ``assert_compile_count``), never timed.

Cells:

* dense full-batch × {dense, compressed} × {superstep, resident}:
  resident is bitwise vs superstep, compressed-resident is bitwise vs
  compressed-superstep on this harness (same in-trace static-k
  ``top_k`` body — the EF accumulator rides the while-loop ring).
* dense slab (fully resident rows) × compressed × resident: bitwise
  replay; PARTIAL slab × compressed: loud dense-wire fallback.
* host-sampled (bernoulli, frac < 1) × resident: loud superstep
  fallback (the per-batch host hop IS the data feed).
* sparse full-batch × resident: bitwise vs the sparse superstep
  program; sparse bernoulli × resident: loud fallback; sparse ×
  compressed: loud no-op (the BCOO wire is already compressed).
* meshed × resident: loud superstep fallback; meshed × compressed:
  matched loss vs the meshed dense wire.
* replica × resident (one device per worker): τ=0 ``resident_rounds=1``
  is bitwise vs the per-cycle threaded loop; ``resident_rounds>=2``
  folds K sampled batches — matched loss; a shared-device fleet is a
  loud per-cycle fallback.
* resident cells run at ONE dispatch per run and ONE compiled body per
  build; resident+compressed pays ≥ 10× fewer dispatches than
  superstep+compressed at matched iterations (BENCH_RESIDENT.json
  records the measured cell).
"""

import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_sgd.config import SGDConfig
from tpu_sgd.ops.gradients import HingeGradient, LeastSquaresGradient
from tpu_sgd.ops.updaters import SimpleUpdater
from tpu_sgd.optimize.gradient_descent import GradientDescent

TOL_MATCHED = 0.01  # compressed cells: <= 1.01x matched final loss


def _dense(n=256, d=16, seed=3):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, d)).astype(np.float32)
    w_true = rng.normal(size=d).astype(np.float32)
    y = (X @ w_true + 0.01 * rng.normal(size=n)).astype(np.float32)
    return X, y, np.zeros(d, np.float32)


def _opt(*, iters=16, frac=1.0, sampling="bernoulli", k=4, c=0, wc=None,
         mesh=None, step=0.1, seed=7):
    o = (GradientDescent()
         .set_num_iterations(iters).set_step_size(step)
         .set_mini_batch_fraction(frac).set_sampling(sampling)
         .set_convergence_tol(0.0).set_seed(seed)
         .set_host_streaming(True))
    if k > 1:
        o.set_superstep(k)
    if c:
        o.set_residency(c)
    if wc:
        o.set_ingest_options(wire_compress=wc)
    if mesh is not None:
        o.set_mesh(mesh)
    return o


def _no_warnings_run(o, X, y, w0):
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        return o.optimize_with_history((X, y), w0)


# ---- dense feed ------------------------------------------------------------

@pytest.mark.parametrize("wc", [None, "topk:0.25"])
def test_grid_dense_full_batch_resident_bitwise_vs_superstep(wc):
    """feed=full-batch × compressed={off,on} × resident={off,on}: the
    resident cell replays the superstep cell BITWISE (same fused body,
    one while_loop around it) with ZERO fallback warnings — the
    compressed pair is the cell the PR 9 deviation used to refuse."""
    X, y, w0 = _dense()
    w_sup, h_sup = _opt(iters=16, k=4, wc=wc).optimize_with_history(
        (X, y), w0)
    w_res, h_res = _no_warnings_run(
        _opt(iters=16, k=4, c=2, wc=wc), X, y, w0)
    np.testing.assert_array_equal(np.asarray(w_res), np.asarray(w_sup))
    np.testing.assert_array_equal(h_res, h_sup)


def test_grid_dense_compressed_matched_loss_not_bitwise():
    """compressed cells are matched-loss vs the DENSE twin (≤ 1.01×),
    never claimed bitwise: top-k + error feedback changes the update
    rule."""
    X, y, w0 = _dense()
    _, h_dense = _opt(iters=120, k=4).optimize_with_history((X, y), w0)
    _, h_comp = _no_warnings_run(
        _opt(iters=120, k=4, c=2, wc="topk:0.75"), X, y, w0)
    assert abs(h_comp[-1] - h_dense[-1]) <= TOL_MATCHED * abs(h_dense[-1])
    assert not np.array_equal(h_comp, h_dense)


def test_grid_slab_fully_resident_compressed_bitwise_replay():
    """feed=slab (resident rows cover the dataset, sliced sampling) ×
    compressed × resident: runs with zero fallback warnings and
    replays itself bitwise."""
    X, y, w0 = _dense(n=200)

    def mk():
        o = _opt(iters=16, frac=0.25, sampling="sliced", k=4, c=2,
                 wc="topk:0.25")
        o.streaming_resident_rows = X.shape[0]
        return o

    w1, h1 = _no_warnings_run(mk(), X, y, w0)
    w2, h2 = _no_warnings_run(mk(), X, y, w0)
    np.testing.assert_array_equal(np.asarray(w1), np.asarray(w2))
    np.testing.assert_array_equal(h1, h2)


def test_grid_slab_partial_compressed_is_loud_dense_wire_cell():
    """feed=slab-partial × compressed: the resident-window step has no
    EF carry, so the wire falls back to dense — LOUDLY, naming this
    grid."""
    X, y, w0 = _dense(n=128, d=8)
    o = _opt(iters=8, frac=0.5, sampling="sliced", k=1, wc="topk:0.25")
    o.streaming_resident_rows = 100
    with pytest.warns(RuntimeWarning, match="partially-resident"):
        _, h = o.optimize_with_history((X, y), w0)
    assert len(h) == 8


def test_grid_host_sampled_resident_is_loud_superstep_cell():
    """feed=host-sampled (bernoulli, frac < 1) × resident: the
    per-batch host hop IS the data feed — loud superstep fallback,
    bitwise vs the plain superstep run."""
    X, y, w0 = _dense(n=128, d=8)
    with pytest.warns(RuntimeWarning, match="test_composition"):
        w_f, h_f = _opt(iters=8, frac=0.5, k=4, c=2) \
            .optimize_with_history((X, y), w0)
    w_s, h_s = _opt(iters=8, frac=0.5, k=4).optimize_with_history(
        (X, y), w0)
    np.testing.assert_array_equal(np.asarray(w_f), np.asarray(w_s))
    np.testing.assert_array_equal(h_f, h_s)


# ---- sparse feed -----------------------------------------------------------

def _sparse(n=120, d=80, seed=5):
    from tpu_sgd.ops.sparse import sparse_data

    X, y, _ = sparse_data(n, d, nnz_per_row=6, kind="svm", seed=seed)
    return X, y, np.zeros(d, np.float32)


def test_grid_sparse_full_batch_resident_bitwise_vs_superstep():
    """feed=sparse (fixed-nse BCOO slab) × resident: the sparse
    superstep body runs as a feed variant of the SAME resident scan —
    whole run on device, bitwise vs the sparse superstep program."""
    from tpu_sgd.optimize.streamed_sparse import \
        optimize_host_streamed_sparse

    X, y, w0 = _sparse()
    cfg = SGDConfig(step_size=0.2, num_iterations=18,
                    mini_batch_fraction=1.0, convergence_tol=0.0,
                    sampling="bernoulli", seed=11)
    w_sup, h_sup = optimize_host_streamed_sparse(
        HingeGradient(), SimpleUpdater(), cfg, X, y, w0, superstep_k=4)
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        w_res, h_res = optimize_host_streamed_sparse(
            HingeGradient(), SimpleUpdater(), cfg, X, y, w0,
            superstep_k=4, resident_cadence=2)
    np.testing.assert_array_equal(np.asarray(w_sup), np.asarray(w_res))
    np.testing.assert_array_equal(h_sup, h_res)


def test_grid_sparse_fallback_cells():
    """feed=sparse × {host-sampled resident, K=1 resident, compressed}:
    all three are loud recorded fallbacks."""
    from tpu_sgd.optimize.streamed_sparse import \
        optimize_host_streamed_sparse

    X, y, w0 = _sparse()
    cfg = SGDConfig(step_size=0.2, num_iterations=8,
                    mini_batch_fraction=0.4, convergence_tol=0.0,
                    sampling="bernoulli", seed=11)
    # host-sampled sparse × resident: superstep keeps running, bitwise
    with pytest.warns(RuntimeWarning, match="test_composition"):
        w_f, h_f = optimize_host_streamed_sparse(
            HingeGradient(), SimpleUpdater(), cfg, X, y, w0,
            superstep_k=4, resident_cadence=2)
    w_s, h_s = optimize_host_streamed_sparse(
        HingeGradient(), SimpleUpdater(), cfg, X, y, w0, superstep_k=4)
    np.testing.assert_array_equal(np.asarray(w_f), np.asarray(w_s))
    np.testing.assert_array_equal(h_f, h_s)
    # resident without the fused executor
    full = cfg.replace(mini_batch_fraction=1.0)
    with pytest.warns(RuntimeWarning, match="superstep"):
        optimize_host_streamed_sparse(
            HingeGradient(), SimpleUpdater(), full, X, y, w0,
            resident_cadence=2)
    # sparse × compressed: the BCOO wire is already compressed
    with pytest.warns(RuntimeWarning, match="already compressed"):
        optimize_host_streamed_sparse(
            HingeGradient(), SimpleUpdater(), full, X, y, w0,
            superstep_k=4, wire_compress="topk:0.5")


# ---- meshed ----------------------------------------------------------------

def test_grid_meshed_cells():
    """meshed × resident: loud superstep fallback (matching the
    unmeshed superstep trajectory is the MESHED driver's own
    contract); meshed × compressed: matched loss vs meshed dense."""
    from tpu_sgd.parallel.mesh import data_mesh

    X, y, w0 = _dense(n=256, d=16)
    mesh = data_mesh(jax.devices()[:4])
    # resident on a mesh: warned fallback, same trajectory as meshed
    # superstep
    with pytest.warns(RuntimeWarning):
        w_r, h_r = _opt(iters=12, frac=0.5, k=4, c=2, mesh=mesh) \
            .optimize_with_history((X, y), w0)
    w_s, h_s = _opt(iters=12, frac=0.5, k=4, mesh=mesh) \
        .optimize_with_history((X, y), w0)
    np.testing.assert_array_equal(np.asarray(w_r), np.asarray(w_s))
    # meshed compressed: matched loss vs meshed dense
    _, h_d = _opt(iters=80, frac=0.5, k=4, mesh=mesh) \
        .optimize_with_history((X, y), w0)
    _, h_c = _opt(iters=80, frac=0.5, k=4, mesh=mesh,
                  wc="topk:0.75").optimize_with_history((X, y), w0)
    assert abs(h_c[-1] - h_d[-1]) <= TOL_MATCHED * abs(h_d[-1])


# ---- replica ---------------------------------------------------------------

def _replica_driver(workers=2, tau=0, rounds=0, wc=None, iters=16):
    from tpu_sgd.replica import ReplicaDriver

    d = (ReplicaDriver(LeastSquaresGradient(), SimpleUpdater())
         .set_step_size(0.3).set_num_iterations(iters)
         .set_mini_batch_fraction(0.5).set_convergence_tol(0.0)
         .set_reg_param(0.1).set_workers(workers).set_staleness(tau))
    if rounds:
        d.set_resident_rounds(rounds)
    if wc:
        d.set_wire_compress(wc)
    return d


@pytest.mark.skipif(len(jax.devices()) < 2,
                    reason="resident replicas need one device per worker")
def test_grid_replica_resident_cells():
    """replica × resident (one device per worker): ``resident_rounds=1``
    at τ=0 is BITWISE the per-cycle threaded loop — the while_loop
    carry (w, version, done) drives the identical pull → local-sums →
    push protocol; the compressed wire rides the same
    ``_push_contribution`` host code, also bitwise vs its per-cycle
    twin; K=2 folds two sampled batches per push — matched loss."""
    X, y, w0 = _dense(n=256, d=12, seed=0)
    w_ref, h_ref = _replica_driver().optimize_with_history((X, y), w0)
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        w_res, h_res = _replica_driver(rounds=1).optimize_with_history(
            (X, y), w0)
    np.testing.assert_array_equal(np.asarray(w_ref), np.asarray(w_res))
    np.testing.assert_array_equal(np.asarray(h_ref), np.asarray(h_res))
    # compressed wire × resident: bitwise vs per-cycle compressed
    w_cs, _ = _replica_driver(wc="topk:0.25").optimize_with_history(
        (X, y), w0)
    w_cr, _ = _replica_driver(rounds=1, wc="topk:0.25") \
        .optimize_with_history((X, y), w0)
    np.testing.assert_array_equal(np.asarray(w_cs), np.asarray(w_cr))
    # K=2: the K-fold batch union per push — matched loss, NOT bitwise.
    # Folding two stale-basis batches per push keeps a bounded
    # trajectory lag behind the per-cycle loop (measured ≈ 5 cycles on
    # this workload), so the 1.01× bar is asserted with a 6-cycle
    # allowance on the geometrically-decaying reference.
    _, h_48 = _replica_driver(iters=48).optimize_with_history((X, y), w0)
    _, h2 = _replica_driver(rounds=2, iters=48).optimize_with_history(
        (X, y), w0)
    assert len(h2) == len(h_48) and np.isfinite(np.asarray(h2)).all()
    assert h2[-1] <= (1 + TOL_MATCHED) * h_48[-1 - 6], (h2[-1], h_48[-7])


def test_grid_replica_resident_shared_device_is_loud_fallback():
    """replica × resident on a shared device: two resident while_loops
    would serialize on the device and deadlock the τ=0 round barrier —
    loud per-cycle fallback, bitwise vs the threaded loop."""
    X, y, w0 = _dense(n=128, d=8, seed=0)
    d = _replica_driver(rounds=1, iters=8)
    d.set_devices([jax.devices()[0]])
    with pytest.warns(RuntimeWarning, match="one device per worker"):
        w_f, h_f = d.optimize_with_history((X, y), w0)
    w_s, h_s = _replica_driver(iters=8).optimize_with_history((X, y), w0)
    np.testing.assert_array_equal(np.asarray(w_f), np.asarray(w_s))


# ---- dispatch / compile pins -----------------------------------------------

def test_grid_resident_compressed_one_dispatch_one_program():
    """The EF-carry resident loop keeps the driver's structural pins:
    ONE dispatch per run (cadence windows are callbacks, not
    launches), ONE compiled body per build."""
    from tpu_sgd.analysis import (assert_compile_count,
                                  assert_dispatch_count)
    from tpu_sgd.optimize.gradient_descent import make_compressed_step
    from tpu_sgd.optimize.resident_driver import (ResidentBookkeeper,
                                                  ResidentLoop)

    X, y, w0 = _dense(n=200, d=10)
    cfg = SGDConfig(step_size=0.1, num_iterations=24,
                    mini_batch_fraction=1.0, convergence_tol=0.0,
                    sampling="bernoulli", seed=7)
    comp = make_compressed_step(LeastSquaresGradient(), SimpleUpdater(),
                                cfg, 0.25)

    def _step(w, e, i, rv, Xr, yr):
        return comp(w, e, Xr, yr, i, rv, None)

    loop = ResidentLoop(_step, cfg, 4, 3, with_extra=True)
    Xd, yd = jnp.asarray(X), jnp.asarray(y)
    ef0 = jnp.zeros_like(jnp.asarray(w0))

    def run():
        hooks = ResidentBookkeeper(cfg, 4, 3, losses=[], reg_val=0.0,
                                   start_iter=1)
        return loop.run(jnp.asarray(w0), 0.0, 1, (Xd, yd), hooks,
                        extra0=ef0)

    run()  # warm the compile
    assert loop.compile_cache_size() == 1
    with assert_compile_count(0, of=loop.compile_cache_size):
        run()
    # last: the dispatch-count hook swaps the jit internals (and drops
    # the warm cache on exit), so it must not precede the compile pin
    with assert_dispatch_count(1):
        run()


def test_grid_resident_compressed_10x_fewer_dispatches():
    """ISSUE 20 acceptance: resident+compressed launches ≥ 10× fewer
    programs than superstep+compressed at matched iterations (the
    counted cell BENCH_RESIDENT.json records)."""
    from tpu_sgd.analysis import count_dispatches

    X, y, w0 = _dense(n=200, d=10)

    def count(c):
        o = _opt(iters=320, k=4, c=c, wc="topk:0.25")
        o.optimize_with_history((X, y), w0)  # warm the compiles
        with count_dispatches() as got:
            o.optimize_with_history((X, y), w0)
        return got["n"]

    n_res, n_sup = count(3), count(0)
    assert n_sup >= 10 * n_res, (n_sup, n_res)


# ---- EF carried in the while_loop: preempt → resume bitwise ----------------

def test_grid_resident_compressed_preempt_resume_bitwise(tmp_path):
    """ISSUE 20 acceptance: the EF accumulator rides the while-loop
    ring, checkpoints through ``extras={"ef": ...}`` at the cadence
    boundary, and a preempted + resumed compressed-resident run is
    BITWISE its uninterrupted twin."""
    from tpu_sgd.reliability.supervisor import TrainingPreempted
    from tpu_sgd.utils.checkpoint import CheckpointManager

    X, y, w0 = _dense(n=256, d=12)

    def mk():
        return _opt(iters=30, k=4, c=2, wc="topk:0.25")

    w_ref, h_ref = mk().optimize_with_history((X, y), w0)

    class StopSecond:
        def __init__(self):
            self.polls = 0

        def __call__(self):
            self.polls += 1
            return self.polls == 2

    ckdir = str(tmp_path / "ck")
    o = mk().set_checkpoint(CheckpointManager(ckdir), every=100)
    o.set_stop_signal(StopSecond())
    with pytest.raises(TrainingPreempted) as ei:
        o.optimize_with_history((X, y), w0)
    assert ei.value.iteration == 16  # second C*K window boundary
    state = CheckpointManager(ckdir).restore()
    assert "ef" in state["extras"]  # EF left the ring into the save
    o2 = mk().set_checkpoint(CheckpointManager(ckdir), every=100)
    w_res, h_res = o2.optimize_with_history((X, y), w0)
    np.testing.assert_array_equal(np.asarray(w_res), np.asarray(w_ref))
    np.testing.assert_array_equal(h_res, h_ref)


# ---- planner: the knobs stopped mutually excluding -------------------------

def test_grid_plan_proposes_residency_and_wire_compress_together():
    """choose_residency × choose_wire_compress: a single-device
    full-batch plan may now propose BOTH (the EF select rides the
    resident body in-trace), apply/reset round-trip the combined
    knobs, and user-set values still win."""
    from tpu_sgd.plan import (apply_gram_knobs, plan,
                              reset_plan_owned_gram_knobs)

    p = plan(200_000, 256, itemsize=4, sampling="bernoulli",
             mini_batch_fraction=1.0, num_iterations=1000,
             free_hbm=8e6, host_resident_ok=True, checkpoint_every=64)
    assert p.schedule == "host_streamed"
    assert p.residency >= 2 and p.wire_compress is not None
    assert "riding the resident body" in p.reason
    assert p.estimates["residency"] == p.residency
    assert p.estimates["wire_compress"] == p.wire_compress

    o = GradientDescent()
    apply_gram_knobs(o, p)
    assert o.resident_cadence == p.residency
    assert o.ingest_wire_compress == p.wire_compress
    reset_plan_owned_gram_knobs(o)
    assert o.resident_cadence == 0 and o.ingest_wire_compress is None
    # user wins on BOTH knobs independently
    o2 = (GradientDescent().set_residency(6)
          .set_ingest_options(wire_compress="topk:0.2"))
    apply_gram_knobs(o2, p)
    assert o2.resident_cadence == 6
    assert o2.ingest_wire_compress == "topk:0.2"