"""Sparse (BCOO) training path tests.

VERDICT r1 missing #2 / SURVEY.md §2 #10: the reference trains directly on
``SparseVector`` features ([U] mllib/linalg/Vectors.scala); these tests prove
the BCOO path gives the SAME results as the dense path (same fused step, same
seeds) and that config-3-shaped data (~47k features, ~0.1% nnz) trains
without ever materializing dense X.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from tpu_sgd.models.classification import (
    LogisticRegressionWithSGD,
    SVMWithSGD,
)
from tpu_sgd.ops.gradients import (
    HingeGradient,
    LeastSquaresGradient,
    LogisticGradient,
    MultinomialLogisticGradient,
)
from tpu_sgd.ops.sparse import (
    append_bias_bcoo,
    csr_to_bcoo,
    is_sparse,
    load_libsvm_file_bcoo,
    sparse_data,
)
from tpu_sgd.ops.updaters import L1Updater, SquaredL2Updater
from tpu_sgd.optimize.gradient_descent import GradientDescent
from tpu_sgd.optimize.lbfgs import LBFGS
from tpu_sgd.optimize.owlqn import OWLQN


def _dense(X):
    return np.asarray(X.todense())


@pytest.fixture
def small_sparse():
    X, y, w_true = sparse_data(400, 60, nnz_per_row=8, kind="linear", seed=3)
    return X, jnp.asarray(y), w_true


def test_is_sparse(small_sparse):
    X, y, _ = small_sparse
    assert is_sparse(X)
    assert not is_sparse(_dense(X))
    assert not is_sparse(y)


def test_csr_to_bcoo_matches_dense_load(tmp_path):
    from tpu_sgd.utils.mlutils import load_libsvm_file, save_as_libsvm_file

    rng = np.random.default_rng(0)
    Xd = rng.normal(size=(30, 12)).astype(np.float32)
    Xd[rng.uniform(size=Xd.shape) < 0.7] = 0.0
    Xd[:, 0] = 1.0  # keep max-index discovery exact
    Xd[0, -1] = 0.5
    y = rng.integers(0, 2, size=30).astype(np.float32)
    path = str(tmp_path / "part.libsvm")
    save_as_libsvm_file(path, Xd, y)

    Xs, ys = load_libsvm_file_bcoo(path)
    Xd2, yd2 = load_libsvm_file(path)
    np.testing.assert_allclose(_dense(Xs), Xd2, rtol=1e-5)
    np.testing.assert_allclose(ys, yd2)


def test_csr_to_bcoo_roundtrip():
    # hand-built CSR triple: [[0, 2, 0], [1, 0, 3]]
    data = np.asarray([2.0, 1.0, 3.0], np.float32)
    indices = np.asarray([1, 0, 2], np.int32)
    indptr = np.asarray([0, 1, 3])
    X = csr_to_bcoo((data, indices, indptr), 3)
    np.testing.assert_allclose(
        _dense(X), [[0.0, 2.0, 0.0], [1.0, 0.0, 3.0]]
    )


def test_append_bias_bcoo(small_sparse):
    X, _, _ = small_sparse
    Xb = append_bias_bcoo(X)
    assert Xb.shape == (X.shape[0], X.shape[1] + 1)
    d = _dense(Xb)
    np.testing.assert_allclose(d[:, -1], 1.0)
    np.testing.assert_allclose(d[:, :-1], _dense(X))


@pytest.mark.parametrize(
    "grad", [LeastSquaresGradient(), LogisticGradient(), HingeGradient()]
)
@pytest.mark.parametrize("with_mask", [False, True])
def test_batch_sums_matches_dense(grad, with_mask, small_sparse):
    X, y, _ = small_sparse
    if not isinstance(grad, LeastSquaresGradient):
        y = (y > 0).astype(jnp.float32)
    w = jnp.asarray(
        np.random.default_rng(1).normal(size=(X.shape[1],)).astype(np.float32)
    )
    mask = (
        jnp.asarray(np.random.default_rng(2).uniform(size=X.shape[0]) < 0.5)
        if with_mask
        else None
    )
    gs, ls, c = grad.batch_sums(X, y, w, mask)
    gd, ld, cd = grad.batch_sums(jnp.asarray(_dense(X)), y, w, mask)
    np.testing.assert_allclose(gs, gd, rtol=2e-5, atol=1e-4)
    np.testing.assert_allclose(ls, ld, rtol=2e-5)
    assert int(c) == int(cd)


def test_multinomial_batch_sums_matches_dense():
    X, y, _ = sparse_data(200, 30, nnz_per_row=6, kind="linear", seed=7)
    y3 = jnp.asarray((np.asarray(y) > 0).astype(np.float32) + (
        np.asarray(y) > 1.0
    ).astype(np.float32))
    g = MultinomialLogisticGradient(3)
    w = jnp.asarray(
        np.random.default_rng(4).normal(size=(2 * 30,)).astype(np.float32)
    )
    gs, ls, c = g.batch_sums(X, y3, w)
    gd, ld, cd = g.batch_sums(jnp.asarray(_dense(X)), y3, w)
    np.testing.assert_allclose(gs, gd, rtol=2e-5, atol=1e-4)
    np.testing.assert_allclose(ls, ld, rtol=2e-5)


def test_gd_sparse_identical_to_dense(small_sparse):
    """Same seed + same fused step => the sparse run IS the dense run."""
    X, y, _ = small_sparse

    def run(Xin):
        opt = (
            GradientDescent(LeastSquaresGradient(), SquaredL2Updater())
            .set_step_size(0.1)
            .set_num_iterations(15)
            .set_reg_param(0.01)
            .set_mini_batch_fraction(0.5)
            .set_seed(9)
        )
        w, hist = opt.optimize_with_history((Xin, y), jnp.zeros((X.shape[1],)))
        return np.asarray(w), np.asarray(hist)

    w_s, h_s = run(X)
    w_d, h_d = run(jnp.asarray(_dense(X)))
    np.testing.assert_allclose(h_s, h_d, rtol=1e-4)
    np.testing.assert_allclose(w_s, w_d, rtol=1e-4, atol=1e-5)
    assert h_s[-1] < h_s[0]


def test_lbfgs_sparse_matches_dense(small_sparse):
    X, y, w_true = small_sparse
    opt = LBFGS(LeastSquaresGradient(), max_num_iterations=30)
    w_s, h_s = opt.optimize_with_history((X, y), jnp.zeros((X.shape[1],)))
    opt_d = LBFGS(LeastSquaresGradient(), max_num_iterations=30)
    w_d, h_d = opt_d.optimize_with_history(
        (jnp.asarray(_dense(X)), y), jnp.zeros((X.shape[1],))
    )
    np.testing.assert_allclose(h_s[-1], h_d[-1], rtol=1e-3)
    # least-squares on well-conditioned data: recovers the truth
    assert float(jnp.linalg.norm(w_s - jnp.asarray(w_true))) < 0.5


def test_owlqn_sparse_sparsifies():
    X, y, _ = sparse_data(500, 40, nnz_per_row=10, kind="logistic", seed=11)
    # reg small enough that w=0 is NOT already optimal (|grad_i(0)| > reg
    # for informative coordinates), large enough to zero the weak ones
    opt = OWLQN(LogisticGradient(), reg_param=0.01, max_num_iterations=40)
    w, hist = opt.optimize_with_history(
        (X, jnp.asarray(y)), jnp.zeros((40,))
    )
    assert hist[-1] < hist[0]
    assert int(jnp.sum(w == 0.0)) > 0  # L1 actually zeroed coordinates


def test_svm_train_bcoo_with_intercept():
    X, y, _ = sparse_data(800, 50, nnz_per_row=10, kind="svm", seed=13)
    model = SVMWithSGD.train(
        (X, y), num_iterations=40, step_size=1.0, reg_param=0.01,
        intercept=True,
    )
    preds = np.asarray(model.predict(X))  # sparse batch predict
    acc = float(np.mean(preds == np.asarray(y)))
    assert acc > 0.85
    # dense rows predict identically
    preds_d = np.asarray(model.predict(_dense(X)))
    np.testing.assert_allclose(preds, preds_d)


def test_logistic_train_bcoo():
    X, y, _ = sparse_data(800, 50, nnz_per_row=10, kind="logistic", seed=17)
    model = LogisticRegressionWithSGD.train(
        (X, y), num_iterations=40, step_size=1.0, reg_param=0.01
    )
    acc = float(np.mean(np.asarray(model.predict(X)) == np.asarray(y)))
    assert acc > 0.75


def test_sparse_guards(small_sparse):
    X, y, _ = small_sparse
    w0 = jnp.zeros((X.shape[1],))
    opt = GradientDescent().set_sampling("sliced").set_mini_batch_fraction(0.5)
    with pytest.raises(NotImplementedError, match="bernoulli"):
        opt.optimize((X, y), w0)
    # host streaming on sparse features TRAINS since the compressed-wire
    # round (optimize/streamed_sparse.py; tests/test_sparse_wire.py) —
    # the remaining guard is the meshed variant (single-device only)
    from tpu_sgd.parallel import data_mesh as _dm

    opt2 = GradientDescent().set_host_streaming(True).set_mesh(_dm())
    with pytest.raises(NotImplementedError, match="single-device"):
        opt2.optimize((X, y), w0)
    # ...and the sliced-sampling guard holds on the streamed path too
    opt3 = (GradientDescent().set_host_streaming(True)
            .set_sampling("sliced").set_mini_batch_fraction(0.5))
    with pytest.raises(NotImplementedError, match="bernoulli"):
        opt3.optimize((X, y), w0)
    from tpu_sgd.optimize.normal import NormalEquations

    with pytest.raises(NotImplementedError, match="dense features"):
        NormalEquations().optimize((X, y), w0)
    from tpu_sgd.config import MeshConfig

    mesh_2d = MeshConfig(data=4, model=2).build()
    with pytest.raises(NotImplementedError, match="model"):
        GradientDescent().set_mesh(mesh_2d).optimize((X, y), w0)


def _uneven_sparse():
    """Uneven row count (1003 % 8 != 0) exercises the padded-shard path."""
    from tpu_sgd.ops.sparse import sparse_data

    X, y, w_true = sparse_data(1003, 80, nnz_per_row=9, kind="linear", seed=3)
    return X, jnp.asarray(y), w_true


def test_sparse_dp_matches_dense_dp():
    """Distributed sparse == distributed dense, bit-for-bit trajectory:
    same contiguous row blocks, same per-shard sample streams, same psum."""
    from tpu_sgd.parallel import data_mesh

    X, y, _ = _uneven_sparse()
    mesh = data_mesh()

    def mk():
        return (
            GradientDescent(LeastSquaresGradient(), SquaredL2Updater())
            .set_step_size(0.2).set_num_iterations(12).set_reg_param(0.01)
            .set_mini_batch_fraction(0.5).set_seed(7).set_mesh(mesh)
        )

    w_s, h_s = mk().optimize_with_history((X, y), jnp.zeros((X.shape[1],)))
    Xd = jnp.asarray(_dense(X))
    w_d, h_d = mk().optimize_with_history((Xd, y), jnp.zeros((X.shape[1],)))
    np.testing.assert_allclose(h_s, h_d, rtol=1e-4)
    np.testing.assert_allclose(w_s, w_d, rtol=1e-4, atol=1e-5)


def test_sparse_lbfgs_dp_matches_single_device():
    from tpu_sgd.parallel import data_mesh

    X, y, _ = _uneven_sparse()
    w0 = jnp.zeros((X.shape[1],))
    w_m, h_m = (LBFGS(LeastSquaresGradient(), max_num_iterations=25)
                .set_mesh(data_mesh()).optimize_with_history((X, y), w0))
    w_1, h_1 = LBFGS(
        LeastSquaresGradient(), max_num_iterations=25
    ).optimize_with_history((X, y), w0)
    np.testing.assert_allclose(h_m[-1], h_1[-1], rtol=1e-4)
    np.testing.assert_allclose(w_m, w_1, rtol=1e-3, atol=1e-4)


def test_sparse_owlqn_dp_trains():
    from tpu_sgd.parallel import data_mesh

    X, y, _ = sparse_data(960, 40, nnz_per_row=10, kind="logistic", seed=11)
    opt = (OWLQN(LogisticGradient(), reg_param=0.01, max_num_iterations=30)
           .set_mesh(data_mesh()))
    w, hist = opt.optimize_with_history(
        (X, jnp.asarray(y)), jnp.zeros((40,))
    )
    assert hist[-1] < hist[0]
    # parity with the single-device orthant-wise run
    w1, h1 = OWLQN(
        LogisticGradient(), reg_param=0.01, max_num_iterations=30
    ).optimize_with_history((X, jnp.asarray(y)), jnp.zeros((40,)))
    np.testing.assert_allclose(hist[-1], h1[-1], rtol=1e-3)


def test_sparse_dp_handles_nse_sentinel_padding():
    """jax pads BCOO nse with out-of-bounds sentinel indices (== shape);
    BCOO ops drop them, and the mesh shard layout must too."""
    from jax.experimental.sparse import BCOO
    from tpu_sgd.parallel import data_mesh

    Xd = np.zeros((16, 5), np.float32)
    Xd[np.arange(16), np.arange(16) % 5] = 1.0
    X = BCOO.fromdense(jnp.asarray(Xd), nse=24)  # 8 sentinel entries
    y = jnp.asarray(np.arange(16, dtype=np.float32) % 5)

    def run(Xin, mesh):
        opt = GradientDescent().set_num_iterations(5).set_step_size(0.1)
        if mesh is not None:
            opt.set_mesh(mesh)
        return opt.optimize_with_history((Xin, y), jnp.zeros((5,)))

    w_m, h_m = run(X, data_mesh())
    w_d, h_d = run(jnp.asarray(Xd), data_mesh())
    np.testing.assert_allclose(h_m, h_d, rtol=1e-5)
    np.testing.assert_allclose(w_m, w_d, rtol=1e-5, atol=1e-6)


def test_sparse_multihost_assembly_degenerate_single_process():
    """The multi-host BCOO assembly path, run in its single-process
    degenerate form (process_allgather over one process), must produce the
    same global layout as the single-host path."""
    from tpu_sgd.parallel import data_mesh
    from tpu_sgd.parallel.sparse_parallel import (
        _shard_bcoo_multihost,
        shard_bcoo,
    )

    X, y, _ = _uneven_sparse()
    mesh = data_mesh()
    d1, i1, y1, v1, rl1, dd1 = shard_bcoo(mesh, X, np.asarray(y))
    d2, i2, y2, v2, rl2, dd2 = _shard_bcoo_multihost(mesh, X, np.asarray(y))
    assert (rl1, dd1) == (rl2, dd2)
    np.testing.assert_allclose(np.asarray(d1), np.asarray(d2))
    np.testing.assert_allclose(np.asarray(i1), np.asarray(i2))
    np.testing.assert_allclose(np.asarray(y1), np.asarray(y2))
    # single-host fast path may drop the mask; multihost always keeps it
    assert v2 is not None
    if v1 is not None:
        np.testing.assert_allclose(np.asarray(v1), np.asarray(v2))


def test_sparse_model_train_with_mesh():
    """SVMWithSGD.train(..., mesh=...) end-to-end on BCOO features."""
    from tpu_sgd.parallel import data_mesh

    X, y, _ = sparse_data(800, 50, nnz_per_row=10, kind="svm", seed=13)
    model = SVMWithSGD.train(
        (X, y), num_iterations=40, reg_param=0.01, intercept=True,
        mesh=data_mesh(),
    )
    acc = float(np.mean(np.asarray(model.predict(X)) == np.asarray(y)))
    assert acc > 0.85


def test_multinomial_lbfgs_sparse_train_and_predict():
    """Multiclass + intercept on BCOO: train via the bias-column override and
    predict on sparse batches (both code paths were sparse-blind before)."""
    from tpu_sgd.models.classification import LogisticRegressionWithLBFGS

    X, y, _ = sparse_data(600, 30, nnz_per_row=8, kind="linear", seed=23)
    y3 = ((np.asarray(y) > -0.5).astype(np.float32)
          + (np.asarray(y) > 0.5).astype(np.float32))
    model = LogisticRegressionWithLBFGS.train(
        (X, y3), max_num_iterations=30, num_classes=3, intercept=True
    )
    preds = np.asarray(model.predict(X))
    acc = float(np.mean(preds == y3))
    assert acc > 0.6
    # dense rows agree
    np.testing.assert_allclose(preds, np.asarray(model.predict(_dense(X))))
    # single sparse row == single dense row
    from jax.experimental.sparse import BCOO

    row = _dense(X)[0]
    p_sparse = model.predict(BCOO.fromdense(jnp.asarray(row)))
    assert float(p_sparse) == float(model.predict(row))


def test_streaming_sparse_batches():
    from tpu_sgd.models.streaming import StreamingLogisticRegressionWithSGD

    X, y, _ = sparse_data(900, 40, nnz_per_row=8, kind="logistic", seed=29)
    alg = StreamingLogisticRegressionWithSGD(
        step_size=1.0, num_iterations=10
    ).set_initial_weights(np.zeros(40))
    n = X.shape[0]
    for lo in range(0, n, 300):  # three sparse micro-batches
        idx = np.arange(lo, min(lo + 300, n))
        from jax.experimental.sparse import BCOO

        batch = BCOO.fromdense(jnp.asarray(_dense(X)[idx]))
        alg.train_on_batch(batch, np.asarray(y)[idx])
    acc = float(np.mean(np.asarray(alg.latest_model().predict(X))
                        == np.asarray(y)))
    assert acc > 0.7


def test_predict_margin_single_vector_shape(small_sparse):
    """Sparse and dense single-vector margins agree in value AND shape."""
    from jax.experimental.sparse import BCOO
    from tpu_sgd.models.regression import LinearRegressionModel

    X, _, _ = small_sparse
    model = LinearRegressionModel(np.ones(X.shape[1], np.float32), 0.5)
    row = _dense(X)[3]
    md = model.predict_margin(row)
    ms = model.predict_margin(BCOO.fromdense(jnp.asarray(row)))
    assert md.shape == ms.shape == (1,)
    np.testing.assert_allclose(md, ms, rtol=1e-6)


def test_pallas_gradient_falls_back_on_sparse(small_sparse):
    """PallasGradient + BCOO routes to the base sparse lowering (the Mosaic
    kernel needs dense rows) instead of crashing inside the kernel."""
    from tpu_sgd.ops.pallas_kernels import PallasGradient

    X, y, _ = small_sparse
    g = PallasGradient(LeastSquaresGradient(), interpret=True)
    w = jnp.ones((X.shape[1],), jnp.float32)
    gs, ls, c = g.batch_sums(X, y, w)
    gd, ld, cd = LeastSquaresGradient().batch_sums(X, y, w)
    np.testing.assert_allclose(gs, gd, rtol=1e-6)
    np.testing.assert_allclose(ls, ld, rtol=1e-6)


def test_sparse_int_features_promote():
    """Integer one-hot BCOO data must not truncate f32 weights (compute
    promotes to >= f32)."""
    from jax.experimental.sparse import BCOO

    onehot = np.zeros((6, 4), np.int32)
    onehot[np.arange(6), np.arange(6) % 4] = 1
    X = BCOO.fromdense(jnp.asarray(onehot))
    y = jnp.zeros((6,), jnp.float32)
    w = jnp.full((4,), 0.5, jnp.float32)
    gs, ls, c = LeastSquaresGradient().batch_sums(X, y, w)
    assert jnp.issubdtype(ls.dtype, jnp.floating)
    assert float(ls) > 0.0  # margins were 0.5, not int-truncated 0


def test_sparse_stepwise_listener_and_checkpoint(tmp_path, small_sparse):
    """The observed path (listener + checkpoint manager) accepts BCOO
    features single-device: per-iteration events fire and a mid-run
    checkpoint resumes to the same trajectory."""
    from tpu_sgd.utils.checkpoint import CheckpointManager
    from tpu_sgd.utils.events import CollectingListener

    X, y, _ = small_sparse
    w0 = jnp.zeros((X.shape[1],))

    listener = CollectingListener()
    opt = (GradientDescent(LeastSquaresGradient(), SquaredL2Updater())
           .set_step_size(0.1).set_num_iterations(8).set_reg_param(0.01)
           .set_seed(5).set_listener(listener))
    w_full, h_full = opt.optimize_with_history((X, y), w0)
    assert len(listener.iterations) == 8
    assert listener.iterations[0].mini_batch_size == X.shape[0]

    # interrupted run saves at iteration 4; a fresh optimizer resumes
    mgr = CheckpointManager(str(tmp_path), keep=5)
    opt_a = (GradientDescent(LeastSquaresGradient(), SquaredL2Updater())
             .set_step_size(0.1).set_num_iterations(4).set_reg_param(0.01)
             .set_seed(5).set_checkpoint(mgr, every=4))
    opt_a.optimize_with_history((X, y), w0)
    opt_b = (GradientDescent(LeastSquaresGradient(), SquaredL2Updater())
             .set_step_size(0.1).set_num_iterations(8).set_reg_param(0.01)
             .set_seed(5).set_checkpoint(mgr, every=4))
    w_res, h_res = opt_b.optimize_with_history((X, y), w0)
    np.testing.assert_allclose(np.asarray(w_res), np.asarray(w_full),
                               rtol=1e-5, atol=1e-6)


def test_config3_shape_trains_undensified():
    """Config-3 scale check (VERDICT r1 #4 'done' criterion): RCV1-shaped
    (d=47,236, ~0.1% nnz) hinge + L1 training in BCOO form.  Dense X here
    would be 100k x 47k f32 = 18.8 GB — far beyond this runner's memory —
    so completing at all proves nothing densified.  Row count is scaled to
    keep CI fast; the FEATURE dimension (what densification chokes on) is
    the real RCV1's."""
    n, d = 20_000, 47_236
    X, y, _ = sparse_data(n, d, nnz_per_row=47, kind="svm", seed=19)
    opt = (
        GradientDescent(HingeGradient(), L1Updater())
        .set_step_size(1.0)
        .set_num_iterations(5)
        .set_reg_param(1e-4)
        .set_mini_batch_fraction(0.3)
    )
    w, hist = opt.optimize_with_history((X, y), jnp.zeros((d,)))
    assert hist.shape[0] == 5
    assert np.isfinite(hist).all()
    assert hist[-1] < hist[0]


def test_rcv1_like_full_width_trains_undensified():
    """The realistic RCV1 stand-in at the REAL 47,236-feature width (Zipf
    feature frequencies, unit-norm tfidf-like rows) trains undensified."""
    from tpu_sgd.utils.mlutils import rcv1_like_data

    X, y, _ = rcv1_like_data(4000, d=47_236, seed=3)
    opt = (
        GradientDescent(HingeGradient(), L1Updater())
        .set_step_size(100.0)
        .set_num_iterations(30)
        .set_reg_param(1e-5)
    )
    w, hist = opt.optimize_with_history(
        (X, jnp.asarray(y)), jnp.zeros((47_236,))
    )
    assert np.isfinite(hist).all()
    assert hist[-1] < hist[0]


def test_take_rows_bcoo_matches_dense_gather(small_sparse):
    from tpu_sgd.ops.sparse import take_rows_bcoo

    X, _, _ = small_sparse
    idx = np.asarray([5, 0, 37, 12, 399])
    got = _dense(take_rows_bcoo(X, idx))
    np.testing.assert_allclose(got, _dense(X)[idx], rtol=1e-6)
    with pytest.raises(ValueError, match="unique"):
        take_rows_bcoo(X, np.asarray([1, 1, 2]))


def test_k_fold_and_split_on_sparse(small_sparse):
    """MLUtils fold utilities serve sparse features like the reference's
    kFold serves sparse RDDs: splits reassemble to the full dataset."""
    from tpu_sgd.utils.mlutils import k_fold, train_test_split

    X, y, _ = small_sparse
    y = np.asarray(y)
    n = X.shape[0]
    folds = list(k_fold(X, y, 4, seed=3))
    assert len(folds) == 4
    total_val = 0
    for (Xtr, ytr), (Xva, yva) in folds:
        assert is_sparse(Xtr) and is_sparse(Xva)
        assert Xtr.shape[0] + Xva.shape[0] == n
        assert Xtr.shape[0] == ytr.shape[0]
        total_val += Xva.shape[0]
        # a fold trains through the ordinary sparse path
    assert total_val == n
    (Xtr, ytr), (Xte, yte) = train_test_split(X, y, 0.25, seed=4)
    assert Xte.shape[0] == round(0.25 * n)
    # gathered rows carry the right contents
    np.testing.assert_allclose(
        _dense(Xtr).sum() + _dense(Xte).sum(), _dense(X).sum(), rtol=1e-4
    )


def test_sparse_stepwise_mesh_listener_matches_fused():
    """Listener/checkpoint (observed) mode now runs sparse over the data
    mesh; its trajectory matches the fused while_loop path exactly."""
    from tpu_sgd.parallel import data_mesh
    from tpu_sgd.utils.events import CollectingListener

    X, y, _ = _uneven_sparse()
    mesh = data_mesh()
    w0 = jnp.zeros((X.shape[1],))

    def mk():
        return (GradientDescent(LeastSquaresGradient(), SquaredL2Updater())
                .set_step_size(0.2).set_num_iterations(10)
                .set_reg_param(0.01).set_mini_batch_fraction(0.5)
                .set_seed(7).set_mesh(mesh))

    listener = CollectingListener()
    w_obs, h_obs = mk().set_listener(listener).optimize_with_history(
        (X, y), w0
    )
    assert len(listener.iterations) == 10
    w_fused, h_fused = mk().optimize_with_history((X, y), w0)
    np.testing.assert_allclose(h_obs, h_fused, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(w_obs), np.asarray(w_fused),
                               rtol=1e-5, atol=1e-6)


def test_multinomial_lbfgs_sparse_over_mesh():
    """Matrix-weight (multinomial) gradient + BCOO + data mesh: the
    quasi-Newton scalar line search path over sharded sparse components
    matches the single-device result."""
    from tpu_sgd.parallel import data_mesh

    X, y, _ = sparse_data(640, 24, nnz_per_row=6, kind="linear", seed=37)
    y3 = jnp.asarray(((np.asarray(y) > -0.5).astype(np.float32)
                      + (np.asarray(y) > 0.5).astype(np.float32)))
    g = MultinomialLogisticGradient(3)  # stateless: shared by both runs
    w0 = jnp.zeros((2 * 24,))
    _, h_m = (LBFGS(g, max_num_iterations=20)
              .set_mesh(data_mesh())
              .optimize_with_history((X, y3), w0))
    _, h_1 = LBFGS(
        g, max_num_iterations=20
    ).optimize_with_history((X, y3), w0)
    assert h_m[-1] < h_m[0]
    np.testing.assert_allclose(h_m[-1], h_1[-1], rtol=1e-3)


def test_labeled_points_with_sparse_vectors_train_undensified():
    """The reference's primary sparse form — LabeledPoint records holding
    SparseVector features — converts to one BCOO matrix and trains through
    the sparse path (previously crashed in to_arrays)."""
    from tpu_sgd.linalg import DenseVector, SparseVector
    from tpu_sgd.models.labeled_point import LabeledPoint, to_arrays

    rng = np.random.default_rng(41)
    d = 30
    pts = []
    dense_rows = np.zeros((300, d), np.float32)
    w_true = rng.normal(size=(d,)).astype(np.float32)
    for i in range(300):
        idx = np.sort(rng.choice(d, size=5, replace=False))
        vals = rng.normal(size=5).astype(np.float32)
        dense_rows[i, idx] = vals
        label = float(dense_rows[i] @ w_true > 0)
        pts.append(LabeledPoint(label, SparseVector(d, idx, vals)))
    X, y = to_arrays(pts)
    assert is_sparse(X) and X.shape == (300, d)
    np.testing.assert_allclose(_dense(X), dense_rows, rtol=1e-6)
    model = SVMWithSGD.train(pts, num_iterations=40, reg_param=1e-4)
    acc = float(np.mean(np.asarray(model.predict(X)) == y))
    assert acc > 0.85
    # DenseVector records still take the dense path
    dpts = [LabeledPoint(float(l), DenseVector(r))
            for l, r in zip(y, dense_rows)]
    Xd, yd = to_arrays(dpts)
    assert isinstance(Xd, np.ndarray)
    np.testing.assert_allclose(Xd, dense_rows)
    # a MIXED collection (reference RDDs mix freely) goes sparse, dense
    # rows contributing their nonzeros
    mixed = pts[:150] + dpts[150:]
    Xm, ym = to_arrays(mixed)
    assert is_sparse(Xm)
    np.testing.assert_allclose(_dense(Xm), dense_rows, rtol=1e-6)


def test_streaming_predict_on_sparse_batches():
    """predict_on / predict_on_values consume BCOO feature batches."""
    from tpu_sgd.models.streaming import StreamingLinearRegressionWithSGD

    X, y, _ = _uneven_sparse()
    alg = StreamingLinearRegressionWithSGD(step_size=0.2, num_iterations=10)
    alg.set_initial_weights(np.zeros(X.shape[1]))
    alg.train_on_batch(X, np.asarray(y))
    from tpu_sgd.ops.sparse import take_rows_bcoo

    batches = [take_rows_bcoo(X, np.arange(0, 100)),
               take_rows_bcoo(X, np.arange(100, 250))]
    preds = list(alg.predict_on(iter(batches)))
    assert [p.shape[0] for p in preds] == [100, 150]
    keyed = list(alg.predict_on_values([("a", batches[0])]))
    assert keyed[0][0] == "a" and keyed[0][1].shape == (100,)
    # sparse and dense batch predictions agree
    np.testing.assert_allclose(
        preds[0], np.asarray(alg.latest_model().predict(_dense(batches[0]))),
        rtol=1e-5,
    )


def test_save_libsvm_from_bcoo_round_trips(tmp_path, small_sparse):
    """saveAsLibSVMFile parity on sparse input: a BCOO saves without
    densifying and round-trips through the sparse loader."""
    from tpu_sgd.utils.mlutils import save_as_libsvm_file

    X, y, _ = small_sparse
    path = str(tmp_path / "sp.libsvm")
    save_as_libsvm_file(path, X, np.asarray(y))
    X2, y2 = load_libsvm_file_bcoo(path, num_features=X.shape[1])
    np.testing.assert_allclose(_dense(X2), _dense(X), rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(y2), np.asarray(y), rtol=1e-5)


def test_save_libsvm_coalesces_duplicates_and_zeros(tmp_path):
    """Duplicate BCOO entries sum (BCOO semantics) and stored zeros drop
    in the LIBSVM writer, so the text round-trips losslessly."""
    from jax.experimental.sparse import BCOO
    from tpu_sgd.utils.mlutils import load_libsvm_file, save_as_libsvm_file

    idx = np.asarray([[0, 1], [0, 1], [0, 3], [1, 2]], np.int32)
    vals = jnp.asarray([1.5, 2.5, 0.0, -1.0], jnp.float32)
    X = BCOO((vals, jnp.asarray(idx)), shape=(2, 5))
    path = str(tmp_path / "dups.libsvm")
    save_as_libsvm_file(path, X, np.asarray([1.0, 0.0], np.float32))
    text = open(path).read()
    assert "2:4" in text  # 1.5 + 2.5 summed at column index 1 (1-based 2)
    assert "4:0" not in text  # stored zero dropped
    Xd, yd = load_libsvm_file(path, num_features=5)
    np.testing.assert_allclose(Xd, np.asarray(X.todense()), rtol=1e-5)


def test_sparse_vector_rejects_out_of_range_indices():
    from tpu_sgd.linalg import SparseVector

    with pytest.raises(ValueError, match="indices must be in"):
        SparseVector(3, [-1], [9.0])
    with pytest.raises(ValueError, match="indices must be in"):
        SparseVector(3, [5], [9.0])


def test_take_rows_bcoo_rejects_out_of_range_indices():
    """Negative indices would silently alias tail rows through the
    position scatter — a split training on the wrong rows."""
    from tpu_sgd.ops.sparse import take_rows_bcoo

    X, y, _ = sparse_data(32, 8, nnz_per_row=3, seed=3)
    with pytest.raises(IndexError, match="row indices"):
        take_rows_bcoo(X, np.array([-1, 0]))
    with pytest.raises(IndexError, match="row indices"):
        take_rows_bcoo(X, np.array([0, 32]))


def test_take_rows_bcoo_inherits_uniqueness_flag():
    """A duplicate-coordinate input keeps its duplicates in the selected
    subset; the output must not falsely promise unique indices (scatter
    in unique mode may drop one duplicate's value)."""
    from jax.experimental.sparse import BCOO

    from tpu_sgd.ops.sparse import take_rows_bcoo

    dup = BCOO(
        (jnp.asarray([1.0, 2.0]), jnp.asarray([[0, 1], [0, 1]])),
        shape=(2, 4), unique_indices=False,
    )
    out = take_rows_bcoo(dup, np.array([0]))
    assert out.unique_indices is False
    assert float(out.todense()[0, 1]) == 3.0  # duplicates still SUM
    # a genuinely-unique input keeps the flag
    X, _, _ = sparse_data(16, 8, nnz_per_row=2, seed=0)
    assert take_rows_bcoo(X, np.arange(4)).unique_indices is True


def test_csr_to_bcoo_rejects_out_of_range_feature(tmp_path):
    """The dense loader raises for a feature index beyond num_features;
    the sparse path must not silently drop the entry instead."""
    p = tmp_path / "oob.txt"
    p.write_text("1 1:0.5 7:1.5\n0 2:2.0\n")
    from tpu_sgd import load_libsvm_file_bcoo

    X, y = load_libsvm_file_bcoo(str(p))  # self-sized: fine
    assert X.shape == (2, 7)
    with pytest.raises(IndexError, match="feature index"):
        load_libsvm_file_bcoo(str(p), num_features=5)
