"""Pallas fused gradient kernel vs the XLA reference path (interpret mode
on CPU; the same kernel compiles to Mosaic on TPU)."""

import numpy as np
import pytest

from tpu_sgd.ops.gradients import (
    HingeGradient,
    LeastSquaresGradient,
    LogisticGradient,
)
from tpu_sgd.ops.pallas_kernels import PallasGradient, fused_gradient_sums


GRADS = [LeastSquaresGradient(), LogisticGradient(), HingeGradient()]


def _data(n=300, d=24, seed=0, classify=False):
    r = np.random.default_rng(seed)
    X = r.normal(size=(n, d)).astype(np.float32)
    if classify:
        y = (r.uniform(size=(n,)) < 0.5).astype(np.float32)
    else:
        y = r.normal(size=(n,)).astype(np.float32)
    w = r.normal(size=(d,)).astype(np.float32)
    return X, y, w


@pytest.mark.parametrize("g", GRADS, ids=lambda g: type(g).__name__)
def test_fused_matches_xla_path(g):
    X, y, w = _data(classify=not isinstance(g, LeastSquaresGradient))
    gs_ref, ls_ref, c_ref = g.batch_sums(X, y, w)
    gs, ls, c = fused_gradient_sums(g.pointwise, X, y, w, tile_m=128,
                                    interpret=True)
    np.testing.assert_allclose(np.asarray(gs), np.asarray(gs_ref), rtol=2e-4,
                               atol=2e-3)
    np.testing.assert_allclose(float(ls), float(ls_ref), rtol=2e-4)
    assert float(c) == float(c_ref)


def test_fused_with_mask_and_ragged_rows():
    """n not a tile multiple AND a sampling mask: padding must be invisible."""
    g = LeastSquaresGradient()
    X, y, w = _data(n=333, d=16, seed=1)
    mask = np.random.default_rng(2).uniform(size=(333,)) < 0.3
    gs_ref, ls_ref, c_ref = g.batch_sums(X, y, w, mask)
    gs, ls, c = fused_gradient_sums(g.pointwise, X, y, w, mask, tile_m=128,
                                    interpret=True)
    np.testing.assert_allclose(np.asarray(gs), np.asarray(gs_ref), rtol=2e-4,
                               atol=2e-3)
    np.testing.assert_allclose(float(ls), float(ls_ref), rtol=2e-4)
    assert float(c) == float(c_ref) == mask.sum()


def test_pallas_gradient_drop_in_optimizer():
    """PallasGradient behind the unchanged optimizer boundary converges to
    the same solution as the XLA path."""
    from tpu_sgd.optimize.gradient_descent import GradientDescent
    from tpu_sgd.ops.updaters import SimpleUpdater
    from tpu_sgd.utils.mlutils import linear_data

    X, y, w_true = linear_data(1024, 16, eps=0.01, seed=3)
    w0 = np.zeros(16, np.float32)

    def fit(gradient):
        return np.asarray(
            GradientDescent(gradient, SimpleUpdater())
            .set_step_size(0.5)
            .set_num_iterations(80)
            .set_convergence_tol(0.0)
            .optimize((X, y), w0)
        )

    w_xla = fit(LeastSquaresGradient())
    w_pal = fit(PallasGradient(LeastSquaresGradient(), tile_m=256,
                               interpret=True))
    np.testing.assert_allclose(w_pal, w_xla, rtol=1e-3, atol=1e-4)
    np.testing.assert_allclose(w_pal, w_true, atol=0.05)


def test_pallas_gradient_falls_back_off_tpu():
    """Default (interpret=None) on CPU: silently uses the XLA path."""
    g = PallasGradient(LogisticGradient())
    X, y, w = _data(classify=True)
    gs, ls, c = g.batch_sums(X, y, w)
    gs_ref, ls_ref, c_ref = LogisticGradient().batch_sums(X, y, w)
    np.testing.assert_allclose(np.asarray(gs), np.asarray(gs_ref), rtol=1e-5)


def test_pallas_gradient_weight_dim_delegates():
    assert PallasGradient(LeastSquaresGradient()).weight_dim(7) == 7


def test_pallas_gradient_under_dp_mesh():
    """The fused kernel composes with shard_map data parallelism."""
    from tpu_sgd.optimize.gradient_descent import GradientDescent
    from tpu_sgd.ops.updaters import SimpleUpdater
    from tpu_sgd.parallel.mesh import data_mesh
    from tpu_sgd.utils.mlutils import linear_data

    X, y, w_true = linear_data(1024, 16, eps=0.01, seed=5)
    w = (
        GradientDescent(
            PallasGradient(LeastSquaresGradient(), tile_m=64, interpret=True),
            SimpleUpdater(),
        )
        .set_step_size(0.5)
        .set_num_iterations(60)
        .set_convergence_tol(0.0)
        .set_mesh(data_mesh())
        .optimize((X, y), np.zeros(16, np.float32))
    )
    np.testing.assert_allclose(np.asarray(w), w_true, atol=0.05)


def test_fused_bf16_inputs():
    import jax.numpy as jnp

    g = LeastSquaresGradient()
    X, y, w = _data(n=256, d=32, seed=4)
    gs, ls, c = fused_gradient_sums(
        g.pointwise, jnp.asarray(X, jnp.bfloat16), y, w, tile_m=128,
        interpret=True
    )
    gs_ref, ls_ref, c_ref = g.batch_sums(X, y, w)
    assert gs.dtype == jnp.float32  # f32 accumulation
    np.testing.assert_allclose(np.asarray(gs), np.asarray(gs_ref), rtol=0.05,
                               atol=0.5)


def test_window_sums_matches_manual_slice():
    """Zero-copy offset kernel == batch_sums on the same materialized rows."""
    import jax.numpy as jnp

    from tpu_sgd.ops.pallas_kernels import fused_window_sums

    g = LeastSquaresGradient()
    X, y, w = _data(n=512, d=24, seed=7)
    start_tile, num_tiles, tile = 2, 3, 64
    gs, ls, c = fused_window_sums(
        g.pointwise, X, y, w, jnp.asarray(start_tile), num_tiles,
        tile_m=tile, interpret=True,
    )
    lo, hi = start_tile * tile, (start_tile + num_tiles) * tile
    gs_ref, ls_ref, c_ref = g.batch_sums(X[lo:hi], y[lo:hi], w)
    np.testing.assert_allclose(np.asarray(gs), np.asarray(gs_ref), rtol=2e-4,
                               atol=2e-3)
    np.testing.assert_allclose(float(ls), float(ls_ref), rtol=2e-4)
    assert float(c) == float(c_ref) == num_tiles * tile


def test_pallas_window_sums_drop_in():
    """PallasGradient.window_sums clamps the start and matches the base
    gradient's dynamic-slice path on tile-aligned starts."""
    import jax.numpy as jnp

    base = LeastSquaresGradient()
    g = PallasGradient(base, tile_m=64, interpret=True)
    X, y, w = _data(n=640, d=16, seed=8)
    m = 128  # two tiles
    for start in (0, 64, 576):  # 576 clamps to 512 so the window fits
        gs, ls, c = g.window_sums(X, y, w, jnp.asarray(start), m)
        eff = min(start, 640 - m)
        gs_ref, ls_ref, c_ref = base.batch_sums(
            X[eff:eff + m], y[eff:eff + m], w
        )
        np.testing.assert_allclose(np.asarray(gs), np.asarray(gs_ref),
                                   rtol=2e-4, atol=2e-3)
        assert float(c) == m


def test_pallas_window_sums_fallback_unaligned():
    """Non-tile-multiple datasets fall back to the base dynamic-slice path."""
    import jax.numpy as jnp

    base = LeastSquaresGradient()
    g = PallasGradient(base, tile_m=64, interpret=True)
    X, y, w = _data(n=333, d=16, seed=9)
    gs, ls, c = g.window_sums(X, y, w, jnp.asarray(10), 100)
    gs_ref, ls_ref, c_ref = base.batch_sums(X[10:110], y[10:110], w)
    np.testing.assert_allclose(np.asarray(gs), np.asarray(gs_ref), rtol=2e-4,
                               atol=2e-3)


def test_pallas_window_sums_subtile_remainder():
    """m not a tile multiple: kernel bulk + base-path remainder == exactly m
    rows, matching the pure dynamic-slice path."""
    import jax.numpy as jnp

    base = LeastSquaresGradient()
    g = PallasGradient(base, tile_m=64, interpret=True)
    X, y, w = _data(n=640, d=16, seed=10)
    m = 150  # 2 tiles + 22-row remainder
    gs, ls, c = g.window_sums(X, y, w, jnp.asarray(128), m)
    gs_ref, ls_ref, c_ref = base.batch_sums(X[128:128 + m], y[128:128 + m], w)
    np.testing.assert_allclose(np.asarray(gs), np.asarray(gs_ref), rtol=2e-4,
                               atol=2e-3)
    np.testing.assert_allclose(float(ls), float(ls_ref), rtol=2e-4)
    assert float(c) == m


def test_vmem_guard_rejects_oversized_tile():
    """Tiles whose double-buffered footprint cannot compile raise an
    actionable error instead of a Mosaic scoped-VMEM OOM (seen on hardware
    at tile 8192 x d=1000 bf16 = 40 MB vs the 16 MB budget)."""
    import jax.numpy as jnp

    from tpu_sgd.ops.pallas_kernels import fused_window_sums

    n, d = 16384, 1000
    X = jnp.zeros((n, d), jnp.bfloat16)
    y = jnp.zeros((n,), jnp.float32)
    w = jnp.zeros((d,), jnp.float32)
    g = LeastSquaresGradient()
    with pytest.raises(ValueError, match="VMEM"):
        fused_window_sums(g.pointwise, X, y, w, 0, 2, tile_m=8192)


def test_vpu_window_kernel_matches_base():
    """The VPU-reduction window kernel (round-3 experiment) computes the
    same sums as the MXU variant and the base path, for every pointwise
    gradient rule."""
    import jax.numpy as jnp

    from tpu_sgd.ops.gradients import (
        HingeGradient,
        LeastSquaresGradient,
        LogisticGradient,
    )
    from tpu_sgd.ops.pallas_kernels import (
        fused_window_sums,
        fused_window_sums_vpu,
    )

    X, y, w = _data(n=512, d=24, seed=11)
    start_tile, num_tiles, tile = 1, 4, 64
    lo, hi = start_tile * tile, (start_tile + num_tiles) * tile
    for g in (LeastSquaresGradient(), LogisticGradient(), HingeGradient()):
        gs_v, ls_v, c_v = fused_window_sums_vpu(
            g.pointwise, X, y, w, jnp.asarray(start_tile), num_tiles,
            tile_m=tile, interpret=True,
        )
        gs_m, ls_m, c_m = fused_window_sums(
            g.pointwise, X, y, w, jnp.asarray(start_tile), num_tiles,
            tile_m=tile, interpret=True,
        )
        gs_ref, ls_ref, c_ref = g.batch_sums(X[lo:hi], y[lo:hi], w)
        np.testing.assert_allclose(np.asarray(gs_v), np.asarray(gs_ref),
                                   rtol=2e-4, atol=2e-3)
        np.testing.assert_allclose(np.asarray(gs_v), np.asarray(gs_m),
                                   rtol=2e-4, atol=2e-3)
        np.testing.assert_allclose(float(ls_v), float(ls_ref), rtol=2e-4)
        assert float(c_v) == float(c_ref) == num_tiles * tile


def test_pallas_gradient_vpu_window_kernel_selection():
    """window_kernel='vpu' routes window_sums through the VPU variant with
    identical results (interpret mode); bad names raise."""
    import jax.numpy as jnp

    from tpu_sgd.ops.gradients import LeastSquaresGradient
    from tpu_sgd.ops.pallas_kernels import PallasGradient

    X, y, w = _data(n=512, d=24, seed=13)
    start, m, tile = 64, 256, 64
    base = LeastSquaresGradient()
    g_mxu = PallasGradient(base, tile_m=tile, interpret=True)
    g_vpu = PallasGradient(base, tile_m=tile, interpret=True,
                           window_kernel="vpu")
    # prove the flag actually routes (the two variants agree numerically,
    # so result comparison alone cannot falsify the selection)
    import tpu_sgd.ops.pallas_kernels as PK

    calls = []
    real_vpu = PK.fused_window_sums_vpu
    PK.fused_window_sums_vpu = (
        lambda *a, **k: (calls.append("vpu"), real_vpu(*a, **k))[1]
    )
    try:
        out_m = g_mxu.window_sums(X, y, w, jnp.asarray(start), m)
        assert calls == []
        out_v = g_vpu.window_sums(X, y, w, jnp.asarray(start), m)
        assert calls == ["vpu"]
    finally:
        PK.fused_window_sums_vpu = real_vpu
    np.testing.assert_allclose(np.asarray(out_v[0]), np.asarray(out_m[0]),
                               rtol=2e-4, atol=2e-3)
    np.testing.assert_allclose(float(out_v[1]), float(out_m[1]), rtol=2e-4)
    assert float(out_v[2]) == float(out_m[2])
    with pytest.raises(ValueError, match="window_kernel"):
        PallasGradient(base, window_kernel="gpu")
