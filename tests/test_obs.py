"""Unified observability layer tests (ISSUE 8): span tracing, runtime
counters, and the trace/SLO report pipeline.

The two load-bearing contracts pinned here:

* DISABLED is a measured no-op — one module-global load and a falsy
  branch per hook, the failpoints discipline (`span()` returns the one
  shared singleton, `inc()` bumps nothing, zero runtime patches
  installed).
* ENABLED adds ZERO dispatches, compiles, or host syncs on the warmed
  superstep and resident hot paths (the acceptance criterion), measured
  both by the analysis twins (disabled baseline) and by the promoted
  counters themselves (enabled run) — the numbers must agree exactly.
"""

import json
import threading
import time

import numpy as np
import pytest

from tpu_sgd import obs
from tpu_sgd.obs import counters as obs_counters
from tpu_sgd.obs import report as obs_report
from tpu_sgd.obs import spans as obs_spans
from tpu_sgd.obs.spans import disable_tracing, enable_tracing
from tpu_sgd.utils.events import JsonLinesEventLog


class ListSink:
    """In-memory sink on the ``emit(kind, payload)`` contract."""

    def __init__(self, raising: bool = False):
        self.records = []
        self.raising = raising

    def emit(self, kind, payload):
        if self.raising:
            raise RuntimeError("sink intentionally broken")
        self.records.append((kind, dict(payload)))

    def spans(self, name=None):
        return [p for k, p in self.records if k == "trace_span"
                and (name is None or p["name"] == name)]

    def events(self, name=None):
        return [p for k, p in self.records if k == "trace_event"
                and (name is None or p["name"] == name)]


@pytest.fixture(autouse=True)
def _obs_off():
    """Every test starts and ends with the layer fully disabled."""
    obs.disable()
    obs_counters.reset()
    yield
    obs.disable()
    obs_counters.reset()


# -- disabled-mode cost contract --------------------------------------------

def test_disabled_span_is_the_shared_noop_singleton():
    """`span(...)` disabled returns ONE shared object — no allocation,
    no formatting; `event`/`inc` return before touching anything."""
    s1 = obs_spans.span("train.superstep", i0=1)
    s2 = obs_spans.span("serve.batch")
    assert s1 is s2  # the singleton, not a fresh object per call
    with s1 as s:
        assert s.set(anything=1) is s  # set() is a no-op that chains
    obs_spans.event("reliability.retry", attempt=1)  # must not raise
    obs_counters.inc("serve.reject")
    assert obs_counters.snapshot() == {}


def test_disabled_hooks_are_measured_noops():
    """The failpoints discipline, measured: sub-microsecond per call on
    this noisy 2-core host (bound ~20x the measured mean for CI
    headroom).  `span()` pays one kwargs dict + global load + branch;
    `inc()` pays the global load + branch."""
    n = 200_000
    t0 = time.perf_counter()
    for _ in range(n):
        obs_spans.span("train.step")
    per_span = (time.perf_counter() - t0) / n
    t0 = time.perf_counter()
    for _ in range(n):
        obs_counters.inc("train.io_callback")
    per_inc = (time.perf_counter() - t0) / n
    assert per_span < 2e-6, f"disabled span costs {per_span*1e9:.0f}ns"
    assert per_inc < 2e-6, f"disabled inc costs {per_inc*1e9:.0f}ns"


def test_disabled_installs_zero_runtime_patches():
    """A production process that never opts in runs the STOCK runtime:
    enabling installs the patches, disabling restores the originals."""
    import jax

    orig_put = jax.device_put
    obs_counters.enable()
    try:
        assert jax.device_put is not orig_put
    finally:
        obs_counters.disable()
    assert jax.device_put is orig_put


# -- span mechanics ----------------------------------------------------------

def test_span_nesting_and_attrs():
    sink = ListSink()
    enable_tracing(sink)
    try:
        with obs_spans.span("train.superstep", i0=5) as outer:
            with obs_spans.span("train.replay"):
                pass
            outer.set(steps=4)
    finally:
        disable_tracing()
    inner, = sink.spans("train.replay")
    outer, = sink.spans("train.superstep")
    assert inner["parent_id"] == outer["span_id"]  # child closed first
    assert outer["parent_id"] == 0
    assert outer["i0"] == 5 and outer["steps"] == 4
    assert outer["dur_s"] >= inner["dur_s"] >= 0.0
    assert outer["error"] is None


def test_span_records_error_class_and_propagates():
    sink = ListSink()
    enable_tracing(sink)
    try:
        with pytest.raises(ValueError):
            with obs_spans.span("checkpoint.save"):
                raise ValueError("boom")
    finally:
        disable_tracing()
    rec, = sink.spans("checkpoint.save")
    assert rec["error"] == "ValueError"


def test_spans_are_thread_aware():
    """Each thread keeps its own stack: a worker's span must not parent
    onto whatever the main thread has open (the prefetch-worker /
    flush-thread contract), and the subsystem tag is per-thread too."""
    sink = ListSink()
    enable_tracing(sink)
    tags = {}
    try:
        def worker():
            with obs_spans.span("ingest.produce"):
                tags["worker"] = obs_spans.current_subsystem()
                time.sleep(0.005)

        with obs_spans.span("train.superstep"):
            t = threading.Thread(target=worker, name="w0")
            t.start()
            tags["main"] = obs_spans.current_subsystem()
            t.join()
    finally:
        disable_tracing()
    produce, = sink.spans("ingest.produce")
    assert produce["parent_id"] == 0  # NOT nested under train.superstep
    assert produce["thread"] == "w0"
    assert tags == {"worker": "ingest", "main": "train"}
    assert obs_spans.current_subsystem() == "untagged"


def test_raising_sink_never_kills_the_hot_path():
    enable_tracing(ListSink(raising=True))
    try:
        with obs_spans.span("train.step", i=1):
            pass  # span exit swallows the sink error
        obs_spans.event("reliability.retry")  # ditto
    finally:
        disable_tracing()


# -- counters ----------------------------------------------------------------

def test_counters_inc_snapshot_deltas_reset():
    obs_counters.enable()
    try:
        obs_counters.inc("serve.reject")
        with obs_counters.deltas() as d:
            obs_counters.inc("serve.reject", 2)
            obs_counters.inc("ingest.wire", nbytes=128)
        got = d.get()
        assert got == {"serve.reject": {"n": 2, "bytes": 0},
                       "ingest.wire": {"n": 1, "bytes": 128}}
        snap = obs_counters.snapshot()
        assert snap["serve.reject"]["n"] == 3
    finally:
        obs_counters.disable()
    # values survive disable (scrape-after-stop); reset clears
    assert obs_counters.snapshot()["serve.reject"]["n"] == 3
    obs_counters.reset()
    assert obs_counters.snapshot() == {}


def test_counters_attribute_runtime_events_to_the_open_subsystem():
    """Dispatches/compiles/syncs/h2d land under the span-derived tag of
    the thread that caused them — the straggler-attribution surface.
    Tagging rides the span stack, so tracing must be on too (the facade
    enables both)."""
    import jax
    import jax.numpy as jnp

    f = jax.jit(lambda x: x * 2 + 1)
    x = jnp.ones((8, 8))
    f(x).block_until_ready()  # warm BEFORE enabling
    enable_tracing(ListSink())
    obs_counters.enable()
    try:
        with obs_spans.span("train.superstep"):
            y = f(x)
            v = float(y[0, 0])  # eager slice + scalar fetch
        with obs_spans.span("ingest.produce"):
            jax.device_put(np.ones((4, 4), np.float32))
        snap = obs_counters.snapshot()
    finally:
        obs_counters.disable()
    assert v == 3.0
    assert snap["train.dispatch"]["n"] >= 1     # the warmed f(x) launch
    assert snap["train.host_sync"]["n"] >= 1    # the float() fetch
    assert snap["train.host_sync"]["bytes"] >= 4
    assert snap["ingest.h2d"]["n"] == 1
    assert snap["ingest.h2d"]["bytes"] == 64
    # no compile of the WARMED function; the eager slice may compile
    assert snap.get("untagged.dispatch", {"n": 0})["n"] == 0


def test_counters_enable_disable_roundtrip_under_twins():
    """The analysis twins nest over the promoted patches (both
    patch/restore LIFO) and agree with them on a warmed function."""
    import jax
    import jax.numpy as jnp

    from tpu_sgd.analysis.runtime import count_dispatches

    f = jax.jit(lambda x: x + 1)
    x = jnp.zeros((4,))
    f(x).block_until_ready()
    obs_counters.enable()
    try:
        obs_counters.reset()
        with count_dispatches() as twin:
            f(x).block_until_ready()
        snap = obs_counters.snapshot()
    finally:
        obs_counters.disable()
    assert twin["n"] == 1
    assert snap["untagged.dispatch"]["n"] == 1  # no span open: untagged


# -- facade ------------------------------------------------------------------

def test_facade_owns_trace_log_and_flushes_counters(tmp_path):
    path = str(tmp_path / "trace.jsonl")
    obs.enable(path)
    with obs.span("train.superstep", i0=1):
        obs.inc("train.io_callback")
    obs.flush_counters()
    obs.disable()  # flushes once more + closes the owned log
    records = JsonLinesEventLog.read(path)
    kinds = [r["kind"] for r in records]
    assert "trace_span" in kinds
    assert kinds.count("metric_counters") == 2
    last = [r for r in records if r["kind"] == "metric_counters"][-1]
    assert last["counters"]["train.io_callback"]["n"] == 1


def test_facade_shares_a_listener_event_log(tmp_path):
    """Traces interleave with listener events on ONE JSONL stream — the
    chaos-soak spelling (caller keeps ownership)."""
    from tpu_sgd.utils.events import IterationEvent

    path = str(tmp_path / "shared.jsonl")
    log = JsonLinesEventLog(path)
    obs.enable(log, with_counters=False)
    log.on_iteration(IterationEvent(1, 0.5, 0.1, 32, 0.01))
    with obs.span("train.step", i=1):
        pass
    obs.disable()  # caller-owned: must NOT close it
    log.on_iteration(IterationEvent(2, 0.4, 0.1, 32, 0.01))
    log.close()
    kinds = [r["kind"] for r in JsonLinesEventLog.read(path)]
    assert kinds == ["iteration", "trace_span", "iteration"]


def test_reenable_with_new_path_closes_previous_owned_log(tmp_path):
    """A second enable() must not leak the first's file handle: the
    previously owned log is closed (tail flushed) when the sink swaps."""
    a = str(tmp_path / "a.jsonl")
    b = str(tmp_path / "b.jsonl")
    obs.enable(a)
    first = obs._OWNED_LOG
    with obs.span("train.step", i=1):
        pass
    obs.enable(b)  # swap without an intervening disable()
    assert first._f.closed  # the leak the review caught
    with obs.span("train.step", i=2):
        pass
    obs.disable()
    ka = [r for r in JsonLinesEventLog.read(a) if r["kind"] == "trace_span"]
    kb = [r for r in JsonLinesEventLog.read(b) if r["kind"] == "trace_span"]
    assert [r["i"] for r in ka] == [1]
    assert [r["i"] for r in kb] == [2]


# -- the acceptance pin: enabled obs adds ZERO runtime events ---------------

def _data(rng, n=400, d=6):
    X = rng.normal(size=(n, d)).astype(np.float32)
    w = rng.uniform(-1, 1, d).astype(np.float32)
    y = (X @ w).astype(np.float32)
    return X, y


def _opt(iters=24, k=4, c=0):
    from tpu_sgd.optimize.gradient_descent import GradientDescent
    from tpu_sgd.utils.events import SGDListener

    o = (GradientDescent().set_num_iterations(iters).set_step_size(0.1)
         .set_mini_batch_fraction(0.5).set_sampling("sliced")
         .set_convergence_tol(0.0).set_seed(7).set_superstep(k)
         .set_listener(SGDListener()))
    if c:
        o.set_residency(c)
    return o


def test_enabled_obs_superstep_driver_zero_added_runtime_events(rng):
    """ISSUE 8 acceptance: tracing+counters ENABLED, the warmed
    superstep driver shows ZERO additional compiles, dispatches, or
    host syncs versus disabled — the disabled baseline measured by the
    analysis twins, the enabled run measured by the promoted counters
    themselves, and the numbers must agree exactly."""
    from tpu_sgd.analysis.runtime import count_dispatches, count_host_syncs

    X, y = _data(rng)
    w0 = np.zeros(6, np.float32)
    o = _opt()
    o.optimize_with_history((X, y), w0)  # warm every program
    with count_host_syncs() as sc, count_dispatches() as dc:
        o.optimize_with_history((X, y), w0)
    base_dispatch, base_sync = dc["n"], sc["n"]

    sink = ListSink()
    obs.enable(sink)  # tracing + counters + TIME-SERIES, the full config
    try:
        obs_counters.reset()
        o.optimize_with_history((X, y), w0)
        snap = obs_counters.snapshot()
        wins = obs.windows_snapshot()
    finally:
        obs.disable()

    def total(kind):
        return sum(v["n"] for k, v in snap.items()
                   if k.endswith("." + kind))

    assert total("dispatch") == base_dispatch
    assert total("host_sync") == base_sync
    assert total("compile") == 0  # warmed: nothing recompiles
    # and the trace really observed the run: one span per superstep
    assert len(sink.spans("train.superstep")) == 24 // 4
    assert all(s["i0"] % 4 == 1 for s in sink.spans("train.superstep"))
    # ISSUE 13 re-pin: the counts above were measured with the windowed
    # time-series ON (obs.enable default), and it really recorded — the
    # span durations, the per-step loss scalars, and the counter series
    # all landed in the live window ring at ZERO added runtime events
    series = {name for w in wins for name in w["series"]}
    assert "train.superstep" in series
    assert "train.loss" in series
    assert "train.dispatch" in series


def test_enabled_obs_compressed_wire_zero_added_runtime_events(rng):
    """ISSUE 9 satellite: the warmed COMPRESSED host-streamed path
    (top-k + error-feedback wire, fused K) shows ZERO additional
    dispatches or host syncs with tracing+counters enabled — same
    methodology as the superstep pin above — and the wire counters tag
    the feed's bytes by format."""
    from tpu_sgd.analysis.runtime import count_dispatches, count_host_syncs
    from tpu_sgd.optimize.gradient_descent import GradientDescent

    X, y = _data(rng)
    w0 = np.zeros(6, np.float32)

    def mk():
        return (GradientDescent().set_num_iterations(16)
                .set_step_size(0.1).set_mini_batch_fraction(0.5)
                .set_convergence_tol(0.0).set_seed(7)
                .set_host_streaming(True).set_superstep(4)
                .set_ingest_options(wire_compress="topk:0.5"))

    mk().optimize_with_history((X, y), w0)  # warm the fused program
    # disabled compile baseline via the same jax.monitoring funnel the
    # counters use (bench_obs.py methodology): the streamed driver
    # re-jits its per-run fused wrapper, a pre-existing warmed cost the
    # enabled delta must not blame on obs
    from jax._src import monitoring as _monitoring

    base_compiles = [0]

    def _listener(ev_name, dur, **kw):
        if ev_name.endswith("backend_compile_duration"):
            base_compiles[0] += 1

    _monitoring.register_event_duration_secs_listener(_listener)
    try:
        with count_host_syncs() as sc, count_dispatches() as dc:
            mk().optimize_with_history((X, y), w0)
    finally:
        _monitoring._unregister_event_duration_listener_by_callback(
            _listener)
    base_dispatch, base_sync = dc["n"], sc["n"]

    sink = ListSink()
    obs.enable(sink)
    try:
        obs_counters.reset()
        mk().optimize_with_history((X, y), w0)
        snap = obs_counters.snapshot()
    finally:
        obs.disable()
        obs_counters.reset()

    def total(kind):
        return sum(v["n"] for k, v in snap.items()
                   if k.endswith("." + kind))

    assert total("dispatch") == base_dispatch
    assert total("host_sync") == base_sync
    # enabled-minus-disabled compile delta is ZERO (the absolute count
    # is the streamed driver's pre-existing per-run re-jit, measured by
    # the same funnel disabled)
    assert total("compile") == base_compiles[0]
    # the feed's wire bytes are format-tagged (dense-f32 batches here;
    # the compressed segments ride inside the traced program)
    from tpu_sgd.obs.counters import wire_ratios

    ratios = wire_ratios(snap)
    dense_wire = [r for n_, r in ratios.items()
                  if n_.endswith(".dense-f32")]
    assert dense_wire and dense_wire[0]["n"] == 16 // 4
    assert len(sink.spans("train.superstep")) == 16 // 4


def test_enabled_obs_resident_driver_pins_one_dispatch_windows_syncs(rng):
    """The resident acceptance pin via the promoted counters: a warmed
    whole-run dispatch is exactly ONE train.dispatch, host syncs are
    exactly windows+3 scalars (the same pin the analysis twin holds
    with tracing OFF — tests/test_resident.py), compiles are zero, and
    every one lands under the `train` tag."""
    import jax.numpy as jnp

    from tpu_sgd.optimize.resident_driver import ResidentBookkeeper

    X, y = _data(rng)
    w0 = np.zeros(6, np.float32)
    iters, k, c = 64, 4, 2
    o = _opt(iters=iters, k=k, c=c)
    o.optimize_with_history((X, y), w0)  # warm the one compiled program
    key = ("resident", o.gradient, o.updater, o.config, k, c)
    loop = o._run_cache[key]
    windows = iters // (k * c)

    sink = ListSink()
    obs.enable(sink)
    try:
        obs_counters.reset()
        hooks = ResidentBookkeeper(o.config, k, c, losses=[], reg_val=0.0,
                                   start_iter=1)
        loop.run(jnp.asarray(w0), 0.0, 1,
                 (jnp.asarray(X), jnp.asarray(y)), hooks)
        snap = obs_counters.snapshot()
    finally:
        obs.disable()
    assert snap["train.dispatch"]["n"] == 1          # the whole-run program
    assert snap["train.host_sync"]["n"] == windows + 3
    assert sum(v["n"] for n, v in snap.items()
               if n.endswith(".compile")) == 0
    assert snap["train.io_callback"]["n"] == windows
    # every window emitted its span on the callback thread, i0 attrs in
    # cadence order
    wins = sink.spans("train.window")
    assert [w["i0"] for w in wins] == [1 + i * k * c for i in range(windows)]
    assert len(sink.spans("train.resident_dispatch")) == 1


# -- serving: the satellite fields -------------------------------------------

def test_serve_batch_event_carries_enqueue_depth_and_deadline_slack(tmp_path):
    """ISSUE 8 satellite: the batcher records queue depth at enqueue and
    deadline slack at flush; both ride the serve_batch JSONL record and
    old positional constructors keep working."""
    from tpu_sgd.serve.batcher import MicroBatcher
    from tpu_sgd.serve.metrics import ServingMetrics
    from tpu_sgd.utils.events import ServeBatchEvent

    # backward compat: the pre-ISSUE positional constructor still works
    legacy = ServeBatchEvent(3, 2, 4, 0.01, 0, 7)
    assert legacy.enqueue_depth == 0 and legacy.deadline_slack_s == 0.0

    path = str(tmp_path / "serve.jsonl")
    log = JsonLinesEventLog(path)
    metrics = ServingMetrics(listener=log)
    b = MicroBatcher(lambda X: np.asarray(X).sum(axis=1),
                     max_batch=8, max_latency_s=0.01, metrics=metrics)
    futs = [b.submit(np.ones((4,), np.float32)) for _ in range(3)]
    b.stop(drain=True)  # synchronous drain: deterministic single flush
    assert [f.result(1.0) for f in futs] == [4.0] * 3
    log.close()
    rec, = [r for r in JsonLinesEventLog.read(path)
            if r["kind"] == "serve_batch"]
    assert rec["batch_size"] == 3
    # the OLDEST request saw an empty queue at its own enqueue
    assert rec["enqueue_depth"] == 0
    # stop() drained before the 10ms deadline ran out -> positive slack
    # is possible but not guaranteed on a loaded CI box; the field just
    # has to be present and finite
    assert np.isfinite(rec["deadline_slack_s"])


def test_enqueue_depth_reflects_queue_at_each_requests_enqueue():
    from tpu_sgd.serve.batcher import MicroBatcher

    seen = {}

    class Capture:
        def record_reject(self):
            pass

        def record_batch(self, **kw):
            seen.update(kw)

    b = MicroBatcher(lambda X: np.zeros((np.asarray(X).shape[0],)),
                     max_batch=8, max_latency_s=0.01, metrics=Capture())
    for _ in range(4):
        b.submit(np.ones((2,), np.float32))
    b.stop(drain=True)
    # oldest request enqueued into an empty queue; the record carries
    # ITS depth (0), not the last request's (3)
    assert seen["enqueue_depth"] == 0
    assert seen["batch_size"] == 4
    assert "deadline_slack_s" in seen


# -- report pipeline ---------------------------------------------------------

def _mk_trace(tmp_path, name="t.jsonl"):
    """A small synthetic trace with spans, counters, a checkpoint save,
    and a reload — enough surface for every report feature."""
    path = str(tmp_path / name)
    log = JsonLinesEventLog(path)
    log.emit("metric_counters", {"ts": 1.0, "counters": {
        "train.dispatch": {"n": 10, "bytes": 0},
        "serve.reject": {"n": 1, "bytes": 0}}})
    for i, dur in enumerate([0.010, 0.012, 0.011, 0.200]):
        log.emit("trace_span", {
            "name": "serve.batch", "ts": 10.0 + i, "t0_s": 1.0 + i,
            "dur_s": dur, "span_id": i + 1, "parent_id": 0,
            "thread": "flush", "error": None, "batch": 4})
    log.emit("trace_span", {
        "name": "checkpoint.save", "ts": 100.0, "t0_s": 50.0,
        "dur_s": 0.05, "span_id": 90, "parent_id": 0,
        "thread": "MainThread", "error": None, "iteration": 40})
    log.emit("trace_event", {
        "name": "reliability.retry", "ts": 101.0, "t0_s": 51.0,
        "thread": "MainThread", "subsystem": "ingest", "attempt": 1})
    log.emit("serve_reload", {"ts": 130.0, "event": "reloaded",
                              "version": 40, "previous_version": None})
    log.emit("obs_alert", {
        "ts": 131.0, "rule": "shed-rate", "series": "serve.lane.batch",
        "value": 0.6, "bound": 0.3, "window_index": 131,
        "t_start": 131.0, "t_end": 132.0, "detail": "test alert"})
    log.emit("metric_counters", {"ts": 200.0, "counters": {
        "train.dispatch": {"n": 25, "bytes": 0},
        "serve.reject": {"n": 1, "bytes": 0}}})
    log.close()
    return path


def test_report_span_stats_counters_and_staleness(tmp_path):
    records = obs_report.load_trace(_mk_trace(tmp_path))
    stats = obs_report.span_stats(records)
    sb = stats["serve.batch"]
    assert sb["count"] == 4
    assert sb["p50_s"] == 0.011   # nearest-rank over [.010,.011,.012,.200]
    assert sb["p99_s"] == 0.200
    assert sb["max_s"] == 0.200
    deltas = obs_report.counter_deltas(records)
    assert deltas == {"train.dispatch": {"n": 15, "bytes": 0}}  # 25-10; 0-delta dropped
    stale, = obs_report.staleness_samples(records)
    assert stale == {"version": 40, "staleness_s": 30.0}


def test_report_chrome_trace_export(tmp_path):
    records = obs_report.load_trace(_mk_trace(tmp_path))
    doc = obs_report.to_chrome_trace(records)
    evs = doc["traceEvents"]
    complete = [e for e in evs if e["ph"] == "X"]
    instants = [e for e in evs if e["ph"] == "i"]
    metas = [e for e in evs if e["ph"] == "M"]
    assert len(complete) == 5 and len(instants) == 1
    assert {m["args"]["name"] for m in metas} == {"flush", "MainThread"}
    sb = [e for e in complete if e["name"] == "serve.batch"][0]
    assert sb["ts"] == pytest.approx(1.0 * 1e6)
    assert sb["dur"] == pytest.approx(0.010 * 1e6)
    assert sb["args"]["batch"] == 4  # non-core fields ride args
    assert json.dumps(doc)  # serializable as-is


def test_slo_evaluation_pass_fail_and_malformed(tmp_path):
    records = obs_report.load_trace(_mk_trace(tmp_path))
    verdicts = obs_report.evaluate_slos(records, {"slos": [
        {"name": "p50", "metric": "span_p50_s", "span": "serve.batch",
         "max": 0.05},
        {"name": "p99", "metric": "span_p99_s", "span": "serve.batch",
         "max": 0.05},
        {"name": "no-drops", "metric": "counter", "counter": "serve.reject",
         "max": 0},
        {"name": "fresh", "metric": "staleness_s", "max": 60.0},
        {"name": "absent-count", "metric": "span_count",
         "span": "never.fired", "max": 0},
        {"name": "absent-latency", "metric": "span_p99_s",
         "span": "never.fired", "max": 1.0},
    ]})
    by = {v["name"]: v for v in verdicts}
    assert by["p50"]["ok"] and not by["p99"]["ok"]
    assert by["no-drops"]["ok"]          # counter DELTA is 0 across the trace
    assert by["fresh"]["ok"] and by["fresh"]["value"] == 30.0
    assert by["absent-count"]["ok"]      # count bound of 0 passes on absence
    assert not by["absent-latency"]["ok"]  # unevaluable latency ≠ free pass
    with pytest.raises(ValueError):
        obs_report.evaluate_slos(records, {"slos": [
            {"name": "typo", "metric": "span_p42_s", "span": "x", "max": 1}]})
    with pytest.raises(ValueError):
        obs_report.evaluate_slos(records, {"slos": [
            {"name": "no-bound", "metric": "staleness_s"}]})


def test_report_cli_exit_codes_and_chrome_file(tmp_path, capsys):
    trace = _mk_trace(tmp_path)
    slo_ok = tmp_path / "ok.json"
    slo_ok.write_text(json.dumps({"slos": [
        {"name": "p50", "metric": "span_p50_s", "span": "serve.batch",
         "max": 0.05}]}))
    slo_bad = tmp_path / "bad.json"
    slo_bad.write_text(json.dumps({"slos": [
        {"name": "p99", "metric": "span_p99_s", "span": "serve.batch",
         "max": 0.05}]}))
    chrome = str(tmp_path / "chrome.json")
    assert obs_report.main([trace, "--slo", str(slo_ok),
                            "--chrome", chrome]) == 0
    out = capsys.readouterr().out
    assert "SLO PASS: p50" in out and "per-stage breakdown" in out
    with open(chrome) as f:
        assert len(json.load(f)["traceEvents"]) > 0
    assert obs_report.main([trace, "--slo", str(slo_bad)]) == 1
    assert "SLO FAIL: p99" in capsys.readouterr().out
    # usage errors are 2, distinct from violations
    assert obs_report.main([str(tmp_path / "missing.jsonl")]) == 2
    # ... including an unwritable --chrome export path
    assert obs_report.main(
        [trace, "--chrome", str(tmp_path / "no_dir" / "t.json")]) == 2
    assert "cannot write Chrome trace" in capsys.readouterr().err
    garbage = tmp_path / "garbage.json"
    garbage.write_text("{not json")
    assert obs_report.main([trace, "--slo", str(garbage)]) == 2
    # --json emits one machine-readable object
    assert obs_report.main([trace, "--json", "--slo", str(slo_ok)]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["spans"]["serve.batch"]["count"] == 4
    assert doc["slos"][0]["ok"] is True


def test_chaos_soak_default_slos_are_well_formed():
    """The soak's built-in SLO doc must stay on the report schema: every
    entry evaluates (no ValueError) — on an empty trace the structural
    min-bounds simply FAIL, they never error or vacuously pass."""
    from scripts.chaos_soak import DEFAULT_SLOS

    verdicts = obs_report.evaluate_slos([], DEFAULT_SLOS)
    assert len(verdicts) == len(DEFAULT_SLOS["slos"])
    by = {v["name"]: v for v in verdicts}
    # a soak that emitted nothing fails its count gates loudly
    assert not by["train-windows-fired"]["ok"]
    assert not by["callback-windows-counted"]["ok"]


def test_report_tolerates_crash_torn_tail(tmp_path):
    """The soak/crash forensics contract, inherited from read(): a torn
    trailing line is skipped, an interior malformed line still raises."""
    trace = _mk_trace(tmp_path)
    with open(trace, "a") as f:
        f.write('{"kind": "trace_span", "name": "torn')  # no newline
    records = obs_report.load_trace(trace)
    assert len(obs_report.span_stats(records)["serve.batch"]) > 0
    with open(trace, "a") as f:
        f.write('ed"}\n{"interior": garbage}\n{"kind": "x"}\n')
    with pytest.raises(json.JSONDecodeError):
        obs_report.load_trace(trace)


# -- windowed time-series (ISSUE 13) -----------------------------------------

def _mk_store(width=1.0, **kw):
    """A WindowStore on a synthetic clock (no sleeping in tests)."""
    from tpu_sgd.obs.timeseries import WindowStore

    clock = {"t": 0.0}
    store = WindowStore(width_s=width, clock=lambda: clock["t"], **kw)
    return store, clock


def test_window_store_aggregates_and_nearest_rank_parity():
    """Per-window count/sum/max are exact and the window p50/p99 agree
    with ServingMetrics' live scrape — ONE percentile rule everywhere
    (serve.metrics.nearest_rank)."""
    from tpu_sgd.serve.metrics import ServingMetrics

    store, clock = _mk_store()
    samples = [0.010, 0.012, 0.011, 0.200, 0.003, 0.050, 0.007]
    for v in samples:
        store.observe("serve.batch", value=v)
    clock["t"] = 1.5  # roll the window
    store.observe("serve.batch", value=1.0)
    w0 = store.snapshot()[0]
    assert w0["closed"] is True
    s = w0["series"]["serve.batch"]
    assert s["count"] == len(samples)
    assert s["sum"] == pytest.approx(sum(samples))
    assert s["max"] == 0.200
    metrics = ServingMetrics()
    metrics.record_batch(queue_depth=0, batch_size=len(samples),
                         padded_size=8, latencies=samples,
                         reject_count=0)
    assert s["p50"] == metrics.latency_percentile(50)
    assert s["p99"] == metrics.latency_percentile(99)


def test_window_store_ring_and_sample_bounds_under_long_run():
    """The acceptance bound: memory is bounded by WINDOW COUNT, never
    run length — a 10k-window synthetic run retains max_windows closed
    windows, and a 10k-observation window caps its sample buffer while
    count/sum/max stay exact."""
    store, clock = _mk_store(width=1.0, max_windows=32,
                             samples_per_series=64)
    for i in range(10_000):
        clock["t"] = float(i)
        store.observe("train.loss", value=float(i % 7))
    assert len(store._windows) == 32          # the ring, full and bounded
    assert len(store.snapshot()) == 33        # + the open window
    # one giant window: samples capped, exact aggregates kept
    store2, _ = _mk_store(samples_per_series=64)
    for i in range(10_000):
        store2.observe("x", value=float(i))
    s = store2.snapshot()[0]["series"]["x"]
    assert s["count"] == 10_000
    assert s["samples_capped"] is True
    assert s["max"] == 9999.0
    assert s["sum"] == pytest.approx(sum(range(10_000)))


def test_window_store_flush_and_late_records():
    """flush() closes the open window (fires listeners) so a finished
    run's trailing data evaluates; a record with an OLDER ts than the
    open window folds into the open window, never reopens a closed
    one."""
    store, clock = _mk_store()
    closed = []
    store.add_close_listener(lambda w: closed.append(w))
    clock["t"] = 5.5
    store.observe("a", value=1.0)
    store.observe("b", ts=4.2)  # late cross-thread record: folds in
    store.flush()
    assert len(closed) == 1
    assert closed[0]["series"]["a"]["count"] == 1
    assert closed[0]["series"]["b"]["count"] == 1
    assert store.snapshot() == [closed[0]]  # flush closed it into the ring
    # a mid-run flush must not duplicate a ring index: the rest of the
    # same wall-clock second lands in the NEXT window
    store.observe("a", value=2.0)  # clock still inside flushed window 5
    store.flush()
    assert [w["index"] for w in store.snapshot()] == [5, 6]


def test_window_store_concurrent_close_joins_dispatch_thread():
    """The racing schedule the ISSUE 19 fix pins: ``close()`` snapshots
    the dispatch-thread handle UNDER ``_dispatch_cv`` (an unlocked read
    raced ``add_close_listener``'s lazy spawn and could miss the thread
    entirely), then joins OUTSIDE the cv — so N concurrent closers all
    return with the dispatch thread really dead, never deadlocked on
    the loop's finally-block."""
    store, _ = _mk_store()
    store.add_close_listener(lambda snap: None)
    t = store._dispatch_thread
    assert t is not None and t.is_alive()
    closers = [threading.Thread(target=store.close, name=f"close{i}")
               for i in range(3)]
    for c in closers:
        c.start()
    for c in closers:
        c.join(timeout=30)
    assert not any(c.is_alive() for c in closers)  # no deadlock
    assert not t.is_alive()  # really joined, not leaked as a daemon


# -- detectors: trip / no-trip fixtures per rule -----------------------------

def _run_detector(detector, feeds, width=1.0):
    """Drive windows through a private store+engine: ``feeds`` is one
    dict per window, series -> list of observe kwargs."""
    from tpu_sgd.obs.detect import DetectorEngine
    from tpu_sgd.obs.timeseries import WindowStore

    clock = {"t": 0.5}
    store = WindowStore(width_s=width, clock=lambda: clock["t"])
    engine = DetectorEngine([detector])
    store.add_close_listener(engine.on_window_close)
    for wi, feed in enumerate(feeds):
        clock["t"] = wi + 0.5
        for series, obs_list in feed.items():
            for kw in obs_list:
                store.observe(series, **kw)
    store.flush()
    return engine


def _vals(v, n=1):
    return [{"value": v}] * n


def test_detector_loss_divergence_trip_and_no_trip():
    from tpu_sgd.obs.detect import LossDivergenceDetector

    steady = [{"train.loss": _vals(1.0, 4)}] * 3
    eng = _run_detector(LossDivergenceDetector(),
                        steady + [{"train.loss": _vals(10.0, 4)}])
    assert eng.trip_counts() == {"loss-divergence": 1}
    # a converging run never trips
    eng = _run_detector(LossDivergenceDetector(), [
        {"train.loss": _vals(1.0 / (i + 1), 4)} for i in range(6)])
    assert eng.trip_counts() == {}


def test_detector_loss_plateau_trip_and_not_in_defaults():
    from tpu_sgd.obs.detect import LossPlateauDetector, default_detectors

    flat = [{"train.loss": _vals(0.5, 4)}] * 5
    eng = _run_detector(LossPlateauDetector(), flat)
    assert eng.trip_counts() == {"loss-plateau": 1}
    falling = [{"train.loss": _vals(1.0 / (i + 1), 4)} for i in range(5)]
    eng = _run_detector(LossPlateauDetector(), falling)
    assert eng.trip_counts() == {}
    # a converged run plateaus legitimately: the rule is control-plane
    # opt-in, NOT part of the default anomaly set
    assert "loss-plateau" not in {d.rule for d in default_detectors()}


def test_detector_staleness_creep_trip_and_no_trip():
    from tpu_sgd.obs.detect import StalenessCreepDetector

    eng = _run_detector(StalenessCreepDetector(max_staleness=8),
                        [{"replica.push.staleness": _vals(2.0, 5)}])
    assert eng.trip_counts() == {}
    eng = _run_detector(StalenessCreepDetector(max_staleness=8),
                        [{"replica.push.staleness": _vals(2.0, 5)},
                         {"replica.push.staleness": _vals(12.0, 1)}])
    assert eng.trip_counts() == {"staleness-creep": 1}


def test_detector_shed_rate_trip_no_trip_and_min_offered():
    from tpu_sgd.obs.detect import LaneRejectionDetector

    def lane_feed(admitted, shed):
        return {"serve.admitted.interactive": [{}] * admitted,
                "serve.shed.interactive": [{}] * shed}

    eng = _run_detector(LaneRejectionDetector(), [lane_feed(30, 30)])
    assert eng.trip_counts() == {"shed-rate": 1}
    # healthy lane: rate under threshold
    eng = _run_detector(LaneRejectionDetector(), [lane_feed(30, 2)])
    assert eng.trip_counts() == {}
    # a tiny window cannot trip on 3 requests (min_offered)
    eng = _run_detector(LaneRejectionDetector(), [lane_feed(1, 2)])
    assert eng.trip_counts() == {}


def test_detector_straggler_trip_no_trip_and_fleet_silence():
    """The rule is cumulative over fleet PROGRESS, not wall clock: a
    silent worker trips once its peers accumulate min_fleet_steps
    steps — however many windows that takes — so ambient load that
    slows the whole fleet down equally can never flake it."""
    from tpu_sgd.obs.detect import StragglerDetector

    def fleet(*counts):
        return {f"replica.step[w{i}]": _vals(0.01, c)
                for i, c in enumerate(counts) if c}

    active = [fleet(5, 5, 5)]
    # w1 goes silent while the others accumulate 10 peer steps: trip —
    # whether the progress arrives fast (one window) or slow (many)
    eng = _run_detector(StragglerDetector(min_fleet_steps=10),
                        active + [fleet(5, 0, 5)])
    assert eng.trip_counts() == {"replica-straggler": 1}
    eng = _run_detector(StragglerDetector(min_fleet_steps=10),
                        active + [fleet(1, 0, 1)] * 5)
    assert eng.trip_counts() == {"replica-straggler": 1}
    # a lagging-but-alive worker under the threshold: no trip (the SSP
    # progress bound caps live lag at ~(n-1)*tau peer steps)
    eng = _run_detector(StragglerDetector(min_fleet_steps=10),
                        active + [fleet(2, 0, 2), fleet(2, 1, 2)] * 3)
    assert eng.trip_counts() == {}
    # the whole fleet goes silent (round ended): NOT a straggler
    eng = _run_detector(StragglerDetector(min_fleet_steps=10),
                        active + [fleet(0, 0, 0)] * 6)
    assert eng.trip_counts() == {}


def test_detector_straggler_membership_events_drive_the_roster():
    """Membership is event-driven (the replica.join/rejoin/leave
    fan-out): a CLEAN leave removes the worker — its residual deficit
    cannot false-trip the NEXT fleet sharing this engine — while a
    death-leave (the .error twin) keeps accumulating until the rejoin,
    and a joined-but-never-stepped worker is tracked from its join."""
    from tpu_sgd.obs.detect import StragglerDetector

    def fleet(*counts, extra=None):
        d = {f"replica.step[w{i}]": _vals(0.01, c)
             for i, c in enumerate(counts) if c}
        d.update(extra or {})
        return d

    # run A ends with w1 slightly behind, leaves CLEANLY; run B's
    # early windows must not inherit the deficit
    run_a_end = fleet(4, 0, 4, extra={
        "replica.leave[w0]": [{}], "replica.leave[w1]": [{}],
        "replica.leave[w2]": [{}]})
    run_b = [fleet(0, 0, 0, extra={f"replica.join[w{i}]": [{}]
                                   for i in range(3)}),
             fleet(4, 0, 4), fleet(2, 1, 2)]
    eng = _run_detector(StragglerDetector(min_fleet_steps=10),
                        [fleet(3, 3, 3), run_a_end] + run_b)
    assert eng.trip_counts() == {}
    # a DEATH-leave keeps the entry hunting: the deficit crosses the
    # threshold while the worker is gone
    death = [fleet(3, 3, 3),
             fleet(3, 0, 3, extra={"replica.leave.error[w1]": [{}]}),
             fleet(3, 0, 3)]
    eng = _run_detector(StragglerDetector(min_fleet_steps=10), death)
    assert eng.trip_counts() == {"replica-straggler": 1}
    # a worker that JOINED but never stepped is tracked from the join:
    # peers moving on without it trips the rule
    spawn_dead = [fleet(0, 0, extra={"replica.join[w0]": [{}],
                                     "replica.join[w1]": [{}]}),
                  fleet(6, 0), fleet(6, 0)]
    eng = _run_detector(StragglerDetector(min_fleet_steps=10),
                        spawn_dead)
    assert eng.trip_counts() == {"replica-straggler": 1}


def test_detector_wire_ratio_collapse_trip_exempt_and_no_trip():
    from tpu_sgd.obs.detect import WireRatioDetector

    def wire(fmt, phys, logical):
        return {f"replica.wire.{fmt}": [{"nbytes": phys}],
                f"replica.wire.{fmt}.logical": [{"nbytes": logical}]}

    # compression collapsed: topk shipping nearly-dense bytes
    eng = _run_detector(WireRatioDetector(), [wire("topk", 100_000,
                                                  105_000)])
    assert eng.trip_counts() == {"wire-ratio-collapse": 1}
    # healthy compression
    eng = _run_detector(WireRatioDetector(), [wire("topk", 10_000,
                                                  500_000)])
    assert eng.trip_counts() == {}
    # dense-f32's 1.0x ratio is BY CONSTRUCTION, never a collapse
    eng = _run_detector(WireRatioDetector(), [wire("dense-f32", 100_000,
                                                  100_000)])
    assert eng.trip_counts() == {}


def test_detector_dispatch_regression_trip_no_trip_and_floor():
    from tpu_sgd.obs.detect import DispatchRegressionDetector

    steady = [{"train.dispatch": [{"n": 100}]}] * 4
    eng = _run_detector(DispatchRegressionDetector(),
                        steady + [{"train.dispatch": [{"n": 400}]}])
    assert eng.trip_counts() == {"dispatch-regression": 1}
    eng = _run_detector(DispatchRegressionDetector(), steady * 2)
    assert eng.trip_counts() == {}
    # idle-phase noise (median under the floor) cannot trip
    tiny = [{"train.dispatch": [{"n": 2}]}] * 4
    eng = _run_detector(DispatchRegressionDetector(),
                        tiny + [{"train.dispatch": [{"n": 12}]}])
    assert eng.trip_counts() == {}


def test_detector_engine_transition_dedup_and_rearm():
    """A rule that stays tripped across consecutive windows emits ONE
    alert; after a clean window it re-arms and a new episode emits a
    new alert."""
    from tpu_sgd.obs.detect import StalenessCreepDetector

    hot = {"replica.push.staleness": _vals(12.0, 2)}
    cool = {"replica.push.staleness": _vals(1.0, 2)}
    eng = _run_detector(StalenessCreepDetector(max_staleness=8),
                        [hot, hot, hot, cool, hot])
    assert eng.trip_counts() == {"staleness-creep": 2}


def test_detector_alert_is_typed_record_counter_and_flightrec(tmp_path):
    """The full alert contract end-to-end through the facade: a shed
    spike trips the rule, the trip is a typed obs_alert record on the
    trace sink, an obs.alert.<rule> counter, an active alert on the
    engine, and a flight-recorder dump."""
    import os

    fr = str(tmp_path / "fr.jsonl")
    sink = ListSink()
    obs.enable(sink, detect=True, window_s=0.05, flightrec=fr)
    try:
        for _ in range(30):
            obs_counters.inc("serve.admitted.interactive")
            obs_counters.inc("serve.shed.interactive")
        time.sleep(0.06)
        obs_counters.inc("serve.admitted.interactive")
        obs.flush_windows()
        alerts = [p for k, p in sink.records if k == "obs_alert"]
        assert alerts and alerts[0]["rule"] == "shed-rate"
        assert alerts[0]["series"] == "serve.lane.interactive"
        assert obs_counters.snapshot()["obs.alert.shed-rate"]["n"] >= 1
        eng = obs.detector_engine()
        assert eng is not None
        assert eng.trip_counts().get("shed-rate", 0) >= 1
        assert os.path.exists(fr)
        recs = JsonLinesEventLog.read(fr)
        assert recs[0]["kind"] == "flightrec_meta"
        assert recs[0]["reason"].startswith("alert:shed-rate")
        assert any(r["kind"] == "obs_window" for r in recs)
    finally:
        obs.disable()
    assert obs.detector_engine() is None  # torn down with the layer


def test_clean_seeded_run_trips_no_detectors(rng):
    """The no-false-positive pin: a fault-free seeded train + serve
    flow under the DEFAULT detector set raises zero alerts."""
    from tpu_sgd.models import LinearRegressionModel
    from tpu_sgd.serve import Server

    X, y = _data(rng)
    w0 = np.zeros(6, np.float32)
    o = _opt()
    o.optimize_with_history((X, y), w0)  # warm before enabling
    sink = ListSink()
    obs.enable(sink, detect=True, window_s=0.25)
    try:
        w, _ = o.optimize_with_history((X, y), w0)
        with Server(LinearRegressionModel(np.asarray(w), 0.0),
                    max_latency_s=0.002) as srv:
            futs = [srv.submit(X[i]) for i in range(64)]
            for f in futs:
                f.result(timeout=30)
        obs.flush_windows()
        assert [k for k, _ in sink.records if k == "obs_alert"] == []
        assert obs.detector_engine().trip_counts() == {}
    finally:
        obs.disable()


# -- flight recorder ---------------------------------------------------------

def test_flight_recorder_dumps_on_error_unwind(tmp_path):
    """An error crossing a span boundary triggers a dump: the ring
    holds the erroring span record itself, the meta header names the
    span, and the run keeps going (the recorder never re-raises)."""
    trace = str(tmp_path / "t.jsonl")
    fr = str(tmp_path / "fr.jsonl")
    obs.enable(trace, flightrec=fr)
    try:
        with obs.span("serve.batch", batch=4):
            pass  # a healthy span first: it must be IN the ring
        with pytest.raises(ValueError):
            with obs.span("train.superstep", i0=9):
                raise ValueError("boom")
    finally:
        obs.disable()
    recs = JsonLinesEventLog.read(fr)
    meta = recs[0]
    assert meta["kind"] == "flightrec_meta"
    assert meta["reason"] == "span-error:train.superstep"
    assert meta["detail"] == "ValueError"
    spans = [r for r in recs if r["kind"] == "trace_span"]
    assert [s["name"] for s in spans] == ["serve.batch",
                                          "train.superstep"]
    assert spans[1]["error"] == "ValueError"


def test_flight_recorder_ring_is_bounded_and_dump_replaces(tmp_path):
    from tpu_sgd.obs.flightrec import FlightRecorder

    fr = FlightRecorder(str(tmp_path / "fr.jsonl"), capacity=8)
    for i in range(100):
        fr.record("trace_event", {"name": "e", "i": i})
    assert fr.trigger("first") is not None
    recs = JsonLinesEventLog.read(fr.path)
    assert len(recs) == 1 + 8  # meta + the BOUNDED ring tail
    assert [r["i"] for r in recs[1:]] == list(range(92, 100))
    fr.record("trace_event", {"name": "e", "i": 100})
    fr.trigger("second", detail="why")
    recs = JsonLinesEventLog.read(fr.path)  # replaced, not appended
    assert recs[0]["reason"] == "second"
    assert recs[0]["dump_ordinal"] == 2
    assert recs[-1]["i"] == 100


# -- live series feeds -------------------------------------------------------

def test_server_healthz_carries_windows_snapshot(rng):
    from tpu_sgd.models import LinearRegressionModel
    from tpu_sgd.serve import Server

    X, _ = _data(rng)
    model = LinearRegressionModel(np.zeros(6, np.float32), 0.0)
    with Server(model, max_latency_s=0.002) as srv:
        srv.predict(X[0], timeout=30)
        assert srv.healthz()["windows"] is None  # layer off: honest None
    sink = ListSink()
    obs.enable(sink, window_s=0.05)
    try:
        with Server(model, max_latency_s=0.002) as srv:
            for i in range(8):
                srv.predict(X[i], timeout=30)
            wins = srv.healthz()["windows"]
    finally:
        obs.disable()
    assert wins, "no serve windows recorded"
    names = {n for w in wins for n in w["series"]}
    assert any(n.startswith("serve.") for n in names)


def test_replica_driver_windows_snapshot(rng):
    from tpu_sgd.replica import ReplicaDriver

    X, y = _data(rng, n=64)
    w0 = np.zeros(6, np.float32)
    sink = ListSink()
    obs.enable(sink, window_s=0.05)
    try:
        drv = (ReplicaDriver().set_num_iterations(8).set_step_size(0.1)
               .set_mini_batch_fraction(1.0).set_convergence_tol(0.0)
               .set_seed(3).set_workers(2).set_staleness(0))
        drv.optimize_with_history((X, y), w0)
        wins = drv.last_windows_snapshot
    finally:
        obs.disable()
    assert wins, "no replica windows recorded"
    names = {n for w in wins for n in w["series"]}
    assert any(n.startswith("replica.step[") for n in names)
    assert "replica.push.staleness" in names  # the version-gap series
    assert drv.windows() is None  # layer off again: honest None


# -- report: windows, alerts, window SLO metrics -----------------------------

def test_report_windowed_stats_alerts_and_staleness_buckets(tmp_path):
    records = obs_report.load_trace(_mk_trace(tmp_path))
    wins = obs_report.windowed_stats(records, 1.0)
    by_idx = {w["index"]: w for w in wins}
    # the four serve.batch spans land one per second at ts 10..13
    for i in range(10, 14):
        assert by_idx[i]["spans"]["serve.batch"]["count"] == 1
    assert by_idx[131]["alerts"][0]["rule"] == "shed-rate"
    # the staleness join gains its time dimension: bucketed at reload ts
    assert by_idx[130]["staleness"] == [
        {"version": 40, "staleness_s": 30.0}]
    txt = obs_report.render_windows(wins)
    assert "window 10" in txt and "ALERT [shed-rate]" in txt
    stats = obs_report.alert_stats(records)
    assert stats["count"] == 1 and stats["by_rule"] == {"shed-rate": 1}
    # a foreign/drifted obs_alert missing value/bound degrades the
    # render, never crashes the report or the live watcher
    weird = records + [{"kind": "obs_alert", "ts": 132.0,
                        "rule": "custom", "series": "x"}]
    assert "value=?" in obs_report.render_report(weird)
    assert "value=?" in obs_report.render_windows(
        obs_report.windowed_stats(weird, 1.0))


def test_report_window_slo_metrics_absent_is_violation(tmp_path):
    records = obs_report.load_trace(_mk_trace(tmp_path))
    verdicts = obs_report.evaluate_slos(records, {"slos": [
        {"name": "w-p99-bad", "metric": "window_span_p99_s",
         "span": "serve.batch", "window_s": 1.0, "max": 0.05},
        {"name": "w-p99-ok", "metric": "window_span_p99_s",
         "span": "serve.batch", "window_s": 1.0, "max": 0.5},
        {"name": "w-absent", "metric": "window_span_p99_s",
         "span": "never.fired", "window_s": 1.0, "max": 10.0},
        {"name": "w-gap", "metric": "window_span_count_min",
         "span": "serve.batch", "window_s": 1.0, "min": 1},
        {"name": "alerts-any", "metric": "alert_count", "max": 0},
        {"name": "alerts-rule", "metric": "alert_count",
         "rule": "shed-rate", "min": 1},
        {"name": "alerts-other", "metric": "alert_count",
         "rule": "replica-straggler", "max": 0},
    ]})
    by = {v["name"]: v for v in verdicts}
    # the ts-13 window holds the 0.200s span: worst window p99
    assert not by["w-p99-bad"]["ok"] and by["w-p99-bad"]["value"] == 0.200
    assert by["w-p99-ok"]["ok"]
    # a windowed latency bound over a span that never fired: violation
    assert not by["w-absent"]["ok"] and by["w-absent"]["value"] is None
    # the grid spans ts 10..131 — the gap windows count ZERO, never
    # silent green
    assert not by["w-gap"]["ok"] and by["w-gap"]["value"] == 0
    assert not by["alerts-any"]["ok"]  # the trace carries one alert
    assert by["alerts-rule"]["ok"]
    assert by["alerts-other"]["ok"]    # absent rule counts 0, max 0 holds
    with pytest.raises(ValueError):
        obs_report.evaluate_slos(records, {"slos": [
            {"name": "no-width", "metric": "window_span_p99_s",
             "span": "serve.batch", "max": 1.0}]})


def test_report_cli_window_flag_and_json(tmp_path, capsys):
    trace = _mk_trace(tmp_path)
    assert obs_report.main([trace, "--window", "1.0"]) == 0
    out = capsys.readouterr().out
    assert "time-bucketed tables" in out and "window 10" in out
    assert "alerts (1 typed obs_alert trips)" in out
    assert obs_report.main([trace, "--window", "1.0", "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["alerts"]["by_rule"] == {"shed-rate": 1}
    assert any(w["index"] == 131 for w in doc["windows"])


# -- the watch CLI -----------------------------------------------------------

def test_watch_once_renders_windows_and_alerts(tmp_path, capsys):
    from tpu_sgd.obs import watch as obs_watch

    trace = _mk_trace(tmp_path)
    with open(trace, "a") as f:
        f.write('{"kind": "torn_mid')  # a live producer mid-write
    assert obs_watch.main([trace, "--once", "--window", "1.0",
                           "--active-s", "1000"]) == 0
    out = capsys.readouterr().out
    assert "window 10" in out
    assert "ACTIVE ALERTS" in out and "shed-rate" in out
    assert "parse_errors" not in out  # the torn tail is buffered, not
    #                                   an error
    assert obs_watch.main([str(tmp_path / "missing.jsonl"),
                           "--once"]) == 2


def test_watch_tail_is_incremental_and_tolerant(tmp_path):
    from tpu_sgd.obs.watch import TraceTail

    path = str(tmp_path / "t.jsonl")
    with open(path, "w") as f:
        f.write('{"kind": "trace_event", "name": "a", "ts": 1.0}\n')
        f.write('{"kind": "trace_')  # torn mid-write
    tail = TraceTail(path)
    recs = tail.poll()
    assert [r["name"] for r in recs] == ["a"]
    with open(path, "a") as f:  # the producer finishes the line
        f.write('event", "name": "b", "ts": 2.0}\n')
        f.write('garbage line\n')  # malformed interior: skipped, counted
        f.write('{"kind": "trace_event", "name": "c", "ts": 3.0}\n')
    recs = tail.poll()
    assert [r["name"] for r in recs] == ["b", "c"]
    assert tail.parse_errors == 1
    assert tail.poll() == []  # EOF: nothing new
    tail.close()


# -- the bench regression gate -----------------------------------------------

def test_bench_gate_self_check_perturbed_and_missing(tmp_path, capsys):
    """The CI contract: exit 0 on the committed baselines, 1 on a
    deliberately perturbed candidate (the gate provably fails bad
    numbers), 1 on a candidate missing a headline metric, 2 on an
    unreadable baseline."""
    import os
    import shutil

    from scripts import bench_gate

    assert bench_gate.main([]) == 0  # the committed files gate green
    capsys.readouterr()
    repo = os.path.dirname(os.path.dirname(
        os.path.abspath(bench_gate.__file__)))
    cand = tmp_path / "cand"
    cand.mkdir()
    for fname in bench_gate.GATES:
        shutil.copy(os.path.join(repo, fname), cand / fname)
    with open(cand / "BENCH_OBS.json") as f:
        doc = json.load(f)
    doc["headline"]["superstep_count_deltas"]["dispatches"] = 3
    with open(cand / "BENCH_OBS.json", "w") as f:
        json.dump(doc, f)
    assert bench_gate.main(["--candidate-dir", str(cand)]) == 1
    assert "GATE FAIL" in capsys.readouterr().out
    # a vanished candidate metric is a regression, not a skip
    del doc["headline"]["superstep_count_deltas"]
    doc["headline"]["superstep_count_deltas"] = {}
    with open(cand / "BENCH_OBS.json", "w") as f:
        json.dump(doc, f)
    assert bench_gate.main(["--candidate-dir", str(cand)]) == 1
    capsys.readouterr()
    # unreadable baseline = usage-error class
    assert bench_gate.main(["--baseline-dir",
                            str(tmp_path / "nope")]) == 2


def test_bench_gate_direction_semantics():
    from scripts.bench_gate import Gate, check_gate

    base = {"x": {"ratio": 100.0, "count": 10}}
    # higher-is-better: improvement passes, collapse beyond band fails
    g = Gate("x/ratio", "higher", rel_tol=0.1)
    assert check_gate(g, base, {"x": {"ratio": 150.0}})["ok"]
    assert check_gate(g, base, {"x": {"ratio": 91.0}})["ok"]
    assert not check_gate(g, base, {"x": {"ratio": 85.0}})["ok"]
    # lower-is-better: fewer dispatches always pass
    g = Gate("x/count", "lower", rel_tol=0.1)
    assert check_gate(g, base, {"x": {"count": 5}})["ok"]
    assert not check_gate(g, base, {"x": {"count": 12}})["ok"]
    # equal: drift either way beyond the band fails
    g = Gate("x/count", "equal")
    assert check_gate(g, base, {"x": {"count": 10}})["ok"]
    assert not check_gate(g, base, {"x": {"count": 9}})["ok"]
