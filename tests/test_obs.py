"""Unified observability layer tests (ISSUE 8): span tracing, runtime
counters, and the trace/SLO report pipeline.

The two load-bearing contracts pinned here:

* DISABLED is a measured no-op — one module-global load and a falsy
  branch per hook, the failpoints discipline (`span()` returns the one
  shared singleton, `inc()` bumps nothing, zero runtime patches
  installed).
* ENABLED adds ZERO dispatches, compiles, or host syncs on the warmed
  superstep and resident hot paths (the acceptance criterion), measured
  both by the analysis twins (disabled baseline) and by the promoted
  counters themselves (enabled run) — the numbers must agree exactly.
"""

import json
import threading
import time

import numpy as np
import pytest

from tpu_sgd import obs
from tpu_sgd.obs import counters as obs_counters
from tpu_sgd.obs import report as obs_report
from tpu_sgd.obs import spans as obs_spans
from tpu_sgd.obs.spans import disable_tracing, enable_tracing
from tpu_sgd.utils.events import JsonLinesEventLog


class ListSink:
    """In-memory sink on the ``emit(kind, payload)`` contract."""

    def __init__(self, raising: bool = False):
        self.records = []
        self.raising = raising

    def emit(self, kind, payload):
        if self.raising:
            raise RuntimeError("sink intentionally broken")
        self.records.append((kind, dict(payload)))

    def spans(self, name=None):
        return [p for k, p in self.records if k == "trace_span"
                and (name is None or p["name"] == name)]

    def events(self, name=None):
        return [p for k, p in self.records if k == "trace_event"
                and (name is None or p["name"] == name)]


@pytest.fixture(autouse=True)
def _obs_off():
    """Every test starts and ends with the layer fully disabled."""
    obs.disable()
    obs_counters.reset()
    yield
    obs.disable()
    obs_counters.reset()


# -- disabled-mode cost contract --------------------------------------------

def test_disabled_span_is_the_shared_noop_singleton():
    """`span(...)` disabled returns ONE shared object — no allocation,
    no formatting; `event`/`inc` return before touching anything."""
    s1 = obs_spans.span("train.superstep", i0=1)
    s2 = obs_spans.span("serve.batch")
    assert s1 is s2  # the singleton, not a fresh object per call
    with s1 as s:
        assert s.set(anything=1) is s  # set() is a no-op that chains
    obs_spans.event("reliability.retry", attempt=1)  # must not raise
    obs_counters.inc("serve.reject")
    assert obs_counters.snapshot() == {}


def test_disabled_hooks_are_measured_noops():
    """The failpoints discipline, measured: sub-microsecond per call on
    this noisy 2-core host (bound ~20x the measured mean for CI
    headroom).  `span()` pays one kwargs dict + global load + branch;
    `inc()` pays the global load + branch."""
    n = 200_000
    t0 = time.perf_counter()
    for _ in range(n):
        obs_spans.span("train.step")
    per_span = (time.perf_counter() - t0) / n
    t0 = time.perf_counter()
    for _ in range(n):
        obs_counters.inc("train.io_callback")
    per_inc = (time.perf_counter() - t0) / n
    assert per_span < 2e-6, f"disabled span costs {per_span*1e9:.0f}ns"
    assert per_inc < 2e-6, f"disabled inc costs {per_inc*1e9:.0f}ns"


def test_disabled_installs_zero_runtime_patches():
    """A production process that never opts in runs the STOCK runtime:
    enabling installs the patches, disabling restores the originals."""
    import jax

    orig_put = jax.device_put
    obs_counters.enable()
    try:
        assert jax.device_put is not orig_put
    finally:
        obs_counters.disable()
    assert jax.device_put is orig_put


# -- span mechanics ----------------------------------------------------------

def test_span_nesting_and_attrs():
    sink = ListSink()
    enable_tracing(sink)
    try:
        with obs_spans.span("train.superstep", i0=5) as outer:
            with obs_spans.span("train.replay"):
                pass
            outer.set(steps=4)
    finally:
        disable_tracing()
    inner, = sink.spans("train.replay")
    outer, = sink.spans("train.superstep")
    assert inner["parent_id"] == outer["span_id"]  # child closed first
    assert outer["parent_id"] == 0
    assert outer["i0"] == 5 and outer["steps"] == 4
    assert outer["dur_s"] >= inner["dur_s"] >= 0.0
    assert outer["error"] is None


def test_span_records_error_class_and_propagates():
    sink = ListSink()
    enable_tracing(sink)
    try:
        with pytest.raises(ValueError):
            with obs_spans.span("checkpoint.save"):
                raise ValueError("boom")
    finally:
        disable_tracing()
    rec, = sink.spans("checkpoint.save")
    assert rec["error"] == "ValueError"


def test_spans_are_thread_aware():
    """Each thread keeps its own stack: a worker's span must not parent
    onto whatever the main thread has open (the prefetch-worker /
    flush-thread contract), and the subsystem tag is per-thread too."""
    sink = ListSink()
    enable_tracing(sink)
    tags = {}
    try:
        def worker():
            with obs_spans.span("ingest.produce"):
                tags["worker"] = obs_spans.current_subsystem()
                time.sleep(0.005)

        with obs_spans.span("train.superstep"):
            t = threading.Thread(target=worker, name="w0")
            t.start()
            tags["main"] = obs_spans.current_subsystem()
            t.join()
    finally:
        disable_tracing()
    produce, = sink.spans("ingest.produce")
    assert produce["parent_id"] == 0  # NOT nested under train.superstep
    assert produce["thread"] == "w0"
    assert tags == {"worker": "ingest", "main": "train"}
    assert obs_spans.current_subsystem() == "untagged"


def test_raising_sink_never_kills_the_hot_path():
    enable_tracing(ListSink(raising=True))
    try:
        with obs_spans.span("train.step", i=1):
            pass  # span exit swallows the sink error
        obs_spans.event("reliability.retry")  # ditto
    finally:
        disable_tracing()


# -- counters ----------------------------------------------------------------

def test_counters_inc_snapshot_deltas_reset():
    obs_counters.enable()
    try:
        obs_counters.inc("serve.reject")
        with obs_counters.deltas() as d:
            obs_counters.inc("serve.reject", 2)
            obs_counters.inc("ingest.wire", nbytes=128)
        got = d.get()
        assert got == {"serve.reject": {"n": 2, "bytes": 0},
                       "ingest.wire": {"n": 1, "bytes": 128}}
        snap = obs_counters.snapshot()
        assert snap["serve.reject"]["n"] == 3
    finally:
        obs_counters.disable()
    # values survive disable (scrape-after-stop); reset clears
    assert obs_counters.snapshot()["serve.reject"]["n"] == 3
    obs_counters.reset()
    assert obs_counters.snapshot() == {}


def test_counters_attribute_runtime_events_to_the_open_subsystem():
    """Dispatches/compiles/syncs/h2d land under the span-derived tag of
    the thread that caused them — the straggler-attribution surface.
    Tagging rides the span stack, so tracing must be on too (the facade
    enables both)."""
    import jax
    import jax.numpy as jnp

    f = jax.jit(lambda x: x * 2 + 1)
    x = jnp.ones((8, 8))
    f(x).block_until_ready()  # warm BEFORE enabling
    enable_tracing(ListSink())
    obs_counters.enable()
    try:
        with obs_spans.span("train.superstep"):
            y = f(x)
            v = float(y[0, 0])  # eager slice + scalar fetch
        with obs_spans.span("ingest.produce"):
            jax.device_put(np.ones((4, 4), np.float32))
        snap = obs_counters.snapshot()
    finally:
        obs_counters.disable()
    assert v == 3.0
    assert snap["train.dispatch"]["n"] >= 1     # the warmed f(x) launch
    assert snap["train.host_sync"]["n"] >= 1    # the float() fetch
    assert snap["train.host_sync"]["bytes"] >= 4
    assert snap["ingest.h2d"]["n"] == 1
    assert snap["ingest.h2d"]["bytes"] == 64
    # no compile of the WARMED function; the eager slice may compile
    assert snap.get("untagged.dispatch", {"n": 0})["n"] == 0


def test_counters_enable_disable_roundtrip_under_twins():
    """The analysis twins nest over the promoted patches (both
    patch/restore LIFO) and agree with them on a warmed function."""
    import jax
    import jax.numpy as jnp

    from tpu_sgd.analysis.runtime import count_dispatches

    f = jax.jit(lambda x: x + 1)
    x = jnp.zeros((4,))
    f(x).block_until_ready()
    obs_counters.enable()
    try:
        obs_counters.reset()
        with count_dispatches() as twin:
            f(x).block_until_ready()
        snap = obs_counters.snapshot()
    finally:
        obs_counters.disable()
    assert twin["n"] == 1
    assert snap["untagged.dispatch"]["n"] == 1  # no span open: untagged


# -- facade ------------------------------------------------------------------

def test_facade_owns_trace_log_and_flushes_counters(tmp_path):
    path = str(tmp_path / "trace.jsonl")
    obs.enable(path)
    with obs.span("train.superstep", i0=1):
        obs.inc("train.io_callback")
    obs.flush_counters()
    obs.disable()  # flushes once more + closes the owned log
    records = JsonLinesEventLog.read(path)
    kinds = [r["kind"] for r in records]
    assert "trace_span" in kinds
    assert kinds.count("metric_counters") == 2
    last = [r for r in records if r["kind"] == "metric_counters"][-1]
    assert last["counters"]["train.io_callback"]["n"] == 1


def test_facade_shares_a_listener_event_log(tmp_path):
    """Traces interleave with listener events on ONE JSONL stream — the
    chaos-soak spelling (caller keeps ownership)."""
    from tpu_sgd.utils.events import IterationEvent

    path = str(tmp_path / "shared.jsonl")
    log = JsonLinesEventLog(path)
    obs.enable(log, with_counters=False)
    log.on_iteration(IterationEvent(1, 0.5, 0.1, 32, 0.01))
    with obs.span("train.step", i=1):
        pass
    obs.disable()  # caller-owned: must NOT close it
    log.on_iteration(IterationEvent(2, 0.4, 0.1, 32, 0.01))
    log.close()
    kinds = [r["kind"] for r in JsonLinesEventLog.read(path)]
    assert kinds == ["iteration", "trace_span", "iteration"]


def test_reenable_with_new_path_closes_previous_owned_log(tmp_path):
    """A second enable() must not leak the first's file handle: the
    previously owned log is closed (tail flushed) when the sink swaps."""
    a = str(tmp_path / "a.jsonl")
    b = str(tmp_path / "b.jsonl")
    obs.enable(a)
    first = obs._OWNED_LOG
    with obs.span("train.step", i=1):
        pass
    obs.enable(b)  # swap without an intervening disable()
    assert first._f.closed  # the leak the review caught
    with obs.span("train.step", i=2):
        pass
    obs.disable()
    ka = [r for r in JsonLinesEventLog.read(a) if r["kind"] == "trace_span"]
    kb = [r for r in JsonLinesEventLog.read(b) if r["kind"] == "trace_span"]
    assert [r["i"] for r in ka] == [1]
    assert [r["i"] for r in kb] == [2]


# -- the acceptance pin: enabled obs adds ZERO runtime events ---------------

def _data(rng, n=400, d=6):
    X = rng.normal(size=(n, d)).astype(np.float32)
    w = rng.uniform(-1, 1, d).astype(np.float32)
    y = (X @ w).astype(np.float32)
    return X, y


def _opt(iters=24, k=4, c=0):
    from tpu_sgd.optimize.gradient_descent import GradientDescent
    from tpu_sgd.utils.events import SGDListener

    o = (GradientDescent().set_num_iterations(iters).set_step_size(0.1)
         .set_mini_batch_fraction(0.5).set_sampling("sliced")
         .set_convergence_tol(0.0).set_seed(7).set_superstep(k)
         .set_listener(SGDListener()))
    if c:
        o.set_residency(c)
    return o


def test_enabled_obs_superstep_driver_zero_added_runtime_events(rng):
    """ISSUE 8 acceptance: tracing+counters ENABLED, the warmed
    superstep driver shows ZERO additional compiles, dispatches, or
    host syncs versus disabled — the disabled baseline measured by the
    analysis twins, the enabled run measured by the promoted counters
    themselves, and the numbers must agree exactly."""
    from tpu_sgd.analysis.runtime import count_dispatches, count_host_syncs

    X, y = _data(rng)
    w0 = np.zeros(6, np.float32)
    o = _opt()
    o.optimize_with_history((X, y), w0)  # warm every program
    with count_host_syncs() as sc, count_dispatches() as dc:
        o.optimize_with_history((X, y), w0)
    base_dispatch, base_sync = dc["n"], sc["n"]

    sink = ListSink()
    obs.enable(sink)  # tracing + counters, the full production config
    try:
        obs_counters.reset()
        o.optimize_with_history((X, y), w0)
        snap = obs_counters.snapshot()
    finally:
        obs.disable()

    def total(kind):
        return sum(v["n"] for k, v in snap.items()
                   if k.endswith("." + kind))

    assert total("dispatch") == base_dispatch
    assert total("host_sync") == base_sync
    assert total("compile") == 0  # warmed: nothing recompiles
    # and the trace really observed the run: one span per superstep
    assert len(sink.spans("train.superstep")) == 24 // 4
    assert all(s["i0"] % 4 == 1 for s in sink.spans("train.superstep"))


def test_enabled_obs_compressed_wire_zero_added_runtime_events(rng):
    """ISSUE 9 satellite: the warmed COMPRESSED host-streamed path
    (top-k + error-feedback wire, fused K) shows ZERO additional
    dispatches or host syncs with tracing+counters enabled — same
    methodology as the superstep pin above — and the wire counters tag
    the feed's bytes by format."""
    from tpu_sgd.analysis.runtime import count_dispatches, count_host_syncs
    from tpu_sgd.optimize.gradient_descent import GradientDescent

    X, y = _data(rng)
    w0 = np.zeros(6, np.float32)

    def mk():
        return (GradientDescent().set_num_iterations(16)
                .set_step_size(0.1).set_mini_batch_fraction(0.5)
                .set_convergence_tol(0.0).set_seed(7)
                .set_host_streaming(True).set_superstep(4)
                .set_ingest_options(wire_compress="topk:0.5"))

    mk().optimize_with_history((X, y), w0)  # warm the fused program
    # disabled compile baseline via the same jax.monitoring funnel the
    # counters use (bench_obs.py methodology): the streamed driver
    # re-jits its per-run fused wrapper, a pre-existing warmed cost the
    # enabled delta must not blame on obs
    from jax._src import monitoring as _monitoring

    base_compiles = [0]

    def _listener(ev_name, dur, **kw):
        if ev_name.endswith("backend_compile_duration"):
            base_compiles[0] += 1

    _monitoring.register_event_duration_secs_listener(_listener)
    try:
        with count_host_syncs() as sc, count_dispatches() as dc:
            mk().optimize_with_history((X, y), w0)
    finally:
        _monitoring._unregister_event_duration_listener_by_callback(
            _listener)
    base_dispatch, base_sync = dc["n"], sc["n"]

    sink = ListSink()
    obs.enable(sink)
    try:
        obs_counters.reset()
        mk().optimize_with_history((X, y), w0)
        snap = obs_counters.snapshot()
    finally:
        obs.disable()
        obs_counters.reset()

    def total(kind):
        return sum(v["n"] for k, v in snap.items()
                   if k.endswith("." + kind))

    assert total("dispatch") == base_dispatch
    assert total("host_sync") == base_sync
    # enabled-minus-disabled compile delta is ZERO (the absolute count
    # is the streamed driver's pre-existing per-run re-jit, measured by
    # the same funnel disabled)
    assert total("compile") == base_compiles[0]
    # the feed's wire bytes are format-tagged (dense-f32 batches here;
    # the compressed segments ride inside the traced program)
    from tpu_sgd.obs.counters import wire_ratios

    ratios = wire_ratios(snap)
    dense_wire = [r for n_, r in ratios.items()
                  if n_.endswith(".dense-f32")]
    assert dense_wire and dense_wire[0]["n"] == 16 // 4
    assert len(sink.spans("train.superstep")) == 16 // 4


def test_enabled_obs_resident_driver_pins_one_dispatch_windows_syncs(rng):
    """The resident acceptance pin via the promoted counters: a warmed
    whole-run dispatch is exactly ONE train.dispatch, host syncs are
    exactly windows+3 scalars (the same pin the analysis twin holds
    with tracing OFF — tests/test_resident.py), compiles are zero, and
    every one lands under the `train` tag."""
    import jax.numpy as jnp

    from tpu_sgd.optimize.resident_driver import ResidentBookkeeper

    X, y = _data(rng)
    w0 = np.zeros(6, np.float32)
    iters, k, c = 64, 4, 2
    o = _opt(iters=iters, k=k, c=c)
    o.optimize_with_history((X, y), w0)  # warm the one compiled program
    key = ("resident", o.gradient, o.updater, o.config, k, c)
    loop = o._run_cache[key]
    windows = iters // (k * c)

    sink = ListSink()
    obs.enable(sink)
    try:
        obs_counters.reset()
        hooks = ResidentBookkeeper(o.config, k, c, losses=[], reg_val=0.0,
                                   start_iter=1)
        loop.run(jnp.asarray(w0), 0.0, 1,
                 (jnp.asarray(X), jnp.asarray(y)), hooks)
        snap = obs_counters.snapshot()
    finally:
        obs.disable()
    assert snap["train.dispatch"]["n"] == 1          # the whole-run program
    assert snap["train.host_sync"]["n"] == windows + 3
    assert sum(v["n"] for n, v in snap.items()
               if n.endswith(".compile")) == 0
    assert snap["train.io_callback"]["n"] == windows
    # every window emitted its span on the callback thread, i0 attrs in
    # cadence order
    wins = sink.spans("train.window")
    assert [w["i0"] for w in wins] == [1 + i * k * c for i in range(windows)]
    assert len(sink.spans("train.resident_dispatch")) == 1


# -- serving: the satellite fields -------------------------------------------

def test_serve_batch_event_carries_enqueue_depth_and_deadline_slack(tmp_path):
    """ISSUE 8 satellite: the batcher records queue depth at enqueue and
    deadline slack at flush; both ride the serve_batch JSONL record and
    old positional constructors keep working."""
    from tpu_sgd.serve.batcher import MicroBatcher
    from tpu_sgd.serve.metrics import ServingMetrics
    from tpu_sgd.utils.events import ServeBatchEvent

    # backward compat: the pre-ISSUE positional constructor still works
    legacy = ServeBatchEvent(3, 2, 4, 0.01, 0, 7)
    assert legacy.enqueue_depth == 0 and legacy.deadline_slack_s == 0.0

    path = str(tmp_path / "serve.jsonl")
    log = JsonLinesEventLog(path)
    metrics = ServingMetrics(listener=log)
    b = MicroBatcher(lambda X: np.asarray(X).sum(axis=1),
                     max_batch=8, max_latency_s=0.01, metrics=metrics)
    futs = [b.submit(np.ones((4,), np.float32)) for _ in range(3)]
    b.stop(drain=True)  # synchronous drain: deterministic single flush
    assert [f.result(1.0) for f in futs] == [4.0] * 3
    log.close()
    rec, = [r for r in JsonLinesEventLog.read(path)
            if r["kind"] == "serve_batch"]
    assert rec["batch_size"] == 3
    # the OLDEST request saw an empty queue at its own enqueue
    assert rec["enqueue_depth"] == 0
    # stop() drained before the 10ms deadline ran out -> positive slack
    # is possible but not guaranteed on a loaded CI box; the field just
    # has to be present and finite
    assert np.isfinite(rec["deadline_slack_s"])


def test_enqueue_depth_reflects_queue_at_each_requests_enqueue():
    from tpu_sgd.serve.batcher import MicroBatcher

    seen = {}

    class Capture:
        def record_reject(self):
            pass

        def record_batch(self, **kw):
            seen.update(kw)

    b = MicroBatcher(lambda X: np.zeros((np.asarray(X).shape[0],)),
                     max_batch=8, max_latency_s=0.01, metrics=Capture())
    for _ in range(4):
        b.submit(np.ones((2,), np.float32))
    b.stop(drain=True)
    # oldest request enqueued into an empty queue; the record carries
    # ITS depth (0), not the last request's (3)
    assert seen["enqueue_depth"] == 0
    assert seen["batch_size"] == 4
    assert "deadline_slack_s" in seen


# -- report pipeline ---------------------------------------------------------

def _mk_trace(tmp_path, name="t.jsonl"):
    """A small synthetic trace with spans, counters, a checkpoint save,
    and a reload — enough surface for every report feature."""
    path = str(tmp_path / name)
    log = JsonLinesEventLog(path)
    log.emit("metric_counters", {"ts": 1.0, "counters": {
        "train.dispatch": {"n": 10, "bytes": 0},
        "serve.reject": {"n": 1, "bytes": 0}}})
    for i, dur in enumerate([0.010, 0.012, 0.011, 0.200]):
        log.emit("trace_span", {
            "name": "serve.batch", "ts": 10.0 + i, "t0_s": 1.0 + i,
            "dur_s": dur, "span_id": i + 1, "parent_id": 0,
            "thread": "flush", "error": None, "batch": 4})
    log.emit("trace_span", {
        "name": "checkpoint.save", "ts": 100.0, "t0_s": 50.0,
        "dur_s": 0.05, "span_id": 90, "parent_id": 0,
        "thread": "MainThread", "error": None, "iteration": 40})
    log.emit("trace_event", {
        "name": "reliability.retry", "ts": 101.0, "t0_s": 51.0,
        "thread": "MainThread", "subsystem": "ingest", "attempt": 1})
    log.emit("serve_reload", {"ts": 130.0, "event": "reloaded",
                              "version": 40, "previous_version": None})
    log.emit("metric_counters", {"ts": 200.0, "counters": {
        "train.dispatch": {"n": 25, "bytes": 0},
        "serve.reject": {"n": 1, "bytes": 0}}})
    log.close()
    return path


def test_report_span_stats_counters_and_staleness(tmp_path):
    records = obs_report.load_trace(_mk_trace(tmp_path))
    stats = obs_report.span_stats(records)
    sb = stats["serve.batch"]
    assert sb["count"] == 4
    assert sb["p50_s"] == 0.011   # nearest-rank over [.010,.011,.012,.200]
    assert sb["p99_s"] == 0.200
    assert sb["max_s"] == 0.200
    deltas = obs_report.counter_deltas(records)
    assert deltas == {"train.dispatch": {"n": 15, "bytes": 0}}  # 25-10; 0-delta dropped
    stale, = obs_report.staleness_samples(records)
    assert stale == {"version": 40, "staleness_s": 30.0}


def test_report_chrome_trace_export(tmp_path):
    records = obs_report.load_trace(_mk_trace(tmp_path))
    doc = obs_report.to_chrome_trace(records)
    evs = doc["traceEvents"]
    complete = [e for e in evs if e["ph"] == "X"]
    instants = [e for e in evs if e["ph"] == "i"]
    metas = [e for e in evs if e["ph"] == "M"]
    assert len(complete) == 5 and len(instants) == 1
    assert {m["args"]["name"] for m in metas} == {"flush", "MainThread"}
    sb = [e for e in complete if e["name"] == "serve.batch"][0]
    assert sb["ts"] == pytest.approx(1.0 * 1e6)
    assert sb["dur"] == pytest.approx(0.010 * 1e6)
    assert sb["args"]["batch"] == 4  # non-core fields ride args
    assert json.dumps(doc)  # serializable as-is


def test_slo_evaluation_pass_fail_and_malformed(tmp_path):
    records = obs_report.load_trace(_mk_trace(tmp_path))
    verdicts = obs_report.evaluate_slos(records, {"slos": [
        {"name": "p50", "metric": "span_p50_s", "span": "serve.batch",
         "max": 0.05},
        {"name": "p99", "metric": "span_p99_s", "span": "serve.batch",
         "max": 0.05},
        {"name": "no-drops", "metric": "counter", "counter": "serve.reject",
         "max": 0},
        {"name": "fresh", "metric": "staleness_s", "max": 60.0},
        {"name": "absent-count", "metric": "span_count",
         "span": "never.fired", "max": 0},
        {"name": "absent-latency", "metric": "span_p99_s",
         "span": "never.fired", "max": 1.0},
    ]})
    by = {v["name"]: v for v in verdicts}
    assert by["p50"]["ok"] and not by["p99"]["ok"]
    assert by["no-drops"]["ok"]          # counter DELTA is 0 across the trace
    assert by["fresh"]["ok"] and by["fresh"]["value"] == 30.0
    assert by["absent-count"]["ok"]      # count bound of 0 passes on absence
    assert not by["absent-latency"]["ok"]  # unevaluable latency ≠ free pass
    with pytest.raises(ValueError):
        obs_report.evaluate_slos(records, {"slos": [
            {"name": "typo", "metric": "span_p42_s", "span": "x", "max": 1}]})
    with pytest.raises(ValueError):
        obs_report.evaluate_slos(records, {"slos": [
            {"name": "no-bound", "metric": "staleness_s"}]})


def test_report_cli_exit_codes_and_chrome_file(tmp_path, capsys):
    trace = _mk_trace(tmp_path)
    slo_ok = tmp_path / "ok.json"
    slo_ok.write_text(json.dumps({"slos": [
        {"name": "p50", "metric": "span_p50_s", "span": "serve.batch",
         "max": 0.05}]}))
    slo_bad = tmp_path / "bad.json"
    slo_bad.write_text(json.dumps({"slos": [
        {"name": "p99", "metric": "span_p99_s", "span": "serve.batch",
         "max": 0.05}]}))
    chrome = str(tmp_path / "chrome.json")
    assert obs_report.main([trace, "--slo", str(slo_ok),
                            "--chrome", chrome]) == 0
    out = capsys.readouterr().out
    assert "SLO PASS: p50" in out and "per-stage breakdown" in out
    with open(chrome) as f:
        assert len(json.load(f)["traceEvents"]) > 0
    assert obs_report.main([trace, "--slo", str(slo_bad)]) == 1
    assert "SLO FAIL: p99" in capsys.readouterr().out
    # usage errors are 2, distinct from violations
    assert obs_report.main([str(tmp_path / "missing.jsonl")]) == 2
    # ... including an unwritable --chrome export path
    assert obs_report.main(
        [trace, "--chrome", str(tmp_path / "no_dir" / "t.json")]) == 2
    assert "cannot write Chrome trace" in capsys.readouterr().err
    garbage = tmp_path / "garbage.json"
    garbage.write_text("{not json")
    assert obs_report.main([trace, "--slo", str(garbage)]) == 2
    # --json emits one machine-readable object
    assert obs_report.main([trace, "--json", "--slo", str(slo_ok)]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["spans"]["serve.batch"]["count"] == 4
    assert doc["slos"][0]["ok"] is True


def test_chaos_soak_default_slos_are_well_formed():
    """The soak's built-in SLO doc must stay on the report schema: every
    entry evaluates (no ValueError) — on an empty trace the structural
    min-bounds simply FAIL, they never error or vacuously pass."""
    from scripts.chaos_soak import DEFAULT_SLOS

    verdicts = obs_report.evaluate_slos([], DEFAULT_SLOS)
    assert len(verdicts) == len(DEFAULT_SLOS["slos"])
    by = {v["name"]: v for v in verdicts}
    # a soak that emitted nothing fails its count gates loudly
    assert not by["train-windows-fired"]["ok"]
    assert not by["callback-windows-counted"]["ok"]


def test_report_tolerates_crash_torn_tail(tmp_path):
    """The soak/crash forensics contract, inherited from read(): a torn
    trailing line is skipped, an interior malformed line still raises."""
    trace = _mk_trace(tmp_path)
    with open(trace, "a") as f:
        f.write('{"kind": "trace_span", "name": "torn')  # no newline
    records = obs_report.load_trace(trace)
    assert len(obs_report.span_stats(records)["serve.batch"]) > 0
    with open(trace, "a") as f:
        f.write('ed"}\n{"interior": garbage}\n{"kind": "x"}\n')
    with pytest.raises(json.JSONDecodeError):
        obs_report.load_trace(trace)
