"""Streaming SGD tests (SURVEY.md §4: StreamingLinearRegressionSuite
analogue): deterministic micro-batch generator, weights move toward truth,
prediction error falls."""

import numpy as np
import pytest

from tpu_sgd.models.streaming import (
    StreamingLinearRegressionWithSGD,
    StreamingLogisticRegressionWithSGD,
)
from tpu_sgd.utils.mlutils import linear_data, logistic_data


def micro_batches(n_batches, n, d, w_true, eps=0.05, seed=0):
    """Deterministic generator — the analogue of ManualClock queued batches."""
    for i in range(n_batches):
        X, y, _ = linear_data(n, d, weights=w_true, eps=eps, seed=seed + i)
        yield X, y


def test_streaming_linear_converges_to_truth():
    d = 8
    w_true = np.linspace(-1, 1, d).astype(np.float32)
    alg = StreamingLinearRegressionWithSGD(step_size=0.3, num_iterations=20)
    alg.set_initial_weights(np.zeros(d, np.float32))
    errs = []
    for X, y in micro_batches(10, 500, d, w_true):
        alg.train_on_batch(X, y)
        errs.append(np.linalg.norm(np.asarray(alg.latest_model().weights) - w_true))
    assert errs[-1] < 0.1
    assert errs[-1] < errs[0]


def test_streaming_prediction_error_falls():
    d = 6
    w_true = np.ones(d, np.float32)
    alg = StreamingLinearRegressionWithSGD(step_size=0.3, num_iterations=20)
    alg.set_initial_weights(np.zeros(d, np.float32))
    Xt, yt, _ = linear_data(500, d, weights=w_true, eps=0.01, seed=99)
    alg.train_on_batch(*next(micro_batches(1, 500, d, w_true, seed=1)))
    early = np.mean((np.asarray(alg.latest_model().predict(Xt)) - yt) ** 2)
    alg.train_on(micro_batches(8, 500, d, w_true, seed=2))
    late = np.mean((np.asarray(alg.latest_model().predict(Xt)) - yt) ** 2)
    assert late < early


def test_streaming_train_on_full_stream():
    d = 4
    w_true = np.asarray([1.0, -2.0, 0.5, 3.0], np.float32)
    alg = StreamingLinearRegressionWithSGD(step_size=0.3, num_iterations=25)
    alg.set_initial_weights(np.zeros(d, np.float32))
    model = alg.train_on(micro_batches(12, 400, d, w_true))
    np.testing.assert_allclose(np.asarray(model.weights), w_true, atol=0.15)


def test_predict_on_uses_latest_model():
    d = 3
    alg = StreamingLinearRegressionWithSGD()
    alg.set_initial_weights(np.ones(d, np.float32))
    stream = [np.eye(d, dtype=np.float32)]
    (pred,) = list(alg.predict_on(iter(stream)))
    np.testing.assert_allclose(pred, np.ones(d), rtol=1e-5)


def test_predict_on_values_keys_preserved():
    d = 2
    alg = StreamingLinearRegressionWithSGD()
    alg.set_initial_weights(np.zeros(d, np.float32))
    out = list(alg.predict_on_values([("a", np.ones((1, d), np.float32))]))
    assert out[0][0] == "a"


def test_uninitialized_model_raises():
    alg = StreamingLinearRegressionWithSGD()
    with pytest.raises(RuntimeError, match="initialized"):
        alg.latest_model()


def test_empty_batch_skipped():
    d = 3
    alg = StreamingLinearRegressionWithSGD()
    alg.set_initial_weights(np.ones(d, np.float32))
    before = np.asarray(alg.latest_model().weights).copy()
    alg.train_on_batch(np.zeros((0, d), np.float32), np.zeros((0,), np.float32))
    np.testing.assert_array_equal(np.asarray(alg.latest_model().weights), before)


def test_streaming_logistic():
    d = 5
    w_true = np.asarray([1.0, -1.0, 2.0, -2.0, 0.5], np.float32)
    alg = StreamingLogisticRegressionWithSGD(step_size=0.5, num_iterations=20)
    alg.set_initial_weights(np.zeros(d, np.float32))
    for i in range(8):
        X, y, _ = logistic_data(600, d, weights=w_true, seed=i)
        alg.train_on_batch(X, y)
    Xt, yt, _ = logistic_data(1000, d, weights=w_true, seed=100)
    acc = np.mean(np.asarray(alg.latest_model().predict(Xt)) == yt)
    bayes = np.mean((Xt @ w_true > 0).astype(np.float32) == yt)
    assert acc > bayes - 0.03


# ---- driver recovery: checkpoint / resume (SURVEY.md §5.4c) ---------------

def _replayable_stream(d=12, batches=10, rows=500):
    w_true = np.linspace(-1, 1, d).astype(np.float32)
    out = []
    for i in range(batches):
        r = np.random.default_rng(100 + i)
        X = r.normal(size=(rows, d)).astype(np.float32)
        y = (X @ w_true + 0.05 * r.normal(size=rows)).astype(np.float32)
        out.append((X, y))
    return out, w_true


def test_streaming_checkpoint_resume_reproduces_run(tmp_path):
    """Kill the stream after batch j, resume from the checkpoint directory,
    replay: weights AND loss history must equal the uninterrupted run's
    bitwise (each micro-batch update is deterministic in (warm weights,
    batch))."""
    from tpu_sgd.models.streaming import StreamingLinearRegressionWithSGD

    stream, w_true = _replayable_stream()
    kwargs = dict(step_size=0.3, num_iterations=20)

    full = StreamingLinearRegressionWithSGD(**kwargs)
    full.set_initial_weights(np.zeros(12, np.float32))
    full.set_checkpoint(str(tmp_path / "full"), every=1)
    full.train_on(stream)

    # interrupted driver: consumes only the first 4 batches, then "dies"
    part = StreamingLinearRegressionWithSGD(**kwargs)
    part.set_initial_weights(np.zeros(12, np.float32))
    part.set_checkpoint(str(tmp_path / "resume"), every=1)
    part.train_on(stream[:4])
    del part

    # restarted driver: resume + replay the SAME stream from the start
    res = StreamingLinearRegressionWithSGD.resume_from(
        str(tmp_path / "resume"), **kwargs)
    assert res._batch_count == 4
    res.train_on(stream)
    assert res._batch_count == 10

    np.testing.assert_array_equal(
        np.asarray(res.latest_model().weights),
        np.asarray(full.latest_model().weights))
    assert res.latest_model().intercept == full.latest_model().intercept
    np.testing.assert_array_equal(np.asarray(res.loss_history),
                                  np.asarray(full.loss_history))
    assert len(res.loss_history) == 10


def test_streaming_resume_preserves_intercept(tmp_path):
    from tpu_sgd.models.streaming import StreamingLinearRegressionWithSGD

    stream, _ = _replayable_stream(batches=3)
    alg = StreamingLinearRegressionWithSGD(step_size=0.3, num_iterations=10)
    alg.algorithm.set_intercept(True)
    alg.set_initial_weights(np.zeros(12, np.float32), intercept=0.5)
    alg.set_checkpoint(str(tmp_path), every=1)
    alg.train_on(stream)
    want = alg.latest_model().intercept

    res = StreamingLinearRegressionWithSGD.resume_from(
        str(tmp_path), step_size=0.3, num_iterations=10)
    res.algorithm.set_intercept(True)
    assert res.latest_model().intercept == want


def test_streaming_resume_empty_dir_raises(tmp_path):
    from tpu_sgd.models.streaming import StreamingLinearRegressionWithSGD

    with pytest.raises(FileNotFoundError, match="no checkpoint"):
        StreamingLinearRegressionWithSGD.resume_from(str(tmp_path / "x"))


def test_streaming_checkpoint_every_k(tmp_path):
    import glob as _glob

    from tpu_sgd.models.streaming import StreamingLinearRegressionWithSGD
    from tpu_sgd.utils.checkpoint import CheckpointManager

    stream, _ = _replayable_stream(batches=6)
    alg = StreamingLinearRegressionWithSGD(step_size=0.3, num_iterations=5)
    alg.set_initial_weights(np.zeros(12, np.float32))
    alg.set_checkpoint(CheckpointManager(str(tmp_path), keep=10), every=2)
    alg.train_on(stream)
    files = sorted(_glob.glob(str(tmp_path / "ckpt_*.npz")))
    # every=2 over 6 batches -> checkpoints at batch 2, 4, 6
    assert [int(f[-12:-4]) for f in files] == [2, 4, 6]


def test_streaming_resume_live_stream_skip_zero(tmp_path):
    """A live stream yields only NEW batches: skip=0 must train them all
    instead of dropping the first batch_count."""
    from tpu_sgd.models.streaming import StreamingLinearRegressionWithSGD

    stream, _ = _replayable_stream(batches=6)
    alg = StreamingLinearRegressionWithSGD(step_size=0.3, num_iterations=5)
    alg.set_initial_weights(np.zeros(12, np.float32))
    alg.set_checkpoint(str(tmp_path), every=1)
    alg.train_on(stream[:3])

    res = StreamingLinearRegressionWithSGD.resume_from(
        str(tmp_path), step_size=0.3, num_iterations=5)
    res.train_on(stream[3:], skip=0)  # live continuation
    assert res._batch_count == 6
    # and the result matches the replayed-resume path on the same data
    res2 = StreamingLinearRegressionWithSGD.resume_from(
        str(tmp_path), step_size=0.3, num_iterations=5)
    res2.train_on(stream)  # replay: default skip drops first 3
    np.testing.assert_array_equal(
        np.asarray(res.latest_model().weights),
        np.asarray(res2.latest_model().weights))


def test_streaming_resume_empty_batches_stay_aligned(tmp_path):
    """An empty micro-batch advances the stream position (no update), so
    a resumed replay's skip cannot double-train the batch after it
    (review r4 finding)."""
    from tpu_sgd.models.streaming import StreamingLinearRegressionWithSGD

    stream, _ = _replayable_stream(batches=5)
    d = stream[0][0].shape[1]
    empty = (np.zeros((0, d), np.float32), np.zeros((0,), np.float32))
    stream = [stream[0], empty] + stream[1:]  # empty at position 1
    kwargs = dict(step_size=0.3, num_iterations=10)

    full = StreamingLinearRegressionWithSGD(**kwargs)
    full.set_initial_weights(np.zeros(d, np.float32))
    full.train_on(stream)

    part = StreamingLinearRegressionWithSGD(**kwargs)
    part.set_initial_weights(np.zeros(d, np.float32))
    part.set_checkpoint(str(tmp_path), every=1)
    part.train_on(stream[:3])  # consumes batch0, empty, batch1
    assert part._batch_count == 3  # stream POSITION, empties included

    res = StreamingLinearRegressionWithSGD.resume_from(str(tmp_path),
                                                       **kwargs)
    res.train_on(stream)
    np.testing.assert_array_equal(
        np.asarray(res.latest_model().weights),
        np.asarray(full.latest_model().weights))
    np.testing.assert_array_equal(np.asarray(res.loss_history),
                                  np.asarray(full.loss_history))


def test_streaming_resume_rejects_non_streaming_checkpoint(tmp_path):
    from tpu_sgd.models.streaming import StreamingLinearRegressionWithSGD
    from tpu_sgd.utils.checkpoint import CheckpointManager

    CheckpointManager(str(tmp_path)).save(
        5, np.zeros(4, np.float32), 0.0, np.zeros(5), config_key="sgd:cfg")
    with pytest.raises(ValueError, match="non-streaming checkpoint"):
        StreamingLinearRegressionWithSGD.resume_from(str(tmp_path))


def test_streaming_resume_family_mismatch_warns(tmp_path):
    import warnings as _warnings

    from tpu_sgd.models.streaming import (
        StreamingLinearRegressionWithSGD,
        StreamingLogisticRegressionWithSGD,
    )

    alg = StreamingLinearRegressionWithSGD(step_size=0.3, num_iterations=5)
    alg.set_initial_weights(np.zeros(6, np.float32))
    alg.set_checkpoint(str(tmp_path), every=1)
    X = np.random.default_rng(0).normal(size=(64, 6)).astype(np.float32)
    y = (X @ np.ones(6, np.float32)).astype(np.float32)
    alg.train_on_batch(X, y)

    with _warnings.catch_warnings(record=True) as rec:
        _warnings.simplefilter("always")
        StreamingLogisticRegressionWithSGD.resume_from(str(tmp_path))
    assert any("construct the same streaming" in str(r.message)
               for r in rec)


def test_checkpoint_history_tail_bounds_persisted_history(tmp_path, rng):
    """history_tail caps per-checkpoint serialization for unbounded
    streams (full-history default stays bitwise; the tail trades the
    resumed history's head for O(N) instead of O(N^2) cumulative I/O)."""
    from tpu_sgd.utils.checkpoint import CheckpointManager

    alg = (StreamingLinearRegressionWithSGD(step_size=0.3,
                                            num_iterations=5)
           .set_initial_weights(np.zeros(4, np.float32))
           .set_checkpoint(str(tmp_path / "ck"), every=1, history_tail=3))
    w = rng.uniform(-1, 1, 4).astype(np.float32)
    for i in range(6):
        X = rng.normal(size=(64, 4)).astype(np.float32)
        y = (X @ w).astype(np.float32)
        alg.train_on_batch(X, y)
    assert len(alg.loss_history) == 6  # in-memory history stays full
    st = CheckpointManager(str(tmp_path / "ck")).restore()
    assert st["iteration"] == 6
    assert len(st["loss_history"]) == 3  # persisted history bounded
    with pytest.raises(ValueError, match="history_tail"):
        StreamingLinearRegressionWithSGD().set_checkpoint(
            str(tmp_path / "ck2"), history_tail=0)
