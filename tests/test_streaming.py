"""Streaming SGD tests (SURVEY.md §4: StreamingLinearRegressionSuite
analogue): deterministic micro-batch generator, weights move toward truth,
prediction error falls."""

import numpy as np
import pytest

from tpu_sgd.models.streaming import (
    StreamingLinearRegressionWithSGD,
    StreamingLogisticRegressionWithSGD,
)
from tpu_sgd.utils.mlutils import linear_data, logistic_data


def micro_batches(n_batches, n, d, w_true, eps=0.05, seed=0):
    """Deterministic generator — the analogue of ManualClock queued batches."""
    for i in range(n_batches):
        X, y, _ = linear_data(n, d, weights=w_true, eps=eps, seed=seed + i)
        yield X, y


def test_streaming_linear_converges_to_truth():
    d = 8
    w_true = np.linspace(-1, 1, d).astype(np.float32)
    alg = StreamingLinearRegressionWithSGD(step_size=0.3, num_iterations=20)
    alg.set_initial_weights(np.zeros(d, np.float32))
    errs = []
    for X, y in micro_batches(10, 500, d, w_true):
        alg.train_on_batch(X, y)
        errs.append(np.linalg.norm(np.asarray(alg.latest_model().weights) - w_true))
    assert errs[-1] < 0.1
    assert errs[-1] < errs[0]


def test_streaming_prediction_error_falls():
    d = 6
    w_true = np.ones(d, np.float32)
    alg = StreamingLinearRegressionWithSGD(step_size=0.3, num_iterations=20)
    alg.set_initial_weights(np.zeros(d, np.float32))
    Xt, yt, _ = linear_data(500, d, weights=w_true, eps=0.01, seed=99)
    alg.train_on_batch(*next(micro_batches(1, 500, d, w_true, seed=1)))
    early = np.mean((np.asarray(alg.latest_model().predict(Xt)) - yt) ** 2)
    alg.train_on(micro_batches(8, 500, d, w_true, seed=2))
    late = np.mean((np.asarray(alg.latest_model().predict(Xt)) - yt) ** 2)
    assert late < early


def test_streaming_train_on_full_stream():
    d = 4
    w_true = np.asarray([1.0, -2.0, 0.5, 3.0], np.float32)
    alg = StreamingLinearRegressionWithSGD(step_size=0.3, num_iterations=25)
    alg.set_initial_weights(np.zeros(d, np.float32))
    model = alg.train_on(micro_batches(12, 400, d, w_true))
    np.testing.assert_allclose(np.asarray(model.weights), w_true, atol=0.15)


def test_predict_on_uses_latest_model():
    d = 3
    alg = StreamingLinearRegressionWithSGD()
    alg.set_initial_weights(np.ones(d, np.float32))
    stream = [np.eye(d, dtype=np.float32)]
    (pred,) = list(alg.predict_on(iter(stream)))
    np.testing.assert_allclose(pred, np.ones(d), rtol=1e-5)


def test_predict_on_values_keys_preserved():
    d = 2
    alg = StreamingLinearRegressionWithSGD()
    alg.set_initial_weights(np.zeros(d, np.float32))
    out = list(alg.predict_on_values([("a", np.ones((1, d), np.float32))]))
    assert out[0][0] == "a"


def test_uninitialized_model_raises():
    alg = StreamingLinearRegressionWithSGD()
    with pytest.raises(RuntimeError, match="initialized"):
        alg.latest_model()


def test_empty_batch_skipped():
    d = 3
    alg = StreamingLinearRegressionWithSGD()
    alg.set_initial_weights(np.ones(d, np.float32))
    before = np.asarray(alg.latest_model().weights).copy()
    alg.train_on_batch(np.zeros((0, d), np.float32), np.zeros((0,), np.float32))
    np.testing.assert_array_equal(np.asarray(alg.latest_model().weights), before)


def test_streaming_logistic():
    d = 5
    w_true = np.asarray([1.0, -1.0, 2.0, -2.0, 0.5], np.float32)
    alg = StreamingLogisticRegressionWithSGD(step_size=0.5, num_iterations=20)
    alg.set_initial_weights(np.zeros(d, np.float32))
    for i in range(8):
        X, y, _ = logistic_data(600, d, weights=w_true, seed=i)
        alg.train_on_batch(X, y)
    Xt, yt, _ = logistic_data(1000, d, weights=w_true, seed=100)
    acc = np.mean(np.asarray(alg.latest_model().predict(Xt)) == yt)
    bayes = np.mean((Xt @ w_true > 0).astype(np.float32) == yt)
    assert acc > bayes - 0.03
