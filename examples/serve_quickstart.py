#!/usr/bin/env python
"""Serving quickstart: train -> checkpoint -> serve -> hot-reload.

The full inference loop in one runnable script (CPU-friendly):

  1. a ``StreamingLinearRegressionWithSGD`` trainer consumes micro-batches
     and publishes every model update as a numbered checkpoint;
  2. a ``ModelRegistry`` + ``Server`` turn that checkpoint directory into
     a micro-batching endpoint;
  3. the trainer keeps learning WHILE the endpoint answers — each publish
     hot-swaps the serving weights atomically, and the script shows the
     serving error dropping as fresher versions arrive.

Run: ``JAX_PLATFORMS=cpu python examples/serve_quickstart.py``
"""

import os
import sys
import tempfile

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tpu_sgd.models import StreamingLinearRegressionWithSGD  # noqa: E402
from tpu_sgd.serve import ModelRegistry, Server  # noqa: E402
from tpu_sgd.utils import JsonLinesEventLog  # noqa: E402


def main():
    rng = np.random.default_rng(7)
    d = 32
    w_true = rng.normal(size=d).astype(np.float32)

    def micro_batch(n=512):
        X = rng.normal(size=(n, d)).astype(np.float32)
        y = X @ w_true + 0.05 * rng.normal(size=n).astype(np.float32)
        return X, y

    ckpt_dir = tempfile.mkdtemp(prefix="tpu_sgd_serve_")
    print(f"checkpoints -> {ckpt_dir}")

    # 1. the training side publishes through the checkpoint manager
    trainer = StreamingLinearRegressionWithSGD(
        step_size=0.4, num_iterations=25
    )
    trainer.set_initial_weights(np.zeros(d, np.float32))
    trainer.set_checkpoint(ckpt_dir, every=1)

    # 2. the serving side consumes the same directory
    registry = ModelRegistry(ckpt_dir, trainer.algorithm.create_model)
    trainer.add_model_update_listener(registry.on_model_update)

    event_log = JsonLinesEventLog(os.path.join(ckpt_dir, "serve.jsonl"))
    trainer.train_on_batch(*micro_batch())  # version 1 exists before serving

    X_test = rng.normal(size=(256, d)).astype(np.float32)
    y_test = X_test @ w_true

    with Server(registry=registry, max_latency_s=0.002,
                event_log=event_log) as server:
        # 3. interleave training and serving: each published version serves
        for round_ in range(4):
            futures = [server.submit(X_test[i]) for i in range(64)]
            preds = np.asarray([f.result(timeout=30) for f in futures])
            mse = float(np.mean((preds - y_test[:64]) ** 2))
            print(f"serving model v{server.model_version}: "
                  f"held-out MSE {mse:.4f}")
            trainer.train_on_batch(*micro_batch())  # publish a new version

        # bulk scoring bypasses the queue but uses the same bucketed path
        bulk = server.predict_batch(X_test)
        print(f"bulk scored {bulk.shape[0]} rows on "
              f"v{server.model_version}; final MSE "
              f"{float(np.mean((bulk - y_test) ** 2)):.4f}")
        print("metrics:", server.metrics.snapshot())
    event_log.close()


if __name__ == "__main__":
    main()
