#!/usr/bin/env python
"""End-to-end tour for users coming from the reference framework.

One runnable script covering the workflow a `spark-parallelized-sgd`
(Spark MLlib SGD) user expects, on the TPU-native stack: load data ->
summarize -> scale -> train (single-device and 8-way data-parallel mesh)
-> evaluate -> persist -> stream.  Every API here maps 1:1 to a reference
surface (see PARITY.md for the ledger).

Run on CPU (8 virtual devices):
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/user_guide.py
"""
import os
import tempfile

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
import jax  # noqa: E402

if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
    jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

import tpu_sgd  # noqa: E402
from tpu_sgd import (BinaryClassificationMetrics, Normalizer,  # noqa: E402
                     RegressionMetrics, StandardScaler, col_stats, corr,
                     data_mesh)
from tpu_sgd.models.classification import (  # noqa: E402
    LogisticRegressionWithSGD, SVMWithSGD)
from tpu_sgd.models.regression import (  # noqa: E402
    LinearRegressionWithLBFGS, LinearRegressionWithSGD)
from tpu_sgd.models.streaming import (  # noqa: E402
    StreamingLinearRegressionWithSGD)
from tpu_sgd.ops.updaters import L1Updater  # noqa: E402
from tpu_sgd.utils.mlutils import (linear_data, load_libsvm_file,  # noqa: E402
                                   logistic_data, save_as_libsvm_file)


def main():
    rng = np.random.default_rng(0)
    tmp = tempfile.mkdtemp(prefix="tpu_sgd_guide_")

    # --- 1. Data I/O: LIBSVM round-trip (MLUtils.loadLibSVMFile) ---------
    X, y, w_true = linear_data(5_000, 20, seed=3)
    path = os.path.join(tmp, "train.libsvm")
    save_as_libsvm_file(path, X, y)
    X, y = load_libsvm_file(path)
    print(f"1. loaded {X.shape[0]}x{X.shape[1]} from LIBSVM")

    # --- 2. Statistics (Statistics.colStats / corr) ----------------------
    s = col_stats(X)
    C = corr(X[:, :4])
    print(f"2. colStats: mean[0]={s.mean[0]:.3f} var[0]={s.variance[0]:.3f}; "
          f"corr(0,1)={C[0, 1]:.3f}")

    # --- 3. Feature transformers (StandardScaler / Normalizer) -----------
    badly_scaled = X * np.logspace(0, 3, X.shape[1], dtype=np.float32)
    scaler = StandardScaler().fit(badly_scaled)
    Xs = np.asarray(scaler.transform(badly_scaled))
    rows = np.asarray(Normalizer().transform(X))
    print(f"3. scaled columns to unit std (col0 std {Xs[:, 0].std():.3f}); "
          f"row norms -> {np.linalg.norm(rows, axis=1)[0]:.3f}")

    # --- 4. Train: linear regression, SGD then quasi-Newton --------------
    model = LinearRegressionWithSGD.train((X, y), num_iterations=80,
                                          step_size=0.5)
    rm = RegressionMetrics(np.asarray(model.predict(X)), y)
    # harness-level feature scaling (GLA.useFeatureScaling) + LBFGS
    model2 = LinearRegressionWithLBFGS.train(
        (badly_scaled, y), feature_scaling=True
    )
    print(f"4. SGD RMSE {rm.root_mean_squared_error:.4f} "
          f"R2 {rm.r2:.4f}; scaled-LBFGS w_err "
          f"{np.abs(np.asarray(model2.weights) * np.logspace(0, 3, 20) - w_true).max():.2e}")

    # --- 5. 8-way data parallelism (treeAggregate -> lax.psum on a mesh) -
    mesh = data_mesh()
    model_dp = LinearRegressionWithSGD.train(
        (X, y), num_iterations=80, step_size=0.5, mesh=mesh
    )
    drift = float(np.abs(
        np.asarray(model_dp.weights) - np.asarray(model.weights)
    ).max())
    print(f"5. {dict(mesh.shape)}-way DP mesh: max |w_dp - w_single| = "
          f"{drift:.2e} (bitwise-parity design)")

    # --- 5b. Sufficient statistics: the same iterations, ~20x faster -----
    # Least-squares gradients from a one-time block-prefix Gram pass
    # (ops/gram.py) — exact trajectory, measured 1.63 -> 0.08 ms/iter on
    # real TPU hardware; composes with intercept and with the mesh above.
    model_ss = LinearRegressionWithSGD.train(
        (X, y), num_iterations=80, step_size=0.5, sufficient_stats=True
    )
    drift_ss = float(np.abs(
        np.asarray(model_ss.weights) - np.asarray(model.weights)
    ).max())
    print(f"5b. sufficient_stats=True: max |w_ss - w| = {drift_ss:.2e} "
          "(same windows, same math)")

    # --- 5c. Beyond-HBM: streamed statistics, zero-transfer iterations --
    # One pass over host data builds the prefix-Gram stack on device; the
    # returned VIRTUAL GramData (no rows!) then trains with block-aligned
    # windows at device speed — the full-size config-4 answer.
    from tpu_sgd.ops import GramLeastSquaresGradient
    from tpu_sgd import GradientDescent, SimpleUpdater

    gg = GramLeastSquaresGradient.build_streamed(X, y, block_rows=256)
    opt_v = (GradientDescent(gg, SimpleUpdater())
             .set_step_size(0.5).set_num_iterations(80)
             .set_mini_batch_fraction(0.25).set_sampling("sliced"))
    w_v, _ = opt_v.optimize_with_history((gg.data, y), np.zeros(X.shape[1]))
    drift_v = float(np.abs(np.asarray(w_v) - np.asarray(model.weights)).max())
    print(f"5c. streamed stats (virtual rows): |w_v - w| = {drift_v:.2e} "
          "(block-aligned windows)")

    # --- 5d. Execution planning (round 4): train() picks the schedule ----
    # With zero schedule flags the planner (tpu_sgd/plan.py — the
    # DAGScheduler/cache() analogue) probes shape/dtype/sampling/free HBM
    # and picks resident/gram/partial/streamed itself, logging one
    # "plan: ..." line; schedule="..." forces one, manual flags always win.
    alg = LinearRegressionWithSGD(0.5, 80)
    alg.run((X, y))
    lp = alg.optimizer.last_plan
    from tpu_sgd.plan import plan as plan_fn

    big = plan_fn(10_000_000, 1000, itemsize=2, gram_able=True,
                  sampling="sliced", mini_batch_fraction=0.1,
                  num_iterations=1000, free_hbm=12e9)
    print(f"5d. auto-plan here: {lp.schedule}; the 10Mx1000 config-4 "
          f"shape would plan: {big.schedule}")

    # --- 5e. Beyond-HBM quasi-Newton for ANY loss (round 5) --------------
    # Least squares has the statistics shortcut above; every OTHER loss
    # gets the chunked treeAggregate CostFun: set_host_streaming on
    # LBFGS/OWL-QN streams each full-batch cost/gradient/line-search
    # evaluation through the device in chunks — the planner picks it
    # automatically for beyond-HBM logistic/hinge/multinomial fits.
    from tpu_sgd import LBFGS, SquaredL2Updater
    from tpu_sgd.ops.gradients import LogisticGradient

    yb = (np.asarray(y) > np.median(np.asarray(y))).astype(np.float32)
    opt_cf = (LBFGS(LogisticGradient(), SquaredL2Updater(),
                    reg_param=0.01, max_num_iterations=8)
              .set_host_streaming(True, batch_rows=512))
    w_cf, hist_cf = opt_cf.optimize_with_history(
        (np.asarray(X), yb), np.zeros(X.shape[1], np.float32))
    print(f"5e. host-streamed chunked CostFun (logistic LBFGS): loss "
          f"{hist_cf[0]:.3f} -> {hist_cf[-1]:.3f} in {len(hist_cf) - 1} "
          "iterations, rows never device-resident in full")

    # --- 5e2. Beyond-HBM EXACT least squares (round 5) -------------------
    # The normal solver streams its Gram totals from host chunks (O(d^2)
    # carry, every row counted) and solves exactly — and it does this
    # AUTOMATICALLY when the data exceeds the device budget.
    from tpu_sgd.models.regression import LinearRegressionWithNormal

    alg_n = LinearRegressionWithNormal(reg_param=0.0)
    alg_n.optimizer.set_host_streaming(True, batch_rows=4096)  # or let AUTO decide
    model_n = alg_n.run((X, y))
    w_err_n = float(np.linalg.norm(
        np.asarray(model_n.weights) - np.asarray(model.weights)))
    print(f"5e2. streamed-totals exact solve: |w_normal - w_sgd| = "
          f"{w_err_n:.4f} (host chunks, zero full-matrix residency)")

    # --- 5f. Planner self-calibration (round 5) --------------------------
    # The planner's decision-boundary constants are calibrated to ONE
    # environment; a ~2 s probe re-measures the two rates that move the
    # boundaries (on-device bandwidth, host feed) for THIS machine.
    from tpu_sgd.plan import CostModel

    cm = CostModel.calibrate(copy_mb=8, feed_mb=8)
    print(f"5f. calibrated cost model: hbm={cm.hbm_gb_s:.1f} GB/s, "
          f"host feed={cm.host_feed_gb_s:.2f} GB/s "
          "(pass cost_model=cm to plan()/plan_for())")

    # --- 6. Classify + evaluate (BinaryClassificationMetrics) ------------
    Xc, yc, _ = logistic_data(4_000, 15, seed=5)
    clf = LogisticRegressionWithSGD.train((Xc, yc), num_iterations=60)
    clf.clear_threshold()
    auc = BinaryClassificationMetrics(
        np.asarray(clf.predict(Xc)), yc
    ).area_under_roc
    svm = SVMWithSGD.train((Xc, yc), num_iterations=60, updater=L1Updater())
    svm_acc = float(np.mean(np.asarray(svm.predict(Xc)) == yc))
    print(f"6. logistic AUC {auc:.4f}; L1-SVM acc {svm_acc:.4f}")

    # --- 7. Persistence (Saveable/Loader) --------------------------------
    from tpu_sgd.models.classification import LogisticRegressionModel

    mpath = os.path.join(tmp, "model")
    clf.set_threshold(0.5)
    clf.save(mpath)
    reloaded = LogisticRegressionModel.load(mpath)
    agree = float(np.mean(
        np.asarray(reloaded.predict(Xc)) == np.asarray(clf.predict(Xc))
    ))
    print(f"7. save/load round-trip: predictions agree {agree:.0%}")

    # --- 8. Streaming (StreamingLinearRegressionWithSGD.trainOn) ---------
    stream = StreamingLinearRegressionWithSGD(
        step_size=0.5, num_iterations=20
    ).set_initial_weights(np.zeros(20, np.float32))
    for t in range(5):
        lo, hi = t * 1000, (t + 1) * 1000
        stream.train_on_batch(X[lo:hi], y[lo:hi])
    w_err = float(np.abs(
        np.asarray(stream.latest_model().weights) - w_true
    ).max())
    print(f"8. streaming: w_err {w_err:.3f} after 5 micro-batches")

    # --- 8b. Streaming driver recovery (round 4): checkpoint + resume ----
    # The DStream-checkpointing analogue: persist (model, stream position)
    # every K micro-batches; a restarted driver resumes mid-stream and a
    # replayed stream reproduces the uninterrupted run bitwise.
    ckdir = os.path.join(tmp, "stream_ck")
    batches = [(X[t * 1000:(t + 1) * 1000], y[t * 1000:(t + 1) * 1000])
               for t in range(5)]
    s1 = StreamingLinearRegressionWithSGD(
        step_size=0.5, num_iterations=20
    ).set_initial_weights(np.zeros(20, np.float32)).set_checkpoint(ckdir)
    s1.train_on(batches[:3])  # ... driver "dies" here ...
    s2 = StreamingLinearRegressionWithSGD.resume_from(
        ckdir, step_size=0.5, num_iterations=20)
    s2.train_on(batches)  # replay: already-consumed batches are skipped
    match = np.array_equal(np.asarray(s2.latest_model().weights),
                           np.asarray(stream.latest_model().weights))
    print(f"8b. resumed mid-stream at batch {3}; replay reproduces the "
          f"uninterrupted run: {match}")
    print("user guide complete")


if __name__ == "__main__":
    main()
