#!/usr/bin/env python
"""Reliability quickstart: inject faults, watch training survive them.

Three demos in one runnable script (CPU-friendly):

  1. **failpoints** — arm a deterministic fault at a real hook site and
     watch a retry policy heal it in place;
  2. **crash-resume** — kill a streamed SGD run mid-iteration with an
     injected fault, resume under the ``TrainingSupervisor``, and verify
     the final weights are BITWISE identical to a fault-free run;
  3. **preemption** — request a SIGTERM-style stop mid-run; the run
     checkpoints the current iteration, exits cleanly, and a second
     ``run()`` finishes from exactly there.

Run: ``JAX_PLATFORMS=cpu python examples/reliability_quickstart.py``
For the full train→checkpoint→serve cycle under randomized fault
schedules, see ``scripts/chaos_soak.py``.
"""

import os
import sys
import tempfile
import threading

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tpu_sgd.optimize.gradient_descent import GradientDescent  # noqa: E402
from tpu_sgd.reliability import (  # noqa: E402
    RetryPolicy,
    TrainingSupervisor,
    fail_nth,
    inject_faults,
)
from tpu_sgd.utils.checkpoint import CheckpointManager  # noqa: E402


def make_optimizer():
    return (GradientDescent()
            .set_num_iterations(30).set_step_size(0.1)
            .set_mini_batch_fraction(0.5).set_sampling("sliced")
            .set_convergence_tol(0.0).set_seed(7)
            .set_host_streaming(True))


def main():
    rng = np.random.default_rng(0)
    X = rng.normal(size=(2048, 16)).astype(np.float32)
    y = (X @ rng.normal(size=16) + 0.01 * rng.normal(size=2048)
         ).astype(np.float32)
    w0 = np.zeros(16, np.float32)

    # ---- 0. the fault-free reference -------------------------------------
    w_ref, h_ref = make_optimizer().optimize_with_history((X, y), w0)
    print(f"reference run: {len(h_ref)} iterations, "
          f"final loss {h_ref[-1]:.5f}")

    # ---- 1. failpoint + in-place retry ------------------------------------
    # every transferred batch passes the io.device_put failpoint; arm a
    # one-shot fault there and let the ingest retry policy heal it
    opt = make_optimizer().set_ingest_options(
        retry=RetryPolicy(max_attempts=3, base_backoff_s=0.01, seed=0))
    with inject_faults({"io.device_put": fail_nth(5)}):
        w, h = opt.optimize_with_history((X, y), w0)
    assert np.array_equal(np.asarray(w), np.asarray(w_ref))
    print("demo 1: transient device_put fault healed by retry — "
          "weights bitwise equal")

    # ---- 2. crash-resume under the supervisor ------------------------------
    ckpt_dir = tempfile.mkdtemp(prefix="tpu_sgd_reliability_")
    sup = TrainingSupervisor(
        make_optimizer(),
        checkpoint_manager=CheckpointManager(ckpt_dir),
        checkpoint_every=5,
        retry=RetryPolicy(max_attempts=5, base_backoff_s=0.01, seed=0),
        install_signal_handlers=False,  # demo drives preemption itself
    )
    with inject_faults({"optimize.streamed.step": fail_nth(17)}):
        result = sup.run((X, y), w0)
    assert result.completed
    assert np.array_equal(np.asarray(result.weights), np.asarray(w_ref))
    print(f"demo 2: crashed at iteration 17, resumed from checkpoint, "
          f"finished in {result.attempts} attempts — weights bitwise equal")

    # ---- 3. preemption: checkpoint + clean exit + resume -------------------
    ckpt_dir2 = tempfile.mkdtemp(prefix="tpu_sgd_reliability_")
    opt3 = make_optimizer()
    sup3 = TrainingSupervisor(
        opt3, checkpoint_manager=CheckpointManager(ckpt_dir2),
        checkpoint_every=100,  # cadence never fires: the preempt saves
        install_signal_handlers=False)
    # simulate the cluster's SIGTERM arriving mid-run (in production the
    # supervisor's signal handler calls request_preempt for you)
    threading.Timer(0.15, sup3.request_preempt).start()
    first = sup3.run((X, y), w0)
    if first.status == "preempted":
        print(f"demo 3: preempted at iteration {first.preempted_at}, "
              "state checkpointed, exited cleanly")
        second = sup3.run((X, y), w0)  # the replacement host's restart
        assert second.completed
        assert np.array_equal(np.asarray(second.weights),
                              np.asarray(w_ref))
        print("demo 3: resumed run finished — weights bitwise equal")
    else:  # tiny dataset may outrun the timer on a fast host
        print("demo 3: run finished before the simulated SIGTERM landed")


if __name__ == "__main__":
    main()
