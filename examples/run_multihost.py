#!/usr/bin/env python
"""Launch a 2-process multi-host training job on one machine.

The multi-host bring-up the framework documents (SURVEY.md §5.8: DCN
across hosts after ``jax.distributed.initialize``) demonstrated end to
end with REAL processes: this launcher spawns two worker processes that
form a ``jax.distributed`` job over localhost (CPU backend + gloo
collectives standing in for a TPU pod's ICI/DCN), each holding its own
local rows — the analogue of Spark executors reading their own input
splits — and trains one model over the combined 8-device global mesh.

On an actual TPU pod the same worker code runs unchanged with ONE line
different per host (no explicit coordinator args — they auto-detect):

    initialize_distributed()            # on every host
    mesh = global_data_mesh()
    LinearRegressionWithSGD.train((X_local, y_local), mesh=mesh)

Usage:  python examples/run_multihost.py
"""

from __future__ import annotations

import os
import socket
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_WORKER = r"""
import sys
import numpy as np

proc_id, num_procs, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]

import jax
jax.config.update("jax_platforms", "cpu")          # demo runs on CPU
jax.config.update("jax_cpu_collectives_implementation", "gloo")

from tpu_sgd.parallel.distributed import (
    global_data_mesh,
    initialize_distributed,
    process_count,
)

initialize_distributed(
    coordinator_address=f"127.0.0.1:{port}",
    num_processes=num_procs,
    process_id=proc_id,
)
assert process_count() == num_procs

from tpu_sgd.models import LinearRegressionWithSGD

# Each process generates ITS OWN rows (different seeds) — no process ever
# sees another's data; only gradient all-reduces cross the process
# boundary at train time.
rng = np.random.default_rng(100 + proc_id)
w_true = np.linspace(-1, 1, 16).astype(np.float32)   # same truth everywhere
n_local = 4000 + 1000 * proc_id                      # uneven on purpose
X = rng.normal(size=(n_local, 16)).astype(np.float32)
y = (X @ w_true + 0.05 * rng.normal(size=n_local)).astype(np.float32)

model = LinearRegressionWithSGD.train(
    (X, y), num_iterations=150, step_size=0.4, mini_batch_fraction=1.0,
    mesh=global_data_mesh(),
)
err = float(np.linalg.norm(np.asarray(model.weights) - w_true))
print(f"process {proc_id}: {len(jax.devices())}-device global mesh, "
      f"local rows={n_local}, w_err={err:.4f}", flush=True)
assert err < 0.05
"""


def main() -> None:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        XLA_FLAGS="--xla_force_host_platform_device_count=4",
        PYTHONPATH=os.pathsep.join(
            p for p in (REPO, os.environ.get("PYTHONPATH")) if p
        ),
    )
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _WORKER, str(i), "2", str(port)], env=env
        )
        for i in range(2)
    ]
    try:
        rcs = [p.wait(timeout=300) for p in procs]
    finally:
        # a hung or crashed worker must not orphan its peer (a standard
        # jax.distributed failure mode: one side stuck in a collective)
        for p in procs:
            if p.poll() is None:
                p.kill()
    if any(rcs):
        raise SystemExit(f"worker failure: rcs={rcs}")
    print("multi-host demo ok: 2 processes, one global mesh, one model")


if __name__ == "__main__":
    main()
