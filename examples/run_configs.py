#!/usr/bin/env python
"""Run the five reference workload configs (BASELINE.json:6-12) end-to-end.

    python examples/run_configs.py [1|2|3|4|5|all] [--scale small|full]

Config 1: LinearRegressionWithSGD, least squares, dense synthetic.
Config 2: LogisticRegressionWithSGD, log loss + L2, LIBSVM file (a real a9a
          when present at data/a9a, else the synthetic stand-in
          data/a9a_synthetic written on first run — see data/README.md).
Config 3: SVMWithSGD, hinge + L1 updater, sparse->densified LIBSVM.
Config 4: Mini-batch SGD frac=0.1, 8-way data-parallel all-reduce.
Config 5: Streaming SGD over micro-batches, online weight updates.

On a machine without the TPU attached, run with JAX_PLATFORMS=cpu and
XLA_FLAGS=--xla_force_host_platform_device_count=8.
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from tpu_sgd.utils.platform import honor_cpu_env

honor_cpu_env()

import numpy as np  # noqa: E402

from tpu_sgd import (  # noqa: E402
    L1Updater,
    LinearRegressionWithSGD,
    LogisticRegressionWithSGD,
    StreamingLinearRegressionWithSGD,
    SVMWithSGD,
    data_mesh,
)
from tpu_sgd.optimize.oracle import (  # noqa: E402
    hinge_l1_oracle,
    least_squares_oracle,
    logistic_l2_oracle,
    objective_gap,
)
from tpu_sgd.ops.gradients import (  # noqa: E402
    HingeGradient,
    LeastSquaresGradient,
    LogisticGradient,
)
from tpu_sgd.utils import (  # noqa: E402
    a9a_like_data,
    linear_data,
    load_libsvm_file,
    rcv1_like_data,
    save_as_libsvm_file,
)

def _parse_args(argv):
    which = "all"
    scale = os.environ.get("SCALE", "small")
    args = list(argv)
    while args:
        a = args.pop(0)
        if a == "--scale":
            if not args or args[0] not in ("small", "full"):
                raise SystemExit("--scale takes 'small' or 'full'")
            scale = args.pop(0)
        elif a in ("1", "2", "3", "4", "5", "all"):
            which = a
        else:
            raise SystemExit(
                f"unknown argument {a!r}; usage: run_configs.py "
                "[1|2|3|4|5|all] [--scale small|full]"
            )
    return which, scale


SMALL = True  # overwritten in __main__ from --scale / SCALE env


def config1():
    n, d = (100_000, 100)
    X, y, w_true = linear_data(n, d, eps=0.1, seed=0)
    t0 = time.perf_counter()
    model = LinearRegressionWithSGD.train((X, y), num_iterations=100,
                                          step_size=1.0)
    mse = float(np.mean((np.asarray(model.predict(X)) - y) ** 2))
    # BASELINE.md pass criterion: final loss matches the EXACT oracle
    # (normal equations) within 1%
    gap, L, L_star = objective_gap(
        LeastSquaresGradient(), X, y, model.weights,
        least_squares_oracle(X, y))
    verdict = "PASS" if gap < 0.01 else "FAIL"
    print(f"config1: n={n} d={d} mse={mse:.4f} "
          f"w_err={float(np.linalg.norm(np.asarray(model.weights) - w_true)):.4f} "
          f"oracle_gap={gap * 100:.2f}% [{verdict} <1%] "
          f"({time.perf_counter() - t0:.1f}s)")


def _libsvm_path(real_name, synthetic_name, maker):
    """Prefer a REAL dataset at ``data/<real_name>`` if the user vendored
    one; otherwise use (writing on first run) the locally generated
    synthetic stand-in at ``data/<synthetic_name>`` — this environment has
    no network, so the real LIBSVM files cannot be fetched (see
    data/README.md)."""
    data_dir = os.path.join(os.path.dirname(__file__), "..", "data")
    real = os.path.join(data_dir, real_name)
    if os.path.exists(real):
        # "vendored", not "real": we can only know the user placed a file
        # here, not that it is the genuine dataset.  (Workspaces that ran
        # the pre-rename script may have a STALE auto-generated file at
        # this path — delete it; the honest stand-in lives at
        # data/<synthetic_name> now.)
        return real, "vendored"
    path = os.path.join(data_dir, synthetic_name)
    if not os.path.exists(path):
        os.makedirs(data_dir, exist_ok=True)
        X, y = maker()
        save_as_libsvm_file(path, X, y)
    return path, "synthetic stand-in"


def config2():
    # Stand-in mirrors the REAL a9a structure: 123 binary one-hot
    # features, exactly 14 active per row (see a9a_like_data)
    path, kind = _libsvm_path(
        "a9a", "a9a_synthetic_v2",
        lambda: a9a_like_data(20_000, seed=1)[:2]
    )
    X, y = load_libsvm_file(path)
    y = np.where(y > 0, 1.0, 0.0).astype(np.float32)  # a9a labels are +/-1
    t0 = time.perf_counter()
    reg = 0.01
    alg = LogisticRegressionWithSGD(2.0, 500, reg, 1.0)
    alg.optimizer.set_convergence_tol(0.0)  # run the full budget
    model = alg.run((X, y))
    acc = float(np.mean(np.asarray(model.predict(X)) == y))
    # BASELINE.md pass criterion: matches a tight-tolerance LBFGS oracle
    # on the same (unbiased) objective within 1%
    gap, L, L_star = objective_gap(
        LogisticGradient(), X, y, model.weights,
        logistic_l2_oracle(X, y, reg), reg, "l2")
    verdict = "PASS" if gap < 0.01 else "FAIL"
    # The evaluation surface a reference user scores this model with
    # ([U] mllib/evaluation/BinaryClassificationMetrics)
    from tpu_sgd.evaluation import BinaryClassificationMetrics

    model.clear_threshold()
    auc = BinaryClassificationMetrics(
        np.asarray(model.predict(X)), y
    ).area_under_roc
    print(f"config2: libsvm={os.path.basename(path)} ({kind}) "
          f"n={X.shape[0]} d={X.shape[1]} acc={acc:.4f} auc={auc:.4f} "
          f"oracle_gap={gap * 100:.2f}% [{verdict} <1%] "
          f"({time.perf_counter() - t0:.1f}s)")


def config3():
    # Stand-in mirrors the REAL RCV1 structure (power-law feature
    # frequencies, positive unit-norm tfidf-like rows) at a densifiable
    # width — the real 47,236-feature width runs undensified below
    def _rcv1_standin():
        X, y, _ = rcv1_like_data(20_000, d=2000, nnz_per_row=75, seed=2)
        return np.asarray(X.todense()), y

    # _v2 filenames: the stand-in generators changed in round 2, and a
    # stale cached file from the old dense-Gaussian generators would
    # silently mismatch the step sizes calibrated for these
    # distributions
    path, kind = _libsvm_path("rcv1", "rcv1_synthetic_v2", _rcv1_standin)
    X, y = load_libsvm_file(path, dense=True)  # sparse -> densified
    y = np.where(y > 0, 1.0, 0.0).astype(np.float32)
    t0 = time.perf_counter()
    reg = 1e-4
    # unit-norm tfidf-like rows give small margins, so the eta/sqrt(t)
    # subgradient schedule needs a large base step (calibrated: gap 1.2%)
    alg = SVMWithSGD(300.0, 3000, reg, 1.0)
    alg.optimizer.set_updater(L1Updater()).set_convergence_tol(0.0)
    model = alg.run((X, y))
    acc = float(np.mean(np.asarray(model.predict(X)) == y))
    # Subgradient descent is O(1/sqrt(t)) on the nonsmooth hinge (the
    # reference's SVMWithSGD has the same rate), so the criterion is a
    # documented 20% objective bound vs the tight OWL-QN reference point
    # plus accuracy parity (see tpu_sgd/optimize/oracle.py)
    w_star = hinge_l1_oracle(X, y, reg)
    gap, L, L_star = objective_gap(
        HingeGradient(), X, y, model.weights, w_star, reg, "l1")
    from tpu_sgd.models.classification import SVMModel

    acc_star = float(np.mean(np.asarray(SVMModel(w_star, 0.0).predict(X)) == y))
    ok = gap < 0.20 and acc > acc_star - 0.01
    verdict = "PASS" if ok else "FAIL"
    print(f"config3: libsvm={os.path.basename(path)} ({kind}) "
          f"n={X.shape[0]} d={X.shape[1]} acc={acc:.4f} "
          f"(oracle acc={acc_star:.4f}) oracle_gap={gap * 100:.1f}% "
          f"[{verdict} <20%+acc] ({time.perf_counter() - t0:.1f}s)")

    # Same config UNDENSIFIED: BCOO features through the sparse path,
    # sharded over the data mesh (real RCV1 at ~47k features cannot be
    # densified at all — this is the path that handles it).
    from tpu_sgd.ops.sparse import load_libsvm_file_bcoo

    Xs, ys = load_libsvm_file_bcoo(path)
    ys = np.where(ys > 0, 1.0, 0.0).astype(np.float32)
    t0 = time.perf_counter()
    alg_s = SVMWithSGD(300.0, 500, reg, 1.0)
    alg_s.optimizer.set_updater(L1Updater()).set_convergence_tol(0.0)
    alg_s.optimizer.set_mesh(data_mesh())
    model_s = alg_s.run((Xs, ys))
    acc_s = float(np.mean(np.asarray(model_s.predict(Xs)) == ys))
    print(f"config3-sparse: BCOO undensified, {dict(data_mesh().shape)}-way "
          f"mesh, nse={Xs.nse} acc={acc_s:.4f} "
          f"({time.perf_counter() - t0:.1f}s)")


def config4():
    n, d = (400_000, 200) if SMALL else (10_000_000, 1000)
    X, y, w_true = linear_data(n, d, eps=0.1, seed=3)
    mesh = data_mesh()
    t0 = time.perf_counter()
    # Full scale is 10M x 1000 f32 = 40 GB — beyond any single chip's HBM
    # (SURVEY.md §7 hard parts).  The EXECUTION PLANNER (tpu_sgd/plan.py,
    # round 4) owns the residency decision now: train() probes free device
    # memory and picks resident / partial-residency / host-streamed
    # itself; CONFIG4_FREE_HBM overrides the probe for smoke tests.
    free_hbm = os.environ.get("CONFIG4_FREE_HBM")
    alg = LinearRegressionWithSGD(0.5, 200, None, 0.1)
    alg.optimizer.set_mesh(mesh)
    if free_hbm is not None:
        # pin the budget by planning explicitly, then run with the result
        import tpu_sgd.plan as _plan_mod

        p = _plan_mod.plan(
            n, d, itemsize=X.dtype.itemsize, gram_able=True,
            sampling=alg.optimizer.config.sampling,
            mini_batch_fraction=0.1, num_iterations=200,
            n_devices=mesh.shape["data"], free_hbm=float(free_hbm),
        )
        p.apply(alg.optimizer)
        alg.set_schedule("off")
    model = alg.run((X, y))
    last = alg.optimizer.last_plan
    mode = last.schedule if last is not None else "unplanned"
    print(f"config4: n={n} d={d} {dict(mesh.shape)}-way DP (plan: {mode}) "
          f"w_err={float(np.linalg.norm(np.asarray(model.weights) - w_true)):.4f} "
          f"({time.perf_counter() - t0:.1f}s)")
    if mode.startswith("resident"):
        # The same shape through the sufficient-statistics schedule
        # (round 3, ops/gram.py): per-shard prefix Grams + the same ICI
        # psum; weights must agree with the stock DP run above.
        t0 = time.perf_counter()
        model_ss = LinearRegressionWithSGD.train(
            (X, y), num_iterations=200, step_size=0.5,
            mini_batch_fraction=0.1, sampling="sliced", mesh=mesh,
            sufficient_stats=True,
        )
        drift = float(np.abs(np.asarray(model_ss.weights)
                             - np.asarray(model.weights)).max())
        w_err = float(np.linalg.norm(
            np.asarray(model_ss.weights) - w_true))
        print(f"config4-gram: sufficient_stats=True w_err={w_err:.4f} "
              f"(|w-w_stock|max={drift:.1e}, sliced windows) "
              f"({time.perf_counter() - t0:.1f}s)")
    # Meshed quasi-Newton variant (round 5, VERDICT r4 #5): the SAME
    # 8-way shape through LBFGS with zero schedule flags — the planner
    # decides the statistics substitution itself (per-shard totals +
    # psum; tpu_sgd/plan.py plan_quasi_newton).
    from tpu_sgd.models import LinearRegressionWithLBFGS

    t0 = time.perf_counter()
    alg_qn = LinearRegressionWithLBFGS(max_num_iterations=25)
    alg_qn.optimizer.set_mesh(mesh)
    model_qn = alg_qn.run((X, y))
    last_qn = alg_qn.optimizer.last_plan
    mode_qn = last_qn.schedule if last_qn is not None else "unplanned"
    w_err_qn = float(np.linalg.norm(
        np.asarray(model_qn.weights) - w_true))
    print(f"config4-lbfgs: {dict(mesh.shape)}-way (plan: {mode_qn}) "
          f"w_err={w_err_qn:.4f} ({time.perf_counter() - t0:.1f}s)")


def config5():
    d = 50
    w_true = np.linspace(-1, 1, d).astype(np.float32)
    alg = StreamingLinearRegressionWithSGD(step_size=0.3, num_iterations=25)
    alg.set_initial_weights(np.zeros(d, np.float32))
    t0 = time.perf_counter()
    errs = []
    for i in range(10):  # micro-batched DStream analogue
        Xb, yb, _ = linear_data(2_000, d, weights=w_true, eps=0.05, seed=10 + i)
        alg.train_on_batch(Xb, yb)
        errs.append(float(np.linalg.norm(
            np.asarray(alg.latest_model().weights) - w_true)))
    print(f"config5: 10 micro-batches w_err {errs[0]:.3f} -> {errs[-1]:.3f} "
          f"({time.perf_counter() - t0:.1f}s)")


if __name__ == "__main__":
    which, scale = _parse_args(sys.argv[1:])
    SMALL = scale == "small"
    fns = {"1": config1, "2": config2, "3": config3, "4": config4,
           "5": config5}
    for k, fn in fns.items():
        if which in (k, "all"):
            fn()
