#!/usr/bin/env python
"""End-to-end sparse workflow at real-RCV1 width — never densified.

    python examples/sparse_rcv1.py [--rows N] [--folds K]

Demonstrates the full sparse surface (SURVEY.md §2 #10; [U]
mllib/linalg/Vectors.scala SparseVector training):

  1. RCV1-shaped data at the REAL 47,236-feature width (Zipf feature
     frequencies, unit-norm tfidf-like rows) as a BCOO matrix — densifying
     it would need ``rows x 47,236 x 4`` bytes (18.8 GB at 100k rows);
  2. linear SVM (hinge + L1) trained UNDENSIFIED, sharded over the data
     mesh with one gradient all-reduce per iteration;
  3. k-fold cross-validation straight on the sparse matrix.

On a machine without the TPU attached run with JAX_PLATFORMS=cpu and
XLA_FLAGS=--xla_force_host_platform_device_count=8.
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from tpu_sgd.utils.platform import honor_cpu_env

honor_cpu_env()

import numpy as np  # noqa: E402

from tpu_sgd import L1Updater, SVMWithSGD, data_mesh  # noqa: E402
from tpu_sgd.utils.mlutils import k_fold, rcv1_like_data  # noqa: E402

D = 47_236  # the real rcv1.binary feature count


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--rows", type=int, default=30_000)
    ap.add_argument("--folds", type=int, default=3)
    args = ap.parse_args(argv)

    t0 = time.perf_counter()
    X, y, _ = rcv1_like_data(args.rows, d=D, seed=0)
    dense_gb = args.rows * D * 4 / 1e9
    sparse_mb = (X.data.nbytes + X.indices.nbytes) / 1e6
    print(f"data: {args.rows} x {D}, nse={X.nse} "
          f"({sparse_mb:.0f} MB sparse vs {dense_gb:.1f} GB densified) "
          f"[{time.perf_counter() - t0:.1f}s]")

    mesh = data_mesh()
    t0 = time.perf_counter()
    model = SVMWithSGD.train(
        (X, y), num_iterations=60, step_size=100.0, reg_param=1e-5,
        updater=L1Updater(), mesh=mesh,
    )
    acc = float(np.mean(np.asarray(model.predict(X)) == np.asarray(y)))
    nz = int(np.sum(np.asarray(model.weights) != 0))
    print(f"train: {dict(mesh.shape)}-way mesh, acc={acc:.4f}, "
          f"{nz}/{D} nonzero weights [{time.perf_counter() - t0:.1f}s]")

    t0 = time.perf_counter()
    accs = []
    for (Xtr, ytr), (Xva, yva) in k_fold(X, np.asarray(y), args.folds,
                                         seed=1):
        m = SVMWithSGD.train(
            (Xtr, ytr), num_iterations=40, step_size=100.0, reg_param=1e-5,
            updater=L1Updater(), mesh=mesh,
        )
        accs.append(float(np.mean(np.asarray(m.predict(Xva)) == yva)))
    print(f"{args.folds}-fold CV (sparse splits): "
          f"val acc {np.mean(accs):.4f} +/- {np.std(accs):.4f} "
          f"[{time.perf_counter() - t0:.1f}s]")


if __name__ == "__main__":
    main()
