#!/usr/bin/env python
"""Microbenchmark: serving under offered load, shedding OFF vs ON.

Drives the tpu_sgd.serve endpoint (micro-batcher + bucketed compiled
predict) with an open-loop request generator at three offered-load
levels, TWICE:

  * ``shed_off`` — the legacy arm: one interactive lane, no deadlines,
    ``shed_utilization={}`` (pure bounded-queue backpressure).  This is
    the configuration whose p99 cliffs at saturation (the ~165 ms
    number ISSUE 12 opens with).
  * ``shed_on``  — admission control (ISSUE 12): mixed
    interactive/batch/shadow traffic, a per-request deadline budget on
    the interactive lane, default utilization shed thresholds, and
    displacement under a full queue.

Per level and lane it reports submitted / answered / typed rejections
(admission sheds, deadline rejects, displacements — counted, never
silently dropped) and p50/p99 end-to-end latency.  Headline per the
2-core harness policy: the admission COUNTS and the interactive-lane
p99 at saturation (counts are exact; walls on a 2-core CPU host carry
scheduling noise — basis strings say what was measured).

Writes ``BENCH_SERVE.json`` and prints ONE JSON line on stdout;
diagnostics go to stderr.

A third section (``tenant_sweep``, ISSUE 18) drives the multi-tenant
slab plane: Zipf-distributed mixed-tenant batches through
``tpu_sgd.tenant`` at M ∈ {16, 256, 2048} tenants over ONE fixed
capacity-256 slab.  Headlines per the 2-core policy: dispatches per
mixed batch (must be flat across M — the shape-trap contract), compiles
after warm-up (must be 0), the slab hit rate under the Zipf head, and
the burst-admission lock-round ledger (one lock round per burst vs one
per request).  ``scripts/bench_gate.py`` gates all four.

Env knobs: BENCH_SERVE_DIM (default 64), BENCH_SERVE_SECONDS per level
(default 2.0), BENCH_SERVE_LOADS (comma rps list, default
"500,2500,10000,40000" — the last level is deliberately far beyond
capacity so overload actually engages), BENCH_SERVE_MAX_BATCH (default
32), BENCH_SERVE_DEADLINE (interactive budget, default 0.02),
BENCH_SERVE_TENANTS (comma tenant counts, default "16,256,2048"),
BENCH_SERVE_TENANT_BATCHES (measured batches per cell, default 100).
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

DIM = int(os.environ.get("BENCH_SERVE_DIM", "64"))
SECONDS = float(os.environ.get("BENCH_SERVE_SECONDS", "2.0"))
# the last level is deliberately far beyond single-host capacity
# (~9-10k rows/s warm): the overload arm is the point of this bench
LOADS = [
    int(v) for v in os.environ.get(
        "BENCH_SERVE_LOADS", "500,2500,10000,40000"
    ).split(",")
]
MAX_LATENCY_S = float(os.environ.get("BENCH_SERVE_MAX_LATENCY", "0.002"))
MAX_QUEUE = int(os.environ.get("BENCH_SERVE_MAX_QUEUE", "4096"))
# 32-row flushes bound per-batch service time the way a real multi-
# tenant endpoint does; with them this host's capacity is ~13-20k
# rows/s, so the top (40k) level is genuine overload and the deep
# queue is where the shed_off arm's latency balloon lives
MAX_BATCH = int(os.environ.get("BENCH_SERVE_MAX_BATCH", "32"))
DEADLINE_S = float(os.environ.get("BENCH_SERVE_DEADLINE", "0.02"))
TENANT_COUNTS = [
    int(v) for v in os.environ.get(
        "BENCH_SERVE_TENANTS", "16,256,2048").split(",")
]
TENANT_CAPACITY = int(os.environ.get("BENCH_SERVE_TENANT_CAPACITY", "256"))
TENANT_BATCHES = int(os.environ.get("BENCH_SERVE_TENANT_BATCHES", "100"))

#: the two arms: (lane, weight, deadline_s) mixes + shed config
ARMS = {
    "shed_off": {
        "shed_utilization": {},
        "mix": [("interactive", 1.0, None)],
    },
    "shed_on": {
        "shed_utilization": None,  # DEFAULT_SHED_UTILIZATION
        "mix": [("interactive", 0.6, DEADLINE_S),
                ("batch", 0.25, None),
                ("shadow", 0.15, None)],
    },
}


def log(msg: str):
    print(msg, file=sys.stderr, flush=True)


def _mix_pattern(mix, n=40, seed=0):
    """A fixed weighted round-robin of (lane, deadline) — deterministic
    arrivals, no per-request RNG on the submit path."""
    pattern = []
    for lane, weight, deadline in mix:
        pattern.extend([(lane, deadline)] * max(1, round(weight * n)))
    rng = np.random.default_rng(seed)
    rng.shuffle(pattern)
    return pattern


def run_level(server, rows, offered_rps: float, seconds: float,
              mix) -> dict:
    """Open-loop load: submit single-row requests on a fixed schedule
    (bursting to catch up after GIL stalls), collect per-lane completion
    latencies from the futures and the typed-rejection counts."""
    from tpu_sgd.serve import Overloaded

    pattern = _mix_pattern(mix)
    n_rows = rows.shape[0]
    lanes = sorted({lane for lane, _, _ in mix})
    per_lane = {lane: {"submitted": 0, "typed_rejections": 0,
                       "latencies": []} for lane in lanes}
    futures = []
    # credit-based pacing with bounded bursts: sleeping between bursts
    # keeps the flush thread scheduled (an uncapped catch-up loop would
    # monopolize the GIL/queue lock and measure its own convoy, not the
    # server), and the credit cap sheds arrivals the generator itself
    # fell behind on rather than compounding them into a thundering herd
    tick = 0.002
    max_credit = offered_rps * 0.05  # at most 50 ms of backlogged arrivals
    t_start = time.perf_counter()
    deadline = t_start + seconds
    t_last = t_start
    credit = 0.0
    i = 0
    while True:
        time.sleep(tick)
        now = time.perf_counter()
        if now >= deadline:
            break
        credit = min(credit + (now - t_last) * offered_rps, max_credit)
        t_last = now
        while credit >= 1.0:
            credit -= 1.0
            lane, dl = pattern[i % len(pattern)]
            st = per_lane[lane]
            st["submitted"] += 1
            t_sub = time.perf_counter()
            try:
                fut = server.submit(rows[i % n_rows], lane=lane,
                                    deadline_s=dl)
            except Overloaded:
                st["typed_rejections"] += 1
            else:
                fut.add_done_callback(
                    lambda f, s=st, t=t_sub: s["latencies"].append(
                        time.perf_counter() - t)
                    if f.exception() is None else None
                )
                futures.append((lane, fut))
            i += 1
    # drain: wait for everything submitted to resolve — answered, or a
    # typed displacement (never a silent drop)
    answered = 0
    for lane, fut in futures:
        try:
            fut.result(timeout=30)
            answered += 1
        except Overloaded:
            per_lane[lane]["typed_rejections"] += 1
        except Exception:
            pass
    # result() wakes before done-callbacks run, so give the flush
    # thread's latency-recording callbacks a moment to finish tallying
    t_wait = time.perf_counter() + 5.0
    while (sum(len(s["latencies"]) for s in per_lane.values()) < answered
           and time.perf_counter() < t_wait):
        time.sleep(0.001)
    elapsed = time.perf_counter() - t_start

    # THE shared nearest-rank rule (serve.metrics) — the SLO gates,
    # healthz p99_batch_wall_s, and this bench must mean the same p99
    from tpu_sgd.serve.metrics import nearest_rank

    def pct(lat, p):
        return nearest_rank(sorted(lat), p)

    out_lanes = {}
    for lane, st in per_lane.items():
        lat = st["latencies"]
        out_lanes[lane] = {
            "submitted": st["submitted"],
            "answered": len(lat),
            "typed_rejections": st["typed_rejections"],
            "p50_ms": round(pct(lat, 50) * 1e3, 3),
            "p99_ms": round(pct(lat, 99) * 1e3, 3),
        }
    total_lat = sum(len(s["latencies"]) for s in per_lane.values())
    return {
        "offered_rps": offered_rps,
        "achieved_rps": round(total_lat / elapsed, 1),
        "lanes": out_lanes,
    }


def run_arm(name: str, arm: dict, rng) -> list:
    from tpu_sgd.analysis import assert_compile_count
    from tpu_sgd.models import LinearRegressionModel
    from tpu_sgd.serve import Server

    model = LinearRegressionModel(
        rng.normal(size=DIM).astype(np.float32), 0.1
    )
    rows = rng.normal(size=(1024, DIM)).astype(np.float32)
    server = Server(
        model, max_latency_s=MAX_LATENCY_S, max_queue=MAX_QUEUE,
        max_batch=MAX_BATCH, shed_utilization=arm["shed_utilization"],
    )
    # warm the compiled bucket programs so measurement never pays XLA
    # compile time (a real endpoint warms at deploy, not per request)
    for b in server.engine.buckets:
        server.engine.predict_batch(model, rows[:b])
    log(f"[{name}] warmed {server.engine.compile_count} compiled "
        f"programs (buckets {server.engine.buckets})")

    levels = []
    # jit-cache-growth guard: after the warm loop above, the measured
    # levels must never reach the XLA compiler — a mid-bench compile is
    # a ~100-200ms stall that silently wrecks the p99 AND means a shape
    # escaped the bucket discipline.  Fail the bench loudly instead
    # (assert_compile_count is graftlint's runtime twin, tpu_sgd/analysis).
    with assert_compile_count(0, of=lambda: server.engine.compile_count), \
            server:
        # prime the queued path end-to-end (first flush pays one-time
        # lazy imports — jax.experimental.sparse via stack_rows — which
        # would otherwise stall the first measured level by ~1s)
        server.predict(rows[0], timeout=30)
        for rps in LOADS:
            before_batches = server.batcher.batch_count
            before_reqs = server.metrics.snapshot()["total_requests"]
            res = run_level(server, rows, rps, SECONDS, arm["mix"])
            snap = server.metrics.snapshot()
            d_batches = server.batcher.batch_count - before_batches
            d_reqs = snap["total_requests"] - before_reqs
            res["mean_batch_size"] = round(
                d_reqs / d_batches, 2) if d_batches else 0.0
            levels.append(res)
            inter = res["lanes"].get("interactive", {})
            log(f"[{name}] offered {rps} rps: achieved "
                f"{res['achieved_rps']} rows/s, interactive p99 "
                f"{inter.get('p99_ms')} ms "
                f"({inter.get('typed_rejections')} typed rejections), "
                f"mean batch {res['mean_batch_size']}")
        health = server.healthz()
    return levels, {k: health[k] for k in ("lanes", "admit_count",
                                           "shed_count", "reject_count")}


def run_tenant_sweep(rng) -> dict:
    """The multi-tenant slab sweep (ISSUE 18): Zipf mixed-tenant batches
    through ``tpu_sgd.tenant`` at several tenant counts over ONE fixed
    slab capacity, plus the burst-admission lock-round cell.  Counts and
    ratios only — the gated headlines are structural."""
    import tempfile

    from tpu_sgd.analysis.runtime import count_dispatches
    from tpu_sgd.serve import MicroBatcher
    from tpu_sgd.serve.metrics import nearest_rank
    from tpu_sgd.tenant import TenantModelStore, TenantPredictEngine

    cells = []
    for m in TENANT_COUNTS:
        tmp = tempfile.TemporaryDirectory()
        store = TenantModelStore(tmp.name, capacity=TENANT_CAPACITY, d=DIM)
        weights = rng.normal(size=(m, DIM)).astype(np.float32)
        for t in range(m):
            store.publish(t, weights[t], intercept=0.01 * (t % 5))
        # Zipf(1.2)-shaped tenant popularity over [0, m): the hot head
        # fits the slab, the cold tail forces admission-on-miss
        ranks = np.arange(1, m + 1, dtype=np.float64)
        p = ranks ** -1.2
        p /= p.sum()
        tids_all = rng.choice(m, size=TENANT_BATCHES * MAX_BATCH, p=p)
        rows = rng.normal(size=(MAX_BATCH, DIM)).astype(np.float32)

        engine = TenantPredictEngine(store)
        # warm: the Zipf head resident, both compiled paths built
        store.slots_for(
            np.unique(tids_all[:4 * TENANT_CAPACITY])[:TENANT_CAPACITY])
        warm_ids = np.unique(tids_all[:64])[:8]
        engine.predict_batch(np.resize(warm_ids, MAX_BATCH), rows)
        engine.predict_batch(np.full(MAX_BATCH, int(warm_ids[0])), rows)
        compiles_warm = engine.compile_count
        led0 = store.slab.ledger_snapshot()

        n_disp = 0
        walls = []
        t0 = time.perf_counter()
        for bi in range(TENANT_BATCHES):
            tb = tids_all[bi * MAX_BATCH:(bi + 1) * MAX_BATCH]
            # residency resolves first (cold tenants restore from disk
            # and pay a row-set dispatch — the slab-churn cost the hit
            # rate prices), then the SCORING dispatch count is measured
            # alone: the number that must stay flat across M
            store.slots_for(tb)
            t1 = time.perf_counter()
            with count_dispatches() as dc:
                engine.predict_batch(tb, rows)
            walls.append(time.perf_counter() - t1)
            n_disp += dc["n"]
        elapsed = time.perf_counter() - t0
        led = store.slab.ledger_snapshot()
        hits = led["hits"] - led0["hits"]
        misses = led["misses"] - led0["misses"]
        cell = {
            "tenants": m,
            "dispatches_per_batch": round(n_disp / TENANT_BATCHES, 4),
            "compiles_after_warm": engine.compile_count - compiles_warm,
            "slab_hit_rate": round(hits / max(1, hits + misses), 4),
            "evictions": led["evicted"] - led0["evicted"],
            "rows_per_s": round(TENANT_BATCHES * MAX_BATCH / elapsed, 1),
            "p99_batch_ms": round(
                nearest_rank(sorted(walls), 99) * 1e3, 3),
        }
        cells.append(cell)
        log(f"[tenant] M={m}: {cell['dispatches_per_batch']} dispatches/"
            f"batch, {cell['compiles_after_warm']} compiles after warm, "
            f"hit rate {cell['slab_hit_rate']}, "
            f"{cell['rows_per_s']} rows/s")
        tmp.cleanup()

    # -- the burst-admission lock-round cell (satellite: vectorized
    # admission prices a whole burst under ONE lock round) --------------
    n_burst = 1024
    xs = list(rng.normal(size=(n_burst, DIM)).astype(np.float32))

    def _zero(X):
        return np.zeros(len(X), np.float32)

    b_burst = MicroBatcher(_zero, max_batch=MAX_BATCH,
                           max_queue=2 * n_burst, shed_utilization={})
    t0 = time.perf_counter()
    b_burst.submit_burst(xs)
    wall_burst = time.perf_counter() - t0
    snap_burst = b_burst.admission_snapshot()
    b_burst.stop()

    b_seq = MicroBatcher(_zero, max_batch=MAX_BATCH,
                         max_queue=2 * n_burst, shed_utilization={})
    t0 = time.perf_counter()
    for x in xs:
        b_seq.submit(x)
    wall_seq = time.perf_counter() - t0
    snap_seq = b_seq.admission_snapshot()
    b_seq.stop()

    burst_admission = {
        "rows": n_burst,
        "burst": {**snap_burst,
                  "rounds_per_row": round(
                      snap_burst["lock_rounds"] / snap_burst["priced"], 6),
                  "admit_wall_ms": round(wall_burst * 1e3, 3)},
        "per_request": {**snap_seq,
                        "rounds_per_row": round(
                            snap_seq["lock_rounds"] / snap_seq["priced"],
                            6),
                        "admit_wall_ms": round(wall_seq * 1e3, 3)},
    }
    log(f"[tenant] burst admission: {snap_burst['lock_rounds']} lock "
        f"round for {snap_burst['priced']} rows "
        f"({burst_admission['burst']['admit_wall_ms']} ms) vs "
        f"{snap_seq['lock_rounds']} rounds per-request "
        f"({burst_admission['per_request']['admit_wall_ms']} ms)")
    return {
        "capacity": TENANT_CAPACITY,
        "batch_rows": MAX_BATCH,
        "batches_per_cell": TENANT_BATCHES,
        "zipf_a": 1.2,
        "basis": (
            "mixed-tenant Zipf(1.2) batches over one capacity-"
            f"{TENANT_CAPACITY} slab; dispatches_per_batch counts XLA "
            "launches of the SCORING dispatch only (residency resolves "
            "first; cold admissions pay their own row-set dispatch, "
            "priced by slab_hit_rate/evictions); compiles_after_warm "
            "and the burst lock-round ledger are exact; rows_per_s and "
            "p99_batch_ms run under the dispatch-counting hook on a "
            "2-core host — context, not gates"
        ),
        "cells": cells,
        "burst_admission": burst_admission,
    }


def main() -> int:
    rng = np.random.default_rng(0)
    arms = {}
    for name, arm in ARMS.items():
        levels, counts = run_arm(name, arm, rng)
        arms[name] = {"levels": levels, "admission_counts": counts}
    tenant_sweep = run_tenant_sweep(rng)

    sat = LOADS[-1]

    def at_sat(arm_name):
        lvl = [l for l in arms[arm_name]["levels"]
               if l["offered_rps"] == sat][0]
        return lvl["lanes"]["interactive"]

    off = at_sat("shed_off")
    on = at_sat("shed_on")
    counts_on = arms["shed_on"]["admission_counts"]
    parsed = {
        "metric": f"serve_interactive_p99_ms_at_{sat}rps_shed_on",
        "value": on["p99_ms"],
        "unit": "ms",
        "shed_off_p99_ms": off["p99_ms"],
        "shed_on_p50_ms": on["p50_ms"],
        "shed_off_p50_ms": off["p50_ms"],
        "shed_on_typed_rejections_at_saturation": on["typed_rejections"],
        "shed_on_counts": {
            "admitted": counts_on["admit_count"],
            "shed": counts_on["shed_count"],
            "rejected_total": counts_on["reject_count"],
        },
        "tenant_dispatches_per_batch": [
            c["dispatches_per_batch"] for c in tenant_sweep["cells"]],
        "tenant_burst_rounds_per_row": (
            tenant_sweep["burst_admission"]["burst"]["rounds_per_row"]),
        "note": (
            "every rejection is a typed Overloaded answer; the shed_on "
            "p99 tail is requests admitted just before a scheduling "
            "stall — admitted requests are answered, never dropped"
        ),
    }
    result = {
        "cmd": "python bench_serving.py",
        "rc": 0,
        "dim": DIM,
        "seconds_per_level": SECONDS,
        "max_latency_s": MAX_LATENCY_S,
        "interactive_deadline_s": DEADLINE_S,
        "basis": (
            "open-loop offered load, 2-core CPU host; counts (admitted/"
            "shed/rejected/typed) are exact ledgers; latencies are "
            "submit->result walls incl. GIL scheduling noise — compare "
            "arms within this file, not across machines; shed_off = "
            "single interactive lane, no deadline, shed_utilization={} "
            "(the pre-ISSUE-12 configuration); shed_on = 60/25/15 "
            f"interactive/batch/shadow mix, {DEADLINE_S * 1e3:.0f}ms "
            "interactive deadline budget, default shed thresholds"
        ),
        "arms": arms,
        "tenant_sweep": tenant_sweep,
        "parsed": parsed,
    }
    out = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "BENCH_SERVE.json")
    with open(out, "w") as f:
        json.dump(result, f, indent=2)
        f.write("\n")
    print(json.dumps(parsed))
    return 0


if __name__ == "__main__":
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    sys.exit(main())
