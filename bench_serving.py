#!/usr/bin/env python
"""Microbenchmark: serving throughput + tail latency under offered load.

Drives the tpu_sgd.serve endpoint (micro-batcher + bucketed compiled
predict) with an open-loop request generator at three offered-load
levels, and reports per level:

  * achieved throughput (rows/sec completed),
  * p50 / p99 end-to-end latency (submit -> result, ms),
  * reject count (backpressure sheds, not silent drops),
  * mean coalesced batch size (how well the batcher amortizes calls).

Writes ``BENCH_SERVE.json`` (same driver-style shape as BENCH_r0*.json:
a ``parsed`` one-line result plus diagnostics) and prints ONE JSON line
on stdout; diagnostics go to stderr.

Env knobs: BENCH_SERVE_DIM (default 64), BENCH_SERVE_SECONDS per level
(default 2.0), BENCH_SERVE_LOADS (comma rps list, default
"500,2500,10000").
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

DIM = int(os.environ.get("BENCH_SERVE_DIM", "64"))
SECONDS = float(os.environ.get("BENCH_SERVE_SECONDS", "2.0"))
LOADS = [
    int(v) for v in os.environ.get(
        "BENCH_SERVE_LOADS", "500,2500,10000"
    ).split(",")
]
MAX_LATENCY_S = float(os.environ.get("BENCH_SERVE_MAX_LATENCY", "0.002"))
MAX_QUEUE = int(os.environ.get("BENCH_SERVE_MAX_QUEUE", "4096"))


def log(msg: str):
    print(msg, file=sys.stderr, flush=True)


def run_level(server, rows, offered_rps: float, seconds: float) -> dict:
    """Open-loop load: submit single-row requests on a fixed schedule
    (bursting to catch up after GIL stalls), collect completion latencies
    from the futures."""
    from tpu_sgd.serve import BackpressureError

    n_rows = rows.shape[0]
    latencies, futures = [], []
    rejects = submitted = 0
    # credit-based pacing with bounded bursts: sleeping between bursts
    # keeps the flush thread scheduled (an uncapped catch-up loop would
    # monopolize the GIL/queue lock and measure its own convoy, not the
    # server), and the credit cap sheds arrivals the generator itself
    # fell behind on rather than compounding them into a thundering herd
    tick = 0.002
    max_credit = offered_rps * 0.05  # at most 50 ms of backlogged arrivals
    t_start = time.perf_counter()
    deadline = t_start + seconds
    t_last = t_start
    credit = 0.0
    i = 0
    while True:
        time.sleep(tick)
        now = time.perf_counter()
        if now >= deadline:
            break
        credit = min(credit + (now - t_last) * offered_rps, max_credit)
        t_last = now
        while credit >= 1.0:
            credit -= 1.0
            t_sub = time.perf_counter()
            try:
                fut = server.submit(rows[i % n_rows])
            except BackpressureError:
                rejects += 1
            else:
                submitted += 1
                fut.add_done_callback(
                    lambda f, t=t_sub: latencies.append(
                        time.perf_counter() - t)
                )
                futures.append(fut)
            i += 1
    # drain: wait for everything submitted to resolve
    done = 0
    for fut in futures:
        try:
            fut.result(timeout=30)
            done += 1
        except Exception:
            pass
    # result() wakes before done-callbacks run, so give the flush
    # thread's latency-recording callbacks a moment to finish tallying
    t_wait = time.perf_counter() + 5.0
    while len(latencies) < done and time.perf_counter() < t_wait:
        time.sleep(0.001)
    elapsed = time.perf_counter() - t_start
    lat = np.sort(np.asarray(latencies)) if latencies else np.zeros(1)

    def pct(p):
        return float(lat[min(len(lat) - 1, int(p / 100.0 * len(lat)))])

    return {
        "offered_rps": offered_rps,
        "achieved_rps": round(len(latencies) / elapsed, 1),
        "submitted": submitted,
        "rejects": rejects,
        "p50_ms": round(pct(50) * 1e3, 3),
        "p99_ms": round(pct(99) * 1e3, 3),
    }


def main() -> int:
    from tpu_sgd.models import LinearRegressionModel
    from tpu_sgd.serve import Server

    rng = np.random.default_rng(0)
    model = LinearRegressionModel(
        rng.normal(size=DIM).astype(np.float32), 0.1
    )
    rows = rng.normal(size=(1024, DIM)).astype(np.float32)

    server = Server(
        model, max_latency_s=MAX_LATENCY_S, max_queue=MAX_QUEUE,
        max_batch=256,
    )
    # warm the compiled bucket programs so measurement never pays XLA
    # compile time (a real endpoint warms at deploy, not per request)
    for b in server.engine.buckets:
        server.engine.predict_batch(model, rows[:b])
    log(f"warmed {server.engine.compile_count} compiled programs "
        f"(buckets {server.engine.buckets})")

    from tpu_sgd.analysis import assert_compile_count

    levels = []
    # jit-cache-growth guard: after the warm loop above, the measured
    # levels must never reach the XLA compiler — a mid-bench compile is
    # a ~100-200ms stall that silently wrecks the p99 AND means a shape
    # escaped the bucket discipline.  Fail the bench loudly instead
    # (assert_compile_count is graftlint's runtime twin, tpu_sgd/analysis).
    with assert_compile_count(0, of=lambda: server.engine.compile_count), \
            server:
        # prime the queued path end-to-end (first flush pays one-time
        # lazy imports — jax.experimental.sparse via stack_rows — which
        # would otherwise stall the first measured level by ~1s)
        server.predict(rows[0], timeout=30)
        for rps in LOADS:
            before_batches = server.batcher.batch_count
            before_reqs = server.metrics.snapshot()["total_requests"]
            res = run_level(server, rows, rps, SECONDS)
            snap = server.metrics.snapshot()
            d_batches = server.batcher.batch_count - before_batches
            d_reqs = snap["total_requests"] - before_reqs
            res["mean_batch_size"] = round(
                d_reqs / d_batches, 2) if d_batches else 0.0
            levels.append(res)
            log(f"offered {rps} rps: achieved {res['achieved_rps']} rows/s, "
                f"p50 {res['p50_ms']} ms, p99 {res['p99_ms']} ms, "
                f"rejects {res['rejects']}, "
                f"mean batch {res['mean_batch_size']}")

    top = max(levels, key=lambda r: r["achieved_rps"])
    parsed = {
        "metric": f"serve_rows_per_sec_dense_{DIM}d",
        "value": top["achieved_rps"],
        "unit": "rows/sec",
        "p99_ms_at_peak": top["p99_ms"],
    }
    result = {
        "cmd": "python bench_serving.py",
        "rc": 0,
        "dim": DIM,
        "seconds_per_level": SECONDS,
        "max_latency_s": MAX_LATENCY_S,
        "levels": levels,
        "parsed": parsed,
    }
    out = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "BENCH_SERVE.json")
    with open(out, "w") as f:
        json.dump(result, f, indent=2)
        f.write("\n")
    print(json.dumps(parsed))
    return 0


if __name__ == "__main__":
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    sys.exit(main())
