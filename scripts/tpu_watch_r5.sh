#!/bin/bash
# Round-5 TPU tunnel watcher (VERDICT r4 #2/#7: front-load hardware windows;
# the aligned-gram decomposition experiment lost its round-4 window to a
# wedged tunnel and runs FIRST here).
#
# Probes the axon tunnel from a timeout-wrapped child process; the moment it
# answers, runs (in order):
#   1. scripts/gram_scan_experiment.py  — the pending decomposition capture
#   2. bench.py                         — live headline capture (persists to
#                                         BENCH_LAST_TPU.json immediately)
#   3. quasi-newton + sparse + streamed-stats correctness checks
# then keeps watching hourly so a later, healthier tunnel can refresh.
#
# Usage: nohup bash scripts/tpu_watch_r5.sh >> tpu_watch.log 2>&1 &
set -u
cd "$(dirname "$0")/.."
PROBE_TIMEOUT="${PROBE_TIMEOUT:-240}"
SLEEP_BETWEEN="${SLEEP_BETWEEN:-420}"
MAX_HOURS="${MAX_HOURS:-11}"
deadline=$(( $(date +%s) + MAX_HOURS * 3600 ))

ran_capture=0
while [ "$(date +%s)" -lt "$deadline" ]; do
  if timeout "$PROBE_TIMEOUT" python -c \
      "import jax; assert jax.devices()[0].platform != 'cpu'" 2>/dev/null; then
    echo "[$(date +%H:%M:%S)] TUNNEL ALIVE"
    if [ ! -f GRAM_SCAN_EXPERIMENT.json ]; then
      echo "[$(date +%H:%M:%S)] gram decomposition experiment (round-4 pending):"
      timeout 3600 python scripts/gram_scan_experiment.py 2>&1 \
        | tee -a gram_exp_watch.log
    fi
    echo "[$(date +%H:%M:%S)] full bench:"
    BENCH_TPU_RETRIES=2 BENCH_TPU_BACKOFF=30 BENCH_PALLAS=0 BENCH_CHUNKS= \
      timeout 3600 python bench.py 2>&1 | tee -a bench_logs/BENCH_STDERR_r05_tpu.txt
    echo "[$(date +%H:%M:%S)] quasi-newton/streaming hardware check:"
    timeout 1800 python scripts/quasi_newton_tpu_check.py 2>&1 | tee qn_check_watch.log
    echo "[$(date +%H:%M:%S)] sparse hardware check:"
    timeout 1800 python scripts/sparse_tpu_check.py 2>&1 | tee sparse_check_watch.log
    echo "[$(date +%H:%M:%S)] streamed sufficient-stats 10Mx1000:"
    timeout 4500 python scripts/stream_gram_tpu_check.py 2>&1 \
      | tee -a bench_logs/STREAM_GRAM_r05_tpu.txt
    if [ -f scripts/streamed_costfun_tpu_check.py ]; then  # optional extra
      echo "[$(date +%H:%M:%S)] streamed-CostFun hardware check:"
      timeout 1800 python scripts/streamed_costfun_tpu_check.py 2>&1 \
        | tee costfun_check_watch.log
    fi
    ran_capture=1
    echo "[$(date +%H:%M:%S)] capture set done"
    sleep 3600
  else
    echo "[$(date +%H:%M:%S)] tunnel wedged (probe >${PROBE_TIMEOUT}s or failed)"
    sleep "$SLEEP_BETWEEN"
  fi
done
echo "[$(date +%H:%M:%S)] watcher deadline reached (ran_capture=$ran_capture)"
