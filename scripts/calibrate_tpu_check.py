#!/usr/bin/env python
"""Validate the planner's self-calibration probe on REAL TPU hardware.

``CostModel.calibrate()`` (``tpu_sgd/plan.py``) exists because the
persisted cost-model defaults are single-environment captures of this
tunnel-attached TPU v5 lite; a pod-local deployment must be able to
re-probe its own rates and have the planner's streaming decision
boundaries move accordingly (VERDICT r4 #6).  The CPU-mesh tests prove
the boundary flips with a fed cost model; this script is the probe's
hardware leg: run ``calibrate()`` against the real chip, record the
measured effective HBM GB/s and host-feed GB/s next to the persisted
defaults, and re-plan the two headline shapes under both models to show
which decisions the measurement confirms.

Pass criterion: the probe completes on ``platform: tpu``, the measured
HBM rate is within 2x of the persisted 730 GB/s default (same chip —
the default IS a capture of this environment), and the planner picks
the same schedule for the headline shapes under default and calibrated
models (this environment is the calibration source; a DIFFERENT
environment flipping boundaries is the feature, exercised in
``tests/test_plan.py``).

Run it when the tunnel is up:  python scripts/calibrate_tpu_check.py
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(REPO, "CALIBRATION_TPU_CHECK.json")

_CHILD = r"""
import os, sys, json, time
import jax
sys.path.insert(0, %(repo)r)
from tpu_sgd.plan import CostModel, DEFAULT_COST_MODEL, plan

dev = jax.devices()[0]
out = {"platform": dev.platform, "device": str(dev.device_kind)}

t0 = time.perf_counter()
cm = CostModel.calibrate(dev)
out["calibrate_s"] = round(time.perf_counter() - t0, 3)
out["measured"] = {"hbm_gb_s": round(cm.hbm_gb_s, 1),
                   "host_feed_gb_s": round(cm.host_feed_gb_s, 4)}
out["report"] = {k: (round(v, 6) if isinstance(v, float) else v)
                 for k, v in cm.calibration_report.items()}
out["defaults"] = {"hbm_gb_s": DEFAULT_COST_MODEL.hbm_gb_s,
                   "host_feed_gb_s": DEFAULT_COST_MODEL.host_feed_gb_s}

# the two headline shapes: the 3M-row resident slab and the true-size
# beyond-HBM 10Mx1000 (both bf16, sliced frac=0.1 - the bench workloads)
shapes = {"slab_3Mx1000": (2_998_272, 1000), "true_10Mx1000": (10_000_000, 1000)}
out["plans"] = {}
for name, (n, d) in shapes.items():
    row = {}
    for label, model in (("default", DEFAULT_COST_MODEL), ("calibrated", cm)):
        p = plan(n, d, itemsize=2, gram_able=True, sampling="sliced",
                 mini_batch_fraction=0.1, num_iterations=1200,
                 cost_model=model)
        row[label] = p.schedule
    row["agree"] = row["default"] == row["calibrated"]
    out["plans"][name] = row

print("CALIB_JSON:" + json.dumps(out))
""" % {"repo": REPO}


def main():
    t0 = time.time()
    proc = subprocess.run([sys.executable, "-c", _CHILD],
                          capture_output=True, text=True, timeout=900)
    line = next((l for l in proc.stdout.splitlines()
                 if l.startswith("CALIB_JSON:")), None)
    if line is None:
        print(proc.stdout[-2000:])
        print(proc.stderr[-2000:])
        raise SystemExit("calibration child produced no record")
    rec = json.loads(line[len("CALIB_JSON:"):])
    rec["timestamp"] = time.strftime("%Y-%m-%dT%H:%M:%S")
    rec["wall_s"] = round(time.time() - t0, 1)

    meas, dflt = rec["measured"], rec["defaults"]
    hbm_ratio = meas["hbm_gb_s"] / dflt["hbm_gb_s"]
    plans_agree = all(v["agree"] for v in rec["plans"].values())
    # calibrate() keeps a default when a probe's measurement is rejected
    # (non-positive slope / implausible rate) and says so in its
    # calibration_report — a hardware check whose probe measured nothing
    # must not report ok.
    fell_back = rec["report"]["hbm_fell_back"] or rec["report"]["feed_fell_back"]
    rec["ok"] = (rec["platform"] == "tpu"
                 and 0.5 <= hbm_ratio <= 2.0
                 and plans_agree
                 and not fell_back)
    rec["note"] = (
        "correctness-only: validates that the ~2s probe measures this "
        "chip's effective rates in the persisted defaults' range and "
        "that the planner's headline decisions are stable under the "
        "measured model; cross-environment boundary FLIPS are the "
        "probe's purpose and are exercised on fed cost models in "
        "tests/test_plan.py"
    )
    with open(OUT, "w") as f:
        json.dump(rec, f, indent=1)
    print(f"platform={rec['platform']} hbm={meas['hbm_gb_s']} GB/s "
          f"(default {dflt['hbm_gb_s']}), feed={meas['host_feed_gb_s']} GB/s "
          f"(default {dflt['host_feed_gb_s']}); plans agree={plans_agree}; "
          f"ok={rec['ok']}; wrote {OUT}")


if __name__ == "__main__":
    main()
