#!/bin/bash
# Light watcher for the gram-scan decomposition experiment: probe the
# tunnel every ~7 min from a killable subprocess; the first time it
# answers, run scripts/gram_scan_experiment.py once and exit.  Bounded at
# 18 attempts (~2.5 h) so it cannot contend with the end-of-round bench.
set -u
cd "$(dirname "$0")/.."
for i in $(seq 1 18); do
  if timeout 240 python -c 'import jax; assert jax.devices()[0].platform != "cpu"' 2>/dev/null; then
    echo "[$(date +%H:%M:%S)] tunnel alive; running gram scan experiment"
    # ONE attempt, stop either way: only a wedged probe retries — a run
    # that failed must not re-hold the TPU for every remaining attempt
    timeout 1500 python scripts/gram_scan_experiment.py
    echo "[$(date +%H:%M:%S)] experiment attempt finished (rc=$?)"
    break
  else
    echo "[$(date +%H:%M:%S)] tunnel wedged (attempt $i)"
  fi
  sleep 420
done
echo "[$(date +%H:%M:%S)] gram-exp watcher done"
