#!/usr/bin/env python
"""Decompose the fused SGD iteration's steady-state time on hardware.

VERDICT r2 #8: BASELINE.md puts the two-HBM-read bandwidth floor at
~1.46 ms/iter vs the achieved 1.64 ms — this script names where the
remaining ~0.18 ms goes, by MEASUREMENT rather than argument.  It times a
ladder of stripped-down loop bodies on the same resident slab, each
isolating one component of the full step:

  full        the real ``make_run`` fused while_loop step (loss history,
              convergence norm, updater, dynamic window)
  two_read_hist  both matvecs + loss reduction + the per-iteration
              loss-history scatter
  two_read_loss  both matvecs + the loss reduction kept live (no scatter)
              — so hist − loss isolates the SCATTER and loss − two_read
              isolates the loss REDUCTION
  two_read    both matvecs (margins + gradient) with the dynamic window,
              but no loss / scatter / convergence / reg bookkeeping
  two_read_0  both matvecs with a STATIC window start (isolates the
              dynamic-slice cost)
  one_read    the margins matvec only (one HBM read of the window — the
              single-read floor; the gradient matvec is the second read)

Per-iter times come from bench's >=3-point regression (K/3K/12K ladder)
so the fixed tunnel launch cost cancels — the same protocol as bench.py.
Optionally captures a jax.profiler trace of the full run (PROFILE_TRACE=1)
under bench_logs/profile_trace/.

Writes PROFILE_TPU.json at the repo root.  Run when the tunnel is up:
    python scripts/profile_iter.py
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
OUT = os.path.join(REPO, "PROFILE_TPU.json")

ROWS = int(os.environ.get("PROFILE_ROWS", "3000000"))
DIM = int(os.environ.get("PROFILE_DIM", "1000"))
FRAC = 0.1
ITERS = int(os.environ.get("PROFILE_ITERS", "30"))
STEP_SIZE = 0.5


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def main():
    from tpu_sgd.utils.platform import honor_cpu_env

    honor_cpu_env()
    import jax
    import jax.numpy as jnp
    from jax import lax

    devices = jax.devices()
    platform = devices[0].platform
    log(f"device: {devices[0].device_kind} ({platform})")

    rows = max(2048, ROWS // 2048 * 2048)
    m = int(FRAC * rows)
    dtype = jnp.bfloat16 if platform != "cpu" else jnp.float32

    key = jax.random.PRNGKey(0)
    kx, kw, kn = jax.random.split(key, 3)

    @jax.jit
    def gen():
        X = jax.random.normal(kx, (rows, DIM), dtype)
        w_true = jax.random.uniform(kw, (DIM,), jnp.float32, -1.0, 1.0)
        y = (X.astype(jnp.float32) @ w_true
             + 0.1 * jax.random.normal(kn, (rows,), jnp.float32))
        return X, y

    X, y = jax.block_until_ready(gen())
    w0 = jnp.zeros((DIM,), jnp.float32)

    from tpu_sgd.config import SGDConfig
    from tpu_sgd.ops.gradients import LeastSquaresGradient, matmul_dtype
    from tpu_sgd.ops.updaters import SimpleUpdater
    from tpu_sgd.optimize.gradient_descent import make_run

    mm = matmul_dtype(X)

    def start_of(i):
        """Per-iteration window-start draw, matching make_run's sliced
        sampler bound (``randint`` high = max(1, n - m + 1), so n - m is
        reachable) — ONE definition shared by the stock and gram rungs."""
        return jax.random.randint(
            jax.random.fold_in(jax.random.PRNGKey(42), i), (), 0,
            max(1, rows - m + 1),
        )

    def window(i, Xa, ya):
        """Same per-iteration window draw as make_run's sliced sampling."""
        start = start_of(i)
        Xb = lax.dynamic_slice_in_dim(Xa, start, m, 0)
        yb = lax.dynamic_slice_in_dim(ya, start, m, 0)
        return Xb, yb

    def loop_of(body, iters):
        @jax.jit
        def run(w, Xa, ya):
            return lax.fori_loop(
                1, iters + 1, lambda i, wc: body(i, wc, Xa, ya), w
            )
        return run

    def body_two_read(i, w, Xa, ya):
        Xb, yb = window(i, Xa, ya)
        r = jnp.dot(Xb.astype(mm), w.astype(mm),
                    preferred_element_type=jnp.float32) - yb
        g = jnp.dot(r.astype(mm), Xb.astype(mm),
                    preferred_element_type=jnp.float32)
        return w - (STEP_SIZE / jnp.sqrt(i.astype(jnp.float32))) * g / m

    def loop_hist(iters):
        """Two matvecs + the loss-history scatter — the carry is (w, hist)
        like the real run, so the scatter's cost (and any fusion it
        blocks) is measured in isolation from convergence/reg
        bookkeeping."""

        def body(i, carry, Xa, ya):
            w, hist = carry
            Xb, yb = window(i, Xa, ya)
            r = jnp.dot(Xb.astype(mm), w.astype(mm),
                        preferred_element_type=jnp.float32) - yb
            g = jnp.dot(r.astype(mm), Xb.astype(mm),
                        preferred_element_type=jnp.float32)
            loss = 0.5 * jnp.mean(r * r)
            hist = jax.lax.dynamic_update_index_in_dim(hist, loss, i - 1, 0)
            w = w - (STEP_SIZE / jnp.sqrt(i.astype(jnp.float32))) * g / m
            return (w, hist)

        @jax.jit
        def run(w, Xa, ya):
            hist0 = jnp.zeros((iters,), jnp.float32)
            return jax.lax.fori_loop(
                1, iters + 1, lambda i, c: body(i, c, Xa, ya), (w, hist0)
            )
        return run

    def body_two_read_loss(i, w, Xa, ya):
        """Two matvecs + the loss reduction, kept live via an epsilon-add
        (a plain unused loss would be dead-code-eliminated; 1e-30*loss is
        numerically negligible but not algebraically removable)."""
        Xb, yb = window(i, Xa, ya)
        r = jnp.dot(Xb.astype(mm), w.astype(mm),
                    preferred_element_type=jnp.float32) - yb
        g = jnp.dot(r.astype(mm), Xb.astype(mm),
                    preferred_element_type=jnp.float32)
        loss = 0.5 * jnp.mean(r * r)
        return (w - (STEP_SIZE / jnp.sqrt(i.astype(jnp.float32))) * g / m
                + 1e-30 * loss)

    def body_two_read_static(i, w, Xa, ya):
        Xb = lax.dynamic_slice_in_dim(Xa, 0, m, 0)
        yb = lax.dynamic_slice_in_dim(ya, 0, m, 0)
        r = jnp.dot(Xb.astype(mm), w.astype(mm),
                    preferred_element_type=jnp.float32) - yb
        g = jnp.dot(r.astype(mm), Xb.astype(mm),
                    preferred_element_type=jnp.float32)
        return w - (STEP_SIZE / jnp.sqrt(i.astype(jnp.float32))) * g / m

    def body_one_read(i, w, Xa, ya):
        Xb, yb = window(i, Xa, ya)
        r = jnp.dot(Xb.astype(mm), w.astype(mm),
                    preferred_element_type=jnp.float32) - yb
        # depend on r without a second X read: rank-1-free update proxy
        return w * (1.0 - 1e-9 * jnp.mean(r))

    def time_fn(name, fn, *args):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(*args))
        log(f"{name}: compile+first {time.perf_counter() - t0:.1f}s")
        t0 = time.perf_counter()
        jax.block_until_ready(fn(*args))
        return time.perf_counter() - t0

    def slope_of(name, make_fn, iters=None):
        """Steady-state per-iteration time via bench's >=3-point
        regression (1x/3x/12x ladder) — the round-4 protocol whose
        residuals expose launch jitter instead of absorbing it (the old
        two-point fit here was the source of round 3's +-25% "spread").
        ``iters`` overrides the base — the gram legs run 30x more
        iterations because their per-iter cost (~0.1 ms and below) would
        otherwise drown in the +-30 ms tunnel launch jitter."""
        from bench import fit_steady_state

        iters = ITERS if iters is None else iters
        pts = []
        for mult in (1, 3, 12):
            fn = make_fn(mult * iters)
            pts.append((mult * iters,
                        time_fn(f"{name}[{mult * iters}]", fn, w0, X, y)))
        slope, _fixed, fit = fit_steady_state(pts)
        if slope <= 0:
            slope = pts[-1][1] / pts[-1][0]
        err = fit.get("slope_rel_err")
        log(f"{name}: {slope * 1e3:.3f} ms/iter steady-state"
            + (f" (+-{err:.1%})" if err is not None else ""))
        return slope

    # the real fused program, loss history and all
    def make_full(iters):
        cfg = SGDConfig(step_size=STEP_SIZE, num_iterations=iters,
                        mini_batch_fraction=FRAC, convergence_tol=0.0,
                        sampling="sliced")
        return jax.jit(make_run(LeastSquaresGradient(), SimpleUpdater(), cfg))

    results = {}
    results["full_ms"] = slope_of("full", make_full) * 1e3
    results["two_read_hist_ms"] = slope_of("two_read_hist", loop_hist) * 1e3
    results["two_read_loss_ms"] = slope_of(
        "two_read_loss", lambda k: loop_of(body_two_read_loss, k)) * 1e3
    results["two_read_ms"] = slope_of(
        "two_read", lambda k: loop_of(body_two_read, k)) * 1e3
    results["two_read_static_ms"] = slope_of(
        "two_read_static", lambda k: loop_of(body_two_read_static, k)) * 1e3
    results["one_read_ms"] = slope_of(
        "one_read", lambda k: loop_of(body_one_read, k)) * 1e3

    # ---- gram (sufficient-statistics) iteration decomposition ----------
    # The round-3 headline schedule: where do its ~0.08 ms go?  Three
    # rungs, stats always passed as ARGUMENTS (GramData pytree — closure
    # constants at GB scale choke lowering):
    #   gram_real    the actual make_run fused program on the gram path
    #   gram_window  window_sums alone (prefix matvecs + edge blocks)
    #   gram_prefix  prefix matvecs only (no edge reads)
    # so edge cost = window − prefix and loop bookkeeping = real − window.
    from tpu_sgd.ops.gram import GramLeastSquaresGradient

    gram = GramLeastSquaresGradient.build(
        X, y, block_rows=int(os.environ.get("PROFILE_GRAM_BLOCK", "4096"))
    )
    gd = gram.data
    iters_g = 30 * ITERS

    def loop_gram(body, iters):
        @jax.jit
        def run(w, g, ya):
            return lax.fori_loop(
                1, iters + 1, lambda i, wc: body(i, wc, g, ya), w
            )
        return lambda w, Xa, ya: run(w, gd, ya)

    def body_gram_window(i, w, g, ya):
        gs, _, c = gram.window_sums(g, ya, w, start_of(i), m)
        return w - (STEP_SIZE / jnp.sqrt(i.astype(jnp.float32))) * gs / c

    def body_gram_prefix(i, w, g, ya):
        start = start_of(i)
        B = g.block_rows
        k1, k2 = start // B, (start + m) // B
        PG1 = lax.dynamic_slice_in_dim(g.PG, k1, 1, 0)[0]
        PG2 = lax.dynamic_slice_in_dim(g.PG, k2, 1, 0)[0]
        hi = jax.lax.Precision.HIGHEST
        gv = (jnp.dot(PG2, w, precision=hi) - jnp.dot(PG1, w, precision=hi))
        return w - (STEP_SIZE / jnp.sqrt(i.astype(jnp.float32))) * gv / m

    def make_gram_real(iters):
        cfg = SGDConfig(step_size=STEP_SIZE, num_iterations=iters,
                        mini_batch_fraction=FRAC, convergence_tol=0.0,
                        sampling="sliced")
        run = jax.jit(make_run(gram, SimpleUpdater(), cfg))
        return lambda w, Xa, ya: run(w, gd, ya)

    results["gram_real_ms"] = slope_of(
        "gram_real", make_gram_real, iters_g) * 1e3
    results["gram_window_ms"] = slope_of(
        "gram_window", lambda k: loop_gram(body_gram_window, k),
        iters_g) * 1e3
    results["gram_prefix_ms"] = slope_of(
        "gram_prefix", lambda k: loop_gram(body_gram_prefix, k),
        iters_g) * 1e3
    results["gram_block_rows"] = gd.block_rows
    results["gram_edge_ms"] = (
        results["gram_window_ms"] - results["gram_prefix_ms"]
    )
    results["gram_bookkeeping_ms"] = (
        results["gram_real_ms"] - results["gram_window_ms"]
    )

    bytes_per_read = m * DIM * (2 if dtype == jnp.bfloat16 else 4)
    results.update({
        "platform": platform,
        "rows": rows,
        "window_rows": m,
        "dim": DIM,
        "dtype": str(dtype.__name__ if hasattr(dtype, "__name__") else dtype),
        "window_gb_per_read": bytes_per_read / 1e9,
        # attribution by subtraction
        "bookkeeping_ms": results["full_ms"] - results["two_read_ms"],
        "history_scatter_ms": (
            results["two_read_hist_ms"] - results["two_read_loss_ms"]
        ),
        "loss_reduction_ms": (
            results["two_read_loss_ms"] - results["two_read_ms"]
        ),
        "dynamic_slice_ms": (
            results["two_read_ms"] - results["two_read_static_ms"]
        ),
        "second_read_ms": results["two_read_ms"] - results["one_read_ms"],
    })

    if os.environ.get("PROFILE_TRACE", "0") == "1":
        trace_dir = os.path.join(REPO, "bench_logs", "profile_trace")
        os.makedirs(trace_dir, exist_ok=True)
        fn = make_full(ITERS)
        jax.block_until_ready(fn(w0, X, y))  # compiled
        with jax.profiler.trace(trace_dir):
            jax.block_until_ready(fn(w0, X, y))
        results["trace_dir"] = trace_dir
        log(f"trace written to {trace_dir}")

    record = {
        "timestamp": time.strftime("%Y-%m-%dT%H:%M:%S"),
        "results": results,
    }
    # A CPU (smoke/fallback) run must never clobber a hardware record.
    out = OUT if platform != "cpu" else OUT.replace(".json", "_cpu.json")
    with open(out, "w") as f:
        json.dump(record, f, indent=1)
    log(f"wrote {out}")
    print(json.dumps(results))


if __name__ == "__main__":
    main()
