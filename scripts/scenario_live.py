"""Live production scenario: serving under admission control while the
replica fleet retrains, hot-reloads, and loses a worker — SLO-gated.

The executable face of ``tpu_sgd/scenario`` (ROADMAP item 1, ISSUE 12):
one seeded run drives an open-loop traffic schedule (warm → overload
burst → cool; mixed dense/sparse/multinomial requests across
interactive/batch/shadow priority lanes) at three serving endpoints
while a bounded-staleness replica fleet retrains on a drifting stream
with compressed pushes, one worker is killed and rejoined mid-run, and
the registry hot-reloads each fresh checkpoint under the traffic.

The run's single JSONL trace then feeds ``python -m tpu_sgd.obs.report
--slo`` and the report's exit code is THIS script's exit code:

* 0 — every SLO holds: per-lane p99 bounds, interactive-lane shed
  fraction bounded, served-weight staleness bounded, ZERO dropped
  requests (every submission answered or typed-rejected), >= 2 hot
  reloads, the worker rejoined;
* 1 — an SLO was violated;
* 2 — usage/parse error.

Usage::

    python scripts/scenario_live.py --smoke [--seed 0] [--out DIR]
    python scripts/scenario_live.py                     # full-size run
    python scripts/scenario_live.py --smoke --violate interactive-p99
                                                        # MUST exit 1

``--violate <slo-name>`` deliberately breaks one SLO bound so CI can
prove the gate fails a bad run (tests/test_scenario.py pins both exit
codes).
"""

from __future__ import annotations

import argparse
import logging
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--smoke", action="store_true",
                    help="seconds-scale seeded run (the CI spelling)")
    ap.add_argument("--out", metavar="DIR", default=None,
                    help="keep trace/SLO/Chrome/summary artifacts here "
                         "(default: temp dir, discarded)")
    ap.add_argument("--violate", metavar="SLO_NAME", default=None,
                    help="deliberately break one named SLO bound; the "
                         "run must then exit 1")
    ap.add_argument("--tenant", action="store_true",
                    help="run the multi-tenant slab stress round "
                         "instead (ISSUE 18): Zipf traffic over "
                         "thousands of tenants, a per-tenant retrain "
                         "trickle, eviction + reload-storm chaos — "
                         "same SLO-gate contract")
    ap.add_argument("--quiet", action="store_true")
    args = ap.parse_args(argv)
    # registry/driver warnings are expected noise under live reload
    logging.basicConfig(level=logging.ERROR)

    from tpu_sgd.scenario import run_scenario, run_tenant_scenario

    run = run_tenant_scenario if args.tenant else run_scenario
    return run(seed=args.seed, smoke=args.smoke,
               out_dir=args.out, violate=args.violate,
               verbose=not args.quiet)


if __name__ == "__main__":
    sys.exit(main())
