#!/usr/bin/env python
"""The full-size config-4 workload through streamed sufficient statistics.

BASELINE.md's streamed section measured the honest truth: the 10M×1000
bf16 dataset (20 GB, beyond HBM) fed window-by-window through this
environment's 0.03 GB/s tunnel costs ~68 s per iteration — the plain
streamed schedule is feed-bound.  `GramLeastSquaresGradient.build_streamed`
changes the game for the quadratic loss: ONE streaming pass over the host
data builds the block-prefix Gram stack on device (~4.9 GB at B=8192),
after which block-aligned sliced iterations touch no rows at all — every
iteration is a prefix difference plus a (d, d) matvec, at device speed,
on the TRUE 10M-row problem (no conversion from a smaller slab).

This script runs that leg end-to-end on hardware and merges the result
into `BENCH_LAST_TPU.json` under ``streamed.gram`` (never touching the
other captured legs).  Run when the tunnel is up:

    python scripts/stream_gram_tpu_check.py
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
LAST = os.path.join(REPO, "BENCH_LAST_TPU.json")


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def main() -> int:
    from bench import DIM, FRAC, STEP_SIZE, TARGET_ROWS, streamed_host_dataset

    from tpu_sgd.utils.platform import honor_cpu_env

    honor_cpu_env()
    import jax
    import jax.numpy as jnp

    platform = jax.devices()[0].platform
    log(f"device: {jax.devices()[0].device_kind} ({platform})")

    rows = int(os.environ.get("BENCH_STREAM_ROWS", str(TARGET_ROWS)))
    block = int(os.environ.get("STREAM_GRAM_BLOCK", "8192"))
    iters_fit = int(os.environ.get("STREAM_GRAM_ITERS", "300"))
    X, y, gen_s = streamed_host_dataset(rows, DIM)

    from tpu_sgd.config import SGDConfig
    from tpu_sgd.ops.gram import GramLeastSquaresGradient
    from tpu_sgd.ops.updaters import SimpleUpdater
    from tpu_sgd.optimize.gradient_descent import make_run

    t0 = time.perf_counter()
    gg = GramLeastSquaresGradient.build_streamed(X, y, block_rows=block)
    jax.block_until_ready(gg.data.PG)
    build_s = time.perf_counter() - t0
    n_use = gg.data.shape[0]
    stats_gb = gg.data.PG.nbytes / 1e9
    feed_gb = n_use * DIM * 2 / 1e9
    log(f"stats built: {build_s:.0f}s for {feed_gb:.0f} GB streamed "
        f"({feed_gb / build_s:.3f} GB/s), prefix {stats_gb:.2f} GB "
        f"on device, rows used {n_use}")

    y_dev = jax.device_put(np.asarray(y[:n_use], np.float32))
    del X, y

    def run_iters(k):
        cfg = SGDConfig(step_size=STEP_SIZE, num_iterations=k,
                        mini_batch_fraction=FRAC, convergence_tol=0.0,
                        sampling="sliced")
        run = jax.jit(make_run(gg, SimpleUpdater(), cfg))
        w0 = jnp.zeros((DIM,), jnp.float32)
        t0 = time.perf_counter()
        jax.block_until_ready(run(w0, gg.data, y_dev))
        log(f"gram[{k}]: compile+first {time.perf_counter() - t0:.1f}s")
        t0 = time.perf_counter()
        w, losses, n_rec = jax.block_until_ready(run(w0, gg.data, y_dev))
        return time.perf_counter() - t0, np.asarray(losses)[: int(n_rec)]

    # >= 3-point regression ladder (VERDICT r3 weak #1 — same evidentiary
    # bar as every bench leg: at ~0.025 ms/iter the old 300/1200 two-point
    # fit resolved ~30 ms of tunnel launch jitter against ~30 ms of slope
    # signal); default 1200/3600/14400 puts the signal well above it.
    from bench import fit_steady_state

    ladder = (4 * iters_fit, 12 * iters_fit, 48 * iters_fit)
    pts = []
    losses = None
    for k in ladder:
        dt, losses = run_iters(k)
        pts.append((k, dt))
    slope, _fixed, fit = fit_steady_state(pts)
    log(f"fit: residuals {fit['residual_ms']} ms, "
        f"slope_rel_err {fit.get('slope_rel_err')}"
        + (" (FALLBACK: launch-cost-inclusive mean)" if "fallback" in fit
           else ""))
    epochs_per_sec = FRAC / slope  # epochs OF THE MEASURED dataset
    # an epoch costs (1/FRAC) iterations; amortization incl. the one-time
    # build pass, quoted at 100 epochs
    epochs = 100
    amortized = epochs / (build_s + epochs * slope / FRAC)
    log(f"steady-state {slope * 1e3:.3f} ms/iter -> "
        f"{epochs_per_sec:.1f} epochs/sec post-build on the true "
        f"{n_use}x{DIM} problem; {amortized:.2f} epochs/sec amortized "
        f"over {epochs} epochs incl. the build pass; final loss "
        f"{losses[-1]:.4f}")

    # ---- round 5: the chunked-gather driver on the SAME statistics ------
    # (optimize/gram_driver.py, set_gram_options(chunk_iters=K)): same
    # ladder, same window stream — if it wins with an identical
    # trajectory, the quoted post-build rate is the winner's and the
    # record says which driver produced it.
    from tpu_sgd.optimize.gram_driver import make_chunked_gram_run

    k_chunk = int(os.environ.get("STREAM_GRAM_CHUNK_ITERS", "16"))

    def run_chunked(k):
        cfg = SGDConfig(step_size=STEP_SIZE, num_iterations=k,
                        mini_batch_fraction=FRAC, convergence_tol=0.0,
                        sampling="sliced")
        run = jax.jit(make_chunked_gram_run(
            SimpleUpdater(), cfg, n=n_use, block_rows=block,
            chunk_iters=k_chunk))
        w0 = jnp.zeros((DIM,), jnp.float32)
        t0 = time.perf_counter()
        jax.block_until_ready(run(w0, gg.data, y_dev))
        log(f"chunked[{k}]: compile+first {time.perf_counter() - t0:.1f}s")
        t0 = time.perf_counter()
        w, ls, n_rec = jax.block_until_ready(run(w0, gg.data, y_dev))
        return time.perf_counter() - t0, np.asarray(ls)[: int(n_rec)]

    pts_c = []
    losses_c = None
    for k in ladder:
        dt, losses_c = run_chunked(k)
        pts_c.append((k, dt))
    slope_c, _fc, fit_c = fit_steady_state(pts_c)
    agree = bool(np.allclose(losses_c, losses, rtol=1e-4, atol=1e-6))
    eps_c = FRAC / slope_c
    log(f"chunked driver: {slope_c * 1e3:.4f} ms/iter -> {eps_c:.1f} "
        f"epochs/sec post-build (trajectory agree={agree})")
    chunked_wins = agree and slope_c < slope
    if chunked_wins:
        epochs_per_sec = eps_c
        amortized = epochs / (build_s + epochs * slope_c / FRAC)
        log("chunked driver WINS with an identical trajectory — quoting "
            "its rate (the per-iteration rate stays in the record)")

    record = {
        "timestamp": time.strftime("%Y-%m-%dT%H:%M:%S"),
        "platform": platform,
        "rows_used": int(n_use),
        "dim": DIM,
        "block_rows": block,
        "sampling": f"block-aligned sliced (B={block})",
        "gen_s": round(gen_s, 1),
        "build_s": round(build_s, 1),
        "build_feed_gb_per_s": feed_gb / build_s,
        "stats_gb_on_device": stats_gb,
        "iter_ms": slope * 1e3,
        "fit": fit,
        "chunked_iter_ms": slope_c * 1e3,
        "chunked_fit": fit_c,
        "chunked_k": k_chunk,
        "chunked_trajectory_agree": agree,
        "driver": (f"chunked (chunk_iters={k_chunk})" if chunked_wins
                   else "per-iteration"),
        "epochs_per_sec_post_build": epochs_per_sec,
        "epochs_per_sec_amortized_100": amortized,
        "final_loss": float(losses[-1]),
        "first_loss": float(losses[0]),
    }

    if platform == "cpu":
        log("CPU fallback: NOT merging into BENCH_LAST_TPU.json")
        print(json.dumps(record))
        return 1
    try:
        with open(LAST) as f:
            last = json.load(f)
    except (OSError, ValueError):
        last = {}
    streamed = last.get("streamed") or {}
    streamed["gram"] = record
    last["streamed"] = streamed
    # Re-promote the measured-at-size headline fields so the persisted
    # top-level result always describes THIS capture (bench may have run
    # earlier in the same watcher cycle and promoted the previous one).
    if isinstance(last.get("result"), dict):
        from bench import promote_measured_at_size

        promote_measured_at_size(last["result"], last)
    with open(LAST, "w") as f:
        json.dump(last, f, indent=1)
    log(f"merged streamed.gram into {LAST} (headline fields re-promoted)")
    print(json.dumps(record))
    return 0


if __name__ == "__main__":
    sys.exit(main())
