#!/usr/bin/env python
"""Hardware leg for the host-streamed chunked CostFun (round 5's flagship).

``quasi_newton_tpu_check.py`` proves one small streamed-LBFGS leg on
hardware; this check exercises the full surface of
``optimize/streamed_costfun.py`` on the real chip at a scale where the
chunk grid and double-buffered feed matter:

* logistic LBFGS and hinge OWL-QN over a 200k x 500 slab forced through
  64 MB chunks (7 chunk programs per full-batch evaluation);
* a multinomial leg whose backtracking ladder streams MATRIX trial
  weights through ``sweep_sums``;
* a same-device resident-vs-streamed agreement gate (the evaluator's
  core contract: identical sums, different execution), plus the usual
  cross-backend CPU check within 2%;
* per-evaluation walls from instrumented ``cost_sums``/``sweep_sums``,
  reported as an effective host->device feed rate — on this
  tunnel-attached environment the expected figure is the ~0.07 GB/s
  tunnel rate (BASELINE.md), NOT device speed; the check is that the
  chunked evaluator sustains the link's rate rather than degrading it.

True beyond-HBM scale (>16 GB) through a 0.07 GB/s tunnel would cost
~15 min per evaluation — the correctness-at-reduced-scale approach is
the same one SPARSE_TPU_CHECK.json uses, and the code path is
byte-for-byte the one a pod-local host runs at PCIe rates.

The script ends by running ``calibrate_tpu_check.py`` (a ~2 s probe) so
the planner-calibration capture rides the same watcher slot.

Run when the tunnel is up:  python scripts/streamed_costfun_tpu_check.py
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(REPO, "STREAMED_COSTFUN_TPU_CHECK.json")

_CHILD = r"""
import os, sys, json, time
if os.environ.get("SC_CHECK_CPU"):
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    import jax; jax.config.update("jax_platforms", "cpu")
else:
    import jax
import numpy as np, jax.numpy as jnp
sys.path.insert(0, %(repo)r)
from tpu_sgd import LBFGS, OWLQN, SquaredL2Updater
from tpu_sgd.ops.gradients import (HingeGradient, LogisticGradient,
                                   MultinomialLogisticGradient)
from tpu_sgd.optimize import streamed_costfun as scf

out = {"platform": jax.devices()[0].platform,
       "device": str(jax.devices()[0].device_kind), "legs": {}}

# instrument the evaluator: per-call walls for every full-batch pass
_walls = []
def _timed(name, orig):
    def wrap(self, w):
        t0 = time.perf_counter()
        r = orig(self, w)
        # 1-element readbacks, not block_until_ready: the experimental
        # axon platform has been observed returning from block_until_ready
        # before the work completes (see CostModel.calibrate), which would
        # record dispatch rather than evaluation walls here
        for leaf in jax.tree_util.tree_leaves(r):
            np.asarray(jnp.ravel(leaf)[:1])
        _walls.append((name, self.n * self.X.shape[1] * self.X.dtype.itemsize,
                       time.perf_counter() - t0))
        return r
    return wrap
for _n in ("cost_sums", "loss_sums", "sweep_sums"):
    setattr(scf.StreamedCostFun, _n,
            _timed(_n, getattr(scf.StreamedCostFun, _n)))

rng = np.random.default_rng(17)
n, d = 200_000, 500
X = rng.normal(size=(n, d)).astype(np.float32)
wt = rng.uniform(-1, 1, size=(d,)).astype(np.float32)
y_log = (1 / (1 + np.exp(-X @ wt)) > rng.uniform(size=(n,))).astype(np.float32)
CHUNK_ROWS = 32_768  # 64 MB chunks -> 7 chunk programs per evaluation

def leg_logistic_lbfgs_streamed():
    opt = (LBFGS(LogisticGradient(), SquaredL2Updater(), reg_param=0.01,
                 max_num_iterations=6)
           .set_host_streaming(True, batch_rows=CHUNK_ROWS))
    w, hist = opt.optimize_with_history((X, y_log), jnp.zeros((d,)))
    jax.block_until_ready(w)
    assert opt._stream_costfun_entry is not None, "CostFun did not engage"
    return [round(float(x), 6) for x in np.asarray(hist)]

def leg_logistic_lbfgs_resident():
    opt = LBFGS(LogisticGradient(), SquaredL2Updater(), reg_param=0.01,
                max_num_iterations=6)
    w, hist = opt.optimize_with_history((X, y_log), jnp.zeros((d,)))
    jax.block_until_ready(w)
    return [round(float(x), 6) for x in np.asarray(hist)]

def leg_hinge_owlqn_streamed():
    opt = (OWLQN(HingeGradient(), reg_param=1e-4, max_num_iterations=6)
           .set_host_streaming(True, batch_rows=CHUNK_ROWS))
    w, hist = opt.optimize_with_history((X, y_log), jnp.zeros((d,)))
    jax.block_until_ready(w)
    assert opt._stream_costfun_entry is not None, "CostFun did not engage"
    return [round(float(x), 6) for x in np.asarray(hist)]

def leg_multinomial_sweep_streamed():
    r = np.random.default_rng(23)
    nm, dm, K = 50_000, 200, 4
    Xm = r.normal(size=(nm, dm)).astype(np.float32)
    Wt = r.uniform(-1, 1, size=(K - 1, dm)).astype(np.float32)
    logits = np.concatenate([np.zeros((nm, 1)), Xm @ Wt.T], axis=1)
    ym = np.argmax(logits + r.gumbel(size=logits.shape), axis=1)
    opt = (LBFGS(MultinomialLogisticGradient(K), SquaredL2Updater(),
                 reg_param=0.01, max_num_iterations=6)
           .set_host_streaming(True, batch_rows=16_384))
    w, hist = opt.optimize_with_history(
        (Xm, ym.astype(np.float32)), jnp.zeros(((K - 1) * dm,)))
    jax.block_until_ready(w)
    assert opt._stream_costfun_entry is not None, "CostFun did not engage"
    return [round(float(x), 6) for x in np.asarray(hist)]

for name, fn in [("logistic_lbfgs_streamed", leg_logistic_lbfgs_streamed),
                 ("logistic_lbfgs_resident", leg_logistic_lbfgs_resident),
                 ("hinge_owlqn_streamed", leg_hinge_owlqn_streamed),
                 ("multinomial_sweep_streamed", leg_multinomial_sweep_streamed)]:
    _walls.clear()
    t0 = time.perf_counter()
    hist = fn()
    wall = round(time.perf_counter() - t0, 3)
    evals = [(nm_, b, round(w_, 4)) for nm_, b, w_ in _walls]
    steady = [w_ for _, _, w_ in _walls[2:]] or [w_ for _, _, w_ in _walls]
    bytes_per = _walls[0][1] if _walls else 0
    feed = (bytes_per / (sum(steady) / len(steady)) / 1e9) if steady else None
    out["legs"][name] = {
        "final": hist[-1], "history": hist, "wall_s": wall,
        "n_evaluations": len(evals), "evaluations": evals,
        "eval_wall_s_steady": round(sum(steady) / len(steady), 4) if steady else None,
        "effective_feed_gb_s": round(feed, 4) if feed else None,
    }
print("SC_JSON:" + json.dumps(out))
""" % {"repo": REPO}


def run_side(env_extra):
    env = dict(os.environ, **env_extra)
    proc = subprocess.run([sys.executable, "-c", _CHILD],
                          capture_output=True, text=True, timeout=1500,
                          env=env)
    line = next((l for l in proc.stdout.splitlines()
                 if l.startswith("SC_JSON:")), None)
    if line is None:
        print(proc.stdout[-2000:])
        print(proc.stderr[-3000:])
        raise SystemExit("streamed-costfun child produced no record")
    return json.loads(line[len("SC_JSON:"):])


def main():
    t0 = time.time()
    print("streamed-CostFun hardware check", file=sys.stderr, flush=True)
    tpu = run_side({})
    print(f"tpu side: {tpu['device']} ({tpu['platform']})",
          file=sys.stderr, flush=True)
    cpu = run_side({"SC_CHECK_CPU": "1"})

    rec = {"timestamp": time.strftime("%Y-%m-%dT%H:%M:%S"),
           "platform": tpu["platform"], "device": tpu["device"],
           "legs": {}}
    ok = tpu["platform"] == "tpu"
    for name, leg in tpu["legs"].items():
        c = cpu["legs"][name]["final"]
        t = leg["final"]
        rel = abs(t - c) / max(abs(c), 1e-12)
        leg_ok = rel < 0.02
        ok = ok and leg_ok
        rec["legs"][name] = dict(leg, cpu_final=c,
                                 rel_gap=round(rel, 6), ok=leg_ok)
        print(f"{name}: tpu {t:.6f} vs cpu {c:.6f} -> "
              f"{'OK' if leg_ok else 'FAIL'}"
              + (f" (feed {leg['effective_feed_gb_s']} GB/s)"
                 if leg.get("effective_feed_gb_s") else ""),
              file=sys.stderr, flush=True)

    # same-device contract: streamed == resident trajectory (both TPU)
    sv = tpu["legs"]["logistic_lbfgs_streamed"]["final"]
    rv = tpu["legs"]["logistic_lbfgs_resident"]["final"]
    same_dev_gap = abs(sv - rv) / max(abs(rv), 1e-12)
    rec["streamed_vs_resident_same_device_gap"] = round(same_dev_gap, 6)
    ok = ok and same_dev_gap < 1e-3
    rec["ok"] = bool(ok)
    rec["wall_s"] = round(time.time() - t0, 1)
    rec["note"] = (
        "correctness + link-rate capture at reduced scale: the chunked "
        "evaluator's code path is identical at any scale; a true >16 GB "
        "dataset through this environment's ~0.07 GB/s tunnel would cost "
        "~15 min per full-batch evaluation, so the feed-rate fields here "
        "document that the evaluator sustains the link rate (pod-local "
        "hosts feed 2-3 orders faster, same code)"
    )
    with open(OUT, "w") as f:
        json.dump(rec, f, indent=1)
    print(f"all legs agree: {ok}; wrote {OUT}", file=sys.stderr, flush=True)

    # ride the same watcher slot for the ~2 s planner-calibration probe
    calib = os.path.join(REPO, "scripts", "calibrate_tpu_check.py")
    try:
        subprocess.run([sys.executable, calib], timeout=900)
    except Exception as e:  # the probe is a bonus capture, never a failure
        print(f"calibration probe skipped ({type(e).__name__}: {e})",
              file=sys.stderr, flush=True)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
