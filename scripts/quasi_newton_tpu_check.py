#!/usr/bin/env python
"""Validate the quasi-Newton and streaming paths on REAL TPU hardware.

LBFGS, OWL-QN, multinomial LBFGS and streaming SGD are CPU-proven by the
test suite; this script is their hardware leg (the same role
``sparse_tpu_check.py`` plays for the BCOO path): run each on the TPU and
cross-check against the (trusted) CPU result computed in a subprocess.
Writes QUASI_NEWTON_TPU_CHECK.json for the record.

Pass criterion: every leg ran on ``platform: tpu`` and its final objective
agrees with the CPU side within 2% (full loss histories are recorded for
inspection, but the gate is the final objective — the batched Armijo ladder
argmax can pick a different-but-valid step under TPU matmul rounding, after
which trajectories legitimately differ iteration-by-iteration while
converging to the same optimum).

Run it when the tunnel is up:  python scripts/quasi_newton_tpu_check.py
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(REPO, "QUASI_NEWTON_TPU_CHECK.json")

_CHILD = r"""
import os, sys, json, time
if os.environ.get("QN_CHECK_CPU"):
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    import jax; jax.config.update("jax_platforms", "cpu")
else:
    import jax
import numpy as np, jax.numpy as jnp
sys.path.insert(0, %(repo)r)
from tpu_sgd import LBFGS, OWLQN, SquaredL2Updater
from tpu_sgd.ops.gradients import (LeastSquaresGradient, LogisticGradient,
                                   MultinomialLogisticGradient)
from tpu_sgd.models.streaming import StreamingLinearRegressionWithSGD

out = {"platform": jax.devices()[0].platform,
       "device": str(jax.devices()[0].device_kind), "legs": {}}

def timed(fn):
    t0 = time.perf_counter()
    r = fn()
    return r, round(time.perf_counter() - t0, 3)

# -- shared binary-logistic data (fixed seeds; identical on both sides) ---
rng = np.random.default_rng(3)
n, d = 20000, 500
Xb = rng.normal(size=(n, d)).astype(np.float32)
wt = rng.uniform(-1, 1, size=(d,)).astype(np.float32)
yb = (1 / (1 + np.exp(-Xb @ wt)) > rng.uniform(size=(n,))).astype(np.float32)

def leg_lbfgs():
    opt = LBFGS(LogisticGradient(), SquaredL2Updater(),
                reg_param=0.01, max_num_iterations=15)
    w, hist = opt.optimize_with_history((Xb, yb), jnp.zeros((d,)))
    jax.block_until_ready(w)
    return [round(float(x), 6) for x in np.asarray(hist)]

def leg_owlqn():
    opt = OWLQN(LogisticGradient(), reg_param=1e-3, max_num_iterations=15)
    w, hist = opt.optimize_with_history((Xb, yb), jnp.zeros((d,)))
    jax.block_until_ready(w)
    return [round(float(x), 6) for x in np.asarray(hist)]

def leg_multinomial():
    r = np.random.default_rng(5)
    nm, dm, K = 10000, 200, 4
    Xm = r.normal(size=(nm, dm)).astype(np.float32)
    Wt = r.uniform(-1, 1, size=(K - 1, dm)).astype(np.float32)
    logits = np.concatenate([np.zeros((nm, 1)), Xm @ Wt.T], axis=1)
    ym = np.argmax(logits + r.gumbel(size=logits.shape), axis=1)
    opt = LBFGS(MultinomialLogisticGradient(K), SquaredL2Updater(),
                reg_param=0.01, max_num_iterations=12)
    w, hist = opt.optimize_with_history(
        (Xm, ym.astype(np.float32)), jnp.zeros(((K - 1) * dm,)))
    jax.block_until_ready(w)
    return [round(float(x), 6) for x in np.asarray(hist)]

def leg_streaming():
    r = np.random.default_rng(9)
    ds = 100
    ws = r.uniform(-1, 1, size=(ds,)).astype(np.float32)
    alg = StreamingLinearRegressionWithSGD(step_size=0.5, num_iterations=20)
    alg.set_initial_weights(np.zeros((ds,), np.float32))
    errs = []
    for _ in range(8):
        Xs = r.normal(size=(2000, ds)).astype(np.float32)
        ys = Xs @ ws + 0.01 * r.normal(size=(2000,)).astype(np.float32)
        m = alg.train_on_batch(Xs, ys)
        errs.append(round(float(np.linalg.norm(
            np.asarray(m.weights) - ws) / np.linalg.norm(ws)), 6))
    return errs

# least-squares data for the sufficient-stats quasi-Newton legs
r2 = np.random.default_rng(21)
nls, dls = 30000, 400
Xls = r2.normal(size=(nls, dls)).astype(np.float32)
wls = r2.uniform(-1, 1, dls).astype(np.float32)
yls = (Xls @ wls + 0.05 * r2.normal(size=nls)).astype(np.float32)

def leg_gram_lbfgs():
    opt = (LBFGS(LeastSquaresGradient(), SquaredL2Updater(),
                 reg_param=1e-3, max_num_iterations=15)
           .set_sufficient_stats(True))
    w, hist = opt.optimize_with_history((Xls, yls), jnp.zeros((dls,)))
    jax.block_until_ready(w)
    assert opt._gram_entry is not None, "gram substitution did not engage"
    return [round(float(x), 6) for x in np.asarray(hist)]

def leg_gram_owlqn():
    opt = (OWLQN(LeastSquaresGradient(), reg_param=1e-3,
                 max_num_iterations=15)
           .set_sufficient_stats(True))
    w, hist = opt.optimize_with_history((Xls, yls), jnp.zeros((dls,)))
    jax.block_until_ready(w)
    assert opt._gram_entry is not None, "gram substitution did not engage"
    return [round(float(x), 6) for x in np.asarray(hist)]

def leg_costfun_lbfgs():
    # Round 5: host-streamed chunked CostFun — beyond-HBM quasi-Newton
    # for a NON-least-squares loss (VERDICT r4 #1).  Forced onto the
    # resident-sized slab with a small chunk so the chunked accumulation
    # (5 chunks/evaluation, double-buffered feed) actually exercises.
    opt = (LBFGS(LogisticGradient(), SquaredL2Updater(), reg_param=0.01,
                 max_num_iterations=10)
           .set_host_streaming(True, batch_rows=4096))
    w, hist = opt.optimize_with_history((Xb, yb), jnp.zeros((d,)))
    jax.block_until_ready(w)
    assert opt._stream_costfun_entry is not None, "CostFun did not engage"
    return [round(float(x), 6) for x in np.asarray(hist)]

for name, fn in [("lbfgs", leg_lbfgs), ("owlqn", leg_owlqn),
                 ("multinomial", leg_multinomial),
                 ("streaming_w_err", leg_streaming),
                 ("gram_lbfgs", leg_gram_lbfgs),
                 ("gram_owlqn", leg_gram_owlqn),
                 ("costfun_lbfgs", leg_costfun_lbfgs)]:
    vals, wall = timed(fn)
    out["legs"][name] = {"values": vals, "wall_s": wall}
    print(f"{name}: {wall}s final {vals[-1]}", file=sys.stderr, flush=True)

print("RESULT::" + json.dumps(out))
"""


def _run(cpu: bool, timeout: int) -> dict:
    env = dict(os.environ)
    if cpu:
        env["QN_CHECK_CPU"] = "1"
    else:
        env.pop("QN_CHECK_CPU", None)  # a stale flag must not silently turn
        # the TPU leg into a CPU-vs-CPU comparison
    code = _CHILD % {"repo": REPO}
    proc = subprocess.run(
        [sys.executable, "-c", code], env=env, timeout=timeout,
        capture_output=True, text=True,
    )
    for line in proc.stdout.splitlines():
        if line.startswith("RESULT::"):
            return json.loads(line[len("RESULT::"):])
    raise RuntimeError(
        f"no result (rc={proc.returncode}):\n{proc.stderr[-2000:]}"
    )


def main() -> int:
    print("quasi-newton/streaming hardware check", flush=True)
    tpu = _run(cpu=False, timeout=1800)
    print(f"tpu side: {tpu['device']} ({tpu['platform']})", flush=True)
    if tpu["platform"] == "cpu":
        print("TPU leg fell back to CPU (tunnel down?); aborting before "
              "the CPU cross-check", flush=True)
        return 1
    cpu = _run(cpu=True, timeout=3600)

    legs = {}
    all_agree = True
    for name in tpu["legs"]:
        ft = tpu["legs"][name]["values"][-1]
        fc = cpu["legs"][name]["values"][-1]
        # streaming errors approach 0; compare absolutely there
        agree = (abs(ft - fc) <= 2e-3 if name == "streaming_w_err"
                 else abs(ft - fc) <= 0.02 * max(abs(fc), 1e-12))
        legs[name] = {"tpu_final": ft, "cpu_final": fc, "agree": bool(agree)}
        all_agree &= agree
        print(f"{name}: tpu {ft} vs cpu {fc} -> "
              f"{'OK' if agree else 'MISMATCH'}", flush=True)

    record = {
        "timestamp": time.strftime("%Y-%m-%dT%H:%M:%S"),
        "note": (
            "correctness check, not a perf claim: the legs are tiny "
            "workloads whose walls are dominated by the remote-TPU "
            "tunnel's ~65 ms per-program dispatch tax (quasi-Newton "
            "iterates sync the host every iteration), so the TPU walls "
            "may read slower than CPU here"
        ),
        "tpu": tpu,
        "cpu": cpu,
        "finals": legs,
        "all_agree": all_agree,
    }
    with open(OUT, "w") as f:
        json.dump(record, f, indent=1)
    print(f"all legs agree: {all_agree}; wrote {OUT}", flush=True)
    return 0 if all_agree and tpu["platform"] != "cpu" else 1


if __name__ == "__main__":
    sys.exit(main())
